"""``hot-path-purity``: ONE declarative registry for every per-event hot
path, replacing the four bespoke checks the old script had grown
(``check_hot_path_instruments``, ``check_kv_transport``'s purity half,
``check_data_streaming_hot_path``, ``check_phase_stamp_hot_path``).

A hot path is declared once in ``HOT_PATHS`` with the contracts it must
keep; adding a new per-event path to the system means appending a
declaration here, not writing a new checker. Contracts available:

- *metric-bind-only*: instruments bind at import/install time; the path
  never constructs or looks one up per event (the PR-8 telemetry
  contract). Optionally no metric RECORDING at all (the BLOB frame
  paths, where a lock per frame is a measured regression).
- *rpc-free*: the path never speaks the wire (call/notify/remote/task
  submission) — data moves over channels/plane pulls.
- *import bans*: the module must not link the control plane.
- *required calls*: load-bearing plumbing that must stay wired (e.g. the
  KV pull must ride ``pull_into``; the worker main must ship phase
  clocks on the done reply).
- *module-level bind*: at least one ``bind()`` assignment at module top
  level (instruments exist before the first event).
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field

from ray_tpu.devtools.lint.core import (
    ProjectCtx, calls_in, callee_name, find_funcs, project_rule)

RULE = "hot-path-purity"

# Metric construction / registry-touching call names that must never run
# per-event on a hot path — instruments bind at import/install time
# (util/metrics.py bind contract, ISSUE-8 telemetry plane).
METRIC_CONSTRUCT_CALLS = {
    "Counter", "Gauge", "Histogram", "bind", "get_metric",
    "registry_snapshot", "wire_snapshot", "prometheus_text",
    "attach_producer",
}
# Any metric recording at all is banned inside the raw BLOB frame paths —
# a lock per frame there is a measured regression (pull metrics live at
# whole-pull granularity in object_plane instead).
METRIC_RECORD_CALLS = {"inc", "observe", "record"}

RPC_CALLS = {"call", "call_async", "notify"}
SUBMIT_CALLS = {"remote", "submit_task"}

CONTROL_PLANE_IMPORTS = (
    "ray_tpu.core.rpc", "ray_tpu.core.runtime", "ray_tpu.core.cluster",
    "ray_tpu.core.client_runtime", "ray_tpu.core.api",
)


@dataclass(frozen=True)
class HotPath:
    file: str
    funcs: tuple = ()            # () = every function in the module
    reason: str = ""             # one line: why this path is hot
    ban_metric_construct: bool = True
    ban_metric_record: bool = False
    ban_rpc: bool = False
    ban_submit: bool = False
    forbid_imports: tuple = ()   # module-level import prefixes
    require_module_bind: bool = False
    # ((func, (one-of-callees...), message), ...) — plumbing that must stay
    require_calls: tuple = ()
    missing_hint: str = ""       # shown when a declared func disappears


HOT_PATHS = (
    # ISSUE-7/8: the compiled-graph actor-resident exec loop. RPC-freedom
    # is dag-loop-rpc-free's job; purity here is bind-at-import metrics.
    HotPath(
        file="ray_tpu/dag/exec_loop.py",
        reason="runs every compiled-graph step; sampled metrics only",
        require_module_bind=True,
        missing_hint="compiled-graph loop renamed?",
    ),
    # ISSUE-5/8: the raw BLOB frame paths — per-FRAME, so even recording
    # through a bound handle (one lock) is a measured regression.
    HotPath(
        file="ray_tpu/core/rpc/peer.py",
        funcs=("_send_blob", "_read_blob"),
        reason="per-frame BLOB send/recv; account at pull granularity",
        ban_metric_record=True,
        missing_hint="BLOB path gone?",
    ),
    HotPath(
        file="ray_tpu/core/object_plane.py",
        funcs=("_h_chunk_raw",),
        reason="per-frame raw-chunk reply; account at pull granularity",
        ban_metric_record=True,
        missing_hint="BLOB path gone?",
    ),
    # ISSUE-11: the KV handoff publish/pull pair (declared since PR 8's
    # contract but previously enforced by a bespoke check).
    HotPath(
        file="ray_tpu/serve/kv_transport.py",
        funcs=("publish", "pull"),
        reason="per-handoff KV page movement",
        require_calls=(
            ("pull", ("pull_into", "pull_into_or_pull"),
             "pull no longer rides pull_into — KV pages must land "
             "zero-copy in the local store"),
            ("publish", ("kv_window",),
             "publish no longer stamps its anatomy KV window — request "
             "ledgers lose the kv_publish phase (serve/anatomy.py)"),
            ("pull", ("kv_window",),
             "pull no longer stamps its anatomy KV window — request "
             "ledgers lose the kv_pull phase (serve/anatomy.py)"),
        ),
        missing_hint="handoff path gone?",
    ),
    # ISSUE-16: serve anatomy stamping — every stamp is ONE bounded-ring
    # append: no instruments (bind or record), no RPC, no task submission
    # on the request path. Recording happens head-side at fold/settle time.
    HotPath(
        file="ray_tpu/serve/anatomy.py",
        funcs=("stamp", "kv_window", "link_kv", "complete", "admit",
               "rid_of", "router_stamp", "replica_dequeue", "drain_since"),
        reason="per-request phase stamps on the serve hot path",
        ban_metric_record=True,
        ban_rpc=True,
        ban_submit=True,
        forbid_imports=CONTROL_PLANE_IMPORTS,
        missing_hint="anatomy stamping API renamed? (update HOT_PATHS)",
    ),
    # ISSUE-16: the stamping sites stay wired — the router marks its
    # decision (compiled dispatch stays ONE channel frame: the stamp is a
    # ring append, not a wire op), the paged engine stamps the first
    # decoded token.
    HotPath(
        file="ray_tpu/serve/controller.py",
        funcs=("_submit_compiled", "submit", "submit_stream"),
        reason="per-request dispatch; anatomy stamps must stay ring-only",
        ban_metric_construct=False,
        require_calls=(
            ("_submit_compiled", ("router_stamp",),
             "compiled dispatch no longer stamps router_decision — "
             "ledgers lose the routing phase on the zero-RPC path"),
            ("submit", ("router_stamp",),
             "per-call dispatch no longer stamps router_decision"),
            ("submit_stream", ("router_stamp",),
             "streaming dispatch no longer stamps router_decision"),
        ),
        missing_hint="router dispatch renamed? (update HOT_PATHS)",
    ),
    HotPath(
        file="ray_tpu/serve/llm_paged.py",
        funcs=("_step_decode",),
        reason="per-step decode loop; first-token stamp is one ring append",
        require_calls=(
            ("_step_decode", ("stamp",),
             "_step_decode no longer stamps decode_first_token — PD "
             "ledgers lose the first-token phase and TTFT degrades to "
             "completion time"),
        ),
        missing_hint="paged decode step renamed? (update HOT_PATHS)",
    ),
    # ISSUE-12: streaming data plane pump / fetch / task bodies. May submit
    # tasks and get objects through the public API (which owns
    # retry/failover) but never speaks the wire directly.
    HotPath(
        file="ray_tpu/data/streaming.py",
        funcs=("_drive_op", "fetch_block", "_prefetch_pump", "__next__",
               "_transform_to_plane", "_slice_to_plane"),
        reason="per-block streaming pump/fetch loops",
        ban_rpc=True,
        forbid_imports=("ray_tpu.core.rpc",),
        missing_hint="streaming pump/pull loop renamed? (update HOT_PATHS)",
    ),
    HotPath(
        file="ray_tpu/data/exchange.py",
        funcs=("_reduce_partition", "_map_partition", "_pull_slices"),
        reason="per-partition shuffle task bodies",
        ban_rpc=True,
        forbid_imports=("ray_tpu.core.rpc",),
        require_calls=(
            ("_map_partition", ("put",),
             "_map_partition no longer seals slices via ray_tpu.put — "
             "slices must stay in the mapper's node store"),
            ("_reduce_partition", ("get", "_pull_slices"),
             "_reduce_partition no longer pulls its own slices — reducers "
             "must resolve slices through the plane failover path "
             "themselves"),
        ),
        missing_hint="shuffle task body renamed? (update HOT_PATHS)",
    ),
    # ISSUE-15: the cross-node compiled-graph edge bridge. Per-FRAME path:
    # metric-free entirely (bind-only would still take a lock per frame),
    # no task submission, no control-plane linkage — its ONLY wire traffic
    # is the persistent dag_ch_* ops on count_ops=False data peers, and
    # host-side reads must leave as raw BLOB frames (the PR-5 sendmsg
    # path). The zero-control-plane steady-state assert rests on this.
    HotPath(
        file="ray_tpu/dag/fabric.py",
        funcs=("read_view", "write", "_h_read", "_h_write", "_poll"),
        reason="per-frame cross-node compiled-graph edge traffic",
        ban_metric_record=True,
        ban_submit=True,
        forbid_imports=("ray_tpu.core.runtime", "ray_tpu.core.cluster",
                        "ray_tpu.core.client_runtime", "ray_tpu.core.api"),
        require_calls=(
            ("_h_read", ("RawReply",),
             "fabric reads no longer answer with raw BLOB frames — the "
             "zero-copy sendmsg reply path is the bridge's contract"),
            ("_poll", ("call_async",),
             "the reader no longer pipelines its long-polls (prefetch) — "
             "each hop would pay exec + RTT + producer instead of max()"),
        ),
        missing_hint="cross-node edge bridge renamed? (update HOT_PATHS)",
    ),
    # ISSUE-13: worker phase stamping — ring append under one lock; no
    # instruments, no RPC. export() may link the runtime; the recording
    # half may not.
    HotPath(
        file="ray_tpu/util/timeline.py",
        funcs=("phase_reply", "stamp_task_phases", "record_span",
               "drain_since"),
        reason="per-task phase stamp on the worker exec path",
        ban_rpc=True,
        ban_submit=True,
        forbid_imports=tuple(m for m in CONTROL_PLANE_IMPORTS
                             if m != "ray_tpu.core.runtime"),
        missing_hint="phase recording path renamed? (update HOT_PATHS)",
    ),
    # ISSUE-17: the front door's ingress dispatch fast path. Per-REQUEST:
    # route lookup, replica pick, and the admission predictor read ONLY the
    # local routing-epoch cache — zero control-plane RPCs, no task
    # submission, no per-request instruments. Fleet management (_spawn,
    # _ensure, _drop) is deliberately NOT declared: it may submit actors.
    # Note: this PR added NO new wire ops — the retained-epoch replay rides
    # the existing pubsub_msg notify frame, so the rpc/schema baseline and
    # version gate are untouched by design.
    HotPath(
        file="ray_tpu/serve/front_door.py",
        funcs=("_refresh", "pick", "_lookup", "_predict", "_admit"),
        reason="per-request ingress dispatch; local epoch cache only",
        ban_rpc=True,
        ban_submit=True,
        forbid_imports=CONTROL_PLANE_IMPORTS,
        require_calls=(
            ("_refresh", ("snapshot",),
             "_refresh no longer reads the local epoch cache — routing "
             "state must come from the last applied epoch, not a "
             "controller poll"),
            ("pick", ("wait_newer",),
             "pick no longer waits on the epoch condition variable — "
             "empty replica sets must block on the NEXT epoch, not "
             "sleep-poll the controller"),
            ("_admit", ("try_admit",),
             "_admit no longer consults the admission gate — requests "
             "reach anatomy.admit ungated and SLO breaches stop shedding"),
        ),
        missing_hint="ingress fast path renamed? (update HOT_PATHS)",
    ),
    # ISSUE-17: admission decisions stay pure + accounted. The gate runs
    # per request BEFORE anatomy.admit; it must never speak the wire, and
    # every shed must land on the shed counter + flight ring.
    HotPath(
        file="ray_tpu/serve/admission.py",
        funcs=("decide", "try_admit", "_shed"),
        reason="per-request admission gate ahead of anatomy.admit",
        ban_rpc=True,
        ban_submit=True,
        forbid_imports=CONTROL_PLANE_IMPORTS,
        require_calls=(
            ("try_admit", ("decide",),
             "try_admit no longer routes through the pure decide() table — "
             "the policy must stay one tested function"),
            ("_shed", ("record_shed",),
             "_shed no longer records through anatomy.record_shed — "
             "ray_tpu_serve_shed_total and the flight-ring shed events "
             "go dark"),
        ),
        missing_hint="admission gate renamed? (update HOT_PATHS)",
    ),
    # ISSUE-18: the plane-store accounting ledger. Every _led_* update is
    # ONE dict operation under the ledger lock on the put/seal/pull path:
    # no instruments (the store gauges are producer-attached at import),
    # no RPC (reports ride the existing metrics_push beat), no control-
    # plane linkage — and the lifecycle hooks must STAY wired, or the
    # cluster memory view silently goes blind.
    HotPath(
        file="ray_tpu/core/shm_store.py",
        funcs=("_led_seal", "_led_pin", "_led_release", "_led_drop",
               "_led_access", "_led_mark_secondary", "_led_finish_seal",
               "put_bytes", "put_parts", "seal", "pin", "release",
               "delete", "get_bytes"),
        reason="per-object plane-store ledger on the put/seal/pull path",
        ban_metric_record=True,
        ban_rpc=True,
        ban_submit=True,
        forbid_imports=CONTROL_PLANE_IMPORTS,
        require_calls=(
            ("put_bytes", ("_led_seal",),
             "put_bytes no longer ledgers its seal — sealed objects "
             "vanish from cluster_memory_view"),
            ("put_parts", ("_led_seal",),
             "put_parts no longer ledgers its seal — vectored puts "
             "vanish from cluster_memory_view"),
            ("seal", ("_led_finish_seal",),
             "seal no longer finishes its pending ledger row — pulled "
             "copies vanish from cluster_memory_view"),
            ("pin", ("_led_pin",),
             "pin no longer ledgers — pinned bytes read as evictable in "
             "the memory view and the pinned gauge goes dark"),
            ("get_bytes", ("_led_access",),
             "get_bytes no longer stamps last-access — idle-age leak "
             "triage goes blind"),
        ),
        missing_hint="store ledger renamed? (update HOT_PATHS)",
    ),
    # ISSUE-13: both halves of the stamping pipeline stay wired — the
    # worker ships clocks on the done reply, the pool parent stamps them.
    HotPath(
        file="ray_tpu/core/process_pool.py",
        funcs=("_worker_main", "_reply_reader"),
        reason="phase-clock transport across the pool pipe",
        ban_metric_construct=False,
        require_calls=(
            ("_worker_main", ("phase_reply",),
             "_worker_main no longer ships phase clocks on the done "
             "reply — worker timeline lanes go dark"),
            ("_reply_reader", ("stamp_task_phases",),
             "_reply_reader no longer stamps worker phase clocks into the "
             "parent's timeline ring"),
        ),
        missing_hint="pool pipe path renamed? (update HOT_PATHS)",
    ),
)


def evaluate_hot_path(ctx, spec: HotPath) -> list:
    out = []
    rel = spec.file
    fctx = ctx.get(rel)
    if fctx is None:
        hint = spec.missing_hint or "hot path gone?"
        return [ctx.finding(RULE, rel, 0, f"{rel} missing — {hint}",
                            "missing-module")]
    tree = fctx.tree

    # module-level import bans
    for node in ast.walk(tree):
        if isinstance(node, (ast.Import, ast.ImportFrom)):
            mods = [a.name for a in node.names]
            mods.append(getattr(node, "module", "") or "")
            for m in mods:
                if any(m == f or m.startswith(f + ".")
                       for f in spec.forbid_imports):
                    out.append(ctx.finding(
                        RULE, rel, node.lineno,
                        f"imports {m} — this hot-path module must not link "
                        "the wire/control plane", f"import:{m}"))

    # module-level bind requirement
    if spec.require_module_bind:
        top_binds = 0
        for node in tree.body:
            if isinstance(node, ast.Assign) and \
                    isinstance(node.value, ast.Call) and \
                    callee_name(node.value) == "bind":
                top_binds += 1
        if top_binds == 0:
            out.append(ctx.finding(
                RULE, rel, 0,
                "no module-level instrument bind() — hot-loop metrics must "
                "be bound at import time, not per event", "no-module-bind"))

    # per-function bans
    banned = set()
    if spec.ban_metric_construct:
        banned |= METRIC_CONSTRUCT_CALLS
    if spec.ban_metric_record:
        banned |= METRIC_RECORD_CALLS
    if spec.ban_rpc:
        banned |= RPC_CALLS
    if spec.ban_submit:
        banned |= SUBMIT_CALLS

    if spec.funcs:
        fns = find_funcs(tree, set(spec.funcs))
    else:
        fns = {n.name: n for n in ast.walk(tree)
               if isinstance(n, ast.FunctionDef)}
    for fname in sorted(spec.funcs or fns):
        fn = fns.get(fname)
        if fn is None:
            out.append(ctx.finding(
                RULE, rel, 0,
                f"hot function {fname} missing — "
                f"{spec.missing_hint or 'update HOT_PATHS'}",
                f"missing:{fname}"))
            continue
        for lineno, callee in calls_in(fn, banned):
            if callee in METRIC_CONSTRUCT_CALLS:
                why = ("instruments bind at import/install time, never "
                       "per event")
            elif callee in METRIC_RECORD_CALLS:
                why = ("this per-frame path must stay metric-free — a "
                       "lock per frame is a measured regression; account "
                       "at coarser granularity")
            else:
                why = ("this hot path is RPC-free — data moves over "
                       "channels/plane pulls; control traffic goes "
                       "through the public API")
            out.append(ctx.finding(
                RULE, rel, lineno,
                f"{fname} calls {callee}() — {why}",
                f"{fname}:calls:{callee}"))
    # load-bearing plumbing that must stay
    for fname, one_of, msg in spec.require_calls:
        fn = fns.get(fname)
        if fn is not None and not calls_in(fn, set(one_of)):
            out.append(ctx.finding(RULE, rel, fn.lineno, msg,
                                   f"{fname}:requires:{'|'.join(one_of)}"))
    return out


def hot_path_findings(ctx, files=None) -> list:
    out = []
    for spec in HOT_PATHS:
        if files is not None and spec.file not in files:
            continue
        out.extend(evaluate_hot_path(ctx, spec))
    return out


@project_rule(RULE,
              doc="declared hot paths keep their purity contracts: "
                  "bind-only metrics, RPC-free bodies, required plumbing "
                  "(see HOT_PATHS — add new per-event paths there)")
def _hot_path_rule(ctx: ProjectCtx) -> list:
    return hot_path_findings(ctx)
