"""The concurrency pass: lock-graph + blocking + thread hygiene.

This is the rule family the runtime has been missing — its hardest
shipped bugs were all concurrency-invariant violations caught late:

- the ObjectRef ``__del__``-under-``runtime._lock`` re-entrancy deadlock
  (PR 5): a container holding the last ObjectRef was popped/dropped
  while the non-reentrant lock was held; the ref's ``__del__`` ran
  ``_on_ref_zero -> _free_plane_copies`` which re-takes the same lock.
  → ``ref-drop-under-lock``
- blocking work parked on shared bounded-reactor slots (PR 7 review)
  → ``blocking-under-lock`` + ``reactor-blocking-handler``
- leaked gang/member threads (PR 10 review)
  → ``thread-hygiene``

Analysis model (per module): lock objects are recognized at their
construction sites (``self.X = threading.Lock()`` in any method;
``X = threading.Lock()`` at module scope — Lock/RLock/Condition/
Semaphore/Event). ``with`` regions over known locks are walked with the
held-set threaded through, nested function bodies excluded (deferred
execution). Cross-method edges come from ``self.method()`` calls under a
held lock joined against each method's transitively-acquired lock set.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field

from ray_tpu.devtools.lint.core import (
    FileCtx, ProjectCtx, callee_name, file_rule, project_rule,
    qualname_index)

LOCK_FACTORIES = {
    "Lock": "lock", "RLock": "rlock", "Condition": "condition",
    "Semaphore": "semaphore", "BoundedSemaphore": "semaphore",
    "Event": "event",
}
# kinds that guard a `with` region (Event is tracked only as a wait target)
REGION_KINDS = {"lock", "rlock", "condition", "semaphore"}
NON_REENTRANT = {"lock"}


def _lock_kind(node) -> "str | None":
    """threading.Lock() / Lock() / threading.Condition(...) -> kind."""
    if not isinstance(node, ast.Call):
        return None
    f = node.func
    if isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name) \
            and f.value.id == "threading":
        return LOCK_FACTORIES.get(f.attr)
    if isinstance(f, ast.Name):
        return LOCK_FACTORIES.get(f.id)
    return None


def _recv_key(expr) -> "str | None":
    """A stable name for a call receiver: ``self.X`` -> "self.X",
    ``name`` -> "name", ``a.b.c`` -> "c" (tail)."""
    if isinstance(expr, ast.Name):
        return expr.id
    if isinstance(expr, ast.Attribute):
        if isinstance(expr.value, ast.Name) and expr.value.id == "self":
            return f"self.{expr.attr}"
        return expr.attr
    return None


@dataclass(frozen=True)
class Lock:
    name: str    # "self._lock" or module-level "_runtime_lock"
    kind: str


def module_locks(tree: ast.Module) -> dict:
    out = {}
    for node in tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 and \
                isinstance(node.targets[0], ast.Name):
            kind = _lock_kind(node.value)
            if kind:
                out[node.targets[0].id] = kind
    return out


def class_locks(cls: ast.ClassDef) -> dict:
    """{attr: kind} for every ``self.X = threading.Lock()``-style assign
    anywhere in the class's methods."""
    out = {}
    for node in ast.walk(cls):
        if isinstance(node, ast.Assign) and len(node.targets) == 1:
            t = node.targets[0]
            if isinstance(t, ast.Attribute) and \
                    isinstance(t.value, ast.Name) and t.value.id == "self":
                kind = _lock_kind(node.value)
                if kind:
                    out[t.attr] = kind
    return out


@dataclass
class MethodScan:
    """Everything the walker saw in one function body."""
    name: str
    qualname: str
    acquisitions: list = field(default_factory=list)  # (Lock, node, held)
    self_calls: list = field(default_factory=list)    # (method, node, held)
    ref_drops: list = field(default_factory=list)     # (node, detail, Lock)
    blocking: list = field(default_factory=list)      # (node, callee, Lock)


# Calls that park the calling thread: never under a lock, never on a
# shared reactor slot. `.join` gets str/os.path exclusions; `.wait`/
# `.notify*` are excused when the receiver is a known Condition (the CV
# protocol releases the lock while parked).
ALWAYS_BLOCKING = {
    "result", "recv", "recv_into", "recv_bytes", "recvmsg", "sendall",
    "sendmsg", "accept", "connect", "sleep", "select", "call",
    "pull", "pull_into", "pull_into_or_pull",
}
_JOIN_EXEMPT_RECV = {"os", "posixpath", "ntpath", "shlex", "string", "path",
                     "sep"}


def _classify_blocking(call: ast.Call, known_conditions: set) -> "str | None":
    """Return the blocking-callee label, or None if benign."""
    name = callee_name(call)
    if name is None:
        return None
    recv = call.func.value if isinstance(call.func, ast.Attribute) else None
    if name in ALWAYS_BLOCKING:
        return name
    if name == "join":
        if recv is None:
            return None           # bare join() — not a thread join
        if isinstance(recv, ast.Constant):
            return None           # ", ".join(...)
        rk = _recv_key(recv)
        if rk in _JOIN_EXEMPT_RECV or \
                (isinstance(recv, ast.Attribute) and recv.attr == "path"):
            return None           # os.path.join and friends
        return name
    if name in ("wait", "wait_for", "notify", "notify_all"):
        rk = _recv_key(recv) if recv is not None else None
        if rk is not None and rk in known_conditions:
            return None           # condition-variable protocol
        if name in ("notify", "notify_all") and recv is None:
            return None
        return name
    return None


class _FuncWalker:
    """Walk one function body threading the held-lock set through
    ``with`` regions. Nested function/lambda bodies are skipped — they
    run later, not under this lock."""

    def __init__(self, scan: MethodScan, resolve, known_conditions: set):
        self.scan = scan
        self.resolve = resolve            # expr -> Lock | None
        self.known_conditions = known_conditions

    def walk(self, body, held=()):
        for stmt in body:
            self._stmt(stmt, held)

    def _stmt(self, node, held):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            return
        if isinstance(node, (ast.With, ast.AsyncWith)):
            new_held = list(held)
            for item in node.items:
                self._expr(item.context_expr, tuple(new_held))
                lk = self.resolve(item.context_expr)
                if lk is not None and lk.kind in REGION_KINDS:
                    self.scan.acquisitions.append(
                        (lk, item.context_expr, tuple(new_held)))
                    new_held.append(lk)
            self.walk(node.body, tuple(new_held))
            return
        if isinstance(node, ast.Delete):
            for tgt in node.targets:
                self._ref_drop(node, f"del {ast.unparse(tgt)}", held)
        if isinstance(node, ast.Expr) and isinstance(node.value, ast.Call):
            call = node.value
            if isinstance(call.func, ast.Attribute) and \
                    call.func.attr in ("pop", "popitem", "clear"):
                recv = _recv_key(call.func.value)
                if recv is not None:
                    self._ref_drop(
                        node, f"discarded {recv}.{call.func.attr}()", held)
        # expressions of this statement (and child statements, via fields)
        for fname, value in ast.iter_fields(node):
            if fname in ("body", "orelse", "finalbody", "handlers"):
                continue
            for sub in (value if isinstance(value, list) else [value]):
                if isinstance(sub, ast.AST):
                    self._expr(sub, held)
        for fname in ("body", "orelse", "finalbody"):
            self.walk(getattr(node, fname, []) or [], held)
        for h in getattr(node, "handlers", []) or []:
            self.walk(h.body, held)

    def _ref_drop(self, node, detail, held):
        for lk in held:
            if lk.kind in NON_REENTRANT:
                self.scan.ref_drops.append((node, detail, lk))
                return

    def _expr(self, node, held):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            return  # deferred execution — not under this lock
        if isinstance(node, ast.Call):
            self._call(node, held)
        for child in ast.iter_child_nodes(node):
            self._expr(child, held)

    def _call(self, sub: ast.Call, held):
        # lock.acquire() participates in ordering like a with-region
        if isinstance(sub.func, ast.Attribute) and \
                sub.func.attr == "acquire":
            lk = self.resolve(sub.func.value)
            if lk is not None:
                self.scan.acquisitions.append((lk, sub, tuple(held)))
                return
        # self.method() — cross-method lock edges
        if isinstance(sub.func, ast.Attribute) and \
                isinstance(sub.func.value, ast.Name) and \
                sub.func.value.id == "self":
            self.scan.self_calls.append((sub.func.attr, sub, tuple(held)))
        if held:
            label = _classify_blocking(sub, self.known_conditions)
            if label is not None:
                self.scan.blocking.append((sub, label, held[-1]))


def _scan_scope(methods, locks: dict, qualnames: dict, prefix: str):
    """Scan a set of functions sharing one lock namespace (a class's
    methods, or a module's top-level functions)."""
    known_conditions = {name for name, kind in locks.items()
                        if kind == "condition"}

    def resolve(expr):
        rk = _recv_key(expr)
        if rk is None:
            return None
        kind = locks.get(rk)
        return Lock(rk, kind) if kind else None

    scans = {}
    for fn in methods:
        scan = MethodScan(fn.name, qualnames.get(id(fn), fn.name))
        _FuncWalker(scan, resolve, known_conditions).walk(fn.body)
        scans[fn.name] = scan
    return scans


def _transitive_locks(scans: dict) -> dict:
    """method -> set of lock names it may acquire, following self-calls."""
    direct = {m: {lk.name for lk, _, _ in s.acquisitions}
              for m, s in scans.items()}
    callees = {m: {c for c, _, _ in s.self_calls} for m, s in scans.items()}
    closure = {m: set(v) for m, v in direct.items()}
    changed = True
    while changed:
        changed = False
        for m in closure:
            for c in callees[m]:
                extra = closure.get(c, set()) - closure[m]
                if extra:
                    closure[m] |= extra
                    changed = True
    return closure


def _find_cycles(edges: dict) -> list:
    """Simple SCC-ish cycle listing over {a: {b: site}} adjacency: every
    distinct cycle's canonical node tuple, with one witness site."""
    cycles = {}

    def dfs(start, node, path, sites):
        for nxt, site in sorted(edges.get(node, {}).items()):
            if nxt == start and len(path) > 1:
                lo = path.index(min(path))
                canon = tuple(path[lo:] + path[:lo])
                cycles.setdefault(canon, sites + [site])
            elif nxt not in path and nxt > start:
                # only explore nodes >= start: each cycle found once, from
                # its smallest node
                dfs(start, nxt, path + [nxt], sites + [site])

    for n in sorted(edges):
        dfs(n, n, [n], [])
    return sorted(cycles.items())


def _concurrency_scans(ctx: FileCtx):
    """Per-scope MethodScans for a file: one scope per class + one for
    module-level functions. Module-level locks are visible inside classes
    too (``with _runtime_lock:`` in a method)."""
    qualnames = qualname_index(ctx.tree)
    mod_locks = module_locks(ctx.tree)
    scopes = []
    top_funcs = [n for n in ctx.tree.body if isinstance(n, ast.FunctionDef)]
    if top_funcs:
        scopes.append(("", _scan_scope(top_funcs, dict(mod_locks),
                                       qualnames, "")))
    for node in ctx.tree.body:
        if not isinstance(node, ast.ClassDef):
            continue
        locks = dict(mod_locks)
        locks.update({f"self.{a}": k
                      for a, k in class_locks(node).items()})
        methods = [n for n in node.body if isinstance(n, ast.FunctionDef)]
        if methods:
            scopes.append((node.name,
                           _scan_scope(methods, locks, qualnames,
                                       node.name + ".")))
    return scopes


@file_rule("lock-order",
           doc="lock-acquisition-order graph per class: flags cycles "
               "(ABBA deadlocks) and re-entrant acquisition of a "
               "non-reentrant lock across method calls")
def lock_order_findings(ctx: FileCtx) -> list:
    out = []
    for scope_name, scans in _concurrency_scans(ctx):
        closure = _transitive_locks(scans)
        lock_kinds = {}
        # edges: held -> acquired, with a witness (qualname, line)
        edges: dict = {}
        for m, scan in scans.items():
            for lk, node, held in scan.acquisitions:
                lock_kinds[lk.name] = lk.kind
                for h in held:
                    if h.name == lk.name and lk.kind in NON_REENTRANT:
                        out.append(ctx.finding(
                            "lock-order", node,
                            f"{scan.qualname} re-acquires non-reentrant "
                            f"{lk.name} already held — guaranteed "
                            "self-deadlock on this path",
                            f"{scan.qualname}:reacquire:{lk.name}"))
                    elif h.name != lk.name:
                        edges.setdefault(h.name, {}).setdefault(
                            lk.name, (scan.qualname, node.lineno))
            for callee, node, held in scan.self_calls:
                if not held or callee not in closure:
                    continue
                for h in held:
                    for t in sorted(closure[callee]):
                        if t == h.name:
                            if h.kind in NON_REENTRANT:
                                out.append(ctx.finding(
                                    "lock-order", node,
                                    f"{scan.qualname} holds non-reentrant "
                                    f"{h.name} while calling "
                                    f"self.{callee}(), which can acquire "
                                    f"{h.name} again — self-deadlock",
                                    f"{scan.qualname}:reacquire-via:"
                                    f"{callee}:{h.name}"))
                        else:
                            edges.setdefault(h.name, {}).setdefault(
                                t, (scan.qualname, node.lineno))
        for canon, sites in _find_cycles(
                {a: {b: s for b, s in bs.items()}
                 for a, bs in edges.items()}):
            qn, line = sites[0] if sites else (scope_name, 0)
            order = " -> ".join(canon + (canon[0],))
            out.append(Finding_for_cycle(ctx, scope_name, order, canon,
                                         line))
    return out


def Finding_for_cycle(ctx, scope_name, order, canon, line):
    from ray_tpu.devtools.lint.core import Finding

    return Finding(
        rule="lock-order", path=ctx.rel, line=line,
        message=f"lock-order cycle in {scope_name or 'module'}: {order} — "
                "two threads entering from different ends deadlock",
        key=f"{scope_name}:cycle:{'|'.join(sorted(canon))}")


@file_rule("ref-drop-under-lock",
           doc="a statement under a non-reentrant lock discards container "
               "contents (del / discarded .pop() / .clear()) — if the "
               "dropped value holds the last ObjectRef, its __del__ runs "
               "release paths that re-enter the lock (the PR-5 deadlock)")
def ref_drop_findings(ctx: FileCtx) -> list:
    out = []
    for scope_name, scans in _concurrency_scans(ctx):
        for m, scan in scans.items():
            for node, detail, lk in scan.ref_drops:
                out.append(ctx.finding(
                    "ref-drop-under-lock", node,
                    f"{scan.qualname}: {detail} under non-reentrant "
                    f"{lk.name} — a dropped value's __del__ (e.g. the last "
                    "ObjectRef -> _on_ref_zero) re-enters the lock; pop "
                    "under the lock, let the value die after release",
                    f"{scan.qualname}:{lk.name}:{detail}"))
    return out


@file_rule("blocking-under-lock",
           doc="RPC call/notify, socket ops, Future.result, Event.wait, "
               "thread join, or sleep while holding a lock — serializes "
               "every contender behind an unbounded wait")
def blocking_under_lock_findings(ctx: FileCtx) -> list:
    out = []
    for scope_name, scans in _concurrency_scans(ctx):
        for m, scan in scans.items():
            for node, label, lk in scan.blocking:
                out.append(ctx.finding(
                    "blocking-under-lock", node,
                    f"{scan.qualname}: {label}() while holding {lk.name} — "
                    "every contender parks behind this wait; move the "
                    "blocking work outside the lock",
                    f"{scan.qualname}:{lk.name}:{label}"))
    return out


# ----------------------------------------------------- reactor handlers

# Handlers not schema-flagged `blocking` run on the bounded shared reactor
# pool; one parked slot stalls unrelated ops behind it. `.call` on a peer,
# future results, joins, waits and sleeps are all parks.
_HANDLER_BLOCKING = {
    "result", "sleep", "select", "accept", "connect", "call",
    "wait", "wait_for", "join",
}


def _handler_tables(tree: ast.AST) -> dict:
    """op -> method-name for every ``{"op": self._h_x}`` dict entry."""
    out = {}
    for node in ast.walk(tree):
        if not isinstance(node, ast.Dict):
            continue
        for k, v in zip(node.keys, node.values):
            if isinstance(k, ast.Constant) and isinstance(k.value, str) \
                    and isinstance(v, ast.Attribute) \
                    and isinstance(v.value, ast.Name) \
                    and v.value.id == "self":
                out.setdefault(k.value, v.attr)
    return out


@project_rule("reactor-blocking-handler",
              doc="an RPC handler not schema-flagged `blocking` parks a "
                  "bounded shared reactor slot (Future.result, join, "
                  "wait, rpc call, sleep) — flag the op blocking=True or "
                  "move the work off the slot")
def reactor_blocking_findings(ctx: ProjectCtx) -> list:
    from ray_tpu.core.rpc import schema
    from ray_tpu.devtools.lint.rules.wire import HANDLER_FILES

    out = []
    for rel in HANDLER_FILES:
        fctx = ctx.get(rel)
        if fctx is None:
            continue
        qualnames = qualname_index(fctx.tree)
        tables = _handler_tables(fctx.tree)
        methods = {n.name: n for n in ast.walk(fctx.tree)
                   if isinstance(n, ast.FunctionDef)}
        for op, mname in sorted(tables.items()):
            spec = schema.REGISTRY.get(op)
            if spec is None or spec.blocking:
                continue
            fn = methods.get(mname)
            if fn is None:
                continue
            # direct body + one level of same-class self-calls
            bodies = [(fn, "")]
            for node in ast.walk(fn):
                if isinstance(node, ast.Call) and \
                        isinstance(node.func, ast.Attribute) and \
                        isinstance(node.func.value, ast.Name) and \
                        node.func.value.id == "self" and \
                        node.func.attr in methods:
                    bodies.append((methods[node.func.attr],
                                   f" (via self.{node.func.attr})"))
            seen = set()
            for body_fn, via in bodies:
                if id(body_fn) in seen:
                    continue
                seen.add(id(body_fn))
                for sub in ast.walk(body_fn):
                    if not isinstance(sub, ast.Call):
                        continue
                    label = _classify_blocking(sub, set())
                    if label is None or label not in _HANDLER_BLOCKING:
                        continue
                    qn = qualnames.get(id(fn), mname)
                    out.append(ctx.finding(
                        "reactor-blocking-handler", rel, sub.lineno,
                        f"handler {qn} for non-blocking op {op!r} calls "
                        f"{label}(){via} — parks a bounded reactor slot; "
                        "flag the schema blocking=True or defer the work",
                        f"{op}:{label}"))
    return out


# ------------------------------------------------------- thread hygiene


@file_rule("thread-hygiene",
           doc="every threading.Thread is daemon=True or reachable from a "
               "tracked join/shutdown path in its module — otherwise "
               "interpreter exit hangs on the leaked thread")
def thread_hygiene_findings(ctx: FileCtx) -> list:
    tree = ctx.tree
    qualnames = qualname_index(tree)

    def _is_thread_ctor(call: ast.Call) -> bool:
        f = call.func
        if isinstance(f, ast.Attribute) and f.attr == "Thread" and \
                isinstance(f.value, ast.Name) and f.value.id == "threading":
            return True
        return isinstance(f, ast.Name) and f.id == "Thread"

    # tails that get joined / daemonized somewhere in this module
    joined, daemonized = set(), set()
    loop_iter_by_var = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.For) and isinstance(node.target, ast.Name):
            it = _recv_key(node.iter)
            if it is None and isinstance(node.iter, ast.Call):
                # e.g. `for t in list(self._threads):`
                args = node.iter.args
                it = _recv_key(args[0]) if args else None
            if it is not None:
                loop_iter_by_var.setdefault(node.target.id, set()).add(it)
        if isinstance(node, ast.Call) and \
                isinstance(node.func, ast.Attribute):
            if node.func.attr == "join":
                rk = _recv_key(node.func.value)
                if rk is not None:
                    joined.add(rk)
                    for it in loop_iter_by_var.get(rk, ()):
                        joined.add(it)
            if node.func.attr == "setDaemon":
                rk = _recv_key(node.func.value)
                if rk is not None:
                    daemonized.add(rk)
        if isinstance(node, ast.Assign) and len(node.targets) == 1 and \
                isinstance(node.targets[0], ast.Attribute) and \
                node.targets[0].attr == "daemon":
            rk = _recv_key(node.targets[0].value)
            if rk is not None and \
                    isinstance(node.value, ast.Constant) and \
                    node.value.value is True:
                daemonized.add(rk)
    # second pass: for-loop joins recorded before their loop var was seen
    for var, iters in loop_iter_by_var.items():
        if var in joined:
            joined |= iters

    # enclosing-function index for keys
    out = []
    parents: dict = {}
    for parent in ast.walk(tree):
        for child in ast.iter_child_nodes(parent):
            parents[id(child)] = parent

    def _enclosing_qualname(node) -> str:
        cur = node
        while id(cur) in parents:
            cur = parents[id(cur)]
            q = qualnames.get(id(cur))
            if q:
                return q
        return "<module>"

    for node in ast.walk(tree):
        if not (isinstance(node, ast.Call) and _is_thread_ctor(node)):
            continue
        kw = {k.arg: k.value for k in node.keywords if k.arg}
        d = kw.get("daemon")
        if isinstance(d, ast.Constant) and d.value is True:
            continue
        if d is not None and not isinstance(d, ast.Constant):
            continue  # daemon=<expr>: caller decides; trust it
        # where does the thread object land?
        parent = parents.get(id(node))
        tail = None
        if isinstance(parent, ast.Assign) and len(parent.targets) == 1:
            tail = _recv_key(parent.targets[0])
        elif isinstance(parent, ast.Call) and \
                isinstance(parent.func, ast.Attribute) and \
                parent.func.attr == "append":
            tail = _recv_key(parent.func.value)
        elif isinstance(parent, ast.Attribute) and parent.attr == "start":
            tail = None  # Thread(...).start() — fire and forget
        if tail is not None and (tail in joined or tail in daemonized):
            continue
        if isinstance(parent, ast.Return):
            continue  # factory: the caller owns the thread's lifecycle
        tname = ""
        tgt = kw.get("target")
        if tgt is not None:
            tname = _recv_key(tgt) or ""
        qn = _enclosing_qualname(node)
        out.append(ctx.finding(
            "thread-hygiene", node,
            f"{qn}: threading.Thread({('target=' + tname) if tname else ''}"
            ") is neither daemon=True nor joined on any path in this "
            "module — a leaked non-daemon thread hangs interpreter exit",
            f"{qn}:thread:{tname or tail or 'anon'}"))
    return out
