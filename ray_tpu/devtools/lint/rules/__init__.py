"""Rule modules self-register on import. To add a rule: write a
``@file_rule``/``@project_rule`` function in one of these modules (or a
new one imported here) and add fixtures to tests/test_lint.py."""

from ray_tpu.devtools.lint.rules import (  # noqa: F401
    concurrency, exceptions, hotpath, wire)
