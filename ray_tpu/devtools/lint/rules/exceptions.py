"""``swallowed-exception``: exception hygiene for ``core/``.

A bare or over-broad ``except`` whose body neither re-raises, nor binds
and uses the exception, nor reports it anywhere (logger, flight
recorder, stderr) erases the only evidence of a failure — the class of
silence that turns a one-line fix into a week of chaos-test bisection.
Scoped to ``ray_tpu/core/`` where every swallowed error is a
control-plane or data-plane invariant disappearing.
"""

from __future__ import annotations

import ast

from ray_tpu.devtools.lint.core import FileCtx, file_rule, qualname_index

_BROAD = {"Exception", "BaseException"}

# A call with any of these callee names counts as reporting the failure.
_REPORT_NAMES = {
    "exception", "warning", "warn", "error", "info", "debug", "log",
    "print", "print_exc", "format_exc", "record", "record_event",
    "set_exception", "fail", "dump",
}


def _is_broad(handler: ast.ExceptHandler) -> bool:
    t = handler.type
    if t is None:
        return True
    names = []
    if isinstance(t, ast.Name):
        names = [t.id]
    elif isinstance(t, ast.Attribute):
        names = [t.attr]
    elif isinstance(t, ast.Tuple):
        for e in t.elts:
            if isinstance(e, ast.Name):
                names.append(e.id)
            elif isinstance(e, ast.Attribute):
                names.append(e.attr)
    return any(n in _BROAD for n in names)


def _handles_it(handler: ast.ExceptHandler) -> bool:
    bound = handler.name
    for node in handler.body:
        for sub in ast.walk(node):
            if isinstance(sub, ast.Raise):
                return True
            if bound and isinstance(sub, ast.Name) and sub.id == bound:
                return True  # the exception value is consumed somewhere
            if isinstance(sub, ast.Call):
                name = None
                f = sub.func
                if isinstance(f, ast.Attribute):
                    name = f.attr
                    recv = f.value
                    rname = (recv.id if isinstance(recv, ast.Name)
                             else recv.attr if isinstance(recv,
                                                          ast.Attribute)
                             else "")
                    if "log" in rname or "flight" in rname or \
                            "record" in rname:
                        return True
                elif isinstance(f, ast.Name):
                    name = f.id
                if name in _REPORT_NAMES:
                    return True
    return False


@file_rule("swallowed-exception", scope=("ray_tpu/core/**/*.py",),
           doc="bare/over-broad except in core/ that neither re-raises, "
               "uses the bound exception, nor reports it (logger / flight "
               "recorder) — failures must leave evidence")
def swallowed_exception_findings(ctx: FileCtx) -> list:
    qualnames = qualname_index(ctx.tree)
    # map each except handler to its enclosing function for stable keys
    out = []
    occurrence: dict = {}

    def visit(node, qn):
        for child in ast.iter_child_nodes(node):
            cqn = qualnames.get(id(child), qn)
            if isinstance(child, ast.ExceptHandler) and _is_broad(child) \
                    and not _handles_it(child):
                caught = ("bare except" if child.type is None
                          else f"except {ast.unparse(child.type)}")
                # keys discriminate per handler (caught type + ordinal), so
                # one baselined swallow cannot mask a NEW broad except added
                # to the same function later
                base = f"{qn or '<module>'}:swallow:{caught}"
                n = occurrence[base] = occurrence.get(base, 0) + 1
                out.append(ctx.finding(
                    "swallowed-exception", child,
                    f"{qn or '<module>'}: {caught} swallows without "
                    "re-raise, use, or report — at minimum "
                    "flight-record or debug-log the failure",
                    base if n == 1 else f"{base}#{n}"))
            visit(child, cqn)

    visit(ctx.tree, "")
    return out
