"""The frozen, append-only findings baseline (scripts/lint_baseline.json).

When a NEW rule class lands, the tree usually already violates it in a
few reviewed-and-tolerated places. Those findings are frozen here so the
analyzer exits 0 on the shipped tree while every *new* violation fails
the build — the schema-baseline idea applied to findings.

Integrity is machine-checked, not convention: every entry carries a
dense sequential ``id`` and a self-hash over ``id|rule|path|key``.
Appending a well-formed entry is legal; editing, deleting, or
renumbering a shipped entry breaks the hash chain (each entry's hash
also folds in the previous entry's hash) and fails the pass. Paying down
debt is done by DELETING nothing: when the finding disappears from the
tree the entry simply goes stale, and stale entries are reported so they
can be retired in an explicit ``--prune-baseline`` commit.
"""

from __future__ import annotations

import hashlib
import json
import os
from dataclasses import dataclass

BASELINE_VERSION = 1


@dataclass(frozen=True)
class BaselineEntry:
    id: int
    rule: str
    path: str
    key: str
    sha: str


def entry_sha(eid: int, rule: str, path: str, key: str,
              prev_sha: str) -> str:
    blob = f"{prev_sha}|{eid}|{rule}|{path}|{key}".encode()
    return hashlib.sha1(blob).hexdigest()[:12]


def validate(doc: dict) -> list:
    """Structural + append-only integrity errors for a baseline document."""
    errors = []
    if not isinstance(doc, dict) or doc.get("version") != BASELINE_VERSION:
        return [f"baseline: unknown version {doc.get('version')!r} "
                f"(expected {BASELINE_VERSION})"]
    entries = doc.get("entries")
    if not isinstance(entries, list):
        return ["baseline: 'entries' must be a list"]
    prev_sha = ""
    for i, e in enumerate(entries):
        want_id = i + 1
        if not isinstance(e, dict) or \
                sorted(e) != ["id", "key", "path", "rule", "sha"]:
            errors.append(f"baseline entry #{want_id}: malformed "
                          "(need exactly id/rule/path/key/sha)")
            continue
        if e["id"] != want_id:
            errors.append(
                f"baseline entry #{want_id}: id={e['id']} — entries are "
                "append-only with dense ids; renumbering or deleting a "
                "shipped entry is rejected")
        want = entry_sha(e["id"], e["rule"], e["path"], e["key"], prev_sha)
        if e["sha"] != want:
            errors.append(
                f"baseline entry #{e['id']} ({e['rule']}:{e['key']}): "
                "hash mismatch — shipped entries must not be edited "
                "(append a new entry instead)")
        prev_sha = e["sha"]
    return errors


def load(path: str) -> dict:
    if not os.path.exists(path):
        return {"version": BASELINE_VERSION, "entries": []}
    with open(path) as f:
        return json.load(f)


def entries(doc: dict):
    return [BaselineEntry(**e) for e in doc.get("entries", [])
            if isinstance(e, dict) and
            sorted(e) == ["id", "key", "path", "rule", "sha"]]


def match_key(entry_list) -> set:
    """The set of (rule, path, key) triples the baseline tolerates. One
    entry matches every finding with that triple — keys carry the
    qualname context, so that is 'this known pattern at this site', not a
    blank cheque for the file."""
    return {(e.rule, e.path, e.key) for e in entry_list}


def append_entries(doc: dict, findings) -> dict:
    """Return a new document with entries appended for every finding
    triple not already present (deduplicated, deterministic order)."""
    ents = list(doc.get("entries", []))
    known = {(e["rule"], e["path"], e["key"]) for e in ents}
    prev_sha = ents[-1]["sha"] if ents else ""
    new_triples = sorted({(f.rule, f.path, f.key) for f in findings
                          if (f.rule, f.path, f.key) not in known})
    for rule, path, key in new_triples:
        eid = len(ents) + 1
        sha = entry_sha(eid, rule, path, key, prev_sha)
        ents.append({"id": eid, "rule": rule, "path": path, "key": key,
                     "sha": sha})
        prev_sha = sha
    return {"version": BASELINE_VERSION, "entries": ents}


def rebuild(findings) -> dict:
    """A fresh baseline from scratch (``--prune-baseline``): the explicit,
    reviewed act that retires stale entries."""
    return append_entries({"version": BASELINE_VERSION, "entries": []},
                          findings)


def save(doc: dict, path: str) -> None:
    parent = os.path.dirname(os.path.abspath(path))
    os.makedirs(parent, exist_ok=True)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(doc, f, indent=1, sort_keys=False)
        f.write("\n")
    os.replace(tmp, path)
