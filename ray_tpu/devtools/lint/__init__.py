"""graftlint: the project-native static analyzer.

Pluggable AST rules over the ray_tpu tree — wire-schema contracts
(migrated from scripts/check_wire_schemas.py), hot-path purity, and the
concurrency/invariant pass (lock-order graph, ref-drop-under-lock,
blocking-under-lock, thread + exception hygiene).

Run it: ``python -m ray_tpu.devtools.lint`` (or the ``graftlint``
console script). Per-line suppression: ``# graftlint: disable=<rule>``.
Pre-existing debt is frozen in ``scripts/lint_baseline.json`` —
append-only, integrity-hashed (see baseline.py).
"""

from ray_tpu.devtools.lint.core import (  # noqa: F401
    RULES, FileCtx, Finding, ProjectCtx, Suppressions, file_rule,
    project_rule)
from ray_tpu.devtools.lint.runner import run_pass, main  # noqa: F401


def lint_source(source: str, rules, rel: str = "fixture.py",
                root: str = "."):
    """Run a subset of FILE rules over one in-memory source string —
    the fixture-test entry point (tests/test_lint.py)."""
    import ast as _ast

    import ray_tpu.devtools.lint.rules  # noqa: F401  (self-register)

    ctx = FileCtx(root, rel, source, _ast.parse(source, filename=rel))
    sup = Suppressions(source)
    out = []
    for name in rules:
        rule = RULES[name]
        if rule.kind != "file":
            raise ValueError(f"{name} is a project rule; lint_source only "
                             "drives file rules")
        for f in rule.fn(ctx):
            if not sup.is_suppressed(f.rule, f.line):
                out.append(f)
    return sorted(out, key=lambda f: (f.line, f.rule))
