"""runtime_env: per-task/actor environment configuration via plugins.

Parity: python/ray/_private/runtime_env/ — the plugin architecture (plugin.py)
with env_vars, working_dir (packaging.py URI-keyed caching), py_modules, and
pip/uv plugins. In the single-controller runtime, env setup happens in-process
around task execution (env vars are save/restored per task); the pip/uv/conda
plugins validate and cache but do NOT install (no network/package installs in
this environment) — they materialize into PYTHONPATH/prefix wiring when an
installer hook is provided.
"""

from __future__ import annotations

import contextlib
import hashlib
import os
import shutil
import sys
import tempfile
import threading
import uuid
from typing import Any, Callable, Optional

# Tasks with a runtime_env mutate process-global state (cwd, env vars) in the
# single-controller thread runtime; serialize them so two envs never interleave.
# (The multi-process cluster backend gives true per-worker isolation, as the
# reference does with one worker process per runtime_env.)
_APPLY_LOCK = threading.RLock()


class RuntimeEnvPlugin:
    """Reference: runtime_env/plugin.py RuntimeEnvPlugin ABC."""

    name: str = "base"
    priority: int = 50

    def validate(self, value: Any) -> Any:
        return value

    def create(self, value: Any, context: "RuntimeEnvContext") -> None:
        raise NotImplementedError

    def delete_uri(self, uri: str) -> None:
        pass


class RuntimeEnvContext:
    """Accumulated environment changes applied around task execution."""

    def __init__(self):
        self.env_vars: dict[str, str] = {}
        self.py_paths: list[str] = []
        self.working_dir: str | None = None


class EnvVarsPlugin(RuntimeEnvPlugin):
    name = "env_vars"

    def validate(self, value):
        if not isinstance(value, dict) or not all(
            isinstance(k, str) and isinstance(v, str) for k, v in value.items()
        ):
            raise ValueError("env_vars must be a dict[str, str]")
        return value

    def create(self, value, context):
        context.env_vars.update(value)


class WorkingDirPlugin(RuntimeEnvPlugin):
    """Packages a directory into the URI cache (reference: working_dir.py +
    packaging.py: zip → content-hash URI → per-node cache)."""

    name = "working_dir"
    CACHE = os.path.join(tempfile.gettempdir(), "ray_tpu_runtime_env", "working_dir")

    def validate(self, value):
        if not isinstance(value, str) or not os.path.isdir(value):
            raise ValueError(f"working_dir must be an existing directory, got {value!r}")
        return value

    def uri_for(self, path: str) -> str:
        h = hashlib.sha256()
        for root, _, files in sorted(os.walk(path)):
            for f in sorted(files):
                p = os.path.join(root, f)
                h.update(p.encode())
                with open(p, "rb") as fh:
                    h.update(fh.read())
        return f"workingdir://{h.hexdigest()[:16]}"

    def create(self, value, context):
        uri = self.uri_for(value)
        dest = os.path.join(self.CACHE, uri.split("//")[1])
        if not os.path.exists(dest):
            os.makedirs(self.CACHE, exist_ok=True)
            # atomic populate: copy aside, rename into place (concurrent creators
            # race benignly; an interrupted copy never becomes visible)
            tmp = f"{dest}.tmp-{uuid.uuid4().hex[:8]}"
            shutil.copytree(value, tmp)
            try:
                os.rename(tmp, dest)
            except OSError:
                shutil.rmtree(tmp, ignore_errors=True)
        context.working_dir = dest
        context.py_paths.append(dest)

    def delete_uri(self, uri: str) -> None:
        dest = os.path.join(self.CACHE, uri.split("//")[1])
        shutil.rmtree(dest, ignore_errors=True)


class PyModulesPlugin(RuntimeEnvPlugin):
    name = "py_modules"

    def validate(self, value):
        if not isinstance(value, list):
            raise ValueError("py_modules must be a list of paths")
        for p in value:
            if not os.path.exists(p):
                raise ValueError(f"py_module path does not exist: {p}")
        return value

    def create(self, value, context):
        context.py_paths.extend(os.path.abspath(p) for p in value)


class PipPlugin(RuntimeEnvPlugin):
    """Validates pip specs; installation requires an installer hook
    (reference: pip.py creates virtualenvs — no installs in this image)."""

    name = "pip"
    installer: Optional[Callable] = None

    def validate(self, value):
        if isinstance(value, dict):
            value = value.get("packages", [])
        if not isinstance(value, list) or not all(isinstance(p, str) for p in value):
            raise ValueError("pip must be a list of requirement strings")
        return value

    def create(self, value, context):
        if not value:
            return
        installer = type(self).installer
        if installer is None:
            raise RuntimeError(
                f"runtime_env {self.name!r} requires an installer hook in this "
                f"environment (package installation is disabled); set "
                f"{type(self).__name__}.installer."
            )
        prefix = installer(value)
        if prefix:
            context.py_paths.append(prefix)


class UvPlugin(PipPlugin):
    name = "uv"
    installer: Optional[Callable] = None  # independent of PipPlugin.installer


_PLUGINS: dict[str, RuntimeEnvPlugin] = {
    p.name: p for p in (EnvVarsPlugin(), WorkingDirPlugin(), PyModulesPlugin(),
                        PipPlugin(), UvPlugin())
}


def register_plugin(plugin: RuntimeEnvPlugin) -> None:
    _PLUGINS[plugin.name] = plugin


def validate_runtime_env(runtime_env: dict) -> dict:
    out = {}
    for key, value in (runtime_env or {}).items():
        plugin = _PLUGINS.get(key)
        if plugin is None:
            raise ValueError(f"Unknown runtime_env field: {key!r} "
                             f"(known: {sorted(_PLUGINS)})")
        out[key] = plugin.validate(value)
    return out


_CTX_CACHE: dict[str, RuntimeEnvContext] = {}
_CTX_CACHE_LOCK = threading.Lock()


def build_context(runtime_env: dict) -> RuntimeEnvContext:
    """Build (memoized) — identical runtime_env dicts share one context, so the
    working_dir content hash/copy is paid once per env, not once per task
    (reference: URI-keyed caching in runtime_env/packaging.py)."""
    import json

    try:
        key = json.dumps(runtime_env, sort_keys=True, default=repr)
    except TypeError:
        key = None
    if key is not None:
        with _CTX_CACHE_LOCK:
            cached = _CTX_CACHE.get(key)
        if cached is not None:
            return cached
    ctx = RuntimeEnvContext()
    env = validate_runtime_env(runtime_env)
    for k in sorted(env, key=lambda k: _PLUGINS[k].priority):
        _PLUGINS[k].create(env[k], ctx)
    if key is not None:
        with _CTX_CACHE_LOCK:
            _CTX_CACHE[key] = ctx
    return ctx


@contextlib.contextmanager
def apply_context(ctx: RuntimeEnvContext):
    """Apply env changes around a task.

    The lock guards only the mutate/restore windows, NOT user code — holding it
    across execution deadlocks any runtime_env task that waits on another
    runtime_env task (both run as threads of this process). Consequence of the
    thread runtime: two concurrently running runtime_env tasks can observe each
    other's env between windows; true isolation is one worker process per env
    (the reference's model, and this framework's multi-process backend)."""
    with _APPLY_LOCK:
        saved_env = {k: os.environ.get(k) for k in ctx.env_vars}
        saved_path = list(sys.path)
        saved_cwd = os.getcwd() if ctx.working_dir else None
        os.environ.update(ctx.env_vars)
        for p in ctx.py_paths:
            if p not in sys.path:
                sys.path.insert(0, p)
        if ctx.working_dir:
            os.chdir(ctx.working_dir)
    try:
        yield
    finally:
        with _APPLY_LOCK:
            for k, v in saved_env.items():
                if v is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = v
            sys.path[:] = saved_path
            if saved_cwd:
                os.chdir(saved_cwd)
