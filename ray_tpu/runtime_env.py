"""runtime_env: per-task/actor environment configuration via plugins.

Parity: python/ray/_private/runtime_env/ — the plugin architecture (plugin.py)
with env_vars, working_dir (packaging.py URI-keyed caching), py_modules, and
pip/uv plugins. In the single-controller runtime, env setup happens in-process
around task execution (env vars are save/restored per task); the pip/uv/conda
plugins validate and cache but do NOT install (no network/package installs in
this environment) — they materialize into PYTHONPATH/prefix wiring when an
installer hook is provided.
"""

from __future__ import annotations

import contextlib
import hashlib
import os
import shutil
import sys
import tempfile
import threading
import uuid
from typing import Any, Callable, Optional

# Tasks with a runtime_env mutate process-global state (cwd, env vars) in the
# single-controller thread runtime; serialize them so two envs never interleave.
# (The multi-process cluster backend gives true per-worker isolation, as the
# reference does with one worker process per runtime_env.)
_APPLY_LOCK = threading.RLock()


class RuntimeEnvPlugin:
    """Reference: runtime_env/plugin.py RuntimeEnvPlugin ABC."""

    name: str = "base"
    priority: int = 50

    def validate(self, value: Any) -> Any:
        return value

    def create(self, value: Any, context: "RuntimeEnvContext") -> None:
        raise NotImplementedError

    def delete_uri(self, uri: str) -> None:
        pass

    def cache_key(self, value: Any) -> Optional[str]:
        """Extra component for the build_context memo key. Plugins whose
        environments are CONTENT-addressed (uv/working_dir) return their
        content hash here so an edited source tree misses the context cache
        instead of silently reusing the stale environment."""
        return None


class RuntimeEnvContext:
    """Accumulated environment changes applied around task execution."""

    def __init__(self):
        self.env_vars: dict[str, str] = {}
        self.py_paths: list[str] = []
        self.working_dir: str | None = None
        self.profiler_dir: str | None = None  # jax XPlane capture around the task


class EnvVarsPlugin(RuntimeEnvPlugin):
    name = "env_vars"

    def validate(self, value):
        if not isinstance(value, dict) or not all(
            isinstance(k, str) and isinstance(v, str) for k, v in value.items()
        ):
            raise ValueError("env_vars must be a dict[str, str]")
        return value

    def create(self, value, context):
        context.env_vars.update(value)


class WorkingDirPlugin(RuntimeEnvPlugin):
    """Packages a directory into the URI cache (reference: working_dir.py +
    packaging.py: zip → content-hash URI → per-node cache)."""

    name = "working_dir"
    CACHE = os.path.join(tempfile.gettempdir(), "ray_tpu_runtime_env", "working_dir")

    def validate(self, value):
        if not isinstance(value, str) or not os.path.isdir(value):
            raise ValueError(f"working_dir must be an existing directory, got {value!r}")
        return value

    def uri_for(self, path: str) -> str:
        h = hashlib.sha256()
        for root, _, files in sorted(os.walk(path)):
            for f in sorted(files):
                p = os.path.join(root, f)
                h.update(p.encode())
                with open(p, "rb") as fh:
                    h.update(fh.read())
        return f"workingdir://{h.hexdigest()[:16]}"

    def create(self, value, context):
        uri = self.uri_for(value)
        dest = os.path.join(self.CACHE, uri.split("//")[1])
        if not os.path.exists(dest):
            os.makedirs(self.CACHE, exist_ok=True)
            # atomic populate: copy aside, rename into place (concurrent creators
            # race benignly; an interrupted copy never becomes visible)
            tmp = f"{dest}.tmp-{uuid.uuid4().hex[:8]}"
            shutil.copytree(value, tmp)
            try:
                os.rename(tmp, dest)
            except OSError:
                shutil.rmtree(tmp, ignore_errors=True)
        context.working_dir = dest
        context.py_paths.append(dest)

    def delete_uri(self, uri: str) -> None:
        dest = os.path.join(self.CACHE, uri.split("//")[1])
        shutil.rmtree(dest, ignore_errors=True)
        _drop_cached_contexts_referencing(dest)

    def cache_key(self, value) -> Optional[str]:
        try:
            return self.uri_for(value)
        except OSError:
            return None


class PyModulesPlugin(RuntimeEnvPlugin):
    name = "py_modules"

    def validate(self, value):
        if not isinstance(value, list):
            raise ValueError("py_modules must be a list of paths")
        for p in value:
            if not os.path.exists(p):
                raise ValueError(f"py_module path does not exist: {p}")
        return value

    def create(self, value, context):
        context.py_paths.extend(os.path.abspath(p) for p in value)


class PipPlugin(RuntimeEnvPlugin):
    """Validates pip specs; installation requires an installer hook
    (reference: pip.py creates virtualenvs — no installs in this image)."""

    name = "pip"
    installer: Optional[Callable] = None

    def validate(self, value):
        if isinstance(value, dict):
            value = value.get("packages", [])
        if not isinstance(value, list) or not all(isinstance(p, str) for p in value):
            raise ValueError("pip must be a list of requirement strings")
        return value

    def create(self, value, context):
        if not value:
            return
        installer = type(self).installer
        if installer is None:
            raise RuntimeError(
                f"runtime_env {self.name!r} requires an installer hook in this "
                f"environment (package installation is disabled); set "
                f"{type(self).__name__}.installer."
            )
        prefix = installer(value)
        if prefix:
            context.py_paths.append(prefix)


class UvPlugin(PipPlugin):
    """REAL uv installs (reference: runtime_env/uv.py): each distinct spec
    list gets a venv keyed by its content hash, created once and reused by
    every task/worker that names the same spec (reference: uri_cache.py).

    Hermetic by construction: installs run `--offline` (this image has no
    egress), so specs must be local paths / wheels / sdists — exactly what
    the tests exercise. Packages land in a plain `--target` directory (no
    venv: a venv would chain to the BASE interpreter and lose the driver
    env's setuptools/numpy) that is appended to the task's py_paths, which
    both the in-process and OS-worker execution paths apply — task code
    sees the env's packages ON TOP of the driver environment.

    An explicit `UvPlugin.installer` hook still overrides (operator-supplied
    installer for networked environments)."""

    name = "uv"
    installer: Optional[Callable] = None  # independent of PipPlugin.installer
    CACHE = os.path.join(tempfile.gettempdir(), "ray_tpu_runtime_env", "uv_envs")

    # setuptools writes these into the SOURCE tree on no-isolation builds;
    # hashing them would give every install a fresh key (cache never hits)
    _HASH_EXCLUDE = ("build", "dist", "__pycache__", ".git")

    def uri_for(self, specs: list) -> str:
        h = hashlib.sha256()
        h.update(sys.version.encode())
        for s in sorted(specs):
            h.update(b"\0" + s.encode())
            # local paths install by content, so the content keys the env
            p = s.split("==")[0]
            if os.path.isdir(p):
                # walk LAZILY: pruning dirs[:] only affects traversal when
                # the generator hasn't been exhausted (sorted(os.walk()) would
                # materialize everything first and ignore the prune); sorting
                # dirs in place also makes the traversal order deterministic
                for root, dirs, files in os.walk(p):
                    dirs[:] = sorted(d for d in dirs
                                     if d not in self._HASH_EXCLUDE
                                     and not d.endswith(".egg-info"))
                    for f in sorted(files):
                        fp = os.path.join(root, f)
                        h.update(fp.encode())
                        with open(fp, "rb") as fh:
                            h.update(fh.read())
            elif os.path.isfile(p):
                with open(p, "rb") as fh:
                    h.update(fh.read())
        return f"uv://{h.hexdigest()[:16]}"

    def create(self, value, context):
        if not value:
            return
        if type(self).installer is not None:  # operator hook wins
            prefix = type(self).installer(value)
            if prefix:
                context.py_paths.append(prefix)
            return
        uv = shutil.which("uv")
        if uv is None:
            raise RuntimeError(
                "runtime_env 'uv' requires the uv binary (not found on PATH) "
                "or a UvPlugin.installer hook")
        uri = self.uri_for(value)
        env_dir = os.path.join(self.CACHE, uri.split("//")[1])
        marker = os.path.join(env_dir, ".ray_tpu_ok")
        if not os.path.exists(marker):
            import subprocess

            os.makedirs(self.CACHE, exist_ok=True)
            tmp = f"{env_dir}.tmp-{uuid.uuid4().hex[:8]}"
            try:
                os.makedirs(tmp, exist_ok=True)
                subprocess.run(
                    [uv, "pip", "install", "--python", sys.executable,
                     "--target", tmp, "--offline", "--no-build-isolation",
                     *value],
                    check=True, capture_output=True, text=True, timeout=600)
                with open(os.path.join(tmp, ".ray_tpu_ok"), "w") as f:
                    f.write(uri)
                try:
                    os.rename(tmp, env_dir)  # atomic publish; loser cleans up
                except OSError:
                    shutil.rmtree(tmp, ignore_errors=True)
            except subprocess.CalledProcessError as e:
                shutil.rmtree(tmp, ignore_errors=True)
                raise RuntimeError(
                    f"uv install failed for {value}: {e.stderr[-500:]}") from e
            except BaseException:
                shutil.rmtree(tmp, ignore_errors=True)
                raise
        os.utime(env_dir)  # LRU touch for gc()
        context.py_paths.append(env_dir)

    def cache_key(self, value) -> Optional[str]:
        try:
            return self.uri_for(value)
        except OSError:
            return None

    def delete_uri(self, uri: str) -> None:
        path = os.path.join(self.CACHE, uri.split("//")[1])
        shutil.rmtree(path, ignore_errors=True)
        _drop_cached_contexts_referencing(path)

    @classmethod
    def gc(cls, max_envs: int = 8) -> list[str]:
        """Evict least-recently-used COMPLETED envs beyond `max_envs`
        (reference: uri_cache.py size-bounded eviction). In-progress
        `.tmp-*` install dirs are never counted or touched. Returns removed
        env names; memoized contexts referencing them are invalidated so the
        next task rebuilds instead of importing from a deleted path."""
        try:
            entries = [(os.path.getmtime(os.path.join(cls.CACHE, d)), d)
                       for d in os.listdir(cls.CACHE) if ".tmp-" not in d]
        except OSError:
            return []
        entries.sort(reverse=True)  # newest first
        removed = []
        for _, d in entries[max_envs:]:
            path = os.path.join(cls.CACHE, d)
            shutil.rmtree(path, ignore_errors=True)
            _drop_cached_contexts_referencing(path)
            removed.append(d)
        return removed


class ProfilerPlugin(RuntimeEnvPlugin):
    """Per-task accelerator profiling (reference: the runtime_env nsight/
    profiler plugins, runtime_env/nsight.py — GPU profilers attached around
    the worker; the TPU-native equivalent is a jax profiler XPlane capture
    scoped to the task's execution). Usage:

        @ray_tpu.remote(runtime_env={"profiler": {"dir": "/tmp/prof"}})
        def step(...): ...

    Artifacts land under dir/ (open with xprof / tensorboard's profile
    plugin); concurrent captures in one process are skipped, not errors
    (jax allows one active trace per process)."""

    name = "profiler"
    priority = 90  # innermost: wraps only the user code, after env/paths

    def validate(self, value):
        if not isinstance(value, dict) or not isinstance(value.get("dir"), str):
            raise ValueError('profiler must be {"dir": <output path>}')
        mode = value.get("mode", "jax")
        if mode != "jax":
            raise ValueError(f"unsupported profiler mode {mode!r} (only 'jax')")
        return value

    def create(self, value, context):
        context.profiler_dir = value["dir"]


_PLUGINS: dict[str, RuntimeEnvPlugin] = {
    p.name: p for p in (EnvVarsPlugin(), WorkingDirPlugin(), PyModulesPlugin(),
                        PipPlugin(), UvPlugin(), ProfilerPlugin())
}


def register_plugin(plugin: RuntimeEnvPlugin) -> None:
    _PLUGINS[plugin.name] = plugin


def validate_runtime_env(runtime_env: dict) -> dict:
    out = {}
    for key, value in (runtime_env or {}).items():
        plugin = _PLUGINS.get(key)
        if plugin is None:
            raise ValueError(f"Unknown runtime_env field: {key!r} "
                             f"(known: {sorted(_PLUGINS)})")
        out[key] = plugin.validate(value)
    return out


_CTX_CACHE: dict[str, RuntimeEnvContext] = {}
_CTX_CACHE_LOCK = threading.Lock()


def _drop_cached_contexts_referencing(path: str) -> None:
    """Evict memoized contexts whose py_paths point inside `path` (the env
    was deleted; serving the cached context would ImportError forever)."""
    with _CTX_CACHE_LOCK:
        stale = [k for k, ctx in _CTX_CACHE.items()
                 if any(p == path or p.startswith(path + os.sep)
                        for p in ctx.py_paths)]
        for k in stale:
            _CTX_CACHE.pop(k, None)


def build_context(runtime_env: dict) -> RuntimeEnvContext:
    """Build (memoized) — identical runtime_env dicts share one context, so the
    working_dir content hash/copy is paid once per env, not once per task
    (reference: URI-keyed caching in runtime_env/packaging.py). Plugins with
    content-addressed environments extend the key via cache_key() so edits to
    a referenced source tree rebuild instead of reusing the stale context."""
    import json

    try:
        key = json.dumps(runtime_env, sort_keys=True, default=repr)
        for k, v in sorted((runtime_env or {}).items()):
            plugin = _PLUGINS.get(k)
            extra = plugin.cache_key(v) if plugin is not None else None
            if extra:
                key += f"|{k}={extra}"
    except (TypeError, OSError):
        key = None
    if key is not None:
        with _CTX_CACHE_LOCK:
            cached = _CTX_CACHE.get(key)
        if cached is not None:
            return cached
    ctx = RuntimeEnvContext()
    env = validate_runtime_env(runtime_env)
    for k in sorted(env, key=lambda k: _PLUGINS[k].priority):
        _PLUGINS[k].create(env[k], ctx)
    if key is not None:
        with _CTX_CACHE_LOCK:
            _CTX_CACHE[key] = ctx
    return ctx


@contextlib.contextmanager
def apply_context(ctx: RuntimeEnvContext):
    """Apply env changes around a task.

    The lock guards only the mutate/restore windows, NOT user code — holding it
    across execution deadlocks any runtime_env task that waits on another
    runtime_env task (both run as threads of this process). Consequence of the
    thread runtime: two concurrently running runtime_env tasks can observe each
    other's env between windows; true isolation is one worker process per env
    (the reference's model, and this framework's multi-process backend)."""
    with _APPLY_LOCK:
        saved_env = {k: os.environ.get(k) for k in ctx.env_vars}
        saved_path = list(sys.path)
        saved_cwd = os.getcwd() if ctx.working_dir else None
        os.environ.update(ctx.env_vars)
        for p in ctx.py_paths:
            if p not in sys.path:
                sys.path.insert(0, p)
        if ctx.working_dir:
            os.chdir(ctx.working_dir)
    profiling = False
    if ctx.profiler_dir:
        try:
            import jax

            os.makedirs(ctx.profiler_dir, exist_ok=True)
            jax.profiler.start_trace(ctx.profiler_dir)
            profiling = True
        except Exception:
            profiling = False  # another trace active / no backend: skip
    try:
        yield
    finally:
        if profiling:
            try:
                import jax

                jax.profiler.stop_trace()
            except Exception:
                pass
        with _APPLY_LOCK:
            for k, v in saved_env.items():
                if v is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = v
            sys.path[:] = saved_path
            if saved_cwd:
                os.chdir(saved_cwd)
