"""Node providers: the cloud-side half of the autoscaler.

Parity: python/ray/autoscaler/ NodeProvider plugins (aws/gcp/... in
_private/<cloud>/) and the v2 instance FSM (instance lifecycle states in
instance_manager/). ``FakeNodeProvider`` mirrors the reference's
fake_multi_node provider used to test autoscaling without a cloud.
"""

from __future__ import annotations

import threading
import time
import uuid
from dataclasses import dataclass, field
from enum import Enum
from typing import Optional


class InstanceStatus(str, Enum):
    # v2 instance FSM (reference: autoscaler/v2 instance_manager states:
    # QUEUED -> REQUESTED -> ALLOCATED -> RAY_RUNNING -> RAY_STOPPING ->
    # TERMINATED, reconciler.py:59)
    QUEUED = "QUEUED"
    REQUESTED = "REQUESTED"
    ALLOCATED = "ALLOCATED"
    RUNNING = "RUNNING"
    DRAINING = "DRAINING"  # cordoned; running work finishing (RAY_STOPPING)
    STOPPING = "STOPPING"
    TERMINATED = "TERMINATED"


@dataclass
class Instance:
    instance_id: str
    node_type: str
    status: InstanceStatus = InstanceStatus.QUEUED
    launch_time: float = field(default_factory=time.time)
    node_id_hex: str | None = None  # filled once the node joins the cluster


class NodeProvider:
    """Plugin ABC (reference: autoscaler node_provider interface)."""

    def launch(self, node_type: str, count: int) -> list[Instance]:
        raise NotImplementedError

    def terminate(self, instance_ids: list[str]) -> None:
        raise NotImplementedError

    def non_terminated_instances(self) -> list[Instance]:
        raise NotImplementedError


class FakeNodeProvider(NodeProvider):
    """In-process provider: 'launching' a node adds a logical node to the
    scheduler after a configurable delay (reference: fake_multi_node)."""

    def __init__(self, node_type_resources: dict[str, dict[str, float]],
                 launch_delay_s: float = 0.0, runtime=None):
        self.node_type_resources = node_type_resources
        self.launch_delay_s = launch_delay_s
        self._instances: dict[str, Instance] = {}
        self._lock = threading.Lock()
        self._runtime = runtime

    def _rt(self):
        if self._runtime is not None:
            return self._runtime
        from ray_tpu.core.runtime import get_runtime

        return get_runtime()

    def launch(self, node_type: str, count: int) -> list[Instance]:
        out = []
        for _ in range(count):
            inst = Instance(f"fake-{uuid.uuid4().hex[:8]}", node_type,
                            InstanceStatus.REQUESTED)
            with self._lock:
                self._instances[inst.instance_id] = inst
            threading.Thread(target=self._boot, args=(inst,), daemon=True).start()
            out.append(inst)
        return out

    def _boot(self, inst: Instance) -> None:
        if self.launch_delay_s:
            time.sleep(self.launch_delay_s)
        with self._lock:
            if inst.status == InstanceStatus.TERMINATED:
                return  # terminated while booting: never join the cluster
        cfg = self.node_type_resources[inst.node_type]
        resources = dict(cfg.get("resources", {}))
        labels = dict(cfg.get("labels", {}))
        node_id = self._rt().scheduler.add_node(
            resources, labels=labels, slice_name=cfg.get("slice_name"))
        ghost = False
        with self._lock:
            if inst.status == InstanceStatus.TERMINATED:
                ghost = True  # raced with terminate during add_node
            else:
                inst.node_id_hex = node_id.hex()
                inst.status = InstanceStatus.RUNNING
        if ghost:
            self._rt().scheduler.remove_node(node_id)
        else:
            self._rt().scheduler.retry_pending_pgs()

    def terminate(self, instance_ids: list[str]) -> None:
        from ray_tpu._private.ids import NodeID

        with self._lock:
            insts = [self._instances[i] for i in instance_ids if i in self._instances]
            node_hexes = []
            for inst in insts:
                inst.status = InstanceStatus.TERMINATED
                if inst.node_id_hex:
                    node_hexes.append(inst.node_id_hex)
        for h in node_hexes:
            self._rt().scheduler.remove_node(NodeID.from_hex(h))

    def non_terminated_instances(self) -> list[Instance]:
        with self._lock:
            return [i for i in self._instances.values()
                    if i.status != InstanceStatus.TERMINATED]


class TPUVMNodeProvider(NodeProvider):
    """GCE TPU-VM provider surface (slice-granular node types, e.g. 'v5p-8').

    Reference pattern: autoscaler/_private/gcp/ node provider + the TPU pod
    head-resource convention (TPU-{pod_type}-head, accelerators/tpu.py:269).
    API calls are delegated to a `gcloud`-style command runner injected by the
    operator; in environments without cloud access this raises cleanly.
    """

    def __init__(self, project: str, zone: str, runner=None):
        self.project = project
        self.zone = zone
        self.runner = runner
        self._instances: dict[str, Instance] = {}
        self._lock = threading.Lock()

    def launch(self, node_type: str, count: int) -> list[Instance]:
        if self.runner is None:
            raise RuntimeError(
                "TPUVMNodeProvider requires a cloud command runner "
                "(no cloud access in this environment)"
            )
        out = []
        for _ in range(count):
            name = f"raytpu-{node_type}-{uuid.uuid4().hex[:6]}"
            self.runner(
                ["gcloud", "compute", "tpus", "tpu-vm", "create", name,
                 f"--zone={self.zone}", f"--accelerator-type={node_type}",
                 f"--project={self.project}"]
            )
            inst = Instance(name, node_type, InstanceStatus.REQUESTED)
            with self._lock:
                self._instances[name] = inst
            out.append(inst)
        return out

    def terminate(self, instance_ids: list[str]) -> None:
        if self.runner is None:
            raise RuntimeError("TPUVMNodeProvider requires a cloud command runner")
        for name in instance_ids:
            self.runner(["gcloud", "compute", "tpus", "tpu-vm", "delete", name,
                         f"--zone={self.zone}", "--quiet"])
            with self._lock:
                if name in self._instances:
                    self._instances[name].status = InstanceStatus.TERMINATED

    def non_terminated_instances(self) -> list[Instance]:
        # in-process view of what we launched (authoritative listing would page
        # `gcloud ... tpus list` through the runner); without it the autoscaler
        # must still see its own launches or min_workers would relaunch forever
        with self._lock:
            return [i for i in self._instances.values()
                    if i.status != InstanceStatus.TERMINATED]
