"""The autoscaler reconciler: demand → launch/terminate decisions.

Parity: autoscaler/v2/ Reconciler (reconciler.py:59) + ResourceDemandScheduler
(scheduler.py:895): each tick it reads (a) pending task/actor demand, (b)
pending placement groups, (c) current node utilization; bin-packs unmet demand
onto the cheapest feasible node types; launches up to max limits; terminates
nodes idle beyond the timeout. Works against any NodeProvider.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Optional

from ray_tpu.autoscaler.node_provider import InstanceStatus, NodeProvider


# Standing demand: resource shapes a consumer needs SOON but has not yet
# queued as tasks — an elastic gang REFORMING after a preemption submits no
# member tasks until capacity exists, so without this the reconciler would
# see zero demand and never launch the replacement node (the chicken-and-egg
# the reference solves with cluster resource constraints /
# request_resources()). Keyed so each consumer owns its entry.
_STANDING_DEMAND: dict[str, list] = {}
_SD_LOCK = threading.Lock()


def register_standing_demand(key: str, shapes: "list[dict]") -> None:
    """Declare resource shapes the autoscaler should provision for even
    though no task/PG currently carries them (ray.autoscaler.sdk
    request_resources analog). Replaces any prior entry under ``key``."""
    with _SD_LOCK:
        _STANDING_DEMAND[key] = [dict(s) for s in shapes]


def clear_standing_demand(key: str) -> None:
    with _SD_LOCK:
        _STANDING_DEMAND.pop(key, None)


def standing_demand() -> "list[dict]":
    with _SD_LOCK:
        return [dict(s) for shapes in _STANDING_DEMAND.values()
                for s in shapes]


@dataclass
class NodeTypeConfig:
    name: str
    resources: dict[str, float]
    min_workers: int = 0
    max_workers: int = 10


@dataclass
class AutoscalingConfig:
    node_types: list[NodeTypeConfig]
    idle_timeout_s: float = 60.0
    upscaling_speed: int = 2  # max launches per tick per type
    tick_interval_s: float = 1.0
    # An instance stuck in REQUESTED/ALLOCATED longer than this is abandoned
    # and relaunch-eligible (reference: reconciler stuck-instance handling)
    boot_timeout_s: float = 300.0


class Autoscaler:
    def __init__(self, config: AutoscalingConfig, provider: NodeProvider, runtime=None):
        self.config = config
        self.provider = provider
        self._runtime = runtime
        self._running = False
        self._thread: Optional[threading.Thread] = None
        self._idle_since: dict[str, float] = {}
        self.launch_count = 0
        self.terminate_count = 0

    def _rt(self):
        if self._runtime is not None:
            return self._runtime
        from ray_tpu.core.runtime import get_runtime

        return get_runtime()

    # ---- demand collection (reference: GcsAutoscalerStateManager feed) ----
    def get_pending_demand(self) -> list[dict[str, float]]:
        rt = self._rt()
        demand: list[dict[str, float]] = []
        # resource shapes of queued tasks
        with rt._lock:
            for entry in rt._tasks.values():
                if entry.state == "PENDING" and entry.spec.resources:
                    demand.append(dict(entry.spec.resources))
        for pg in rt.scheduler.placement_groups():
            if pg.state == "PENDING":
                for b in pg.bundles:
                    demand.append(dict(b.resources))
        demand.extend(standing_demand())
        return demand

    def _feasible_now(self, shape: dict[str, float]) -> bool:
        for n in self._rt().scheduler.nodes():
            if n.alive and all(n.total.get(k, 0.0) >= v for k, v in shape.items()):
                return True
        return False

    # ---- one reconcile tick ----
    def reconcile(self) -> dict:
        decisions = {"launched": {}, "terminated": []}
        instances = self.provider.non_terminated_instances()
        per_type = {}
        for inst in instances:
            per_type.setdefault(inst.node_type, []).append(inst)

        # 1) min_workers floors
        for nt in self.config.node_types:
            have = len(per_type.get(nt.name, []))
            if have < nt.min_workers:
                n = min(nt.min_workers - have, self.config.upscaling_speed)
                self.provider.launch(nt.name, n)
                self.launch_count += n
                decisions["launched"][nt.name] = decisions["launched"].get(nt.name, 0) + n
                per_type.setdefault(nt.name, []).extend([None] * n)

        # 2) unmet demand -> bin-pack onto node types (first feasible, smallest).
        # Nodes still booting (REQUESTED/ALLOCATED) count as satisfying demand so
        # one pending task can't launch a new node every tick until max_workers.
        booting = {}
        for inst in instances:
            if inst.status in (InstanceStatus.QUEUED, InstanceStatus.REQUESTED,
                               InstanceStatus.ALLOCATED):
                booting[inst.node_type] = booting.get(inst.node_type, 0) + 1
        unmet = [d for d in self.get_pending_demand() if not self._feasible_now(d)]
        if unmet:
            for shape in unmet:
                for nt in sorted(self.config.node_types,
                                 key=lambda t: sum(t.resources.values())):
                    fits = all(nt.resources.get(k, 0.0) >= v for k, v in shape.items())
                    if not fits:
                        continue
                    if booting.get(nt.name, 0) > 0:
                        booting[nt.name] -= 1  # a booting node will absorb this shape
                        break
                    have = len(per_type.get(nt.name, []))
                    launched = decisions["launched"].get(nt.name, 0)
                    if have + launched < nt.max_workers and launched < self.config.upscaling_speed:
                        self.provider.launch(nt.name, 1)
                        self.launch_count += 1
                        decisions["launched"][nt.name] = launched + 1
                        break

        # 3) stuck boots -> abandon (relaunch happens via demand next tick)
        now_wall = time.time()
        for inst in instances:
            if (inst.status in (InstanceStatus.REQUESTED, InstanceStatus.ALLOCATED)
                    and now_wall - inst.launch_time > self.config.boot_timeout_s):
                self.provider.terminate([inst.instance_id])
                decisions["terminated"].append(inst.instance_id)

        # 4) idle nodes -> DRAIN (cordon) after the timeout, then terminate
        # only once the cordoned node is verifiably still idle — the two-step
        # protocol of the reference's v2 reconciler (drain-before-terminate),
        # so a task placed in the idle-check window is never yanked.
        rt = self._rt()
        now = time.monotonic()
        by_node_id = {i.node_id_hex: i for i in instances if i.node_id_hex}
        for node in rt.scheduler.nodes():
            nid = node.node_id.hex()
            inst = by_node_id.get(nid)
            if inst is None or not node.alive:
                continue
            if inst.status == InstanceStatus.DRAINING:
                if rt.scheduler.node_is_idle(node.node_id):
                    self.provider.terminate([inst.instance_id])
                    self.terminate_count += 1
                    decisions["terminated"].append(inst.instance_id)
                else:
                    # work is still finishing on the cordoned node; keep
                    # waiting (or un-cordon if new demand has nowhere to go)
                    if not self._feasible_without(node) and self.get_pending_demand():
                        rt.scheduler.undrain_node(node.node_id)
                        inst.status = InstanceStatus.RUNNING
                self._idle_since.pop(nid, None)
                continue
            busy = any(node.total.get(k, 0) != node.available.get(k, 0) for k in node.total)
            if busy:
                self._idle_since.pop(nid, None)
                continue
            first_idle = self._idle_since.setdefault(nid, now)
            nt = next((t for t in self.config.node_types if t.name == inst.node_type), None)
            same_type = [i for i in instances if i.node_type == inst.node_type
                         and i.status == InstanceStatus.RUNNING]
            if (now - first_idle >= self.config.idle_timeout_s and nt is not None
                    and len(same_type) > nt.min_workers):
                if rt.scheduler.drain_node(node.node_id):
                    inst.status = InstanceStatus.DRAINING
                    decisions.setdefault("draining", []).append(inst.instance_id)
                self._idle_since.pop(nid, None)
        return decisions

    def _feasible_without(self, node) -> bool:
        """Is any OTHER live node able to take new work? (If not, and demand
        exists, an un-idle draining node should be un-cordoned.)"""
        return any(n.alive and not n.draining and n.node_id != node.node_id
                   for n in self._rt().scheduler.nodes())

    # ---- loop ----
    def start(self) -> None:
        self._running = True
        self._thread = threading.Thread(target=self._loop, daemon=True, name="autoscaler")
        self._thread.start()

    def _loop(self) -> None:
        import logging

        log = logging.getLogger("ray_tpu.autoscaler")
        while self._running:
            try:
                self.reconcile()
            except Exception:
                log.warning("autoscaler reconcile failed", exc_info=True)
            time.sleep(self.config.tick_interval_s)

    def stop(self) -> None:
        self._running = False
