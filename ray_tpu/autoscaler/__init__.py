"""Autoscaler v2: demand-driven node scaling with pluggable providers.

Parity: python/ray/autoscaler/v2/ — InstanceManager
(instance_manager/instance_manager.py:29), Reconciler (reconciler.py:59),
ResourceDemandScheduler (scheduler.py:895 bin-packing) and the NodeProvider
plugin contract (autoscaler/_private/ node_provider). The TPU-native provider
surface is slice-granular: a node type is a TPU slice topology (v5p-8 etc.),
and the demand scheduler bin-packs gang (placement-group) demand onto whole
slices — reference: SlicePlacementGroup util/tpu.py:420.
"""

from ray_tpu.autoscaler.autoscaler import (
    Autoscaler,
    AutoscalingConfig,
    NodeTypeConfig,
)
from ray_tpu.autoscaler.node_provider import (
    FakeNodeProvider,
    Instance,
    InstanceStatus,
    NodeProvider,
    TPUVMNodeProvider,
)

__all__ = [
    "Autoscaler",
    "AutoscalingConfig",
    "NodeTypeConfig",
    "NodeProvider",
    "FakeNodeProvider",
    "TPUVMNodeProvider",
    "Instance",
    "InstanceStatus",
]
