"""GCE TPU-VM node provider: launch/terminate real TPU slices over the
Cloud TPU REST API.

Parity: python/ray/autoscaler/_private/gcp/node_provider.py (GCPNodeProvider)
+ gcp/node.py (GCPTPU resource: create/delete/list via the tpu v2 REST
surface, operation polling) + gcp/config.py (bootstrap). Re-scoped TPU-first:
node types ARE accelerator types (``v5p-8``, ``v6e-16``…), one instance = one
slice, and the bootstrap script joins the cluster with
``ray_tpu start --address <head> --token <token>`` (the repo's raylet-join
entrypoint) instead of a ray-specific image.

The HTTP layer is injectable (``transport``) so unit tests run against
recorded responses with zero egress; production uses urllib against
``tpu.googleapis.com`` with a token from the GCE metadata server or an
operator-provided ``token_provider``.
"""

from __future__ import annotations

import inspect
import json
import logging
import threading
import time
import urllib.error
import urllib.request
import uuid
from typing import Callable, Optional

from ray_tpu.autoscaler.node_provider import Instance, InstanceStatus, NodeProvider

logger = logging.getLogger("ray_tpu")

TPU_API = "https://tpu.googleapis.com/v2"
METADATA_TOKEN_URL = ("http://metadata.google.internal/computeMetadata/v1/"
                      "instance/service-accounts/default/token")
# The VM-local preemption signal: flips to "TRUE" when GCE schedules this
# VM for reclaim (spot/preemptible TPU-VMs get ~30s of notice). Polled by
# the node agent's watcher thread (node_agent.py) and the driver-side
# watcher (train/elastic.py) — reference: the ray spot-drain handler
# reading the same endpoint.
PREEMPTED_METADATA_URL = ("http://metadata.google.internal/computeMetadata/"
                          "v1/instance/preempted")


def poll_preempted(url: str = PREEMPTED_METADATA_URL,
                   timeout: float = 5.0) -> bool:
    """One metadata-server probe: True iff the VM has a preemption notice.
    Unreachable metadata (not on GCE, CI) reads as 'not preempted'."""
    req = urllib.request.Request(url, headers={"Metadata-Flavor": "Google"})
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.read().decode("utf-8", "replace").strip().upper() == "TRUE"
    except Exception:
        return False

# TPU node state -> instance FSM (reference: gcp/node.py GCPTPUNode.is_running
# / autoscaler v2 reconciler states, reconciler.py:59)
_TPU_STATE_MAP = {
    "CREATING": InstanceStatus.REQUESTED,
    "STARTING": InstanceStatus.ALLOCATED,
    "READY": InstanceStatus.RUNNING,
    "RESTARTING": InstanceStatus.ALLOCATED,
    "STOPPING": InstanceStatus.STOPPING,
    "STOPPED": InstanceStatus.STOPPING,
    "DELETING": InstanceStatus.STOPPING,
    "TERMINATED": InstanceStatus.TERMINATED,
    "PREEMPTED": InstanceStatus.TERMINATED,
}


def _default_transport(method: str, url: str, body: Optional[dict],
                       headers: dict) -> tuple[int, dict]:
    """urllib transport (production path; tests inject a fake)."""
    data = json.dumps(body).encode() if body is not None else None
    req = urllib.request.Request(url, method=method, data=data, headers=headers)
    try:
        with urllib.request.urlopen(req, timeout=60) as resp:
            payload = resp.read()
            return resp.status, json.loads(payload) if payload else {}
    except urllib.error.HTTPError as e:
        try:
            detail = json.loads(e.read())
        except Exception:
            detail = {"error": {"message": str(e)}}
        return e.code, detail


def metadata_token_provider() -> str:
    """Access token from the GCE metadata server (the default when the head
    itself runs on a GCE/TPU VM, like the reference's VM-default credentials)."""
    req = urllib.request.Request(METADATA_TOKEN_URL,
                                 headers={"Metadata-Flavor": "Google"})
    with urllib.request.urlopen(req, timeout=10) as resp:
        return json.loads(resp.read())["access_token"]


class TpuVmApi:
    """Thin Cloud-TPU v2 REST client: create/get/list/delete + operation
    polling (reference: gcp/node.py GCPTPU wait_for_operation)."""

    def __init__(self, project: str, zone: str,
                 transport: Callable = _default_transport,
                 token_provider: Callable[[], str] = metadata_token_provider,
                 poll_interval_s: float = 2.0):
        self.project = project
        self.zone = zone
        self._transport = transport
        self._token_provider = token_provider
        self._poll_interval_s = poll_interval_s

    @property
    def parent(self) -> str:
        return f"projects/{self.project}/locations/{self.zone}"

    def _call(self, method: str, path: str, body: Optional[dict] = None) -> dict:
        url = f"{TPU_API}/{path}" if not path.startswith("http") else path
        headers = {"Content-Type": "application/json",
                   "Authorization": f"Bearer {self._token_provider()}"}
        status, payload = self._transport(method, url, body, headers)
        if status >= 400:
            msg = payload.get("error", {}).get("message", str(payload))[:300]
            raise RuntimeError(f"TPU API {method} {path}: HTTP {status}: {msg}")
        return payload

    def create_node(self, node_id: str, accelerator_type: str,
                    runtime_version: str, startup_script: str = "",
                    labels: Optional[dict] = None,
                    spot: bool = False) -> dict:
        body = {
            "acceleratorType": accelerator_type,
            "runtimeVersion": runtime_version,
            "labels": labels or {},
            "metadata": ({"startup-script": startup_script}
                         if startup_script else {}),
        }
        if spot:
            body["schedulingConfig"] = {"spot": True}
        return self._call("POST", f"{self.parent}/nodes?nodeId={node_id}", body)

    def get_node(self, node_id: str) -> dict:
        return self._call("GET", f"{self.parent}/nodes/{node_id}")

    def list_nodes(self) -> list[dict]:
        out, token = [], None
        while True:
            path = f"{self.parent}/nodes"
            if token:
                path += f"?pageToken={token}"
            page = self._call("GET", path)
            out.extend(page.get("nodes", []))
            token = page.get("nextPageToken")
            if not token:
                return out

    def delete_node(self, node_id: str) -> dict:
        return self._call("DELETE", f"{self.parent}/nodes/{node_id}")

    def wait_operation(self, op: dict, timeout_s: float = 600.0) -> dict:
        """Poll a long-running operation to completion (create/delete)."""
        deadline = time.monotonic() + timeout_s
        while not op.get("done"):
            if time.monotonic() > deadline:
                raise TimeoutError(f"TPU operation {op.get('name')} timed out")
            time.sleep(self._poll_interval_s)
            op = self._call("GET", op["name"])
        if "error" in op:
            raise RuntimeError(f"TPU operation failed: {op['error']}")
        return op


def slice_host_count(accelerator_type: str) -> int:
    """UPPER BOUND on worker VMs in a TPU slice: the accelerator-type
    suffix counts cores or chips (generation-dependent), and GCE never
    packs fewer than 4 of either on a host VM — v4-32 is 4 hosts of 8,
    v6e-16 is 4 hosts of 4. Every host runs the same startup script, so a
    join token needs one redemption per host; dividing by the smallest
    host size deliberately over-counts dense generations, because a spare
    redemption on a TTL'd token is far cheaper than a stranded slice whose
    later workers can never join."""
    try:
        n = int(accelerator_type.rsplit("-", 1)[1])
    except (IndexError, ValueError):
        return 1
    return max(1, n // 4)


def join_startup_script(head_address: str, token: str,
                        num_cpus: int = 4) -> str:
    """The bootstrap a freshly-created TPU VM runs to join the cluster —
    the repo's `ray start --address` analog, shipped as VM startup metadata
    (reference: gcp/config.py injecting the ray bootstrap into user-data).

    ``token`` should be a short-lived single-use join token
    (ControlPlane.mint_join_token), NOT the session token: VM metadata is
    readable by any process on the VM for its whole life, so the durable
    credential must never land there. The agent exchanges the join token
    for the session token at first hello."""
    return (
        "#!/bin/bash\n"
        f"python3 -m ray_tpu.scripts.cli start --address {head_address} "
        f"--token {token} --num-cpus {num_cpus} "
        ">> /var/log/ray_tpu_join.log 2>&1 &\n"
    )


class GceTpuNodeProvider(NodeProvider):
    """NodeProvider over real TPU-VM slices.

    launch() creates slices whose startup script joins this cluster's head;
    non_terminated_instances() reconciles against the live API list (filtered
    by the cluster label), mapping TPU states onto the instance FSM — the
    autoscaler's reconcile loop then sees cloud truth, not just local intent
    (reference: GCPNodeProvider.non_terminated_nodes + v2 reconciler)."""

    CLUSTER_LABEL = "ray-tpu-cluster"

    def __init__(self, project: str, zone: str, cluster_name: str,
                 head_address: str, cluster_token: Optional[str] = None,
                 runtime_version: str = "tpu-ubuntu2204-base",
                 api: Optional[TpuVmApi] = None,
                 transport: Callable = _default_transport,
                 token_provider: Callable[[], str] = metadata_token_provider,
                 spot: bool = False,
                 join_token_provider: Optional[Callable[[], str]] = None):
        """``join_token_provider`` (typically the head's
        ``control_plane.mint_join_token``) mints a fresh short-lived,
        single-use credential per launched node, keeping the long-lived
        session token out of VM startup metadata. ``cluster_token`` is the
        legacy fallback when no provider is wired (token lands in metadata
        verbatim — avoid outside dev clusters)."""
        if cluster_token is None and join_token_provider is None:
            raise ValueError(
                "need a join_token_provider (preferred) or cluster_token")
        self.api = api or TpuVmApi(project, zone, transport=transport,
                                   token_provider=token_provider)
        self.cluster_name = cluster_name
        self.head_address = head_address
        self.cluster_token = cluster_token
        self.join_token_provider = join_token_provider
        self.runtime_version = runtime_version
        self.spot = spot
        self._instances: dict[str, Instance] = {}
        self._lock = threading.Lock()

    def _node_token(self, node_type: "str | None" = None) -> str:
        """A fresh per-node join token when a provider is wired (redeemable
        once per worker VM of the slice — all hosts run the same startup
        script); the long-lived cluster token only as legacy fallback."""
        if self.join_token_provider is not None:
            uses = slice_host_count(node_type) if node_type else 1
            try:
                params = inspect.signature(
                    self.join_token_provider).parameters
                accepts_uses = "max_uses" in params or any(
                    p.kind is inspect.Parameter.VAR_KEYWORD
                    for p in params.values())
            except (TypeError, ValueError):  # builtins/C callables
                accepts_uses = False
            if accepts_uses:
                return self.join_token_provider(max_uses=uses)
            if uses > 1:
                # one single-use token in a script every host runs: worker 0
                # joins, workers 1..N are locked out of a billing slice
                logger.warning(
                    "join_token_provider does not accept max_uses; the %d "
                    "extra hosts of a %s slice will fail to join — mint "
                    "with ControlPlane.mint_join_token", uses - 1, node_type)
            return self.join_token_provider()
        return self.cluster_token

    def launch(self, node_type: str, count: int) -> list[Instance]:
        out = []
        for _ in range(count):
            name = f"raytpu-{self.cluster_name}-{uuid.uuid4().hex[:6]}"
            op = self.api.create_node(
                name, accelerator_type=node_type,
                runtime_version=self.runtime_version,
                startup_script=join_startup_script(
                    self.head_address, self._node_token(node_type)),
                labels={self.CLUSTER_LABEL: self.cluster_name,
                        "ray-tpu-node-type": node_type.replace(".", "-")},
                spot=self.spot,
            )
            inst = Instance(name, node_type, InstanceStatus.REQUESTED)
            with self._lock:
                self._instances[name] = inst
            # operations complete in the background; the reconcile in
            # non_terminated_instances picks up READY (don't block launch)
            threading.Thread(target=self._await_create, args=(name, op),
                             daemon=True).start()
            out.append(inst)
        return out

    def _await_create(self, name: str, op: dict) -> None:
        try:
            self.api.wait_operation(op)
            with self._lock:
                inst = self._instances.get(name)
                if inst is not None and inst.status == InstanceStatus.REQUESTED:
                    inst.status = InstanceStatus.ALLOCATED
        except Exception as e:
            logger.warning("TPU slice %s failed to create: %s", name, e)
            with self._lock:
                inst = self._instances.get(name)
                if inst is not None:
                    inst.status = InstanceStatus.TERMINATED

    def terminate(self, instance_ids: list[str]) -> None:
        """Fire the deletes and return — operation completion is observed by
        the reconcile in non_terminated_instances (a synchronous wait here
        would stall the autoscaler's tick for up to minutes per slice)."""
        for name in instance_ids:
            try:
                op = self.api.delete_node(name)
            except Exception as e:
                logger.warning("TPU slice %s delete failed: %s", name, e)
                op = None
            with self._lock:
                inst = self._instances.get(name)
                if inst is not None:
                    inst.status = InstanceStatus.TERMINATED
            if op is not None:
                threading.Thread(target=self._await_delete, args=(name, op),
                                 daemon=True).start()

    def _await_delete(self, name: str, op: dict) -> None:
        try:
            self.api.wait_operation(op, timeout_s=300)
        except Exception as e:
            logger.warning("TPU slice %s delete did not complete: %s", name, e)

    def non_terminated_instances(self) -> list[Instance]:
        """Reconcile local intent with the cloud list: adopt foreign-created
        nodes carrying our cluster label, advance states, and drop nodes the
        cloud no longer reports."""
        try:
            live = {
                n["name"].rsplit("/", 1)[-1]: n
                for n in self.api.list_nodes()
                if n.get("labels", {}).get(self.CLUSTER_LABEL) == self.cluster_name
            }
        except Exception as e:
            logger.warning("TPU list failed (%s); serving cached view", e)
            with self._lock:
                return [i for i in self._instances.values()
                        if i.status != InstanceStatus.TERMINATED]
        with self._lock:
            for name, node in live.items():
                mapped = _TPU_STATE_MAP.get(node.get("state", ""),
                                            InstanceStatus.REQUESTED)
                inst = self._instances.get(name)
                if inst is None:
                    inst = self._instances[name] = Instance(
                        name,
                        node.get("labels", {}).get("ray-tpu-node-type",
                                                   node.get("acceleratorType", "")),
                        mapped)
                elif inst.status != InstanceStatus.TERMINATED:
                    inst.status = mapped
            for name, inst in self._instances.items():
                if name not in live and inst.status in (
                        InstanceStatus.ALLOCATED, InstanceStatus.RUNNING,
                        InstanceStatus.STOPPING):
                    # cloud no longer reports it (deleted/preempted out-of-band)
                    inst.status = InstanceStatus.TERMINATED
            return [i for i in self._instances.values()
                    if i.status != InstanceStatus.TERMINATED]

    # ---- operator conveniences -------------------------------------------
    def node_ips(self, instance_id: str) -> list[str]:
        """Internal IPs of a slice's workers (networkEndpoints of the node;
        reference: gcp/node.py GCPTPUNode.get_internal_ips)."""
        node = self.api.get_node(instance_id)
        return [ep.get("ipAddress", "") for ep in node.get("networkEndpoints", [])]

    def ssh_join_command(self, instance_id: str) -> list[str]:
        """Manual-bootstrap fallback (startup scripts need image support):
        the gcloud ssh line an operator runs to join a slice by hand."""
        with self._lock:
            inst = self._instances.get(instance_id)
        node_type = inst.node_type if inst else None
        if node_type is None and self.join_token_provider is not None:
            # cache miss (fresh process, pre-reconcile): the command still
            # runs on --worker=all, so the token MUST cover every host —
            # ask the API rather than defaulting to a single-use token
            # that would strand all hosts but one of a multi-host slice
            try:
                node_type = self.api.get_node(instance_id).get(
                    "acceleratorType")
            except Exception:
                logger.warning(
                    "could not resolve accelerator type of %s; join token "
                    "minted single-use — multi-host slices need "
                    "mint_join_token(max_uses=<hosts>)", instance_id)
        join = (f"python3 -m ray_tpu.scripts.cli start "
                f"--address {self.head_address} "
                f"--token {self._node_token(node_type)}")
        return ["gcloud", "compute", "tpus", "tpu-vm", "ssh", instance_id,
                f"--zone={self.api.zone}", f"--project={self.api.project}",
                "--worker=all", f"--command={join}"]
