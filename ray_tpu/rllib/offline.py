"""Offline RL: train from recorded experience, no environment interaction.

Parity: rllib/offline/ (OfflineData over Ray Data) + the offline algorithm
family — BC (algorithms/bc/), MARWIL (algorithms/marwil/), and CQL
(algorithms/cql/, discrete variant). Datasets are JSONL/parquet transition
rows read through ray_tpu.data (the reference reads SampleBatches through
Ray Data the same way), or numpy dicts passed directly.

Row schema: {"obs": [...], "action": int, "reward": float,
"next_obs": [...], "done": 0/1}.
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Any

import numpy as np

from ray_tpu.rllib.ppo import _mlp_apply, _mlp_init


# ------------------------------------------------------------------ data
def write_offline_json(transitions: dict, path: str) -> int:
    """Write a transition batch (numpy dict) as JSONL rows; returns row count."""
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    n = len(transitions["obs"])
    with open(path, "w") as f:
        for i in range(n):
            f.write(json.dumps({
                "obs": np.asarray(transitions["obs"][i]).tolist(),
                # preserve numeric kind: continuous actions must not
                # truncate (the loader mirrors this via action_dtype)
                "action": (np.asarray(transitions["actions"][i]).tolist()),
                "reward": float(transitions["rewards"][i]),
                "next_obs": np.asarray(transitions["next_obs"][i]).tolist(),
                "done": float(transitions["dones"][i]),
            }) + "\n")
    return n


def load_offline_data(source: Any, action_dtype=None) -> dict:
    """Normalize an offline source into a numpy transition dict.

    Accepts a numpy dict, a JSONL path, or a ray_tpu.data Dataset of rows
    (reference: OfflineData wraps Ray Data datasets, offline/offline_data.py).
    ``action_dtype`` defaults to the data's own type — continuous actions
    loaded from JSONL must NOT truncate to integers; discrete consumers
    (BC/MARWIL/CQL) pass np.int64 explicitly."""
    if isinstance(source, dict):
        return source
    if isinstance(source, str):
        from ray_tpu import data

        source = data.read_json(source)
    rows = source.take_all() if hasattr(source, "take_all") else list(source)
    return {
        "obs": np.asarray([r["obs"] for r in rows], np.float32),
        "actions": np.asarray([r["action"] for r in rows], action_dtype),
        "rewards": np.asarray([r["reward"] for r in rows], np.float32),
        "next_obs": np.asarray([r["next_obs"] for r in rows], np.float32),
        "dones": np.asarray([r["done"] for r in rows], np.float32),
    }


# ------------------------------------------------------------------ configs
@dataclasses.dataclass
class OfflineConfig:
    dataset: Any = None  # numpy dict | JSONL path | data.Dataset
    lr: float = 1e-3
    train_batch_size: int = 256
    hidden: tuple = (64, 64)
    seed: int = 0
    gamma: float = 0.99

    def offline_data(self, dataset) -> "OfflineConfig":
        self.dataset = dataset
        return self

    def training(self, **kw) -> "OfflineConfig":
        fields = {f.name for f in dataclasses.fields(self)}
        for k, v in kw.items():
            if k not in fields:
                raise ValueError(f"Unknown training option {k}")
            setattr(self, k, v)
        return self


@dataclasses.dataclass
class BCConfig(OfflineConfig):
    def build(self) -> "BC":
        return BC(self)


@dataclasses.dataclass
class MARWILConfig(OfflineConfig):
    beta: float = 1.0       # 0 = plain BC; >0 advantage-weights the cloning
    vf_coeff: float = 1.0

    def build(self) -> "MARWIL":
        return MARWIL(self)


@dataclasses.dataclass
class CQLConfig(OfflineConfig):
    alpha_cql: float = 1.0  # conservative penalty weight
    target_update_freq: int = 100

    def build(self) -> "CQL":
        return CQL(self)


class _OfflineAlgorithm:
    """Shared train() loop: minibatch SGD epochs over the fixed dataset."""

    def __init__(self, cfg: OfflineConfig):
        self.cfg = cfg
        self.data = load_offline_data(cfg.dataset, action_dtype=np.int64)
        if not len(self.data["obs"]):
            raise ValueError("offline dataset is empty")
        self.obs_dim = int(self.data["obs"].shape[-1])
        self.num_actions = int(self.data["actions"].max()) + 1
        self._rng = np.random.default_rng(cfg.seed)
        self.updates_total = 0
        self._build()

    def train(self, num_updates: int = 50) -> dict:
        n = len(self.data["obs"])
        bs = min(self.cfg.train_batch_size, n)
        metrics = {}
        for _ in range(num_updates):
            idx = self._rng.integers(0, n, bs)
            metrics = self._update({k: v[idx] for k, v in self.data.items()})
            self.updates_total += 1
        self._policy_np_cache = None  # params changed: invalidate
        return {"updates_total": self.updates_total, **metrics}

    _policy_np_cache = None

    def compute_single_action(self, obs) -> int:
        from ray_tpu.rllib.np_policy import np_mlp

        if self._policy_np_cache is None:
            # device->host conversion once per train() round, not per step
            self._policy_np_cache = [
                {k: np.asarray(w) for k, w in layer.items()}
                for layer in self._policy_params()]
        return int(np.argmax(
            np_mlp(self._policy_np_cache, np.asarray(obs, np.float64)[None])[0]))

    def evaluate(self, env_creator, episodes: int = 2, max_steps: int = 500) -> float:
        """Mean episode reward of the greedy learned policy."""
        totals = []
        for ep in range(episodes):
            env = env_creator()
            obs, _ = env.reset(seed=self.cfg.seed + ep)
            total = 0.0
            for _ in range(max_steps):
                obs, r, term, trunc, _ = env.step(self.compute_single_action(obs))
                total += float(r)
                if term or trunc:
                    break
            env.close()
            totals.append(total)
        return float(np.mean(totals))


class BC(_OfflineAlgorithm):
    """Behavior cloning: NLL of the logged actions (algorithms/bc/)."""

    def _build(self):
        import jax
        import jax.numpy as jnp
        import optax

        cfg = self.cfg
        self.params = _mlp_init(jax.random.PRNGKey(cfg.seed),
                                (self.obs_dim, *cfg.hidden, self.num_actions))
        self.optimizer = optax.adam(cfg.lr)
        self.opt_state = self.optimizer.init(self.params)

        def loss_fn(params, obs, actions):
            logp = jax.nn.log_softmax(_mlp_apply(params, obs, jnp))
            nll = -jnp.take_along_axis(logp, actions[:, None], axis=1).mean()
            return nll

        def update(params, opt_state, obs, actions):
            loss, grads = jax.value_and_grad(loss_fn)(params, obs, actions)
            updates, opt_state = self.optimizer.update(grads, opt_state, params)
            return optax.apply_updates(params, updates), opt_state, loss

        self._jit_update = jax.jit(update)
        self._jnp = jnp

    def _policy_params(self):
        return self.params

    def _update(self, batch) -> dict:
        jnp = self._jnp
        self.params, self.opt_state, loss = self._jit_update(
            self.params, self.opt_state,
            jnp.asarray(batch["obs"]), jnp.asarray(batch["actions"], jnp.int32))
        return {"bc_loss": float(loss)}


class MARWIL(_OfflineAlgorithm):
    """Monotonic advantage re-weighted imitation learning (algorithms/marwil/):
    clone the data policy with per-sample weights exp(beta * advantage), where
    the advantage baseline V is regressed on observed one-step returns."""

    def _build(self):
        import jax
        import jax.numpy as jnp
        import optax

        cfg = self.cfg
        kp, kv = jax.random.split(jax.random.PRNGKey(cfg.seed))
        self.params = {
            "pi": _mlp_init(kp, (self.obs_dim, *cfg.hidden, self.num_actions)),
            "vf": _mlp_init(kv, (self.obs_dim, *cfg.hidden, 1)),
        }
        self.optimizer = optax.adam(cfg.lr)
        self.opt_state = self.optimizer.init(self.params)

        def loss_fn(params, obs, actions, rewards, next_obs, dones):
            v = _mlp_apply(params["vf"], obs, jnp)[:, 0]
            v_next = jax.lax.stop_gradient(
                _mlp_apply(params["vf"], next_obs, jnp)[:, 0])
            target = rewards + cfg.gamma * (1.0 - dones) * v_next
            vf_loss = ((v - target) ** 2).mean()
            adv = jax.lax.stop_gradient(target - v)
            adv = adv / (jnp.abs(adv).mean() + 1e-8)  # scale-free exponent
            w = jnp.exp(jnp.clip(cfg.beta * adv, -5.0, 5.0))
            logp = jax.nn.log_softmax(_mlp_apply(params["pi"], obs, jnp))
            nll = -jnp.take_along_axis(logp, actions[:, None], axis=1)[:, 0]
            pi_loss = (w * nll).mean()
            return pi_loss + cfg.vf_coeff * vf_loss, {
                "pi_loss": pi_loss, "vf_loss": vf_loss}

        def update(params, opt_state, batch):
            (loss, m), grads = jax.value_and_grad(loss_fn, has_aux=True)(
                params, batch["obs"], batch["actions"], batch["rewards"],
                batch["next_obs"], batch["dones"])
            updates, opt_state = self.optimizer.update(grads, opt_state, params)
            m["total_loss"] = loss
            return optax.apply_updates(params, updates), opt_state, m

        self._jit_update = jax.jit(update)
        self._jnp = jnp

    def _policy_params(self):
        return self.params["pi"]

    def _update(self, batch) -> dict:
        jnp = self._jnp
        b = {"obs": jnp.asarray(batch["obs"]),
             "actions": jnp.asarray(batch["actions"], jnp.int32),
             "rewards": jnp.asarray(batch["rewards"]),
             "next_obs": jnp.asarray(batch["next_obs"]),
             "dones": jnp.asarray(batch["dones"])}
        self.params, self.opt_state, m = self._jit_update(
            self.params, self.opt_state, b)
        return {k: float(v) for k, v in m.items()}


class CQL(_OfflineAlgorithm):
    """Conservative Q-learning, discrete (algorithms/cql/): double-Q TD loss
    plus the conservative gap logsumexp(Q) - Q(a_data), which pushes down
    Q-values for actions the dataset never took."""

    def _build(self):
        import jax
        import jax.numpy as jnp
        import optax

        cfg = self.cfg
        self.params = _mlp_init(jax.random.PRNGKey(cfg.seed),
                                (self.obs_dim, *cfg.hidden, self.num_actions))
        self.target_params = jax.tree.map(lambda x: x, self.params)
        self.optimizer = optax.adam(cfg.lr)
        self.opt_state = self.optimizer.init(self.params)

        def loss_fn(params, target_params, obs, actions, rewards, next_obs, dones):
            q = _mlp_apply(params, obs, jnp)
            q_a = jnp.take_along_axis(q, actions[:, None], axis=1)[:, 0]
            # double-Q target: online argmax, target net evaluation
            next_q_online = _mlp_apply(params, next_obs, jnp)
            next_a = jnp.argmax(next_q_online, axis=1)
            next_q_t = _mlp_apply(target_params, next_obs, jnp)
            next_q = jnp.take_along_axis(next_q_t, next_a[:, None], axis=1)[:, 0]
            target = rewards + cfg.gamma * (1.0 - dones) * jax.lax.stop_gradient(next_q)
            td_loss = ((q_a - target) ** 2).mean()
            cql_gap = (jax.nn.logsumexp(q, axis=1) - q_a).mean()
            return td_loss + cfg.alpha_cql * cql_gap, {
                "td_loss": td_loss, "cql_gap": cql_gap}

        def update(params, target_params, opt_state, batch):
            (loss, m), grads = jax.value_and_grad(loss_fn, has_aux=True)(
                params, target_params, batch["obs"], batch["actions"],
                batch["rewards"], batch["next_obs"], batch["dones"])
            updates, opt_state = self.optimizer.update(grads, opt_state, params)
            m["total_loss"] = loss
            return optax.apply_updates(params, updates), opt_state, m

        self._jit_update = jax.jit(update)
        self._jnp = jnp

    def _policy_params(self):
        return self.params

    def _update(self, batch) -> dict:
        jnp = self._jnp
        b = {"obs": jnp.asarray(batch["obs"]),
             "actions": jnp.asarray(batch["actions"], jnp.int32),
             "rewards": jnp.asarray(batch["rewards"]),
             "next_obs": jnp.asarray(batch["next_obs"]),
             "dones": jnp.asarray(batch["dones"])}
        self.params, self.opt_state, m = self._jit_update(
            self.params, self.target_params, self.opt_state, b)
        if (self.updates_total + 1) % self.cfg.target_update_freq == 0:
            import jax

            self.target_params = jax.tree.map(lambda x: x, self.params)
        return {k: float(v) for k, v in m.items()}
