"""EnvRunners: actor fleet collecting environment rollouts.

Parity: rllib/env/env_runner.py:36 (EnvRunner ABC with FaultAwareApply),
single_agent_env_runner.py:68 (SingleAgentEnvRunner) and env_runner_group.py:70
(EnvRunnerGroup). Runners hold envs + a policy snapshot and return batched
trajectories; the group fans sampling out over actors and syncs weights.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable

import numpy as np

import ray_tpu


@dataclass
class Episode:
    obs: list = field(default_factory=list)
    actions: list = field(default_factory=list)
    rewards: list = field(default_factory=list)
    logprobs: list = field(default_factory=list)
    values: list = field(default_factory=list)
    dones: list = field(default_factory=list)
    # terminated only (no time-limit truncation): off-policy targets bootstrap
    # through truncation but not termination (rllib's terminated/truncated split)
    terminateds: list = field(default_factory=list)
    # value of the next obs when a rollout fragment cuts a live episode
    # (reference: rllib bootstraps fragment boundaries with vf(last_obs))
    bootstrap_value: float = 0.0
    # the env's final observation at episode end (gymnasium returns it from
    # the terminal step); off-policy targets bootstrap from it on truncation
    final_obs: object = None
    # reward accumulated by this episode in PREVIOUS fragments (an episode can
    # span rollout fragments; metrics must report the whole episode)
    reward_offset: float = 0.0

    def total_reward(self) -> float:
        return float(sum(self.rewards)) + self.reward_offset

    def __len__(self):
        return len(self.actions)


class SingleAgentEnvRunner:
    """One actor running one (or vectorized) env with the current policy."""

    def __init__(self, env_creator: Callable, policy_fn: Callable, seed: int = 0,
                 env_to_module: Callable | None = None,
                 module_to_env: Callable | None = None):
        self.env = env_creator()
        self.policy_fn = policy_fn  # (params, obs) -> (action, logprob, value)
        self.params = None
        self.rng = np.random.default_rng(seed)
        # Connector pipelines (reference: rllib/connectors) — factories so each
        # runner actor owns its stateful instances (frame stacks, running stats)
        self._env_to_module = env_to_module() if env_to_module else None
        self._module_to_env = module_to_env() if module_to_env else None
        raw, _ = self.env.reset(seed=seed)
        self._obs = self._process_obs(raw)
        self._carry_reward = 0.0  # live episode's reward from prior fragments

    def _process_obs(self, raw):
        return self._env_to_module(raw) if self._env_to_module else raw

    def set_weights(self, params) -> None:
        self.params = params

    def sample(self, num_steps: int) -> list[Episode]:
        """Collect ~num_steps of experience, episode-segmented."""
        episodes: list[Episode] = []
        ep = Episode(reward_offset=self._carry_reward)
        steps = 0
        while steps < num_steps:
            action, logprob, value = self.policy_fn(self.params, np.asarray(self._obs), self.rng)
            env_action = (self._module_to_env(action) if self._module_to_env
                          else action)
            nxt, reward, terminated, truncated, _ = self.env.step(env_action)
            done = bool(terminated or truncated)
            ep.obs.append(np.asarray(self._obs))
            ep.actions.append(action)  # module-space: what the learner trains on
            ep.rewards.append(float(reward))
            ep.logprobs.append(float(logprob))
            ep.values.append(float(value))
            ep.dones.append(done)
            ep.terminateds.append(bool(terminated))
            steps += 1
            if done:
                ep.final_obs = np.asarray(self._process_obs(nxt))
                if self._env_to_module is not None:
                    self._env_to_module.reset()  # drop per-episode state
                raw, _ = self.env.reset()
                self._obs = self._process_obs(raw)
                self._carry_reward = 0.0
                episodes.append(ep)
                ep = Episode()
            else:
                self._obs = self._process_obs(nxt)
        if len(ep):
            # live episode cut by the fragment boundary: bootstrap with V(next obs)
            _, _, ep.bootstrap_value = self.policy_fn(self.params, np.asarray(self._obs), self.rng)
            self._carry_reward = ep.total_reward()
            episodes.append(ep)
        return episodes

    def ping(self) -> str:
        return "ok"


class EnvRunnerGroup:
    """Fan-out sampling over runner actors (reference: env_runner_group.py:70)."""

    def __init__(self, env_creator: Callable, policy_fn: Callable, num_runners: int = 2,
                 env_to_module: Callable | None = None,
                 module_to_env: Callable | None = None):
        runner_cls = ray_tpu.remote(num_cpus=1, max_concurrency=2)(SingleAgentEnvRunner)
        self.runners = [
            runner_cls.remote(env_creator, policy_fn, seed=i,
                              env_to_module=env_to_module,
                              module_to_env=module_to_env)
            for i in range(num_runners)
        ]

    def sync_weights(self, params) -> None:
        ray_tpu.get([r.set_weights.remote(params) for r in self.runners])

    def sample(self, steps_per_runner: int) -> list[Episode]:
        batches = ray_tpu.get([r.sample.remote(steps_per_runner) for r in self.runners])
        return [ep for b in batches for ep in b]

    def stop(self) -> None:
        for r in self.runners:
            try:
                ray_tpu.kill(r)
            except Exception:
                pass
