"""IQL: Implicit Q-Learning (Kostrikov et al. 2021) — offline continuous RL.

Parity: the rllib offline family's continuous-control member (the reference
ships BC/MARWIL/CQL; IQL is the named missing offline algorithm from the
round verdicts). The in-sample trick: never evaluate Q on out-of-dataset
actions —

- V(s) chases the EXPECTILE of Q(s, a_data): L2^tau penalizes under-
  estimation asymmetrically, so V approaches max_a Q within dataset support;
- Q(s,a) regresses to r + gamma * V(s') (no next-action sampling at all);
- the policy is extracted by advantage-weighted regression:
  max E[exp(beta * (Q - V)) * log pi(a_data | s)].

One jitted XLA update covers V, both Qs, and the actor; training consumes an
offline transitions dict (rllib.offline.load_offline_data formats).
"""

from __future__ import annotations

import dataclasses

import numpy as np

from ray_tpu.rllib.offline import OfflineConfig
from ray_tpu.rllib.ppo import _mlp_apply, _mlp_init
from ray_tpu.rllib.sac_continuous import LOG_STD_MAX, LOG_STD_MIN


@dataclasses.dataclass
class IQLConfig(OfflineConfig):
    """Shares the offline family's dataset/lr/train_batch_size/gamma fields
    and its fields-validated training() builder (offline.py:64)."""

    hidden: tuple = (256, 256)
    lr: float = 3e-4
    expectile: float = 0.7    # tau — 0.5 is SARSA, ->1 approaches max_a Q
    beta: float = 3.0         # AWR inverse temperature
    adv_clip: float = 100.0   # exp-weight cap (paper's stabilizer)
    polyak: float = 0.005     # target-Q rate

    def build(self) -> "IQL":
        return IQL(self)


class IQL:
    def __init__(self, cfg: IQLConfig):
        import jax
        import jax.numpy as jnp
        import optax

        from ray_tpu.rllib.offline import load_offline_data

        self.cfg = cfg
        data = load_offline_data(cfg.dataset)
        self._data = {k: np.asarray(v, np.float32) for k, v in data.items()}
        obs_dim = self._data["obs"].shape[1]
        self._acts = self._data["actions"].astype(np.float32)
        if self._acts.ndim == 1:
            self._acts = self._acts[:, None]
        act_dim = self._acts.shape[1]
        self._n = len(self._data["obs"])

        key = jax.random.PRNGKey(cfg.seed)
        kp, k1, k2, kv, self._key = jax.random.split(key, 5)
        self.params = {
            "pi": _mlp_init(kp, (obs_dim, *cfg.hidden, 2 * act_dim)),
            "q1": _mlp_init(k1, (obs_dim + act_dim, *cfg.hidden, 1)),
            "q2": _mlp_init(k2, (obs_dim + act_dim, *cfg.hidden, 1)),
            "v": _mlp_init(kv, (obs_dim, *cfg.hidden, 1)),
        }
        self.target = {"q1": self.params["q1"], "q2": self.params["q2"]}
        self.optimizer = optax.adam(cfg.lr)
        self.opt_state = self.optimizer.init(self.params)

        def q_apply(q, obs, act):
            return _mlp_apply(q, jnp.concatenate([obs, act], axis=-1), jnp)[:, 0]

        def loss_fn(params, target, obs, actions, rewards, next_obs, dones):
            # --- V: expectile regression toward in-sample target-Q ---
            tq = jnp.minimum(q_apply(target["q1"], obs, actions),
                             q_apply(target["q2"], obs, actions))
            v = _mlp_apply(params["v"], obs, jnp)[:, 0]
            u = jax.lax.stop_gradient(tq) - v
            w_exp = jnp.abs(cfg.expectile - (u < 0.0).astype(jnp.float32))
            v_loss = (w_exp * u ** 2).mean()
            # --- Q: one-step backup through V(s') — never through a policy ---
            next_v = _mlp_apply(params["v"], next_obs, jnp)[:, 0]
            y = jax.lax.stop_gradient(
                rewards + cfg.gamma * (1.0 - dones) * next_v)
            q_loss = (((q_apply(params["q1"], obs, actions) - y) ** 2)
                      + ((q_apply(params["q2"], obs, actions) - y) ** 2)).mean()
            # --- actor: advantage-weighted regression on DATASET actions ---
            adv = jax.lax.stop_gradient(tq) - jax.lax.stop_gradient(v)
            w = jnp.minimum(jnp.exp(cfg.beta * adv), cfg.adv_clip)
            out = _mlp_apply(params["pi"], obs, jnp)
            mu, log_std = out[:, : actions.shape[1]], out[:, actions.shape[1]:]
            log_std = jnp.clip(log_std, LOG_STD_MIN, LOG_STD_MAX)
            logp = (-0.5 * ((actions - mu) / jnp.exp(log_std)) ** 2
                    - log_std - 0.5 * jnp.log(2 * jnp.pi)).sum(axis=1)
            actor_loss = -(jax.lax.stop_gradient(w) * logp).mean()
            total = v_loss + q_loss + actor_loss
            return total, {"v_loss": v_loss, "q_loss": q_loss,
                           "actor_loss": actor_loss, "adv_mean": adv.mean()}

        def update(params, target, opt_state, batch):
            (loss, metrics), grads = jax.value_and_grad(loss_fn, has_aux=True)(
                params, target, batch["obs"], batch["actions"],
                batch["rewards"], batch["next_obs"], batch["dones"],
            )
            updates, opt_state = self.optimizer.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            target = jax.tree.map(
                lambda t, o: (1 - cfg.polyak) * t + cfg.polyak * o,
                target, {"q1": params["q1"], "q2": params["q2"]},
            )
            metrics["total_loss"] = loss
            return params, target, opt_state, metrics

        self._update = jax.jit(update)
        self._pi_apply = jax.jit(lambda p, o: _mlp_apply(p, o, jnp))
        self._jax, self._jnp = jax, jnp
        self._rng = np.random.default_rng(cfg.seed)
        self._act_dim = act_dim

    def train(self, num_updates: int = 100) -> dict:
        jnp = self._jnp
        metrics = {}
        for _ in range(num_updates):
            idx = self._rng.integers(0, self._n, self.cfg.train_batch_size)
            batch = {
                "obs": jnp.asarray(self._data["obs"][idx]),
                "actions": jnp.asarray(self._acts[idx]),
                "rewards": jnp.asarray(self._data["rewards"][idx]),
                "next_obs": jnp.asarray(self._data["next_obs"][idx]),
                "dones": jnp.asarray(self._data["dones"][idx]),
            }
            self.params, self.target, self.opt_state, metrics = self._update(
                self.params, self.target, self.opt_state, batch)
        return {k: float(v) for k, v in metrics.items()}

    def compute_single_action(self, obs) -> np.ndarray:
        out = np.asarray(self._pi_apply(
            self.params["pi"], np.asarray(obs, np.float32)[None]))[0]
        return out[: self._act_dim]  # deterministic mean action
