"""LearnerGroup: data-parallel learner workers with synchronous gradient
averaging.

Parity: rllib/core/learner/learner_group.py:100 (LearnerGroup — N learner
workers updating one logical policy; the reference averages gradients across
learners each step via its multi-GPU towers / NCCL). Here each learner is an
actor hosting the algorithm's Learner (PPOLearner etc. exposing the
compute_grads/apply_grads split of core/learner/learner.py); a group update
shards the batch, gathers per-shard gradients, averages them example-weighted
host-side, and broadcasts the averaged gradients so every learner applies the
IDENTICAL optimizer step — bitwise-equal replicas, the DDP contract.

On TPU pods the same Learner code scales differently (one jitted update over
a data-sharded Mesh, psum riding ICI — train/spmd.py); this group exists for
the reference's heterogeneous-learner topology and its API surface.
"""

from __future__ import annotations

from typing import Callable

import numpy as np

import ray_tpu


class _LearnerWorker:
    """Actor hosting one learner replica."""

    def __init__(self, factory_blob: bytes):
        import cloudpickle

        self.learner = cloudpickle.loads(factory_blob)()

    def compute_grads(self, shard: dict):
        return self.learner.compute_grads(shard)

    def apply_grads(self, grads) -> bool:
        self.learner.apply_grads(grads)
        return True

    def update(self, batch: dict) -> dict:
        return self.learner.update(batch)

    def get_params(self):
        import jax

        return jax.tree.map(lambda p: np.asarray(p), self.learner.params)


class LearnerGroup:
    def __init__(self, learner_factory: Callable, num_learners: int = 2,
                 num_cpus_per_learner: float = 0.5):
        import cloudpickle

        if num_learners < 1:
            raise ValueError("num_learners must be >= 1")
        blob = cloudpickle.dumps(learner_factory)
        cls = ray_tpu.remote(num_cpus=num_cpus_per_learner,
                             max_concurrency=2)(_LearnerWorker)
        self.workers = [cls.remote(blob) for _ in range(num_learners)]
        self.num_learners = num_learners
        # replica-identity check: gradient averaging is only valid against
        # IDENTICAL parameters — an unseeded factory silently trains garbage
        if num_learners > 1:
            import jax

            all_params = ray_tpu.get(
                [w.get_params.remote() for w in self.workers], timeout=300)
            base = jax.tree.leaves(all_params[0])
            for rank, other in enumerate(all_params[1:], start=1):
                for a, b in zip(base, jax.tree.leaves(other)):
                    if not np.array_equal(np.asarray(a), np.asarray(b)):
                        raise ValueError(
                            "learner replicas diverge at init (rank 0 vs "
                            f"rank {rank}): the learner_factory must produce "
                            "deterministic (seeded) parameters")

    def update(self, batch: dict) -> dict:
        """One data-parallel step: shard -> per-learner grads -> example-
        weighted average -> identical apply on every learner."""
        import jax

        n = len(next(iter(batch.values())))
        if n == 0:
            return {}
        bounds = np.linspace(0, n, self.num_learners + 1).astype(int)
        shards, sizes = [], []
        for i in range(self.num_learners):
            lo, hi = bounds[i], bounds[i + 1]
            if hi > lo:
                shards.append({k: v[lo:hi] for k, v in batch.items()})
                sizes.append(hi - lo)
        refs = [w.compute_grads.remote(s)
                for w, s in zip(self.workers, shards)]
        results = ray_tpu.get(refs, timeout=600)
        total = float(sum(sizes))
        weights = [s / total for s in sizes]

        def avg(*gs):
            return sum(w * g for w, g in zip(weights, gs))

        grads = jax.tree.map(avg, *[g for g, _ in results])
        ray_tpu.get([w.apply_grads.remote(grads) for w in self.workers],
                    timeout=600)
        # example-weighted metric average (loss means are per-shard means)
        metrics: dict = {}
        for (_, m), w in zip(results, weights):
            for k, v in m.items():
                metrics[k] = metrics.get(k, 0.0) + w * v
        return metrics

    def get_params(self):
        return ray_tpu.get(self.workers[0].get_params.remote(), timeout=120)

    def shutdown(self) -> None:
        for w in self.workers:
            try:
                ray_tpu.kill(w)
            except Exception:
                pass
