"""LearnerGroup: data-parallel learner workers with synchronous gradient
averaging.

Parity: rllib/core/learner/learner_group.py:100 (LearnerGroup — N learner
workers updating one logical policy; the reference averages gradients across
learners each step via its multi-GPU towers / NCCL). Here each learner is an
actor hosting the algorithm's Learner (PPOLearner etc. exposing the
compute_grads/apply_grads split of core/learner/learner.py); a group update
shards the batch, gathers per-shard gradients, averages them example-weighted
host-side, and broadcasts the averaged gradients so every learner applies the
IDENTICAL optimizer step — bitwise-equal replicas, the DDP contract.

On TPU pods the same Learner code scales differently (one jitted update over
a data-sharded Mesh, psum riding ICI — train/spmd.py); this group exists for
the reference's heterogeneous-learner topology and its API surface.
"""

from __future__ import annotations

from typing import Callable

import numpy as np

import ray_tpu


class _LearnerWorker:
    """Actor hosting one learner replica."""

    def __init__(self, factory_blob: bytes, rank: int = 0, world: int = 1):
        import cloudpickle

        self.learner = cloudpickle.loads(factory_blob)()
        self.rank = rank
        self.world = world

    def compute_grads(self, shard: dict):
        return self.learner.compute_grads(shard)

    def apply_grads(self, grads) -> bool:
        self.learner.apply_grads(grads)
        return True

    # ---- compiled gang-step surface (ISSUE 15): the WHOLE batch rides
    # the graph's broadcast input; each member slices its own rank's shard
    # (SPMD contract) so the per-step scatter needs no driver round trip.
    def step_shard(self, batch: dict):
        """(grads, metrics, shard_size) for MY contiguous shard."""
        n = len(next(iter(batch.values())))
        bounds = np.linspace(0, n, self.world + 1).astype(int)
        lo, hi = int(bounds[self.rank]), int(bounds[self.rank + 1])
        if hi <= lo:
            return (None, {}, 0)
        shard = {k: v[lo:hi] for k, v in batch.items()}
        grads, metrics = self.learner.compute_grads(shard)
        return (grads, metrics, hi - lo)

    def apply_from(self, averaged) -> bool:
        """Apply the aggregator's averaged grads (identical on every
        member — the DDP contract); no-op on an all-empty step."""
        grads = averaged[0]
        if grads is not None:
            self.learner.apply_grads(grads)
        return True

    def update(self, batch: dict) -> dict:
        return self.learner.update(batch)

    def get_params(self):
        import jax

        return jax.tree.map(lambda p: np.asarray(p), self.learner.params)


class _GradAverager:
    """Head-hosted fan-in/fan-out pivot of the compiled learner graph:
    example-weighted gradient average in, identical grads out to every
    member, metrics as the graph output."""

    def average(self, *results):
        import jax

        live = [(g, m, s) for g, m, s in results if s > 0]
        if not live:
            return (None, {})
        total = float(sum(s for _, _, s in live))
        weights = [s / total for _, _, s in live]

        def avg(*gs):
            return sum(w * g for w, g in zip(weights, gs))

        grads = jax.tree.map(avg, *[g for g, _, _ in live])
        metrics: dict = {}
        for (_, m, _), w in zip(live, weights):
            for k, v in m.items():
                metrics[k] = metrics.get(k, 0.0) + w * v
        return (grads, metrics)

    def finish(self, averaged, *acks) -> dict:
        return averaged[1]  # metrics, once every member applied


class LearnerGroup:
    def __init__(self, learner_factory: Callable, num_learners: int = 2,
                 num_cpus_per_learner: float = 0.5):
        import cloudpickle
        import os

        if num_learners < 1:
            raise ValueError("num_learners must be >= 1")
        blob = cloudpickle.dumps(learner_factory)
        cls = ray_tpu.remote(num_cpus=num_cpus_per_learner,
                             max_concurrency=2)(_LearnerWorker)
        self.workers = [cls.remote(blob, i, num_learners)
                        for i in range(num_learners)]
        self.num_learners = num_learners
        # Resident compiled step graph (ISSUE 15): batch -> per-member
        # shard grads -> averaged -> identical apply -> metrics, all over
        # channels — one channel write + one read per update() instead of
        # 2N actor-task submits. RAY_TPU_GANG_COMPILED=0 keeps per-call
        # dispatch (the A/B baseline); compile failure falls back too.
        self._dag = None
        self._averager = None
        if os.environ.get("RAY_TPU_GANG_COMPILED", "1") != "0":
            self._compile_step_graph()
        # replica-identity check: gradient averaging is only valid against
        # IDENTICAL parameters — an unseeded factory silently trains garbage
        if num_learners > 1:
            import jax

            all_params = ray_tpu.get(
                [w.get_params.remote() for w in self.workers], timeout=300)
            base = jax.tree.leaves(all_params[0])
            for rank, other in enumerate(all_params[1:], start=1):
                for a, b in zip(base, jax.tree.leaves(other)):
                    if not np.array_equal(np.asarray(a), np.asarray(b)):
                        raise ValueError(
                            "learner replicas diverge at init (rank 0 vs "
                            f"rank {rank}): the learner_factory must produce "
                            "deterministic (seeded) parameters")

    def _compile_step_graph(self) -> None:
        import logging

        from ray_tpu.dag import InputNode
        from ray_tpu.dag.compiled import CompiledActorDAG

        averager = None
        try:
            agg_cls = ray_tpu.remote(num_cpus=0)(_GradAverager)
            averager = agg_cls.remote()
            with InputNode() as inp:
                grads = [w.step_shard.bind(inp) for w in self.workers]
                avg = averager.average.bind(*grads)
                acks = [w.apply_from.bind(avg) for w in self.workers]
                out = averager.finish.bind(avg, *acks)
            compiled = out.experimental_compile()
        except Exception:
            logging.getLogger("ray_tpu").warning(
                "learner-group step graph failed to build; per-call "
                "dispatch", exc_info=True)
            if averager is not None:  # don't leak the fan-in actor
                try:
                    ray_tpu.kill(averager)
                except Exception:
                    pass
            return
        if isinstance(compiled, CompiledActorDAG):
            self._dag = compiled
            self._averager = averager
        else:
            try:  # legacy RPC-dispatch driver: per-call path is cheaper
                compiled.teardown()
            except Exception:
                pass
            try:
                ray_tpu.kill(averager)
            except Exception:
                pass

    def update(self, batch: dict) -> dict:
        """One data-parallel step: shard -> per-learner grads -> example-
        weighted average -> identical apply on every learner. With the
        compiled step graph installed this is one channel write + one
        channel read; otherwise classic per-call dispatch."""
        import jax

        n = len(next(iter(batch.values())))
        if n and self._dag is not None:
            return self._dag.execute(batch).get(timeout=600)
        if n == 0:
            return {}
        bounds = np.linspace(0, n, self.num_learners + 1).astype(int)
        shards, sizes = [], []
        for i in range(self.num_learners):
            lo, hi = bounds[i], bounds[i + 1]
            if hi > lo:
                shards.append({k: v[lo:hi] for k, v in batch.items()})
                sizes.append(hi - lo)
        refs = [w.compute_grads.remote(s)
                for w, s in zip(self.workers, shards)]
        results = ray_tpu.get(refs, timeout=600)
        total = float(sum(sizes))
        weights = [s / total for s in sizes]

        def avg(*gs):
            return sum(w * g for w, g in zip(weights, gs))

        grads = jax.tree.map(avg, *[g for g, _ in results])
        ray_tpu.get([w.apply_grads.remote(grads) for w in self.workers],
                    timeout=600)
        # example-weighted metric average (loss means are per-shard means)
        metrics: dict = {}
        for (_, m), w in zip(results, weights):
            for k, v in m.items():
                metrics[k] = metrics.get(k, 0.0) + w * v
        return metrics

    def get_params(self):
        return ray_tpu.get(self.workers[0].get_params.remote(), timeout=120)

    def shutdown(self) -> None:
        if self._dag is not None:
            try:
                self._dag.teardown()
            except Exception:
                pass
            self._dag = None
        for w in self.workers + ([self._averager] if self._averager else []):
            try:
                ray_tpu.kill(w)
            except Exception:
                pass
        self._averager = None
