"""TQC: Truncated Quantile Critics (Kuznetsov et al. 2020).

Parity: the rllib algorithm family's continuous-control tail (the reference
ships SAC; TQC is its distributional successor used by SB3/contrib and named
in the round verdicts as a missing family). Design: SAC's actor/temperature
machinery (sac_continuous.py) with the twin scalar critics replaced by M
quantile critics of K atoms each; the Bellman target pools all M*K next-state
atoms, sorts, and DROPS the top d-per-net atoms — truncating the
overestimation tail that max-entropy bootstrapping amplifies. One jitted XLA
update covers all critics (vmapped over the critic axis), the actor, and
alpha.
"""

from __future__ import annotations

import dataclasses

from ray_tpu.rllib.ppo import _mlp_apply, _mlp_init
from ray_tpu.rllib.sac_continuous import (
    ContinuousSAC,
    ContinuousSACConfig,
    ContinuousSACLearner,
    _squashed_gaussian,
)


@dataclasses.dataclass
class TQCConfig(ContinuousSACConfig):
    num_critics: int = 5                 # M
    num_quantiles: int = 25              # K atoms per critic
    top_quantiles_to_drop_per_net: int = 2  # d — the truncation knob

    def build(self) -> "TQC":
        return TQC(self)


class TQCLearner:
    """M vmapped quantile critics + SAC actor/alpha in one jitted update."""

    def __init__(self, cfg: TQCConfig, obs_dim: int, act_dim: int):
        import jax
        import jax.numpy as jnp
        import optax

        self.cfg = cfg
        M, K = cfg.num_critics, cfg.num_quantiles
        if not 0 <= cfg.top_quantiles_to_drop_per_net < K:
            raise ValueError(
                f"top_quantiles_to_drop_per_net={cfg.top_quantiles_to_drop_per_net}"
                f" must be in [0, num_quantiles={K}) — dropping every atom"
                " leaves an empty target (NaN losses)")
        n_drop = cfg.top_quantiles_to_drop_per_net * M
        n_keep = M * K - n_drop
        key = jax.random.PRNGKey(cfg.seed)
        kp, kq, self._key = jax.random.split(key, 3)
        q_trees = [
            _mlp_init(k, (obs_dim + act_dim, *cfg.hidden, K))
            for k in jax.random.split(kq, M)
        ]
        stack = lambda *xs: jnp.stack(xs)  # noqa: E731 - leafwise critic axis
        self.params = {
            "pi": _mlp_init(kp, (obs_dim, *cfg.hidden, 2 * act_dim)),
            "qs": jax.tree.map(stack, *q_trees),
            "log_alpha": jnp.zeros(()),
        }
        self.target = {"qs": self.params["qs"]}
        self.optimizer = optax.chain(
            optax.clip_by_global_norm(5.0),
            optax.multi_transform(
                {"actor": optax.adam(cfg.actor_lr),
                 "critic": optax.adam(cfg.critic_lr),
                 "alpha": optax.adam(cfg.alpha_lr)},
                {"pi": "actor", "qs": "critic", "log_alpha": "alpha"},
            ),
        )
        self.opt_state = self.optimizer.init(self.params)
        target_entropy = (cfg.target_entropy if cfg.target_entropy is not None
                          else -float(act_dim))
        self.num_updates = 0
        taus = (jnp.arange(K, dtype=jnp.float32) + 0.5) / K  # quantile midpoints

        def z_all(qs, obs, act):
            """[M, B, K] atoms from the stacked critic trees."""
            x = jnp.concatenate([obs, act], axis=-1)
            return jax.vmap(lambda q: _mlp_apply(q, x, jnp))(qs)

        def loss_fn(params, target, key, obs, actions, rewards, next_obs, dones):
            alpha = jnp.exp(params["log_alpha"])
            k_next, k_pi = jax.random.split(key)
            B, A = actions.shape
            # --- truncated distributional target ---
            next_a, next_logp = _squashed_gaussian(
                jnp, jax, _mlp_apply(params["pi"], next_obs, jnp),
                jax.random.normal(k_next, (B, A)),
            )
            nz = z_all(target["qs"], next_obs, next_a)          # [M, B, K]
            pooled = jnp.sort(nz.transpose(1, 0, 2).reshape(B, M * K), axis=1)
            kept = pooled[:, :n_keep]                            # drop the top tail
            y = jax.lax.stop_gradient(
                rewards[:, None] + cfg.gamma * (1.0 - dones[:, None])
                * (kept - jax.lax.stop_gradient(alpha) * next_logp[:, None])
            )                                                    # [B, n_keep]
            # --- quantile Huber regression, every critic against every kept atom ---
            z = z_all(params["qs"], obs, actions)                # [M, B, K]
            delta = y[None, :, None, :] - z[:, :, :, None]       # [M, B, K, n_keep]
            ad = jnp.abs(delta)
            huber = jnp.where(ad <= 1.0, 0.5 * delta ** 2, ad - 0.5)
            w = jnp.abs(taus[None, None, :, None]
                        - (delta < 0.0).astype(jnp.float32))
            critic_loss = (w * huber).mean()
            # --- actor: maximize the UNtruncated mean of all atoms ---
            a_pi, logp_pi = _squashed_gaussian(
                jnp, jax, _mlp_apply(params["pi"], obs, jnp),
                jax.random.normal(k_pi, (B, A)),
            )
            q_pi = z_all(jax.lax.stop_gradient(params["qs"]), obs, a_pi).mean(
                axis=(0, 2))                                     # [B]
            actor_loss = (jax.lax.stop_gradient(alpha) * logp_pi - q_pi).mean()
            alpha_loss = (-params["log_alpha"]
                          * jax.lax.stop_gradient(logp_pi + target_entropy)).mean()
            total = critic_loss + actor_loss + alpha_loss
            return total, {
                "critic_loss": critic_loss, "actor_loss": actor_loss,
                "alpha": alpha, "entropy": -logp_pi.mean(),
            }

        def update(params, target, opt_state, key, batch):
            (loss, metrics), grads = jax.value_and_grad(loss_fn, has_aux=True)(
                params, target, key, batch["obs"], batch["actions"],
                batch["rewards"], batch["next_obs"], batch["dones"],
            )
            updates, opt_state = self.optimizer.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            target = jax.tree.map(
                lambda t, o: (1 - cfg.tau) * t + cfg.tau * o,
                target, {"qs": params["qs"]},
            )
            metrics["total_loss"] = loss
            return params, target, opt_state, metrics

        self._update = jax.jit(update)
        self._jax, self._jnp = jax, jnp

    # Same host-side batch marshaling as the SAC learner — the jitted
    # kernels differ, the update() contract doesn't.
    update = ContinuousSACLearner.update


class TQC(ContinuousSAC):
    """SAC shell + TQC learner (same runners/buffer/off-policy loop)."""

    learner_cls = TQCLearner
