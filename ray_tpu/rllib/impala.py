"""IMPALA on JAX: importance-weighted actor-learner with V-trace.

Parity: rllib/algorithms/impala/ — actors collect with a (stale) behavior
policy while the learner updates, and V-trace (Espeholt et al. 2018) corrects
the off-policyness with clipped importance ratios. Staleness is real here:
weights broadcast to the env runners only every `broadcast_interval`
iterations, so the correction actually earns its keep. The learner update is
one jitted XLA program (policy gradient with rho-weighted advantages, value
regression to v-trace targets, entropy bonus).
"""

from __future__ import annotations

import dataclasses
from typing import Callable

import numpy as np

import ray_tpu
from ray_tpu.rllib.env_runner import Episode, EnvRunnerGroup
from ray_tpu.rllib.ppo import _mlp_apply, _mlp_init


@dataclasses.dataclass
class IMPALAConfig:
    """Reference: IMPALAConfig surface (fluent API below)."""

    env: str | Callable = "CartPole-v1"
    num_env_runners: int = 2
    rollout_fragment_length: int = 256
    lr: float = 6e-4
    gamma: float = 0.99
    rho_clip: float = 1.0  # V-trace rho-bar (importance ratio cap)
    c_clip: float = 1.0  # V-trace c-bar (trace-cutting cap)
    vf_coeff: float = 0.5
    entropy_coeff: float = 0.01
    broadcast_interval: int = 2  # iterations between weight broadcasts
    hidden: tuple = (64, 64)
    seed: int = 0

    def environment(self, env) -> "IMPALAConfig":
        self.env = env
        return self

    def env_runners(self, num_env_runners: int,
                    rollout_fragment_length: int | None = None) -> "IMPALAConfig":
        self.num_env_runners = num_env_runners
        if rollout_fragment_length:
            self.rollout_fragment_length = rollout_fragment_length
        return self

    def training(self, **kw) -> "IMPALAConfig":
        fields = {f.name for f in dataclasses.fields(self)}
        for k, v in kw.items():
            if k not in fields:
                raise ValueError(f"Unknown training option {k}")
            setattr(self, k, v)
        return self

    def build(self) -> "IMPALA":
        return IMPALA(self)


def vtrace(behavior_logp, current_logp, rewards, values, bootstrap, dones,
           gamma, rho_clip, c_clip):
    """Host-side V-trace for one trajectory (numpy; Espeholt eq. 1).

    Returns (vs targets [T], pg advantages [T])."""
    T = len(rewards)
    ratios = np.exp(current_logp - behavior_logp)
    rhos = np.minimum(rho_clip, ratios)
    cs = np.minimum(c_clip, ratios)
    next_values = np.append(values[1:], bootstrap)
    next_values = np.where(dones, 0.0, next_values)
    deltas = rhos * (rewards + gamma * next_values - values)
    vs_minus_v = np.zeros(T + 1)
    for t in range(T - 1, -1, -1):
        not_done = 0.0 if dones[t] else 1.0
        vs_minus_v[t] = deltas[t] + gamma * cs[t] * not_done * vs_minus_v[t + 1]
    vs = values + vs_minus_v[:-1]
    next_vs = np.append(vs[1:], bootstrap)
    next_vs = np.where(dones, 0.0, next_vs)
    advantages = rhos * (rewards + gamma * next_vs - values)
    return vs, advantages


class IMPALALearner:
    """Policy + value nets with a jitted V-trace-corrected update."""

    def __init__(self, cfg: IMPALAConfig, obs_dim: int, num_actions: int):
        import jax
        import jax.numpy as jnp
        import optax

        self.cfg = cfg
        key = jax.random.PRNGKey(cfg.seed)
        kp, kv = jax.random.split(key)
        self.params = {
            "pi": _mlp_init(kp, (obs_dim, *cfg.hidden, num_actions)),
            "vf": _mlp_init(kv, (obs_dim, *cfg.hidden, 1)),
        }
        self.optimizer = optax.chain(
            optax.clip_by_global_norm(5.0), optax.adam(cfg.lr)
        )
        self.opt_state = self.optimizer.init(self.params)

        def loss_fn(params, obs, actions, vs_targets, advantages):
            logits = _mlp_apply(params["pi"], obs, jnp)
            logp_all = jax.nn.log_softmax(logits)
            logp = jnp.take_along_axis(logp_all, actions[:, None], axis=1)[:, 0]
            pg_loss = -(logp * advantages).mean()
            values = _mlp_apply(params["vf"], obs, jnp)[:, 0]
            vf_loss = ((values - vs_targets) ** 2).mean()
            entropy = -(jnp.exp(logp_all) * logp_all).sum(axis=1).mean()
            total = pg_loss + cfg.vf_coeff * vf_loss - cfg.entropy_coeff * entropy
            return total, {"pg_loss": pg_loss, "vf_loss": vf_loss,
                           "entropy": entropy}

        def update(params, opt_state, batch):
            (loss, metrics), grads = jax.value_and_grad(loss_fn, has_aux=True)(
                params, batch["obs"], batch["actions"], batch["vs_targets"],
                batch["advantages"],
            )
            updates, opt_state = self.optimizer.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            metrics["total_loss"] = loss
            return params, opt_state, metrics

        self._update = jax.jit(update)
        self._jnp = jnp

    def update(self, batch: dict) -> dict:
        jnp = self._jnp
        b = {
            "obs": jnp.asarray(batch["obs"], jnp.float32),
            "actions": jnp.asarray(batch["actions"], jnp.int32),
            "vs_targets": jnp.asarray(batch["vs_targets"], jnp.float32),
            "advantages": jnp.asarray(batch["advantages"], jnp.float32),
        }
        self.params, self.opt_state, metrics = self._update(
            self.params, self.opt_state, b
        )
        return {k: float(v) for k, v in metrics.items()}


class IMPALA:
    """The Algorithm (reference: algorithms/algorithm.py train() loop)."""

    def __init__(self, cfg: IMPALAConfig):
        import gymnasium as gym

        from ray_tpu.rllib.off_policy import probe_env_spaces

        self.cfg = cfg
        env_creator = (cfg.env if callable(cfg.env)
                       else (lambda name=cfg.env: gym.make(name)))
        obs_dim, num_actions = probe_env_spaces(env_creator)
        self.learner = self._make_learner(obs_dim, num_actions)
        self.env_steps_total = 0
        self.iterations = 0

        # shared numpy actor-critic policy: real V(obs) flows into
        # ep.bootstrap_value, so fragment-cut episodes bootstrap correctly
        from ray_tpu.rllib.np_policy import actor_critic_policy_fn as policy_fn

        self.runners = EnvRunnerGroup(env_creator, policy_fn,
                                      num_runners=cfg.num_env_runners)
        self.runners.sync_weights(self.learner.params)

    def _make_learner(self, obs_dim: int, num_actions: int):
        """Subclass hook (APPO swaps in its clipped-surrogate learner)."""
        return IMPALALearner(self.cfg, obs_dim, num_actions)

    def _episode_batch(self, episodes: list[Episode]) -> dict:
        cfg = self.cfg
        from ray_tpu.rllib.np_policy import log_softmax, np_mlp

        # numpy host pass for the V-trace inputs: episode lengths vary
        # continuously, so a jitted forward would recompile per length
        from ray_tpu.rllib.np_policy import to_numpy_params

        params_np = to_numpy_params(self.learner.params)
        obs_all, act_all, vs_all, adv_all = [], [], [], []
        for ep in episodes:
            if not len(ep):
                continue
            obs = np.asarray(ep.obs, np.float32)
            logp_cur_all = log_softmax(np_mlp(params_np["pi"], obs.astype(np.float64)))
            values = np_mlp(params_np["vf"], obs.astype(np.float64))[:, 0]
            actions = np.asarray(ep.actions, np.int64)
            logp_cur = logp_cur_all[np.arange(len(actions)), actions]
            dones = np.asarray(ep.dones, bool)
            vs, adv = vtrace(
                np.asarray(ep.logprobs, np.float64), logp_cur.astype(np.float64),
                np.asarray(ep.rewards, np.float64), values.astype(np.float64),
                float(ep.bootstrap_value), dones,
                cfg.gamma, cfg.rho_clip, cfg.c_clip,
            )
            obs_all.append(obs)
            act_all.append(actions)
            vs_all.append(vs)
            adv_all.append(adv)
        return {
            "obs": np.concatenate(obs_all),
            "actions": np.concatenate(act_all),
            "vs_targets": np.concatenate(vs_all).astype(np.float32),
            "advantages": np.concatenate(adv_all).astype(np.float32),
        }

    def _update_from_batch(self, batch: dict) -> dict:
        """Subclass hook: one plain update here; APPO does clipped
        multi-epoch minibatch SGD over the same batch."""
        return self.learner.update(batch)

    def train(self) -> dict:
        cfg = self.cfg
        episodes = self.runners.sample(cfg.rollout_fragment_length)
        self.env_steps_total += sum(len(e) for e in episodes)
        batch = self._episode_batch(episodes)
        metrics = self._update_from_batch(batch) if len(batch["obs"]) else {}
        self.iterations += 1
        # stale-broadcast: actors keep collecting with old weights between
        # broadcasts — the off-policy gap V-trace corrects
        if self.iterations % cfg.broadcast_interval == 0:
            self.runners.sync_weights(self.learner.params)
        finished = [e for e in episodes if e.dones and e.dones[-1]]
        return {
            "env_steps_total": self.env_steps_total,
            "episodes_this_iter": len(finished),
            "episode_reward_mean": (
                float(np.mean([e.total_reward() for e in finished]))
                if finished else float("nan")
            ),
            **metrics,
        }

    def stop(self) -> None:
        self.runners.stop()
