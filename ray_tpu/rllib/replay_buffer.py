"""Replay buffer: experience storage shared through the actor runtime.

Parity: rllib/utils/replay_buffers/ (ReplayBuffer + the actor-hosted usage in
off-policy algorithms) — transitions live in one buffer actor that env-runner
sampling feeds and learner updates draw from, so collection and learning
scale independently (reference: DQN's replay actor pattern).
"""

from __future__ import annotations

import numpy as np


class ReplayBuffer:
    """Uniform FIFO transition store (usable inline or as a runtime actor)."""

    def __init__(self, capacity: int = 100_000, seed: int = 0):
        self.capacity = capacity
        self._rng = np.random.default_rng(seed)
        self._storage: dict[str, np.ndarray] | None = None
        self._next = 0
        self._size = 0
        self.added_total = 0

    def add_batch(self, batch: dict) -> int:
        """Add {obs, actions, rewards, next_obs, dones} arrays (N rows each)."""
        n = len(batch["obs"])
        if n == 0:
            return self._size
        if self._storage is None:
            self._storage = {
                k: np.zeros((self.capacity, *np.asarray(v).shape[1:]),
                            dtype=np.asarray(v).dtype)
                for k, v in batch.items()
            }
        for k, v in batch.items():
            v = np.asarray(v)
            idx = (self._next + np.arange(n)) % self.capacity
            self._storage[k][idx] = v
        self._next = (self._next + n) % self.capacity
        self._size = min(self._size + n, self.capacity)
        self.added_total += n
        return self._size

    def sample(self, batch_size: int) -> dict:
        if self._size == 0:
            return {}
        idx = self._rng.integers(0, self._size, size=batch_size)
        return {k: v[idx] for k, v in self._storage.items()}

    def __len__(self) -> int:
        return self._size

    def size(self) -> int:
        return self._size

    def stats(self) -> dict:
        return {"size": self._size, "capacity": self.capacity,
                "added_total": self.added_total}
