"""ray_tpu.rllib: reinforcement learning (reference: rllib/).

Round-1 scope: PPO (jax learner + actor env-runner fleet). The Algorithm/
Learner/EnvRunner layering mirrors the reference's RLModule/Learner/EnvRunner
split so further algorithms (DQN/SAC/IMPALA) slot into the same structure.
"""

from ray_tpu.rllib.env_runner import EnvRunnerGroup, Episode, SingleAgentEnvRunner
from ray_tpu.rllib.ppo import PPO, PPOConfig, PPOLearner

__all__ = ["PPO", "PPOConfig", "PPOLearner", "EnvRunnerGroup", "Episode", "SingleAgentEnvRunner"]

from ray_tpu._private.usage_stats import record_library_usage as _rec

_rec("rllib")
del _rec
