"""ray_tpu.rllib: reinforcement learning (reference: rllib/).

Algorithms: PPO (on-policy, clipped surrogate + GAE), DQN (off-policy,
double-Q + target network + replay buffer actor), and discrete SAC (twin Q
critics, soft targets, learned temperature). The Algorithm/Learner/EnvRunner
layering mirrors the reference's RLModule/Learner/EnvRunner split; IMPALA
(V-trace with stale-broadcast actors) rides the same EnvRunner fleet.
"""

from ray_tpu.rllib.env_runner import EnvRunnerGroup, Episode, SingleAgentEnvRunner
from ray_tpu.rllib.connectors import (
    ClipActions,
    ClipObs,
    Connector,
    ConnectorPipeline,
    FlattenObs,
    FrameStack,
    NormalizeObs,
    UnsquashActions,
    pipeline,
)
from ray_tpu.rllib.ppo import PPO, PPOConfig, PPOLearner
from ray_tpu.rllib.dqn import DQN, DQNConfig, DQNLearner
from ray_tpu.rllib.replay_buffer import ReplayBuffer
from ray_tpu.rllib.sac import SAC, SACConfig, SACLearner
from ray_tpu.rllib.sac_continuous import (
    ContinuousSAC,
    ContinuousSACConfig,
    ContinuousSACLearner,
)
from ray_tpu.rllib.tqc import TQC, TQCConfig
from ray_tpu.rllib.iql import IQL, IQLConfig
from ray_tpu.rllib.impala import IMPALA, IMPALAConfig, IMPALALearner
from ray_tpu.rllib.dreamerv3 import DreamerV3, DreamerV3Config
from ray_tpu.rllib.learner_group import LearnerGroup
from ray_tpu.rllib.appo import APPO, APPOConfig, APPOLearner
from ray_tpu.rllib.multi_agent import (
    MultiAgentEnv,
    MultiAgentEnvRunner,
    MultiAgentPPO,
    MultiAgentPPOConfig,
)
from ray_tpu.rllib.offline import (
    BC,
    CQL,
    MARWIL,
    BCConfig,
    CQLConfig,
    MARWILConfig,
    load_offline_data,
    write_offline_json,
)

__all__ = ["PPO", "PPOConfig", "PPOLearner", "DQN", "DQNConfig", "DQNLearner",
           "ReplayBuffer", "SAC", "SACConfig", "SACLearner",
           "IMPALA", "IMPALAConfig", "IMPALALearner",
           "DreamerV3", "DreamerV3Config", "LearnerGroup",
           "APPO", "APPOConfig", "APPOLearner",
           "BC", "BCConfig", "MARWIL", "MARWILConfig", "CQL", "CQLConfig",
           "load_offline_data", "write_offline_json",
           "MultiAgentEnv", "MultiAgentEnvRunner", "MultiAgentPPO",
           "MultiAgentPPOConfig",
           "EnvRunnerGroup", "Episode", "SingleAgentEnvRunner",
           "ContinuousSAC", "ContinuousSACConfig", "TQC", "TQCConfig", "IQL", "IQLConfig", "ContinuousSACLearner",
           "Connector", "ConnectorPipeline", "FlattenObs", "ClipObs",
           "NormalizeObs", "FrameStack", "ClipActions", "UnsquashActions",
           "pipeline"]

from ray_tpu._private.usage_stats import record_library_usage as _rec

_rec("rllib")
del _rec
