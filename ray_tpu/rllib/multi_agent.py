"""Multi-agent RL: MultiAgentEnv + per-policy learners over one env fleet.

Parity: rllib/env/multi_agent_env.py (the dict-keyed env API with the
"__all__" done convention), multi_agent_env_runner.py:73 (per-agent episode
collection) and the policies/policy_mapping_fn config surface
(algorithm_config.multi_agent()). Each policy gets its own PPOLearner; one
shared EnvRunner fleet collects dict-keyed steps and routes each agent's
trajectory to its mapped policy.
"""

from __future__ import annotations

import dataclasses
from typing import Callable

import numpy as np

import ray_tpu
from ray_tpu.rllib.env_runner import Episode
from ray_tpu.rllib.ppo import PPOConfig, PPOLearner


class MultiAgentEnv:
    """Dict-keyed env ABC (reference: env/multi_agent_env.py).

    reset() -> (obs_dict, info_dict)
    step(action_dict) -> (obs, rewards, terminateds, truncateds, infos),
    each keyed by agent id; terminateds/truncateds carry "__all__".
    """

    possible_agents: list = []

    def reset(self, seed=None):
        raise NotImplementedError

    def step(self, action_dict: dict):
        raise NotImplementedError

    def close(self):
        pass


class MultiAgentEnvRunner:
    """One actor stepping a MultiAgentEnv; per-agent Episode segmentation
    (reference: multi_agent_env_runner.py:73)."""

    def __init__(self, env_creator: Callable, policy_fn: Callable,
                 policy_mapping: dict, seed: int = 0):
        self.env = env_creator()
        self.policy_fn = policy_fn  # (params, obs, rng) -> (action, logp, value)
        self.policy_mapping = policy_mapping  # agent_id -> policy_id
        self.weights = {}  # policy_id -> params
        self.rng = np.random.default_rng(seed)
        self._obs, _ = self.env.reset(seed=seed)
        # live episodes' reward from PRIOR fragments, per agent (the
        # single-agent runner's _carry_reward, here dict-keyed)
        self._carry: dict = {}

    def set_weights(self, weights: dict) -> None:
        self.weights = weights

    def sample(self, num_steps: int) -> dict:
        """~num_steps env steps; returns {policy_id: [Episode, ...]}."""
        out: dict[str, list[Episode]] = {}
        eps: dict[str, Episode] = {}  # live episode per agent
        steps = 0
        while steps < num_steps:
            actions, logps, values = {}, {}, {}
            for aid, obs in self._obs.items():
                pid = self.policy_mapping[aid]
                a, lp, v = self.policy_fn(
                    self.weights[pid], np.asarray(obs, np.float64), self.rng)
                actions[aid], logps[aid], values[aid] = a, lp, v
            nxt, rews, terms, truncs, _ = self.env.step(actions)
            for aid in actions:
                ep = eps.setdefault(
                    aid, Episode(reward_offset=self._carry.get(aid, 0.0)))
                done = bool(terms.get(aid) or truncs.get(aid)
                            or terms.get("__all__") or truncs.get("__all__"))
                ep.obs.append(np.asarray(self._obs[aid]))
                ep.actions.append(actions[aid])
                ep.rewards.append(float(rews.get(aid, 0.0)))
                ep.logprobs.append(logps[aid])
                ep.values.append(values[aid])
                ep.dones.append(done)
                ep.terminateds.append(bool(terms.get(aid) or terms.get("__all__")))
            steps += 1
            if terms.get("__all__") or truncs.get("__all__"):
                for aid, ep in eps.items():
                    out.setdefault(self.policy_mapping[aid], []).append(ep)
                eps = {}
                self._carry = {}
                self._obs, _ = self.env.reset()
            else:
                self._obs = nxt
        # fragment boundary: bootstrap live episodes with V(current obs) and
        # carry their reward-so-far into the next fragment's Episode
        for aid, ep in eps.items():
            if len(ep):
                pid = self.policy_mapping[aid]
                if aid in self._obs:
                    _, _, ep.bootstrap_value = self.policy_fn(
                        self.weights[pid],
                        np.asarray(self._obs[aid], np.float64), self.rng)
                self._carry[aid] = ep.total_reward()
                out.setdefault(pid, []).append(ep)
        return out

    def ping(self) -> str:
        return "ok"


@dataclasses.dataclass
class MultiAgentPPOConfig(PPOConfig):
    """policies: {policy_id: (obs_dim, num_actions)};
    policy_mapping: {agent_id: policy_id} (the reference's policy_mapping_fn,
    tabulated — mappings here are static per agent id)."""

    policies: dict = dataclasses.field(default_factory=dict)
    policy_mapping: dict = dataclasses.field(default_factory=dict)

    def multi_agent(self, policies: dict, policy_mapping: dict) -> "MultiAgentPPOConfig":
        self.policies = policies
        self.policy_mapping = policy_mapping
        return self

    def build(self) -> "MultiAgentPPO":
        return MultiAgentPPO(self)


class MultiAgentPPO:
    """Independent PPO per policy over a shared multi-agent env fleet."""

    def __init__(self, cfg: MultiAgentPPOConfig):
        if not cfg.policies or not cfg.policy_mapping:
            raise ValueError("multi_agent(policies=..., policy_mapping=...) required")
        if not callable(cfg.env):
            raise ValueError("MultiAgentPPO needs an env_creator callable")
        self.cfg = cfg
        self.learners = {
            pid: PPOLearner(cfg, obs_dim, num_actions)
            for pid, (obs_dim, num_actions) in cfg.policies.items()
        }
        from ray_tpu.rllib.np_policy import actor_critic_policy_fn

        runner_cls = ray_tpu.remote(num_cpus=1, max_concurrency=2)(MultiAgentEnvRunner)
        self.runners = [
            runner_cls.remote(cfg.env, actor_critic_policy_fn,
                              cfg.policy_mapping, seed=i)
            for i in range(cfg.num_env_runners)
        ]
        self._iteration = 0
        self._sync()

    def _np_weights(self) -> dict:
        from ray_tpu.rllib.np_policy import to_numpy_params

        return {pid: to_numpy_params(ln.params)
                for pid, ln in self.learners.items()}

    def _sync(self) -> None:
        w = self._np_weights()
        ray_tpu.get([r.set_weights.remote(w) for r in self.runners])

    def train(self) -> dict:
        from ray_tpu.rllib.ppo import gae

        cfg = self.cfg
        self._sync()
        per_policy: dict[str, list[Episode]] = {}
        for batch in ray_tpu.get(
            [r.sample.remote(cfg.rollout_fragment_length) for r in self.runners]
        ):
            for pid, eps in batch.items():
                per_policy.setdefault(pid, []).extend(eps)
        metrics: dict = {}
        rewards_all = []
        for pid, episodes in per_policy.items():
            obs, actions, logprobs, advs, rets = [], [], [], [], []
            for ep in episodes:
                if not len(ep):
                    continue
                a, r = gae(cfg, ep)
                obs.extend(ep.obs)
                actions.extend(ep.actions)
                logprobs.extend(ep.logprobs)
                advs.extend(a)
                rets.extend(r)
            if not obs:
                continue
            advs = np.asarray(advs, np.float32)
            advs = (advs - advs.mean()) / (advs.std() + 1e-8)
            from ray_tpu.rllib.ppo import minibatch_sgd

            metrics[pid] = minibatch_sgd(
                self.learners[pid].update,
                {"obs": np.asarray(obs, np.float32),
                 "actions": np.asarray(actions, np.int32),
                 "logprobs": np.asarray(logprobs, np.float32),
                 "advantages": advs,
                 "returns": np.asarray(rets, np.float32)},
                cfg.num_epochs, cfg.minibatch_size,
            )
            finished = [e for e in episodes if e.dones and e.dones[-1]]
            rewards_all += [e.total_reward() for e in finished]
        self._iteration += 1
        return {
            "training_iteration": self._iteration,
            "episode_reward_mean": (float(np.mean(rewards_all))
                                    if rewards_all else float("nan")),
            "policies": metrics,
        }

    def stop(self) -> None:
        for r in self.runners:
            try:
                ray_tpu.kill(r)
            except Exception:
                pass
