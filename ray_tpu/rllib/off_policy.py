"""Shared off-policy machinery: replay-driven train iteration.

Used by DQN and SAC (reference: the replay/update loop both inherit from the
off-policy Algorithm base in rllib/algorithms/)."""

from __future__ import annotations

import numpy as np

import ray_tpu


def off_policy_train_iteration(algo) -> dict:
    """One iteration: collect a fragment per runner, push transitions to the
    buffer actor, run pipelined replay updates, sync weights. `algo` provides
    cfg/runners/buffer/learner/env_steps_total (duck-typed)."""
    from ray_tpu.rllib.dqn import _episodes_to_transitions

    cfg = algo.cfg
    episodes = algo.runners.sample(cfg.rollout_fragment_length)
    algo.env_steps_total += sum(len(e) for e in episodes)
    batch = _episodes_to_transitions(episodes)
    size = ray_tpu.get(algo.buffer.add_batch.remote(batch), timeout=60)
    metrics: dict = {}
    updates = 0
    if size >= cfg.learning_starts:
        # pipeline: the next minibatch is in flight while this one trains
        next_ref = algo.buffer.sample.remote(cfg.train_batch_size)
        for _ in range(cfg.updates_per_iter):
            sample = ray_tpu.get(next_ref, timeout=60)
            next_ref = algo.buffer.sample.remote(cfg.train_batch_size)
            if not sample:
                break
            metrics = algo.learner.update(sample)
            updates += 1
        algo.runners.sync_weights(algo.learner.params)
    finished = [e for e in episodes if e.dones and e.dones[-1]]
    return {
        "env_steps_total": algo.env_steps_total,
        "buffer_size": size,
        "num_updates": updates,
        "episodes_this_iter": len(finished),
        "episode_reward_mean": (
            float(np.mean([e.total_reward() for e in finished]))
            if finished else float("nan")
        ),
        **metrics,
    }


def probe_env_spaces(env_creator) -> tuple[int, int]:
    """(obs_dim, num_actions) from one throwaway env instance."""
    probe = env_creator()
    obs_dim = int(np.prod(probe.observation_space.shape))
    num_actions = int(probe.action_space.n)
    probe.close()
    return obs_dim, num_actions
