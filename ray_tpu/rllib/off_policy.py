"""Shared off-policy machinery: replay-driven train iteration.

Used by DQN and SAC (reference: the replay/update loop both inherit from the
off-policy Algorithm base in rllib/algorithms/)."""

from __future__ import annotations

import numpy as np

import ray_tpu


def _episodes_to_transitions(episodes, action_dtype=np.int64) -> dict:
    """SARS'd tuples from episode fragments. The last step of a fragment cut
    mid-episode has no next_obs recorded — it is dropped (negligible at
    fragment lengths >> 1)."""
    obs, actions, rewards, next_obs, dones = [], [], [], [], []
    for ep in episodes:
        n = len(ep)
        terms = ep.terminateds or ep.dones
        for i in range(n):
            if ep.dones[i]:
                # terminated: masked out of the target; truncated: bootstrap
                # from the env's true final observation
                nxt = ep.final_obs if ep.final_obs is not None else ep.obs[i]
            elif i + 1 < n:
                nxt = ep.obs[i + 1]
            else:
                continue  # fragment-cut live step: next obs unknown
            obs.append(ep.obs[i])
            actions.append(ep.actions[i])
            rewards.append(ep.rewards[i])
            next_obs.append(nxt)
            # Q-targets bootstrap through time-limit TRUNCATION (next state
            # exists, the env just stopped watching) but not TERMINATION —
            # rllib's terminated/truncated distinction.
            dones.append(float(terms[i]))
    if not obs:
        return {"obs": np.zeros((0,)), "actions": np.zeros((0,), action_dtype),
                "rewards": np.zeros((0,)), "next_obs": np.zeros((0,)),
                "dones": np.zeros((0,))}
    return {
        "obs": np.asarray(obs, np.float32),
        "actions": np.asarray(actions, action_dtype),
        "rewards": np.asarray(rewards, np.float32),
        "next_obs": np.asarray(next_obs, np.float32),
        "dones": np.asarray(dones, np.float32),
    }



def off_policy_train_iteration(algo) -> dict:
    """One iteration: collect a fragment per runner, push transitions to the
    buffer actor, run pipelined replay updates, sync weights. `algo` provides
    cfg/runners/buffer/learner/env_steps_total (duck-typed)."""
    cfg = algo.cfg
    episodes = algo.runners.sample(cfg.rollout_fragment_length)
    algo.env_steps_total += sum(len(e) for e in episodes)
    batch = _episodes_to_transitions(
        episodes, getattr(algo, "action_dtype", np.int64)
    )
    size = ray_tpu.get(algo.buffer.add_batch.remote(batch), timeout=60)
    metrics: dict = {}
    updates = 0
    if size >= cfg.learning_starts:
        # pipeline: the next minibatch is in flight while this one trains
        next_ref = algo.buffer.sample.remote(cfg.train_batch_size)
        for _ in range(cfg.updates_per_iter):
            sample = ray_tpu.get(next_ref, timeout=60)
            next_ref = algo.buffer.sample.remote(cfg.train_batch_size)
            if not sample:
                break
            metrics = algo.learner.update(sample)
            updates += 1
        algo.runners.sync_weights(algo.learner.params)
    finished = [e for e in episodes if e.dones and e.dones[-1]]
    return {
        "env_steps_total": algo.env_steps_total,
        "buffer_size": size,
        "num_updates": updates,
        "episodes_this_iter": len(finished),
        "episode_reward_mean": (
            float(np.mean([e.total_reward() for e in finished]))
            if finished else float("nan")
        ),
        **metrics,
    }


def probe_env_spaces(env_creator) -> tuple[int, int]:
    """(obs_dim, num_actions) from one throwaway env instance."""
    probe = env_creator()
    obs_dim = int(np.prod(probe.observation_space.shape))
    num_actions = int(probe.action_space.n)
    probe.close()
    return obs_dim, num_actions


def probe_env_spaces_continuous(env_creator) -> tuple[int, int, np.ndarray, np.ndarray]:
    """(obs_dim, act_dim, low, high) for a Box action space."""
    probe = env_creator()
    try:
        space = probe.action_space
        if not hasattr(space, "high"):
            raise ValueError(
                f"continuous algorithm needs a Box action space, got {space}"
            )
        obs_dim = int(np.prod(probe.observation_space.shape))
        act_dim = int(np.prod(space.shape))
        low = np.asarray(space.low, np.float32).reshape(-1)
        high = np.asarray(space.high, np.float32).reshape(-1)
        if not (np.isfinite(low).all() and np.isfinite(high).all()):
            raise ValueError(
                f"continuous algorithm needs finite Box bounds, got "
                f"low={low} high={high} (wrap the env with a bounded action "
                f"space or rescale)"
            )
    finally:
        probe.close()
    return obs_dim, act_dim, low, high
