"""DreamerV3: model-based RL — RSSM world model + imagination actor-critic.

Parity: rllib/algorithms/dreamerv3/ (DreamerV3Config, the RSSM world model of
utils/summaries + torch/dreamerv3_torch_model, and the imagined-rollout
actor/critic losses). Re-designed jax-first: the RSSM unrolls under
``lax.scan`` (one XLA program for the whole sequence — no per-step Python),
categorical latents use straight-through gradients, and the three optimizers
(world model / actor / critic) are independent optax chains, matching the
reference's training split.

Kept small and dependency-free on purpose (vector observations; symlog
targets; KL balancing with free bits; lambda-returns over imagined
trajectories) — the algorithmic shape of DreamerV3 at unit-test scale.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable

import numpy as np

from ray_tpu.rllib.env_runner import Episode  # noqa: F401 (public surface)


@dataclasses.dataclass
class DreamerV3Config:
    """Reference: dreamerv3/dreamerv3.py DreamerV3Config."""

    env: str | Callable = "CartPole-v1"
    seed: int = 0
    # world model
    deter_dim: int = 64          # GRU / deterministic state
    stoch_classes: int = 8       # categorical latent: classes per group
    stoch_groups: int = 4        # ... and groups
    hidden: int = 64
    free_bits: float = 1.0
    kl_dyn_scale: float = 0.5
    kl_rep_scale: float = 0.1
    wm_lr: float = 3e-4
    # actor critic (imagination)
    horizon: int = 8
    gamma: float = 0.985
    lambda_: float = 0.95
    entropy_coeff: float = 3e-3
    actor_lr: float = 1e-4
    critic_lr: float = 1e-4
    # replay / batching
    batch_size: int = 8
    batch_length: int = 16
    buffer_capacity: int = 200   # episodes
    collect_episodes: int = 4
    max_episode_len: int = 200

    def environment(self, env) -> "DreamerV3Config":
        self.env = env
        return self

    def training(self, **kw) -> "DreamerV3Config":
        for k, v in kw.items():
            if not hasattr(self, k):
                raise ValueError(f"Unknown training option {k}")
            setattr(self, k, v)
        return self

    def build(self) -> "DreamerV3":
        return DreamerV3(self)


def _symlog(x):
    import jax.numpy as jnp

    return jnp.sign(x) * jnp.log1p(jnp.abs(x))


def _symexp(x):
    import jax.numpy as jnp

    return jnp.sign(x) * (jnp.exp(jnp.abs(x)) - 1.0)


def _linear(key, m, n, scale=1.0):
    import jax

    return {"w": jax.random.normal(key, (m, n)) * np.sqrt(scale / m),
            "b": np.zeros(n) * 0.0}


def _mlp(key, sizes):
    import jax

    keys = jax.random.split(key, len(sizes) - 1)
    return [_linear(k, m, n, 2.0) for k, m, n in
            zip(keys, sizes[:-1], sizes[1:])]


def _apply_mlp(params, x, jnp, act=True):
    for i, layer in enumerate(params):
        x = x @ layer["w"] + layer["b"]
        if act and i < len(params) - 1:
            x = jnp.tanh(x)
    return x


class DreamerV3:
    """The Algorithm (reference: dreamerv3.py training_step): collect with
    the filtering policy, train the world model on replayed sequences, train
    actor+critic on imagined rollouts from posterior states."""

    def __init__(self, cfg: DreamerV3Config):
        import gymnasium as gym
        import jax
        import jax.numpy as jnp
        import optax

        self.cfg = cfg
        self._env_creator = (cfg.env if callable(cfg.env)
                             else (lambda: gym.make(cfg.env)))
        probe = self._env_creator()
        self.obs_dim = int(np.prod(probe.observation_space.shape))
        self.num_actions = int(probe.action_space.n)
        probe.close()

        Z = cfg.stoch_classes * cfg.stoch_groups
        D, H, A = cfg.deter_dim, cfg.hidden, self.num_actions
        key = jax.random.PRNGKey(cfg.seed)
        ks = jax.random.split(key, 12)
        self.wm = {
            "enc": _mlp(ks[0], (self.obs_dim, H, H)),
            # GRU over [z, a] with hidden D (fused gates)
            "gru_x": _linear(ks[1], Z + A, 3 * D),
            "gru_h": _linear(ks[2], D, 3 * D),
            "prior": _mlp(ks[3], (D, H, Z)),
            "post": _mlp(ks[4], (D + H, H, Z)),
            "dec": _mlp(ks[5], (D + Z, H, self.obs_dim)),
            "rew": _mlp(ks[6], (D + Z, H, 1)),
            "cont": _mlp(ks[7], (D + Z, H, 1)),
        }
        self.actor = _mlp(ks[8], (D + Z, H, A))
        self.critic = _mlp(ks[9], (D + Z, H, 1))
        self.wm_opt = optax.chain(optax.clip_by_global_norm(100.0),
                                  optax.adam(cfg.wm_lr))
        self.actor_opt = optax.adam(cfg.actor_lr)
        self.critic_opt = optax.adam(cfg.critic_lr)
        self.wm_state = self.wm_opt.init(self.wm)
        self.actor_state = self.actor_opt.init(self.actor)
        self.critic_state = self.critic_opt.init(self.critic)
        self._rng = jax.random.PRNGKey(cfg.seed + 1)
        self._np_rng = np.random.default_rng(cfg.seed)
        self._buffer: list[dict] = []  # episodes of {obs, actions, rewards, dones}
        self._iteration = 0
        self._build_programs(jax, jnp)

    # ------------------------------------------------------------ programs
    def _build_programs(self, jax, jnp):
        cfg = self.cfg
        Z = cfg.stoch_classes * cfg.stoch_groups
        G, C = cfg.stoch_groups, cfg.stoch_classes
        A = self.num_actions

        def gru(wm, h, x):
            gates = x @ wm["gru_x"]["w"] + wm["gru_x"]["b"] \
                + h @ wm["gru_h"]["w"] + wm["gru_h"]["b"]
            r, u, c = jnp.split(gates, 3, axis=-1)
            r, u = jax.nn.sigmoid(r), jax.nn.sigmoid(u)
            cand = jnp.tanh(r * c)
            return u * h + (1 - u) * cand

        def sample_st(logits, key):
            """Straight-through categorical sample per latent group."""
            lg = logits.reshape(logits.shape[:-1] + (G, C))
            idx = jax.random.categorical(key, lg, axis=-1)
            onehot = jax.nn.one_hot(idx, C)
            probs = jax.nn.softmax(lg, axis=-1)
            st = onehot + probs - jax.lax.stop_gradient(probs)
            return st.reshape(st.shape[:-2] + (Z,))

        def kl_cat(lhs_logits, rhs_logits):
            """KL(lhs || rhs) summed over groups, free-bits clipped."""
            lp = jax.nn.log_softmax(lhs_logits.reshape(
                lhs_logits.shape[:-1] + (G, C)), axis=-1)
            rp = jax.nn.log_softmax(rhs_logits.reshape(
                rhs_logits.shape[:-1] + (G, C)), axis=-1)
            kl = (jnp.exp(lp) * (lp - rp)).sum(-1).sum(-1)
            return jnp.maximum(kl, cfg.free_bits)

        def observe(wm, obs_seq, act_seq, key):
            """Filter a [B, T, ...] batch through the RSSM (posterior)."""
            B = obs_seq.shape[0]
            embed = _apply_mlp(wm["enc"], obs_seq, jnp)  # [B,T,H]
            h0 = jnp.zeros((B, cfg.deter_dim))
            z0 = jnp.zeros((B, Z))
            keys = jax.random.split(key, obs_seq.shape[1])

            def step(carry, inp):
                h, z = carry
                emb_t, act_t, k = inp
                h = gru(wm, h, jnp.concatenate([z, act_t], -1))
                prior_logits = _apply_mlp(wm["prior"], h, jnp)
                post_logits = _apply_mlp(
                    wm["post"], jnp.concatenate([h, emb_t], -1), jnp)
                z = sample_st(post_logits, k)
                return (h, z), (h, z, prior_logits, post_logits)

            (_, _), (hs, zs, priors, posts) = jax.lax.scan(
                step, (h0, z0),
                (embed.swapaxes(0, 1), act_seq.swapaxes(0, 1), keys))
            # back to [B, T, ...]
            return (hs.swapaxes(0, 1), zs.swapaxes(0, 1),
                    priors.swapaxes(0, 1), posts.swapaxes(0, 1))

        def wm_loss(wm, batch, key):
            obs, acts = batch["obs"], batch["actions"]
            # PREVIOUS action drives the transition into step t (matches the
            # collector: h_{t+1} = gru(h_t, [z_t, a_t]) with a_t sampled
            # AFTER observing o_t); without the shift the filter would
            # condition step t's posterior on the action taken at t — a
            # temporal leak the imagination rollout can't reproduce.
            prev_acts = jnp.concatenate(
                [jnp.zeros_like(acts[:, :1]), acts[:, :-1]], axis=1)
            hs, zs, priors, posts = observe(wm, obs, prev_acts, key)
            feat = jnp.concatenate([hs, zs], -1)
            recon = _apply_mlp(wm["dec"], feat, jnp)
            rew_hat = _apply_mlp(wm["rew"], feat, jnp)[..., 0]
            cont_hat = _apply_mlp(wm["cont"], feat, jnp)[..., 0]
            recon_l = ((recon - _symlog(obs)) ** 2).sum(-1).mean()
            rew_l = ((rew_hat - _symlog(batch["rewards"])) ** 2).mean()
            cont_t = 1.0 - batch["dones"]
            cont_l = optax_sigmoid_bce(cont_hat, cont_t).mean()
            dyn = kl_cat(jax.lax.stop_gradient(posts), priors).mean()
            rep = kl_cat(posts, jax.lax.stop_gradient(priors)).mean()
            loss = (recon_l + rew_l + cont_l
                    + cfg.kl_dyn_scale * dyn + cfg.kl_rep_scale * rep)
            return loss, {"wm_loss": loss, "recon": recon_l, "reward": rew_l,
                          "continue": cont_l, "kl_dyn": dyn, "kl_rep": rep,
                          "hs": hs, "zs": zs}

        import optax

        def optax_sigmoid_bce(logits, labels):
            return optax.sigmoid_binary_cross_entropy(logits, labels)

        def imagine(wm, actor, h, z, key):
            """Roll the PRIOR forward under the actor for `horizon` steps."""
            keys = jax.random.split(key, cfg.horizon)

            def step(carry, k):
                h, z = carry
                feat = jnp.concatenate([h, z], -1)
                ka, kz = jax.random.split(k)
                logits = _apply_mlp(actor, feat, jnp)
                a = jax.nn.one_hot(
                    jax.random.categorical(ka, logits, axis=-1), A)
                h = gru(wm, h, jnp.concatenate([z, a], -1))
                z = sample_st(_apply_mlp(wm["prior"], h, jnp), kz)
                logp = jax.nn.log_softmax(logits)
                ent = -(jnp.exp(logp) * logp).sum(-1)
                return (h, z), (h, z, ent)

            (_, _), (hs, zs, ents) = jax.lax.scan(step, (h, z), keys)
            return hs, zs, ents  # [T, N, ...]

        def lambda_returns(rews, conts, values):
            def step(nxt, inp):
                r, c, v_next = inp
                ret = r + cfg.gamma * c * (
                    (1 - cfg.lambda_) * v_next + cfg.lambda_ * nxt)
                return ret, ret

            last = values[-1]
            _, rets = jax.lax.scan(
                step, last, (rews[:-1], conts[:-1], values[1:]), reverse=True)
            return rets  # [T-1, N]

        def ac_update(actor, critic, a_state, c_state, wm, sh, sz, key):
            # ONE imagination rollout per step: the actor grad owns it (the
            # rollout depends on the actor's sampled actions); the critic
            # regresses against the SAME rollout's stop-gradient features —
            # re-imagining for the critic would double the dominant cost.
            def a_fn(a):
                hs, zs, ents = imagine(wm, a, sh, sz, key)
                feat = jnp.concatenate([hs, zs], -1)
                rews = _symexp(_apply_mlp(wm["rew"], feat, jnp)[..., 0])
                conts = jax.nn.sigmoid(
                    _apply_mlp(wm["cont"], feat, jnp)[..., 0])
                values = _apply_mlp(
                    critic, jax.lax.stop_gradient(feat), jnp)[..., 0]
                rets = lambda_returns(rews, conts, values)
                actor_l = -(rets.mean() + cfg.entropy_coeff * ents.mean())
                return actor_l, (rets, feat)

            (al, (rets, feat)), a_grads = jax.value_and_grad(
                a_fn, has_aux=True)(actor)
            sg_feat = jax.lax.stop_gradient(feat)
            sg_rets = jax.lax.stop_gradient(rets)

            def c_fn(c):
                values = _apply_mlp(c, sg_feat, jnp)[..., 0]
                return ((values[:-1] - sg_rets) ** 2).mean()

            cl, c_grads = jax.value_and_grad(c_fn)(critic)
            au, a_state = self.actor_opt.update(a_grads, a_state, actor)
            cu, c_state = self.critic_opt.update(c_grads, c_state, critic)
            import optax as _ox

            return (_ox.apply_updates(actor, au),
                    _ox.apply_updates(critic, cu),
                    a_state, c_state,
                    {"actor_loss": al, "critic_loss": cl,
                     "imagined_return": rets.mean()})

        def wm_update(wm, state, batch, key):
            (loss, aux), grads = jax.value_and_grad(wm_loss, has_aux=True)(
                wm, batch, key)
            updates, state = self.wm_opt.update(grads, state, wm)
            import optax as _ox

            return _ox.apply_updates(wm, updates), state, aux

        self._wm_update = jax.jit(wm_update)
        self._ac_update = jax.jit(ac_update)

        def policy_step(wm, actor, h, z, obs, key):
            """One filtering + acting step for the collector."""
            emb = _apply_mlp(wm["enc"], obs, jnp)
            post = _apply_mlp(wm["post"], jnp.concatenate([h, emb], -1), jnp)
            kz, ka = jax.random.split(key)
            z = sample_st(post, kz)
            logits = _apply_mlp(actor, jnp.concatenate([h, z], -1), jnp)
            a = jax.random.categorical(ka, logits, axis=-1)
            h_next = gru(wm, h, jnp.concatenate(
                [z, jax.nn.one_hot(a, A)], -1))
            return h_next, z, a

        self._policy_step = jax.jit(policy_step)

    # ------------------------------------------------------------ data
    def _collect(self) -> float:
        import jax
        import jax.numpy as jnp

        cfg = self.cfg
        total = 0.0
        for _ in range(cfg.collect_episodes):
            env = self._env_creator()
            obs, _ = env.reset(seed=int(self._np_rng.integers(1 << 30)))
            h = jnp.zeros((1, cfg.deter_dim))
            z = jnp.zeros((1, cfg.stoch_classes * cfg.stoch_groups))
            ep = {"obs": [], "actions": [], "rewards": [], "dones": []}
            for _t in range(cfg.max_episode_len):
                self._rng, k = jax.random.split(self._rng)
                o = jnp.asarray(np.asarray(obs, np.float32))[None]
                h, z, a = self._policy_step(self.wm, self.actor, h, z, o, k)
                act = int(a[0])
                nxt, rew, term, trunc, _ = env.step(act)
                ep["obs"].append(np.asarray(obs, np.float32))
                ep["actions"].append(act)
                ep["rewards"].append(float(rew))
                ep["dones"].append(bool(term))
                total += float(rew)
                obs = nxt
                if term or trunc:
                    break
            env.close()
            self._buffer.append({k2: np.asarray(v) for k2, v in ep.items()})
            if len(self._buffer) > cfg.buffer_capacity:
                self._buffer.pop(0)
        return total / cfg.collect_episodes

    def _sample_batch(self) -> dict:
        cfg = self.cfg
        B, T = cfg.batch_size, cfg.batch_length
        obs = np.zeros((B, T, self.obs_dim), np.float32)
        acts = np.zeros((B, T, self.num_actions), np.float32)
        rews = np.zeros((B, T), np.float32)
        dones = np.zeros((B, T), np.float32)
        for b in range(B):
            ep = self._buffer[self._np_rng.integers(len(self._buffer))]
            L = len(ep["rewards"])
            lo = self._np_rng.integers(max(1, L - T + 1))
            sl = slice(lo, lo + T)
            n = len(ep["rewards"][sl])
            obs[b, :n] = ep["obs"][sl]
            onehot = np.eye(self.num_actions, dtype=np.float32)[ep["actions"][sl]]
            acts[b, :n] = onehot
            rews[b, :n] = ep["rewards"][sl]
            dones[b, :n] = ep["dones"][sl]
        return {"obs": obs, "actions": acts, "rewards": rews, "dones": dones}

    # ------------------------------------------------------------ train
    def train(self) -> dict:
        import jax

        mean_reward = self._collect()
        batch = self._sample_batch()
        self._rng, k1, k2 = jax.random.split(self._rng, 3)
        self.wm, self.wm_state, aux = self._wm_update(
            self.wm, self.wm_state, batch, k1)
        # imagination starts: flatten the posterior states
        hs, zs = aux.pop("hs"), aux.pop("zs")
        sh = hs.reshape(-1, hs.shape[-1])
        sz = zs.reshape(-1, zs.shape[-1])
        (self.actor, self.critic, self.actor_state, self.critic_state,
         ac_metrics) = self._ac_update(
            self.actor, self.critic, self.actor_state, self.critic_state,
            self.wm, sh, sz, k2)
        self._iteration += 1
        out = {k: float(v) for k, v in {**aux, **ac_metrics}.items()}
        out["episode_reward_mean"] = mean_reward
        out["training_iteration"] = self._iteration
        out["buffer_episodes"] = len(self._buffer)
        return out
