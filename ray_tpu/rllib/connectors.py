"""Connector pipelines: composable observation/action transforms for rollouts.

Parity: rllib/connectors/ — env-to-module pipelines shape raw env
observations into what the policy consumes (flatten, running-stat
normalization, frame stacking), module-to-env pipelines shape policy outputs
into what the env consumes (clip, unsquash). Connectors are stateful where
the transform requires it (frame stacks reset at episode boundaries; running
stats accumulate per runner), and pipelines are built per EnvRunner from a
factory so actor-parallel runners never share mutable state.
"""

from __future__ import annotations

from collections import deque
from typing import Callable, Sequence

import numpy as np


class Connector:
    """One transform stage. Override __call__; override reset() if stateful."""

    def __call__(self, x: np.ndarray) -> np.ndarray:
        raise NotImplementedError

    def reset(self) -> None:
        """Called at episode boundaries (stateful connectors drop state)."""


class ConnectorPipeline(Connector):
    def __init__(self, connectors: Sequence[Connector]):
        self.connectors = list(connectors)

    def __call__(self, x):
        for c in self.connectors:
            x = c(x)
        return x

    def reset(self) -> None:
        for c in self.connectors:
            c.reset()


# ------------------------------------------------------------- env-to-module
class FlattenObs(Connector):
    def __call__(self, obs):
        return np.asarray(obs, np.float32).reshape(-1)


class ClipObs(Connector):
    def __init__(self, low: float = -10.0, high: float = 10.0):
        self.low, self.high = low, high

    def __call__(self, obs):
        return np.clip(obs, self.low, self.high)


class NormalizeObs(Connector):
    """Running mean/std standardization (Welford). Stats persist across
    episodes (they describe the observation distribution, not the episode)."""

    def __init__(self, eps: float = 1e-8, clip: float = 10.0):
        self.count = 0
        self.mean: np.ndarray | None = None
        self.m2: np.ndarray | None = None
        self.eps, self.clip = eps, clip

    def __call__(self, obs):
        obs = np.asarray(obs, np.float64)
        if self.mean is None:
            self.mean = np.zeros_like(obs)
            self.m2 = np.zeros_like(obs)
        self.count += 1
        delta = obs - self.mean
        self.mean += delta / self.count
        self.m2 += delta * (obs - self.mean)
        var = self.m2 / max(1, self.count - 1) if self.count > 1 else np.ones_like(obs)
        out = (obs - self.mean) / np.sqrt(var + self.eps)
        return np.clip(out, -self.clip, self.clip).astype(np.float32)


class FrameStack(Connector):
    """Concatenate the last k observations (zero-padded at episode start)."""

    def __init__(self, k: int = 4):
        if k < 1:
            raise ValueError("FrameStack k must be >= 1")
        self.k = k
        self.frames: deque = deque(maxlen=k)

    def __call__(self, obs):
        obs = np.asarray(obs, np.float32)
        if not self.frames:
            for _ in range(self.k - 1):
                self.frames.append(np.zeros_like(obs))
        self.frames.append(obs)
        return np.concatenate(list(self.frames), axis=-1)

    def reset(self) -> None:
        self.frames.clear()


# ------------------------------------------------------------- module-to-env
class ClipActions(Connector):
    def __init__(self, low, high):
        self.low = np.asarray(low, np.float32)
        self.high = np.asarray(high, np.float32)

    def __call__(self, action):
        return np.clip(action, self.low, self.high)


class UnsquashActions(Connector):
    """Map policy-space [-1, 1] onto the env's Box range."""

    def __init__(self, low, high):
        self.low = np.asarray(low, np.float32)
        self.high = np.asarray(high, np.float32)

    def __call__(self, action):
        a = np.clip(np.asarray(action, np.float32), -1.0, 1.0)
        return self.low + (a + 1.0) * 0.5 * (self.high - self.low)


PipelineFactory = Callable[[], ConnectorPipeline]


def pipeline(*connector_factories: Callable[[], Connector]) -> PipelineFactory:
    """Factory-of-factories: each EnvRunner actor builds its own stateful
    pipeline instance (reference: connector pipelines are per-EnvRunner)."""

    def make() -> ConnectorPipeline:
        return ConnectorPipeline([f() for f in connector_factories])

    return make
