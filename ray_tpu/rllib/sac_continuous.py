"""Continuous-action SAC: tanh-squashed Gaussian policy, twin Q, learned alpha.

Parity: rllib/algorithms/sac/ in its original continuous-control form
(Haarnoja 2018) — the discrete variant lives in sac.py. Same Learner/
EnvRunner layering; one jitted XLA update covers both critics, the
reparameterized actor, and the temperature. Actions map env-range <->
[-1, 1] at the algorithm boundary, so the learner is scale-free.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Optional

import numpy as np

import ray_tpu
from ray_tpu.rllib.env_runner import EnvRunnerGroup
from ray_tpu.rllib.ppo import _mlp_apply, _mlp_init
from ray_tpu.rllib.replay_buffer import ReplayBuffer

LOG_STD_MIN, LOG_STD_MAX = -5.0, 2.0


@dataclasses.dataclass
class ContinuousSACConfig:
    env: str | Callable = "Pendulum-v1"
    num_env_runners: int = 2
    rollout_fragment_length: int = 200
    actor_lr: float = 3e-4
    critic_lr: float = 3e-4
    alpha_lr: float = 3e-4
    gamma: float = 0.99
    tau: float = 0.005
    target_entropy: Optional[float] = None  # None => -act_dim (SAC default)
    buffer_capacity: int = 100_000
    learning_starts: int = 1000
    train_batch_size: int = 256
    updates_per_iter: int = 64
    hidden: tuple = (64, 64)
    seed: int = 0

    def environment(self, env) -> "ContinuousSACConfig":
        self.env = env
        return self

    def env_runners(self, num_env_runners: int,
                    rollout_fragment_length: int | None = None) -> "ContinuousSACConfig":
        self.num_env_runners = num_env_runners
        if rollout_fragment_length:
            self.rollout_fragment_length = rollout_fragment_length
        return self

    def training(self, **kw) -> "ContinuousSACConfig":
        fields = {f.name for f in dataclasses.fields(self)}
        for k, v in kw.items():
            if k not in fields:
                raise ValueError(f"Unknown training option {k}")
            setattr(self, k, v)
        return self

    def build(self) -> "ContinuousSAC":
        return ContinuousSAC(self)


def _squashed_gaussian(jnp, jax, pi_out, eps):
    """tanh(mu + std*eps) with its log-prob (change-of-variables corrected)."""
    mu, log_std = jnp.split(pi_out, 2, axis=-1)
    log_std = jnp.clip(log_std, LOG_STD_MIN, LOG_STD_MAX)
    std = jnp.exp(log_std)
    raw = mu + std * eps
    act = jnp.tanh(raw)
    # N(mu, std) log-density at raw, minus the tanh Jacobian term
    logp = (-0.5 * ((raw - mu) / std) ** 2 - log_std
            - 0.5 * jnp.log(2.0 * jnp.pi)).sum(-1)
    logp -= (2.0 * (jnp.log(2.0) - raw - jax.nn.softplus(-2.0 * raw))).sum(-1)
    return act, logp


class ContinuousSACLearner:
    """Twin Q(s,a) critics + reparameterized actor + temperature, one jit."""

    def __init__(self, cfg: ContinuousSACConfig, obs_dim: int, act_dim: int):
        import jax
        import jax.numpy as jnp
        import optax

        self.cfg = cfg
        key = jax.random.PRNGKey(cfg.seed)
        kp, k1, k2, self._key = jax.random.split(key, 4)
        self.params = {
            "pi": _mlp_init(kp, (obs_dim, *cfg.hidden, 2 * act_dim)),
            "q1": _mlp_init(k1, (obs_dim + act_dim, *cfg.hidden, 1)),
            "q2": _mlp_init(k2, (obs_dim + act_dim, *cfg.hidden, 1)),
            "log_alpha": jnp.zeros(()),
        }
        self.target = {"q1": self.params["q1"], "q2": self.params["q2"]}
        self.optimizer = optax.chain(
            optax.clip_by_global_norm(5.0),
            optax.multi_transform(
                {"actor": optax.adam(cfg.actor_lr),
                 "critic": optax.adam(cfg.critic_lr),
                 "alpha": optax.adam(cfg.alpha_lr)},
                {"pi": "actor", "q1": "critic", "q2": "critic",
                 "log_alpha": "alpha"},
            ),
        )
        self.opt_state = self.optimizer.init(self.params)
        target_entropy = (cfg.target_entropy if cfg.target_entropy is not None
                          else -float(act_dim))
        self.num_updates = 0

        def q_apply(q, obs, act):
            return _mlp_apply(q, jnp.concatenate([obs, act], axis=-1), jnp)[:, 0]

        def loss_fn(params, target, key, obs, actions, rewards, next_obs, dones):
            alpha = jnp.exp(params["log_alpha"])
            k_next, k_pi = jax.random.split(key)
            B, A = actions.shape
            # --- critic target: soft Bellman backup through the next action ---
            next_a, next_logp = _squashed_gaussian(
                jnp, jax, _mlp_apply(params["pi"], next_obs, jnp),
                jax.random.normal(k_next, (B, A)),
            )
            tq = jnp.minimum(q_apply(target["q1"], next_obs, next_a),
                             q_apply(target["q2"], next_obs, next_a))
            target_q = jax.lax.stop_gradient(
                rewards + cfg.gamma * (1.0 - dones)
                * (tq - jax.lax.stop_gradient(alpha) * next_logp)
            )
            q1 = q_apply(params["q1"], obs, actions)
            q2 = q_apply(params["q2"], obs, actions)
            critic_loss = ((q1 - target_q) ** 2).mean() + ((q2 - target_q) ** 2).mean()
            # --- actor: reparameterized sample through min-Q ---
            a_pi, logp_pi = _squashed_gaussian(
                jnp, jax, _mlp_apply(params["pi"], obs, jnp),
                jax.random.normal(k_pi, (B, A)),
            )
            # gradient flows through the ACTION (reparameterization) but must
            # not reach critic weights — else the actor term inflates Q
            q1_frozen = jax.lax.stop_gradient(params["q1"])
            q2_frozen = jax.lax.stop_gradient(params["q2"])
            q_min = jnp.minimum(q_apply(q1_frozen, obs, a_pi),
                                q_apply(q2_frozen, obs, a_pi))
            actor_loss = (jax.lax.stop_gradient(alpha) * logp_pi - q_min).mean()
            # --- temperature ---
            alpha_loss = (-params["log_alpha"]
                          * jax.lax.stop_gradient(logp_pi + target_entropy)).mean()
            total = critic_loss + actor_loss + alpha_loss
            return total, {
                "critic_loss": critic_loss, "actor_loss": actor_loss,
                "alpha": alpha, "entropy": -logp_pi.mean(),
            }

        def update(params, target, opt_state, key, batch):
            (loss, metrics), grads = jax.value_and_grad(loss_fn, has_aux=True)(
                params, target, key, batch["obs"], batch["actions"],
                batch["rewards"], batch["next_obs"], batch["dones"],
            )
            updates, opt_state = self.optimizer.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            target = jax.tree.map(
                lambda t, o: (1 - cfg.tau) * t + cfg.tau * o,
                target, {"q1": params["q1"], "q2": params["q2"]},
            )
            metrics["total_loss"] = loss
            return params, target, opt_state, metrics

        self._update = jax.jit(update)
        self._jax, self._jnp = jax, jnp

    def update(self, batch: dict) -> dict:
        jnp = self._jnp
        # actions arrive module-space [-1,1] (UnsquashActions maps to env range
        # at the runner boundary), so the learner is scale-free
        b = {
            "obs": jnp.asarray(batch["obs"], jnp.float32),
            "actions": jnp.asarray(batch["actions"], jnp.float32),
            "rewards": jnp.asarray(batch["rewards"], jnp.float32),
            "next_obs": jnp.asarray(batch["next_obs"], jnp.float32),
            "dones": jnp.asarray(batch["dones"], jnp.float32),
        }
        self._key, sub = self._jax.random.split(self._key)
        self.params, self.target, self.opt_state, metrics = self._update(
            self.params, self.target, self.opt_state, sub, b
        )
        self.num_updates += 1
        return {k: float(v) for k, v in metrics.items()}


class ContinuousSAC:
    """The Algorithm (reference: algorithms/algorithm.py train() loop)."""

    action_dtype = np.float32  # consulted by off_policy_train_iteration
    learner_cls: "type | None" = None  # TQC swaps in its quantile learner

    def __init__(self, cfg: ContinuousSACConfig):
        import gymnasium as gym

        from ray_tpu.rllib.off_policy import probe_env_spaces_continuous

        self.cfg = cfg
        env_creator = (cfg.env if callable(cfg.env)
                       else (lambda name=cfg.env: gym.make(name)))
        obs_dim, act_dim, low, high = probe_env_spaces_continuous(env_creator)
        learner_cls = type(self).learner_cls or ContinuousSACLearner
        self.learner = learner_cls(cfg, obs_dim, act_dim)
        self.env_steps_total = 0

        import jax
        import jax.numpy as jnp

        from ray_tpu.rllib.connectors import UnsquashActions, pipeline

        pi_apply = jax.jit(lambda p, o: _mlp_apply(p, o, jnp))

        def policy_fn(params, obs, rng):
            # module-space action in [-1,1]; the UnsquashActions connector maps
            # to the env's Box range at the runner boundary, so episodes (and
            # the replay buffer) hold module-space actions
            out = np.asarray(pi_apply(params["pi"], obs[None]))[0]
            mu, log_std = out[:act_dim], out[act_dim:]
            std = np.exp(np.clip(log_std, LOG_STD_MIN, LOG_STD_MAX))
            a = np.tanh(mu + std * rng.standard_normal(act_dim))
            return a.astype(np.float32), 0.0, 0.0

        self.runners = EnvRunnerGroup(
            env_creator, policy_fn, num_runners=cfg.num_env_runners,
            module_to_env=pipeline(lambda: UnsquashActions(low, high)),
        )
        self.runners.sync_weights(self.learner.params)
        BufferActor = ray_tpu.remote(num_cpus=0)(ReplayBuffer)
        self.buffer = BufferActor.remote(cfg.buffer_capacity, cfg.seed)

    def train(self) -> dict:
        from ray_tpu.rllib.off_policy import off_policy_train_iteration

        return off_policy_train_iteration(self)

    def stop(self) -> None:
        self.runners.stop()
        try:
            ray_tpu.kill(self.buffer)
        except Exception:
            pass
