"""Numpy-side actor-critic policy for env runners.

Shared by PPO/IMPALA: env-runner actors evaluate the tiny MLP in numpy — no
jit dispatch per env step, no traced functions shipped to actors (reference:
env runners hold plain RLModule forward passes)."""

from __future__ import annotations

import numpy as np


def to_numpy_params(params):
    """Host-side copy of a {head: [layer dicts]} param tree (or a bare layer
    list) — the one serialization used everywhere actors receive weights."""
    if isinstance(params, dict):
        return {k: [{kk: np.asarray(vv) for kk, vv in layer.items()}
                    for layer in v]
                for k, v in params.items()}
    return [{k: np.asarray(w) for k, w in layer.items()} for layer in params]


def np_mlp(layers, x: np.ndarray) -> np.ndarray:
    """Forward the _mlp_init layer list in numpy (tanh hidden activations)."""
    for i, layer in enumerate(layers):
        x = x @ np.asarray(layer["w"]) + np.asarray(layer["b"])
        if i < len(layers) - 1:
            x = np.tanh(x)
    return x


def log_softmax(logits: np.ndarray) -> np.ndarray:
    z = logits - logits.max(axis=-1, keepdims=True)
    return z - np.log(np.exp(z).sum(axis=-1, keepdims=True))


def actor_critic_policy_fn(params, obs, rng):
    """(action, logprob, value) from {"pi": layers, "vf": layers} params."""
    logits = np_mlp(params["pi"], obs.astype(np.float64))
    z = logits - logits.max()
    p = np.exp(z)
    p /= p.sum()
    action = int(rng.choice(len(p), p=p))
    v = np_mlp(params["vf"], obs.astype(np.float64))
    return action, float(np.log(p[action] + 1e-12)), float(v[0])
