"""Discrete SAC on JAX: twin Q critics, entropy-temperature auto-tuning.

Parity: rllib/algorithms/sac/ (SAC with twin Q networks, soft targets, and
learned alpha) in its discrete-action form (Christodoulou 2019), over the
shared Learner/EnvRunner layering like PPO/DQN. One jitted XLA update covers
actor, both critics, and the temperature.
"""

from __future__ import annotations

import dataclasses
from typing import Callable

import numpy as np

import ray_tpu
from ray_tpu.rllib.env_runner import EnvRunnerGroup
from ray_tpu.rllib.ppo import _mlp_apply, _mlp_init
from ray_tpu.rllib.replay_buffer import ReplayBuffer


@dataclasses.dataclass
class SACConfig:
    """Reference: SACConfig surface (fluent API below)."""

    env: str | Callable = "CartPole-v1"
    num_env_runners: int = 2
    rollout_fragment_length: int = 256
    actor_lr: float = 3e-4
    critic_lr: float = 3e-4
    alpha_lr: float = 3e-4
    gamma: float = 0.99
    tau: float = 0.01  # soft target update rate
    target_entropy_scale: float = 0.7  # of max entropy log(|A|)
    buffer_capacity: int = 50_000
    learning_starts: int = 500
    train_batch_size: int = 128
    updates_per_iter: int = 64
    hidden: tuple = (64, 64)
    seed: int = 0

    def environment(self, env) -> "SACConfig":
        self.env = env
        return self

    def env_runners(self, num_env_runners: int,
                    rollout_fragment_length: int | None = None) -> "SACConfig":
        self.num_env_runners = num_env_runners
        if rollout_fragment_length:
            self.rollout_fragment_length = rollout_fragment_length
        return self

    def training(self, **kw) -> "SACConfig":
        fields = {f.name for f in dataclasses.fields(self)}
        for k, v in kw.items():
            if k not in fields:
                raise ValueError(f"Unknown training option {k}")
            setattr(self, k, v)
        return self

    def build(self) -> "SAC":
        return SAC(self)


class SACLearner:
    """Actor + twin critics + temperature in one jitted update."""

    def __init__(self, cfg: SACConfig, obs_dim: int, num_actions: int):
        import jax
        import jax.numpy as jnp
        import optax

        self.cfg = cfg
        key = jax.random.PRNGKey(cfg.seed)
        kp, k1, k2 = jax.random.split(key, 3)
        sizes = (obs_dim, *cfg.hidden, num_actions)
        self.params = {
            "pi": _mlp_init(kp, sizes),
            "q1": _mlp_init(k1, sizes),
            "q2": _mlp_init(k2, sizes),
            "log_alpha": jnp.zeros(()),
        }
        self.target = {"q1": self.params["q1"], "q2": self.params["q2"]}
        # per-component learning rates (reference: SAC's separate actor/
        # critic/alpha optimizers) via multi_transform over top-level keys
        self.optimizer = optax.chain(
            optax.clip_by_global_norm(5.0),
            optax.multi_transform(
                {"actor": optax.adam(cfg.actor_lr),
                 "critic": optax.adam(cfg.critic_lr),
                 "alpha": optax.adam(cfg.alpha_lr)},
                {"pi": "actor", "q1": "critic", "q2": "critic",
                 "log_alpha": "alpha"},
            ),
        )
        self.opt_state = self.optimizer.init(self.params)
        target_entropy = cfg.target_entropy_scale * float(np.log(num_actions))
        self.num_updates = 0

        def loss_fn(params, target, obs, actions, rewards, next_obs, dones):
            alpha = jnp.exp(params["log_alpha"])
            # --- critic targets (soft state value of next state) ---
            next_logits = _mlp_apply(params["pi"], next_obs, jnp)
            next_logp = jax.nn.log_softmax(next_logits)
            next_p = jnp.exp(next_logp)
            tq1 = _mlp_apply(target["q1"], next_obs, jnp)
            tq2 = _mlp_apply(target["q2"], next_obs, jnp)
            tq = jnp.minimum(tq1, tq2)
            next_v = (next_p * (tq - jax.lax.stop_gradient(alpha) * next_logp)).sum(-1)
            target_q = jax.lax.stop_gradient(
                rewards + cfg.gamma * (1.0 - dones) * next_v
            )
            q1 = jnp.take_along_axis(
                _mlp_apply(params["q1"], obs, jnp), actions[:, None], axis=1)[:, 0]
            q2 = jnp.take_along_axis(
                _mlp_apply(params["q2"], obs, jnp), actions[:, None], axis=1)[:, 0]
            critic_loss = ((q1 - target_q) ** 2).mean() + ((q2 - target_q) ** 2).mean()
            # --- actor: minimize E[alpha*logp - Q] over action distribution ---
            logits = _mlp_apply(params["pi"], obs, jnp)
            logp = jax.nn.log_softmax(logits)
            p = jnp.exp(logp)
            q_min = jax.lax.stop_gradient(jnp.minimum(
                _mlp_apply(params["q1"], obs, jnp),
                _mlp_apply(params["q2"], obs, jnp),
            ))
            actor_loss = (p * (jax.lax.stop_gradient(alpha) * logp - q_min)).sum(-1).mean()
            # --- temperature: match target entropy ---
            entropy = -(p * logp).sum(-1)
            alpha_loss = (params["log_alpha"]
                          * jax.lax.stop_gradient(entropy - target_entropy)).mean()
            total = critic_loss + actor_loss + alpha_loss
            return total, {
                "critic_loss": critic_loss, "actor_loss": actor_loss,
                "alpha": alpha, "entropy": entropy.mean(),
            }

        def update(params, target, opt_state, batch):
            (loss, metrics), grads = jax.value_and_grad(loss_fn, has_aux=True)(
                params, target, batch["obs"], batch["actions"], batch["rewards"],
                batch["next_obs"], batch["dones"],
            )
            updates, opt_state = self.optimizer.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            # polyak soft target update (reference: tau)
            target = jax.tree.map(
                lambda t, o: (1 - cfg.tau) * t + cfg.tau * o,
                target, {"q1": params["q1"], "q2": params["q2"]},
            )
            metrics["total_loss"] = loss
            return params, target, opt_state, metrics

        self._update = jax.jit(update)
        self._jnp = jnp

    def update(self, batch: dict) -> dict:
        jnp = self._jnp
        b = {
            "obs": jnp.asarray(batch["obs"], jnp.float32),
            "actions": jnp.asarray(batch["actions"], jnp.int32),
            "rewards": jnp.asarray(batch["rewards"], jnp.float32),
            "next_obs": jnp.asarray(batch["next_obs"], jnp.float32),
            "dones": jnp.asarray(batch["dones"], jnp.float32),
        }
        self.params, self.target, self.opt_state, metrics = self._update(
            self.params, self.target, self.opt_state, b
        )
        self.num_updates += 1
        return {k: float(v) for k, v in metrics.items()}


class SAC:
    """The Algorithm (reference: algorithms/algorithm.py train() loop)."""

    def __init__(self, cfg: SACConfig):
        import gymnasium as gym

        from ray_tpu.rllib.off_policy import probe_env_spaces

        self.cfg = cfg
        env_creator = (cfg.env if callable(cfg.env)
                       else (lambda name=cfg.env: gym.make(name)))
        obs_dim, num_actions = probe_env_spaces(env_creator)
        self.learner = SACLearner(cfg, obs_dim, num_actions)
        self.env_steps_total = 0

        import jax
        import jax.numpy as jnp

        pi_apply = jax.jit(lambda p, o: _mlp_apply(p, o, jnp))

        def policy_fn(params, obs, rng):
            # stochastic policy IS the exploration (no epsilon schedule)
            logits = np.asarray(pi_apply(params["pi"], obs[None]))[0]
            z = (logits - logits.max()).astype(np.float64)
            p = np.exp(z)
            p /= p.sum()  # float64: rng.choice validates the sum at ~1e-8
            action = int(rng.choice(len(p), p=p))
            return action, float(np.log(p[action] + 1e-9)), 0.0

        self.runners = EnvRunnerGroup(env_creator, policy_fn,
                                      num_runners=cfg.num_env_runners)
        self.runners.sync_weights(self.learner.params)
        BufferActor = ray_tpu.remote(num_cpus=0)(ReplayBuffer)
        self.buffer = BufferActor.remote(cfg.buffer_capacity, cfg.seed)

    def train(self) -> dict:
        from ray_tpu.rllib.off_policy import off_policy_train_iteration

        return off_policy_train_iteration(self)

    def stop(self) -> None:
        self.runners.stop()
        try:
            ray_tpu.kill(self.buffer)
        except Exception:
            pass
