"""APPO on JAX: asynchronous PPO (IMPALA architecture, PPO surrogate).

Parity: rllib/algorithms/appo/ — the actor-learner decoupling and stale weight
broadcasts of IMPALA, with the clipped PPO surrogate applied to V-trace
corrected advantages and multiple SGD epochs per collected batch. Where IMPALA
does one plain policy-gradient step per batch, APPO re-uses each batch for
several clipped updates (the clip keeps the re-use stable even off-policy).
"""

from __future__ import annotations

import dataclasses

import numpy as np

from ray_tpu.rllib.env_runner import Episode
from ray_tpu.rllib.impala import IMPALA, IMPALAConfig
from ray_tpu.rllib.ppo import _mlp_apply, _mlp_init


@dataclasses.dataclass
class APPOConfig(IMPALAConfig):
    """Fluent surface mirrors the reference's APPOConfig."""

    clip_param: float = 0.3
    num_epochs: int = 2
    minibatch_size: int = 256
    lr: float = 3e-3

    def build(self) -> "APPO":
        return APPO(self)


class APPOLearner:
    """Policy + value nets; jitted clipped-surrogate update on V-trace targets."""

    def __init__(self, cfg: APPOConfig, obs_dim: int, num_actions: int):
        import jax
        import jax.numpy as jnp
        import optax

        self.cfg = cfg
        key = jax.random.PRNGKey(cfg.seed)
        kp, kv = jax.random.split(key)
        self.params = {
            "pi": _mlp_init(kp, (obs_dim, *cfg.hidden, num_actions)),
            "vf": _mlp_init(kv, (obs_dim, *cfg.hidden, 1)),
        }
        self.optimizer = optax.chain(
            optax.clip_by_global_norm(5.0), optax.adam(cfg.lr)
        )
        self.opt_state = self.optimizer.init(self.params)

        def loss_fn(params, obs, actions, behavior_logp, vs_targets, advantages):
            logits = _mlp_apply(params["pi"], obs, jnp)
            logp_all = jax.nn.log_softmax(logits)
            logp = jnp.take_along_axis(logp_all, actions[:, None], axis=1)[:, 0]
            # clipped surrogate vs the BEHAVIOR policy (the stale actor
            # weights) — the asynchronous analog of PPO's old-policy ratio
            ratio = jnp.exp(logp - behavior_logp)
            clipped = jnp.clip(ratio, 1 - cfg.clip_param, 1 + cfg.clip_param)
            pg_loss = -jnp.minimum(ratio * advantages, clipped * advantages).mean()
            values = _mlp_apply(params["vf"], obs, jnp)[:, 0]
            vf_loss = ((values - vs_targets) ** 2).mean()
            entropy = -(jnp.exp(logp_all) * logp_all).sum(axis=1).mean()
            total = pg_loss + cfg.vf_coeff * vf_loss - cfg.entropy_coeff * entropy
            return total, {"pg_loss": pg_loss, "vf_loss": vf_loss,
                           "entropy": entropy}

        def update(params, opt_state, batch):
            (loss, metrics), grads = jax.value_and_grad(loss_fn, has_aux=True)(
                params, batch["obs"], batch["actions"], batch["behavior_logp"],
                batch["vs_targets"], batch["advantages"],
            )
            updates, opt_state = self.optimizer.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            metrics["total_loss"] = loss
            return params, opt_state, metrics

        self._update = jax.jit(update)
        self._jnp = jnp

    def update(self, batch: dict) -> dict:
        jnp = self._jnp
        b = {
            "obs": jnp.asarray(batch["obs"], jnp.float32),
            "actions": jnp.asarray(batch["actions"], jnp.int32),
            "behavior_logp": jnp.asarray(batch["behavior_logp"], jnp.float32),
            "vs_targets": jnp.asarray(batch["vs_targets"], jnp.float32),
            "advantages": jnp.asarray(batch["advantages"], jnp.float32),
        }
        self.params, self.opt_state, metrics = self._update(
            self.params, self.opt_state, b
        )
        return {k: float(v) for k, v in metrics.items()}


class APPO(IMPALA):
    """IMPALA's collection loop; PPO-clipped multi-epoch learner."""

    def _make_learner(self, obs_dim: int, num_actions: int):
        return APPOLearner(self.cfg, obs_dim, num_actions)

    def _episode_batch(self, episodes: list[Episode]) -> dict:
        batch = super()._episode_batch(episodes)
        # the surrogate ratio needs the behavior (actor-side) logprobs
        batch["behavior_logp"] = np.concatenate(
            [np.asarray(ep.logprobs, np.float32) for ep in episodes if len(ep)]
        )
        return batch

    def _update_from_batch(self, batch: dict) -> dict:
        """Multi-epoch clipped minibatch SGD over the collected batch —
        IMPALA's train() loop (sampling, broadcasts, metrics) is inherited."""
        from ray_tpu.rllib.ppo import minibatch_sgd

        cfg = self.cfg
        return minibatch_sgd(
            self.learner.update, batch, cfg.num_epochs, cfg.minibatch_size,
            rng=np.random.default_rng(cfg.seed + self.iterations),
        )
