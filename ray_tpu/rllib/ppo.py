"""PPO on JAX: RLModule + Learner + Algorithm.

Parity: rllib/algorithms/ppo/ (PPO with clipped surrogate + GAE),
rllib/core/rl_module/ (the policy module), rllib/core/learner/learner.py:112
(Learner: owns optimizer + update step) and learner_group.py:100. The learner
update is one jitted XLA program; multi-learner data parallelism is a mesh
axis (ray_tpu.parallel), not DDP wrappers.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable

import numpy as np

import ray_tpu
from ray_tpu.rllib.env_runner import Episode, EnvRunnerGroup


@dataclasses.dataclass
class PPOConfig:
    """Reference: AlgorithmConfig + PPOConfig surface (fluent API below)."""

    env: str | Callable = "CartPole-v1"
    num_env_runners: int = 2
    rollout_fragment_length: int = 256
    lr: float = 3e-4
    gamma: float = 0.99
    lambda_: float = 0.95
    clip_param: float = 0.2
    entropy_coeff: float = 0.01
    vf_coeff: float = 0.5
    num_epochs: int = 4
    minibatch_size: int = 128
    hidden: tuple = (64, 64)
    seed: int = 0
    # >1: data-parallel learner workers with synchronous gradient averaging
    # (reference: core/learner/learner_group.py:100 LearnerGroup)
    num_learners: int = 1
    # connector pipeline factories (reference: rllib/connectors) — each env
    # runner builds its own stateful instances
    env_to_module: Callable | None = None
    module_to_env: Callable | None = None

    # fluent configuration (reference: AlgorithmConfig.environment/.training/...)
    def environment(self, env) -> "PPOConfig":
        self.env = env
        return self

    def env_runners(self, num_env_runners: int, rollout_fragment_length: int | None = None) -> "PPOConfig":
        self.num_env_runners = num_env_runners
        if rollout_fragment_length:
            self.rollout_fragment_length = rollout_fragment_length
        return self

    def training(self, **kw) -> "PPOConfig":
        for k, v in kw.items():
            if not hasattr(self, k):
                raise ValueError(f"Unknown training option {k}")
            setattr(self, k, v)
        return self

    def build(self) -> "PPO":
        return PPO(self)


def _mlp_init(key, sizes):
    import jax

    params = []
    for i, (m, n) in enumerate(zip(sizes[:-1], sizes[1:])):
        key, sub = jax.random.split(key)
        params.append({
            "w": jax.random.normal(sub, (m, n)) * np.sqrt(2.0 / m),
            "b": np.zeros(n) * 0.0,
        })
    return params


def _mlp_apply(params, x, jnp):
    for i, layer in enumerate(params):
        x = x @ layer["w"] + layer["b"]
        if i < len(params) - 1:
            x = jnp.tanh(x)
    return x


class PPOLearner:
    """The update half (reference: core/learner/learner.py:112 — loss+optimizer)."""

    def __init__(self, cfg: PPOConfig, obs_dim: int, num_actions: int):
        import jax
        import jax.numpy as jnp
        import optax

        self.cfg = cfg
        key = jax.random.PRNGKey(cfg.seed)
        kp, kv = jax.random.split(key)
        self.params = {
            "pi": _mlp_init(kp, (obs_dim, *cfg.hidden, num_actions)),
            "vf": _mlp_init(kv, (obs_dim, *cfg.hidden, 1)),
        }
        self.optimizer = optax.adam(cfg.lr)
        self.opt_state = self.optimizer.init(self.params)

        def loss_fn(params, obs, actions, old_logprobs, advantages, returns):
            logits = _mlp_apply(params["pi"], obs, jnp)
            logp_all = jax.nn.log_softmax(logits)
            logp = jnp.take_along_axis(logp_all, actions[:, None], axis=1)[:, 0]
            ratio = jnp.exp(logp - old_logprobs)
            clipped = jnp.clip(ratio, 1 - cfg.clip_param, 1 + cfg.clip_param)
            pg_loss = -jnp.minimum(ratio * advantages, clipped * advantages).mean()
            values = _mlp_apply(params["vf"], obs, jnp)[:, 0]
            vf_loss = ((values - returns) ** 2).mean()
            entropy = -(jnp.exp(logp_all) * logp_all).sum(axis=1).mean()
            total = pg_loss + cfg.vf_coeff * vf_loss - cfg.entropy_coeff * entropy
            return total, {"pg_loss": pg_loss, "vf_loss": vf_loss, "entropy": entropy}

        def grads_of(params, batch):
            (loss, metrics), grads = jax.value_and_grad(loss_fn, has_aux=True)(
                params, batch["obs"], batch["actions"], batch["logprobs"],
                batch["advantages"], batch["returns"],
            )
            metrics["total_loss"] = loss
            return grads, metrics

        def update(params, opt_state, batch):
            grads, metrics = grads_of(params, batch)
            updates, opt_state = self.optimizer.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            return params, opt_state, metrics

        def apply(params, opt_state, grads):
            updates, opt_state = self.optimizer.update(grads, opt_state, params)
            return optax.apply_updates(params, updates), opt_state

        self._update = jax.jit(update)
        self._grads = jax.jit(grads_of)
        self._apply = jax.jit(apply)
        self._jnp = jnp

    def update(self, batch: dict) -> dict:
        jnp = self._jnp
        batch = {k: jnp.asarray(v) for k, v in batch.items()}
        self.params, self.opt_state, metrics = self._update(self.params, self.opt_state, batch)
        return {k: float(v) for k, v in metrics.items()}

    # --- distributed data-parallel protocol (LearnerGroup; reference:
    # core/learner/learner.py compute_gradients/apply_gradients split) ---
    def compute_grads(self, batch: dict) -> tuple:
        import jax

        jnp = self._jnp
        batch = {k: jnp.asarray(v) for k, v in batch.items()}
        grads, metrics = self._grads(self.params, batch)
        return (jax.tree.map(lambda g: np.asarray(g), grads),
                {k: float(v) for k, v in metrics.items()})

    def apply_grads(self, grads) -> None:
        self.params, self.opt_state = self._apply(self.params, self.opt_state,
                                                  grads)


def minibatch_sgd(update_fn, batch: dict, num_epochs: int, minibatch_size: int,
                  rng=None) -> dict:
    """Shared epoch/shuffle/slice loop (PPO, APPO, MultiAgentPPO).

    Full minibatches only: a variable-size tail would retrace the jitted
    update each iteration (n < minibatch_size falls back to one full batch)."""
    n = len(batch["obs"])
    if n == 0:
        return {}
    rng = rng or np.random.default_rng()
    mb = min(minibatch_size, n)
    idx = np.arange(n)
    metrics: dict = {}
    for _ in range(num_epochs):
        rng.shuffle(idx)
        for lo in range(0, n - mb + 1, mb):
            sel = idx[lo:lo + mb]
            metrics = update_fn({k: v[sel] for k, v in batch.items()})
    return metrics


def gae(cfg, ep: Episode) -> tuple[np.ndarray, np.ndarray]:
    """Generalized advantage estimation over one episode segment.

    Shared by PPO and MultiAgentPPO; cfg needs .gamma/.lambda_."""
    rewards = np.asarray(ep.rewards)
    values = np.asarray(ep.values + [ep.bootstrap_value])
    adv = np.zeros(len(rewards))
    last = 0.0
    for t in reversed(range(len(rewards))):
        nonterminal = 0.0 if ep.dones[t] else 1.0
        delta = rewards[t] + cfg.gamma * values[t + 1] * nonterminal - values[t]
        last = delta + cfg.gamma * cfg.lambda_ * nonterminal * last
        adv[t] = last
    returns = adv + values[:-1]
    return adv, returns


class PPO:
    """The Algorithm (reference: algorithms/algorithm.py train() loop)."""

    def __init__(self, cfg: PPOConfig):
        import gymnasium as gym
        import jax
        import jax.numpy as jnp

        self.cfg = cfg
        env_creator = cfg.env if callable(cfg.env) else (lambda: gym.make(cfg.env))
        probe = env_creator()
        obs_dim = int(np.prod(probe.observation_space.shape))
        num_actions = int(probe.action_space.n)
        if cfg.env_to_module is not None:
            # the policy consumes CONNECTED observations — probe their shape
            # through a throwaway pipeline instance
            sample, _ = probe.reset(seed=0)
            obs_dim = int(np.prod(np.asarray(cfg.env_to_module()(sample)).shape))
        probe.close()
        self.learner = PPOLearner(cfg, obs_dim, num_actions)
        self.learner_group = None
        if cfg.num_learners > 1:
            from ray_tpu.rllib.learner_group import LearnerGroup

            self.learner_group = LearnerGroup(
                lambda: PPOLearner(cfg, obs_dim, num_actions),
                num_learners=cfg.num_learners)

        # numpy-side policy for env runners (no jit: tiny MLP, avoids
        # shipping traced fns to actors); rng is the runner's own generator
        # so thread-actors don't share global RNG state
        from ray_tpu.rllib.np_policy import actor_critic_policy_fn as policy_fn

        self.runner_group = EnvRunnerGroup(env_creator, policy_fn, cfg.num_env_runners,
                                           env_to_module=cfg.env_to_module,
                                           module_to_env=cfg.module_to_env)
        self._iteration = 0

    def _gae(self, ep: Episode) -> tuple[np.ndarray, np.ndarray]:
        return gae(self.cfg, ep)

    def train(self) -> dict:
        """One iteration: sample -> GAE -> minibatch SGD epochs -> metrics."""
        from ray_tpu.rllib.np_policy import to_numpy_params

        cfg = self.cfg
        self.runner_group.sync_weights(to_numpy_params(self.learner.params))
        episodes = self.runner_group.sample(cfg.rollout_fragment_length)
        obs, actions, logprobs, advs, rets = [], [], [], [], []
        for ep in episodes:
            a, r = self._gae(ep)
            obs.extend(ep.obs)
            actions.extend(ep.actions)
            logprobs.extend(ep.logprobs)
            advs.extend(a)
            rets.extend(r)
        obs = np.asarray(obs, dtype=np.float32)
        actions = np.asarray(actions, dtype=np.int32)
        logprobs = np.asarray(logprobs, dtype=np.float32)
        advs = np.asarray(advs, dtype=np.float32)
        advs = (advs - advs.mean()) / (advs.std() + 1e-8)
        rets = np.asarray(rets, dtype=np.float32)

        n = len(obs)
        update_fn = (self.learner_group.update if self.learner_group is not None
                     else self.learner.update)
        metrics = minibatch_sgd(
            update_fn,
            {"obs": obs, "actions": actions, "logprobs": logprobs,
             "advantages": advs, "returns": rets},
            cfg.num_epochs, cfg.minibatch_size,
        )
        if self.learner_group is not None:
            # runner weight sync reads self.learner.params: adopt the group's
            # (identical-across-replicas) parameters
            self.learner.params = self.learner_group.get_params()
        self._iteration += 1
        finished = [ep for ep in episodes if ep.dones and ep.dones[-1]]
        mean_reward = float(np.mean([ep.total_reward() for ep in finished])) if finished else 0.0
        return {
            "training_iteration": self._iteration,
            "episode_reward_mean": mean_reward,
            "episodes_this_iter": len(finished),
            "timesteps_this_iter": n,
            **metrics,
        }

    def stop(self) -> None:
        self.runner_group.stop()
        if self.learner_group is not None:
            self.learner_group.shutdown()
