"""DQN on JAX: double Q-learning with a target network + replay actor.

Parity: rllib/algorithms/dqn/ (DQN with target network, double-Q targets,
epsilon-greedy exploration, replay buffer) over the shared Learner/EnvRunner
layering (core/learner/learner.py:112, env/single_agent_env_runner.py:68).
The learner update is one jitted XLA program; experience flows env runners →
replay buffer actor → learner minibatches.
"""

from __future__ import annotations

import dataclasses
from typing import Callable

import numpy as np

import ray_tpu
from ray_tpu.rllib.env_runner import Episode, EnvRunnerGroup
from ray_tpu.rllib.ppo import _mlp_apply, _mlp_init
from ray_tpu.rllib.off_policy import _episodes_to_transitions  # noqa: F401 (re-export)
from ray_tpu.rllib.replay_buffer import ReplayBuffer


@dataclasses.dataclass
class DQNConfig:
    """Reference: DQNConfig surface (fluent API below)."""

    env: str | Callable = "CartPole-v1"
    num_env_runners: int = 2
    rollout_fragment_length: int = 128
    lr: float = 1e-3
    gamma: float = 0.99
    buffer_capacity: int = 50_000
    learning_starts: int = 500  # min transitions before updates
    train_batch_size: int = 64
    updates_per_iter: int = 64
    target_update_freq: int = 500  # learner updates between target syncs
    epsilon_start: float = 1.0
    epsilon_end: float = 0.05
    epsilon_decay_steps: int = 4000  # env steps to anneal over
    double_q: bool = True
    hidden: tuple = (64, 64)
    seed: int = 0

    def environment(self, env) -> "DQNConfig":
        self.env = env
        return self

    def env_runners(self, num_env_runners: int,
                    rollout_fragment_length: int | None = None) -> "DQNConfig":
        self.num_env_runners = num_env_runners
        if rollout_fragment_length:
            self.rollout_fragment_length = rollout_fragment_length
        return self

    def training(self, **kw) -> "DQNConfig":
        fields = {f.name for f in dataclasses.fields(self)}
        for k, v in kw.items():
            if k not in fields:
                raise ValueError(f"Unknown training option {k}")
            setattr(self, k, v)
        return self

    def build(self) -> "DQN":
        return DQN(self)


class DQNLearner:
    """Q-network + target network + jitted double-DQN update
    (reference: dqn torch_learner loss; here one XLA program)."""

    def __init__(self, cfg: DQNConfig, obs_dim: int, num_actions: int):
        import jax
        import jax.numpy as jnp
        import optax

        self.cfg = cfg
        key = jax.random.PRNGKey(cfg.seed)
        self.params = _mlp_init(key, (obs_dim, *cfg.hidden, num_actions))
        self.target_params = self.params  # immutable pytrees: rebinding copies
        self.optimizer = optax.adam(cfg.lr)
        self.opt_state = self.optimizer.init(self.params)
        self.num_updates = 0

        def loss_fn(params, target_params, obs, actions, rewards, next_obs, dones):
            q = _mlp_apply(params, obs, jnp)
            q_taken = jnp.take_along_axis(q, actions[:, None], axis=1)[:, 0]
            q_next_target = _mlp_apply(target_params, next_obs, jnp)
            if cfg.double_q:
                # online net picks the argmax, target net evaluates it
                q_next_online = _mlp_apply(params, next_obs, jnp)
                best = jnp.argmax(q_next_online, axis=1)
                q_next = jnp.take_along_axis(q_next_target, best[:, None], axis=1)[:, 0]
            else:
                q_next = q_next_target.max(axis=1)
            target = rewards + cfg.gamma * (1.0 - dones) * q_next
            td = q_taken - jax.lax.stop_gradient(target)
            # Huber loss (reference: dqn default)
            loss = jnp.where(jnp.abs(td) < 1.0, 0.5 * td ** 2,
                             jnp.abs(td) - 0.5).mean()
            return loss, {"td_error_mean": jnp.abs(td).mean(), "q_mean": q_taken.mean()}

        def update(params, target_params, opt_state, batch):
            (loss, metrics), grads = jax.value_and_grad(loss_fn, has_aux=True)(
                params, target_params, batch["obs"], batch["actions"],
                batch["rewards"], batch["next_obs"], batch["dones"],
            )
            updates, opt_state = self.optimizer.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            metrics["loss"] = loss
            return params, opt_state, metrics

        self._update = jax.jit(update)
        self._jnp = jnp

    def update(self, batch: dict) -> dict:
        import jax

        jnp = self._jnp
        batch = {
            "obs": jnp.asarray(batch["obs"], jnp.float32),
            "actions": jnp.asarray(batch["actions"], jnp.int32),
            "rewards": jnp.asarray(batch["rewards"], jnp.float32),
            "next_obs": jnp.asarray(batch["next_obs"], jnp.float32),
            "dones": jnp.asarray(batch["dones"], jnp.float32),
        }
        self.params, self.opt_state, metrics = self._update(
            self.params, self.target_params, self.opt_state, batch
        )
        self.num_updates += 1
        if self.num_updates % self.cfg.target_update_freq == 0:
            self.target_params = self.params
        return {k: float(v) for k, v in metrics.items()}




class DQN:
    """The Algorithm (reference: algorithms/algorithm.py train() loop)."""

    def __init__(self, cfg: DQNConfig):
        import gymnasium as gym

        from ray_tpu.rllib.off_policy import probe_env_spaces

        self.cfg = cfg
        env_creator = (cfg.env if callable(cfg.env)
                       else (lambda name=cfg.env: gym.make(name)))
        obs_dim, num_actions = probe_env_spaces(env_creator)
        self.learner = DQNLearner(cfg, obs_dim, num_actions)
        self.env_steps_total = 0

        import jax
        import jax.numpy as jnp

        q_apply = jax.jit(lambda p, o: _mlp_apply(p, o, jnp))
        algo = self

        def policy_fn(params, obs, rng):
            # epsilon-greedy exploration with annealed epsilon; logprob/value
            # slots unused by DQN (EnvRunner protocol shared with PPO)
            eps = algo.epsilon()
            if rng.random() < eps:
                action = int(rng.integers(num_actions))
            else:
                action = int(np.argmax(np.asarray(q_apply(params, obs[None]))[0]))
            return action, 0.0, 0.0

        self.runners = EnvRunnerGroup(env_creator, policy_fn,
                                      num_runners=cfg.num_env_runners)
        self.runners.sync_weights(self.learner.params)
        # replay buffer as a runtime actor: collection and learning share it
        # through the control plane (reference: replay actor pattern)
        BufferActor = ray_tpu.remote(num_cpus=0)(ReplayBuffer)
        self.buffer = BufferActor.remote(cfg.buffer_capacity, cfg.seed)

    def epsilon(self) -> float:
        cfg = self.cfg
        frac = min(1.0, self.env_steps_total / max(1, cfg.epsilon_decay_steps))
        return cfg.epsilon_start + frac * (cfg.epsilon_end - cfg.epsilon_start)

    def train(self) -> dict:
        """One iteration: collect a fragment per runner, replay-update
        (shared loop in rllib/off_policy.py)."""
        from ray_tpu.rllib.off_policy import off_policy_train_iteration

        out = off_policy_train_iteration(self)
        out["epsilon"] = self.epsilon()
        return out

    def stop(self) -> None:
        self.runners.stop()
        try:
            ray_tpu.kill(self.buffer)
        except Exception:
            pass
