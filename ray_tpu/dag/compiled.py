"""Compiled actor graphs: static schedules over pre-negotiated channels.

Parity: the reference's Compiled Graphs (python/ray/dag/compiled_dag_node.py
+ experimental/channel/) — ``experimental_compile()`` on an actor-method DAG
topo-sorts it ONCE into a fixed per-actor operation schedule, negotiates one
channel per DAG edge up front, and installs a resident execution loop in
every participating actor (``dag/exec_loop.py``). After compilation,
``execute()`` is one input-channel write and ``ref.get()`` one output-channel
read: **zero control-plane round trips at steady state** — the Podracer
shape (arXiv 2104.06272) of long-lived actor fleets driven by data instead
of per-call RPC dispatch (the original Ray task model, arXiv 1712.05889).

Channel kinds per edge:
- same-node (everything reachable over the head host's shm): a
  ``core/shm_channel.py`` seqlock channel — one mapped segment per edge.
- driver edges of a REMOTE driver (``ray_tpu.init(address=...)``): a
  persistent wire channel over the client's control-plane connection, whose
  read side answers with raw BLOB frames (the PR-5 zero-copy sendmsg path).
  Peers that negotiated a pre-v4 wire cannot install graphs; compilation
  falls back to legacy RPC dispatch with a warning instead of crashing.

Lifecycle: bind -> experimental_compile (analyze + dag_install: channel
creation + loop install) -> execute/get over channels -> teardown (channels
closed + destroyed, loops exit, actors keep serving normal RPC calls).
An actor dying mid-loop closes its channels; the close cascades edge-by-edge
through every loop and the driver, so in-flight ``execute()``s raise instead
of hanging.
"""

from __future__ import annotations

import logging
import os
import threading
import time

from ray_tpu.core.shm_channel import ChannelClosed, default_timeout
from ray_tpu.dag.exec_loop import CHAN, CONST, SLOT, ActorPlan, OpStep

logger = logging.getLogger("ray_tpu")

CHANNEL_BYTES_ENV = "RAY_TPU_DAG_CHANNEL_BYTES"


def _channel_capacity() -> int:
    try:
        return int(os.environ.get(CHANNEL_BYTES_ENV, str(1 << 20)))
    except ValueError:
        return 1 << 20


class GraphSpec:
    """The install payload shipped to the runtime (picklable)."""

    def __init__(self, graph_id: bytes, plans: list, all_chans: list,
                 input_chans: list, output_chan: int, capacity: int):
        self.graph_id = graph_id
        self.plans = plans
        self.all_chans = all_chans
        self.input_chans = input_chans
        self.output_chan = output_chan
        self.capacity = capacity


class UnsupportedGraph(Exception):
    """The DAG shape cannot compile to a static actor graph (function nodes,
    collectives, no InputNode ancestry, ...) — callers fall back to the
    legacy driver-thread CompiledDAG."""


def analyze(output_node) -> GraphSpec:
    """Topo-sort an actor-method DAG into per-actor schedules + edge list.

    Raises UnsupportedGraph unless every non-input node is a
    ``ClassMethodNode`` and every method node transitively consumes the
    InputNode (a source with no input ancestry would run unthrottled)."""
    from ray_tpu.dag import ClassMethodNode, DAGNode, InputNode

    order: list = []
    seen: set = set()

    def visit(n):
        if id(n) in seen:
            return
        seen.add(id(n))
        for d in n._deps():
            visit(d)
        order.append(n)

    visit(output_node)

    methods = [n for n in order if isinstance(n, ClassMethodNode)]
    if not methods or not isinstance(output_node, ClassMethodNode):
        raise UnsupportedGraph("not an actor-method DAG")
    for n in order:
        if not isinstance(n, (ClassMethodNode, InputNode)):
            raise UnsupportedGraph(
                f"unsupported node type {type(n).__name__} in actor graph")

    # input ancestry: every method node must be throttled by the driver input
    reaches_input: set = set()
    for n in order:  # order is topological: deps appear first
        if isinstance(n, InputNode):
            reaches_input.add(id(n))
        elif any(id(d) in reaches_input for d in n._deps()):
            reaches_input.add(id(n))
    for n in methods:
        if id(n) not in reaches_input:
            raise UnsupportedGraph(
                f"method node {n._method_name!r} does not depend on "
                "InputNode (unthrottled source)")

    # resident loops invoke methods synchronously — async/generator methods
    # would yield un-awaited coroutines/generators into the channels; those
    # DAGs keep the legacy RPC-dispatch driver, which handles them
    import inspect

    for n in methods:
        fn = getattr(n._handle._cls, n._method_name, None)
        if fn is not None and (
                inspect.iscoroutinefunction(fn)
                or inspect.isgeneratorfunction(fn)
                or inspect.isasyncgenfunction(fn)):
            raise UnsupportedGraph(
                f"method {n._method_name!r} is async/generator — compiled "
                "loops call methods synchronously")

    node_idx = {id(n): i for i, n in enumerate(methods)}
    actor_of = {id(n): n._handle._actor_id.binary() for n in methods}

    next_chan = [0]

    def new_chan() -> int:
        next_chan[0] += 1
        return next_chan[0] - 1

    # per-(producer, consumer) channels; per-consumer input channels
    edge_chan: dict = {}
    input_chan: dict = {}
    out_chans: dict = {i: [] for i in range(len(methods))}
    keep_slot: set = set()
    input_ids: list = []

    def template(consumer, arg):
        if not isinstance(arg, DAGNode):
            return (CONST, arg)
        if isinstance(arg, InputNode):
            key = id(consumer)
            if key not in input_chan:
                input_chan[key] = new_chan()
                input_ids.append(input_chan[key])
            return (CHAN, input_chan[key])
        pidx = node_idx[id(arg)]
        if actor_of[id(arg)] == actor_of[id(consumer)]:
            keep_slot.add(pidx)
            return (SLOT, pidx)
        key = (id(arg), id(consumer))
        if key not in edge_chan:
            edge_chan[key] = new_chan()
            out_chans[pidx].append(edge_chan[key])
        return (CHAN, edge_chan[key])

    steps: dict = {}
    for n in methods:
        i = node_idx[id(n)]
        args = tuple(template(n, a) for a in n._bound_args)
        kwargs = {k: template(n, v) for k, v in n._bound_kwargs.items()}
        steps[i] = (n, args, kwargs)

    output_chan = new_chan()
    out_chans[node_idx[id(output_node)]].append(output_chan)

    # group steps per actor, preserving global topological order
    plans: dict = {}
    for n in methods:
        i = node_idx[id(n)]
        abin = actor_of[id(n)]
        _, args, kwargs = steps[i]
        read = [t[1] for t in args if t[0] == CHAN]
        read += [t[1] for t in kwargs.values() if t[0] == CHAN]
        op = OpStep(node_idx=i, method=n._method_name, args=args,
                    kwargs=kwargs, out_chans=tuple(out_chans[i]),
                    keep_slot=(i in keep_slot))
        plan = plans.setdefault(abin, {"steps": [], "read": []})
        plan["steps"].append(op)
        plan["read"].extend(read)

    actor_plans = [
        ActorPlan(actor_bin=abin, steps=tuple(p["steps"]),
                  read_chans=tuple(dict.fromkeys(p["read"])))
        for abin, p in plans.items()
    ]
    return GraphSpec(
        graph_id=os.urandom(8),
        plans=actor_plans,
        all_chans=list(range(next_chan[0])),
        input_chans=input_ids,
        output_chan=output_chan,
        capacity=_channel_capacity(),
    )


class _WireShim:
    """Adapter giving wire driver-channels the read_view/write surface the
    driver uses for shm channels."""

    def __init__(self, inner):
        self._inner = inner

    def write(self, blob, timeout=None):
        self._inner.write(bytes(blob), timeout)

    def read_view(self, last, timeout=None):
        # the caller's poll timeout is NOT forwarded: a wire read abandoned
        # mid-flight would lose the frame the server already consumed — the
        # wire channel owns its own (bounded) long-poll window
        ver, payload = self._inner.read(last)
        return ver, memoryview(payload)

    def close_channel(self):
        pass

    def detach(self):
        close = getattr(self._inner, "close", None)
        if close is not None:
            close()


class ResultBufferDriver:
    """Shared driver half of a compiled graph handle: a background drain
    buffers (seq, status, payload) results as they land so the producing
    end never stalls on un-fetched outputs, and ``get()`` waits on the
    buffer. ``CompiledActorDAG`` and ``ShmCompiledDAG`` (dag/__init__.py)
    both ride this — one implementation of the seq/buffer/death protocol."""

    _desc = "compiled DAG"

    def _init_result_buffer(self) -> None:
        self._seq = 0
        self._buffer: dict = {}
        self._cond = threading.Condition()  # guards _buffer/_dead
        self._exec_lock = threading.Lock()
        self._running = True
        self._dead: str | None = None
        self._done_cbs: dict = {}  # seq -> [fn]; fired on land OR death

    def notify_on(self, seq: int, fn) -> None:
        """Call ``fn()`` (no args, exception-swallowed) once execution
        ``seq`` has a buffered result or the graph dies — the serve
        router's in-flight accounting hook: completion tracking without a
        watcher thread polling refs."""
        with self._cond:
            if seq not in self._buffer and self._dead is None:
                self._done_cbs.setdefault(seq, []).append(fn)
                return
        self._run_cb(fn)

    @staticmethod
    def _run_cb(fn) -> None:
        try:
            fn()
        except Exception:
            logger.exception("compiled-DAG completion callback failed")

    def _publish_result(self, seq: int, status: str, payload) -> None:
        with self._cond:
            self._buffer[seq] = (status, payload)
            cbs = self._done_cbs.pop(seq, ())
            self._cond.notify_all()
        for fn in cbs:
            self._run_cb(fn)

    def _mark_dead(self, message: str, *, only_if_running: bool = False) -> None:
        with self._cond:
            if self._dead is None and not (only_if_running
                                           and not self._running):
                self._dead = message
            cbs = [fn for fns in self._done_cbs.values() for fn in fns]
            self._done_cbs.clear()
            self._cond.notify_all()
        for fn in cbs:
            self._run_cb(fn)

    def get(self, seq: int, timeout: float | None = None):
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._cond:
            while seq not in self._buffer:
                if self._dead:
                    raise RuntimeError(self._dead)
                remaining = (None if deadline is None
                             else max(0.0, deadline - time.monotonic()))
                if remaining == 0.0 or not self._cond.wait(timeout=remaining):
                    if seq in self._buffer or self._dead:
                        continue
                    raise TimeoutError(
                        f"{self._desc} execution {seq} did not finish in "
                        f"{timeout}s")
            status, payload = self._buffer.pop(seq)
        if status == "err":
            raise payload
        return payload


class CompiledActorDAG(ResultBufferDriver):
    """Driver handle for an installed compiled actor graph."""

    _desc = "compiled actor DAG"

    def __init__(self, spec: GraphSpec):
        import cloudpickle

        rt = _get_runtime()
        self._spec = spec
        self._timeout = default_timeout()
        res = rt.dag_install(cloudpickle.dumps(self._spec))
        self.graph_id = res["graph"]
        self._rt = rt
        try:
            if res.get("wire"):
                self._in_chs = [
                    _WireShim(rt.dag_wire_in(self.graph_id, cid))
                    for cid in self._spec.input_chans
                ]
                self._out_ch = _WireShim(
                    rt.dag_wire_out(self.graph_id, self._spec.output_chan))
            else:
                # Local driver shares the runtime's channel objects (one
                # writer/reader per end still holds: the driver is the only
                # writer of input edges and the only reader of the output).
                # Edges whose ring lives on a REMOTE node (first/last stage
                # actors placed off-head) come back as fabric descriptors
                # — bridged over pre-opened data-plane peers, so execute()
                # stays one frame write and get() one frame read with zero
                # control-plane traffic (dag/fabric.py).
                from ray_tpu.dag import fabric

                live = rt.dag_channels(self.graph_id)
                edges = res.get("edges") or {}
                self._attached = []  # by-name rings we must detach

                def _driver_chan(cid):
                    if cid in edges:
                        ch = fabric.build_edge(edges[cid],
                                               self.graph_id, cid)
                        if edges[cid][0] == "shm":
                            self._attached.append(ch)
                        return ch
                    return live[cid]

                self._in_chs = [_driver_chan(cid)
                                for cid in self._spec.input_chans]
                self._out_ch = _driver_chan(self._spec.output_chan)
        except BaseException:
            rt.dag_teardown(self.graph_id)
            raise
        self._init_result_buffer()
        self._drain = threading.Thread(
            target=self._drain_loop, daemon=True,
            name=f"ray_tpu-dag-drain-{self.graph_id.hex()[:8]}")
        self._drain.start()
        register = getattr(rt, "dag_register_abort_cb", None)
        if register is not None and not res.get("wire"):
            # head-side abort hook (actor/node death): wake THIS driver
            # now — channels this process attached to a DEAD node's rings
            # cannot be closed by anyone else (the node's segments were
            # already unlinked with its resource tracker), so without the
            # hook a parked execute()/get() would sit out its timeout.
            register(self.graph_id, self._on_graph_abort)

    def _on_graph_abort(self, reason: str) -> None:
        self._mark_dead(
            "compiled DAG aborted (actor died, node died, or graph torn "
            f"down): {reason}", only_if_running=True)
        if not self._running:
            return  # teardown already owns channel shutdown
        for ch in list(self._in_chs) + [self._out_ch]:
            try:
                ch.close_channel()
            except Exception:
                logger.debug("abort-hook channel close failed",
                             exc_info=True)

    # -------------------------------------------------------------- driver
    def _drain_loop(self) -> None:
        """Ack every result frame as it lands so the terminal actor never
        stalls on un-fetched outputs; flag graph death promptly."""
        import cloudpickle

        last = 0
        while self._running:
            try:
                last, view = self._out_ch.read_view(last, timeout=0.5)
                # loads stays INSIDE the try: an undeserializable frame
                # (e.g. a worker-only exception class) must flag the graph
                # dead, not silently kill this thread and hang every get()
                seq, status, payload = cloudpickle.loads(view)
            except TimeoutError:
                continue
            except (ChannelClosed, ConnectionError) as e:
                self._mark_dead(
                    "compiled DAG channels closed (actor died or graph "
                    f"torn down): {e}", only_if_running=True)
                self._release_parked_writers()
                return
            except BaseException as e:  # noqa: BLE001 — never die silently
                self._mark_dead(f"compiled DAG drain failed: {e!r}")
                self._release_parked_writers()
                return
            self._publish_result(seq, status, payload)

    def _release_parked_writers(self) -> None:
        """The graph is dead: close the input channels so an execute()
        parked in a ring write — e.g. toward a ring whose consumer's NODE
        just died and can no longer drain it — wakes with ChannelClosed
        NOW instead of sitting out the full channel timeout."""
        if self._running:
            for ch in self._in_chs:
                try:
                    ch.close_channel()
                except Exception:
                    logger.debug("input-channel close on death failed",
                                 exc_info=True)

    def execute(self, *input_args) -> "CompiledDAGRef":
        import cloudpickle

        from ray_tpu.dag import CompiledDAGRef

        if not self._running:
            raise RuntimeError(
                "CompiledActorDAG was torn down; re-compile to execute again")
        with self._cond:
            if self._dead:
                raise RuntimeError(self._dead)
        value = input_args[0] if len(input_args) == 1 else input_args
        with self._exec_lock:
            seq = self._seq
            blob = cloudpickle.dumps((seq, "ok", value))
            if len(self._in_chs) > 1:
                # fan-out pre-admission: wait until EVERY input ring can
                # take the whole frame before publishing anything — a
                # healthy-but-slow branch then surfaces as a clean
                # retryable TimeoutError instead of a partially-published
                # frame (which would have to poison the graph). The driver
                # is each ring's sole writer, so admission can't be raced
                # away. (Frames bigger than a whole ring still need reader
                # progress mid-write; the channel's mid-frame poison stays
                # the backstop for that case.)
                for ch in self._in_chs:
                    wait = getattr(ch, "wait_writable", None)
                    if wait is not None:
                        try:
                            wait(timeout=self._timeout,
                                 slots=ch.slots_for(len(blob)))
                        except ChannelClosed as e:
                            raise RuntimeError(
                                "compiled DAG input channel closed (actor "
                                f"died or graph torn down): {e}") from e
            wrote = 0
            try:
                for ch in self._in_chs:
                    # blocks only while that edge's ring is full (bounded
                    # in-flight = channel slots, the pipeline backpressure)
                    ch.write(blob, timeout=self._timeout)
                    wrote += 1
            except BaseException as e:
                if wrote:
                    # PARTIAL FAN-OUT: earlier input channels hold a frame
                    # for a seq that will never be accounted for — from now
                    # on the fan-in would pair payloads from DIFFERENT
                    # executions. The graph is unrecoverable: poison it so
                    # every end fails loudly instead of computing garbage.
                    self._poison(
                        f"input fan-out failed after {wrote}/"
                        f"{len(self._in_chs)} channels (seq {seq}): {e!r}")
                if isinstance(e, (ChannelClosed, ConnectionError)):
                    raise RuntimeError(
                        "compiled DAG input channel closed (actor died or "
                        f"graph torn down): {e}") from e
                raise
            self._seq += 1  # only after every input frame really landed
        return CompiledDAGRef(self, seq)

    def _poison(self, message: str) -> None:
        """Mark the graph dead and cascade channel closure (best effort)."""
        self._mark_dead(message)
        for ch in list(self._in_chs) + [self._out_ch]:
            try:
                ch.close_channel()
            except Exception:
                pass
        try:
            # closes the head-side channels too (wire drivers can't reach
            # them directly); idempotent with a later user teardown()
            self._rt.dag_teardown(self.graph_id)
        except Exception:
            pass

    def teardown(self) -> None:
        if not self._running:
            return
        self._running = False
        # flag the channels closed before destroying them so the drain (and
        # any racing execute) exits on ChannelClosed, not on a torn mapping
        for ch in self._in_chs:
            ch.close_channel()
        self._out_ch.close_channel()
        wire = isinstance(self._out_ch, _WireShim)
        if not wire:
            # shm drain wakes on the closed flag — park it BEFORE the
            # runtime unmaps the segments underneath it
            self._drain.join(timeout=5)
        try:
            self._rt.dag_teardown(self.graph_id)
        except Exception:
            pass
        if wire:
            # the wire drain unblocks when the head reaps the graph
            self._drain.join(timeout=5)
        with self._cond:
            if self._dead is None:
                self._dead = "CompiledActorDAG torn down"
            self._cond.notify_all()
        # shm objects are the runtime's (dag_teardown destroyed them); only
        # wire shims and rings this driver attached BY NAME (cross-node
        # same-machine edges) have driver-side state to release
        for ch in list(self._in_chs) + [self._out_ch]:
            if isinstance(ch, _WireShim):
                ch.detach()
        for ch in getattr(self, "_attached", ()):
            ch.detach()


def _get_runtime():
    from ray_tpu.core.runtime import get_runtime

    return get_runtime()


def try_compile_actor_dag(output_node):
    """Compile ``output_node`` into a CompiledActorDAG, or return None when
    the graph/peer cannot support one (caller falls back to the legacy
    driver-thread CompiledDAG — plain RPC dispatch)."""
    from ray_tpu.core.rpc.schema import WireVersionError

    try:
        spec = analyze(output_node)
    except UnsupportedGraph as e:
        logger.debug("experimental_compile: %s; using RPC-dispatch driver", e)
        return None
    try:
        return CompiledActorDAG(spec)
    except (WireVersionError, NotImplementedError) as e:
        from ray_tpu.util import flight_recorder

        flight_recorder.record(
            "dag", "compile_fallback",
            reason=f"{type(e).__name__}: {e}"[:200])
        logger.warning(
            "experimental_compile: compiled-graph install unavailable (%s); "
            "falling back to per-call RPC dispatch", e)
        return None
