"""Cross-node compiled-graph edge fabric (wire v9, ISSUE 15).

A compiled actor graph's edges are shm ring channels. When producer and
consumer live on DIFFERENT nodes, the ring is created on the node that
hosts the edge's PRODUCER actor (driver-input edges: the consumer's node,
so the resident loop still reads local shm) and the far end bridges over
the wire:

- the channel HOST (a node agent, or the head runtime) registers the ring
  with a :class:`DagChannelHost` served on its object-plane endpoint — the
  persistent v4 ``dag_ch_write``/``dag_ch_read`` ops, reads answered with
  raw BLOB frames out of the ring's scratch (the PR-5 ``sendmsg`` path);
- the far end holds a :class:`WireEdgeReader`/:class:`WireEdgeWriter`
  whose peer connection is PRE-OPENED at graph install and marked
  ``count_ops=False``: its frames are accounted as ``fabric:*``, never
  ``rpc:*`` — the steady-state step stays ZERO control-plane requests,
  counter-asserted, even with stages on different machines.

Closure cascades cross the wire too: a loop's ``finally`` closes every
channel its plan touches; for a wire edge that is a ``dag_ch_close``
notify to the host, and a host-side closure (teardown, worker death,
agent death) surfaces at the far end as ``ChannelClosed`` — or as a
``PeerDisconnected`` mapped to ``ChannelClosed`` when the host process
itself is gone. Nothing ever hangs.
"""

from __future__ import annotations

import logging
import threading
import time
from concurrent.futures import TimeoutError as _FutTimeoutError

from ray_tpu.core.shm_channel import ChannelClosed, default_timeout

logger = logging.getLogger("ray_tpu")

# Server-side long-poll window per dag_ch_read; the client's per-call wire
# budget leaves slack for the reply to cross.
READ_POLL_S = 30.0
WIRE_BUDGET_S = READ_POLL_S + 15.0

# Test/benchmark knob: treat every cross-NODE edge as cross-HOST (wire
# bridged) even when the nodes share a machine — exercises the BLOB path
# on a single box.
FORCE_WIRE_ENV = "RAY_TPU_DAG_FABRIC_FORCE_WIRE"


def force_wire() -> bool:
    import os

    return os.environ.get(FORCE_WIRE_ENV) == "1"


def machine_uid() -> str:
    """Stable identity of THIS machine (not node/agent): two node agents on
    one host share /dev/shm, so a cross-NODE edge between them can attach
    the ring by name instead of bridging over TCP — the same-host fast
    path cross-host placement falls back from."""
    try:
        with open("/proc/sys/kernel/random/boot_id") as f:
            return f.read().strip()
    except OSError:
        import socket

        return socket.gethostname()


class DagChannelHost:
    """Ring channels this process hosts for compiled graphs, keyed
    (graph_id, chan_id), served over the v4 ``dag_ch_*`` ops on a plane /
    fabric RpcServer. One lock per channel: a budget-expired client retry
    must never run concurrently with the still-parked previous handler on
    a strictly single-reader channel (the PR-7 bridge contract)."""

    def __init__(self):
        self._chans: dict = {}   # (graph, chan) -> ShmChannel
        self._locks: dict = {}
        self._mu = threading.Lock()

    def handlers(self) -> dict:
        return {
            "dag_ch_write": self._h_write,
            "dag_ch_read": self._h_read,
            "dag_ch_close": self._h_close,
        }

    def register(self, graph: bytes, chan_id: int, channel) -> None:
        with self._mu:
            self._chans[(graph, chan_id)] = channel
            self._locks[(graph, chan_id)] = threading.Lock()

    def channels_of(self, graph: bytes) -> dict:
        with self._mu:
            return {c: ch for (g, c), ch in self._chans.items() if g == graph}

    def unregister_graph(self, graph: bytes) -> list:
        """Drop every channel of ``graph``; returns them for the owner to
        close/destroy. Subsequent fabric reads/writes for the graph raise
        ChannelClosed (the cross-node closure cascade)."""
        with self._mu:
            keys = [k for k in self._chans if k[0] == graph]
            out = [self._chans.pop(k) for k in keys]
            dropped_locks = [self._locks.pop(k, None) for k in keys]
        del dropped_locks  # dies outside _mu (graftlint ref-drop-under-lock)
        return out

    def _get(self, msg: dict):
        key = (msg["graph"], msg["chan"])
        with self._mu:
            ch = self._chans.get(key)
            lock = self._locks.get(key)
        if ch is None:
            raise ChannelClosed(
                "compiled-graph channel is gone (graph torn down?)")
        return ch, lock

    # ------------------------------------------------------------ handlers
    def _h_write(self, peer, msg):
        ch, lock = self._get(msg)
        with lock:
            ch.write(msg["frame"], timeout=default_timeout())
        return True

    def _h_read(self, peer, msg):
        from ray_tpu.core.rpc import RawReply

        ch, lock = self._get(msg)
        # bounded long-poll: the far end loops on TimeoutError, so an idle
        # graph never parks a request past the poll window. Payload frozen
        # UNDER the lock (the channel scratch is reused by the next read);
        # the 8-byte version prefix rides the sendmsg iovec.
        with lock:
            version, view = ch.read_view(msg["last"], timeout=READ_POLL_S)
            return RawReply(bytes(view), prefix=version.to_bytes(8, "big"))

    def _h_close(self, peer, msg):
        try:
            ch, _ = self._get(msg)
        except ChannelClosed:
            return True  # already gone: close is idempotent
        ch.close_channel()
        return True


# ------------------------------------------------------- fabric peer cache
# One data-plane connection per (process, host endpoint), shared by every
# edge bridging to that host — pre-opened at install so the first step pays
# no connect latency and the steady state is pure frame traffic.
_PEERS: dict = {}
_PEERS_LOCK = threading.Lock()


def fabric_peer(addr: str):
    """Cached count_ops=False connection to a channel host endpoint."""
    from ray_tpu.core import rpc as wire

    with _PEERS_LOCK:
        p = _PEERS.get(addr)
        if p is not None and not p.closed:
            return p
    host, _, port = addr.rpartition(":")
    p = wire.connect(host, int(port), name=f"dag-fabric-{addr}",
                     timeout=10, count_ops=False)
    with _PEERS_LOCK:
        old = _PEERS.get(addr)
        if old is not None and not old.closed:
            p.close()
            return old
        _PEERS[addr] = p
    return p


def _drop_peer(addr: str, peer) -> None:
    try:
        peer.close()
    except Exception as e:
        logger.debug("fabric peer %s close failed: %r", addr, e)
    with _PEERS_LOCK:
        dropped = (_PEERS.pop(addr)
                   if _PEERS.get(addr) is peer else None)
    del dropped  # dies outside the lock (graftlint ref-drop-under-lock)


def close_all_peers() -> None:
    """Session teardown: drop every cached fabric connection."""
    with _PEERS_LOCK:
        peers = [_PEERS.pop(a) for a in list(_PEERS)]
    for p in peers:
        try:
            p.close()
        except Exception as e:
            logger.debug("fabric peer close at shutdown failed: %r", e)


class _WireEdge:
    """Shared half: resolve the (possibly reconnected) host peer."""

    def __init__(self, addr: str, graph: bytes, chan_id: int):
        self._addr = addr
        self._graph = graph
        self._chan = chan_id
        self._closed = False
        fabric_peer(addr)  # pre-open at construction (graph install time)

    def _peer(self):
        return fabric_peer(self._addr)

    def close_channel(self) -> None:
        """Cascade closure to the hosted ring (best effort): the host marks
        the ring closed, waking ITS local reader/writer with ChannelClosed."""
        self._closed = True
        try:
            self._peer().notify("dag_ch_close", graph=self._graph,
                                chan=self._chan)
        except Exception:
            pass  # host gone: its rings died with it

    def detach(self) -> None:
        pass  # the peer is cache-shared by every edge to this host

    def occupancy(self) -> int:
        return 0  # ring depth lives host-side; not sampled over the wire


class WireEdgeReader(_WireEdge):
    """Consumer end of a cross-node edge: long-poll ``dag_ch_read`` against
    the producer-side host; replies are raw BLOB frames
    ``[u64 version | payload]``. Retries are lossless: the host ring's
    scratch cache redelivers the last consumed frame on a stale ``last``,
    so a budget-expired poll never loses a result.

    PREFETCH: the moment frame N lands, the poll for frame N+1 is issued —
    the host parks waiting for the producer WHILE this end deserializes and
    executes, so at steady state a hop costs max(exec, producer), not
    exec + RTT + producer (pipelined long-polls; the single-reader
    protocol makes the one-deep window trivially ordered)."""

    def __init__(self, addr: str, graph: bytes, chan_id: int):
        super().__init__(addr, graph, chan_id)
        self._pending = None  # (expect_last, peer, mid, fut)

    def _poll(self, last: int):
        """The in-flight long-poll for ``last``, reusing a matching
        prefetch; returns (peer, mid, fut)."""
        pend, self._pending = self._pending, None
        peer = self._peer()
        if pend is not None:
            if pend[0] == last and pend[1] is peer:
                return pend[1], pend[2], pend[3]
            pend[1].finish_call(pend[2])  # stale (reconnect/odd last)
        mid, fut = peer.call_async("dag_ch_read", graph=self._graph,
                                   chan=self._chan, last=last)
        return peer, mid, fut

    def read_view(self, last: int, timeout: float | None = None):
        deadline = (None if timeout is None
                    else time.monotonic() + max(timeout, WIRE_BUDGET_S))
        while True:
            if self._closed:
                raise ChannelClosed(f"wire edge chan {self._chan} closed")
            try:
                peer, mid, fut = self._poll(last)
            except ConnectionError as e:
                _drop_peer(self._addr, self._peer())
                raise ChannelClosed(
                    f"edge host {self._addr} unreachable: {e}") from e
            try:
                raw = fut.result(timeout=WIRE_BUDGET_S)
            except (_FutTimeoutError, TimeoutError):
                # idle poll window (server TimeoutError) or local wire
                # budget — both safely retryable thanks to redelivery
                peer.finish_call(mid)
                if deadline is not None and time.monotonic() > deadline:
                    raise TimeoutError(
                        f"wire edge chan {self._chan} idle past "
                        f"{timeout}s") from None
                continue
            except ChannelClosed:
                peer.finish_call(mid)
                raise
            except ConnectionError as e:  # PeerDisconnected: host died
                peer.finish_call(mid)
                _drop_peer(self._addr, peer)
                raise ChannelClosed(
                    f"edge host {self._addr} unreachable: {e}") from e
            peer.finish_call(mid)
            version = int.from_bytes(raw[:8], "big")
            try:  # prefetch the NEXT frame's poll (see class doc)
                nmid, nfut = peer.call_async(
                    "dag_ch_read", graph=self._graph, chan=self._chan,
                    last=version)
                self._pending = (version, peer, nmid, nfut)
            except Exception:
                self._pending = None  # next read_view re-issues plainly
            return version, memoryview(raw)[8:]

    def read(self, last: int, timeout: float | None = None):
        ver, view = self.read_view(last, timeout)
        return ver, bytes(view)

    def close_channel(self) -> None:
        pend, self._pending = self._pending, None
        if pend is not None:
            pend[1].finish_call(pend[2])
        super().close_channel()


class WireEdgeWriter(_WireEdge):
    """Producer end of a cross-node edge (driver-input edges into remote
    actors): each ``write`` is one ``dag_ch_write`` whose reply lands after
    the host ring admitted the frame — the ring's bounded-queue
    backpressure propagates over the wire. A server-side admission timeout
    (ring full past its window) leaves the frame UNWRITTEN, so retrying is
    safe; a timeout=None caller (resident loops) retries forever."""

    def write(self, blob, timeout: float | None = None) -> None:
        frame = bytes(blob)
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            if self._closed:
                raise ChannelClosed(f"wire edge chan {self._chan} closed")
            try:
                self._peer().call(
                    "dag_ch_write", graph=self._graph, chan=self._chan,
                    frame=frame, timeout=default_timeout() + 15.0)
                return
            except (_FutTimeoutError, TimeoutError):
                if deadline is not None and time.monotonic() > deadline:
                    raise TimeoutError(
                        f"wire edge chan {self._chan} not admitted in "
                        f"{timeout}s (reader not consuming)") from None
                continue
            except ChannelClosed:
                raise
            except ConnectionError as e:
                _drop_peer(self._addr, self._peer())
                raise ChannelClosed(
                    f"edge host {self._addr} unreachable: {e}") from e


def build_edge(desc, graph: bytes, chan_id: int):
    """Construct the far end for a remote channel descriptor:
    ``["shm", ring_name]`` — the hosting node shares this machine, attach
    the ring directly (pure shm, no wire); ``[addr, kind]`` — a genuinely
    cross-host edge, bridge over the fabric peer (kind "read": this
    process consumes the hosted ring; "write": it publishes into it)."""
    if desc[0] == "shm":
        from ray_tpu.core.shm_channel import ShmChannel

        return ShmChannel(name=desc[1], create=False)
    addr, kind = desc[0], desc[1]
    cls = WireEdgeReader if kind == "read" else WireEdgeWriter
    return cls(addr, graph, chan_id)
