"""Compiled-graph worker process: executes a function pipeline fed by
mutable shm channels (reference: the compiled-graph execution loop living on
persistent workers with preallocated channels, compiled_dag_node.py — the
point is NO per-call RPC/scheduling at steady state).

Protocol: the driver sends one INIT frame (cloudpickled output node) on the
input channel, then per execution a (seq, args) frame; this process replies
(seq, "ok"/"err", payload) on the output channel. FunctionNodes run their raw
underlying callables inline — the whole pipeline is local to this process,
the channels are the only boundary.
"""

from __future__ import annotations

import sys


def _exec_inline(node, cache, input_args):
    """DAGNode._exec with FunctionNodes unwrapped to their raw callables."""
    from ray_tpu.dag import DAGNode, FunctionNode, InputNode

    if id(node) in cache:
        return cache[id(node)]
    args = [_exec_inline(a, cache, input_args) if isinstance(a, DAGNode) else a
            for a in node._bound_args]
    kwargs = {k: (_exec_inline(v, cache, input_args) if isinstance(v, DAGNode) else v)
              for k, v in node._bound_kwargs.items()}
    if isinstance(node, InputNode):
        out = input_args[0] if len(input_args) == 1 else input_args
    elif isinstance(node, FunctionNode):
        out = node._fn._fn(*args, **kwargs)  # raw callable, in-process
    else:
        raise TypeError(
            f"shm-compiled pipelines support function/input nodes only, "
            f"got {type(node).__name__}")
    cache[id(node)] = out
    return out


def main(in_name: str, out_name: str) -> None:
    import cloudpickle

    from ray_tpu.core.shm_channel import ChannelClosed, ShmChannel

    cin = ShmChannel(name=in_name, create=False)
    cout = ShmChannel(name=out_name, create=False)
    last = 0
    last, blob = cin.read(last, timeout=60.0)
    output_node = cloudpickle.loads(blob)
    try:
        while True:
            try:
                last, frame = cin.read(last, timeout=None)
            except ChannelClosed:
                return
            seq, input_args = cloudpickle.loads(frame)
            try:
                result = (seq, "ok", _exec_inline(output_node, {}, input_args))
            except BaseException as e:  # noqa: BLE001 — error crosses the channel
                result = (seq, "err", e)
            try:
                cout.write(cloudpickle.dumps(result), timeout=None)
            except ChannelClosed:
                return
    finally:
        cin.detach()
        cout.detach()


if __name__ == "__main__":
    main(sys.argv[1], sys.argv[2])
