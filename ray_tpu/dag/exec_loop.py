"""Compiled-graph actor-side execution: a resident loop over a static plan.

Parity: the reference's ``do_exec_tasks`` loop (compiled_dag_node.py:186) —
once a DAG is compiled, every participating actor runs a FIXED schedule of
operations per execution, fed and drained by preallocated channels. At steady
state this module makes **zero control-plane calls**: no ``.remote()``, no
RPC ``call``/``notify``, no task submission — the only cross-actor traffic is
shm ring-channel reads/writes (``core/shm_channel.py``). That property is
pinned by ``scripts/check_wire_schemas.py::check_dag_loop_steady_state``.

The plan dataclasses live here (not in ``ray_tpu.dag``) so dedicated actor
worker processes can import them without pulling the full public API in.

Frame protocol on every channel: ``cloudpickle.dumps((seq, status, payload))``
with status ``"ok"`` or ``"err"``. An error input short-circuits the step and
is FORWARDED downstream, so one failing execution surfaces at the driver
without desynchronizing the pipeline; the loop itself stays alive for the
next execution. A closed channel (teardown, actor death) ends the loop and
closes every channel the plan touches, cascading the shutdown through the
graph so no end ever hangs.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field

from ray_tpu.core.shm_channel import ChannelClosed
from ray_tpu.util import timeline as _timeline
from ray_tpu.util.metrics import Counter, Histogram

# Telemetry: instruments bound ONCE at import (util/metrics.py bind
# contract, pinned by check_wire_schemas::check_hot_path_instruments) and
# recorded SAMPLED — the steady-state loop stays RPC-free and
# allocation-free; every _SAMPLE_EVERY-th execution pays two perf_counter
# reads and a handful of locked dict increments at the flush.
_SAMPLE_EVERY = 32
_M_STEPS = Counter("ray_tpu_dag_steps_total",
                   "compiled-graph executions completed by resident "
                   "exec loops").bind()
_M_STEP_MS = Histogram(
    "ray_tpu_dag_step_latency_ms",
    "sampled wall-clock of one execution (first input frame -> outputs "
    "published)",
    boundaries=[0.05, 0.2, 1, 5, 20, 100, 1000]).bind()
_M_RING_OCC = Histogram(
    "ray_tpu_dag_ring_occupancy",
    "sampled input-ring depth (frames published, unconsumed) at flush",
    boundaries=[0, 1, 2, 4, 8, 16]).bind()

# Argument templates (picklable, interpreted per step):
CONST = "const"   # ("const", value)           literal bound at .bind() time
CHAN = "chan"     # ("chan", chan_id)          read from a channel this step
SLOT = "slot"     # ("slot", node_idx)         same-actor upstream result


@dataclass
class OpStep:
    """One scheduled method execution on this plan's actor."""

    node_idx: int
    method: str
    args: tuple = ()
    kwargs: dict = field(default_factory=dict)
    out_chans: tuple = ()   # channel ids the result frame is published to
    keep_slot: bool = False  # a later same-actor step consumes the result


@dataclass
class ActorPlan:
    """The static per-actor schedule: steps in topological order plus the
    channel ids this actor reads. One plan per participating actor."""

    actor_bin: bytes
    steps: tuple = ()
    read_chans: tuple = ()

    def write_chans(self) -> list:
        out = []
        for s in self.steps:
            out.extend(s.out_chans)
        return out


class _ErrorFrame(Exception):
    """Internal: an input frame carried an upstream error."""

    def __init__(self, payload):
        self.payload = payload


def run_plan(instance, plan: ActorPlan, channels: dict, *,
             detach_on_exit: bool = False, step_lock=None) -> None:
    """Drive ``instance`` through ``plan`` until the channels close.

    ``channels`` maps chan_id -> ShmChannel (reader AND writer ends this
    actor touches). Blocks the calling thread for the graph's lifetime —
    callers run it on a dedicated thread per installed graph.

    ``step_lock``: mutex shared with the actor's normal dispatch path.
    Held ONLY around the method invocation (never across channel waits),
    it preserves the max_concurrency=1 sequential-execution guarantee when
    ``.remote()`` calls — or a second installed graph — run concurrently
    with this loop.
    """
    import cloudpickle
    import contextlib

    guard = step_lock if step_lock is not None else contextlib.nullcontext()

    last = {cid: 0 for cid in plan.read_chans}
    slots: dict = {}
    execs = 0       # executions since the last metrics flush
    sampled_ms = -1.0
    t_exec = 0.0    # start of the SAMPLED execution (first frame in hand)
    t_wall = 0.0    # wall twin of t_exec: the timeline window's anchor
    try:
        while True:
            frames: dict = {}   # chan_id -> (seq, status, payload)
            seq = None
            sampling = execs == 0  # first execution of each flush window
            if sampling:
                t_exec = 0.0  # a frameless execution must not reuse a stale clock

            def _chan_value(cid):
                nonlocal seq, t_exec, t_wall
                fr = frames.get(cid)
                if fr is None:
                    last[cid], view = channels[cid].read_view(
                        last[cid], timeout=None)
                    fr = frames[cid] = cloudpickle.loads(view)
                if seq is None:
                    seq = fr[0]
                    if sampling:
                        # clock starts when the first input frame is in hand
                        # — idle channel wait is arrival time, not step cost
                        t_exec = time.perf_counter()
                        t_wall = time.time()
                if fr[1] != "ok":
                    raise _ErrorFrame(fr[2])
                return fr[2]

            def _resolve(t):
                kind = t[0]
                if kind == CONST:
                    return t[1]
                if kind == CHAN:
                    return _chan_value(t[1])
                val = slots[t[1]]  # SLOT
                if isinstance(val, _ErrorFrame):
                    raise val
                return val

            slots.clear()
            for step in plan.steps:
                status, payload = "ok", None
                try:
                    args = [_resolve(t) for t in step.args]
                    kwargs = {k: _resolve(t) for k, t in step.kwargs.items()}
                    with guard:
                        payload = getattr(instance, step.method)(*args,
                                                                 **kwargs)
                except ChannelClosed:
                    raise
                except _ErrorFrame as ef:
                    status, payload = "err", ef.payload
                except BaseException as e:  # noqa: BLE001 — crosses the channel
                    status, payload = "err", e
                if step.keep_slot:
                    # a later same-actor step consumes this; an error input
                    # re-raises there so it forwards through the schedule
                    slots[step.node_idx] = (payload if status == "ok"
                                            else _ErrorFrame(payload))
                if step.out_chans:
                    try:
                        blob = cloudpickle.dumps((seq, status, payload))
                    except BaseException as e:  # noqa: BLE001 — unserializable
                        blob = cloudpickle.dumps(
                            (seq, "err",
                             RuntimeError(f"result of {step.method} not "
                                          f"serializable: {e!r}")))
                    for cid in step.out_chans:
                        channels[cid].write(blob, timeout=None)
            # error short-circuits can leave input channels unread; consume
            # them now so every channel advances exactly one generation per
            # execution (the lockstep invariant the seq protocol rests on)
            _drain_unread(plan, frames, channels, last)
            if sampling and t_exec:
                sampled_ms = (time.perf_counter() - t_exec) * 1e3
                # sampled timeline window: one ring append per flush window
                # (same cadence as the metrics sample — the loop stays
                # RPC-free and per-step-allocation-free)
                _timeline.record_span("dag_step", "exec", t_wall,
                                      sampled_ms / 1e3)
            execs += 1
            if execs >= _SAMPLE_EVERY:
                _M_STEPS.inc(execs)
                if sampled_ms >= 0.0:
                    _M_STEP_MS.observe(sampled_ms)
                occ = 0
                for cid in plan.read_chans:
                    o = channels[cid].occupancy()
                    if o > occ:
                        occ = o
                _M_RING_OCC.observe(occ)
                execs = 0
                sampled_ms = -1.0
    except ChannelClosed:
        pass
    except BaseException:  # noqa: BLE001 — loop must never die silently:
        # closing the channels below converts this into ChannelClosed at
        # every other end instead of a hang — but the ROOT CAUSE must land
        # in a log, or a production graph death leaves zero evidence
        import logging

        from ray_tpu.util import flight_recorder

        flight_recorder.record("dag", "exec_loop_died",
                               steps=len(plan.steps),
                               read_chans=list(plan.read_chans))
        logging.getLogger("ray_tpu").exception(
            "compiled-graph exec loop died; closing its channels")
    finally:
        if execs:  # partial flush window: don't lose the tail count
            _M_STEPS.inc(execs)
        for ch in channels.values():
            try:
                ch.close_channel()
            except Exception:
                pass
            if detach_on_exit:
                ch.detach()


def _drain_unread(plan: ActorPlan, frames: dict, channels: dict,
                  last: dict) -> None:
    """Consume any input channel not yet read this execution (short-circuited
    by an upstream error) so the graph stays in lockstep."""
    import cloudpickle

    for cid in plan.read_chans:
        if cid not in frames:
            last[cid], view = channels[cid].read_view(last[cid], timeout=None)
            frames[cid] = cloudpickle.loads(view)
