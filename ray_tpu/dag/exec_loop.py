"""Compiled-graph actor-side execution: a resident loop over a static plan.

Parity: the reference's ``do_exec_tasks`` loop (compiled_dag_node.py:186) —
once a DAG is compiled, every participating actor runs a FIXED schedule of
operations per execution, fed and drained by preallocated channels. At steady
state this module makes **zero control-plane calls**: no ``.remote()``, no
RPC ``call``/``notify``, no task submission — the only cross-actor traffic is
shm ring-channel reads/writes (``core/shm_channel.py``). That property is
pinned by ``scripts/check_wire_schemas.py::check_dag_loop_steady_state``.

The plan dataclasses live here (not in ``ray_tpu.dag``) so dedicated actor
worker processes can import them without pulling the full public API in.

Frame protocol on every channel: ``cloudpickle.dumps((seq, status, payload))``
with status ``"ok"`` or ``"err"``. An error input short-circuits the step and
is FORWARDED downstream, so one failing execution surfaces at the driver
without desynchronizing the pipeline; the loop itself stays alive for the
next execution. A closed channel (teardown, actor death) ends the loop and
closes every channel the plan touches, cascading the shutdown through the
graph so no end ever hangs.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from ray_tpu.core.shm_channel import ChannelClosed

# Argument templates (picklable, interpreted per step):
CONST = "const"   # ("const", value)           literal bound at .bind() time
CHAN = "chan"     # ("chan", chan_id)          read from a channel this step
SLOT = "slot"     # ("slot", node_idx)         same-actor upstream result


@dataclass
class OpStep:
    """One scheduled method execution on this plan's actor."""

    node_idx: int
    method: str
    args: tuple = ()
    kwargs: dict = field(default_factory=dict)
    out_chans: tuple = ()   # channel ids the result frame is published to
    keep_slot: bool = False  # a later same-actor step consumes the result


@dataclass
class ActorPlan:
    """The static per-actor schedule: steps in topological order plus the
    channel ids this actor reads. One plan per participating actor."""

    actor_bin: bytes
    steps: tuple = ()
    read_chans: tuple = ()

    def write_chans(self) -> list:
        out = []
        for s in self.steps:
            out.extend(s.out_chans)
        return out


class _ErrorFrame(Exception):
    """Internal: an input frame carried an upstream error."""

    def __init__(self, payload):
        self.payload = payload


def run_plan(instance, plan: ActorPlan, channels: dict, *,
             detach_on_exit: bool = False, step_lock=None) -> None:
    """Drive ``instance`` through ``plan`` until the channels close.

    ``channels`` maps chan_id -> ShmChannel (reader AND writer ends this
    actor touches). Blocks the calling thread for the graph's lifetime —
    callers run it on a dedicated thread per installed graph.

    ``step_lock``: mutex shared with the actor's normal dispatch path.
    Held ONLY around the method invocation (never across channel waits),
    it preserves the max_concurrency=1 sequential-execution guarantee when
    ``.remote()`` calls — or a second installed graph — run concurrently
    with this loop.
    """
    import cloudpickle
    import contextlib

    guard = step_lock if step_lock is not None else contextlib.nullcontext()

    last = {cid: 0 for cid in plan.read_chans}
    slots: dict = {}
    try:
        while True:
            frames: dict = {}   # chan_id -> (seq, status, payload)
            seq = None

            def _chan_value(cid):
                nonlocal seq
                fr = frames.get(cid)
                if fr is None:
                    last[cid], view = channels[cid].read_view(
                        last[cid], timeout=None)
                    fr = frames[cid] = cloudpickle.loads(view)
                if seq is None:
                    seq = fr[0]
                if fr[1] != "ok":
                    raise _ErrorFrame(fr[2])
                return fr[2]

            def _resolve(t):
                kind = t[0]
                if kind == CONST:
                    return t[1]
                if kind == CHAN:
                    return _chan_value(t[1])
                val = slots[t[1]]  # SLOT
                if isinstance(val, _ErrorFrame):
                    raise val
                return val

            slots.clear()
            for step in plan.steps:
                status, payload = "ok", None
                try:
                    args = [_resolve(t) for t in step.args]
                    kwargs = {k: _resolve(t) for k, t in step.kwargs.items()}
                    with guard:
                        payload = getattr(instance, step.method)(*args,
                                                                 **kwargs)
                except ChannelClosed:
                    raise
                except _ErrorFrame as ef:
                    status, payload = "err", ef.payload
                except BaseException as e:  # noqa: BLE001 — crosses the channel
                    status, payload = "err", e
                if step.keep_slot:
                    # a later same-actor step consumes this; an error input
                    # re-raises there so it forwards through the schedule
                    slots[step.node_idx] = (payload if status == "ok"
                                            else _ErrorFrame(payload))
                if step.out_chans:
                    try:
                        blob = cloudpickle.dumps((seq, status, payload))
                    except BaseException as e:  # noqa: BLE001 — unserializable
                        blob = cloudpickle.dumps(
                            (seq, "err",
                             RuntimeError(f"result of {step.method} not "
                                          f"serializable: {e!r}")))
                    for cid in step.out_chans:
                        channels[cid].write(blob, timeout=None)
            # error short-circuits can leave input channels unread; consume
            # them now so every channel advances exactly one generation per
            # execution (the lockstep invariant the seq protocol rests on)
            _drain_unread(plan, frames, channels, last)
    except ChannelClosed:
        pass
    except BaseException:  # noqa: BLE001 — loop must never die silently:
        # closing the channels below converts this into ChannelClosed at
        # every other end instead of a hang — but the ROOT CAUSE must land
        # in a log, or a production graph death leaves zero evidence
        import logging

        logging.getLogger("ray_tpu").exception(
            "compiled-graph exec loop died; closing its channels")
    finally:
        for ch in channels.values():
            try:
                ch.close_channel()
            except Exception:
                pass
            if detach_on_exit:
                ch.detach()


def _drain_unread(plan: ActorPlan, frames: dict, channels: dict,
                  last: dict) -> None:
    """Consume any input channel not yet read this execution (short-circuited
    by an upstream error) so the graph stays in lockstep."""
    import cloudpickle

    for cid in plan.read_chans:
        if cid not in frames:
            last[cid], view = channels[cid].read_view(last[cid], timeout=None)
            frames[cid] = cloudpickle.loads(view)
