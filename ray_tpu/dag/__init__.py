"""Lazy DAGs + compiled execution (accelerator pipelines).

Parity: python/ray/dag/ — DAGNode (.bind/.execute, dag_node.py:33), InputNode,
``experimental_compile`` (dag_node.py:283) → CompiledDAG (compiled_dag_node.py:813):
a static dataflow over actors where per-call RPC/scheduling is replaced by
preallocated channels and a fixed per-actor operation schedule (do_exec_tasks
loop, :186). Here channels are in-process queues feeding persistent actor
driver threads — the same compile-then-loop lifecycle; mutable-shm channels
(core/shm) are the cross-process upgrade path.
"""

from __future__ import annotations

import itertools
import queue
import threading
from typing import Any, Callable, Optional

import ray_tpu
from ray_tpu.core.api import ActorHandle


class DAGNode:
    """Base lazy node (reference: dag_node.py:33)."""

    def __init__(self, args: tuple, kwargs: dict):
        self._bound_args = args
        self._bound_kwargs = kwargs

    def _deps(self):
        for a in itertools.chain(self._bound_args, self._bound_kwargs.values()):
            if isinstance(a, DAGNode):
                yield a

    # ---- eager execution (reference: DAGNode.execute) ----
    def execute(self, *input_args):
        cache: dict[int, Any] = {}
        return self._exec(cache, input_args)

    def _exec(self, cache: dict, input_args: tuple):
        if id(self) in cache:
            return cache[id(self)]
        args = [a._exec(cache, input_args) if isinstance(a, DAGNode) else a
                for a in self._bound_args]
        kwargs = {k: (v._exec(cache, input_args) if isinstance(v, DAGNode) else v)
                  for k, v in self._bound_kwargs.items()}
        out = self._run(args, kwargs, input_args)
        cache[id(self)] = out
        return out

    def _run(self, args, kwargs, input_args):
        raise NotImplementedError

    def experimental_compile(self) -> "CompiledDAG":
        """Reference: dag_node.py:283."""
        return CompiledDAG(self)


class InputNode(DAGNode):
    """The DAG's input placeholder (used as a context manager for parity)."""

    def __init__(self):
        super().__init__((), {})

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def _run(self, args, kwargs, input_args):
        if len(input_args) == 1:
            return input_args[0]
        return input_args


class FunctionNode(DAGNode):
    def __init__(self, fn: Callable, args, kwargs):
        super().__init__(args, kwargs)
        self._fn = fn

    def _run(self, args, kwargs, input_args):
        ref = self._fn.remote(*args, **kwargs)
        return ray_tpu.get(ref)


class ClassMethodNode(DAGNode):
    def __init__(self, handle: ActorHandle, method_name: str, args, kwargs):
        super().__init__(args, kwargs)
        self._handle = handle
        self._method_name = method_name

    def _run(self, args, kwargs, input_args):
        method = getattr(self._handle, self._method_name)
        return ray_tpu.get(method.remote(*args, **kwargs))


def bind_function(remote_fn, *args, **kwargs) -> FunctionNode:
    return FunctionNode(remote_fn, args, kwargs)


def bind_method(handle: ActorHandle, method: str, *args, **kwargs) -> ClassMethodNode:
    return ClassMethodNode(handle, method, args, kwargs)


class CompiledDAG:
    """Static schedule execution (reference: compiled_dag_node.py:813).

    compile(): topo-sort the graph once; execute(): push input, run the fixed
    schedule with results flowing through preallocated slots — no per-node
    scheduling decisions at steady state.
    """

    def __init__(self, output_node: DAGNode):
        self._output = output_node
        self._in_q: "queue.Queue[tuple[int, tuple]]" = queue.Queue()
        self._results: dict[int, queue.Queue] = {}
        self._seq = 0
        self._lock = threading.Lock()
        self._running = True
        self._driver = threading.Thread(target=self._drive, daemon=True)
        self._driver.start()

    def execute(self, *input_args) -> "CompiledDAGRef":
        if not self._running:
            raise RuntimeError("CompiledDAG was torn down; re-compile to execute again")
        with self._lock:
            seq = self._seq
            self._seq += 1
            self._results[seq] = queue.Queue(maxsize=1)
        self._in_q.put((seq, input_args))
        return CompiledDAGRef(self, seq)

    def _drive(self) -> None:
        while self._running:
            try:
                seq, input_args = self._in_q.get(timeout=0.2)
            except queue.Empty:
                continue
            try:
                # same topological evaluation DAGNode.execute uses, with a fresh
                # per-execution cache (the static schedule is the memoized walk)
                self._results[seq].put(("ok", self._output._exec({}, input_args)))
            except BaseException as e:  # noqa: BLE001
                self._results[seq].put(("err", e))

    def get(self, seq: int, timeout: float | None = None):
        q = self._results[seq]
        try:
            status, val = q.get(timeout=timeout)
        except queue.Empty:
            raise TimeoutError(f"CompiledDAG execution {seq} did not finish in {timeout}s") from None
        with self._lock:
            self._results.pop(seq, None)
        if status == "err":
            raise val
        return val

    def teardown(self) -> None:
        self._running = False
        # fail anything still queued or un-fetched so get() never hangs
        err = RuntimeError("CompiledDAG torn down before this execution completed")
        try:
            while True:
                seq, _ = self._in_q.get_nowait()
                self._results[seq].put(("err", err))
        except queue.Empty:
            pass


class CompiledDAGRef:
    """Reference: experimental/compiled_dag_ref.py."""

    def __init__(self, dag: CompiledDAG, seq: int):
        self._dag = dag
        self._seq = seq

    def get(self, timeout: float | None = None):
        return self._dag.get(self._seq, timeout)
