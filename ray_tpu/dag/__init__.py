"""Lazy DAGs + compiled execution (accelerator pipelines).

Parity: python/ray/dag/ — DAGNode (.bind/.execute, dag_node.py:33), InputNode,
``experimental_compile`` (dag_node.py:283) → CompiledDAG (compiled_dag_node.py:813):
a static dataflow over actors where per-call RPC/scheduling is replaced by
preallocated channels and a fixed per-actor operation schedule (do_exec_tasks
loop, :186). Here channels are in-process queues feeding persistent actor
driver threads — the same compile-then-loop lifecycle; mutable-shm channels
(core/shm) are the cross-process upgrade path.
"""

from __future__ import annotations

import itertools
import queue
import threading
import time
from typing import Any, Callable, Optional

import ray_tpu
from ray_tpu.core.api import ActorHandle
from ray_tpu.dag.compiled import ResultBufferDriver as _ResultBufferDriver


class DAGNode:
    """Base lazy node (reference: dag_node.py:33)."""

    def __init__(self, args: tuple, kwargs: dict):
        self._bound_args = args
        self._bound_kwargs = kwargs

    def _deps(self):
        for a in itertools.chain(self._bound_args, self._bound_kwargs.values()):
            if isinstance(a, DAGNode):
                yield a

    # ---- eager execution (reference: DAGNode.execute) ----
    def execute(self, *input_args):
        cache: dict[int, Any] = {}
        return self._exec(cache, input_args)

    def _exec(self, cache: dict, input_args: tuple):
        if id(self) in cache:
            return cache[id(self)]
        args = [a._exec(cache, input_args) if isinstance(a, DAGNode) else a
                for a in self._bound_args]
        kwargs = {k: (v._exec(cache, input_args) if isinstance(v, DAGNode) else v)
                  for k, v in self._bound_kwargs.items()}
        out = self._run(args, kwargs, input_args)
        cache[id(self)] = out
        return out

    def _run(self, args, kwargs, input_args):
        raise NotImplementedError

    def experimental_compile(self, channel: str | None = None):
        """Reference: dag_node.py:283. An ACTOR-METHOD DAG compiles into a
        true compiled graph (dag/compiled.py): a static per-actor schedule
        over pre-negotiated shm/wire channels with resident exec loops —
        zero control-plane round trips per execute at steady state. Other
        shapes (function nodes, collectives) keep the legacy driver-thread
        CompiledDAG; ``channel="shm"`` runs a function-node pipeline in a
        dedicated worker process fed by mutable shm channels
        (core/shm_channel.py)."""
        if channel == "shm":
            return ShmCompiledDAG(self)
        from ray_tpu.dag.compiled import try_compile_actor_dag

        compiled = try_compile_actor_dag(self)
        if compiled is not None:
            return compiled
        return CompiledDAG(self)


class InputNode(DAGNode):
    """The DAG's input placeholder (used as a context manager for parity)."""

    def __init__(self):
        super().__init__((), {})

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def _run(self, args, kwargs, input_args):
        if len(input_args) == 1:
            return input_args[0]
        return input_args


class FunctionNode(DAGNode):
    def __init__(self, fn: Callable, args, kwargs):
        super().__init__(args, kwargs)
        self._fn = fn

    def _run(self, args, kwargs, input_args):
        ref = self._fn.remote(*args, **kwargs)
        return ray_tpu.get(ref)


class ClassMethodNode(DAGNode):
    def __init__(self, handle: ActorHandle, method_name: str, args, kwargs):
        super().__init__(args, kwargs)
        self._handle = handle
        self._method_name = method_name

    def _run(self, args, kwargs, input_args):
        method = getattr(self._handle, self._method_name)
        return ray_tpu.get(method.remote(*args, **kwargs))


def bind_function(remote_fn, *args, **kwargs) -> FunctionNode:
    return FunctionNode(remote_fn, args, kwargs)


def bind_method(handle: ActorHandle, method: str, *args, **kwargs) -> ClassMethodNode:
    return ClassMethodNode(handle, method, args, kwargs)


class CompiledDAG:
    """Static schedule execution (reference: compiled_dag_node.py:813).

    compile(): topo-sort the graph once; execute(): push input, run the fixed
    schedule with results flowing through preallocated slots — no per-node
    scheduling decisions at steady state.
    """

    def __init__(self, output_node: DAGNode):
        self._output = output_node
        self._in_q: "queue.Queue[tuple[int, tuple]]" = queue.Queue()
        self._results: dict[int, queue.Queue] = {}
        self._seq = 0
        self._lock = threading.Lock()
        self._running = True
        self._driver = threading.Thread(target=self._drive, daemon=True)
        self._driver.start()

    def execute(self, *input_args) -> "CompiledDAGRef":
        if not self._running:
            raise RuntimeError("CompiledDAG was torn down; re-compile to execute again")
        with self._lock:
            seq = self._seq
            self._seq += 1
            self._results[seq] = queue.Queue(maxsize=1)
        self._in_q.put((seq, input_args))
        return CompiledDAGRef(self, seq)

    def _drive(self) -> None:
        while self._running:
            try:
                seq, input_args = self._in_q.get(timeout=0.2)
            except queue.Empty:
                continue
            try:
                # same topological evaluation DAGNode.execute uses, with a fresh
                # per-execution cache (the static schedule is the memoized walk)
                self._publish(seq, ("ok", self._output._exec({}, input_args)))
            except BaseException as e:  # noqa: BLE001
                self._publish(seq, ("err", e))

    def _publish(self, seq: int, result: tuple) -> None:
        # teardown may have cleared/failed this slot concurrently — the
        # publish must tolerate that instead of KeyError-ing the daemon
        q = self._results.get(seq)
        if q is not None:
            q.put(result)

    def get(self, seq: int, timeout: float | None = None):
        q = self._results[seq]
        try:
            status, val = q.get(timeout=timeout)
        except queue.Empty:
            raise TimeoutError(f"CompiledDAG execution {seq} did not finish in {timeout}s") from None
        with self._lock:
            self._results.pop(seq, None)
        if status == "err":
            raise val
        return val

    def teardown(self) -> None:
        self._running = False
        # join the driver: after this no daemon thread can race the drain
        # below (it exits within its 0.2s queue-poll window)
        self._driver.join(timeout=5)
        # fail anything still queued or un-fetched so get() never hangs
        err = RuntimeError("CompiledDAG torn down before this execution completed")
        try:
            while True:
                seq, _ = self._in_q.get_nowait()
                q = self._results.get(seq)
                if q is not None:
                    q.put(("err", err))
        except queue.Empty:
            pass


class ShmCompiledDAG(_ResultBufferDriver):
    """Function pipeline on a persistent worker process, driven through two
    mutable shm channels (reference: compiled graphs over shared-memory
    channels, experimental/channel/shared_memory_channel.py). Per-execute
    cost is two channel writes — no task submission, no control plane.

    A drain thread continuously acks the output channel into a result buffer,
    so the worker never blocks on un-fetched results and any number of
    executes may be in flight (execute() blocks only while the input ring is
    full — in-flight work is bounded by the channel's slot count)."""

    _desc = "shm DAG"

    def __init__(self, output_node: DAGNode, channel_capacity: int = 1 << 20):
        import subprocess
        import sys as _sys

        import cloudpickle

        from ray_tpu.core.process_pool import worker_env
        from ray_tpu.core.shm_channel import ShmChannel, default_timeout

        self._in_ch = ShmChannel(capacity=channel_capacity)
        self._out_ch = ShmChannel(capacity=channel_capacity)
        # one knob for every compiled-graph channel wait
        # (env RAY_TPU_DAG_CHANNEL_TIMEOUT_S, default 60s)
        self._timeout = default_timeout()
        self._proc = None
        try:
            self._proc = subprocess.Popen(
                [_sys.executable, "-m", "ray_tpu.dag.shm_worker",
                 self._in_ch.name, self._out_ch.name],
                env=worker_env(),
            )
            self._in_ch.write(cloudpickle.dumps(output_node),
                              timeout=self._timeout)
        except BaseException:
            # nothing reaches the caller: clean up or the segments +
            # subprocess leak with no handle to teardown()
            if self._proc is not None:
                self._proc.kill()
            self._in_ch.destroy()
            self._out_ch.destroy()
            raise
        # _exec_lock (from the shared driver) serializes seq allocation +
        # input write: holding _cond across a (possibly blocking) channel
        # write would starve the drain thread and deadlock the pipeline
        # (worker can't publish results)
        self._init_result_buffer()
        self._drain = threading.Thread(target=self._drain_loop, daemon=True)
        self._drain.start()

    def _drain_loop(self) -> None:
        """Ack every result as it lands so the worker never stalls on
        un-fetched outputs; flag worker death promptly for getters."""
        import cloudpickle

        from ray_tpu.core.shm_channel import ChannelClosed

        last = 0
        while self._running:
            try:
                last, frame = self._out_ch.read(last, timeout=0.5)
                # loads stays INSIDE the try: an undeserializable frame must
                # flag the DAG dead, not silently kill this thread
                got_seq, status, payload = cloudpickle.loads(frame)
            except TimeoutError:
                if self._proc.poll() is not None:
                    self._mark_dead(f"shm DAG worker died "
                                    f"(rc={self._proc.returncode})")
                    return
                continue
            except ChannelClosed:
                self._mark_dead("shm DAG channel closed")
                return
            except BaseException as e:  # noqa: BLE001 — never die silently
                self._mark_dead(f"shm DAG drain failed: {e!r}")
                return
            self._publish_result(got_seq, status, payload)

    def execute(self, *input_args) -> "CompiledDAGRef":
        import cloudpickle

        if not self._running:
            raise RuntimeError("ShmCompiledDAG was torn down")
        with self._cond:
            if self._dead:
                raise RuntimeError(self._dead)
        with self._exec_lock:
            seq = self._seq
            # blocks only while the input ring is full (bounded in-flight)
            self._in_ch.write(cloudpickle.dumps((seq, input_args)),
                              timeout=self._timeout)
            self._seq += 1  # incremented only after the frame is really sent
        return CompiledDAGRef(self, seq)

    # get() inherited from _ResultBufferDriver (dag/compiled.py)

    def teardown(self) -> None:
        self._running = False
        self._in_ch.close_channel()
        self._out_ch.close_channel()
        try:
            self._proc.wait(timeout=5)
        except Exception:
            self._proc.kill()
        # join the drain BEFORE unmapping the segments it may be mid-read on
        # (the closed flag above wakes it within its 0.5s poll window)
        self._drain.join(timeout=5)
        self._in_ch.destroy()
        self._out_ch.destroy()
        # the drain may have exited on the _running flag without marking
        # death — fail un-fetched refs explicitly so get() never hangs
        self._mark_dead("ShmCompiledDAG torn down")


class CollectiveOutputNode(DAGNode):
    """Gang collective as a DAG node (reference: dag/collective_node.py:212
    allreduce + CollectiveOutputNode :252): binds one method call per gang
    actor; the node's value is the elementwise allreduce of all members'
    outputs — members run concurrently, the reduction happens once."""

    def __init__(self, member_nodes: list, op: str = "sum"):
        super().__init__(tuple(member_nodes), {})
        if op not in ("sum", "max", "min"):
            raise ValueError(f"unsupported collective op {op!r}")
        if not member_nodes:
            raise ValueError("collective needs at least one member node")
        for m in member_nodes:
            if not isinstance(m, ClassMethodNode):
                raise ValueError(
                    f"collective members must be actor-method nodes, "
                    f"got {type(m).__name__}")
        self._op = op

    def _exec(self, cache: dict, input_args: tuple):
        # override: members launch CONCURRENTLY (refs first, one gather),
        # not sequentially like generic arg evaluation
        if id(self) in cache:
            return cache[id(self)]
        refs = []
        for m in self._bound_args:
            args = [a._exec(cache, input_args) if isinstance(a, DAGNode) else a
                    for a in m._bound_args]
            kwargs = {k: (v._exec(cache, input_args) if isinstance(v, DAGNode) else v)
                      for k, v in m._bound_kwargs.items()}
            refs.append(getattr(m._handle, m._method_name).remote(*args, **kwargs))
        outs = ray_tpu.get(refs)
        import numpy as np

        acc = np.asarray(outs[0])
        for o in outs[1:]:
            if self._op == "sum":
                acc = acc + np.asarray(o)
            elif self._op == "max":
                acc = np.maximum(acc, np.asarray(o))
            else:
                acc = np.minimum(acc, np.asarray(o))
        cache[id(self)] = acc
        return acc

    def _run(self, args, kwargs, input_args):  # pragma: no cover - _exec overridden
        raise AssertionError


def allreduce_bind(member_nodes: list, op: str = "sum") -> CollectiveOutputNode:
    """Reference: collective_node.py allreduce.bind over gang actors."""
    return CollectiveOutputNode(member_nodes, op)


class CompiledDAGRef:
    """Reference: experimental/compiled_dag_ref.py."""

    def __init__(self, dag: CompiledDAG, seq: int):
        self._dag = dag
        self._seq = seq

    def get(self, timeout: float | None = None):
        return self._dag.get(self._seq, timeout)
