"""Tombstone (reference parity: python/ray/workflow/__init__.py — the
workflow library was removed upstream in 2.44 and its import raises)."""

raise ModuleNotFoundError(
    "ray_tpu.workflow does not exist: the reference removed Ray Workflows "
    "in 2.44; durable execution belongs to external orchestrators."
)
