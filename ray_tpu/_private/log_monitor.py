"""Per-worker log plumbing: worker stdout/stderr land in session log files
and stream back to the driver.

Parity: python/ray/_private/log_monitor.py — every worker process writes to
its own files under the session dir; a monitor tails them and forwards new
lines to the driver's stdout prefixed with the worker identity, so `print`
inside tasks is visible at the driver (log_to_driver semantics).
"""

from __future__ import annotations

import os
import sys
import threading
import time
from typing import Optional, TextIO


class LogMonitor:
    """Tails every *.out/*.err file in a session log dir to a sink stream."""

    def __init__(self, log_dir: str, sink: Optional[TextIO] = None,
                 poll_interval: float = 0.25):
        self.log_dir = log_dir
        self.sink = sink or sys.stdout
        self.poll_interval = poll_interval
        self._offsets: dict[str, int] = {}
        self._partial: dict[str, str] = {}
        self._running = True
        self._poll_lock = threading.Lock()  # stop() drains concurrently
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="ray_tpu-log-monitor")
        self._thread.start()

    def _loop(self) -> None:
        while self._running:
            try:
                self.poll_once()
            except Exception:
                pass
            time.sleep(self.poll_interval)

    def poll_once(self, flush_partial: bool = False) -> int:
        """Forward any new lines; returns the number forwarded (test hook)."""
        with self._poll_lock:
            return self._poll_locked(flush_partial)

    def _poll_locked(self, flush_partial: bool) -> int:
        forwarded = 0
        if not os.path.isdir(self.log_dir):
            return 0
        for name in sorted(os.listdir(self.log_dir)):
            if not (name.endswith(".out") or name.endswith(".err")):
                continue
            path = os.path.join(self.log_dir, name)
            try:
                size = os.path.getsize(path)
                offset = self._offsets.get(name, 0)
                if size <= offset:
                    continue
                with open(path, "r", errors="replace") as f:
                    f.seek(offset)
                    chunk = f.read()
                    self._offsets[name] = f.tell()
            except OSError:
                continue
            chunk = self._partial.pop(name, "") + chunk
            lines = chunk.split("\n")
            if lines and lines[-1]:
                self._partial[name] = lines[-1]  # hold incomplete tail line
            for line in lines[:-1]:
                if not line:
                    continue
                tag = name.rsplit(".", 1)[0]
                stream = "stderr" if name.endswith(".err") else "stdout"
                try:
                    self.sink.write(f"({tag} {stream}) {line}\n")
                    forwarded += 1
                except Exception:
                    pass
        if flush_partial:
            # final drain: emit held incomplete tail lines (a worker crash
            # often ends mid-line — its last output must not vanish)
            for name, tail in sorted(self._partial.items()):
                if not tail:
                    continue
                tag = name.rsplit(".", 1)[0]
                stream = "stderr" if name.endswith(".err") else "stdout"
                try:
                    self.sink.write(f"({tag} {stream}) {tail}\n")
                    forwarded += 1
                except Exception:
                    pass
            self._partial.clear()
        if forwarded:
            try:
                self.sink.flush()
            except Exception:
                pass
        return forwarded

    def stop(self) -> None:
        self._running = False
        try:
            self.poll_once(flush_partial=True)  # final drain
        except Exception:
            pass
