"""Binary IDs for objects/tasks/actors/nodes/jobs.

TPU-native analog of the reference's ID system (src/ray/common/id.h; bit layout spec in
src/ray/design_docs/id_specification.md). We keep the load-bearing properties:

- ObjectIDs embed the owning TaskID plus a return/put index, so ownership and lineage
  can be derived from the ID alone (reference: id_specification.md ObjectID layout).
- TaskIDs embed the ActorID for actor tasks (so actor affinity is derivable).
- IDs are fixed-width bytes, hashable, hex-printable, cheap to compare.

Layouts (bytes):
  JobID:    4  random
  ActorID:  12 = 8 unique + 4 job
  TaskID:   24 = 8 unique + 4 job + 12 actor (nil actor for normal tasks)
  ObjectID: 28 = 24 task + 4 index (big-endian; index 0..2^31 = returns, high bit = puts)
  NodeID:   16 random
  PlacementGroupID: 16 = 12 unique + 4 job
  WorkerID: 16 random
"""

from __future__ import annotations

import os
import struct


class BaseID:
    SIZE = 16
    __slots__ = ("_bytes",)

    def __init__(self, b: bytes):
        if len(b) != self.SIZE:
            raise ValueError(f"{type(self).__name__} needs {self.SIZE} bytes, got {len(b)}")
        self._bytes = b

    @classmethod
    def from_random(cls):
        return cls(os.urandom(cls.SIZE))

    @classmethod
    def nil(cls):
        return cls(b"\x00" * cls.SIZE)

    def is_nil(self) -> bool:
        return self._bytes == b"\x00" * self.SIZE

    def binary(self) -> bytes:
        return self._bytes

    def hex(self) -> str:
        return self._bytes.hex()

    @classmethod
    def from_hex(cls, h: str):
        return cls(bytes.fromhex(h))

    def __eq__(self, other):
        return type(other) is type(self) and other._bytes == self._bytes

    def __hash__(self):
        return hash((type(self).__name__, self._bytes))

    def __repr__(self):
        return f"{type(self).__name__}({self.hex()[:12]}…)" if not self.is_nil() else f"{type(self).__name__}(nil)"

    def __lt__(self, other):
        return self._bytes < other._bytes


class JobID(BaseID):
    SIZE = 4


class NodeID(BaseID):
    SIZE = 16


class WorkerID(BaseID):
    SIZE = 16


class ActorID(BaseID):
    SIZE = 12

    @classmethod
    def of(cls, job_id: JobID) -> "ActorID":
        return cls(os.urandom(8) + job_id.binary())

    def job_id(self) -> JobID:
        return JobID(self._bytes[8:])


class TaskID(BaseID):
    SIZE = 24

    @classmethod
    def for_normal_task(cls, job_id: JobID) -> "TaskID":
        return cls(os.urandom(8) + job_id.binary() + ActorID.nil().binary())

    @classmethod
    def for_actor_task(cls, actor_id: ActorID) -> "TaskID":
        return cls(os.urandom(8) + actor_id.job_id().binary() + actor_id.binary())

    @classmethod
    def for_driver(cls, job_id: JobID) -> "TaskID":
        return cls(b"\xff" * 8 + job_id.binary() + ActorID.nil().binary())

    def job_id(self) -> JobID:
        return JobID(self._bytes[8:12])

    def actor_id(self) -> ActorID:
        return ActorID(self._bytes[12:])


_PUT_BIT = 1 << 31


class ObjectID(BaseID):
    SIZE = 28

    @classmethod
    def for_task_return(cls, task_id: TaskID, index: int) -> "ObjectID":
        return cls(task_id.binary() + struct.pack(">I", index))

    @classmethod
    def for_put(cls, task_id: TaskID, put_index: int) -> "ObjectID":
        return cls(task_id.binary() + struct.pack(">I", _PUT_BIT | put_index))

    def task_id(self) -> TaskID:
        return TaskID(self._bytes[:24])

    def index(self) -> int:
        return struct.unpack(">I", self._bytes[24:])[0] & ~_PUT_BIT

    def is_put(self) -> bool:
        return bool(struct.unpack(">I", self._bytes[24:])[0] & _PUT_BIT)


class PlacementGroupID(BaseID):
    SIZE = 16

    @classmethod
    def of(cls, job_id: JobID) -> "PlacementGroupID":
        return cls(os.urandom(12) + job_id.binary())
