"""Usage stats: opt-out telemetry recording (local only).

Parity: python/ray/dashboard/modules/usage_stats/ + usage.proto — feature-tag
recording behind an opt-out env var. This implementation only aggregates tags
locally (written next to the session log dir); there is no network reporting.
"""

from __future__ import annotations

import json
import os
import threading
import time

_lock = threading.Lock()
_tags: dict[str, str] = {}
_counters: dict[str, int] = {}


def usage_stats_enabled() -> bool:
    """Opt-out (reference: RAY_USAGE_STATS_ENABLED)."""
    return os.environ.get("RAY_TPU_USAGE_STATS_ENABLED", "1") != "0"


def record_extra_usage_tag(key: str, value: str) -> None:
    """Reference: usage_lib TagKey recording API."""
    if not usage_stats_enabled():
        return
    with _lock:
        _tags[key] = value


def record_library_usage(library: str) -> None:
    if not usage_stats_enabled():
        return
    with _lock:
        _counters[f"library:{library}"] = _counters.get(f"library:{library}", 0) + 1


def usage_report() -> dict:
    with _lock:
        return {"tags": dict(_tags), "counters": dict(_counters), "ts": time.time()}


def write_report(path: str | None = None) -> str:
    if path is None:
        from ray_tpu._private.config import get_config

        path = os.path.join(get_config().session_dir_prefix, "usage_stats.json")
    parent = os.path.dirname(path)
    if parent:
        os.makedirs(parent, exist_ok=True)
    with open(path, "w") as f:
        json.dump(usage_report(), f)
    return path


def reset() -> None:
    with _lock:
        _tags.clear()
        _counters.clear()
