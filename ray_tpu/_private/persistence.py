"""Control-plane persistence: session metadata that survives driver restarts.

Parity: the reference's GCS fault tolerance — metadata tables checkpointed to
an external Redis (gcs/store_client/redis_store_client.h, gcs_table_storage.cc)
so a restarted head recovers jobs/actors/KV; the serve controller additionally
checkpoints its app state into the internal KV and reloads it on restart
(serve/_private/controller.py:124-133, storage/kv_store.py:24).

Here the backing store is a pickle file under a user-chosen directory
(`_system_config={"gcs_storage_path": ...}`): every internal-KV mutation and
detached-actor registration writes through; `ray_tpu.init()` with the same
storage path restores the KV and re-creates named detached actors from their
recorded creation specs (the serve controller then self-heals its apps from
its KV checkpoint).
"""

from __future__ import annotations

import logging
import os
import pickle
import threading
from typing import Any, Optional

logger = logging.getLogger("ray_tpu")


class GcsStore:
    """Durable map of {kv: {(ns, key): val}, detached_actors: {key: spec}}."""

    def __init__(self, path: str):
        self.dir = path
        self.file = os.path.join(path, "gcs_store.pkl")
        self._lock = threading.Lock()
        self._data: dict[str, dict] = {"kv": {}, "detached_actors": {}}
        os.makedirs(path, exist_ok=True)
        if os.path.exists(self.file):
            try:
                with open(self.file, "rb") as f:
                    self._data = pickle.load(f)
            except Exception as e:
                logger.warning("gcs store at %s unreadable (%s); starting fresh",
                               self.file, e)

    def _flush(self) -> None:
        tmp = self.file + ".tmp"
        with open(tmp, "wb") as f:
            pickle.dump(self._data, f)
        os.replace(tmp, self.file)  # atomic: a crash never corrupts the store

    # ---- internal KV write-through ----
    def kv_snapshot(self) -> dict:
        with self._lock:
            return dict(self._data["kv"])

    def kv_put(self, fk: tuple, value: bytes) -> None:
        with self._lock:
            self._data["kv"][fk] = value
            self._flush()

    def kv_del(self, fks: list) -> None:
        with self._lock:
            for fk in fks:
                self._data["kv"].pop(fk, None)
            self._flush()

    # ---- detached actors ----
    def record_detached_actor(self, namespace: str, name: str, cls, args, kwargs,
                              options: dict) -> None:
        import cloudpickle

        try:
            blob = cloudpickle.dumps(
                {
                    "cls": cls,
                    "args": args,
                    "kwargs": kwargs,
                    "options": {
                        k: v for k, v in options.items()
                        if k not in ("placement_group",)  # not durable
                    },
                }
            )
        except Exception as e:
            logger.warning("detached actor %s/%s not persistable: %s", namespace, name, e)
            return
        with self._lock:
            self._data["detached_actors"][(namespace, name)] = blob
            self._flush()

    def remove_detached_actor(self, namespace: str, name: str) -> None:
        with self._lock:
            if self._data["detached_actors"].pop((namespace, name), None) is not None:
                self._flush()

    def detached_actors(self) -> dict:
        with self._lock:
            return dict(self._data["detached_actors"])


_store: Optional[GcsStore] = None


def get_store() -> Optional[GcsStore]:
    return _store


def set_store(store: Optional[GcsStore]) -> None:
    global _store
    _store = store


def restore_session(runtime) -> int:
    """Recreate named detached actors from the durable store (reference: GCS
    restart reconstructing actor metadata; here the actors re-run __init__,
    and checkpoint-aware actors like the serve controller self-heal from the
    restored internal KV). Returns the number restored."""
    import cloudpickle

    store = get_store()
    if store is None:
        return 0
    # KV first: actors' __init__ may read their checkpoints from it.
    from ray_tpu.experimental import internal_kv

    internal_kv._load_snapshot(store.kv_snapshot())
    restored = 0
    for (namespace, name), blob in store.detached_actors().items():
        try:
            spec = cloudpickle.loads(blob)
            opts = dict(spec["options"])
            opts["name"] = name
            opts["namespace"] = namespace
            opts["get_if_exists"] = True
            runtime.create_actor(spec["cls"], spec["args"], spec["kwargs"], opts)
            restored += 1
        except Exception as e:
            logger.warning("failed to restore detached actor %s/%s: %s",
                           namespace, name, e)
    return restored
