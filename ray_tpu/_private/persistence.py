"""Control-plane persistence: head state that survives head crashes.

Parity: the reference's GCS fault tolerance — metadata tables checkpointed to
an external Redis (gcs/store_client/redis_store_client.h,
gcs_table_storage.cc:200) so a restarted GCS recovers jobs/actors/PGs/KV and
raylets/clients reconnect (gcs_rpc_client/rpc_client.h:622). The serve
controller additionally checkpoints its app state into the internal KV and
reloads it on restart (serve/_private/controller.py:124-133).

Here the backing store is an APPEND LOG under a user-chosen directory
(`_system_config={"gcs_storage_path": ...}` or RAY_TPU_GCS_STORAGE_PATH):
every control-plane mutation appends one pickled record; load replays the
log over the last snapshot and compacts. Tables:

- ``kv``              internal KV (serve checkpoints live here)
- ``detached_actors`` named detached actor creation specs
- ``session``         control-plane identity: auth token (so agents/clients
                      reconnect to a restarted head without re-keying)
- ``pgs``             placement-group specs (restored PENDING; they re-place
                      as agents re-register)
- ``jobs``            job-submission metadata snapshots
- ``plane``           object-plane locations {oid: {node_bin: size}} — lets a
                      restarted head serve pre-crash ObjectRefs by
                      chunk-pulling from surviving node stores

A head crash (kill -9) mid-append leaves at most one truncated record; replay
stops at the first bad frame (write-ahead semantics: the acknowledged state
is always recovered).
"""

from __future__ import annotations

import logging
import os
import pickle
import threading
from typing import Any, Optional

logger = logging.getLogger("ray_tpu")

_TABLES = ("kv", "detached_actors", "session", "pgs", "jobs", "plane")


class GcsStore:
    """Durable control-plane tables over snapshot + append log."""

    def __init__(self, path: str):
        self.dir = path
        self.snap_file = os.path.join(path, "gcs_store.pkl")
        self.log_file = os.path.join(path, "gcs_log.pkl")
        self._lock = threading.Lock()
        self._data: dict[str, dict] = {t: {} for t in _TABLES}
        os.makedirs(path, exist_ok=True)
        self._load()
        # Compact: fold the replayed log into a fresh snapshot, then start a
        # new log (bounds replay time across repeated restarts).
        self._write_snapshot()
        self._log_fh = open(self.log_file, "wb")

    # ------------------------------------------------------------ load/save
    def _load(self) -> None:
        if os.path.exists(self.snap_file):
            try:
                with open(self.snap_file, "rb") as f:
                    snap = pickle.load(f)
                for t in _TABLES:
                    self._data[t] = snap.get(t, {})
            except Exception as e:
                logger.warning("gcs snapshot at %s unreadable (%s); starting fresh",
                               self.snap_file, e)
        if os.path.exists(self.log_file):
            try:
                with open(self.log_file, "rb") as f:
                    while True:
                        try:
                            table, op, key, val = pickle.load(f)
                        except EOFError:
                            break
                        except Exception:
                            # torn tail record from a crash mid-append
                            logger.info("gcs log: stopping replay at torn record")
                            break
                        self._apply(table, op, key, val)
            except OSError as e:
                logger.warning("gcs log at %s unreadable: %s", self.log_file, e)

    def _apply(self, table: str, op: str, key, val) -> None:
        tab = self._data.setdefault(table, {})
        if op == "put":
            tab[key] = val
        elif op == "del":
            tab.pop(key, None)
        elif op == "plane_add":  # plane table: key=oid_bin, val=(node_bin, size)
            node_bin, size = val
            tab.setdefault(key, {})[node_bin] = size
        elif op == "plane_del":
            holders = tab.get(key)
            if holders is not None:
                holders.pop(val, None)
                if not holders:
                    tab.pop(key, None)

    def _write_snapshot(self) -> None:
        tmp = self.snap_file + ".tmp"
        with open(tmp, "wb") as f:
            pickle.dump(self._data, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self.snap_file)  # atomic: a crash never corrupts it
        # log folded into the snapshot -> truncate
        with open(self.log_file, "wb"):
            pass

    # Compact when the log outgrows this (bounds replay time and disk for
    # long-lived heads with churny writers, e.g. per-task plane records).
    _COMPACT_BYTES = 64 * 1024 * 1024

    def _append(self, table: str, op: str, key, val=None) -> None:
        """Apply + durably log one mutation (write-through, like the
        reference's per-mutation Redis writes). Periodically folds the log
        into the snapshot in-session."""
        with self._lock:
            self._apply(table, op, key, val)
            try:
                pickle.dump((table, op, key, val), self._log_fh)
                self._log_fh.flush()
                if self._log_fh.tell() >= self._COMPACT_BYTES:
                    self._log_fh.close()
                    self._write_snapshot()  # truncates the log file
                    self._log_fh = open(self.log_file, "wb")
            except (OSError, ValueError) as e:
                logger.warning("gcs log append failed: %s", e)

    def close(self) -> None:
        with self._lock:
            try:
                self._log_fh.close()
            except OSError:
                pass

    # ---- internal KV write-through ----
    def kv_snapshot(self) -> dict:
        with self._lock:
            return dict(self._data["kv"])

    def kv_put(self, fk: tuple, value: bytes) -> None:
        self._append("kv", "put", fk, value)

    def kv_del(self, fks: list) -> None:
        for fk in fks:
            self._append("kv", "del", fk)

    # ---- session identity ----
    def session_meta(self) -> dict:
        with self._lock:
            return dict(self._data["session"])

    def set_session_meta(self, key: str, value: Any) -> None:
        self._append("session", "put", key, value)

    # ---- detached actors ----
    def record_detached_actor(self, namespace: str, name: str, cls, args, kwargs,
                              options: dict) -> None:
        import cloudpickle

        try:
            blob = cloudpickle.dumps(
                {
                    "cls": cls,
                    "args": args,
                    "kwargs": kwargs,
                    "options": {
                        k: v for k, v in options.items()
                        if k not in ("placement_group",)  # not durable
                    },
                }
            )
        except Exception as e:
            logger.warning("detached actor %s/%s not persistable: %s", namespace, name, e)
            return
        self._append("detached_actors", "put", (namespace, name), blob)

    def remove_detached_actor(self, namespace: str, name: str) -> None:
        self._append("detached_actors", "del", (namespace, name))

    def detached_actors(self) -> dict:
        with self._lock:
            return dict(self._data["detached_actors"])

    # ---- placement groups ----
    def record_pg(self, pg_id_bin: bytes, spec: dict) -> None:
        """spec: {bundles: [dict], strategy, name, slice_name}."""
        self._append("pgs", "put", pg_id_bin, spec)

    def remove_pg(self, pg_id_bin: bytes) -> None:
        self._append("pgs", "del", pg_id_bin)

    def pgs(self) -> dict:
        with self._lock:
            return dict(self._data["pgs"])

    # ---- jobs ----
    def record_job(self, job_id: str, info: dict) -> None:
        self._append("jobs", "put", job_id, info)

    def jobs(self) -> dict:
        with self._lock:
            return dict(self._data["jobs"])

    # ---- object-plane locations ----
    def plane_add(self, oid_bin: bytes, node_bin: bytes, size: int) -> None:
        self._append("plane", "plane_add", oid_bin, (node_bin, size))

    def plane_remove(self, oid_bin: bytes, node_bin: bytes) -> None:
        self._append("plane", "plane_del", oid_bin, node_bin)

    def plane_snapshot(self) -> dict:
        with self._lock:
            return {k: dict(v) for k, v in self._data["plane"].items()}


_store: Optional[GcsStore] = None


def get_store() -> Optional[GcsStore]:
    return _store


def set_store(store: Optional[GcsStore]) -> None:
    global _store
    if _store is not None and store is not _store:
        _store.close()
    _store = store


def restore_session(runtime) -> int:
    """Rebuild a restarted head's control-plane state from the durable store
    (reference: GCS restart reconstructing its tables from Redis). Restores,
    in dependency order: internal KV, object-plane locations (pre-crash refs
    become chunk-pullable again once their node agents re-register), PGs
    (PENDING; they place as agents register), then named detached actors
    (whose __init__ may read KV checkpoints — e.g. the serve controller
    self-heals its apps). Returns the number of detached actors restored."""
    import cloudpickle

    store = get_store()
    if store is None:
        return 0
    # KV first: actors' __init__ may read their checkpoints from it.
    from ray_tpu.experimental import internal_kv

    internal_kv._load_snapshot(store.kv_snapshot())

    # Object-plane locations: seed markers so get() on pre-crash refs pulls
    # from surviving node stores instead of raising ObjectLostError.
    from ray_tpu._private.ids import NodeID, ObjectID
    from ray_tpu.core.object_store import RayObject

    for oid_bin, holders in store.plane_snapshot().items():
        oid = ObjectID(oid_bin)
        size = 0
        for node_bin, sz in holders.items():
            # seeded=True: unconfirmed until the holder agent re-registers;
            # expires after the reconnect grace window (runtime
            # _expire_seeded_planes) so pre-crash refs whose holder died
            # with the old head don't hang get() forever
            runtime.plane_object_added(oid, NodeID(node_bin), size=sz,
                                       _persist=False, seeded=True)
            size = max(size, sz)
        if not runtime.memory_store.contains(oid):
            runtime.memory_store.put(oid, RayObject(size=size, in_shm=True))

    # Placement groups: same ids, PENDING until nodes re-register.
    for pg_id_bin, spec in store.pgs().items():
        try:
            runtime.scheduler.restore_placement_group(pg_id_bin, spec)
        except Exception as e:
            logger.warning("failed to restore PG %s: %s", pg_id_bin.hex()[:12], e)

    restored = 0
    for (namespace, name), blob in store.detached_actors().items():
        try:
            spec = cloudpickle.loads(blob)
            opts = dict(spec["options"])
            opts["name"] = name
            opts["namespace"] = namespace
            opts["get_if_exists"] = True
            runtime.create_actor(spec["cls"], spec["args"], spec["kwargs"], opts)
            restored += 1
        except Exception as e:
            logger.warning("failed to restore detached actor %s/%s: %s",
                           namespace, name, e)
    return restored
