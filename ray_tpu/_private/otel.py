"""OTLP emission for export events and tracing spans.

Parity: the reference's OpenTelemetry wiring (src/ray/util/event.cc export
sinks + the dashboard's OTel collector guidance) — here a dependency-free
OTLP/JSON encoder: events become OTLP LogRecords and tracing spans become
OTLP Spans, shipped either to a file (`RAY_TPU_OTLP_FILE`) or POSTed to an
OTLP/HTTP collector endpoint (`RAY_TPU_OTLP_ENDPOINT`, e.g.
http://localhost:4318). Zero-egress environments use the file sink; the
JSON shape follows opentelemetry-proto's JSON mapping so a collector's
filelogreceiver ingests it unchanged.
"""

from __future__ import annotations

import json
import os
import threading
import time
import urllib.request
import uuid
from typing import Any, Optional

_LOCK = threading.Lock()
_STATE: dict = {"file": None, "endpoint": None, "configured": False}

_SERVICE_RESOURCE = {
    "attributes": [
        {"key": "service.name", "value": {"stringValue": "ray_tpu"}},
    ]
}


def _attr(key: str, value: Any) -> dict:
    if isinstance(value, bool):
        v = {"boolValue": value}
    elif isinstance(value, int):
        v = {"intValue": str(value)}
    elif isinstance(value, float):
        v = {"doubleValue": value}
    else:
        v = {"stringValue": str(value)}
    return {"key": key, "value": v}


def configured() -> bool:
    # lock-free fast path: this sits on task-completion and span-exit hot
    # paths; the flag flip in _ensure is a benign one-time race
    if not _STATE["configured"]:
        _ensure()
    return _STATE["file"] is not None or _STATE["endpoint"] is not None


def _ensure() -> None:
    with _LOCK:
        if _STATE["configured"]:
            return
        path = os.environ.get("RAY_TPU_OTLP_FILE")
        if path:
            try:
                _STATE["file"] = open(path, "a", buffering=1)
            except OSError:
                _STATE["file"] = None
        _STATE["endpoint"] = os.environ.get("RAY_TPU_OTLP_ENDPOINT") or None
        _STATE["configured"] = True


def _ship(kind: str, payload: dict) -> None:
    """Enqueue for the background shipper (kind: 'logs' or 'traces' — the
    OTLP/HTTP path suffix). NEVER blocks the caller: file writes and HTTP
    POSTs happen on the shipper thread, and a full queue drops (the
    reference batches/destages for exactly this reason)."""
    q = _shipper_queue()
    try:
        q.put_nowait((kind, payload))
    except Exception:
        pass  # queue full: drop rather than stall a task/span hot path


def _shipper_queue():
    q = _STATE.get("queue")
    if q is None:
        with _LOCK:
            q = _STATE.get("queue")
            if q is None:
                import queue as _qmod

                q = _STATE["queue"] = _qmod.Queue(maxsize=10_000)
                t = threading.Thread(target=_shipper_loop, args=(q,),
                                     daemon=True, name="otlp-shipper")
                _STATE["thread"] = t
                t.start()
    return q


def _shipper_loop(q) -> None:
    while True:
        try:
            item = q.get(timeout=1.0)
        except Exception:
            # generation check: a shutdown that couldn't enqueue the
            # sentinel (full queue) reset _STATE — exit instead of shipping
            # a dead session's records into the next session's sink
            if _STATE.get("queue") is not q:
                return
            continue
        if item is None:
            return
        kind, payload = item
        line = json.dumps(payload, separators=(",", ":"))
        f = _STATE["file"]
        if f is not None:
            try:
                f.write(line + "\n")
            except (OSError, ValueError):
                pass
        ep = _STATE["endpoint"]
        if ep is not None:
            try:
                req = urllib.request.Request(
                    f"{ep.rstrip('/')}/v1/{kind}", method="POST",
                    data=line.encode(),
                    headers={"Content-Type": "application/json"})
                urllib.request.urlopen(req, timeout=5).close()
            except Exception:
                pass  # collector down: drop, never stall


def emit_log(source_type: str, event_data: dict, event_id: str | None = None,
             ts: float | None = None) -> None:
    """One export event -> one OTLP LogRecord (resourceLogs envelope)."""
    if not configured():
        return
    ts_ns = str(int((ts if ts is not None else time.time()) * 1e9))
    record = {
        "timeUnixNano": ts_ns,
        "severityNumber": 9,  # INFO
        "severityText": "INFO",
        "body": {"stringValue": source_type},
        "attributes": [_attr("event.id", event_id or uuid.uuid4().hex)]
        + [_attr(f"ray_tpu.{k}", v) for k, v in event_data.items()
           if v is not None],
    }
    _ship("logs", {
        "resourceLogs": [{
            "resource": _SERVICE_RESOURCE,
            "scopeLogs": [{
                "scope": {"name": "ray_tpu.export_events"},
                "logRecords": [record],
            }],
        }]
    })


def emit_span(name: str, start_s: float, end_s: float,
              attributes: Optional[dict] = None,
              trace_id: str | None = None, span_id: str | None = None,
              parent_span_id: str | None = None) -> None:
    """One tracing span -> one OTLP Span (resourceSpans envelope)."""
    if not configured():
        return
    span = {
        "traceId": trace_id or uuid.uuid4().hex,
        "spanId": (span_id or uuid.uuid4().hex)[:16],
        "name": name,
        "kind": 1,  # INTERNAL
        "startTimeUnixNano": str(int(start_s * 1e9)),
        "endTimeUnixNano": str(int(end_s * 1e9)),
        "attributes": [_attr(k, v) for k, v in (attributes or {}).items()
                       if v is not None],
    }
    if parent_span_id:
        span["parentSpanId"] = parent_span_id[:16]
    _ship("traces", {
        "resourceSpans": [{
            "resource": _SERVICE_RESOURCE,
            "scopeSpans": [{
                "scope": {"name": "ray_tpu.tracing"},
                "spans": [span],
            }],
        }]
    })


def shutdown() -> None:
    # drain: the shipper flushes queued records before the file closes
    q = _STATE.get("queue")
    t = _STATE.get("thread")
    if q is not None:
        sent = False
        for _ in range(3):  # producers can refill between drain and put
            try:
                q.put_nowait(None)
                sent = True
                break
            except Exception:
                # full queue: drop backlog so the sentinel fits — a fast
                # drain beats shipping stale records into the next session
                try:
                    while True:
                        q.get_nowait()
                except Exception:
                    pass
        if t is not None:
            # no sentinel landed: don't burn 5s — the generation check ends
            # the thread once _STATE resets below
            t.join(timeout=5 if sent else 0.2)
    with _LOCK:
        f = _STATE["file"]
        if f is not None:
            try:
                f.close()
            except OSError:
                pass
        _STATE.update(file=None, endpoint=None, configured=False,
                      queue=None, thread=None)
