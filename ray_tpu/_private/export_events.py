"""Export-event pipeline: schema'd JSONL event files for external ingestion.

Parity: the reference's export API (src/ray/util/event.cc RayExportEvent +
python exportable events — task/actor/node/driver-job state transitions
written as JSON lines under the session dir, consumed by external
observability pipelines rather than the in-process dashboard).

Config-gated (config.export_events_enabled / env
RAY_TPU_EXPORT_EVENTS_ENABLED):
every emit appends one line to `<dir>/export_<source>.jsonl` with the
reference's envelope shape {event_id, timestamp, source_type, event_data}.
Files rotate at `max_bytes` (one `.1` generation, like the reference's
size-capped event logs)."""

from __future__ import annotations

import json
import os
import threading
import time
import uuid
from typing import Any

_LOCK = threading.Lock()
_WRITERS: dict[str, "_Writer"] = {}
_DIR: str | None = None
# Non-owner processes (pool workers joining via RAY_TPU_SESSION_DIR) write
# per-pid files: _Writer's tell()-based rotation is single-process-only, and
# concurrent os.replace() rotations would clobber each other's .1 files.
_SUFFIX = ""
MAX_BYTES = 8 * 1024 * 1024


class _Writer:
    def __init__(self, path: str):
        self.path = path
        self._f = open(path, "a", buffering=1)
        self._lock = threading.Lock()

    def emit(self, line: str) -> None:
        with self._lock:
            if self._f.tell() + len(line.encode("utf-8")) > MAX_BYTES:
                self._f.close()
                try:  # one rotated generation, reference-style size cap
                    os.replace(self.path, self.path + ".1")
                except OSError:
                    pass
                self._f = open(self.path, "a", buffering=1)
            self._f.write(line)

    def close(self) -> None:
        with self._lock:
            try:
                self._f.close()
            except OSError:
                pass


_ENABLED = False


def _compute_enabled() -> bool:
    """One flag, one override tier: config.export_events_enabled (env form
    RAY_TPU_EXPORT_EVENTS_ENABLED via Config.apply_env_overrides — parsed
    like every other config boolean)."""
    try:
        from ray_tpu._private.config import get_config

        return bool(getattr(get_config(), "export_events_enabled", False))
    except Exception:
        return False


def enabled() -> bool:
    return _ENABLED


def configure(session_dir: str, owner: bool = True) -> None:
    """Point the pipeline at this session's export dir and refresh the
    enabled decision (called by init; safe across re-inits — prior sessions'
    writers are closed so events never land in an old session's files).
    Non-owner joiners (workers) get per-pid file names."""
    global _DIR, _ENABLED, _SUFFIX
    with _LOCK:
        for w in _WRITERS.values():
            w.close()
        _WRITERS.clear()
        _DIR = os.path.join(session_dir, "export_events")
        _SUFFIX = "" if owner else f"_{os.getpid()}"
        _ENABLED = _compute_enabled()


def emit(source_type: str, event_data: dict[str, Any]) -> None:
    """Append one export event; a single boolean check when disabled (the
    default — this sits on the task-transition hot path). Never raises into
    the runtime paths that call it."""
    if not _ENABLED:
        return
    try:
        # lock-free fast path: dict reads are atomic under the GIL, and the
        # writer exists after the first event per source — only a miss takes
        # the global lock (this sits on the task-transition hot path)
        w = _WRITERS.get(source_type)
        if w is None:
            with _LOCK:
                w = _WRITERS.get(source_type)
                if w is None:
                    assert _DIR is not None  # configure() precedes _ENABLED
                    os.makedirs(_DIR, exist_ok=True)
                    w = _WRITERS[source_type] = _Writer(
                        os.path.join(_DIR, f"export_{source_type}{_SUFFIX}.jsonl"))
        event_id = uuid.uuid4().hex
        ts = time.time()
        w.emit(json.dumps({
            "event_id": event_id,
            "timestamp": ts,
            "source_type": source_type,
            "event_data": event_data,
        }, default=str) + "\n")
        # optional OTLP sink (RAY_TPU_OTLP_FILE / RAY_TPU_OTLP_ENDPOINT):
        # the same event as an OpenTelemetry LogRecord
        from ray_tpu._private import otel

        if otel.configured():
            otel.emit_log(source_type, event_data, event_id=event_id, ts=ts)
    except Exception:
        pass


def shutdown() -> None:
    """Close writers and disable: a daemon thread finishing after
    Runtime.shutdown (e.g. a job supervisor's _wait) must not resurrect
    export files in the dead session's dir."""
    global _ENABLED
    with _LOCK:
        _ENABLED = False
        for w in _WRITERS.values():
            w.close()
        _WRITERS.clear()
