"""Serialization: cloudpickle envelope with out-of-band zero-copy buffers.

TPU-native analog of the reference's serialization layer
(python/ray/_private/serialization.py: msgpack envelope + pickle5 out-of-band buffers;
zero-copy numpy reads from plasma). Design:

- ``serialize(obj) -> (meta: bytes, buffers: list[memoryview/bytes])`` using pickle5
  protocol with buffer_callback, so large numpy / jax host arrays are captured as
  out-of-band buffers and can be written into (and later mapped zero-copy out of) the
  shared-memory object store.
- jax.Array device values are pulled to host (np.asarray) at put() time — device
  residency across process boundaries is handled by the L4 channel layer, not the
  object store (matching the reference, where GPU tensors bypass plasma via
  NCCL/RDT: python/ray/experimental/rdt/).
- Exceptions are wrapped so they re-raise at ``get`` (reference:
  RayTaskError in python/ray/exceptions.py).
"""

from __future__ import annotations

import pickle
import sys
from typing import Any, Iterable

import cloudpickle


def _jax_array_types():
    # Never IMPORT jax here: a value can only be a jax.Array if jax is already
    # loaded in this process, and importing jax in a fresh worker is multi-
    # second (plus sitecustomize hooks may register a TPU platform the worker
    # must not touch — one process per chip).
    jax = sys.modules.get("jax")
    if jax is None:
        return ()
    try:
        return (jax.Array,)
    except AttributeError:  # partially-imported jax
        return ()


def _to_host(obj: Any) -> Any:
    """Convert device arrays to host numpy for cross-process transport."""
    import numpy as np

    if _jax_array_types() and isinstance(obj, _jax_array_types()):
        return np.asarray(obj)
    return obj


def serialize(obj: Any) -> tuple[bytes, list]:
    """Serialize to (metadata, out-of-band buffers)."""
    buffers: list[pickle.PickleBuffer] = []
    obj = _to_host(obj)
    meta = cloudpickle.dumps(obj, protocol=5, buffer_callback=buffers.append)
    return meta, [b.raw() for b in buffers]


def deserialize(meta: bytes, buffers: Iterable) -> Any:
    return pickle.loads(meta, buffers=list(buffers))


def serialize_to_bytes(obj: Any) -> bytes:
    """Single-blob form: 4-byte buffer count + lengths header + concatenated payloads."""
    _, parts = serialize_parts(obj)
    return b"".join(bytes(p) if not isinstance(p, (bytes, bytearray)) else p for p in parts)


def serialize_parts(obj: Any) -> tuple[int, list]:
    """Like serialize_to_bytes but WITHOUT the final concatenation copy:
    returns (total_size, parts) where writing the parts back-to-back produces
    exactly the single-blob format. Lets the shm store scatter-copy large
    arrays straight into the mapped arena (one memcpy total instead of two)."""
    import struct

    meta, bufs = serialize(obj)
    mvs = [memoryview(b).cast("B") for b in bufs]
    header = struct.pack(">I", len(mvs)) + b"".join(
        struct.pack(">Q", n) for n in [len(meta)] + [m.nbytes for m in mvs]
    )
    parts = [header, meta, *mvs]
    return len(header) + len(meta) + sum(m.nbytes for m in mvs), parts


def deserialize_from_bytes(data) -> Any:
    import struct

    mv = memoryview(data)
    (nbuf,) = struct.unpack_from(">I", mv, 0)
    off = 4
    lengths = []
    for _ in range(nbuf + 1):
        (ln,) = struct.unpack_from(">Q", mv, off)
        lengths.append(ln)
        off += 8
    meta = bytes(mv[off : off + lengths[0]])
    off += lengths[0]
    bufs = []
    for ln in lengths[1:]:
        bufs.append(mv[off : off + ln])  # zero-copy view into the source buffer
        off += ln
    return deserialize(meta, bufs)
