"""Shared future plumbing for the async-get paths (Runtime.get_async and
ClientRuntime.get_async): settle-if-live semantics and the small bounded
resolve pool, in one place so a fix lands on both runtimes."""

from __future__ import annotations

import threading
from concurrent.futures import Future, ThreadPoolExecutor
from typing import Callable

_pool_lock = threading.Lock()


def settle(fut: Future, setter: Callable, value) -> None:
    """Complete `fut` unless the consumer already cancelled it (e.g.
    asyncio.wait_for timed out and cancelled the wrapped future) — the
    check+set race resolves to a silent no-op, never InvalidStateError."""
    if fut.done():
        return
    try:
        setter(value)
    except Exception:
        pass  # lost the race with cancellation


def finish_get(runtime, ref, fut: Future, timeout: float = 120.0) -> None:
    """Resolve-and-settle: the bounded tail of an async get, run on the
    resolve pool once the object is known to exist."""
    try:
        val = runtime.get([ref], timeout=timeout)[0]
    except BaseException as e:  # noqa: BLE001
        settle(fut, fut.set_exception, e)
        return
    settle(fut, fut.set_result, val)


def resolve_pool(owner) -> ThreadPoolExecutor:
    """A lazily-created 4-thread pool attached to `owner` — bounded resolve
    work (deserialize / plane pull), never per-request blocking waits."""
    pool = getattr(owner, "_shared_resolve_pool", None)
    if pool is None:
        with _pool_lock:
            pool = getattr(owner, "_shared_resolve_pool", None)
            if pool is None:
                pool = ThreadPoolExecutor(max_workers=4,
                                          thread_name_prefix="async-get")
                owner._shared_resolve_pool = pool
    return pool
