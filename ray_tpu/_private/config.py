"""Central flag/config system.

TPU-native analog of the reference's ``RAY_CONFIG(type, name, default)`` X-macro table
(reference: src/ray/common/ray_config_def.h, ~400 flags materialized by the RayConfig
singleton in ray_config.h). We keep the same three-tier override model:

1. compiled-in defaults (this file),
2. per-process env overrides via ``RAY_TPU_<NAME>``,
3. cluster-wide ``_system_config`` dict passed to ``ray_tpu.init()`` (propagated
   through the controller, reference: gcs propagation of _system_config).
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Any


def _env(name: str, default: Any, typ: type) -> Any:
    raw = os.environ.get(f"RAY_TPU_{name.upper()}")
    if raw is None:
        return default
    if typ is bool:
        return raw.lower() in ("1", "true", "yes", "on")
    return typ(raw)


@dataclasses.dataclass
class Config:
    # --- object store (reference: ray_config_def.h:245 max_direct_call_object_size) ---
    max_inline_object_size: int = 100 * 1024  # bytes; larger objects go to the shm store
    object_store_memory: int = 512 * 1024 * 1024  # shm arena size for the node store
    object_spill_threshold: float = 0.8  # spill to disk when arena this full
    object_chunk_size: int = 1024 * 1024  # node-to-node transfer chunk (~1MB, object_manager.cc:536)

    # --- scheduling (reference: raylet/scheduling/) ---
    scheduler_top_k_fraction: float = 0.2  # hybrid top-k pack-then-spread
    scheduler_spread_threshold: float = 0.5
    lease_reuse: bool = True  # reuse worker leases per scheduling key (normal_task_submitter.cc)
    worker_pool_prestart: int = 0

    # --- execution backend (reference: every task executes in a worker process,
    #     task_receiver.cc:228; "thread" is an in-process debugging mode) ---
    task_execution: str = "process"  # "process" | "thread"
    process_workers: int = 0  # workers per node pool; 0 = min(cpu_count, 8)

    # --- control plane (reference: gcs_server + raylet gRPC mesh) ---
    control_plane_host: str = "127.0.0.1"
    control_plane_port: int = 0  # 0 = ephemeral
    agent_heartbeat_period_s: float = 0.5
    agent_heartbeat_timeout_s: float = 5.0

    # --- health / fault tolerance (reference: ray_config_def.h:985-991) ---
    health_check_initial_delay_s: float = 5.0
    health_check_period_s: float = 3.0
    health_check_failure_threshold: int = 5
    task_max_retries_default: int = 3
    actor_max_restarts_default: int = 0

    # --- memory pressure (reference: memory_monitor + worker_killing_policy_*;
    #     kill a worker when host usage crosses the threshold; 1.0 disables) ---
    memory_usage_threshold: float = 0.95
    memory_monitor_refresh_ms: int = 250

    # --- cgroup2 worker isolation (reference: common/cgroup2/cgroup_manager) ---
    # opt-in: needs an owned writable cgroup2 subtree (usual inside containers)
    worker_cgroups_enabled: bool = False
    worker_memory_limit_bytes: int = 0  # per-worker memory.max (0 = unlimited)
    worker_cpu_quota: float = 0.0       # per-worker CPUs via cpu.max (0 = unlimited)

    # --- streaming generators (reference: _generator_backpressure_num_objects;
    #     max unacked items a worker-process generator keeps in flight; 0 = off) ---
    generator_backpressure_num_objects: int = 64

    # --- timeouts ---
    get_timeout_default_s: float | None = None
    rpc_connect_timeout_s: float = 10.0

    # --- fault injection (reference: rpc_chaos.cc, RAY_testing_rpc_failure) ---
    testing_rpc_failure: str = ""  # "method=N" comma list: inject N failures for method

    # --- task events / observability (reference: task_event_buffer.h) ---
    task_events_enabled: bool = True
    task_events_max_buffer: int = 10000
    # terminal task-table entries kept for the state API / drilldowns; beyond
    # this, oldest finished entries are GC'd (reference: GcsTaskManager's
    # bounded task storage, gcs_task_manager.h)
    task_table_max_size: int = 20000
    # Export-event pipeline (reference: export API JSONL files under the
    # session dir for external ingestion); env: RAY_TPU_EXPORT_EVENTS_ENABLED
    export_events_enabled: bool = False

    # --- logging ---
    log_to_driver: bool = True
    session_dir_prefix: str = "/tmp/ray_tpu"

    # --- control-plane persistence (reference: GCS FT via external Redis,
    #     gcs/store_client/redis_store_client.h; empty = volatile session) ---
    gcs_storage_path: str = ""

    def apply_env_overrides(self) -> "Config":
        for f in dataclasses.fields(self):
            setattr(self, f.name, _env(f.name, getattr(self, f.name), type(getattr(self, f.name)) if getattr(self, f.name) is not None else str))
        return self

    def apply_system_config(self, system_config: dict | None) -> "Config":
        if system_config:
            for k, v in system_config.items():
                if not hasattr(self, k):
                    raise ValueError(f"Unknown _system_config key: {k}")
                setattr(self, k, v)
        return self

    def to_json(self) -> str:
        return json.dumps(dataclasses.asdict(self))

    @staticmethod
    def from_json(s: str) -> "Config":
        return Config(**json.loads(s))


_global_config: Config | None = None


def get_config() -> Config:
    global _global_config
    if _global_config is None:
        _global_config = Config().apply_env_overrides()
    return _global_config


def set_config(cfg: Config) -> None:
    global _global_config
    _global_config = cfg
