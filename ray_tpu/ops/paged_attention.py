"""Paged decode attention as a Pallas TPU kernel.

The serving-side hot op (PAPERS.md ragged/paged attention): one query token
per sequence attends over a KV cache stored in block_size-token PAGES scattered
through a pool. The block table rides in scalar-prefetch memory so the
BlockSpec index_map can route each grid step's page straight from HBM into
VMEM — pages are read IN PLACE, with none of the [B, max_blocks*block_size]
gathered-view materialization the XLA fallback pays
(models/llama.py forward_paged).

Reference: vLLM's paged_attention CUDA kernel is the analog (the reference
delegates serving to vLLM); this is the TPU-native equivalent built on the
pallas playbook (/opt/skills/guides/pallas_guide.md).

Layout contract: pages are [Hkv, num_blocks, block_size, D] per layer (head
major) so a (head, block) pair maps to one VMEM tile of (block_size, D) —
Mosaic's block-shape rule needs the last two dims tile-aligned.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp

NEG_INF = -1e30


def _decode_kernel(tables_ref, lens_ref,       # scalar-prefetch
                   q_ref, k_ref, v_ref,        # blocks
                   o_ref,                      # output
                   m_scr, l_scr, acc_scr, *,
                   block_size: int, num_blocks: int):
    """Grid (B, Hkv, seq_blocks); the page for (b, i) was DMA'd via the
    table-driven index_map. Streaming softmax over the sequence's pages."""
    from jax.experimental import pallas as pl

    b = pl.program_id(0)
    i = pl.program_id(2)

    @pl.when(i == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    seq_len = lens_ref[b]
    live = i * block_size < seq_len  # pages past the ragged end are skipped

    @pl.when(live)
    def _compute():
        q = q_ref[0, 0].astype(jnp.float32)   # [Gp, D]
        k = k_ref[0, 0].astype(jnp.float32)   # [BS, D]
        v = v_ref[0, 0].astype(jnp.float32)   # [BS, D]
        scale = 1.0 / math.sqrt(q.shape[-1])
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ()))) * scale  # [Gp, BS]
        kpos = i * block_size + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        s = jnp.where(kpos < seq_len, s, NEG_INF)
        m_prev = m_scr[:]
        m_new = jnp.maximum(m_prev, s.max(axis=1))
        alive = (m_new > NEG_INF / 2).astype(jnp.float32)
        m_safe = m_new * alive
        p = jnp.exp(s - m_safe[:, None]) * alive[:, None]
        corr = jnp.exp(m_prev - m_safe) * alive
        l_scr[:] = l_scr[:] * corr + p.sum(axis=1)
        acc_scr[:] = acc_scr[:] * corr[:, None] + jax.lax.dot(p, v)
        m_scr[:] = m_new

    @pl.when(i == num_blocks - 1)
    def _finalize():
        o_ref[0, 0] = (acc_scr[:] /
                       jnp.maximum(l_scr[:], 1e-30)[:, None]).astype(o_ref.dtype)


def paged_decode_attention(q, k_pages, v_pages, tables, lengths, *,
                           interpret: bool | None = None):
    """q [B, Hq, D]; k/v_pages [Hkv, NB, BS, D]; tables [B, max_blocks]
    (pool block id per sequence block; unused entries must be a valid id —
    their reads are masked); lengths [B] = valid KV tokens (incl. the token
    being decoded). Returns [B, Hq, D].
    """
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    if interpret is None:
        interpret = jax.devices()[0].platform not in ("tpu", "axon")
    B, Hq, D = q.shape
    Hkv, NB, BS, _ = k_pages.shape
    max_blocks = tables.shape[1]
    g = Hq // Hkv
    gp = -(-g // 8) * 8  # pad the per-kv-head query group to a sublane multiple
    # [B, Hkv, Gp, D] query groups
    q4 = q.reshape(B, Hkv, g, D)
    if gp != g:
        q4 = jnp.pad(q4, [(0, 0), (0, 0), (0, gp - g), (0, 0)])

    kernel = functools.partial(_decode_kernel, block_size=BS,
                               num_blocks=max_blocks)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(B, Hkv, max_blocks),
        in_specs=[
            pl.BlockSpec((1, 1, gp, D), lambda b, h, i, tab, lens: (b, h, 0, 0)),
            # the table routes sequence-block i of sequence b to its pool page
            pl.BlockSpec((1, 1, BS, D), lambda b, h, i, tab, lens: (h, tab[b, i], 0, 0)),
            pl.BlockSpec((1, 1, BS, D), lambda b, h, i, tab, lens: (h, tab[b, i], 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, 1, gp, D), lambda b, h, i, tab, lens: (b, h, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((gp,), jnp.float32),
            pltpu.VMEM((gp,), jnp.float32),
            pltpu.VMEM((gp, D), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, Hkv, gp, D), q.dtype),
        interpret=interpret,
        **({} if interpret else {"compiler_params": pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"))}),
    )(tables.astype(jnp.int32), lengths.astype(jnp.int32), q4, k_pages, v_pages)
    return out[:, :, :g].reshape(B, Hq, D)
