"""Flash attention as a Pallas TPU kernel.

The hot-op playbook from /opt/skills/guides/pallas_guide.md applied to the
attention bottleneck: blockwise streaming softmax in VMEM scratch so the [S,S]
score matrix never materializes in HBM. Grid = (batch*heads, q_blocks, k_blocks)
with the k dimension 'arbitrary' (sequential) so (m, l, acc) scratch persists
across k iterations; causally-dead (q_block, k_block) tiles are skipped.

This replaces the XLA dense attention in models.llama for long sequences —
HBM traffic drops from O(S^2) to O(S*D) per head. The reference has no such
kernel (vLLM/torch own it there); this is the TPU-native equivalent.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp

NEG_INF = -1e30


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, m_scr, l_scr, acc_scr, *,
                  sm_scale: float, block_q: int, block_k: int, causal: bool,
                  num_k_blocks: int, kv_len: int):
    from jax.experimental import pallas as pl

    qi = pl.program_id(1)
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    # causal: skip tiles strictly above the diagonal band
    live = (not causal) or (ki * block_k <= qi * block_q + block_q - 1)

    @pl.when(live)
    def _compute():
        q = q_ref[0].astype(jnp.float32)  # [BQ, D]
        k = k_ref[0].astype(jnp.float32)  # [BK, D]
        v = v_ref[0].astype(jnp.float32)  # [BK, D]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ()))) * sm_scale  # [BQ, BK]
        cols = ki * block_k + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        s = jnp.where(cols < kv_len, s, NEG_INF)  # mask padded key rows
        if causal:
            rows = qi * block_q + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
            s = jnp.where(rows >= cols, s, NEG_INF)
        m_prev = m_scr[:]
        m_new = jnp.maximum(m_prev, s.max(axis=1))
        alive = m_new > NEG_INF / 2
        m_safe = jnp.where(alive, m_new, 0.0)
        p = jnp.exp(s - m_safe[:, None])
        p = jnp.where(alive[:, None], p, 0.0)
        corr = jnp.where(alive, jnp.exp(m_prev - m_safe), 0.0)
        l_scr[:] = l_scr[:] * corr + p.sum(axis=1)
        acc_scr[:] = acc_scr[:] * corr[:, None] + jax.lax.dot(p, v)
        m_scr[:] = m_new

    @pl.when(ki == num_k_blocks - 1)
    def _finalize():
        o_ref[0] = (acc_scr[:] / jnp.maximum(l_scr[:], 1e-30)[:, None]).astype(o_ref.dtype)


def _scratch(block_q: int, d: int):
    """(m, l, acc) VMEM scratch persisting across the sequential k dimension."""
    try:
        from jax.experimental.pallas import tpu as pltpu

        return [
            pltpu.VMEM((block_q,), jnp.float32),
            pltpu.VMEM((block_q,), jnp.float32),
            pltpu.VMEM((block_q, d), jnp.float32),
        ]
    except Exception:  # pragma: no cover
        return [
            jax.ShapeDtypeStruct((block_q,), jnp.float32),
            jax.ShapeDtypeStruct((block_q,), jnp.float32),
            jax.ShapeDtypeStruct((block_q, d), jnp.float32),
        ]


def _flash_bh(qbh, kbh, vbh, *, causal: bool, block_q: int, block_k: int,
              interpret: bool, kv_len: int | None = None):
    """qbh/kbh/vbh: [BH, S, D] -> [BH, S, D]. kv_len masks padded key rows."""
    from jax.experimental import pallas as pl

    BH, Sq, D = qbh.shape
    Sk = kbh.shape[1]
    block_q = min(block_q, Sq)
    block_k = min(block_k, Sk)
    nq = Sq // block_q
    nk = Sk // block_k
    sm_scale = 1.0 / math.sqrt(D)

    kernel = functools.partial(
        _flash_kernel, sm_scale=sm_scale, block_q=block_q, block_k=block_k,
        causal=causal, num_k_blocks=nk, kv_len=kv_len if kv_len is not None else Sk,
    )
    try:
        from jax.experimental.pallas import tpu as pltpu

        compiler_params = pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")
        )
    except Exception:
        compiler_params = None

    return pl.pallas_call(
        kernel,
        grid=(BH, nq, nk),
        in_specs=[
            pl.BlockSpec((1, block_q, D), lambda b, q, k: (b, q, 0)),
            pl.BlockSpec((1, block_k, D), lambda b, q, k: (b, k, 0)),
            pl.BlockSpec((1, block_k, D), lambda b, q, k: (b, k, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, D), lambda b, q, k: (b, q, 0)),
        out_shape=jax.ShapeDtypeStruct((BH, Sq, D), qbh.dtype),
        scratch_shapes=_scratch(block_q, D),
        interpret=interpret,
        **({"compiler_params": compiler_params} if compiler_params and not interpret else {}),
    )(qbh, kbh, vbh)


def flash_attention(q, k, v, *, causal: bool = True, block_q: int = 128,
                    block_k: int = 128, interpret: bool | None = None):
    """Drop-in attn_fn for models.llama: q [B,S,Hq,D], k/v [B,S,Hkv,D] (GQA).

    Falls back to interpret mode off-TPU (correctness everywhere; speed on MXU).
    """
    if interpret is None:
        # compile only on real TPU platforms; interpret everywhere else
        interpret = jax.devices()[0].platform not in ("tpu", "axon")
    B, S, Hq, D = q.shape
    Hkv = k.shape[2]
    g = Hq // Hkv
    # GQA: repeat kv heads to match q heads, fold heads into batch
    if g > 1:
        k = jnp.repeat(k, g, axis=2)
        v = jnp.repeat(v, g, axis=2)
    block_q = min(block_q, S)
    block_k = min(block_k, S)
    # pad sequence to block multiples; padded KEY rows are masked inside the
    # kernel (global col >= real length => NEG_INF), padded query rows sliced off
    S_pad = -(-S // block_q) * block_q
    S_pad = -(-S_pad // block_k) * block_k
    if S_pad != S:
        pad = [(0, 0), (0, S_pad - S), (0, 0), (0, 0)]
        q = jnp.pad(q, pad)
        k = jnp.pad(k, pad)
        v = jnp.pad(v, pad)
    qbh = q.transpose(0, 2, 1, 3).reshape(B * Hq, S_pad, D)
    kbh = k.transpose(0, 2, 1, 3).reshape(B * Hq, S_pad, D)
    vbh = v.transpose(0, 2, 1, 3).reshape(B * Hq, S_pad, D)
    obh = _flash_bh(qbh, kbh, vbh, causal=causal, block_q=block_q, block_k=block_k,
                    interpret=interpret, kv_len=S)
    return obh.reshape(B, Hq, S_pad, D).transpose(0, 2, 1, 3)[:, :S]
