"""Flash attention (forward + backward) as Pallas TPU kernels.

The hot-op playbook from /opt/skills/guides/pallas_guide.md applied to the
attention bottleneck: blockwise streaming softmax in VMEM scratch so the [S,S]
score matrix never materializes in HBM. Grid = (batch*heads, q_blocks, k_blocks)
with the k dimension 'arbitrary' (sequential) so (m, l, acc) scratch persists
across k iterations; causally-dead (q_block, k_block) tiles are skipped.

Training support: the op carries a `jax.custom_vjp`. The forward kernel emits
the per-row logsumexp as a residual; the backward pass runs two kernels — one
accumulating dQ over k-blocks, one accumulating dK/dV over q-blocks — using the
standard flash-attention recurrences (P = exp(S - lse), Δ = rowsum(dO∘O),
dS = P∘(dOVᵀ - Δ)). Memory stays O(S·D) per head in both directions.

This replaces the XLA dense attention in models.llama for long sequences —
HBM traffic drops from O(S^2) to O(S*D) per head. The reference has no such
kernel (vLLM/torch own it there); this is the TPU-native equivalent.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp

NEG_INF = -1e30


# ---------------------------------------------------------------- forward

def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, m_scr, l_scr, acc_scr, *,
                sm_scale: float, block_q: int, block_k: int, causal: bool,
                num_k_blocks: int, kv_len: int):
    from jax.experimental import pallas as pl

    qi = pl.program_id(1)
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    # causal: skip tiles strictly above the diagonal band
    live = (not causal) or (ki * block_k <= qi * block_q + block_q - 1)

    @pl.when(live)
    def _compute():
        q = q_ref[0].astype(jnp.float32)  # [BQ, D]
        k = k_ref[0].astype(jnp.float32)  # [BK, D]
        v = v_ref[0].astype(jnp.float32)  # [BK, D]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ()))) * sm_scale  # [BQ, BK]
        cols = ki * block_k + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        s = jnp.where(cols < kv_len, s, NEG_INF)  # mask padded key rows
        if causal:
            rows = qi * block_q + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
            s = jnp.where(rows >= cols, s, NEG_INF)
        m_prev = m_scr[:]
        m_new = jnp.maximum(m_prev, s.max(axis=1))
        # Masks as f32 arithmetic: Mosaic can't reshape i1 vectors to [BQ, 1],
        # and exp(NEG_INF - x) underflows to exactly 0 anyway (NEG_INF is a
        # finite -1e30, so no inf-inf NaNs).
        alive = (m_new > NEG_INF / 2).astype(jnp.float32)
        m_safe = m_new * alive
        p = jnp.exp(s - m_safe[:, None]) * alive[:, None]
        corr = jnp.exp(m_prev - m_safe) * alive
        l_scr[:] = l_scr[:] * corr + p.sum(axis=1)
        acc_scr[:] = acc_scr[:] * corr[:, None] + jax.lax.dot(p, v)
        m_scr[:] = m_new

    @pl.when(ki == num_k_blocks - 1)
    def _finalize():
        l = l_scr[:]
        o_ref[0] = (acc_scr[:] / jnp.maximum(l, 1e-30)[:, None]).astype(o_ref.dtype)
        # lse = m + log(l); dead rows (fully masked) keep NEG_INF so the bwd
        # kernels zero their P contributions. Stored [BQ, 1]: Mosaic requires
        # the last two block dims be (8k, 128m) or match the array dims.
        lse_ref[0] = jnp.where(l > 0.0, m_scr[:] + jnp.log(jnp.maximum(l, 1e-30)),
                               NEG_INF)[:, None]


# ---------------------------------------------------------------- backward

def _recompute_p(q, k, lse, qi, ki, *, sm_scale, block_q, block_k, causal,
                 kv_len):
    """Shared bwd-side reconstruction of the probability tile:
    P = exp(S - lse) with kv_len + causal masking, dead rows zeroed.
    One definition so dQ and dK/dV can never disagree on masking."""
    s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ()))) * sm_scale
    cols = ki * block_k + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
    s = jnp.where(cols < kv_len, s, NEG_INF)
    if causal:
        rows = qi * block_q + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
        s = jnp.where(rows >= cols, s, NEG_INF)
    alive = (lse > NEG_INF / 2).astype(jnp.float32)
    return jnp.exp(s - (lse * alive)[:, None]) * alive[:, None]


def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
                   acc_scr, *, sm_scale: float, block_q: int, block_k: int,
                   causal: bool, num_k_blocks: int, kv_len: int):
    """Grid (BH, nq, nk), k sequential: accumulate dQ for one q block."""
    from jax.experimental import pallas as pl

    qi = pl.program_id(1)
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        acc_scr[:] = jnp.zeros_like(acc_scr)

    live = (not causal) or (ki * block_k <= qi * block_q + block_q - 1)

    @pl.when(live)
    def _compute():
        q = q_ref[0].astype(jnp.float32)        # [BQ, D]
        k = k_ref[0].astype(jnp.float32)        # [BK, D]
        v = v_ref[0].astype(jnp.float32)        # [BK, D]
        do = do_ref[0].astype(jnp.float32)      # [BQ, D]
        lse = lse_ref[0][:, 0].astype(jnp.float32)    # [BQ]
        delta = delta_ref[0][:, 0].astype(jnp.float32)  # [BQ]
        p = _recompute_p(q, k, lse, qi, ki, sm_scale=sm_scale, block_q=block_q,
                         block_k=block_k, causal=causal, kv_len=kv_len)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())))  # [BQ, BK]
        ds = p * (dp - delta[:, None]) * sm_scale
        acc_scr[:] = acc_scr[:] + jax.lax.dot(ds, k)

    @pl.when(ki == num_k_blocks - 1)
    def _finalize():
        dq_ref[0] = acc_scr[:].astype(dq_ref.dtype)


def _bwd_dkv_kernel(k_ref, v_ref, q_ref, do_ref, lse_ref, delta_ref,
                    dk_ref, dv_ref, dk_scr, dv_scr, *, sm_scale: float,
                    block_q: int, block_k: int, causal: bool,
                    num_q_blocks: int, kv_len: int):
    """Grid (BH, nk, nq), q sequential: accumulate dK/dV for one k block."""
    from jax.experimental import pallas as pl

    ki = pl.program_id(1)
    qi = pl.program_id(2)

    @pl.when(qi == 0)
    def _init():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    live = (not causal) or (qi * block_q + block_q - 1 >= ki * block_k)

    @pl.when(live)
    def _compute():
        k = k_ref[0].astype(jnp.float32)        # [BK, D]
        v = v_ref[0].astype(jnp.float32)        # [BK, D]
        q = q_ref[0].astype(jnp.float32)        # [BQ, D]
        do = do_ref[0].astype(jnp.float32)      # [BQ, D]
        lse = lse_ref[0][:, 0].astype(jnp.float32)    # [BQ]
        delta = delta_ref[0][:, 0].astype(jnp.float32)  # [BQ]
        p = _recompute_p(q, k, lse, qi, ki, sm_scale=sm_scale, block_q=block_q,
                         block_k=block_k, causal=causal, kv_len=kv_len)
        dv_scr[:] = dv_scr[:] + jax.lax.dot_general(p, do, (((0,), (0,)), ((), ())))
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())))
        ds = p * (dp - delta[:, None]) * sm_scale
        dk_scr[:] = dk_scr[:] + jax.lax.dot_general(ds, q, (((0,), (0,)), ((), ())))

    @pl.when(qi == num_q_blocks - 1)
    def _finalize():
        dk_ref[0] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[:].astype(dv_ref.dtype)


# ---------------------------------------------------------------- plumbing

def _vmem(shape):
    try:
        from jax.experimental.pallas import tpu as pltpu

        return pltpu.VMEM(shape, jnp.float32)
    except Exception:  # pragma: no cover
        return jax.ShapeDtypeStruct(shape, jnp.float32)


def _compiler_params(interpret: bool):
    if interpret:
        return {}
    try:
        from jax.experimental.pallas import tpu as pltpu

        return {"compiler_params": pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"))}
    except Exception:  # pragma: no cover
        return {}


def _fwd_call(qbh, kbh, vbh, causal, block_q, block_k, interpret, kv_len):
    from jax.experimental import pallas as pl

    BH, Sq, D = qbh.shape
    Sk = kbh.shape[1]
    nq = Sq // block_q
    nk = Sk // block_k
    sm_scale = 1.0 / math.sqrt(D)
    kernel = functools.partial(
        _fwd_kernel, sm_scale=sm_scale, block_q=block_q, block_k=block_k,
        causal=causal, num_k_blocks=nk, kv_len=kv_len)
    return pl.pallas_call(
        kernel,
        grid=(BH, nq, nk),
        in_specs=[
            pl.BlockSpec((1, block_q, D), lambda b, q, k: (b, q, 0)),
            pl.BlockSpec((1, block_k, D), lambda b, q, k: (b, k, 0)),
            pl.BlockSpec((1, block_k, D), lambda b, q, k: (b, k, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, D), lambda b, q, k: (b, q, 0)),
            pl.BlockSpec((1, block_q, 1), lambda b, q, k: (b, q, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((BH, Sq, D), qbh.dtype),
            jax.ShapeDtypeStruct((BH, Sq, 1), jnp.float32),
        ],
        scratch_shapes=[_vmem((block_q,)), _vmem((block_q,)),
                        _vmem((block_q, D))],
        interpret=interpret,
        **_compiler_params(interpret),
    )(qbh, kbh, vbh)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash_bh(qbh, kbh, vbh, causal, block_q, block_k, interpret, kv_len):
    """qbh/kbh/vbh: [BH, S, D] -> [BH, S, D]. kv_len masks padded key rows."""
    o, _ = _fwd_call(qbh, kbh, vbh, causal, block_q, block_k, interpret, kv_len)
    return o


def _flash_bh_fwd(qbh, kbh, vbh, causal, block_q, block_k, interpret, kv_len):
    o, lse = _fwd_call(qbh, kbh, vbh, causal, block_q, block_k, interpret, kv_len)
    return o, (qbh, kbh, vbh, o, lse)


def _flash_bh_bwd(causal, block_q, block_k, interpret, kv_len, res, do):
    from jax.experimental import pallas as pl

    qbh, kbh, vbh, o, lse = res
    BH, Sq, D = qbh.shape
    Sk = kbh.shape[1]
    nq = Sq // block_q
    nk = Sk // block_k
    sm_scale = 1.0 / math.sqrt(D)
    # Δ_i = rowsum(dO ∘ O): tiny O(S·D) reduction, fine as plain XLA.
    # Kept [BH, S, 1] like lse (Mosaic block-shape rule).
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32), axis=-1,
                    keepdims=True)

    dq_kernel = functools.partial(
        _bwd_dq_kernel, sm_scale=sm_scale, block_q=block_q, block_k=block_k,
        causal=causal, num_k_blocks=nk, kv_len=kv_len)
    dq = pl.pallas_call(
        dq_kernel,
        grid=(BH, nq, nk),
        in_specs=[
            pl.BlockSpec((1, block_q, D), lambda b, q, k: (b, q, 0)),
            pl.BlockSpec((1, block_k, D), lambda b, q, k: (b, k, 0)),
            pl.BlockSpec((1, block_k, D), lambda b, q, k: (b, k, 0)),
            pl.BlockSpec((1, block_q, D), lambda b, q, k: (b, q, 0)),
            pl.BlockSpec((1, block_q, 1), lambda b, q, k: (b, q, 0)),
            pl.BlockSpec((1, block_q, 1), lambda b, q, k: (b, q, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, D), lambda b, q, k: (b, q, 0)),
        out_shape=jax.ShapeDtypeStruct((BH, Sq, D), qbh.dtype),
        scratch_shapes=[_vmem((block_q, D))],
        interpret=interpret,
        **_compiler_params(interpret),
    )(qbh, kbh, vbh, do, lse, delta)

    dkv_kernel = functools.partial(
        _bwd_dkv_kernel, sm_scale=sm_scale, block_q=block_q, block_k=block_k,
        causal=causal, num_q_blocks=nq, kv_len=kv_len)
    dk, dv = pl.pallas_call(
        dkv_kernel,
        grid=(BH, nk, nq),
        in_specs=[
            pl.BlockSpec((1, block_k, D), lambda b, k, q: (b, k, 0)),
            pl.BlockSpec((1, block_k, D), lambda b, k, q: (b, k, 0)),
            pl.BlockSpec((1, block_q, D), lambda b, k, q: (b, q, 0)),
            pl.BlockSpec((1, block_q, D), lambda b, k, q: (b, q, 0)),
            pl.BlockSpec((1, block_q, 1), lambda b, k, q: (b, q, 0)),
            pl.BlockSpec((1, block_q, 1), lambda b, k, q: (b, q, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_k, D), lambda b, k, q: (b, k, 0)),
            pl.BlockSpec((1, block_k, D), lambda b, k, q: (b, k, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((BH, Sk, D), kbh.dtype),
            jax.ShapeDtypeStruct((BH, Sk, D), vbh.dtype),
        ],
        scratch_shapes=[_vmem((block_k, D)), _vmem((block_k, D))],
        interpret=interpret,
        **_compiler_params(interpret),
    )(kbh, vbh, qbh, do, lse, delta)

    return dq, dk, dv


_flash_bh.defvjp(_flash_bh_fwd, _flash_bh_bwd)


def flash_attention(q, k, v, *, causal: bool = True, block_q: int = 128,
                    block_k: int = 128, interpret: bool | None = None):
    """Drop-in attn_fn for models.llama: q [B,S,Hq,D], k/v [B,S,Hkv,D] (GQA).

    Differentiable (custom VJP with flash backward kernels). Falls back to
    interpret mode off-TPU (correctness everywhere; speed on MXU).
    """
    if interpret is None:
        # compile only on real TPU platforms; interpret everywhere else
        interpret = jax.devices()[0].platform not in ("tpu", "axon")
    B, S, Hq, D = q.shape
    Hkv = k.shape[2]
    g = Hq // Hkv
    # GQA: repeat kv heads to match q heads, fold heads into batch. The repeat
    # is outside the custom_vjp, so its adjoint (sum over the group) is
    # handled by normal AD.
    if g > 1:
        k = jnp.repeat(k, g, axis=2)
        v = jnp.repeat(v, g, axis=2)
    block_q = min(block_q, S)
    block_k = min(block_k, S)
    # pad sequence to block multiples; padded KEY rows are masked inside the
    # kernel (global col >= real length => NEG_INF), padded query rows sliced off
    S_pad = -(-S // block_q) * block_q
    S_pad = -(-S_pad // block_k) * block_k
    if S_pad != S:
        pad = [(0, 0), (0, S_pad - S), (0, 0), (0, 0)]
        q = jnp.pad(q, pad)
        k = jnp.pad(k, pad)
        v = jnp.pad(v, pad)
    qbh = q.transpose(0, 2, 1, 3).reshape(B * Hq, S_pad, D)
    kbh = k.transpose(0, 2, 1, 3).reshape(B * Hq, S_pad, D)
    vbh = v.transpose(0, 2, 1, 3).reshape(B * Hq, S_pad, D)
    obh = _flash_bh(qbh, kbh, vbh, causal, block_q, block_k, interpret, S)
    return obh.reshape(B, Hq, S_pad, D).transpose(0, 2, 1, 3)[:, :S]
