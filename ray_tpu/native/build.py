"""Build native components (g++ → .so), cached by source hash.

The reference builds its native runtime with bazel (src/ray/BUILD.bazel); here a
single translation unit per component keeps the toolchain to `g++ -shared`.
"""

from __future__ import annotations

import hashlib
import os
import subprocess
import sys

_NATIVE_DIR = os.path.dirname(os.path.abspath(__file__))


def build_library(name: str, extra_flags: list[str] | None = None,
                  sanitize: str | None = None) -> str:
    """Compile ray_tpu/native/<name>.cpp to a cached .so; returns its path.

    ``sanitize`` in {"address", "thread"} builds an instrumented variant
    (reference: the TSAN/ASAN bazel configs, .bazelrc:119-139) — the store's
    race/leak surface is its shared header mutex + arena bookkeeping, which
    the sanitizer stress harness (tests/test_sanitizers.py) drives hard.
    The instrumented .so must be loaded with the matching runtime preloaded
    (see sanitizer_env())."""
    src = os.path.join(_NATIVE_DIR, f"{name}.cpp")
    with open(src, "rb") as f:
        digest = hashlib.sha256(f.read()).hexdigest()[:16]
    tag = f"-{sanitize}" if sanitize else ""
    out = os.path.join(_NATIVE_DIR, f"lib{name}{tag}-{digest}.so")
    if os.path.exists(out):
        return out
    san_flags = []
    if sanitize:
        if sanitize not in ("address", "thread"):
            raise ValueError(f"unknown sanitizer {sanitize!r}")
        san_flags = [f"-fsanitize={sanitize}", "-g", "-fno-omit-frame-pointer"]
    cmd = [
        "g++", "-O1" if sanitize else "-O2", "-std=c++17", "-shared", "-fPIC",
        *san_flags, "-o", out, src, "-lpthread", "-lrt",
    ] + (extra_flags or [])
    try:
        subprocess.run(cmd, check=True, capture_output=True, text=True)
    except subprocess.CalledProcessError as e:
        raise RuntimeError(f"native build failed for {name}:\n{e.stderr}") from e
    # clean stale builds of the SAME variant only: after the prefix there
    # must be just a digest (a '-' would mean a different variant's tag,
    # e.g. plain 'libx-' also prefixes 'libx-address-...')
    prefix = f"lib{name}{tag}-"
    for f in os.listdir(_NATIVE_DIR):
        rest = f[len(prefix):-3] if f.endswith(".so") else ""
        if (f.startswith(prefix) and f != os.path.basename(out)
                and rest and "-" not in rest):
            try:
                os.unlink(os.path.join(_NATIVE_DIR, f))
            except OSError:
                pass
    return out


def sanitizer_env(sanitize: str) -> dict:
    """Env for a python subprocess that dlopens a sanitized .so: the matching
    runtime must be preloaded (the host interpreter isn't instrumented)."""
    lib = {"address": "libasan.so", "thread": "libtsan.so"}[sanitize]
    path = subprocess.run(["gcc", f"-print-file-name={lib}"],
                          capture_output=True, text=True).stdout.strip()
    if not path or not os.path.exists(path):
        raise FileNotFoundError(f"{lib} not found (gcc sanitizer runtime)")
    env = dict(os.environ)
    env["LD_PRELOAD"] = path
    # leak checking sees the whole (uninstrumented) interpreter — noise only
    env["ASAN_OPTIONS"] = "detect_leaks=0:abort_on_error=0:exitcode=66"
    env["TSAN_OPTIONS"] = "halt_on_error=0:exitcode=66:report_signal_unsafe=0"
    return env
