"""Build native components (g++ → .so), cached by source hash.

The reference builds its native runtime with bazel (src/ray/BUILD.bazel); here a
single translation unit per component keeps the toolchain to `g++ -shared`.
"""

from __future__ import annotations

import hashlib
import os
import subprocess
import sys

_NATIVE_DIR = os.path.dirname(os.path.abspath(__file__))


def build_library(name: str, extra_flags: list[str] | None = None) -> str:
    """Compile ray_tpu/native/<name>.cpp to a cached .so; returns its path."""
    src = os.path.join(_NATIVE_DIR, f"{name}.cpp")
    with open(src, "rb") as f:
        digest = hashlib.sha256(f.read()).hexdigest()[:16]
    out = os.path.join(_NATIVE_DIR, f"lib{name}-{digest}.so")
    if os.path.exists(out):
        return out
    cmd = [
        "g++", "-O2", "-std=c++17", "-shared", "-fPIC",
        "-o", out, src, "-lpthread", "-lrt",
    ] + (extra_flags or [])
    try:
        subprocess.run(cmd, check=True, capture_output=True, text=True)
    except subprocess.CalledProcessError as e:
        raise RuntimeError(f"native build failed for {name}:\n{e.stderr}") from e
    # clean stale builds
    for f in os.listdir(_NATIVE_DIR):
        if f.startswith(f"lib{name}-") and f != os.path.basename(out):
            try:
                os.unlink(os.path.join(_NATIVE_DIR, f))
            except OSError:
                pass
    return out
