// Shared-memory object store: the node-local object plane.
//
// TPU-native equivalent of the reference's Plasma store
// (src/ray/object_manager/plasma/: PlasmaStore store.h:55, PlasmaAllocator +
// dlmalloc.cc, ObjectLifecycleManager, LRU EvictionPolicy eviction_policy.h,
// CreateRequestQueue backpressure). Semantics preserved, mechanism re-designed:
//
// - Instead of a store *process* serving clients over a unix socket with fd
//   passing (plasma's fling.cc SCM_RIGHTS), the arena AND its metadata live in
//   one POSIX shm segment that every worker process maps directly. All
//   bookkeeping (object table, free list, LRU) is inside the segment, guarded
//   by a process-shared mutex — create/seal/get are a few hundred ns with zero
//   syscalls or copies on the hot path.
// - Objects are immutable after seal (plasma's create→seal→get lifecycle).
// - Refcounted pins (plasma client Release); eviction is LRU over sealed,
//   unpinned objects (eviction_policy.h) triggered on allocation pressure.
// - Blocking gets use a process-shared condvar (plasma's GetRequestQueue).
//
// C ABI for ctypes; no C++ symbols exported.
//
// Layout of the segment:
//   [Header | ObjectEntry table (cap slots) | data arena (free-list allocated)]

#include <atomic>
#include <cerrno>
#include <cstdint>
#include <cstring>
#include <ctime>

#include <fcntl.h>
#include <pthread.h>
#include <signal.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <sys/types.h>
#include <unistd.h>

namespace {

constexpr uint64_t kMagic = 0x5241595f54505553ull;  // "RAY_TPUS"
constexpr uint32_t kIdSize = 28;                    // ObjectID bytes (ids.py)
constexpr uint32_t kAlign = 64;                     // cacheline-align payloads

enum ObjState : uint32_t {
  OBJ_FREE = 0,
  OBJ_CREATING = 1,
  OBJ_SEALED = 2,
  OBJ_DELETING = 3,   // delete requested while pinned; freed on last release
  OBJ_TOMBSTONE = 4,  // deleted slot: keeps linear-probe chains intact
};

struct ObjectEntry {
  uint8_t id[kIdSize];
  uint64_t offset;     // payload offset from segment base
  uint64_t size;       // payload size
  uint32_t state;
  int32_t pins;        // client pin count (get without release)
  uint64_t lru_tick;   // last access tick for eviction
  uint64_t create_us;  // creation timestamp
  int32_t writer_pid;  // pid of the CREATING writer (orphan detection)
};

struct FreeNode {   // lives inside the data arena
  uint64_t size;    // bytes including this node header
  uint64_t next;    // offset of next free node (0 = none)
};

struct Header {
  uint64_t magic;
  uint64_t segment_size;
  uint64_t table_off;
  uint32_t table_cap;
  uint64_t arena_off;
  uint64_t arena_size;
  uint64_t free_head;  // offset of first FreeNode (0 = none)
  uint64_t lru_clock;
  uint64_t bytes_in_use;
  uint64_t num_objects;
  uint64_t evictions;
  pthread_mutex_t mu;
  pthread_cond_t cv;
};

struct Store {  // per-process view
  void* base;
  Header* hdr;
  ObjectEntry* table;
  int fd;
};

uint64_t align_up(uint64_t v, uint64_t a) { return (v + a - 1) & ~(a - 1); }

uint64_t hash_id(const uint8_t* id) {
  // FNV-1a over the id bytes
  uint64_t h = 1469598103934665603ull;
  for (uint32_t i = 0; i < kIdSize; i++) h = (h ^ id[i]) * 1099511628211ull;
  return h;
}

ObjectEntry* find_slot(Store* s, const uint8_t* id, bool for_insert) {
  Header* h = s->hdr;
  uint64_t cap = h->table_cap;
  uint64_t idx = hash_id(id) % cap;
  ObjectEntry* first_reusable = nullptr;  // first TOMBSTONE seen (insert target)
  for (uint64_t probe = 0; probe < cap; probe++) {
    ObjectEntry* e = &s->table[(idx + probe) % cap];
    if (e->state == OBJ_FREE) {
      // chain end: never-used slot
      if (for_insert) return first_reusable ? first_reusable : e;
      return nullptr;
    }
    if (e->state == OBJ_TOMBSTONE) {
      if (!first_reusable) first_reusable = e;
      continue;  // deleted slot: probe past it (chain continues)
    }
    if (memcmp(e->id, id, kIdSize) == 0) return e;
  }
  return for_insert ? first_reusable : nullptr;
}

// --- free-list allocator (first fit with coalescing on free) ---
uint64_t arena_alloc(Header* h, void* base, uint64_t want) {
  want = align_up(want + sizeof(uint64_t), kAlign);  // prefix stores chunk size
  uint64_t prev_off = 0;
  uint64_t cur = h->free_head;
  while (cur) {
    FreeNode* node = (FreeNode*)((char*)base + cur);
    if (node->size >= want) {
      uint64_t remaining = node->size - want;
      if (remaining >= sizeof(FreeNode) + kAlign) {
        // split: tail stays free
        uint64_t tail_off = cur + want;
        FreeNode* tail = (FreeNode*)((char*)base + tail_off);
        tail->size = remaining;
        tail->next = node->next;
        if (prev_off) ((FreeNode*)((char*)base + prev_off))->next = tail_off;
        else h->free_head = tail_off;
      } else {
        want = node->size;  // take the whole chunk
        if (prev_off) ((FreeNode*)((char*)base + prev_off))->next = node->next;
        else h->free_head = node->next;
      }
      *(uint64_t*)((char*)base + cur) = want;  // chunk size prefix
      h->bytes_in_use += want;
      return cur + sizeof(uint64_t);  // payload offset
    }
    prev_off = cur;
    cur = node->next;
  }
  return 0;  // out of memory
}

void arena_free(Header* h, void* base, uint64_t payload_off) {
  uint64_t chunk_off = payload_off - sizeof(uint64_t);
  uint64_t chunk_size = *(uint64_t*)((char*)base + chunk_off);
  h->bytes_in_use -= chunk_size;
  // insert sorted by offset, coalesce neighbors
  uint64_t prev = 0, cur = h->free_head;
  while (cur && cur < chunk_off) {
    prev = cur;
    cur = ((FreeNode*)((char*)base + cur))->next;
  }
  FreeNode* node = (FreeNode*)((char*)base + chunk_off);
  node->size = chunk_size;
  node->next = cur;
  if (prev) ((FreeNode*)((char*)base + prev))->next = chunk_off;
  else h->free_head = chunk_off;
  // coalesce with next
  if (cur && chunk_off + node->size == cur) {
    FreeNode* nx = (FreeNode*)((char*)base + cur);
    node->size += nx->size;
    node->next = nx->next;
  }
  // coalesce with prev
  if (prev) {
    FreeNode* pv = (FreeNode*)((char*)base + prev);
    if (prev + pv->size == chunk_off) {
      pv->size += node->size;
      pv->next = node->next;
    }
  }
}

void free_entry_locked(Store* s, ObjectEntry* e) {
  Header* h = s->hdr;
  arena_free(h, s->base, e->offset);
  e->state = OBJ_TOMBSTONE;  // preserve probe chains (see find_slot)
  memset(e->id, 0, kIdSize);
  e->pins = 0;
  e->writer_pid = 0;
  h->num_objects--;
  // If the slot after this one is FREE, no probe chain continues through it:
  // convert the tombstone run ending here back to FREE so absent-key lookups
  // don't degrade to full-table scans in long sessions.
  uint64_t cap = h->table_cap;
  uint64_t idx = (uint64_t)(e - s->table);
  if (s->table[(idx + 1) % cap].state == OBJ_FREE) {
    uint64_t i = idx;
    while (s->table[i].state == OBJ_TOMBSTONE) {
      s->table[i].state = OBJ_FREE;
      i = (i + cap - 1) % cap;
      if (i == idx) break;  // wrapped: entire table was tombstones
    }
  }
}

// Is the recorded writer of a CREATING entry still alive? EPERM counts as
// alive (process exists under another uid); only ESRCH proves death. Our own
// pid is alive too: another THREAD of this process may be mid-memcpy on the
// entry — reclaiming it would free the chunk under that live writer.
bool writer_alive(int32_t pid) {
  if (pid <= 0) return false;
  if ((pid_t)pid == getpid()) return true;
  return kill((pid_t)pid, 0) == 0 || errno == EPERM;
}

// Evict least-recently-used sealed unpinned objects until an allocation of
// `need` bytes would succeed. Returns number evicted. Mutex held by caller.
int evict_lru(Store* s, uint64_t need) {
  Header* h = s->hdr;
  int evicted = 0;
  while (true) {
    uint64_t off = arena_alloc(h, s->base, need);
    if (off) {
      arena_free(h, s->base, off);  // probe only; caller re-allocates
      return evicted;
    }
    ObjectEntry* victim = nullptr;
    for (uint32_t i = 0; i < h->table_cap; i++) {
      ObjectEntry* e = &s->table[i];
      if (e->state == OBJ_SEALED && e->pins <= 0) {
        if (!victim || e->lru_tick < victim->lru_tick) victim = e;
      }
    }
    if (!victim) return evicted;
    free_entry_locked(s, victim);
    h->evictions++;
    evicted++;
  }
}

}  // namespace

extern "C" {

// Create (or open, if exists) a store segment. Returns opaque handle or null.
void* shm_store_create(const char* name, uint64_t segment_size, uint32_t table_cap) {
  int fd = shm_open(name, O_CREAT | O_EXCL | O_RDWR, 0600);
  bool creator = fd >= 0;
  if (!creator) {
    if (errno != EEXIST) return nullptr;
    fd = shm_open(name, O_RDWR, 0600);
    if (fd < 0) return nullptr;
    // wait for creator to size it
    struct stat st;
    for (int i = 0; i < 1000; i++) {
      if (fstat(fd, &st) == 0 && (uint64_t)st.st_size >= sizeof(Header)) break;
      usleep(1000);
    }
    if (fstat(fd, &st) != 0 || st.st_size == 0) { close(fd); return nullptr; }
    segment_size = st.st_size;
  } else {
    if (ftruncate(fd, segment_size) != 0) { close(fd); shm_unlink(name); return nullptr; }
  }
  void* base = mmap(nullptr, segment_size, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  if (base == MAP_FAILED) { close(fd); return nullptr; }

  Store* s = new Store();
  s->base = base;
  s->hdr = (Header*)base;
  s->fd = fd;

  if (creator) {
    Header* h = s->hdr;
    memset(h, 0, sizeof(Header));
    h->segment_size = segment_size;
    h->table_off = align_up(sizeof(Header), kAlign);
    h->table_cap = table_cap ? table_cap : 65536;
    h->arena_off = align_up(h->table_off + (uint64_t)h->table_cap * sizeof(ObjectEntry), 4096);
    h->arena_size = segment_size - h->arena_off;
    memset((char*)base + h->table_off, 0, (uint64_t)h->table_cap * sizeof(ObjectEntry));
    FreeNode* first = (FreeNode*)((char*)base + h->arena_off);
    first->size = h->arena_size;
    first->next = 0;
    h->free_head = h->arena_off;

    pthread_mutexattr_t ma;
    pthread_mutexattr_init(&ma);
    pthread_mutexattr_setpshared(&ma, PTHREAD_PROCESS_SHARED);
    pthread_mutexattr_setrobust(&ma, PTHREAD_MUTEX_ROBUST);
    pthread_mutex_init(&h->mu, &ma);
    pthread_condattr_t ca;
    pthread_condattr_init(&ca);
    pthread_condattr_setpshared(&ca, PTHREAD_PROCESS_SHARED);
    pthread_cond_init(&h->cv, &ca);
    std::atomic_thread_fence(std::memory_order_seq_cst);
    h->magic = kMagic;  // publish
  } else {
    for (int i = 0; i < 1000 && s->hdr->magic != kMagic; i++) usleep(1000);
    if (s->hdr->magic != kMagic) { munmap(base, segment_size); close(fd); delete s; return nullptr; }
    s->table = nullptr;
  }
  s->table = (ObjectEntry*)((char*)base + s->hdr->table_off);
  return s;
}

static int lock_mu(Header* h) {
  int rc = pthread_mutex_lock(&h->mu);
  if (rc == EOWNERDEAD) {  // holder died: state is consistent enough (coarse ops)
    pthread_mutex_consistent(&h->mu);
    rc = 0;
  }
  return rc;
}

// Allocate space for object `id` of `size` bytes. Returns payload offset or 0.
// err: 0 ok, 1 exists, 2 out-of-memory (after eviction), 3 internal.
uint64_t shm_store_create_object(void* handle, const uint8_t* id, uint64_t size, int* err) {
  Store* s = (Store*)handle;
  Header* h = s->hdr;
  if (lock_mu(h) != 0) { *err = 3; return 0; }
  ObjectEntry* existing = find_slot(s, id, false);
  if (existing && existing->state != OBJ_FREE) {
    *err = 1;
    pthread_mutex_unlock(&h->mu);
    return 0;
  }
  uint64_t off = arena_alloc(h, s->base, size);
  if (!off) {
    evict_lru(s, size);
    off = arena_alloc(h, s->base, size);
  }
  if (!off) {
    *err = 2;
    pthread_mutex_unlock(&h->mu);
    return 0;
  }
  ObjectEntry* e = find_slot(s, id, true);
  if (!e) {  // table full
    arena_free(h, s->base, off);
    *err = 2;
    pthread_mutex_unlock(&h->mu);
    return 0;
  }
  memcpy(e->id, id, kIdSize);
  e->offset = off;
  e->size = size;
  e->state = OBJ_CREATING;
  e->pins = 1;  // creator holds a pin until seal+release
  e->lru_tick = ++h->lru_clock;
  e->create_us = (uint64_t)time(nullptr) * 1000000ull;
  e->writer_pid = (int32_t)getpid();
  h->num_objects++;
  *err = 0;
  pthread_mutex_unlock(&h->mu);
  return off;
}

int shm_store_seal(void* handle, const uint8_t* id) {
  Store* s = (Store*)handle;
  Header* h = s->hdr;
  if (lock_mu(h) != 0) return 3;
  ObjectEntry* e = find_slot(s, id, false);
  if (!e || e->state != OBJ_CREATING) { pthread_mutex_unlock(&h->mu); return 1; }
  e->state = OBJ_SEALED;
  e->pins -= 1;
  pthread_cond_broadcast(&h->cv);
  pthread_mutex_unlock(&h->mu);
  return 0;
}

// Get (pin) a sealed object. Blocks up to timeout_ms (-1 = forever, 0 = poll).
// Returns payload offset (size in *size_out) or 0 if absent/timeout.
uint64_t shm_store_get(void* handle, const uint8_t* id, int64_t timeout_ms, uint64_t* size_out) {
  Store* s = (Store*)handle;
  Header* h = s->hdr;
  if (lock_mu(h) != 0) return 0;
  struct timespec deadline;
  if (timeout_ms > 0) {
    clock_gettime(CLOCK_REALTIME, &deadline);
    deadline.tv_sec += timeout_ms / 1000;
    deadline.tv_nsec += (timeout_ms % 1000) * 1000000L;
    if (deadline.tv_nsec >= 1000000000L) { deadline.tv_sec++; deadline.tv_nsec -= 1000000000L; }
  }
  while (true) {
    ObjectEntry* e = find_slot(s, id, false);
    if (e && e->state == OBJ_SEALED) {
      e->pins += 1;
      e->lru_tick = ++h->lru_clock;
      *size_out = e->size;
      uint64_t off = e->offset;
      pthread_mutex_unlock(&h->mu);
      return off;
    }
    if (timeout_ms == 0) break;
    if (timeout_ms < 0) {
      pthread_cond_wait(&h->cv, &h->mu);
    } else if (pthread_cond_timedwait(&h->cv, &h->mu, &deadline) == ETIMEDOUT) {
      break;
    }
  }
  pthread_mutex_unlock(&h->mu);
  return 0;
}

// Pin a sealed object without mapping it (runtime holds one pin per live
// ObjectRef so LRU eviction never takes referenced objects — plasma's
// pin-primary-copy rule, local_object_manager.h:45). Returns 1 if pinned.
int shm_store_pin(void* handle, const uint8_t* id) {
  Store* s = (Store*)handle;
  Header* h = s->hdr;
  if (lock_mu(h) != 0) return 0;
  ObjectEntry* e = find_slot(s, id, false);
  int ok = 0;
  if (e && e->state == OBJ_SEALED) {
    e->pins += 1;
    ok = 1;
  }
  pthread_mutex_unlock(&h->mu);
  return ok;
}

int shm_store_contains(void* handle, const uint8_t* id) {
  Store* s = (Store*)handle;
  if (lock_mu(s->hdr) != 0) return 0;
  ObjectEntry* e = find_slot(s, id, false);
  int ok = (e && e->state == OBJ_SEALED) ? 1 : 0;
  pthread_mutex_unlock(&s->hdr->mu);
  return ok;
}

static void free_entry(Store* s, ObjectEntry* e) { free_entry_locked(s, e); }

// Drop one pin. If the object was delete-requested and this was the last pin,
// free it now (plasma-client Release semantics: buffers keep objects alive).
int shm_store_release(void* handle, const uint8_t* id) {
  Store* s = (Store*)handle;
  Header* h = s->hdr;
  if (lock_mu(h) != 0) return 3;
  ObjectEntry* e = find_slot(s, id, false);
  if (e && e->pins > 0) {
    e->pins -= 1;
    if (e->pins == 0 && e->state == OBJ_DELETING) {
      free_entry(s, e);
      pthread_cond_broadcast(&h->cv);
    }
  }
  pthread_mutex_unlock(&h->mu);
  return 0;
}

// Returns 0 freed/deferred, 1 absent, 2 busy (live writer mid-create), 3 lock err.
int shm_store_delete(void* handle, const uint8_t* id) {
  Store* s = (Store*)handle;
  Header* h = s->hdr;
  if (lock_mu(h) != 0) return 3;
  ObjectEntry* e = find_slot(s, id, false);
  if (!e || e->state == OBJ_FREE) { pthread_mutex_unlock(&h->mu); return 1; }
  if (e->state == OBJ_CREATING) {
    // CREATING entries can have no readers (get only returns SEALED). Reclaim
    // only when the recorded writer is verifiably dead (or is us): freeing the
    // arena chunk under a live writer mid-memcpy would corrupt whatever object
    // the allocator hands that memory to next.
    if (writer_alive(e->writer_pid)) {
      pthread_mutex_unlock(&h->mu);
      return 2;
    }
    free_entry(s, e);
  } else if (e->pins > 0) {
    e->state = OBJ_DELETING;  // invisible to get/contains; freed on last release
  } else {
    free_entry(s, e);
  }
  pthread_cond_broadcast(&h->cv);
  pthread_mutex_unlock(&h->mu);
  return 0;
}

// Abort this process's own in-progress create (failed copy, interrupted put):
// frees the CREATING entry iff we are its recorded writer. Returns 0 freed,
// 1 absent/not-creating/not-ours, 3 lock err.
int shm_store_abort(void* handle, const uint8_t* id) {
  Store* s = (Store*)handle;
  Header* h = s->hdr;
  if (lock_mu(h) != 0) return 3;
  ObjectEntry* e = find_slot(s, id, false);
  int rc = 1;
  if (e && e->state == OBJ_CREATING && (pid_t)e->writer_pid == getpid()) {
    free_entry(s, e);
    pthread_cond_broadcast(&h->cv);
    rc = 0;
  }
  pthread_mutex_unlock(&h->mu);
  return rc;
}

void* shm_store_base(void* handle) { return ((Store*)handle)->base; }

// Prefault a range of the segment so later memcpys into fresh arena space hit
// warm page tables instead of zero-fill faults (~2 GB/s cold vs ~12 GB/s warm
// measured). MADV_POPULATE_WRITE faults pages in WITHOUT altering contents, so
// it is safe to run concurrently with live writers. Returns 0 on success.
int shm_store_prefault(void* handle, uint64_t offset, uint64_t length) {
  Store* s = (Store*)handle;
  uint64_t seg = s->hdr->segment_size;
  if (offset >= seg) return 0;
  if (offset + length > seg) length = seg - offset;
#ifdef MADV_POPULATE_WRITE
  if (madvise((char*)s->base + offset, length, MADV_POPULATE_WRITE) == 0) return 0;
#endif
  // Fallback (old kernels): read-touch one byte per page. A read fault is not
  // as effective as a write fault but warms the page tables without the
  // read-modify-write race a write-touch would have against live writers.
  volatile char* p = (volatile char*)s->base + offset;
  volatile char sink = 0;
  for (uint64_t i = 0; i < length; i += 4096) sink = p[i];
  (void)sink;
  return 0;
}

void shm_store_stats(void* handle, uint64_t* out4) {
  Store* s = (Store*)handle;
  Header* h = s->hdr;
  lock_mu(h);
  out4[0] = h->num_objects;
  out4[1] = h->bytes_in_use;
  out4[2] = h->arena_size;
  out4[3] = h->evictions;
  pthread_mutex_unlock(&h->mu);
}

void shm_store_close(void* handle) {
  Store* s = (Store*)handle;
  munmap(s->base, s->hdr ? s->hdr->segment_size : 0);
  close(s->fd);
  delete s;
}

int shm_store_unlink(const char* name) { return shm_unlink(name); }

}  // extern "C"
