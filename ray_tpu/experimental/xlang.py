"""Cross-language client plane: JSON-framed TCP for non-Python clients.

Parity: the reference's cross-language surface — Java/C++ workers invoke
Python functions through language-neutral descriptors
(python/ray/cross_language.py, msgpack envelopes per
src/ray/protobuf/serialization.proto) and the C++ worker API (cpp/include/
ray/api.h). Here the neutral encoding is length-prefixed JSON (binary values
as {"__bytes__": base64}); callables are invoked by REGISTERED name, the
same "function descriptor, not pickled code" model the reference uses across
languages. The C++ client library lives in cpp/ (ray_tpu_client.hpp).

Frames: 4-byte big-endian length + JSON object. Requests carry {"id", "op",
...}; replies {"id", "result"} or {"id", "error"}.

Ops: hello{token} | call{func,args,kwargs} (submit + wait, returns the value)
| submit{func,args} -> {ref} | get{ref} | put{value} -> {ref} | free{ref}
| actor_create{cls,args} -> {actor} | actor_call{actor,method,args}
| kill_actor{actor} | list_funcs.
"""

from __future__ import annotations

import base64
import json
import socket
import threading
from typing import Any, Callable, Optional

from ray_tpu.core.wire import _LEN, MAX_FRAME

_registry: dict[str, Callable] = {}
_actor_registry: dict[str, type] = {}


def register(name: str, fn: Callable) -> None:
    """Expose a function to cross-language clients by name (the descriptor
    model: clients name code, they never ship it)."""
    _registry[name] = fn


def register_actor(name: str, cls: type) -> None:
    _actor_registry[name] = cls


def _decode(v: Any) -> Any:
    if isinstance(v, dict):
        if "__bytes__" in v and len(v) == 1:
            return base64.b64decode(v["__bytes__"])
        return {k: _decode(x) for k, x in v.items()}
    if isinstance(v, list):
        return [_decode(x) for x in v]
    return v


def _encode(v: Any) -> Any:
    import numpy as np

    if isinstance(v, (bytes, bytearray, memoryview)):
        return {"__bytes__": base64.b64encode(bytes(v)).decode()}
    if isinstance(v, np.generic):
        return v.item()
    if isinstance(v, np.ndarray):
        return [_encode(x) for x in v.tolist()]
    if isinstance(v, dict):
        return {str(k): _encode(x) for k, x in v.items()}
    if isinstance(v, (list, tuple)):
        return [_encode(x) for x in v]
    return v


class XLangServer:
    """One listener; each connection served by a reader thread. Ops execute
    through the session runtime, so cross-language tasks get the same
    scheduling/FT as Python tasks."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 token: str | None = None):
        import ray_tpu

        if not ray_tpu.is_initialized():
            raise RuntimeError("ray_tpu.init() before starting the xlang server")
        from ray_tpu.core.runtime import get_runtime

        self._rt = get_runtime()
        self.token = token if token is not None else (
            self._rt.control_plane.token if self._rt.control_plane else "")
        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind((host, port))
        self._listener.listen(16)
        self.address = "%s:%d" % self._listener.getsockname()
        self._closed = False
        self._refs: dict[str, Any] = {}  # held for the client (borrow analog)
        self._actors: dict[str, Any] = {}
        threading.Thread(target=self._accept_loop, daemon=True,
                         name="xlang-accept").start()

    # ---------------------------------------------------------------- ops
    def _op_call(self, msg):
        import ray_tpu

        fn = _registry[msg["func"]]
        args = _decode(msg.get("args") or [])
        kwargs = _decode(msg.get("kwargs") or {})
        ref = ray_tpu.remote(fn).remote(*args, **kwargs)
        return _encode(ray_tpu.get(ref, timeout=msg.get("timeout")))

    def _op_submit(self, msg):
        import ray_tpu

        fn = _registry[msg["func"]]
        ref = ray_tpu.remote(fn).remote(*_decode(msg.get("args") or []))
        rid = ref.object_id().hex()
        self._refs[rid] = ref
        return {"ref": rid}

    def _op_get(self, msg):
        import ray_tpu

        ref = self._refs.get(msg["ref"])
        if ref is None:
            raise KeyError(f"unknown ref {msg['ref']}")
        return _encode(ray_tpu.get(ref, timeout=msg.get("timeout")))

    def _op_put(self, msg):
        import ray_tpu

        ref = ray_tpu.put(_decode(msg["value"]))
        rid = ref.object_id().hex()
        self._refs[rid] = ref
        return {"ref": rid}

    def _op_free(self, msg):
        self._refs.pop(msg["ref"], None)
        return True

    def _op_actor_create(self, msg):
        import ray_tpu

        cls = _actor_registry[msg["cls"]]
        handle = ray_tpu.remote(cls).remote(*_decode(msg.get("args") or []))
        aid = handle._actor_id.hex()
        self._actors[aid] = handle
        return {"actor": aid}

    def _op_actor_call(self, msg):
        import ray_tpu

        handle = self._actors[msg["actor"]]
        method = getattr(handle, msg["method"])
        ref = method.remote(*_decode(msg.get("args") or []))
        return _encode(ray_tpu.get(ref, timeout=msg.get("timeout")))

    def _op_kill_actor(self, msg):
        import ray_tpu

        handle = self._actors.pop(msg["actor"], None)
        if handle is not None:
            ray_tpu.kill(handle)
        return True

    def _op_list_funcs(self, msg):
        return {"funcs": sorted(_registry), "actors": sorted(_actor_registry)}

    # ---------------------------------------------------------- plumbing
    def _accept_loop(self):
        while not self._closed:
            try:
                sock, _ = self._listener.accept()
            except OSError:
                return
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            threading.Thread(target=self._serve_conn, args=(sock,),
                             daemon=True, name="xlang-conn").start()

    def _recv_exact(self, sock, n):
        if n > MAX_FRAME:
            # bound honored BEFORE auth: an unauthenticated peer must not be
            # able to drive allocation with a forged length header (wire.py's
            # MAX_FRAME discipline)
            raise ConnectionError(f"frame too large: {n}")
        buf = bytearray()
        while len(buf) < n:
            chunk = sock.recv(n - len(buf))
            if not chunk:
                raise ConnectionError("closed")
            buf.extend(chunk)
        return bytes(buf)

    def _serve_conn(self, sock):
        ops = {
            "call": self._op_call, "submit": self._op_submit,
            "get": self._op_get, "put": self._op_put, "free": self._op_free,
            "actor_create": self._op_actor_create,
            "actor_call": self._op_actor_call,
            "kill_actor": self._op_kill_actor,
            "list_funcs": self._op_list_funcs,
        }
        authed = False
        try:
            while True:
                (n,) = _LEN.unpack(self._recv_exact(sock, 4))
                msg = json.loads(self._recv_exact(sock, n))
                mid = msg.get("id")
                try:
                    op = msg.get("op")
                    if op == "hello":
                        if self.token and msg.get("token") != self.token:
                            raise PermissionError("bad token")
                        authed = True
                        reply = {"id": mid, "result": {"ok": True}}
                    elif not authed:
                        raise PermissionError("hello first")
                    else:
                        reply = {"id": mid, "result": ops[op](msg)}
                except BaseException as e:  # noqa: BLE001 — ship error to client
                    reply = {"id": mid,
                             "error": f"{type(e).__name__}: {e}"}
                try:
                    blob = json.dumps(reply).encode()
                except (TypeError, ValueError) as e:
                    # result not JSON-encodable: an error reply, not a dead
                    # connection (sets, custom objects, NaN keys...)
                    blob = json.dumps({
                        "id": mid,
                        "error": f"result not JSON-serializable: {e}",
                    }).encode()
                sock.sendall(_LEN.pack(len(blob)) + blob)
        except (ConnectionError, OSError, json.JSONDecodeError):
            pass
        finally:
            try:
                sock.close()
            except OSError:
                pass

    def close(self):
        self._closed = True
        try:
            self._listener.close()
        except OSError:
            pass


def serve(host: str = "127.0.0.1", port: int = 0,
          token: Optional[str] = None) -> XLangServer:
    """Start the cross-language endpoint for this session."""
    return XLangServer(host, port, token)
