"""Cross-language client plane: non-Python clients on the NATIVE wire.

Parity: the reference's cross-language surface — Java/C++ workers invoke
Python functions through language-neutral descriptors
(python/ray/cross_language.py, msgpack envelopes per
src/ray/protobuf/serialization.proto) and the C++ worker API
(cpp/include/ray/api.h). Callables are invoked by REGISTERED name — the
"function descriptor, not pickled code" model the reference uses across
languages.

Historically this module ran a separate JSON-framed TCP endpoint. That
side-channel is gone: the ``xl_*`` ops are numbered, versioned msgpack
schemas on the MAIN control plane (core/rpc/schema.py ops 41-49, served by
core/cluster.py), so a C++ client (cpp/ray_tpu_client.hpp) authenticates
with the session token and speaks the same framed protocol as Python
workers — version negotiation, retry semantics, and all. Values are
msgpack-native (bytes travel as bin, no base64 envelope); this module keeps
the registry and the numpy-aware value codec.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

_registry: dict[str, Callable] = {}
_actor_registry: dict[str, type] = {}


def register(name: str, fn: Callable) -> None:
    """Expose a function to cross-language clients by name (the descriptor
    model: clients name code, they never ship it)."""
    _registry[name] = fn


def register_actor(name: str, cls: type) -> None:
    _actor_registry[name] = cls


def lookup(name: str) -> Callable:
    fn = _registry.get(name)
    if fn is None:
        raise KeyError(f"unknown xlang function {name!r} "
                       f"(registered: {sorted(_registry)})")
    return fn


def lookup_actor(name: str) -> type:
    cls = _actor_registry.get(name)
    if cls is None:
        raise KeyError(f"unknown xlang actor {name!r} "
                       f"(registered: {sorted(_actor_registry)})")
    return cls


def _decode(v: Any) -> Any:
    """Wire value -> Python. msgpack gives us native types; kept as a hook
    (and for the legacy {"__bytes__": b64} envelope older clients send)."""
    if isinstance(v, dict):
        if "__bytes__" in v and len(v) == 1:
            import base64

            return base64.b64decode(v["__bytes__"])
        return {k: _decode(x) for k, x in v.items()}
    if isinstance(v, list):
        return [_decode(x) for x in v]
    return v


def _encode(v: Any) -> Any:
    """Python value -> msgpack-native wire value (numpy flattened; tuples
    become lists; bytes pass through as bin)."""
    import numpy as np

    if isinstance(v, (bytes, bytearray, memoryview)):
        return bytes(v)
    if isinstance(v, np.generic):
        return v.item()
    if isinstance(v, np.ndarray):
        return [_encode(x) for x in v.tolist()]
    if isinstance(v, dict):
        return {str(k): _encode(x) for k, x in v.items()}
    if isinstance(v, (list, tuple)):
        return [_encode(x) for x in v]
    return v


class XLangEndpoint:
    """Handle for the cross-language surface of a live session: the address
    + token a non-Python client needs. The ops are served by the session's
    control plane itself; close() is retained for API compatibility and
    drops nothing but this handle."""

    def __init__(self, control_plane):
        self._cp = control_plane
        self.address = control_plane.address
        self.token = control_plane.token

    def close(self) -> None:
        pass  # the control plane outlives the xlang handle


def serve(host: str = "127.0.0.1", port: int = 0,
          token: Optional[str] = None) -> XLangEndpoint:
    """Return the session's cross-language endpoint (the control plane).

    ``host``/``port``/``token`` parameters are legacy: the endpoint now IS
    the control plane, whose bind address/token are fixed at init. Passing
    non-defaults is loudly ignored — clients must use the returned handle's
    ``address``/``token``, not values they configured here."""
    import logging

    if host != "127.0.0.1" or port != 0 or token is not None:
        logging.getLogger("ray_tpu").warning(
            "xlang.serve(host/port/token) is ignored: the cross-language "
            "endpoint is the session control plane; point clients at the "
            "returned handle's .address/.token (got host=%r port=%r "
            "token=%s)", host, port, "<set>" if token else None)
    import ray_tpu

    if not ray_tpu.is_initialized():
        raise RuntimeError("ray_tpu.init() before starting the xlang server")
    from ray_tpu.core.runtime import get_runtime

    rt = get_runtime()
    if rt.control_plane is None:
        raise RuntimeError("session has no control plane; xlang unavailable")
    return XLangEndpoint(rt.control_plane)
