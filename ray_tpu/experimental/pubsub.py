"""Public pub/sub surface: publish/subscribe on control-plane channels.

Parity: src/ray/pubsub (Publisher/Subscriber) + the GCS channels of
protobuf/pubsub.proto. Works from the driver (direct queues) and from inside
worker processes (pushed over the control plane). The runtime itself
publishes lifecycle events on the "actors" and "nodes" channels.
"""

from __future__ import annotations

from typing import Any

from ray_tpu.core import runtime as rt_mod
from ray_tpu.core.pubsub import Subscriber


def publish(channel: str, message: Any, retain: bool = False) -> int:
    """Deliver `message` to every subscriber of `channel`; returns count.

    ``retain=True`` keeps the message as the channel's last-value cache:
    future subscribers receive it immediately on subscribe (routing epochs
    use this so a freshly placed ingress serves from its first request).
    Retention is a head-side property — worker publishers fall back to a
    plain publish rather than growing the wire protocol a new op.
    """
    rt = rt_mod.get_runtime()
    if hasattr(rt, "publisher"):
        return rt.publisher.publish(channel, message, retain=retain)
    return rt.publish(channel, message)  # worker client runtime


def subscribe(channel: str) -> Subscriber:
    """Subscribe to `channel`; poll() the returned Subscriber for messages."""
    rt = rt_mod.get_runtime()
    if hasattr(rt, "publisher"):
        return rt.publisher.subscribe(channel)
    return rt.subscribe(channel)  # worker client runtime
