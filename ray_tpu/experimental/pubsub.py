"""Public pub/sub surface: publish/subscribe on control-plane channels.

Parity: src/ray/pubsub (Publisher/Subscriber) + the GCS channels of
protobuf/pubsub.proto. Works from the driver (direct queues) and from inside
worker processes (pushed over the control plane). The runtime itself
publishes lifecycle events on the "actors" and "nodes" channels.
"""

from __future__ import annotations

from typing import Any

from ray_tpu.core import runtime as rt_mod
from ray_tpu.core.pubsub import Subscriber


def publish(channel: str, message: Any) -> int:
    """Deliver `message` to every subscriber of `channel`; returns count."""
    rt = rt_mod.get_runtime()
    if hasattr(rt, "publisher"):
        return rt.publisher.publish(channel, message)
    return rt.publish(channel, message)  # worker client runtime


def subscribe(channel: str) -> Subscriber:
    """Subscribe to `channel`; poll() the returned Subscriber for messages."""
    rt = rt_mod.get_runtime()
    if hasattr(rt, "publisher"):
        return rt.publisher.subscribe(channel)
    return rt.subscribe(channel)  # worker client runtime
