"""Device-tensor transport (RDT equivalent).

Parity: the reference's `ray.experimental` RDT / GPU-object path — GPU
tensors move actor-to-actor without a plasma round-trip (collective or
p2p transports chosen per topology). The TPU-native mapping:

- **On-chip (in-process)**: one process owns each chip, so every thread
  actor / compiled-graph stage in that process shares the chip.
  `ray_tpu.put(jax_array)` keeps the buffer DEVICE-RESIDENT and consumers
  receive the same `jax.Array` by reference — zero copies, zero host
  traffic (runtime._store_value's device branch).
- **Cross-chip (one jitted program)**: collectives belong to XLA — shard
  over a Mesh and let `psum`/`ppermute` ride ICI. RDT's collective
  transport has no user-level equivalent here BY DESIGN (SURVEY §2.6).
- **Cross-process / cross-host**: arg marshaling and client gets serialize
  through `_to_host` at the boundary — the DCN path, paid only when a
  device object actually leaves the process (e.g. the paged-KV handoff in
  serve/pd.py ships KV blocks this way).

This module is the thin API + introspection over that behavior.
"""

from __future__ import annotations

from typing import Any

import ray_tpu
from ray_tpu.core.object_ref import ObjectRef


def device_put(value: Any) -> ObjectRef:
    """Store a device array without leaving the device (alias of
    `ray_tpu.put` — the runtime keeps ACCELERATOR-backed jax.Arrays resident
    automatically; CPU-backed arrays take the normal shm path, where a host
    snapshot is strictly better). This name documents intent at call sites.

    ALIASING: the stored object IS the caller's buffer — no snapshot is
    taken. Donating the array to a jitted call (donate_argnums) after
    putting it invalidates the stored object ("Array has been deleted" on
    get). Snapshot first (`jnp.copy`) if the buffer will be donated."""
    return ray_tpu.put(value)


def is_device_resident(ref: ObjectRef) -> bool:
    """True if the object is held as a live device buffer (in-process
    reference), False if it lives in shm/host memory."""
    from ray_tpu.core.runtime import _is_device_array, get_runtime

    obj = get_runtime().memory_store.get_if_exists(ref.object_id())
    return obj is not None and _is_device_array(obj.value)


# --------------------------------------------------------------------------
# Cross-process device transport (reference: experimental/rdt/
# nixl_tensor_transport.py — GPU tensors move producer->consumer over
# NIXL/RDMA without a host bounce). The jax-native equivalent is
# jax.experimental.transfer: a per-process DCN transfer server; the producer
# offers a pytree of device arrays under a uuid, the consumer pulls them
# straight into ITS device memory. Only a tiny TICKET (uuid + address +
# shapes) crosses the control plane — no pickled tensor bytes.
# --------------------------------------------------------------------------

import itertools
import os
import threading

_xfer = {"server": None, "conns": {}, "uuid": itertools.count(1),
         "lock": threading.Lock()}


def _transfer_server():
    """This process's transfer server, started lazily on its default jax
    backend. Bind/advertise host comes from RAY_TPU_TRANSFER_HOST (loopback
    default; set a routable address for multi-host PD)."""
    with _xfer["lock"]:
        if _xfer["server"] is None:
            import jax
            from jax.experimental import transfer

            host = os.environ.get("RAY_TPU_TRANSFER_HOST", "127.0.0.1")
            client = jax.devices()[0].client
            # transport_addresses carries the BULK data channels; without it
            # cross-process pulls fail with "Connection closed recv() == 0"
            _xfer["server"] = transfer.start_transfer_server(
                client, f"{host}:0", [f"{host}:0"])
        return _xfer["server"]


def offer_device(tree: Any) -> dict:
    """Make a pytree of device arrays pullable by a remote process; returns
    a small picklable ticket. The arrays stay pinned by the transfer server
    until pulled exactly once (pull-based, like the reference's NIXL
    descriptors — the consumer initiates the move).

    LIMITATION: jax's transfer server exposes no cancellation, so a ticket
    the consumer never pulls pins its arrays for the producer process's
    lifetime. Offer only when a pull is imminent (e.g. the PD handoff offers
    after prefill and the decode side pulls before any failable validation
    it can do earlier)."""
    import jax

    srv = _transfer_server()
    uid = next(_xfer["uuid"])
    leaves, treedef = jax.tree.flatten(tree)
    srv.await_pull(uid, leaves)
    import cloudpickle

    return {
        "kind": "jax_transfer",
        "uuid": uid,
        "address": srv.address(),
        "specs": [(tuple(x.shape), str(x.dtype)) for x in leaves],
        "treedef": cloudpickle.dumps(treedef),
        "nbytes": int(sum(x.size * x.dtype.itemsize for x in leaves)),
    }


def pull_device(ticket: dict) -> Any:
    """Fetch an offered pytree into THIS process's device memory (device→
    device over the transfer connection; no host pickle)."""
    import cloudpickle
    import jax
    import numpy as np
    from jax.sharding import SingleDeviceSharding

    srv = _transfer_server()
    addr = ticket["address"]
    with _xfer["lock"]:
        conn = _xfer["conns"].get(addr)
        if conn is None:
            conn = _xfer["conns"][addr] = srv.connect(addr)
    dev = jax.devices()[0]
    specs = [
        jax.ShapeDtypeStruct(shape, np.dtype(dt),
                             sharding=SingleDeviceSharding(dev))
        for shape, dt in ticket["specs"]
    ]
    leaves = conn.pull(ticket["uuid"], specs)
    treedef = cloudpickle.loads(ticket["treedef"])
    return treedef.unflatten(leaves)
