"""Device-tensor transport (RDT equivalent).

Parity: the reference's `ray.experimental` RDT / GPU-object path — GPU
tensors move actor-to-actor without a plasma round-trip (collective or
p2p transports chosen per topology). The TPU-native mapping:

- **On-chip (in-process)**: one process owns each chip, so every thread
  actor / compiled-graph stage in that process shares the chip.
  `ray_tpu.put(jax_array)` keeps the buffer DEVICE-RESIDENT and consumers
  receive the same `jax.Array` by reference — zero copies, zero host
  traffic (runtime._store_value's device branch).
- **Cross-chip (one jitted program)**: collectives belong to XLA — shard
  over a Mesh and let `psum`/`ppermute` ride ICI. RDT's collective
  transport has no user-level equivalent here BY DESIGN (SURVEY §2.6).
- **Cross-process / cross-host**: arg marshaling and client gets serialize
  through `_to_host` at the boundary — the DCN path, paid only when a
  device object actually leaves the process (e.g. the paged-KV handoff in
  serve/pd.py ships KV blocks this way).

This module is the thin API + introspection over that behavior.
"""

from __future__ import annotations

from typing import Any

import ray_tpu
from ray_tpu.core.object_ref import ObjectRef


def device_put(value: Any) -> ObjectRef:
    """Store a device array without leaving the device (alias of
    `ray_tpu.put` — the runtime keeps ACCELERATOR-backed jax.Arrays resident
    automatically; CPU-backed arrays take the normal shm path, where a host
    snapshot is strictly better). This name documents intent at call sites.

    ALIASING: the stored object IS the caller's buffer — no snapshot is
    taken. Donating the array to a jitted call (donate_argnums) after
    putting it invalidates the stored object ("Array has been deleted" on
    get). Snapshot first (`jnp.copy`) if the buffer will be donated."""
    return ray_tpu.put(value)


def is_device_resident(ref: ObjectRef) -> bool:
    """True if the object is held as a live device buffer (in-process
    reference), False if it lives in shm/host memory."""
    from ray_tpu.core.runtime import _is_device_array, get_runtime

    obj = get_runtime().memory_store.get_if_exists(ref.object_id())
    return obj is not None and _is_device_array(obj.value)
