"""Internal KV: namespaced key-value store on the control plane.

Parity: python/ray/experimental/internal_kv.py (+ gcs_kv_manager.cc backing):
_internal_kv_get/put/del/exists/keys with namespaces. Backs function/config
storage the way the reference's GCS KV backs runtime-env packages and cluster
config.
"""

from __future__ import annotations

import threading
from typing import Optional

_store: dict[tuple[bytes, bytes], bytes] = {}
_lock = threading.Lock()


_NO_NAMESPACE = b"\x00__none__"  # distinct from any user namespace (incl. "default")


def _key(key: bytes | str, namespace: bytes | str | None) -> tuple[bytes, bytes]:
    if not isinstance(key, (str, bytes)):
        raise TypeError(f"key must be str or bytes, got {type(key)}")
    k = key.encode() if isinstance(key, str) else key
    ns = _NO_NAMESPACE if namespace is None else namespace
    ns = ns.encode() if isinstance(ns, str) else ns
    return (ns, k)


def _persist():
    from ray_tpu._private import persistence

    return persistence.get_store()


def _load_snapshot(snapshot: dict) -> None:
    """Restore-from-durable-store path (reference: GCS tables reloaded from
    Redis on restart, redis_store_client.h)."""
    with _lock:
        _store.update(snapshot)


def _internal_kv_put(key, value, overwrite: bool = True, namespace=None) -> bool:
    """Returns True if the key already existed (reference semantics)."""
    if not isinstance(value, (str, bytes)):
        raise TypeError(f"value must be str or bytes, got {type(value)}")
    v = value.encode() if isinstance(value, str) else value
    with _lock:
        fk = _key(key, namespace)
        existed = fk in _store
        if existed and not overwrite:
            return True
        _store[fk] = v
        # persist UNDER the lock: durable order must match in-memory order or
        # a restart can restore a stale value over a newer one
        p = _persist()
        if p is not None:
            p.kv_put(fk, v)
    return existed


def _internal_kv_get(key, namespace=None) -> Optional[bytes]:
    with _lock:
        return _store.get(_key(key, namespace))


def _internal_kv_exists(key, namespace=None) -> bool:
    with _lock:
        return _key(key, namespace) in _store


def _internal_kv_del(key, del_by_prefix: bool = False, namespace=None) -> int:
    with _lock:
        if del_by_prefix:
            ns, p = _key(key, namespace)
            victims = [fk for fk in _store if fk[0] == ns and fk[1].startswith(p)]
            for fk in victims:
                del _store[fk]
        else:
            fk = _key(key, namespace)
            victims = [fk] if _store.pop(fk, None) is not None else []
        p2 = _persist()
        if p2 is not None and victims:
            p2.kv_del(victims)
    return len(victims)


def _internal_kv_list(prefix, namespace=None) -> list[bytes]:
    ns, p = _key(prefix, namespace)
    with _lock:
        return [k for (n, k) in _store if n == ns and k.startswith(p)]


def _internal_kv_reset() -> None:
    with _lock:
        _store.clear()
