"""Cluster test utility: build multi-node sessions programmatically.

Parity: python/ray/cluster_utils.py (Cluster :141, add_node :208) — the
reference's single most load-bearing test asset (SURVEY §4): simulate
multi-node scheduling/FT behavior without real machines. Nodes come in two
flavors:

- logical nodes: extra entries in the head scheduler's resource view (fast,
  for scheduling-policy tests), and
- real nodes (``real_process=True``): a node-agent OS process that registers
  over the TCP control plane, runs its own worker pool, and can be killed
  with SIGKILL to exercise node-death fault tolerance — the analog of the
  reference spawning extra raylets on one machine.
"""

from __future__ import annotations

import os
import signal
import time
from typing import Optional

import ray_tpu
from ray_tpu._private.ids import NodeID
from ray_tpu.core.runtime import get_runtime


class Cluster:
    def __init__(self, initialize_head: bool = True, head_node_args: dict | None = None):
        self._node_ids: list[NodeID] = []
        self._agent_procs: dict[NodeID, "object"] = {}
        if initialize_head:
            args = dict(head_node_args or {})
            if not ray_tpu.is_initialized():
                ray_tpu.init(num_cpus=args.get("num_cpus", 4),
                             resources=args.get("resources"),
                             ignore_reinit_error=True)
            self._node_ids = [n.node_id for n in get_runtime().scheduler.nodes()]

    def add_node(self, *, num_cpus: float = 4, num_tpus: float = 0,
                 resources: dict | None = None, labels: dict | None = None,
                 slice_name: str | None = None,
                 ici_coords: tuple | None = None,
                 real_process: bool = False,
                 isolated_plane: bool = False,
                 timeout: float = 60.0) -> NodeID:
        """Reference: cluster_utils.py:208 add_node."""
        res = {"CPU": float(num_cpus), **(resources or {})}
        if num_tpus:
            res["TPU"] = float(num_tpus)
        if isolated_plane and not real_process:
            raise ValueError(
                "isolated_plane requires real_process=True (an in-process "
                "node has no agent to host a node-local store)"
            )
        rt = get_runtime()
        if real_process:
            from ray_tpu.core.cluster import start_node_agent

            if rt.control_plane is None:
                raise RuntimeError("control plane unavailable; cannot start node agents")
            before = {n.node_id for n in rt.scheduler.nodes()}
            proc = start_node_agent(
                rt.control_plane.address, rt.control_plane.token,
                num_cpus=num_cpus, resources=resources, labels=labels,
                slice_name=slice_name, ici_coords=ici_coords,
                isolated_plane=isolated_plane,
            )
            deadline = time.monotonic() + timeout
            while time.monotonic() < deadline:
                new = [n.node_id for n in rt.scheduler.nodes()
                       if n.node_id not in before and n.node_id in rt._agents]
                if new:
                    nid = new[0]
                    self._agent_procs[nid] = proc
                    self._node_ids.append(nid)
                    return nid
                if proc.poll() is not None:
                    raise RuntimeError(f"node agent exited rc={proc.returncode} before registering")
                time.sleep(0.05)
            proc.kill()
            raise TimeoutError("node agent did not register in time")
        nid = rt.scheduler.add_node(
            res, labels=labels, slice_name=slice_name, ici_coords=ici_coords
        )
        rt.scheduler.retry_pending_pgs()
        self._node_ids.append(nid)
        return nid

    def agent_pid(self, node_id: NodeID) -> int | None:
        proc = self._agent_procs.get(node_id)
        return proc.pid if proc is not None else None

    @staticmethod
    def _reap(proc) -> None:
        try:
            proc.wait(timeout=5)
        except Exception:
            pass

    def kill_node(self, node_id: NodeID) -> None:
        """SIGKILL a real node agent (node-death chaos; the head notices via
        socket EOF / missed heartbeats)."""
        proc = self._agent_procs.pop(node_id, None)
        if proc is None:
            raise ValueError("kill_node requires a real_process node")
        os.kill(proc.pid, signal.SIGKILL)
        self._reap(proc)
        if node_id in self._node_ids:
            self._node_ids.remove(node_id)

    def remove_node(self, node_id: NodeID) -> None:
        """Node death: resources vanish; queued work reschedules elsewhere."""
        proc = self._agent_procs.pop(node_id, None)
        if proc is not None:
            proc.terminate()
            self._reap(proc)
        get_runtime().scheduler.remove_node(node_id)
        if node_id in self._node_ids:
            self._node_ids.remove(node_id)

    @property
    def node_ids(self) -> list[NodeID]:
        return list(self._node_ids)

    def shutdown(self) -> None:
        procs = list(self._agent_procs.values())
        self._agent_procs.clear()
        for proc in procs:
            try:
                proc.terminate()
            except Exception:
                pass
        for proc in procs:
            self._reap(proc)
        ray_tpu.shutdown()
