"""Cluster test utility: build multi-node sessions programmatically.

Parity: python/ray/cluster_utils.py (Cluster :141, add_node :208) — the
reference's single most load-bearing test asset (SURVEY §4): simulate
multi-node scheduling/FT behavior without real machines. Here nodes are
logical scheduler nodes (the single-controller analog of extra raylets).
"""

from __future__ import annotations

from typing import Optional

import ray_tpu
from ray_tpu._private.ids import NodeID
from ray_tpu.core.runtime import get_runtime


class Cluster:
    def __init__(self, initialize_head: bool = True, head_node_args: dict | None = None):
        self._node_ids: list[NodeID] = []
        if initialize_head:
            args = dict(head_node_args or {})
            if not ray_tpu.is_initialized():
                ray_tpu.init(num_cpus=args.get("num_cpus", 4),
                             resources=args.get("resources"),
                             ignore_reinit_error=True)
            self._node_ids = [n.node_id for n in get_runtime().scheduler.nodes()]

    def add_node(self, *, num_cpus: float = 4, num_tpus: float = 0,
                 resources: dict | None = None, labels: dict | None = None,
                 slice_name: str | None = None,
                 ici_coords: tuple | None = None) -> NodeID:
        """Reference: cluster_utils.py:208 add_node."""
        res = {"CPU": float(num_cpus), **(resources or {})}
        if num_tpus:
            res["TPU"] = float(num_tpus)
        nid = get_runtime().scheduler.add_node(
            res, labels=labels, slice_name=slice_name, ici_coords=ici_coords
        )
        get_runtime().scheduler.retry_pending_pgs()
        self._node_ids.append(nid)
        return nid

    def remove_node(self, node_id: NodeID) -> None:
        """Node death: resources vanish; queued work reschedules elsewhere."""
        get_runtime().scheduler.remove_node(node_id)
        if node_id in self._node_ids:
            self._node_ids.remove(node_id)

    @property
    def node_ids(self) -> list[NodeID]:
        return list(self._node_ids)

    def shutdown(self) -> None:
        ray_tpu.shutdown()
