"""Dataset: lazy logical plan → streaming execution over ray_tpu tasks.

Parity: python/ray/data/dataset.py (Dataset :202, map_batches :531,
iter_batches :5981, streaming_split :2117) + read_api.py constructors +
_internal/logical planner. The logical plan is a linear op list compiled to
PhysicalOps for the streaming executor; reads are split into blocks up front
(file- or range-partitioned) so the whole pipeline streams.

TPU-first: `iter_batches(batch_format="jax", device_put=...)` moves batches
straight to HBM with jax.device_put against a sharding — the ingest path the
reference wires through iter_torch_batches+DMA instead.
"""

from __future__ import annotations

import functools
import itertools
import math
from dataclasses import dataclass, field, replace
from typing import Any, Callable, Iterable, Iterator, Optional

import numpy as np

from ray_tpu.data.block import Block, Row
from ray_tpu.data.executor import (
    ActorPoolStrategy,
    OutputSplitter,
    PhysicalOp,
    execute_streaming,
)


@dataclass(frozen=True)
class LogicalOp:
    kind: str  # map_batches | map | filter | flat_map | limit | select
    fn: Callable | None = None
    kwargs: dict = field(default_factory=dict)
    name: str = ""


class Dataset:
    def __init__(self, source_fn: Callable[[], Iterator[Block]], ops: tuple[LogicalOp, ...] = (),
                 name: str = "dataset"):
        self._source_fn = source_fn
        self._ops = ops
        self._name = name

    # ------------------------------------------------------------- transforms
    def _append(self, op: LogicalOp) -> "Dataset":
        return Dataset(self._source_fn, self._ops + (op,), self._name)

    def map_batches(self, fn: Callable, *, batch_size: int | None = None,
                    batch_format: str = "numpy", num_cpus: float = 1.0,
                    compute=None, memory_budget_bytes: int | None = None,
                    **_) -> "Dataset":
        """Reference: dataset.py:531. ``compute=ActorPoolStrategy(...)`` runs
        `fn` (a callable class is constructed once per pool actor) on
        long-lived actors; ``memory_budget_bytes`` caps the stage's in-flight
        input bytes (memory-aware backpressure)."""
        return self._append(LogicalOp("map_batches", fn,
                                      dict(batch_size=batch_size, batch_format=batch_format,
                                           num_cpus=num_cpus, compute=compute,
                                           memory_budget_bytes=memory_budget_bytes),
                                      name=getattr(fn, "__name__", "fn")))

    def map(self, fn: Callable[[Row], Row], **kw) -> "Dataset":
        return self._append(LogicalOp("map", fn, kw, name=getattr(fn, "__name__", "fn")))

    def flat_map(self, fn: Callable[[Row], list[Row]], **kw) -> "Dataset":
        return self._append(LogicalOp("flat_map", fn, kw))

    def filter(self, fn: Callable[[Row], bool], **kw) -> "Dataset":
        return self._append(LogicalOp("filter", fn, kw))

    def select_columns(self, cols: list[str]) -> "Dataset":
        return self._append(LogicalOp("select", None, dict(cols=cols)))

    def limit(self, n: int) -> "Dataset":
        return self._append(LogicalOp("limit", None, dict(n=n)))

    def random_shuffle(self, *, seed: int | None = None) -> "Dataset":
        """True global shuffle via an all-to-all exchange: rows scatter
        uniformly over partitions, each partition permutes (reference:
        random_shuffle as a full exchange, hash_shuffle.py)."""
        return self._append(LogicalOp("shuffle", None, dict(seed=seed)))

    def repartition(self, num_blocks: int) -> "Dataset":
        return self._append(LogicalOp("repartition", None, dict(num_blocks=num_blocks)))

    def sort(self, key: str, descending: bool = False) -> "Dataset":
        from ray_tpu.data.aggregate import sort as _sort

        return _sort(self, key, descending)

    def groupby(self, key: str):
        from ray_tpu.data.aggregate import GroupedData

        return GroupedData(self, key)

    def unique(self, column: str) -> list:
        from ray_tpu.data.aggregate import unique as _unique

        return _unique(self, column)

    def sum(self, column: str) -> float:
        from ray_tpu.data.aggregate import ds_sum

        return ds_sum(self, column)

    def min(self, column: str) -> float:
        from ray_tpu.data.aggregate import ds_min

        return ds_min(self, column)

    def max(self, column: str) -> float:
        from ray_tpu.data.aggregate import ds_max

        return ds_max(self, column)

    def mean(self, column: str) -> float:
        from ray_tpu.data.aggregate import ds_mean

        return ds_mean(self, column)

    def join(self, other: "Dataset", on: str, how: str = "inner",
             num_partitions: int | None = None) -> "Dataset":
        """Hash join over an all-to-all exchange (reference:
        _internal/execution/operators/join.py): both sides partition on the
        key; each partition joins independently in a task."""
        left, right = self, other

        def source():
            from ray_tpu.data.exchange import DEFAULT_PARTITIONS, join_exchange

            yield from join_exchange(
                left.iter_blocks(), right.iter_blocks(), on, how,
                num_partitions or DEFAULT_PARTITIONS,
            )

        return Dataset(source, (), f"join({self._name},{other._name})")

    def union(self, other: "Dataset") -> "Dataset":
        left, right = self, other

        def source():
            yield from left.iter_blocks()
            yield from right.iter_blocks()

        return Dataset(source, (), f"union({left._name},{right._name})")

    def zip(self, other: "Dataset") -> "Dataset":
        """Row-aligned zip: pairs row i of self with row i of other regardless of
        block boundaries (reference: dataset.zip). Truncates to the shorter side."""
        left, right = self, other

        def source():
            rstream = right.iter_blocks()
            rbuf: list[Block] = []
            rbuf_rows = 0

            def pull_right(n: int) -> Block | None:
                nonlocal rbuf_rows
                while rbuf_rows < n:
                    try:
                        b = next(rstream)
                    except StopIteration:
                        break
                    rbuf.append(b)
                    rbuf_rows += b.num_rows()
                if rbuf_rows == 0:
                    return None
                merged = Block.concat(rbuf)
                take = min(n, merged.num_rows())
                out = merged.slice(0, take)
                rest = merged.slice(take, merged.num_rows())
                rbuf.clear()
                if rest.num_rows():
                    rbuf.append(rest)
                rbuf_rows = rest.num_rows()
                return out

            for a in left.iter_blocks():
                b = pull_right(a.num_rows())
                if b is None:
                    return
                n = min(a.num_rows(), b.num_rows())
                cols = {k: v[:n] for k, v in a.columns.items()}
                for k, v in b.columns.items():
                    cols[k if k not in cols else f"{k}_1"] = v[:n]
                yield Block(cols)
                if b.num_rows() < a.num_rows():
                    return  # right exhausted

        return Dataset(source, (), f"zip({left._name},{right._name})")

    # ------------------------------------------------------------- execution
    @staticmethod
    def _compile_op(op: LogicalOp) -> PhysicalOp:
        if op.kind == "map_batches":
            compute = op.kwargs.get("compute") or "tasks"
            factory = None
            transform = None
            if isinstance(compute, ActorPoolStrategy):
                # a class UDF constructs once per pool actor; a plain callable
                # is shared as-is (reference: compute.py ActorPoolStrategy)
                fn, kw = op.fn, op.kwargs

                def factory(fn=fn, kw=kw):
                    udf = fn() if isinstance(fn, type) else fn
                    return _make_map_batches(udf, kw)

            elif isinstance(op.fn, type):
                # reference compute.py raises the same requirement: a class
                # UDF needs actor-pool compute (stateless tasks would
                # construct it per batch — or worse, WITH the batch)
                raise ValueError(
                    f"map_batches got the class {op.fn.__name__!r}; callable-"
                    "class UDFs require compute=ActorPoolStrategy(...)")
            else:
                transform = _make_map_batches(op.fn, op.kwargs)
            return PhysicalOp(f"MapBatches({op.name})",
                              transform,
                              num_cpus=op.kwargs.get("num_cpus", 1.0),
                              compute=compute,
                              transform_factory=factory,
                              memory_budget_bytes=op.kwargs.get("memory_budget_bytes"))
        if op.kind == "map":
            return PhysicalOp(f"Map({op.name})", _make_row_op(op.fn, "map"))
        if op.kind == "flat_map":
            return PhysicalOp("FlatMap", _make_row_op(op.fn, "flat_map"))
        if op.kind == "filter":
            return PhysicalOp("Filter", _make_row_op(op.fn, "filter"))
        if op.kind == "select":
            cols = op.kwargs["cols"]
            return PhysicalOp("Select", lambda b, c=cols: [b.select(c)])
        raise ValueError(f"Unknown logical op {op.kind}")

    def _iter_items(self) -> Iterator[Any]:
        """Compile the op chain in order, yielding a MIXED stream of Blocks
        (driver-local segments) and plane descriptors (outputs of remote
        segments — ``data/streaming.py`` BlockRefs). Task-parallel segments
        stream through the executor; stream-side stateful ops
        (limit/repartition) apply at their position in the chain. Consumers
        pick their edge: ``iter_blocks`` materializes payloads here,
        ``iter_block_refs`` keeps everything plane-resident."""
        from ray_tpu.data import streaming

        plane = streaming.plane_streaming_enabled()
        stream: Iterator[Any] = self._source_fn()
        segment: list[PhysicalOp] = []
        # per-execution sink, atomically rebound: concurrent iterations of the
        # same Dataset each own their list; stats() shows the latest execution
        sink: list = []
        self._last_stats = sink

        def flush(s: Iterator[Any], seg: list[PhysicalOp]) -> Iterator[Any]:
            if not seg:
                return s
            if plane:
                return streaming.execute_streaming_refs(s, seg, stats_sink=sink)
            return execute_streaming(s, seg, stats_sink=sink)

        for op in self._ops:
            if op.kind == "limit":
                stream = _limit_stream(flush(stream, segment), op.kwargs["n"])
                segment = []
            elif op.kind == "repartition":
                stream = _repartition_stream(
                    streaming.materialize(flush(stream, segment)),
                    op.kwargs["num_blocks"])
                segment = []
            elif op.kind == "shuffle":
                stream = _shuffle_stream(flush(stream, segment),
                                         op.kwargs.get("seed"), plane)
                segment = []
            else:
                segment.append(self._compile_op(op))
        yield from flush(stream, segment)

    def iter_blocks(self) -> Iterator[Block]:
        """Blocks materialized in THIS process (the consumer edge): plane
        descriptors land once via the zero-copy pull path."""
        from ray_tpu.data import streaming

        yield from streaming.materialize(self._iter_items())

    def iter_block_refs(self) -> Iterator[Any]:
        """Plane-native consumption: yields ``BlockRef`` descriptors — block
        payloads stay in the object plane (driver-produced source blocks are
        sealed into the local store). The surface streaming_split, the
        exchange, and data/llm.py batch inference feed from."""
        from ray_tpu.data import streaming

        for item in self._iter_items():
            yield streaming.ensure_ref(item)

    # ------------------------------------------------------------- consumption
    def take(self, n: int = 20) -> list[Row]:
        out: list[Row] = []
        for block in self.iter_blocks():
            for row in block.rows():
                out.append(row)
                if len(out) >= n:
                    return out
        return out

    def take_all(self) -> list[Row]:
        return [r for b in self.iter_blocks() for r in b.rows()]

    def count(self) -> int:
        # metadata-only: descriptors carry num_rows, so counting never
        # pulls a block payload into the driver
        from ray_tpu.data import streaming

        return sum(streaming.item_rows(i) for i in self._iter_items())

    def to_pandas(self):
        """Reference: Dataset.to_pandas — materialize every block into one
        DataFrame (caller asserts the result fits in driver memory)."""
        import pandas as pd

        frames = [b.to_pandas() for b in self.iter_blocks()]
        if not frames:
            return pd.DataFrame()
        return pd.concat(frames, ignore_index=True)

    def schema(self) -> dict[str, str]:
        for b in self.iter_blocks():
            return b.schema()
        return {}

    def stats(self) -> str:
        """Per-operator counters for the LAST execution of this dataset
        (reference: Dataset.stats / _internal stats.py): rows/blocks plus
        the plane-native accounting — bytes in/out, plane puts, and
        backpressure-stall seconds per operator (sourced from the ISSUE-12
        streaming instruments)."""
        rows = getattr(self, "_last_stats", [])
        if not rows:
            return "No execution stats recorded yet (run an action first)."
        return "\n".join(st.render() for st in rows)

    def materialize(self) -> "Dataset":
        blocks = list(self.iter_blocks())
        return Dataset(lambda: iter(blocks), (), self._name + ".materialized")

    def iter_rows(self) -> Iterator[Row]:
        for b in self.iter_blocks():
            yield from b.rows()

    def iter_batches(self, *, batch_size: int = 256, batch_format: str = "numpy",
                     drop_last: bool = False, device_put=None) -> Iterator[Any]:
        """Reference: dataset.py:5981. batch_format: numpy|pandas|jax.

        O(rows) batching: an offset tracks the consumed prefix of the head block;
        a batch concatenates at most the (few) blocks it actually spans.
        """
        carry: list[Block] = []  # pending blocks; carry[0] consumed up to `offset`
        offset = 0
        carried = 0  # unconsumed rows across carry

        def emit(n: int) -> Block:
            nonlocal offset, carried
            parts: list[Block] = []
            need = n
            while need > 0:
                head = carry[0]
                avail = head.num_rows() - offset
                take = min(avail, need)
                parts.append(head.slice(offset, offset + take))
                offset += take
                need -= take
                carried -= take
                if offset >= head.num_rows():
                    carry.pop(0)
                    offset = 0
            return parts[0] if len(parts) == 1 else Block.concat(parts)

        for block in self.iter_blocks():
            if block.num_rows() == 0:
                continue
            carry.append(block)
            carried += block.num_rows()
            while carried >= batch_size:
                yield _format_batch(emit(batch_size), batch_format, device_put)
        if carried and not drop_last:
            yield _format_batch(emit(carried), batch_format, device_put)

    def streaming_split(self, n: int, *, equal: bool = False,
                        prefetch_blocks: int = 4) -> list["DataIterator"]:
        """Reference: dataset.py:2117 — one iterator shard per train worker.

        The shards MUST be consumed concurrently (one consumer per shard, the
        train-worker pattern): output flows through bounded per-shard queues
        for backpressure, so draining one shard alone blocks once the others'
        queues fill — the same contract as the reference's streaming_split.

        Plane-native (default): the per-shard queues carry DESCRIPTORS and
        each consumer prefetches up to ``prefetch_blocks`` block pulls
        holder→itself (equal splits slice inside a task, sealed into the
        executing node's store) — the gang-training input pipeline where no
        block payload transits the driver and a step finds its next block
        already local (see train/ingest.py)."""
        from ray_tpu.data import streaming

        if streaming.plane_streaming_enabled():
            splitter = streaming.RefOutputSplitter(
                self._iter_items(), n, equal, queue_depth=prefetch_blocks)
        else:
            splitter = OutputSplitter(self.iter_blocks(), n, equal)
        return [DataIterator(functools.partial(splitter.iterator, i),
                             prefetch_blocks=prefetch_blocks)
                for i in range(n)]

    def split(self, n: int) -> list["Dataset"]:
        blocks = list(self.iter_blocks())
        chunks = [blocks[i::n] for i in range(n)]
        return [Dataset(lambda c=c: iter(c), (), f"{self._name}.split{i}")
                for i, c in enumerate(chunks)]

    # ------------------------------------------------------------- writes
    def write_parquet(self, path: str) -> None:
        import pyarrow.parquet as pq
        import os

        os.makedirs(path, exist_ok=True)
        for i, b in enumerate(self.iter_blocks()):
            pq.write_table(b.to_arrow(), f"{path}/part-{i:05d}.parquet")

    def write_csv(self, path: str) -> None:
        import os

        os.makedirs(path, exist_ok=True)
        for i, b in enumerate(self.iter_blocks()):
            b.to_pandas().to_csv(f"{path}/part-{i:05d}.csv", index=False)

    def write_tfrecords(self, path: str) -> None:
        """Write blocks as TFRecord shards of tf.train.Example (reference:
        Dataset.write_tfrecords; hermetic codec in data/tfrecords.py)."""
        import os

        from ray_tpu.data.tfrecords import encode_example, write_tfrecord_file

        os.makedirs(path, exist_ok=True)
        for i, b in enumerate(self.iter_blocks()):
            write_tfrecord_file(
                f"{path}/part-{i:05d}.tfrecord",
                (encode_example(row) for row in b.rows()),
            )

    def write_avro(self, path: str, *, codec: str = "null") -> None:
        """Write blocks as Avro container shards (reference: avro datasink;
        hermetic codec in data/avro.py)."""
        import os

        from ray_tpu.data.avro import write_avro_file

        os.makedirs(path, exist_ok=True)
        for i, b in enumerate(self.iter_blocks()):
            write_avro_file(f"{path}/part-{i:05d}.avro",
                            (dict(row) for row in b.rows()), codec=codec)

    def write_json(self, path: str) -> None:
        import os

        os.makedirs(path, exist_ok=True)
        for i, b in enumerate(self.iter_blocks()):
            b.to_pandas().to_json(f"{path}/part-{i:05d}.json", orient="records", lines=True)

    def __repr__(self):
        ops = " -> ".join(o.kind for o in self._ops) or "source"
        return f"Dataset({self._name}: {ops})"


class DataIterator:
    """Per-worker shard iterator (reference: data/iterator.py DataIterator).

    The wrapped stream may yield Blocks (legacy) or plane descriptors
    (plane-native streaming_split): ``iter_blocks`` runs a prefetching
    pull loop — up to ``prefetch_blocks`` async block fetches in flight,
    landing in the CONSUMING process's store — and exposes starvation
    accounting on ``last_ingest_stats`` (the gang never-starve signal,
    train/ingest.py)."""

    def __init__(self, blocks_fn: Callable[[], Iterator[Any]],
                 prefetch_blocks: int = 4):
        self._blocks_fn = blocks_fn
        self._prefetch = max(1, prefetch_blocks)
        # IngestStats of the most recent iteration (live-updated while
        # consuming): blocks/bytes/wait_s/starved_steps
        self.last_ingest_stats = None

    def iter_blocks(self) -> Iterator[Block]:
        from ray_tpu.data.streaming import PrefetchingBlockIterator

        it = PrefetchingBlockIterator(self._blocks_fn(), depth=self._prefetch)
        self.last_ingest_stats = it.stats
        return it

    def iter_batches(self, *, batch_size: int = 256, batch_format: str = "numpy",
                     drop_last: bool = False, device_put=None) -> Iterator[Any]:
        ds = Dataset(self.iter_blocks, (), "shard")
        return ds.iter_batches(batch_size=batch_size, batch_format=batch_format,
                               drop_last=drop_last, device_put=device_put)

    def iter_rows(self) -> Iterator[Row]:
        for b in self.iter_blocks():
            yield from b.rows()


# ---------------------------------------------------------------- helpers
def _format_batch(block: Block, batch_format: str, device_put) -> Any:
    if batch_format == "pandas":
        return block.to_pandas()
    batch = block.to_numpy()
    if batch_format == "jax":
        import jax

        def to_dev(v):
            if v.dtype.kind not in "biufc":
                return v  # strings/objects stay host-side numpy
            if device_put is not None:
                return jax.device_put(v, device_put)
            return jax.numpy.asarray(v)

        return {k: to_dev(v) for k, v in batch.items()}
    return batch


def _make_map_batches(fn: Callable, kwargs: dict) -> Callable[[Block], list[Block]]:
    batch_size = kwargs.get("batch_size")
    batch_format = kwargs.get("batch_format", "numpy")

    def transform(block: Block) -> list[Block]:
        def run(b: Block) -> Block:
            if batch_format == "pandas":
                out = fn(b.to_pandas())
                return Block.from_pandas(out)
            out = fn(b.to_numpy())
            if isinstance(out, dict):
                return Block.from_numpy(out)
            if isinstance(out, Block):
                return out
            raise TypeError(f"map_batches fn must return dict/DataFrame, got {type(out)}")

        if batch_size is None or block.num_rows() <= batch_size:
            return [run(block)]
        return [
            run(block.slice(i, min(i + batch_size, block.num_rows())))
            for i in range(0, block.num_rows(), batch_size)
        ]

    return transform


def _make_row_op(fn: Callable, kind: str) -> Callable[[Block], list[Block]]:
    def transform(block: Block) -> list[Block]:
        rows = list(block.rows())
        if kind == "map":
            out = [fn(r) for r in rows]
        elif kind == "flat_map":
            out = [x for r in rows for x in fn(r)]
        else:  # filter
            out = [r for r in rows if fn(r)]
        return [Block.from_rows(out)] if out else []

    return transform


def _limit_stream(stream: Iterator[Any], n: int) -> Iterator[Any]:
    """Limit over a mixed Block/BlockRef stream: whole items pass through as
    descriptors (rows counted from metadata, payload untouched); only the
    BOUNDARY block is materialized to slice it."""
    from ray_tpu.data import streaming

    remaining = n
    for item in stream:
        if remaining <= 0:
            return
        rows = streaming.item_rows(item)
        if rows <= remaining:
            remaining -= rows
            yield item
        else:
            yield streaming.fetch_block(item).slice(0, remaining)
            return


def _repartition_stream(stream: Iterator[Block], num_blocks: int) -> Iterator[Block]:
    all_blocks = Block.concat(list(stream))
    n = all_blocks.num_rows()
    if n == 0 or num_blocks <= 0:
        return
    per = max(1, math.ceil(n / num_blocks))
    for i in range(0, n, per):
        yield all_blocks.slice(i, min(i + per, n))


def _shuffle_stream(stream: Iterator[Any], seed: int | None,
                    plane: bool = True) -> Iterator[Any]:
    """Full random shuffle as an all-to-all exchange over tasks. On the
    plane path input descriptors go in and reduced-partition descriptors
    come out — shuffle bytes never touch the driver."""
    from ray_tpu.data import streaming
    from ray_tpu.data.exchange import shuffle_exchange, shuffle_refs

    if plane:
        yield from shuffle_refs(stream, seed)
    else:
        yield from shuffle_exchange(streaming.materialize(stream), seed)
