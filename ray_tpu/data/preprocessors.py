"""Preprocessors: fit/transform over Datasets.

Parity: python/ray/data/preprocessors/ — StandardScaler, MinMaxScaler,
LabelEncoder, Concatenator (the fit-statistics pattern: one pass to compute
stats, then a stateless map_batches transform).
"""

from __future__ import annotations

import numpy as np

from ray_tpu.data.dataset import Dataset


class Preprocessor:
    def fit(self, ds: Dataset) -> "Preprocessor":
        return self

    def transform(self, ds: Dataset) -> Dataset:
        raise NotImplementedError

    def fit_transform(self, ds: Dataset) -> Dataset:
        return self.fit(ds).transform(ds)


class StandardScaler(Preprocessor):
    def __init__(self, columns: list[str]):
        self.columns = columns
        self.stats_: dict[str, tuple[float, float]] = {}

    def fit(self, ds: Dataset) -> "StandardScaler":
        sums = {c: 0.0 for c in self.columns}
        sqs = {c: 0.0 for c in self.columns}
        n = 0
        for b in ds.iter_blocks():
            n += b.num_rows()
            for c in self.columns:
                v = b.columns[c].astype(np.float64)
                sums[c] += float(v.sum())
                sqs[c] += float((v * v).sum())
        for c in self.columns:
            mean = sums[c] / max(n, 1)
            var = max(sqs[c] / max(n, 1) - mean ** 2, 0.0)
            self.stats_[c] = (mean, float(np.sqrt(var)) or 1.0)
        return self

    def transform(self, ds: Dataset) -> Dataset:
        stats = dict(self.stats_)
        cols = list(self.columns)

        def scale(batch):
            out = dict(batch)
            for c in cols:
                mean, std = stats[c]
                out[c] = (batch[c].astype(np.float64) - mean) / (std or 1.0)
            return out

        return ds.map_batches(scale)


class MinMaxScaler(Preprocessor):
    def __init__(self, columns: list[str]):
        self.columns = columns
        self.stats_: dict[str, tuple[float, float]] = {}

    def fit(self, ds: Dataset) -> "MinMaxScaler":
        lo = {c: np.inf for c in self.columns}
        hi = {c: -np.inf for c in self.columns}
        for b in ds.iter_blocks():
            for c in self.columns:
                lo[c] = min(lo[c], float(b.columns[c].min()))
                hi[c] = max(hi[c], float(b.columns[c].max()))
        self.stats_ = {c: (lo[c], hi[c]) for c in self.columns}
        return self

    def transform(self, ds: Dataset) -> Dataset:
        stats = dict(self.stats_)
        cols = list(self.columns)

        def scale(batch):
            out = dict(batch)
            for c in cols:
                lo, hi = stats[c]
                span = (hi - lo) or 1.0
                out[c] = (batch[c].astype(np.float64) - lo) / span
            return out

        return ds.map_batches(scale)


class LabelEncoder(Preprocessor):
    def __init__(self, label_column: str):
        self.label_column = label_column
        self.classes_: list = []

    def fit(self, ds: Dataset) -> "LabelEncoder":
        from ray_tpu.data.aggregate import unique

        self.classes_ = unique(ds, self.label_column)
        return self

    def transform(self, ds: Dataset) -> Dataset:
        mapping = {c: i for i, c in enumerate(self.classes_)}
        col = self.label_column

        def encode(batch):
            out = dict(batch)
            out[col] = np.asarray([mapping[_item(v)] for v in batch[col]], dtype=np.int64)
            return out

        return ds.map_batches(encode)


class Concatenator(Preprocessor):
    """Merge feature columns into one float matrix column (reference:
    preprocessors/concatenator.py) — the shape models consume."""

    def __init__(self, columns: list[str], output_column_name: str = "features"):
        self.columns = columns
        self.output_column_name = output_column_name

    def transform(self, ds: Dataset) -> Dataset:
        cols = list(self.columns)
        out_col = self.output_column_name

        def concat(batch):
            stacked = np.stack([batch[c].astype(np.float64) for c in cols], axis=1)
            out = {k: v for k, v in batch.items() if k not in cols}
            out[out_col] = stacked
            return out

        return ds.map_batches(concat)


def _item(v):
    try:
        return v.item()
    except AttributeError:
        return v
