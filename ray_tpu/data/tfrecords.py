"""TFRecord file format + tf.train.Example codec — hermetic (no tensorflow).

Parity: ray.data read_tfrecords/write_tfrecords (read_api.py:2517,
_internal/datasource/tfrecords_datasource.py). The reference requires
tensorflow/crc32c at runtime; here the record framing (length + masked
crc32c) and the Example protobuf (Features -> map<string, Feature> with
bytes/float/int64 lists) are implemented directly, so TFRecord pipelines work
with zero extra dependencies.
"""

from __future__ import annotations

import struct
from typing import Any, Iterator

import numpy as np

# ------------------------------------------------------------------ crc32c
_CRC_TABLE = []


def _crc_table():
    global _CRC_TABLE
    if not _CRC_TABLE:
        poly = 0x82F63B78  # Castagnoli, reflected
        table = []
        for i in range(256):
            crc = i
            for _ in range(8):
                crc = (crc >> 1) ^ poly if crc & 1 else crc >> 1
            table.append(crc)
        _CRC_TABLE = table
    return _CRC_TABLE


def crc32c(data: bytes) -> int:
    table = _crc_table()
    crc = 0xFFFFFFFF
    for b in data:
        crc = table[(crc ^ b) & 0xFF] ^ (crc >> 8)
    return crc ^ 0xFFFFFFFF


def _masked_crc(data: bytes) -> int:
    crc = crc32c(data)
    return ((crc >> 15) | (crc << 17)) + 0xA282EAD8 & 0xFFFFFFFF


# ------------------------------------------------------------------ framing
def read_tfrecord_file(path: str, *, verify: bool = True) -> Iterator[bytes]:
    """Yield raw record payloads (length|len_crc|data|data_crc framing)."""
    with open(path, "rb") as f:
        while True:
            header = f.read(12)
            if not header:
                return
            if len(header) < 12:
                raise ValueError(f"truncated TFRecord header in {path}")
            (length,) = struct.unpack("<Q", header[:8])
            (len_crc,) = struct.unpack("<I", header[8:12])
            if verify and _masked_crc(header[:8]) != len_crc:
                raise ValueError(f"corrupt TFRecord length crc in {path}")
            data = f.read(length)
            footer = f.read(4)
            if len(data) < length or len(footer) < 4:
                raise ValueError(f"truncated TFRecord body in {path}")
            if verify and _masked_crc(data) != struct.unpack("<I", footer)[0]:
                raise ValueError(f"corrupt TFRecord data crc in {path}")
            yield data


def write_tfrecord_file(path: str, records: Iterator[bytes]) -> int:
    n = 0
    with open(path, "wb") as f:
        for rec in records:
            header = struct.pack("<Q", len(rec))
            f.write(header)
            f.write(struct.pack("<I", _masked_crc(header)))
            f.write(rec)
            f.write(struct.pack("<I", _masked_crc(rec)))
            n += 1
    return n


# ------------------------------------------------------------------ protobuf
def _varint(value: int) -> bytes:
    out = bytearray()
    while True:
        bits = value & 0x7F
        value >>= 7
        out.append(bits | (0x80 if value else 0))
        if not value:
            return bytes(out)


def _read_varint(buf: memoryview, pos: int) -> tuple[int, int]:
    result = shift = 0
    while True:
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7


def _length_delimited(field: int, payload: bytes) -> bytes:
    return _varint(field << 3 | 2) + _varint(len(payload)) + payload


def encode_example(features: dict[str, Any]) -> bytes:
    """dict -> serialized tf.train.Example. Values: bytes/str -> bytes_list,
    float(s) -> float_list, int(s) -> int64_list; numpy arrays by dtype."""
    feats = bytearray()
    for name, value in features.items():
        if isinstance(value, (bytes, str)):
            value = [value]
        elif isinstance(value, np.ndarray):
            value = value.tolist()
        elif not isinstance(value, (list, tuple)):
            value = [value]
        if all(isinstance(v, (bytes, str)) for v in value):
            inner = b"".join(
                _length_delimited(1, v.encode() if isinstance(v, str) else v)
                for v in value
            )
            kind = _length_delimited(1, inner)  # BytesList in field 1
        elif all(isinstance(v, (int, np.integer)) for v in value):
            packed = b"".join(_varint(int(v) & 0xFFFFFFFFFFFFFFFF) for v in value)
            kind = _length_delimited(3, _length_delimited(1, packed))  # Int64List
        else:
            packed = b"".join(struct.pack("<f", float(v)) for v in value)
            kind = _length_delimited(2, _length_delimited(1, packed))  # FloatList
        entry = _length_delimited(1, name.encode()) + _length_delimited(2, kind)
        feats += _length_delimited(1, entry)  # map entry in Features.feature
    return _length_delimited(1, bytes(feats))  # Example.features


def decode_example(data: bytes) -> dict[str, Any]:
    """Serialized tf.train.Example -> {name: scalar or list}."""
    buf = memoryview(data)

    def parse_fields(view: memoryview) -> Iterator[tuple[int, int, Any]]:
        pos = 0
        while pos < len(view):
            key, pos = _read_varint(view, pos)
            field, wire = key >> 3, key & 7
            if wire == 2:
                ln, pos = _read_varint(view, pos)
                yield field, wire, view[pos:pos + ln]
                pos += ln
            elif wire == 0:
                v, pos = _read_varint(view, pos)
                yield field, wire, v
            elif wire == 5:
                yield field, wire, view[pos:pos + 4]
                pos += 4
            elif wire == 1:
                yield field, wire, view[pos:pos + 8]
                pos += 8
            else:
                raise ValueError(f"unsupported wire type {wire}")

    out: dict[str, Any] = {}
    for field, _, features_view in parse_fields(buf):
        if field != 1:
            continue
        for f2, _, entry in parse_fields(features_view):
            if f2 != 1:
                continue
            name, feature = None, None
            for f3, _, val in parse_fields(entry):
                if f3 == 1:
                    name = bytes(val).decode()
                elif f3 == 2:
                    feature = val
            if name is None or feature is None:
                continue
            for kind, _, payload in parse_fields(feature):
                if kind == 1:  # BytesList
                    vals = [bytes(v) for f4, _, v in parse_fields(payload) if f4 == 1]
                elif kind == 2:  # FloatList (packed or repeated)
                    vals = []
                    for f4, w4, v in parse_fields(payload):
                        if f4 != 1:
                            continue
                        if w4 == 2:
                            vals.extend(
                                struct.unpack(f"<{len(v) // 4}f", bytes(v))
                            )
                        else:
                            vals.append(struct.unpack("<f", bytes(v))[0])
                elif kind == 3:  # Int64List (packed varints or repeated)
                    vals = []
                    for f4, w4, v in parse_fields(payload):
                        if f4 != 1:
                            continue
                        if w4 == 2:
                            pos = 0
                            while pos < len(v):
                                iv, pos = _read_varint(v, pos)
                                if iv >= 1 << 63:
                                    iv -= 1 << 64
                                vals.append(iv)
                        else:
                            iv = v if isinstance(v, int) else 0
                            if iv >= 1 << 63:
                                iv -= 1 << 64  # two's complement
                            vals.append(iv)
                else:
                    continue
                out[name] = vals[0] if len(vals) == 1 else vals
    return out
