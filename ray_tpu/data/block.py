"""Blocks: the unit of data exchanged between streaming operators.

Parity: python/ray/data/block.py + arrow_block.py — the reference's block is an
Arrow table or pandas DataFrame in plasma. TPU-first choice: the canonical block
is a **columnar dict of numpy arrays** (zero-copy to `jax.device_put`, no Arrow
round-trip on the hot path), with Arrow/pandas conversion at the edges.
"""

from __future__ import annotations

from typing import Any, Iterable, Iterator

import numpy as np

Row = dict[str, Any]


class Block:
    """Columnar block: {column: np.ndarray} with equal lengths."""

    __slots__ = ("columns",)

    def __init__(self, columns: dict[str, np.ndarray]):
        self.columns = columns

    # --- constructors ---
    @staticmethod
    def from_rows(rows: list[Row]) -> "Block":
        if not rows:
            return Block({})
        # union of keys across rows (sparse rows are legal — TFRecord optional
        # features, WebDataset optional per-sample files); missing -> None
        keys: dict = {}
        for r in rows:
            for k in r:
                keys.setdefault(k, None)
        if all(len(r) == len(keys) for r in rows):
            return Block({k: np.asarray([r[k] for r in rows]) for k in keys})
        return Block({k: np.asarray([r.get(k) for r in rows]) for k in keys})

    @staticmethod
    def from_items(items: list[Any]) -> "Block":
        if items and isinstance(items[0], dict):
            return Block.from_rows(items)
        return Block({"item": np.asarray(items)})

    @staticmethod
    def from_numpy(arr: np.ndarray | dict[str, np.ndarray]) -> "Block":
        if isinstance(arr, dict):
            return Block({k: np.asarray(v) for k, v in arr.items()})
        return Block({"data": np.asarray(arr)})

    @staticmethod
    def from_pandas(df) -> "Block":
        return Block({c: df[c].to_numpy() for c in df.columns})

    @staticmethod
    def from_arrow(table) -> "Block":
        return Block({name: col.to_numpy(zero_copy_only=False) for name, col in zip(table.column_names, table.columns)})

    # --- conversions ---
    def to_pandas(self):
        import pandas as pd

        return pd.DataFrame({k: list(v) if v.ndim > 1 else v for k, v in self.columns.items()})

    def to_arrow(self):
        import pyarrow as pa

        return pa.table({k: v.tolist() if v.ndim > 1 else v for k, v in self.columns.items()})

    def to_numpy(self) -> dict[str, np.ndarray]:
        return self.columns

    # --- ops ---
    def num_rows(self) -> int:
        for v in self.columns.values():
            return len(v)
        return 0

    def size_bytes(self) -> int:
        return sum(v.nbytes for v in self.columns.values())

    def slice(self, start: int, end: int) -> "Block":
        return Block({k: v[start:end] for k, v in self.columns.items()})

    def rows(self) -> Iterator[Row]:
        n = self.num_rows()
        keys = list(self.columns)
        for i in range(n):
            yield {k: self.columns[k][i] for k in keys}

    @staticmethod
    def concat(blocks: "list[Block]") -> "Block":
        blocks = [b for b in blocks if b.num_rows() > 0]
        if not blocks:
            return Block({})
        keys = blocks[0].columns.keys()
        return Block({k: np.concatenate([b.columns[k] for b in blocks]) for k in keys})

    def select(self, cols: list[str]) -> "Block":
        return Block({c: self.columns[c] for c in cols})

    def schema(self) -> dict[str, str]:
        return {k: f"{v.dtype}{list(v.shape[1:]) if v.ndim > 1 else ''}" for k, v in self.columns.items()}
