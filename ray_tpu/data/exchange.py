"""All-to-all exchange operators: hash shuffle, sample-sort, join.

Parity: python/ray/data/_internal/execution/operators/hash_shuffle.py (+ _v2),
join.py, and planner/exchange/ (sort's boundary-sampling exchange). Shape kept
from the reference: a MAP stage partitions every input block (one task per
block, one return per partition) and a REDUCE stage combines each partition's
slices (one task per partition); the object plane carries the slices, so the
exchange parallelizes across worker processes and spills under pressure.
"""

from __future__ import annotations

import math
from typing import Any, Callable, Iterator, Optional

import numpy as np

import ray_tpu
from ray_tpu.data.block import Block
from ray_tpu.exceptions import GetTimeoutError

DEFAULT_PARTITIONS = 8


# ------------------------------------------------------------------ map/reduce
def _split_by_index(block: Block, idx: np.ndarray, P: int):
    outs = []
    for i in range(P):
        mask = idx == i
        outs.append(Block({k: v[mask] for k, v in block.columns.items()}))
    return tuple(outs) if P > 1 else outs[0]


def _map_partition(block: Block, part_fn, P: int, block_idx: int):
    """One map task per input block -> P partition-slice REFS.

    The slices are ray_tpu.put() from INSIDE the mapper: on an isolated-plane
    node that seals them into the node-LOCAL store (the head records only
    locations), and reducers pull their slices holder->consumer through the
    object plane — the head never carries block bytes, so the exchange
    scales past the head's memory budget (reference: hash_shuffle.py
    emitting block refs; object_manager.cc:369 pull protocol)."""
    idx = part_fn(block, block_idx)
    outs = _split_by_index(block, np.asarray(idx, dtype=np.int64), P)
    if P == 1:
        outs = [outs]
    return [ray_tpu.put(o) for o in outs]


def _scatter(blocks: Iterator[Block], part_fn, P: int, map_task):
    """MAP stage shared by exchange() and join_exchange(): one task per block
    returning P slice refs (tiny — the slices themselves stay in the
    mappers' node stores). Returns (per-partition ref lists, n_blocks,
    schema of the first non-empty block)."""
    partitions: list[list] = [[] for _ in range(P)]
    ref_lists = []
    n_blocks = 0
    schema: dict | None = None
    for b in blocks:
        if schema is None and b.num_rows() > 0:
            schema = {k: v.dtype for k, v in b.columns.items()}
        ref_lists.append(map_task.remote(b, part_fn, P, n_blocks))
        n_blocks += 1
    # harvest in COMPLETION order (a slow mapper doesn't head-of-line block
    # collecting the fast ones' metadata) but PLACE by block index —
    # within-partition slice order must be deterministic or seeded shuffles
    # and stable-sort tie order change run to run
    block_idx = {r: i for i, r in enumerate(ref_lists)}
    slots: list[list | None] = [None] * n_blocks
    pending = list(ref_lists)
    while pending:
        ready, pending = ray_tpu.wait(pending, num_returns=1, timeout=600)
        if not ready:
            raise GetTimeoutError(
                f"exchange map stage stalled: {len(pending)} mapper(s) not "
                "done after 600s")
        for r in ready:
            slots[block_idx[r]] = ray_tpu.get(r, timeout=600)  # P small refs
    for slice_refs in slots:
        for i, pref in enumerate(slice_refs):
            partitions[i].append(pref)
    return partitions, n_blocks, schema


def _reduce_partition(reduce_fn, *slices: Block) -> Block:
    blocks = [s for s in slices if s.num_rows() > 0]
    return reduce_fn(blocks) if blocks else Block({})


def exchange(
    blocks: Iterator[Block],
    part_fn: Callable[[Block], np.ndarray],
    num_partitions: int,
    reduce_fn: Callable[[list[Block]], Block],
    ordered: bool = True,
) -> Iterator[Block]:
    """Partition every block with `part_fn`, then reduce each partition.

    An exchange is a barrier by nature (every reducer needs a slice of every
    mapper); memory pressure is absorbed by the object store (spilling)."""
    P = num_partitions
    map_task = ray_tpu.remote(name="data::exchange_map")(_map_partition)
    reduce_task = ray_tpu.remote(name="data::exchange_reduce")(_reduce_partition)
    partitions, n_blocks, _ = _scatter(blocks, part_fn, P, map_task)
    if n_blocks == 0:
        return
    out_refs = [reduce_task.remote(reduce_fn, *parts) for parts in partitions]
    if ordered:
        for r in out_refs:
            blk = ray_tpu.get(r)
            if blk.num_rows() > 0:
                yield blk
    else:
        pending = list(out_refs)
        while pending:
            ready, pending = ray_tpu.wait(pending, num_returns=1)
            blk = ray_tpu.get(ready[0])
            if blk.num_rows() > 0:
                yield blk


def _concat_reduce(blocks: list[Block]) -> Block:
    return Block.concat(blocks)


# ------------------------------------------------------------------ shuffle
def shuffle_exchange(blocks: Iterator[Block], seed: Optional[int],
                     num_partitions: int = DEFAULT_PARTITIONS) -> Iterator[Block]:
    """True global random shuffle: rows scatter uniformly over partitions,
    each partition permutes (reference: random_shuffle as full exchange)."""
    root = np.random.SeedSequence(seed)
    mix, reduce_seed = [int(s.generate_state(1)[0]) for s in root.spawn(2)]

    def part(block: Block, block_idx: int) -> np.ndarray:
        # per-block substream keyed by block POSITION: deterministic for a
        # given seed across runs/processes, distinct per block
        rng = np.random.default_rng([mix, block_idx])
        return rng.integers(0, num_partitions, size=block.num_rows())

    def reduce(bs: list[Block]) -> Block:
        merged = Block.concat(bs)
        rng = np.random.default_rng([reduce_seed, merged.num_rows()])
        perm = rng.permutation(merged.num_rows())
        return Block({k: v[perm] for k, v in merged.columns.items()})

    yield from exchange(blocks, part, num_partitions, reduce, ordered=False)


# ------------------------------------------------------------------ sort
def sort_exchange(blocks: Iterator[Block], key: str, descending: bool = False,
                  num_partitions: int = DEFAULT_PARTITIONS) -> Iterator[Block]:
    """Distributed sample-sort (reference: planner/exchange sort): sample key
    values -> P-1 range boundaries -> range-partition -> per-partition sort ->
    emit partitions in boundary order."""
    block_list = list(blocks)
    if not block_list:
        return
    samples = []
    for b in block_list:
        col = b.columns.get(key)
        if col is not None and len(col):
            k = min(len(col), 20)
            samples.append(np.random.default_rng(0).choice(col, size=k, replace=False))
    if not samples:
        return
    sample = np.sort(np.concatenate(samples))
    P = min(num_partitions, max(1, len(sample)))
    bounds = sample[np.linspace(0, len(sample) - 1, P + 1).astype(int)][1:-1]

    def part(block: Block, block_idx: int) -> np.ndarray:
        return np.searchsorted(bounds, block.columns[key], side="right")

    def reduce(bs: list[Block]) -> Block:
        merged = Block.concat(bs)
        order = np.argsort(merged.columns[key], kind="stable")
        return Block({k2: v[order] for k2, v in merged.columns.items()})

    out = list(exchange(iter(block_list), part, P, reduce, ordered=True))
    if descending:
        for blk in reversed(out):
            rev = slice(None, None, -1)
            yield Block({k2: v[rev] for k2, v in blk.columns.items()})
    else:
        yield from out


# ------------------------------------------------------------------ groupby
def _hash_key_col(col: np.ndarray, P: int) -> np.ndarray:
    # stable content hash per element (abs of Python hash is per-process stable
    # for numbers; strings need a content hash because PYTHONHASHSEED varies
    # across worker processes)
    if col.dtype.kind in "biufc":
        if col.dtype.kind in "biu":
            return col.astype(np.int64, copy=False) % P
        # hash(nan) is id-based since py3.10 — all NaNs must co-partition
        return np.asarray(
            [0 if x != x else hash(float(x)) for x in col.tolist()]
        ) % P
    import zlib

    return np.asarray([zlib.crc32(str(x).encode()) for x in col]) % P


def hash_partitioner(key: str, P: int):
    def part(block: Block, block_idx: int) -> np.ndarray:
        return np.abs(_hash_key_col(block.columns[key], P)) % P

    return part


def grouped_aggregate(blocks: Iterator[Block], key: str, agg_block_fn,
                      num_partitions: int = DEFAULT_PARTITIONS) -> Iterator[Block]:
    """Hash-exchange on the group key, then aggregate each partition locally
    (every group lands wholly in one partition — hash_shuffle.py semantics)."""
    yield from exchange(
        blocks, hash_partitioner(key, num_partitions), num_partitions,
        lambda bs: agg_block_fn(Block.concat(bs)), ordered=False,
    )


# ------------------------------------------------------------------ join
def join_exchange(left: Iterator[Block], right: Iterator[Block], on: str,
                  how: str = "inner",
                  num_partitions: int = DEFAULT_PARTITIONS) -> Iterator[Block]:
    """Hash join (reference: execution/operators/join.py): both sides hash-
    partition on the key; each partition joins independently."""
    if how not in ("inner", "left", "outer", "right"):
        raise ValueError(f"unsupported join how={how!r}")
    P = num_partitions
    map_task = ray_tpu.remote(name="data::join_map")(_map_partition)
    join_task = ray_tpu.remote(name="data::join_reduce")(_join_partition)
    part = hash_partitioner(on, P)

    lparts, _, lschema = _scatter(left, part, P, map_task)
    rparts, _, rschema = _scatter(right, part, P, map_task)
    out_refs = []
    for i in range(P):
        if not lparts[i] and not rparts[i]:
            continue
        out_refs.append(
            join_task.remote(on, how, len(lparts[i]),
                             {k: str(v) for k, v in (lschema or {}).items()},
                             {k: str(v) for k, v in (rschema or {}).items()},
                             *(lparts[i] + rparts[i]))
        )
    pending = list(out_refs)
    while pending:
        ready, pending = ray_tpu.wait(pending, num_returns=1)
        blk = ray_tpu.get(ready[0])
        if blk.num_rows() > 0:
            yield blk


def _join_partition(on: str, how: str, n_left: int, lschema: dict, rschema: dict,
                    *slices: Block) -> Block:
    import pandas as pd

    def side_df(bs: list[Block], schema: dict):
        if bs:
            return Block.concat(bs).to_pandas()
        # An empty side still joins with the full OUTPUT SCHEMA (its columns
        # come out NaN-filled) so every partition's block has identical
        # columns — downstream Block.concat requires it. Dtypes must match
        # the real side's or pandas refuses to merge the key column.
        schema = schema or {on: "object"}
        return pd.DataFrame({c: pd.Series(dtype=dt) for c, dt in schema.items()})

    ldf = side_df([s for s in slices[:n_left] if s.num_rows() > 0], lschema)
    rdf = side_df([s for s in slices[n_left:] if s.num_rows() > 0], rschema)
    if ldf.empty and rdf.empty:
        return Block({})
    merged = ldf.merge(rdf, on=on, how=how, suffixes=("", "_r"))
    return Block.from_pandas(merged)
