"""All-to-all exchange operators: hash shuffle, sample-sort, join.

Parity: python/ray/data/_internal/execution/operators/hash_shuffle.py (+ _v2),
join.py, and planner/exchange/ (sort's boundary-sampling exchange). Shape kept
from the reference: a MAP stage partitions every input block (one task per
block, one return per partition) and a REDUCE stage combines each partition's
slices (one task per partition); the object plane carries the slices, so the
exchange parallelizes across worker processes and spills under pressure.

Plane-native since ISSUE-12: slices AND reduced partitions live as sealed
object-plane entries — mappers ``put`` slices into their node's store,
reducers PULL THEIR OWN slices (``ray_tpu.get`` inside the reduce task rides
the PR-5 ``pull_into`` failover path holder→reducer) and seal their output
locally, and the driver carries only descriptors end to end. A holder that
dies mid-exchange surfaces as a ``PartitionLostError`` naming the partition
and the input blocks whose slices were lost; when the exchange still holds
the inputs (``replayable``), the lost blocks are re-mapped (partition
functions are deterministic in ``block_idx``, so the re-mapped slices are
byte-identical) and the reduce retried off the survivors.
"""

from __future__ import annotations

import os
from typing import Callable, Iterator, Optional

import numpy as np

import ray_tpu
from ray_tpu.data.block import Block
from ray_tpu.data.streaming import BlockRef, ensure_ref, fetch_block
from ray_tpu.exceptions import (
    GetTimeoutError,
    ObjectLostError,
    RayTpuError,
    TaskError,
)
from ray_tpu.util import flight_recorder

DEFAULT_PARTITIONS = 8
# Base pull deadline inside a reduce task: the backstop behind the
# event-driven loss path (a dead holder's slices normally surface as
# ObjectLostError as soon as the head processes the node death). The
# actual deadline SCALES with the bytes being pulled (base + bytes at the
# assumed-minimum bandwidth below) so a slow-but-healthy bulk pull is
# never misclassified as lost data.
SLICE_TIMEOUT_S = float(os.environ.get("RAY_TPU_DATA_SLICE_TIMEOUT_S", "60"))
# The slowest link the deadline scaling assumes: a pull slower than this
# is treated as lost (conservatively low — the deadline is a backstop,
# not a throughput SLO).
MIN_PULL_MBPS = float(os.environ.get("RAY_TPU_DATA_MIN_PULL_MBPS", "16"))


def _pull_deadline_s(nbytes: int) -> float:
    return SLICE_TIMEOUT_S + nbytes / (MIN_PULL_MBPS * (1 << 20))
# How many times a partition's reduce is retried after re-mapping its lost
# input blocks before the PartitionLostError propagates.
EXCHANGE_RETRIES = int(os.environ.get("RAY_TPU_DATA_EXCHANGE_RETRIES", "2"))


class PartitionLostError(RayTpuError):
    """A reduce task could not pull some of its partition's slices: every
    holder of those slices is gone and the exchange could not (or may not)
    re-map the input blocks that produced them. Names the partition and
    the lost input block indices — the debuggable face of "a node died
    mid-shuffle". ``partition == MAP_STAGE`` (-1) means the loss happened
    before any partition existed: a map task's INPUT block became
    unpullable (its holder died before the mapper fetched it)."""

    MAP_STAGE = -1

    def __init__(self, partition: int, lost_blocks: list,
                 detail: str = ""):
        self.partition = int(partition)
        self.lost_blocks = sorted(set(int(b) for b in lost_blocks))
        self.detail = detail
        where = ("map stage" if self.partition == self.MAP_STAGE
                 else f"partition {self.partition}")
        super().__init__(
            f"exchange {where} lost input block(s) {self.lost_blocks}"
            + (f": {detail}" if detail else ""))

    def __reduce__(self):
        return (type(self), (self.partition, self.lost_blocks, self.detail))


# ------------------------------------------------------------------ map/reduce
def _split_by_index(block: Block, idx: np.ndarray, P: int):
    outs = []
    for i in range(P):
        mask = idx == i
        outs.append(Block({k: v[mask] for k, v in block.columns.items()}))
    return outs


def _map_partition(block: Block, part_fn, P: int, block_idx: int):
    """One map task per input block -> P plane-sealed slice DESCRIPTORS.

    The slices are ray_tpu.put() from INSIDE the mapper: on an isolated-plane
    node that seals them into the node-LOCAL store (the head records only
    locations), and reducers pull their slices holder->consumer through the
    object plane — the head never carries block bytes, so the exchange
    scales past the head's memory budget (reference: hash_shuffle.py
    emitting block refs; object_manager.cc:369 pull protocol). Each row is
    ``[ref, rows, bytes]`` so the driver can account without touching
    payloads."""
    idx = part_fn(block, block_idx)
    outs = _split_by_index(block, np.asarray(idx, dtype=np.int64), P)
    return [[ray_tpu.put(o), o.num_rows(), o.size_bytes()] for o in outs]


def _scatter(items, part_fn, P: int, map_task):
    """MAP stage shared by exchange_refs() and join_exchange(): one task per
    input item (Block or BlockRef) returning P slice-descriptor rows (tiny —
    the slices themselves stay in the mappers' node stores). Returns
    ``(partitions, inputs, schema)`` where ``partitions[p]`` is a list of
    ``[slice_ref, block_idx, rows, bytes]`` ordered by block index and
    ``inputs`` holds every input's DESCRIPTOR (the ref kept alive for
    lost-slice re-mapping)."""
    partitions: list[list] = [[] for _ in range(P)]
    ref_lists = []
    inputs: list = []
    schema: dict | None = None
    for item in items:
        blk = item if isinstance(item, Block) else None
        if schema is None and blk is not None and blk.num_rows() > 0:
            schema = {k: v.dtype for k, v in blk.columns.items()}
        # A driver-local Block is sealed into THIS process's store and held
        # only as its descriptor: replay needs the input PULLABLE, not
        # heap-resident — holding payloads would grow the driver by the
        # whole dataset on a shuffle over a driver-local stream (the store
        # absorbs the residency and spills under pressure).
        desc = ensure_ref(item)
        ref_lists.append(map_task.remote(desc.ref, part_fn, P, len(inputs)))
        inputs.append(desc)
    # harvest in COMPLETION order (a slow mapper doesn't head-of-line block
    # collecting the fast ones' metadata) but PLACE by block index —
    # within-partition slice order must be deterministic or seeded shuffles
    # and stable-sort tie order change run to run
    block_idx = {r: i for i, r in enumerate(ref_lists)}
    slots: list = [None] * len(inputs)
    pending = list(ref_lists)
    while pending:
        ready, pending = ray_tpu.wait(pending, num_returns=1, timeout=600)
        if not ready:
            raise GetTimeoutError(
                f"exchange map stage stalled: {len(pending)} mapper(s) not "
                "done after 600s")
        for r in ready:
            try:
                slots[block_idx[r]] = ray_tpu.get(r)  # P small descriptor rows
            except TaskError as e:
                cause = e.as_cause()
                if not isinstance(cause, (ObjectLostError, GetTimeoutError)):
                    raise  # user error in part_fn — propagate as-is
                # The mapper could not pull its INPUT block (holder died
                # before the map ran). The input is the thing that's gone,
                # so there is nothing to re-map from — surface the named
                # error the exchange contract promises, never a raw
                # transport error.
                raise PartitionLostError(
                    PartitionLostError.MAP_STAGE, [block_idx[r]],
                    detail=f"input block unpullable "
                           f"({type(cause).__name__}: {cause})") from None
            except (ObjectLostError, GetTimeoutError) as e:
                # Same loss, other surface: when every retry of the mapper
                # died on the unpullable input, the driver's lineage
                # recovery gives up on the PUT input (puts have no task
                # spec to re-execute) and get() raises the transport error
                # RAW rather than as a TaskError.
                raise PartitionLostError(
                    PartitionLostError.MAP_STAGE, [block_idx[r]],
                    detail=f"input block unpullable "
                           f"({type(e).__name__}: {e})") from None
    for bidx, rows in enumerate(slots):
        for p, (ref, nrows, nbytes) in enumerate(rows):
            partitions[p].append([ref, bidx, nrows, nbytes])
    return partitions, inputs, schema


def _reduce_partition(reduce_fn, part_idx: int, slice_descs: list) -> list:
    """One reduce task per partition: PULL every slice yourself (resolved
    through the local store or the object plane's failover pull across live
    holders), combine, and seal the result into THIS node's store. Returns
    one ``[ref, rows, bytes]`` descriptor row — reduced partitions never
    transit the driver either.

    The happy path is ONE batched get (one control-plane RPC for the whole
    partition, store/plane resolution per slice); only when that fails does
    the per-slice loop run, to NAME the lost input blocks. Slices whose
    every holder died surface as ObjectLostError (or the deadline backstop)
    and are re-raised as a PartitionLostError the driver can re-map from."""
    blocks, lost = _pull_slices(part_idx, slice_descs, "slice")
    if lost:
        raise PartitionLostError(
            part_idx, [b for b, _ in lost],
            detail="; ".join(f"block {b}: {w}" for b, w in lost[:4]))
    blocks = [b for b in blocks if b.num_rows() > 0]
    out = reduce_fn(blocks) if blocks else Block({})
    return [ray_tpu.put(out), out.num_rows(), out.size_bytes()]


def exchange_refs(
    items: "Iterator[Block | BlockRef]",
    part_fn: Callable,
    num_partitions: int,
    reduce_fn: Callable[[list[Block]], Block],
    ordered: bool = True,
    replayable: bool = True,
    _after_scatter: "Callable | None" = None,
) -> "Iterator[BlockRef]":
    """Plane-native exchange: partition every input with ``part_fn`` (one
    map task per block), then reduce each partition (one task per
    partition, pulling its own slices). Yields DESCRIPTORS of the reduced
    partitions — callers materialize at their edge.

    An exchange is a barrier by nature (every reducer needs a slice of
    every mapper); memory pressure is absorbed by the node stores
    (spilling). Holder death mid-exchange re-maps the lost input blocks
    off ``items`` (held as plane descriptors until completion) up to
    EXCHANGE_RETRIES times, then propagates the named
    PartitionLostError."""
    P = num_partitions
    map_task = ray_tpu.remote(name="data::exchange_map")(_map_partition)
    reduce_task = ray_tpu.remote(name="data::exchange_reduce")(_reduce_partition)
    partitions, inputs, _ = _scatter(items, part_fn, P, map_task)
    if not inputs:
        return
    if _after_scatter is not None:
        # chaos-injection seam: tests strike a holder at the exact barrier
        # between the map and reduce stages (deterministic, no timing games)
        _after_scatter(partitions, inputs)

    def submit_reduce(p: int):
        descs = [[ref, bidx, nbytes] for ref, bidx, _r, nbytes
                 in partitions[p]]
        return reduce_task.remote(reduce_fn, p, descs)

    out_refs = {submit_reduce(p): p for p in range(P)}
    attempts = [0] * P
    # One holder death loses the same input blocks from EVERY in-flight
    # reduce; re-mapping once refreshes every partition's entries (the
    # splice in _remap_blocks covers all P). Generation counters let the
    # 2nd..Pth failure resubmit off the already-fresh refs instead of
    # re-running the same map tasks P times.
    remap_gen = 0
    remapped_at: dict[int, int] = {}
    submit_gen = [0] * P
    emitted: dict[int, BlockRef] = {}
    next_ordered = 0
    pending = list(out_refs)
    while pending:
        ready, pending = ray_tpu.wait(pending, num_returns=1)
        r = ready[0]
        p = out_refs.pop(r)
        try:
            row = ray_tpu.get(r)
        except (TaskError, PartitionLostError) as e:
            cause = e.as_cause() if isinstance(e, TaskError) else e
            if not isinstance(cause, PartitionLostError):
                raise
            attempts[p] += 1
            if not replayable or attempts[p] > EXCHANGE_RETRIES:
                raise cause from None
            need = [b for b in cause.lost_blocks
                    if remapped_at.get(b, -1) <= submit_gen[p]]
            if need:
                flight_recorder.record(
                    "data", "partition_slices_remap", partition=p,
                    lost_blocks=list(need), attempt=attempts[p])
                _remap_blocks(need, inputs, partitions, part_fn, P,
                              map_task)
                remap_gen += 1
                for b in need:
                    remapped_at[b] = remap_gen
            submit_gen[p] = remap_gen
            nr = submit_reduce(p)
            out_refs[nr] = p
            pending.append(nr)
            continue
        ref, nrows, nbytes = row
        desc = BlockRef(ref, nrows, nbytes)
        if not ordered:
            if nrows > 0:
                yield desc
            continue
        emitted[p] = desc
        while next_ordered in emitted:
            d = emitted.pop(next_ordered)
            next_ordered += 1
            if d.num_rows > 0:
                yield d


def _remap_blocks(lost_blocks, inputs, partitions, part_fn, P, map_task):
    """Re-run the map task for the named input blocks and splice the fresh
    slice refs into every partition's entry list (the partition function is
    deterministic in block_idx, so re-mapped slices are identical). If an
    input itself is unpullable, the retried reduce reports it lost again
    and the retry budget converts that into the user-facing error."""
    remapped = {bidx: map_task.remote(inputs[bidx].ref, part_fn, P, bidx)
                for bidx in lost_blocks}
    for bidx, r in remapped.items():
        try:
            rows = ray_tpu.get(r, timeout=600)
        except (ObjectLostError, GetTimeoutError, TaskError):
            # the INPUT is unpullable too (its holder died as well): leave
            # this block's entries stale — the retried reduce names it lost
            # again and the retry budget converts that into the user-facing
            # PartitionLostError (never a raw transport error)
            continue
        for p, (ref, nrows, nbytes) in enumerate(rows):
            for ent in partitions[p]:
                if ent[1] == bidx:
                    ent[0], ent[2], ent[3] = ref, nrows, nbytes


def exchange(
    blocks: Iterator[Block],
    part_fn: Callable[[Block], np.ndarray],
    num_partitions: int,
    reduce_fn: Callable[[list[Block]], Block],
    ordered: bool = True,
) -> Iterator[Block]:
    """Block-level exchange surface (legacy callers): the plane-native
    exchange with the driver as the consumer edge."""
    for desc in exchange_refs(blocks, part_fn, num_partitions, reduce_fn,
                              ordered=ordered):
        yield fetch_block(desc)


def _concat_reduce(blocks: list[Block]) -> Block:
    return Block.concat(blocks)


# ------------------------------------------------------------------ shuffle
def shuffle_refs(items: "Iterator[Block | BlockRef]", seed: Optional[int],
                 num_partitions: int = DEFAULT_PARTITIONS,
                 ) -> "Iterator[BlockRef]":
    """True global random shuffle over descriptors: rows scatter uniformly
    over partitions, each partition permutes (reference: random_shuffle as
    full exchange)."""
    root = np.random.SeedSequence(seed)
    mix, reduce_seed = [int(s.generate_state(1)[0]) for s in root.spawn(2)]

    def part(block: Block, block_idx: int) -> np.ndarray:
        # per-block substream keyed by block POSITION: deterministic for a
        # given seed across runs/processes, distinct per block
        rng = np.random.default_rng([mix, block_idx])
        return rng.integers(0, num_partitions, size=block.num_rows())

    def reduce(bs: list[Block]) -> Block:
        merged = Block.concat(bs)
        rng = np.random.default_rng([reduce_seed, merged.num_rows()])
        perm = rng.permutation(merged.num_rows())
        return Block({k: v[perm] for k, v in merged.columns.items()})

    yield from exchange_refs(items, part, num_partitions, reduce,
                             ordered=False)


def shuffle_exchange(blocks: Iterator[Block], seed: Optional[int],
                     num_partitions: int = DEFAULT_PARTITIONS) -> Iterator[Block]:
    for desc in shuffle_refs(blocks, seed, num_partitions):
        yield fetch_block(desc)


# ------------------------------------------------------------------ sort
def sort_exchange(blocks: Iterator[Block], key: str, descending: bool = False,
                  num_partitions: int = DEFAULT_PARTITIONS) -> Iterator[Block]:
    """Distributed sample-sort (reference: planner/exchange sort): sample key
    values -> P-1 range boundaries -> range-partition -> per-partition sort ->
    emit partitions in boundary order."""
    block_list = list(blocks)
    if not block_list:
        return
    samples = []
    for b in block_list:
        col = b.columns.get(key)
        if col is not None and len(col):
            k = min(len(col), 20)
            samples.append(np.random.default_rng(0).choice(col, size=k, replace=False))
    if not samples:
        return
    sample = np.sort(np.concatenate(samples))
    P = min(num_partitions, max(1, len(sample)))
    bounds = sample[np.linspace(0, len(sample) - 1, P + 1).astype(int)][1:-1]

    def part(block: Block, block_idx: int) -> np.ndarray:
        return np.searchsorted(bounds, block.columns[key], side="right")

    def reduce(bs: list[Block]) -> Block:
        merged = Block.concat(bs)
        order = np.argsort(merged.columns[key], kind="stable")
        return Block({k2: v[order] for k2, v in merged.columns.items()})

    out = list(exchange(iter(block_list), part, P, reduce, ordered=True))
    if descending:
        for blk in reversed(out):
            rev = slice(None, None, -1)
            yield Block({k2: v[rev] for k2, v in blk.columns.items()})
    else:
        yield from out


# ------------------------------------------------------------------ groupby
def _hash_key_col(col: np.ndarray, P: int) -> np.ndarray:
    # stable content hash per element (abs of Python hash is per-process stable
    # for numbers; strings need a content hash because PYTHONHASHSEED varies
    # across worker processes)
    if col.dtype.kind in "biufc":
        if col.dtype.kind in "biu":
            return col.astype(np.int64, copy=False) % P
        # hash(nan) is id-based since py3.10 — all NaNs must co-partition
        return np.asarray(
            [0 if x != x else hash(float(x)) for x in col.tolist()]
        ) % P
    import zlib

    return np.asarray([zlib.crc32(str(x).encode()) for x in col]) % P


def hash_partitioner(key: str, P: int):
    def part(block: Block, block_idx: int) -> np.ndarray:
        return np.abs(_hash_key_col(block.columns[key], P)) % P

    return part


def grouped_aggregate(blocks: Iterator[Block], key: str, agg_block_fn,
                      num_partitions: int = DEFAULT_PARTITIONS) -> Iterator[Block]:
    """Hash-exchange on the group key, then aggregate each partition locally
    (every group lands wholly in one partition — hash_shuffle.py semantics)."""
    yield from exchange(
        blocks, hash_partitioner(key, num_partitions), num_partitions,
        lambda bs: agg_block_fn(Block.concat(bs)), ordered=False,
    )


# ------------------------------------------------------------------ join
def join_exchange(left: Iterator[Block], right: Iterator[Block], on: str,
                  how: str = "inner",
                  num_partitions: int = DEFAULT_PARTITIONS) -> Iterator[Block]:
    """Hash join (reference: execution/operators/join.py): both sides hash-
    partition on the key; each partition joins independently in a task that
    pulls its own slices off the plane."""
    if how not in ("inner", "left", "outer", "right"):
        raise ValueError(f"unsupported join how={how!r}")
    P = num_partitions
    map_task = ray_tpu.remote(name="data::join_map")(_map_partition)
    join_task = ray_tpu.remote(name="data::join_reduce")(_join_partition)
    part = hash_partitioner(on, P)

    lparts, _linputs, lschema = _scatter(left, part, P, map_task)
    rparts, _rinputs, rschema = _scatter(right, part, P, map_task)
    out_refs = []
    for i in range(P):
        if not lparts[i] and not rparts[i]:
            continue
        ldescs = [[ref, bidx, nb] for ref, bidx, _r, nb in lparts[i]]
        rdescs = [[ref, bidx, nb] for ref, bidx, _r, nb in rparts[i]]
        out_refs.append(
            join_task.remote(on, how, i, ldescs, rdescs,
                             {k: str(v) for k, v in (lschema or {}).items()},
                             {k: str(v) for k, v in (rschema or {}).items()})
        )
    pending = list(out_refs)
    while pending:
        ready, pending = ray_tpu.wait(pending, num_returns=1)
        try:
            row = ray_tpu.get(ready[0])
        except TaskError as e:
            cause = e.as_cause()
            if isinstance(cause, PartitionLostError):
                raise cause from None
            raise
        ref, nrows, nbytes = row
        if nrows > 0:
            yield fetch_block(BlockRef(ref, nrows, nbytes))


def _pull_slices(part_idx: int, slice_descs: list,
                 side: str) -> "tuple[list[Block], list]":
    """Resolve a partition's slice refs in THIS process: one batched get
    (single RPC) when everything is pullable, per-slice resolution naming
    the lost blocks when it is not. Deadlines scale with the bytes being
    pulled (``_pull_deadline_s``) so a large partition on a slow link
    isn't misreported as a lost partition."""
    if not slice_descs:
        return [], []
    total = sum(nb for _r, _b, nb in slice_descs)
    try:
        return ray_tpu.get([ref for ref, _b, _nb in slice_descs],
                           timeout=_pull_deadline_s(total)), []
    except (ObjectLostError, GetTimeoutError):
        pass
    blocks, lost = [], []
    for ref, bidx, nb in slice_descs:
        try:
            blocks.append(ray_tpu.get(ref, timeout=_pull_deadline_s(nb)))
        except (ObjectLostError, GetTimeoutError) as e:
            lost.append((bidx, f"{side} {type(e).__name__}"))
    return blocks, lost


def _join_partition(on: str, how: str, part_idx: int, ldescs: list,
                    rdescs: list, lschema: dict, rschema: dict) -> list:
    import pandas as pd

    lblocks, llost = _pull_slices(part_idx, ldescs, "left")
    rblocks, rlost = _pull_slices(part_idx, rdescs, "right")
    if llost or rlost:
        lost = llost + rlost
        raise PartitionLostError(
            part_idx, [b for b, _ in lost],
            detail="; ".join(f"block {b}: {w}" for b, w in lost[:4]))

    def side_df(bs: list[Block], schema: dict):
        bs = [b for b in bs if b.num_rows() > 0]
        if bs:
            return Block.concat(bs).to_pandas()
        # An empty side still joins with the full OUTPUT SCHEMA (its columns
        # come out NaN-filled) so every partition's block has identical
        # columns — downstream Block.concat requires it. Dtypes must match
        # the real side's or pandas refuses to merge the key column.
        schema = schema or {on: "object"}
        return pd.DataFrame({c: pd.Series(dtype=dt) for c, dt in schema.items()})

    ldf = side_df(lblocks, lschema)
    rdf = side_df(rblocks, rschema)
    if ldf.empty and rdf.empty:
        out = Block({})
    else:
        merged = ldf.merge(rdf, on=on, how=how, suffixes=("", "_r"))
        out = Block.from_pandas(merged)
    return [ray_tpu.put(out), out.num_rows(), out.size_bytes()]
