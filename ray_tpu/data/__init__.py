"""ray_tpu.data: streaming datasets over the task runtime.

Parity surface: ray.data (Dataset, read_*/from_*, map_batches, iter_batches,
streaming_split). Blocks are columnar numpy, streamed through backpressured
task pipelines; `iter_batches(batch_format="jax")` lands batches in HBM.
"""

from ray_tpu.data.block import Block
from ray_tpu.data.dataset import DataIterator, Dataset
from ray_tpu.data.executor import ActorPoolStrategy
from ray_tpu.data.exchange import PartitionLostError
from ray_tpu.data.streaming import BlockRef, IngestStats
from ray_tpu.data.read_api import (
    from_arrow,
    from_huggingface,
    from_items,
    from_numpy,
    from_pandas,
    range,
    read_binary_files,
    read_csv,
    read_json,
    read_images,
    read_numpy,
    read_parquet,
    read_text,
    read_tfrecords,
    read_webdataset,
)

from ray_tpu.data import llm  # noqa: F401  (ray.data.llm parity surface)

__all__ = [
    "llm",
    "Block", "Dataset", "DataIterator", "BlockRef", "IngestStats",
    "PartitionLostError",
    "range", "from_items", "from_numpy", "from_pandas", "from_arrow",
    "from_huggingface", "read_parquet", "read_csv", "read_json", "read_text",
    "read_binary_files", "read_numpy", "read_images", "read_tfrecords", "read_webdataset",
]

from ray_tpu._private.usage_stats import record_library_usage as _rec

_rec("data")
del _rec

from ray_tpu.data.read_api import from_torch, read_avro, read_sql  # noqa: E402,F401
from ray_tpu.data.read_api import read_delta, read_iceberg  # noqa: E402,F401

__all__ += ["read_avro", "read_sql", "from_torch", "read_delta", "read_iceberg"]
