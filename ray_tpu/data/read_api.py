"""Read/from constructors for Datasets.

Parity: python/ray/data/read_api.py (range :*, read_parquet :1342, read_json
:1849, read_csv :2023, read_text, read_binary_files, read_numpy;
from_pandas/from_numpy/from_items/from_arrow/from_huggingface). Reads are
file-partitioned: one block per file (or per range chunk) so downstream
operators stream.
"""

from __future__ import annotations

import functools
import glob as _glob
import math
import os
from typing import Any, Iterable, Iterator

import numpy as np

from ray_tpu.data.block import Block
from ray_tpu.data.dataset import Dataset


def _expand_paths(paths: str | list[str], suffix: str | None = None) -> list[str]:
    if isinstance(paths, str):
        paths = [paths]
    out: list[str] = []
    for p in paths:
        if os.path.isdir(p):
            out.extend(sorted(_glob.glob(os.path.join(p, "**", "*"), recursive=True)))
        elif any(ch in p for ch in "*?["):
            out.extend(sorted(_glob.glob(p)))
        else:
            out.append(p)
    files = [p for p in out if os.path.isfile(p)]
    if suffix:
        matching = [p for p in files if p.endswith(suffix)]
        files = matching or files
    if not files:
        raise FileNotFoundError(f"No files matched {paths}")
    return files


_range = range


def range(n: int, *, parallelism: int = 8) -> Dataset:  # noqa: A001
    """Reference: read_api.range — integer dataset with `id` column."""
    chunk = max(1, math.ceil(n / max(1, parallelism)))

    def source() -> Iterator[Block]:
        for start in _range(0, n, chunk):
            yield Block({"id": np.arange(start, min(start + chunk, n))})

    return Dataset(source, (), f"range({n})")


def from_items(items: list[Any], *, parallelism: int = 8) -> Dataset:
    chunk = max(1, math.ceil(len(items) / max(1, parallelism)))

    def source() -> Iterator[Block]:
        for i in _range(0, len(items), chunk):
            yield Block.from_items(items[i : i + chunk])

    return Dataset(source, (), "from_items")


def from_numpy(arr: np.ndarray | dict, *, blocks: int = 8) -> Dataset:
    block = Block.from_numpy(arr)
    n = block.num_rows()
    per = max(1, math.ceil(n / blocks))

    def source() -> Iterator[Block]:
        for i in _range(0, n, per):
            yield block.slice(i, min(i + per, n))

    return Dataset(source, (), "from_numpy")


def from_pandas(df) -> Dataset:
    return Dataset(lambda: iter([Block.from_pandas(df)]), (), "from_pandas")


def from_arrow(table) -> Dataset:
    return Dataset(lambda: iter([Block.from_arrow(table)]), (), "from_arrow")


def from_huggingface(hf_dataset) -> Dataset:
    """Bridge from a datasets.Dataset (reference: read_api.from_huggingface)."""

    def source() -> Iterator[Block]:
        batch = hf_dataset.with_format("numpy")
        size = len(hf_dataset)
        per = max(1, size // 8)
        for i in _range(0, size, per):
            rows = batch[i : min(i + per, size)]
            yield Block({k: np.asarray(v) for k, v in rows.items()})

    return Dataset(source, (), "from_huggingface")


def _read_parquet_columns(path: str) -> dict:
    import pyarrow.parquet as pq

    table = pq.read_table(path, use_threads=False)
    return {name: col.to_numpy(zero_copy_only=False)
            for name, col in zip(table.column_names, table.columns)}


def _read_parquet(path: str) -> dict:
    """One decision point for the in-process vs subprocess parquet dispatch."""
    if os.environ.get("RAY_TPU_PARQUET_INPROC") == "1":
        return _read_parquet_columns(path)
    return _read_parquet_subprocess(path)


class _ChildDied(IOError):
    pass


def _read_parquet_subprocess(path: str) -> dict:
    """Read in a child process: pyarrow's parquet reader sporadically segfaults
    inside this long-lived multi-threaded process (native-state interaction we
    could not root-cause; see README known issues) — a child sidesteps it and a
    crash there surfaces as an exception, not a driver death.

    First attempt forks (fast, but inherits the driver's process image — the
    corruption occasionally follows); on child death we retry once with a
    spawned interpreter (clean state, slower)."""
    try:
        return _read_in_child(path, "fork")
    except _ChildDied:
        # only a crashed child warrants the clean-interpreter retry; app-level
        # read errors (corrupt file, schema mismatch) surface immediately
        return _read_in_child(path, "spawn")


def _read_in_child(path: str, method: str) -> dict:
    import multiprocessing as mp
    import pickle

    ctx = mp.get_context(method)
    parent, child = ctx.Pipe()
    proc = ctx.Process(target=_parquet_child_main, args=(child, path), daemon=True)
    proc.start()
    child.close()
    try:
        if not parent.poll(120):
            raise TimeoutError(f"parquet read of {path} timed out")
        status, payload = pickle.loads(parent.recv_bytes())
    except EOFError:
        raise _ChildDied(f"parquet reader subprocess ({method}) died reading {path}") from None
    finally:
        proc.join(timeout=5)
        if proc.is_alive():
            proc.terminate()
    if status == "err":
        raise IOError(f"failed to read parquet {path}: {payload}")
    return payload


def _parquet_child_main(conn, path: str) -> None:
    import pickle

    try:
        conn.send_bytes(pickle.dumps(("ok", _read_parquet_columns(path)), protocol=5))
    except BaseException as e:  # noqa: BLE001
        try:
            conn.send_bytes(pickle.dumps(("err", repr(e))))
        except Exception:
            pass


def read_parquet(paths: str | list[str]) -> Dataset:
    """Reference: read_api.read_parquet :1342 — one block per file.

    Reads run in short-lived subprocesses by default (crash isolation; see
    _read_parquet_subprocess). Set RAY_TPU_PARQUET_INPROC=1 to read in-process.
    """
    files = _expand_paths(paths, ".parquet")

    def source() -> Iterator[Block]:
        for f in files:
            yield Block.from_numpy(_read_parquet(f))

    return Dataset(source, (), "read_parquet")


def read_csv(paths: str | list[str]) -> Dataset:
    files = _expand_paths(paths, ".csv")

    def source() -> Iterator[Block]:
        import pandas as pd

        for f in files:
            yield Block.from_pandas(pd.read_csv(f))

    return Dataset(source, (), "read_csv")


def read_json(paths: str | list[str]) -> Dataset:
    files = _expand_paths(paths, ".json")

    def source() -> Iterator[Block]:
        import pandas as pd

        for f in files:
            yield Block.from_pandas(pd.read_json(f, orient="records", lines=True))

    return Dataset(source, (), "read_json")


def read_text(paths: str | list[str]) -> Dataset:
    files = _expand_paths(paths)

    def source() -> Iterator[Block]:
        for f in files:
            with open(f) as fh:
                lines = [ln.rstrip("\n") for ln in fh]
            yield Block({"text": np.asarray(lines, dtype=object)})

    return Dataset(source, (), "read_text")


def read_binary_files(paths: str | list[str]) -> Dataset:
    files = _expand_paths(paths)

    def source() -> Iterator[Block]:
        for f in files:
            with open(f, "rb") as fh:
                data = fh.read()
            yield Block({"path": np.asarray([f], dtype=object),
                         "bytes": np.asarray([data], dtype=object)})

    return Dataset(source, (), "read_binary_files")


def read_numpy(paths: str | list[str]) -> Dataset:
    files = _expand_paths(paths, ".npy")

    def source() -> Iterator[Block]:
        for f in files:
            yield Block.from_numpy(np.load(f))

    return Dataset(source, (), "read_numpy")


def read_images(paths: str | list[str], *, size: tuple[int, int] | None = None,
                mode: str = "RGB", batch_size: int = 32) -> Dataset:
    """Reference: read_api.read_images :1690 — image files -> {image, path} blocks.

    The BASELINE ViT/CLIP ingest path: decoded (optionally resized) uint8 arrays
    batch-ready for `iter_batches(batch_format="jax")` → HBM.
    """
    files = _expand_paths(paths)
    exts = (".png", ".jpg", ".jpeg", ".bmp", ".gif", ".webp")
    files = [f for f in files if f.lower().endswith(exts)]
    if not files:
        raise FileNotFoundError(
            f"No image files ({', '.join(exts)}) matched {paths}"
        )

    def source() -> Iterator[Block]:
        from PIL import Image

        for i in _range(0, len(files), batch_size):
            chunk = files[i : i + batch_size]
            images, okpaths = [], []
            for f in chunk:
                try:
                    img = Image.open(f).convert(mode)
                except Exception:
                    continue  # skip unreadable files (reference: ignore_missing)
                if size is not None:
                    img = img.resize(size)
                images.append(np.asarray(img))
                okpaths.append(f)
            if not images:
                continue
            same_shape = len({im.shape for im in images}) == 1
            arr = (np.stack(images) if same_shape
                   else np.asarray(images, dtype=object))
            yield Block({"image": arr, "path": np.asarray(okpaths, dtype=object)})

    return Dataset(source, (), "read_images")


def read_tfrecords(paths: str | list[str], *, batch_rows: int = 1024) -> Dataset:
    """TFRecord files of tf.train.Example -> rows (reference: read_api.py:2517;
    hermetic framing/proto codec in data/tfrecords.py — no tensorflow)."""
    files = _expand_paths(paths, ".tfrecord")

    def source() -> Iterator[Block]:
        from ray_tpu.data.tfrecords import decode_example, read_tfrecord_file

        rows: list[dict] = []
        for f in files:
            for rec in read_tfrecord_file(f):
                rows.append(decode_example(rec))
                if len(rows) >= batch_rows:
                    yield Block.from_rows(rows)
                    rows = []
        if rows:
            yield Block.from_rows(rows)

    return Dataset(source, (), "read_tfrecords")


def read_webdataset(paths: str | list[str]) -> Dataset:
    """WebDataset tar shards -> one row per sample key, columns per extension
    (reference: read_api.py:2794 read_webdataset)."""
    import tarfile

    files = _expand_paths(paths, ".tar")

    def source() -> Iterator[Block]:
        for f in files:
            rows: dict[str, dict] = {}
            order: list[str] = []
            with tarfile.open(f) as tar:
                for member in tar:
                    if not member.isfile():
                        continue
                    base = os.path.basename(member.name)
                    key, _, ext = base.partition(".")
                    sample = rows.get(key)
                    if sample is None:
                        sample = rows[key] = {"__key__": key}
                        order.append(key)
                    sample[ext] = tar.extractfile(member).read()
            if order:
                yield Block.from_rows([rows[k] for k in order])

    return Dataset(source, (), "read_webdataset")


def read_avro(paths: str | list[str]) -> Dataset:
    """Reference: read_api.read_avro :? (avro datasource via fastavro in
    _internal/datasource/avro_datasource.py) — hermetic codec here
    (data/avro.py), one block per file."""
    files = _expand_paths(paths, ".avro")

    def source() -> Iterator[Block]:
        import pandas as pd

        from ray_tpu.data.avro import read_avro_file

        for f in files:
            yield Block.from_pandas(pd.DataFrame(list(read_avro_file(f))))

    return Dataset(source, (), "read_avro")


def read_delta(table_path: str, *, version: int | None = None) -> Dataset:
    """Reference: read_api.read_delta :4822 (delta-sharing/deltalake SDK).

    Hermetic: replays the _delta_log JSON commits (+parquet checkpoints)
    locally — see data/lakehouse.py — then streams one block per live data
    file. ``version`` time-travels to that commit. Partition values from the
    log are injected as columns (Hive-style tables omit them from the files).
    """
    from ray_tpu.data.lakehouse import delta_active_files

    def source() -> Iterator[Block]:
        files, parts = delta_active_files(table_path, version=version)
        for f, pv in zip(files, parts):
            cols = _read_parquet(f)
            if pv:
                n = len(next(iter(cols.values()))) if cols else 0
                for k, v in pv.items():
                    if k not in cols:
                        cols[k] = np.full(n, v)
            yield Block.from_numpy(cols)

    return Dataset(source, (), "read_delta")


def read_iceberg(table_path: str, *, snapshot_id: int | None = None) -> Dataset:
    """Reference: read_api.read_iceberg :4386 (pyiceberg SDK).

    Hermetic: walks metadata/*.metadata.json → manifest-list avro → manifest
    avro → parquet data files with the in-repo codecs (data/lakehouse.py).
    ``snapshot_id`` time-travels to that snapshot.
    """
    from ray_tpu.data.lakehouse import iceberg_data_files

    def source() -> Iterator[Block]:
        for f in iceberg_data_files(table_path, snapshot_id=snapshot_id):
            yield Block.from_numpy(_read_parquet(f))

    return Dataset(source, (), "read_iceberg")


def read_sql(sql: str, connection_factory) -> Dataset:
    """Reference: read_api.read_sql :3004 — rows from any DB-API connection
    (sqlite3, etc.). connection_factory() -> connection; the query runs inside
    the read task."""

    def source() -> Iterator[Block]:
        import pandas as pd

        conn = connection_factory()
        try:
            cur = conn.cursor()
            cur.execute(sql)
            cols = [d[0] for d in cur.description]
            rows = cur.fetchall()
        finally:
            conn.close()
        yield Block.from_pandas(pd.DataFrame(rows, columns=cols))

    return Dataset(source, (), "read_sql")


def from_torch(torch_dataset, *, blocks: int = 8) -> Dataset:
    """Reference: read_api.from_torch — map-style torch datasets become row
    blocks ({'item': value} rows, matching the reference's column name)."""
    n = len(torch_dataset)

    def source() -> Iterator[Block]:
        import builtins

        per = max(1, -(-n // blocks))
        for lo in builtins.range(0, n, per):
            items = [torch_dataset[i]
                     for i in builtins.range(lo, min(lo + per, n))]
            yield Block({"item": np.asarray(items, dtype=object)})

    return Dataset(source, (), "from_torch")
