"""Read/from constructors for Datasets.

Parity: python/ray/data/read_api.py (range :*, read_parquet :1342, read_json
:1849, read_csv :2023, read_text, read_binary_files, read_numpy;
from_pandas/from_numpy/from_items/from_arrow/from_huggingface). Reads are
file-partitioned: one block per file (or per range chunk) so downstream
operators stream.
"""

from __future__ import annotations

import functools
import glob as _glob
import math
import os
from typing import Any, Iterable, Iterator

import numpy as np

from ray_tpu.data.block import Block
from ray_tpu.data.dataset import Dataset


def _expand_paths(paths: str | list[str], suffix: str | None = None) -> list[str]:
    if isinstance(paths, str):
        paths = [paths]
    out: list[str] = []
    for p in paths:
        if os.path.isdir(p):
            out.extend(sorted(_glob.glob(os.path.join(p, "**", "*"), recursive=True)))
        elif any(ch in p for ch in "*?["):
            out.extend(sorted(_glob.glob(p)))
        else:
            out.append(p)
    files = [p for p in out if os.path.isfile(p)]
    if suffix:
        matching = [p for p in files if p.endswith(suffix)]
        files = matching or files
    if not files:
        raise FileNotFoundError(f"No files matched {paths}")
    return files


_range = range


def range(n: int, *, parallelism: int = 8) -> Dataset:  # noqa: A001
    """Reference: read_api.range — integer dataset with `id` column."""
    chunk = max(1, math.ceil(n / max(1, parallelism)))

    def source() -> Iterator[Block]:
        for start in _range(0, n, chunk):
            yield Block({"id": np.arange(start, min(start + chunk, n))})

    return Dataset(source, (), f"range({n})")


def from_items(items: list[Any], *, parallelism: int = 8) -> Dataset:
    chunk = max(1, math.ceil(len(items) / max(1, parallelism)))

    def source() -> Iterator[Block]:
        for i in _range(0, len(items), chunk):
            yield Block.from_items(items[i : i + chunk])

    return Dataset(source, (), "from_items")


def from_numpy(arr: np.ndarray | dict, *, blocks: int = 8) -> Dataset:
    block = Block.from_numpy(arr)
    n = block.num_rows()
    per = max(1, math.ceil(n / blocks))

    def source() -> Iterator[Block]:
        for i in _range(0, n, per):
            yield block.slice(i, min(i + per, n))

    return Dataset(source, (), "from_numpy")


def from_pandas(df) -> Dataset:
    return Dataset(lambda: iter([Block.from_pandas(df)]), (), "from_pandas")


def from_arrow(table) -> Dataset:
    return Dataset(lambda: iter([Block.from_arrow(table)]), (), "from_arrow")


def from_huggingface(hf_dataset) -> Dataset:
    """Bridge from a datasets.Dataset (reference: read_api.from_huggingface)."""

    def source() -> Iterator[Block]:
        batch = hf_dataset.with_format("numpy")
        size = len(hf_dataset)
        per = max(1, size // 8)
        for i in _range(0, size, per):
            rows = batch[i : min(i + per, size)]
            yield Block({k: np.asarray(v) for k, v in rows.items()})

    return Dataset(source, (), "from_huggingface")


def read_parquet(paths: str | list[str]) -> Dataset:
    """Reference: read_api.read_parquet :1342 — one block per file."""
    files = _expand_paths(paths, ".parquet")

    def source() -> Iterator[Block]:
        import pyarrow.parquet as pq

        for f in files:
            # use_threads=False: pyarrow's internal pool segfaults sporadically
            # inside this multi-threaded runtime (and 1-core hosts gain nothing)
            yield Block.from_arrow(pq.read_table(f, use_threads=False))

    return Dataset(source, (), "read_parquet")


def read_csv(paths: str | list[str]) -> Dataset:
    files = _expand_paths(paths, ".csv")

    def source() -> Iterator[Block]:
        import pandas as pd

        for f in files:
            yield Block.from_pandas(pd.read_csv(f))

    return Dataset(source, (), "read_csv")


def read_json(paths: str | list[str]) -> Dataset:
    files = _expand_paths(paths, ".json")

    def source() -> Iterator[Block]:
        import pandas as pd

        for f in files:
            yield Block.from_pandas(pd.read_json(f, orient="records", lines=True))

    return Dataset(source, (), "read_json")


def read_text(paths: str | list[str]) -> Dataset:
    files = _expand_paths(paths)

    def source() -> Iterator[Block]:
        for f in files:
            with open(f) as fh:
                lines = [ln.rstrip("\n") for ln in fh]
            yield Block({"text": np.asarray(lines, dtype=object)})

    return Dataset(source, (), "read_text")


def read_binary_files(paths: str | list[str]) -> Dataset:
    files = _expand_paths(paths)

    def source() -> Iterator[Block]:
        for f in files:
            with open(f, "rb") as fh:
                data = fh.read()
            yield Block({"path": np.asarray([f], dtype=object),
                         "bytes": np.asarray([data], dtype=object)})

    return Dataset(source, (), "read_binary_files")


def read_numpy(paths: str | list[str]) -> Dataset:
    files = _expand_paths(paths, ".npy")

    def source() -> Iterator[Block]:
        for f in files:
            yield Block.from_numpy(np.load(f))

    return Dataset(source, (), "read_numpy")
