"""Hermetic Avro Object Container File codec (no avro package dependency).

Parity: the reference's avro datasource (_internal/datasource/avro_datasource.py,
which depends on the fastavro package). Scope: tabular container files —
record schemas of primitive fields, nullable ["null", X] unions, and arrays
of primitives; codecs null and deflate. That covers the files the reference's
tabular read path produces/consumes.

Format (Avro 1.11 spec): magic Obj\\x01, file-metadata map carrying
avro.schema JSON + avro.codec, 16-byte sync marker, then blocks of
(count, byte-size, records..., sync).
"""

from __future__ import annotations

import io
import json
import os
import struct
import zlib
from typing import Any, Iterator

MAGIC = b"Obj\x01"


# ---------------------------------------------------------------- primitives
def _zigzag_encode(n: int) -> int:
    return (n << 1) ^ (n >> 63)


def _zigzag_decode(n: int) -> int:
    return (n >> 1) ^ -(n & 1)


def _write_long(buf: io.BytesIO, n: int) -> None:
    n = _zigzag_encode(n)
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            buf.write(bytes([b | 0x80]))
        else:
            buf.write(bytes([b]))
            return


def _read_long(buf) -> int:
    shift, acc = 0, 0
    while True:
        (b,) = buf.read(1)
        acc |= (b & 0x7F) << shift
        if not b & 0x80:
            return _zigzag_decode(acc)
        shift += 7


def _write_bytes(buf, data: bytes) -> None:
    _write_long(buf, len(data))
    buf.write(data)


def _read_bytes(buf) -> bytes:
    return buf.read(_read_long(buf))


# ---------------------------------------------------------------- values
def _write_value(buf, schema, value) -> None:
    if isinstance(schema, list):  # union: index then value
        if value is None and "null" in schema:
            _write_long(buf, schema.index("null"))
            return
        idx = next(i for i, s in enumerate(schema) if s != "null")
        _write_long(buf, idx)
        _write_value(buf, schema[idx], value)
        return
    t = schema["type"] if isinstance(schema, dict) else schema
    if t == "null":
        return
    if t == "boolean":
        buf.write(b"\x01" if value else b"\x00")
    elif t in ("int", "long"):
        _write_long(buf, int(value))
    elif t == "float":
        buf.write(struct.pack("<f", float(value)))
    elif t == "double":
        buf.write(struct.pack("<d", float(value)))
    elif t == "bytes":
        _write_bytes(buf, bytes(value))
    elif t == "string":
        _write_bytes(buf, str(value).encode())
    elif t == "array":
        items = list(value)
        if items:
            _write_long(buf, len(items))
            for it in items:
                _write_value(buf, schema["items"], it)
        _write_long(buf, 0)
    elif t == "record":
        for field in schema["fields"]:
            fv = value.get(field["name"]) if isinstance(value, dict) else value[field["name"]]
            _write_value(buf, field["type"], fv)
    else:
        raise ValueError(f"unsupported avro type for write: {t!r}")


def _read_value(buf, schema):
    if isinstance(schema, list):  # union
        return _read_value(buf, schema[_read_long(buf)])
    t = schema["type"] if isinstance(schema, dict) else schema
    if t == "null":
        return None
    if t == "boolean":
        return buf.read(1) == b"\x01"
    if t in ("int", "long"):
        return _read_long(buf)
    if t == "float":
        return struct.unpack("<f", buf.read(4))[0]
    if t == "double":
        return struct.unpack("<d", buf.read(8))[0]
    if t == "bytes":
        return _read_bytes(buf)
    if t == "string":
        return _read_bytes(buf).decode()
    if t == "array":
        out = []
        while True:
            n = _read_long(buf)
            if n == 0:
                return out
            if n < 0:  # block with byte-size prefix
                _read_long(buf)
                n = -n
            for _ in range(n):
                out.append(_read_value(buf, schema["items"]))
    if t == "map":
        out = {}
        while True:
            n = _read_long(buf)
            if n == 0:
                return out
            if n < 0:
                _read_long(buf)
                n = -n
            for _ in range(n):
                out[_read_bytes(buf).decode()] = _read_value(buf, schema["values"])
    if t == "record":
        return {f["name"]: _read_value(buf, f["type"]) for f in schema["fields"]}
    if t == "enum":
        return schema["symbols"][_read_long(buf)]
    if t == "fixed":
        return buf.read(schema["size"])
    raise ValueError(f"unsupported avro type for read: {t!r}")


# ---------------------------------------------------------------- container
def _value_type(v, name: str = "field") -> Any:
    import numbers

    import numpy as np

    if isinstance(v, np.generic):  # numpy scalars -> python types
        v = v.item()
    if isinstance(v, np.ndarray):
        v = v.tolist()
    if v is None:
        return "null"
    if isinstance(v, bool):
        return "boolean"
    if isinstance(v, numbers.Integral):
        return "long"
    if isinstance(v, numbers.Real):
        return "double"
    if isinstance(v, bytes):
        return "bytes"
    if isinstance(v, (list, tuple)):
        return {"type": "array",
                "items": _value_type(v[0], f"{name}_item") if v else "string"}
    if isinstance(v, dict):  # nested record (e.g. Iceberg manifest data_file)
        # record names must be unique within a schema (Avro spec) — derive
        # them from the field path so two dict-valued fields don't collide
        return {
            "type": "record",
            "name": f"{name}_rec",
            "fields": [
                {"name": str(k), "type": _value_type(x, f"{name}_{k}")}
                for k, x in v.items()
            ],
        }
    return "string"


def _merge_types(a, b):
    if a == b:
        return a
    # Union operands first: merging "null" into an already-nullable union must
    # NOT double-wrap (["null", ["null", X]] is invalid Avro for external
    # readers even though this codec round-trips it).
    if isinstance(a, list) and "null" in a:
        if b == "null":
            return a
        return ["null", _merge_types(next(s for s in a if s != "null"), b)]
    if isinstance(b, list) and "null" in b:
        return _merge_types(b, a)
    if a == "null" or b == "null":  # widen to a nullable union
        other = b if a == "null" else a
        return ["null", other]
    if isinstance(a, str) and isinstance(b, str) and {a, b} == {"long", "double"}:
        return "double"
    if (isinstance(a, dict) and isinstance(b, dict)
            and a.get("type") == "record" and b.get("type") == "record"):
        # field-wise merge: fields present in only one side become nullable
        af = {f["name"]: f["type"] for f in a["fields"]}
        bf = {f["name"]: f["type"] for f in b["fields"]}
        fields = []
        for n in dict.fromkeys(list(af) + list(bf)):
            if n in af and n in bf:
                t = _merge_types(af[n], bf[n])
            else:
                t = _merge_types("null", af.get(n) or bf.get(n))
            fields.append({"name": n, "type": t})
        return {"type": "record", "name": a.get("name", "Rec"), "fields": fields}
    return "string"  # incompatible: fall back to string coercion


def infer_schema(rows, name: str = "Row") -> dict:
    """Record schema inferred over ALL sampled rows (a dict is treated as a
    one-row sample): types widen across rows — None anywhere makes a field a
    nullable union, mixed long/double becomes double, anything else falls
    back to string."""
    if isinstance(rows, dict):
        rows = [rows]
    types: dict[str, Any] = {}
    seen: dict[str, int] = {}
    n_rows = 0
    for row in rows:
        n_rows += 1
        for k, v in row.items():
            t = _value_type(v, str(k))
            types[k] = t if k not in types else _merge_types(types[k], t)
            seen[k] = seen.get(k, 0) + 1
    fields = []
    for k, t in types.items():
        if seen[k] < n_rows:  # absent in some rows ⇒ nullable
            t = _merge_types("null", t)
        if t == "null":
            t = ["null", "string"]
        fields.append({"name": str(k), "type": t})
    return {"type": "record", "name": name, "fields": fields}


def write_avro_file(path: str, rows: Iterator[dict], schema: dict | None = None,
                    codec: str = "null", block_rows: int = 1024) -> int:
    if codec not in ("null", "deflate"):
        raise ValueError(f"unsupported avro codec {codec!r} (null|deflate)")
    rows = iter(rows)
    first = next(rows, None)
    if first is None:
        raise ValueError("cannot write an empty avro file without a schema")
    if schema is None:
        # buffer one block for schema inference over a real sample, not just
        # the first row (a None in row 1 must not type the column "string")
        sample = [first]
        for r in rows:
            sample.append(r)
            if len(sample) >= block_rows:
                break
        schema = infer_schema(sample)
        import itertools

        rows = itertools.chain(sample[1:], rows)
    sync = os.urandom(16)
    n = 0
    with open(path, "wb") as f:
        f.write(MAGIC)
        header = io.BytesIO()
        meta = {"avro.schema": json.dumps(schema).encode(),
                "avro.codec": codec.encode()}
        _write_long(header, len(meta))
        for k, v in meta.items():
            _write_bytes(header, k.encode())
            _write_bytes(header, v)
        _write_long(header, 0)
        f.write(header.getvalue())
        f.write(sync)

        def flush(batch):
            nonlocal n
            if not batch:
                return
            body = io.BytesIO()
            for r in batch:
                _write_value(body, schema, r)
            payload = body.getvalue()
            if codec == "deflate":
                payload = zlib.compress(payload)[2:-4]  # raw deflate per spec
            blk = io.BytesIO()
            _write_long(blk, len(batch))
            _write_long(blk, len(payload))
            f.write(blk.getvalue())
            f.write(payload)
            f.write(sync)
            n += len(batch)

        batch = [first]
        for r in rows:
            batch.append(r)
            if len(batch) >= block_rows:
                flush(batch)
                batch = []
        flush(batch)
    return n


def read_avro_file(path: str) -> Iterator[dict]:
    with open(path, "rb") as f:
        data = f.read()
    buf = io.BytesIO(data)
    if buf.read(4) != MAGIC:
        raise ValueError(f"{path} is not an avro container file")
    meta = {}
    while True:
        cnt = _read_long(buf)
        if cnt == 0:
            break
        if cnt < 0:
            _read_long(buf)
            cnt = -cnt
        for _ in range(cnt):
            k = _read_bytes(buf).decode()
            meta[k] = _read_bytes(buf)
    schema = json.loads(meta["avro.schema"])
    codec = meta.get("avro.codec", b"null").decode()
    sync = buf.read(16)
    while buf.tell() < len(data):
        count = _read_long(buf)
        size = _read_long(buf)
        payload = buf.read(size)
        if codec == "deflate":
            payload = zlib.decompress(payload, -15)
        elif codec != "null":
            raise ValueError(f"unsupported avro codec {codec!r}")
        body = io.BytesIO(payload)
        for _ in range(count):
            yield _read_value(body, schema)
        marker = buf.read(16)
        if marker != sync:
            raise ValueError(f"{path}: bad sync marker (corrupt block)")
