"""Sort / groupby / aggregate for Datasets.

Parity: python/ray/data — Dataset.sort, Dataset.groupby → GroupedData with
count/sum/min/max/mean/std (aggregate fns in data/aggregate.py), unique.
Implementation: blocks are reduced per-block in parallel tasks, then merged
on the consumer (tree-reduce shape); sort materializes (the reference's sort
is also an all-to-all exchange barrier).
"""

from __future__ import annotations

from typing import Any, Callable

import numpy as np

from ray_tpu.data.block import Block
from ray_tpu.data.dataset import Dataset


def sort(ds: Dataset, key: str, descending: bool = False) -> Dataset:
    """Reference: Dataset.sort → sample-sort exchange (planner/exchange/):
    boundary sampling, range partition over tasks, per-partition sort."""

    def source():
        from ray_tpu.data.exchange import sort_exchange

        yield from sort_exchange(ds.iter_blocks(), key, descending)

    return Dataset(source, (), "sort")


def unique(ds: Dataset, column: str) -> list:
    vals: set = set()
    for b in ds.iter_blocks():
        vals.update(np.unique(b.columns[column]).tolist())
    return sorted(vals)


def _block_groups(block: Block, key: str):
    """Yield (group_key, mask) per group in one block; all NaN keys form ONE
    group (nan != nan, and np.unique may emit several)."""
    keys = block.columns[key]
    seen_nan = False
    for gk in np.unique(keys):
        if isinstance(gk, float) and np.isnan(gk):
            if seen_nan:
                continue
            seen_nan = True
            yield float("nan"), np.isnan(keys)
        else:
            yield _scalar(gk), keys == gk


def _agg_partition(block: Block, key: str, fn: Callable, cols: tuple,
                   suffix: str) -> Block:
    """Aggregate one hash partition — post-exchange, every group here is
    complete (all of its rows landed in this partition)."""
    rows = []
    for gk, mask in _block_groups(block, key):
        row = {key: gk}
        for col, vals in block.columns.items():
            if col == key or (cols and col not in cols):
                continue
            arr = vals[mask]
            if not cols and arr.dtype.kind not in "biufc":
                continue  # default aggregation covers numeric columns only
            row[f"{col}_{suffix}" if suffix else col] = fn(arr)
        rows.append(row)
    return Block.from_rows(rows) if rows else Block({})


def _count_partition(block: Block, key: str) -> Block:
    rows = [{key: gk, "count": int(mask.sum())} for gk, mask in _block_groups(block, key)]
    return Block.from_rows(rows) if rows else Block({})


class GroupedData:
    """Reference: data/grouped_data.py GroupedData. Aggregations run as a
    hash-partition exchange (every group lands wholly in one partition —
    _internal/execution/operators/hash_shuffle.py) followed by per-partition
    aggregation tasks."""

    def __init__(self, ds: Dataset, key: str):
        self._ds = ds
        self._key = key

    def _run_exchange(self, partition_agg: Callable[[Block], Block], name: str) -> Dataset:
        ds, key = self._ds, self._key

        def source():
            from ray_tpu.data.exchange import grouped_aggregate

            blocks = list(grouped_aggregate(ds.iter_blocks(), key, partition_agg))
            if not blocks:
                return
            # deterministic output order across runs/partitionings
            merged = Block.concat(blocks)
            order = np.argsort([str(v) for v in merged.columns[key]], kind="stable")
            yield Block({c: v[order] for c, v in merged.columns.items()})

        return Dataset(source, (), name)

    def _agg(self, fn: Callable, cols: tuple, suffix: str) -> Dataset:
        import functools

        return self._run_exchange(
            functools.partial(_agg_partition, key=self._key, fn=fn, cols=cols,
                              suffix=suffix),
            f"groupby.{suffix}",
        )

    def count(self) -> Dataset:
        import functools

        return self._run_exchange(
            functools.partial(_count_partition, key=self._key), "groupby.count"
        )

    def map_groups(self, fn: Callable) -> Dataset:
        """Apply fn to each whole group (reference: GroupedData.map_groups).
        fn receives {col: np.ndarray} for one group and returns a row dict,
        a list of row dicts, or a {col: array} mapping."""
        key = self._key

        def partition_fn(block: Block) -> Block:
            out_rows: list = []
            for gk, mask in _block_groups(block, key):
                group = {c: v[mask] for c, v in block.columns.items()}
                res = fn(group)
                if isinstance(res, dict):
                    vals = list(res.values())
                    if vals and isinstance(vals[0], (np.ndarray, list)):
                        n = len(vals[0])
                        out_rows.extend(
                            {c: (v[i] if hasattr(v, "__len__") else v)
                             for c, v in res.items()}
                            for i in range(n)
                        )
                    else:
                        out_rows.append(res)
                elif isinstance(res, list):
                    out_rows.extend(res)
                else:
                    raise TypeError(f"map_groups fn returned {type(res)}")
            return Block.from_rows(out_rows) if out_rows else Block({})

        ds, k = self._ds, self._key

        def source():
            from ray_tpu.data.exchange import grouped_aggregate

            yield from grouped_aggregate(ds.iter_blocks(), k, partition_fn)

        return Dataset(source, (), "groupby.map_groups")

    def sum(self, *cols) -> Dataset:
        return self._agg(np.sum, cols, "sum")

    def min(self, *cols) -> Dataset:
        return self._agg(np.min, cols, "min")

    def max(self, *cols) -> Dataset:
        return self._agg(np.max, cols, "max")

    def mean(self, *cols) -> Dataset:
        return self._agg(np.mean, cols, "mean")

    def std(self, *cols) -> Dataset:
        return self._agg(lambda a: np.std(a, ddof=1) if len(a) > 1 else 0.0, cols, "std")


def _scalar(x):
    try:
        return x.item()
    except AttributeError:
        return x


# dataset-level simple aggregates (reference: Dataset.sum/min/max/mean/std)
def _nonempty(ds: Dataset, column: str):
    return [b.columns[column] for b in ds.iter_blocks() if b.num_rows()]


def ds_sum(ds: Dataset, column: str):
    chunks = _nonempty(ds, column)
    return float(sum(float(c.sum()) for c in chunks)) if chunks else None


def ds_min(ds: Dataset, column: str):
    chunks = _nonempty(ds, column)
    return float(min(float(c.min()) for c in chunks)) if chunks else None


def ds_max(ds: Dataset, column: str):
    chunks = _nonempty(ds, column)
    return float(max(float(c.max()) for c in chunks)) if chunks else None


def ds_mean(ds: Dataset, column: str):
    chunks = _nonempty(ds, column)
    if not chunks:
        return None
    total = sum(float(c.sum()) for c in chunks)
    n = sum(len(c) for c in chunks)
    return total / n
