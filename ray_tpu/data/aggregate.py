"""Sort / groupby / aggregate for Datasets.

Parity: python/ray/data — Dataset.sort, Dataset.groupby → GroupedData with
count/sum/min/max/mean/std (aggregate fns in data/aggregate.py), unique.
Implementation: blocks are reduced per-block in parallel tasks, then merged
on the consumer (tree-reduce shape); sort materializes (the reference's sort
is also an all-to-all exchange barrier).
"""

from __future__ import annotations

from typing import Any, Callable

import numpy as np

from ray_tpu.data.block import Block
from ray_tpu.data.dataset import Dataset


def sort(ds: Dataset, key: str, descending: bool = False) -> Dataset:
    """Reference: Dataset.sort — global order requires materializing."""

    def source():
        blocks = list(ds.iter_blocks())
        if not blocks:
            return
        merged = Block.concat(blocks)
        order = np.argsort(merged.columns[key], kind="stable")
        if descending:
            order = order[::-1]
        yield Block({k: v[order] for k, v in merged.columns.items()})

    return Dataset(source, (), "sort")


def unique(ds: Dataset, column: str) -> list:
    vals: set = set()
    for b in ds.iter_blocks():
        vals.update(np.unique(b.columns[column]).tolist())
    return sorted(vals)


class GroupedData:
    """Reference: data/grouped_data.py GroupedData."""

    def __init__(self, ds: Dataset, key: str):
        self._ds = ds
        self._key = key

    _NAN_KEY = "\x00__nan_group__"  # merges NaN keys across blocks (nan != nan)

    def _gather(self) -> dict[Any, dict[str, list[np.ndarray]]]:
        groups: dict[Any, dict[str, list]] = {}
        for b in self._ds.iter_blocks():
            keys = b.columns[self._key]
            for gk in np.unique(keys):
                if isinstance(gk, float) and np.isnan(gk):
                    mask = np.isnan(keys)
                    group_key = self._NAN_KEY
                else:
                    mask = keys == gk
                    group_key = _scalar(gk)
                slot = groups.setdefault(group_key, {})
                for col, vals in b.columns.items():
                    slot.setdefault(col, []).append(vals[mask])
        return groups

    def _agg(self, fn: Callable, cols: tuple, suffix: str) -> Dataset:
        groups = self._gather()
        rows = []
        for gk, colmap in sorted(groups.items(), key=lambda kv: str(kv[0])):
            row = {self._key: gk}
            for col, chunks in colmap.items():
                if col == self._key or (cols and col not in cols):
                    continue
                arr = np.concatenate(chunks)
                if not cols and arr.dtype.kind not in "biufc":
                    continue  # default aggregation covers numeric columns only
                row[f"{col}_{suffix}" if suffix else col] = fn(arr)
            rows.append(row)
        return Dataset(lambda r=rows: iter([Block.from_rows(r)] if r else []), (), f"groupby.{suffix}")

    def count(self) -> Dataset:
        groups = self._gather()
        rows = [{self._key: gk, "count": len(np.concatenate(cm[self._key]))}
                for gk, cm in sorted(groups.items(), key=lambda kv: str(kv[0]))]
        return Dataset(lambda: iter([Block.from_rows(rows)] if rows else []), (), "groupby.count")

    def sum(self, *cols) -> Dataset:
        return self._agg(np.sum, cols, "sum")

    def min(self, *cols) -> Dataset:
        return self._agg(np.min, cols, "min")

    def max(self, *cols) -> Dataset:
        return self._agg(np.max, cols, "max")

    def mean(self, *cols) -> Dataset:
        return self._agg(np.mean, cols, "mean")

    def std(self, *cols) -> Dataset:
        return self._agg(lambda a: np.std(a, ddof=1) if len(a) > 1 else 0.0, cols, "std")


def _scalar(x):
    try:
        return x.item()
    except AttributeError:
        return x


# dataset-level simple aggregates (reference: Dataset.sum/min/max/mean/std)
def _nonempty(ds: Dataset, column: str):
    return [b.columns[column] for b in ds.iter_blocks() if b.num_rows()]


def ds_sum(ds: Dataset, column: str):
    chunks = _nonempty(ds, column)
    return float(sum(float(c.sum()) for c in chunks)) if chunks else None


def ds_min(ds: Dataset, column: str):
    chunks = _nonempty(ds, column)
    return float(min(float(c.min()) for c in chunks)) if chunks else None


def ds_max(ds: Dataset, column: str):
    chunks = _nonempty(ds, column)
    return float(max(float(c.max()) for c in chunks)) if chunks else None


def ds_mean(ds: Dataset, column: str):
    chunks = _nonempty(ds, column)
    if not chunks:
        return None
    total = sum(float(c.sum()) for c in chunks)
    n = sum(len(c) for c in chunks)
    return total / n
