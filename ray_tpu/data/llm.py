"""Batch LLM inference over Datasets.

Parity: python/ray/data/llm.py (ProcessorConfig :26, build_llm_processor :104)
and the staged batch pipeline in ray.llm _internal/batch/stages/
(chat_template → tokenize → engine → detokenize). The engine stage runs the
same continuous-batching LLMEngine the serve path uses — one engine per
processor, shared across blocks, so the MXU sees full decode batches even when
dataset blocks are small.

Plane-native since ISSUE-12: the engine stage CONSUMES THE STREAMING
EXECUTOR — upstream blocks arrive as plane descriptors
(``Dataset.iter_block_refs``), materialize one at a time at the engine's
edge, and every prompt is submitted the moment its block lands while up to
``max_inflight_batches`` earlier blocks are still decoding. Dataset blocks
feed the engine's continuous batches WITHOUT materializing the dataset:
the driver holds a bounded window of in-flight batches, never the corpus.
"""

from __future__ import annotations

import dataclasses
from collections import deque
from typing import Any, Callable, Optional

import numpy as np

from ray_tpu.data.block import Block
from ray_tpu.data.dataset import Dataset
from ray_tpu.serve.llm import LLMConfig, LLMEngine


@dataclasses.dataclass
class ProcessorConfig:
    """Reference: data/llm.py ProcessorConfig / vLLMEngineProcessorConfig."""

    llm_config: LLMConfig = dataclasses.field(default_factory=LLMConfig)
    prompt_column: str = "prompt_ids"
    output_column: str = "generated_ids"
    max_new_tokens: int | None = None
    tokenizer: Callable[[str], list[int]] | None = None
    detokenizer: Callable[[list[int]], str] | None = None
    batch_size: int = 16
    # Engine-feed window: how many dataset batches may be decoding at once
    # before the stream stops pulling upstream (the engine-side analog of
    # the executor's byte budget — keeps slots full across block
    # boundaries, bounds driver residency).
    max_inflight_batches: int = 4
    generate_timeout_s: float = 600.0


class Processor:
    """Dataset -> Dataset map with a shared generation engine fed by the
    streaming executor (blocks in flight bounded, prompts submitted as
    blocks land, outputs yielded in input order)."""

    def __init__(self, config: ProcessorConfig, engine: LLMEngine | None = None):
        self.config = config
        self._engine = engine

    def _get_engine(self) -> LLMEngine:
        if self._engine is None:
            self._engine = LLMEngine(self.config.llm_config)
        return self._engine

    def _tokenize(self, prompts) -> list[list[int]]:
        cfg = self.config
        token_lists = []
        for p in prompts:
            if cfg.tokenizer is not None and isinstance(p, str):
                token_lists.append(list(cfg.tokenizer(p)))
            else:
                token_lists.append([int(t) for t in np.asarray(p).tolist()])
        return token_lists

    def _submit_batch(self, engine: LLMEngine, batch: dict):
        """Submit every prompt of one batch; continuous batching interleaves
        them with whatever earlier batches are still decoding."""
        toks = self._tokenize(batch[self.config.prompt_column])
        futs = [engine.generate(t, self.config.max_new_tokens) for t in toks]
        return batch, futs

    def _finish_batch(self, pending) -> Block:
        cfg = self.config
        batch, futs = pending
        results = [f.result(cfg.generate_timeout_s) for f in futs]
        out = dict(batch)
        generated = [r.token_ids for r in results]
        if cfg.detokenizer is not None:
            out[cfg.output_column.replace("_ids", "_text")] = np.asarray(
                [cfg.detokenizer(g) for g in generated], dtype=object
            )
        out[cfg.output_column] = np.asarray(generated, dtype=object)
        out["num_generated"] = np.asarray([r.num_generated for r in results])
        return Block.from_numpy(out)

    def __call__(self, dataset: Dataset) -> Dataset:
        cfg = self.config
        proc = self

        def batches():
            # blocks arrive as plane descriptors and land here, at the
            # engine edge; batching stays WITHIN blocks (prompt columns may
            # be ragged — cross-block concat is not defined for them)
            for blk in dataset.iter_blocks():
                rows = blk.num_rows()
                if rows == 0:
                    continue
                for i in range(0, rows, max(1, cfg.batch_size)):
                    yield blk.slice(i, min(i + cfg.batch_size, rows)).to_numpy()

        def source():
            engine = proc._get_engine()
            window: deque = deque()
            for batch in batches():
                window.append(proc._submit_batch(engine, batch))
                # the NEXT batch is admitted while these decode; drain the
                # head only once the window is full — input-order outputs,
                # engine slots stay occupied across batch boundaries
                while len(window) >= max(1, cfg.max_inflight_batches):
                    yield proc._finish_batch(window.popleft())
            while window:
                yield proc._finish_batch(window.popleft())

        return Dataset(source, (), f"{dataset._name}.llm")

    def shutdown(self) -> None:
        if self._engine is not None:
            self._engine.shutdown()


def build_llm_processor(config: ProcessorConfig) -> Processor:
    """Reference: data/llm.py:104 build_llm_processor."""
    return Processor(config)
