"""Batch LLM inference over Datasets.

Parity: python/ray/data/llm.py (ProcessorConfig :26, build_llm_processor :104)
and the staged batch pipeline in ray.llm _internal/batch/stages/
(chat_template → tokenize → engine → detokenize). The engine stage runs the
same continuous-batching LLMEngine the serve path uses — one engine per
processor, shared across blocks, so the MXU sees full decode batches even when
dataset blocks are small.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Optional

import numpy as np

from ray_tpu.data.dataset import Dataset
from ray_tpu.serve.llm import LLMConfig, LLMEngine


@dataclasses.dataclass
class ProcessorConfig:
    """Reference: data/llm.py ProcessorConfig / vLLMEngineProcessorConfig."""

    llm_config: LLMConfig = dataclasses.field(default_factory=LLMConfig)
    prompt_column: str = "prompt_ids"
    output_column: str = "generated_ids"
    max_new_tokens: int | None = None
    tokenizer: Callable[[str], list[int]] | None = None
    detokenizer: Callable[[list[int]], str] | None = None
    batch_size: int = 16


class Processor:
    """Dataset -> Dataset map with a shared generation engine."""

    def __init__(self, config: ProcessorConfig, engine: LLMEngine | None = None):
        self.config = config
        self._engine = engine

    def _get_engine(self) -> LLMEngine:
        if self._engine is None:
            self._engine = LLMEngine(self.config.llm_config)
        return self._engine

    def __call__(self, dataset: Dataset) -> Dataset:
        cfg = self.config

        def generate_batch(batch: dict) -> dict:
            engine = self._get_engine()
            prompts = batch[cfg.prompt_column]
            token_lists = []
            for p in prompts:
                if cfg.tokenizer is not None and isinstance(p, str):
                    token_lists.append(list(cfg.tokenizer(p)))
                else:
                    token_lists.append([int(t) for t in np.asarray(p).tolist()])
            # overlap: submit everything, let continuous batching fill slots
            futs = [engine.generate(toks, cfg.max_new_tokens) for toks in token_lists]
            results = [f.result(600) for f in futs]
            out = dict(batch)
            generated = [r.token_ids for r in results]
            if cfg.detokenizer is not None:
                out[cfg.output_column.replace("_ids", "_text")] = np.asarray(
                    [cfg.detokenizer(g) for g in generated], dtype=object
                )
            out[cfg.output_column] = np.asarray(generated, dtype=object)
            out["num_generated"] = np.asarray([r.num_generated for r in results])
            return out

        # num_cpus=0: the stage blocks on the engine, not a CPU slot — keeps the
        # streaming executor from serializing engine-bound blocks behind CPU caps
        return dataset.map_batches(generate_batch, batch_size=cfg.batch_size, num_cpus=0)

    def shutdown(self) -> None:
        if self._engine is not None:
            self._engine.shutdown()


def build_llm_processor(config: ProcessorConfig) -> Processor:
    """Reference: data/llm.py:104 build_llm_processor."""
    return Processor(config)
