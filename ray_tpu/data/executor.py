"""Streaming executor: pull-based pipeline of block transforms over ray_tpu tasks.

Parity: python/ray/data/_internal/execution/streaming_executor.py:103
(StreamingExecutor; run loop :397, step :472) + backpressure_policy/. Design kept:
operators process blocks as tasks with a bounded number in flight (backpressure);
blocks stream to the consumer as soon as their chain completes — no barrier
between stages (outputs of op k feed op k+1 immediately).

Since ISSUE-12 the default engine is the PLANE-NATIVE executor in
``data/streaming.py``: intermediate blocks live as sealed object-plane
entries, tasks exchange descriptors, admission is byte-budgeted off
``node_io_view`` pressure, and the driver materializes blocks only at the
consumer edge. The legacy driver-get pipeline below (every operator
boundary ``ray_tpu.get``s block payloads back to the driver) is kept as
the measured A/B baseline — select it with
``RAY_TPU_DATA_PLANE_STREAMING=0``.
"""

from __future__ import annotations

import queue
import threading
from dataclasses import dataclass
from typing import Any, Callable, Iterator

import ray_tpu
from ray_tpu.data.block import Block
from ray_tpu.data.streaming import (
    StreamOpStats,
    _StreamError,
    plane_streaming_enabled,
)

# Unified per-operator stats row (legacy name kept for callers; the legacy
# engine fills the byte counters too — only plane_pulls and
# backpressure_s stay zero there).
OpStats = StreamOpStats


@dataclass
class ActorPoolStrategy:
    """Run a stage's UDF on a pool of long-lived actors instead of stateless
    tasks — stateful/expensive-to-construct UDFs (model replicas, tokenizers)
    initialize once per actor (reference:
    data/_internal/execution/operators/actor_pool_map_operator.py +
    ActorPoolStrategy in compute.py)."""

    size: int = 2
    max_tasks_in_flight_per_actor: int = 2


@dataclass
class PhysicalOp:
    """One pipeline stage: Block -> list[Block] executed as a ray_tpu task
    (or on an actor pool, see `compute`)."""

    name: str
    transform: Callable[[Block], list[Block]]
    num_cpus: float = 1.0
    max_in_flight: int = 4
    # "tasks" | ActorPoolStrategy — the reference's compute strategy knob
    compute: Any = "tasks"
    # Constructed once per pool actor (stateful UDFs); falls back to
    # `transform` when None.
    transform_factory: Callable[[], Callable[[Block], list[Block]]] | None = None
    # Memory-aware backpressure: stop pulling upstream while the estimated
    # bytes of in-flight input blocks exceed this budget (reference:
    # streaming_executor_state.py:841 under_resource_limits +
    # backpressure_policy/). None = the executor default
    # (RAY_TPU_DATA_OP_BUDGET_BYTES on the plane-native path; window-only
    # backpressure on the legacy path).
    memory_budget_bytes: int | None = None


def execute_streaming(
    source: Iterator[Block],
    ops: list[PhysicalOp],
    preserve_order: bool = True,
    stats_sink: list | None = None,
) -> Iterator[Block]:
    """Run blocks from `source` through `ops`, yielding result blocks.

    Each op keeps ≤ max_in_flight tasks outstanding (and ≤ its byte
    budget); completed blocks flow to the next op without waiting for stage
    completion (streaming, not bulk). Per-op counters land in `stats_sink`
    (reference: data stats.py). On the default plane-native path the
    yielded blocks are materialized HERE (the consumer edge) — mid-pipeline
    they were descriptors."""
    from ray_tpu.data import streaming

    if plane_streaming_enabled():
        return streaming.materialize(
            streaming.execute_streaming_refs(
                source, ops, preserve_order=preserve_order,
                stats_sink=stats_sink))
    return _execute_streaming_driver_get(
        source, ops, preserve_order, stats_sink)


def _execute_streaming_driver_get(
    source: Iterator[Block],
    ops: list[PhysicalOp],
    preserve_order: bool = True,
    stats_sink: list | None = None,
) -> Iterator[Block]:
    """LEGACY engine (the ISSUE-12 A/B baseline): every operator boundary
    materializes block payloads on the driver."""
    # NOTE: not a generator — stats register eagerly (in pipeline order) even
    # though block flow is lazy; the inner generator does the streaming.
    stats = [OpStats(op.name) for op in ops]
    if stats_sink is not None:
        stats_sink.extend(stats)
    stream: Iterator[Block] = source
    for op, st in zip(ops, stats):
        stream = _apply_op(stream, op, st, preserve_order)
    return stream


class _TransformActor:
    """Pool actor hosting one constructed-once transform (reference:
    actor_pool_map_operator's _MapWorker)."""

    def __init__(self, factory):
        self._transform = factory()

    def run(self, block):
        return self._transform(block)


def _apply_op(
    upstream: Iterator[Block], op: PhysicalOp, stats: OpStats, preserve_order: bool
) -> Iterator[Block]:
    pool = None
    loads: dict = {}
    if isinstance(op.compute, ActorPoolStrategy):
        factory = op.transform_factory or (lambda t=op.transform: t)
        actor_cls = ray_tpu.remote(num_cpus=op.num_cpus)(_TransformActor)
        pool = [actor_cls.remote(factory) for _ in range(max(1, op.compute.size))]
        loads = {i: 0 for i in range(len(pool))}
        window = len(pool) * max(1, op.compute.max_tasks_in_flight_per_actor)
    else:
        remote_fn = ray_tpu.remote(num_cpus=op.num_cpus, name=f"data::{op.name}")(
            _run_transform
        )
        window = op.max_in_flight

    def submit(blk):
        if pool is None:
            return remote_fn.remote(op.transform, blk), None
        idx = min(loads, key=loads.get)  # least-loaded actor
        loads[idx] += 1
        return pool[idx].run.remote(blk), idx

    in_flight: list = []   # [(ref, actor_idx|None, est_bytes)]
    in_flight_bytes = 0
    upstream_done = False
    up = iter(upstream)
    try:
        while True:
            # fill the window (concurrency AND memory backpressure; always
            # admit one so an over-budget single block still makes progress)
            while not upstream_done and len(in_flight) < window and (
                op.memory_budget_bytes is None
                or in_flight_bytes < op.memory_budget_bytes
                or not in_flight
            ):
                try:
                    blk = next(up)
                except StopIteration:
                    upstream_done = True
                    break
                stats.blocks_in += 1
                est = blk.size_bytes()
                stats.bytes_in += est
                ref, idx = submit(blk)
                in_flight.append((ref, idx, est))
                in_flight_bytes += est
            if not in_flight:
                if upstream_done:
                    return
                continue
            if preserve_order:
                ready_ref, idx, est = in_flight.pop(0)
            else:
                ready, _ = ray_tpu.wait([r for r, _, _ in in_flight],
                                        num_returns=1, timeout=None)
                pos = next(i for i, (r, _, _) in enumerate(in_flight)
                           if r == ready[0])
                ready_ref, idx, est = in_flight.pop(pos)
            in_flight_bytes -= est
            if idx is not None:
                loads[idx] -= 1
            out_blocks = ray_tpu.get(ready_ref)
            for b in out_blocks:
                stats.blocks_out += 1
                stats.rows_out += b.num_rows()
                stats.bytes_out += b.size_bytes()
                yield b
    finally:
        for a in pool or ():
            try:
                ray_tpu.kill(a)
            except Exception:
                pass


def _run_transform(transform: Callable[[Block], list[Block]], block: Block) -> list[Block]:
    return transform(block)


class OutputSplitter:
    """Fan one block stream out to n consumers (reference:
    execution/operators/output_splitter.py backing Dataset.streaming_split)
    — the LEGACY driver-side splitter (block payloads pass through the
    driver's queues); the plane-native splitter is
    ``data/streaming.py::RefOutputSplitter``.

    equal=True slices every block into n equal parts so shard row counts differ
    by at most 1 per block — required when each SPMD rank must step the same
    number of batches.
    """

    def __init__(self, stream: Iterator[Block], n: int, equal: bool = False):
        self.equal = equal
        self.queues: list["queue.Queue[Block | _StreamError | None]"] = [
            queue.Queue(maxsize=4) for _ in range(n)
        ]
        self._thread = threading.Thread(target=self._pump, args=(stream,), daemon=True)
        self._thread.start()

    def _pump(self, stream: Iterator[Block]) -> None:
        i = 0
        n = len(self.queues)
        err: BaseException | None = None
        try:
            for block in stream:
                if self.equal:
                    rows = block.num_rows()
                    per = rows // n
                    extra = rows % n
                    start = 0
                    for q in range(n):
                        take = per + (1 if q < extra else 0)
                        if take:
                            self.queues[(i + q) % n].put(block.slice(start, start + take))
                        start += take
                    i += extra  # rotate who gets the remainder rows
                else:
                    self.queues[i % n].put(block)
                    i += 1
        except BaseException as e:  # noqa: BLE001 - propagate to every consumer
            err = e
        finally:
            tail = _StreamError(err) if err is not None else None
            for q in self.queues:
                q.put(tail)

    def iterator(self, idx: int) -> Iterator[Block]:
        q = self.queues[idx]
        while True:
            item = q.get()
            if item is None:
                return
            if isinstance(item, _StreamError):
                raise item.exc
            yield item
