"""Hermetic local-filesystem readers for lakehouse table formats.

Delta Lake and Apache Iceberg tables are plain files + metadata: Delta's
transaction log is newline-delimited JSON actions next to parquet data files;
Iceberg's metadata is a JSON file pointing at avro manifest lists/manifests
pointing at parquet data files. Neither needs a vendor SDK to read from local
storage, so unlike the reference (which delegates to deltalake/pyiceberg in
python/ray/data/_internal/datasource/delta_sharing_datasource.py and
iceberg_datasource.py), these readers parse the open formats directly with
the in-repo parquet and avro codecs. Cloud object stores need egress + SDKs
this environment lacks; path-based local/NFS warehouses are fully supported.
"""

from __future__ import annotations

import glob as _glob
import json
import os
from typing import Any


class DeltaProtocolError(ValueError):
    pass


def _delta_log_entries(table_path: str) -> tuple[dict[int, list[str]], dict[int, str]]:
    """{checkpoint version: sorted part paths} + {version: commit-json-path}."""
    log_dir = os.path.join(table_path, "_delta_log")
    if not os.path.isdir(log_dir):
        raise DeltaProtocolError(
            f"{table_path!r} is not a Delta table (no _delta_log/ directory)"
        )
    commits: dict[int, str] = {}
    checkpoints: dict[int, list[str]] = {}  # version -> part file(s)
    for p in _glob.glob(os.path.join(log_dir, "*")):
        base = os.path.basename(p)
        if base.endswith(".json") and base[: -len(".json")].isdigit():
            commits[int(base[: -len(".json")])] = p
        elif base.endswith(".parquet") and ".checkpoint" in base:
            # single-part: NN.checkpoint.parquet
            # multi-part:  NN.checkpoint.MM.PP.parquet (delta PROTOCOL.md) —
            # all parts of a version together hold the full state
            head = base.split(".checkpoint", 1)[0]
            if head.isdigit():
                checkpoints.setdefault(int(head), []).append(p)
    if not commits and not checkpoints:
        raise DeltaProtocolError(f"empty _delta_log in {table_path!r}")
    for v, parts in checkpoints.items():
        parts.sort()
    return checkpoints, commits


def _declared_part_count(part_path: str):
    """PP from NN.checkpoint.MM.PP.parquet; None for single-part checkpoints."""
    base = os.path.basename(part_path)
    fields = base[: -len(".parquet")].split(".")
    if len(fields) == 4 and fields[1] == "checkpoint":
        try:
            return int(fields[3])
        except ValueError:
            return None
    return None


def _apply_checkpoint_part(path: str, active: dict, _read_parquet) -> None:
    """Fold one checkpoint parquet (or one part of a multi-part checkpoint)
    into the active-file map. Checkpoint rows carry one action per row; the
    'add' struct arrives either flattened (add.path columns) or as an object
    column of dicts, depending on the writer."""
    cols = _read_parquet(path)
    add_paths = cols.get("add.path")
    if add_paths is None and "add" in cols:
        for a in cols["add"]:
            if isinstance(a, dict) and a.get("path"):
                active[a["path"]] = a.get("partitionValues") or {}
    elif add_paths is not None:
        pvals = cols.get("add.partitionValues", [None] * len(add_paths))
        for pth, pv in zip(add_paths, pvals):
            if pth is not None:
                active[str(pth)] = pv if isinstance(pv, dict) else {}


def delta_active_files(table_path: str, version: int | None = None) -> tuple[list[str], list[dict]]:
    """Replay the Delta transaction log; return (data file paths, partition values).

    Supports JSON commits and parquet checkpoints (a checkpoint replaces the
    log prefix up to its version). ``version`` time-travels to that commit.
    """
    checkpoints, commits = _delta_log_entries(table_path)
    start_version = 0
    active: dict[str, dict] = {}  # relative path -> partitionValues

    # newest checkpoint version at or below the requested version
    eligible = [v for v in checkpoints if version is None or v <= version]
    ckpt_version_used = max(eligible) if eligible else None
    if ckpt_version_used is None and commits and 0 not in commits:
        # pre-checkpoint commits were vacuumed and no checkpoint covers them:
        # replaying the surviving tail alone would silently drop files
        raise DeltaProtocolError(
            f"delta log in {table_path!r} starts at version {min(commits)} "
            "with no usable checkpoint — cannot reconstruct table state"
        )
    if ckpt_version_used is not None:
        from ray_tpu.data.read_api import _read_parquet

        parts_list = checkpoints[ckpt_version_used]
        # multi-part names encode the total (NN.checkpoint.MM.PP.parquet):
        # an incomplete part set (writer crash mid-checkpoint) must fail, not
        # silently return a table missing the absent parts' files
        declared = _declared_part_count(parts_list[0])
        if declared is not None and len(parts_list) != declared:
            raise DeltaProtocolError(
                f"checkpoint {ckpt_version_used} in {table_path!r} has "
                f"{len(parts_list)}/{declared} parts — incomplete checkpoint"
            )
        for part in parts_list:
            _apply_checkpoint_part(part, active, _read_parquet)
        start_version = ckpt_version_used + 1
    for v in sorted(commits):
        if v < start_version:
            continue
        if version is not None and v > version:
            break
        with open(commits[v]) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                action = json.loads(line)
                if "add" in action:
                    add = action["add"]
                    active[add["path"]] = add.get("partitionValues") or {}
                elif "remove" in action:
                    active.pop(action["remove"]["path"], None)
    paths, parts = [], []
    for rel, pv in active.items():
        paths.append(rel if os.path.isabs(rel) else os.path.join(table_path, rel))
        parts.append(pv)
    return paths, parts


def _iceberg_current_metadata(table_path: str) -> dict:
    meta_dir = os.path.join(table_path, "metadata")
    if not os.path.isdir(meta_dir):
        raise ValueError(f"{table_path!r} is not an Iceberg table (no metadata/ dir)")
    hint = os.path.join(meta_dir, "version-hint.text")
    candidates = sorted(_glob.glob(os.path.join(meta_dir, "*.metadata.json")))
    if os.path.isfile(hint):
        with open(hint) as f:
            v = f.read().strip()
        for pat in (f"v{v}.metadata.json", f"{v}.metadata.json"):
            p = os.path.join(meta_dir, pat)
            if os.path.isfile(p):
                return _load_json(p)
    if not candidates:
        raise ValueError(f"no *.metadata.json under {meta_dir!r}")
    return _load_json(candidates[-1])


def _load_json(path: str) -> dict:
    with open(path) as f:
        return json.load(f)


def _resolve_location(uri: str, table_path: str, meta: dict) -> str:
    """Map a metadata file URI onto the local table directory."""
    for scheme in ("file://", "s3a://", "s3://", "gs://", "abfs://", "hdfs://"):
        if uri.startswith(scheme):
            uri = uri[len(scheme):]
            break
    if os.path.isfile(uri):
        return uri
    # re-root: metadata written elsewhere ('location' prefix) but files moved
    # with the table dir — strip the recorded table location prefix.
    loc = (meta.get("location") or "").rstrip("/")
    for scheme in ("file://", "s3a://", "s3://", "gs://", "abfs://", "hdfs://"):
        if loc.startswith(scheme):
            loc = loc[len(scheme):]
            break
    if loc and uri.startswith(loc + "/"):
        rel = uri[len(loc) + 1:]
        cand = os.path.join(table_path, rel)
        if os.path.isfile(cand):
            return cand
    # last resort: match by basename under the table dir
    base = os.path.basename(uri)
    hits = _glob.glob(os.path.join(table_path, "**", base), recursive=True)
    if hits:
        return hits[0]
    raise FileNotFoundError(f"Iceberg file {uri!r} not found under {table_path!r}")


def iceberg_data_files(table_path: str, snapshot_id: int | None = None) -> list[str]:
    """Walk Iceberg metadata → manifest list → manifests → live data files.

    Manifest avro files are decoded with the in-repo container codec
    (data/avro.py); entry status 2 (DELETED) drops the file.
    """
    from ray_tpu.data.avro import read_avro_file

    meta = _iceberg_current_metadata(table_path)
    snaps = meta.get("snapshots") or []
    if not snaps:
        return []
    if snapshot_id is None:
        snapshot_id = meta.get("current-snapshot-id")
        if snapshot_id in (None, -1):
            snapshot_id = snaps[-1].get("snapshot-id")
    snap = next((s for s in snaps if s.get("snapshot-id") == snapshot_id), None)
    if snap is None:
        raise ValueError(f"snapshot {snapshot_id} not in {table_path!r}")

    manifests: list[str] = []
    if snap.get("manifest-list"):
        mlist = _resolve_location(snap["manifest-list"], table_path, meta)
        for entry in read_avro_file(mlist):
            manifests.append(entry["manifest_path"])
    else:  # v1 tables may inline 'manifests'
        manifests = list(snap.get("manifests") or [])

    out: list[str] = []
    for m_uri in manifests:
        m_path = _resolve_location(m_uri, table_path, meta)
        for entry in read_avro_file(m_path):
            status = entry.get("status", 1)
            df: Any = entry.get("data_file") or {}
            fp = df.get("file_path") if isinstance(df, dict) else None
            if fp and status != 2:
                out.append(_resolve_location(fp, table_path, meta))
            elif fp and status == 2:
                resolved = _resolve_location(fp, table_path, meta)
                if resolved in out:
                    out.remove(resolved)
    return out
