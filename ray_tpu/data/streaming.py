"""Streaming data plane: plane-native block exchange with byte-budgeted
backpressure.

Parity: python/ray/data/_internal/execution/streaming_executor.py +
backpressure_policy/ — but grown onto THIS runtime's substrate instead of
bypassing it: intermediate blocks live as sealed object-plane entries
(workers ``put`` their outputs into the node-local store, ISSUE-5 zero-copy
BLOB path) and move holder→consumer via ``pull_into``; the driver carries
only **descriptors** (``BlockRef``: ref + rows + bytes), never block
payloads. The legacy executor (``data/executor.py`` pre-ISSUE-12)
``ray_tpu.get()`` every block back to the driver at every operator
boundary — the driver was a copy bottleneck and the PR-5/PR-8 substrate
went unused.

Admission is byte-budgeted, not block-counted (reference:
streaming_executor_state.py under_resource_limits + the PR-5 plane pull
budget): each operator keeps at most ``RAY_TPU_DATA_OP_BUDGET_BYTES`` of
input bytes in flight, and stops pulling upstream while the consuming
node's I/O is hot (``node_io_view()`` pending-pull bytes / the local plane
client's in-flight bytes — the ISSUE-8 pressure signal, read through the
``core/object_plane.py`` budget hooks). Stalls are metered
(``ray_tpu_data_backpressure_seconds_total``) and flight-recorded on the
"data" ring.

Hot-path contract (AST-linted by ``scripts/check_wire_schemas.py::
check_data_streaming_hot_path``): the pump/fetch loops record only through
instrument handles bound at operator-install time — no metric
construction, no registry lookups, and no raw control-plane
``call``/``notify`` (tasks and gets go through the public ``ray_tpu``
API, which owns retry/failover).
"""

from __future__ import annotations

import os
import queue as _queue
import threading
import time
import weakref
from collections import deque
from dataclasses import dataclass
from typing import Any, Callable, Iterator

import ray_tpu
from ray_tpu.data.block import Block
from ray_tpu.util import flight_recorder
from ray_tpu.util.metrics import Counter, Gauge

# Per-operator bytes-in-flight admission budget (the analog of the plane's
# RAY_TPU_PLANE_PULL_BYTES, at operator granularity).
OP_BUDGET_BYTES = int(
    os.environ.get("RAY_TPU_DATA_OP_BUDGET_BYTES", str(128 << 20)))
# Stop admitting upstream blocks while a node's pending pull bytes exceed
# this fraction of the plane pull budget (the node_io_view hot signal).
PRESSURE_FRACTION = float(
    os.environ.get("RAY_TPU_DATA_PRESSURE_FRACTION", "0.8"))
# How long a sampled pressure verdict stays fresh — admission runs per
# block, the cluster view is polled at most once per TTL.
PRESSURE_TTL_S = float(os.environ.get("RAY_TPU_DATA_PRESSURE_TTL_S", "0.2"))
def plane_streaming_enabled() -> bool:
    """Read per execution (not cached at import) so the interleaved A/B can
    flip engines within one process: "0" restores the legacy driver-get
    executor."""
    return os.environ.get("RAY_TPU_DATA_PLANE_STREAMING", "1") != "0"

# ---------------------------------------------------------------- metrics
# Families registered once at import; per-op handles bind at operator
# install time (_OpInstruments) — the pump loop records through handles
# only (util/metrics.py hot-path contract).
_M_BYTES_IN = Counter(
    "ray_tpu_data_op_bytes_in_total",
    "block bytes admitted into each streaming operator", tag_keys=("op",))
_M_BYTES_OUT = Counter(
    "ray_tpu_data_op_bytes_out_total",
    "block bytes produced by each streaming operator", tag_keys=("op",))
_M_ROWS_OUT = Counter(
    "ray_tpu_data_op_rows_out_total",
    "rows produced by each streaming operator", tag_keys=("op",))
_M_STALL = Counter(
    "ray_tpu_data_backpressure_seconds_total",
    "seconds each operator spent admission-blocked (byte budget or node "
    "I/O pressure)", tag_keys=("op", "cause"))
_M_FETCHES = Counter(
    "ray_tpu_data_plane_block_fetches_total",
    "blocks materialized from plane descriptors in this process").bind()
_M_DRIVER_BYTES = Counter(
    "ray_tpu_data_driver_block_bytes_total",
    "block payload bytes materialized in this process at the consumer "
    "edge — the driver-transit counter the plane-native A/B asserts "
    "stays flat through exchanges").bind()

# Live op drivers, sampled at scrape time for the in-flight gauge.
_LIVE_OPS: "weakref.WeakSet[_OpDriver]" = weakref.WeakSet()


def _op_inflight_producer():
    agg: dict[str, float] = {}
    for d in list(_LIVE_OPS):
        agg[d.stats.name] = agg.get(d.stats.name, 0.0) + d.inflight_bytes
    return [({"op": n}, v) for n, v in agg.items()]


Gauge("ray_tpu_data_op_inflight_bytes",
      "input bytes currently in flight per streaming operator",
      tag_keys=("op",)).attach_producer(_op_inflight_producer)


# ------------------------------------------------------------- descriptors
@dataclass
class BlockRef:
    """Driver-side handle to a plane-resident block: the ref plus the
    metadata every scheduling decision needs (rows for batching/limit,
    bytes for admission) — block payloads never ride along."""

    ref: Any  # ObjectRef
    num_rows: int
    size_bytes: int


def put_block(block: Block) -> BlockRef:
    """Seal a block into this process's store and hand back its
    descriptor. In a worker this is a node-local client put (the node
    holds the primary; the head records only the location)."""
    return BlockRef(ray_tpu.put(block), block.num_rows(), block.size_bytes())


def ensure_ref(item: "Block | BlockRef") -> BlockRef:
    return item if isinstance(item, BlockRef) else put_block(item)


def fetch_block(item: "Block | BlockRef", timeout: float | None = None) -> Block:
    """Materialize one block in THIS process (consumer edge): a
    plane-resident block lands via the zero-copy ``pull_into`` path of the
    local runtime's get. The only place descriptor payloads are touched."""
    if isinstance(item, Block):
        return item
    blk = ray_tpu.get(item.ref, timeout=timeout)
    _M_FETCHES.inc()
    _M_DRIVER_BYTES.inc(item.size_bytes)
    return blk


def item_rows(item: "Block | BlockRef") -> int:
    return item.num_rows if isinstance(item, BlockRef) else item.num_rows()


def item_bytes(item: "Block | BlockRef") -> int:
    return item.size_bytes if isinstance(item, BlockRef) else item.size_bytes()


# ---------------------------------------------------------------- pressure
# Test hook: replace the cluster sample with a deterministic callable.
_pressure_provider: "Callable[[], bool] | None" = None
_pressure_cache = [0.0, False]  # [sampled_at_monotonic, verdict]
_pressure_lock = threading.Lock()


def set_pressure_provider(fn: "Callable[[], bool] | None") -> None:
    """Override the node-I/O pressure sample (tests / embedders). ``None``
    restores the real node_io_view-backed sample."""
    global _pressure_provider
    _pressure_provider = fn
    with _pressure_lock:
        _pressure_cache[0] = 0.0


def _sample_pressure() -> bool:
    """One real pressure sample: local plane-client in-flight bytes vs the
    plane budget (any process), plus — on the head — every node's pending
    pull bytes from node_io_view()."""
    from ray_tpu.core import object_plane

    budget = max(1, object_plane.pull_budget_bytes())
    if object_plane.local_inflight_pull_bytes() > PRESSURE_FRACTION * budget:
        return True
    try:
        from ray_tpu.core.runtime import get_runtime_or_none

        rt = get_runtime_or_none()
        if rt is None or not hasattr(rt, "scheduler"):
            return False  # worker/client process: local signal only
        from ray_tpu.util import state

        view = state.node_io_view()
    except Exception:
        return False
    for row in view["nodes"].values():
        if row["pending_pull_bytes"] > PRESSURE_FRACTION * budget:
            return True
    return False


def io_pressure_hot() -> bool:
    """Cached pressure verdict (at most one cluster sample per
    PRESSURE_TTL_S) — cheap enough to consult per admitted block."""
    if _pressure_provider is not None:
        return bool(_pressure_provider())
    now = time.monotonic()
    with _pressure_lock:
        if now - _pressure_cache[0] < PRESSURE_TTL_S:
            return _pressure_cache[1]
    hot = _sample_pressure()
    with _pressure_lock:
        _pressure_cache[0] = time.monotonic()
        _pressure_cache[1] = hot
    return hot


# -------------------------------------------------------------- op stats
@dataclass
class StreamOpStats:
    """Per-operator counters for one execution (Dataset.stats() rows).
    Superset of the legacy OpStats: byte/pull/stall accounting rides the
    new instruments."""

    name: str
    blocks_in: int = 0
    blocks_out: int = 0
    rows_out: int = 0
    bytes_in: int = 0
    bytes_out: int = 0
    plane_puts: int = 0
    backpressure_s: float = 0.0
    max_inflight_bytes: int = 0
    task_time_s: float = 0.0

    def render(self) -> str:
        return (
            f"{self.name}: blocks_in={self.blocks_in} "
            f"blocks_out={self.blocks_out} rows_out={self.rows_out} "
            f"bytes_in={self.bytes_in} bytes_out={self.bytes_out} "
            f"plane_puts={self.plane_puts} "
            f"backpressure_s={self.backpressure_s:.3f}")


class _OpInstruments:
    """Bound metric handles for one operator — created when the operator
    is installed, so the pump loop never touches the registry."""

    __slots__ = ("bytes_in", "bytes_out", "rows_out", "stall_budget",
                 "stall_pressure")

    def __init__(self, op_name: str):
        tags = {"op": op_name}
        self.bytes_in = _M_BYTES_IN.bind(tags)
        self.bytes_out = _M_BYTES_OUT.bind(tags)
        self.rows_out = _M_ROWS_OUT.bind(tags)
        self.stall_budget = _M_STALL.bind({"op": op_name, "cause": "budget"})
        self.stall_pressure = _M_STALL.bind(
            {"op": op_name, "cause": "pressure"})


# ---------------------------------------------------------- worker tasks
def _transform_to_plane(transform: Callable[[Block], list[Block]],
                        block: Block) -> list:
    """Worker side of one operator task: run the transform, seal every
    output block into THIS node's store, return tiny descriptor rows.
    The input arrived as a ShmArg (zero-copy from the local store, or a
    plane pull on miss); the outputs' primary copies stay here — the
    driver sees ``[[ref, rows, bytes], ...]`` only. Seals are BATCHED:
    the whole task's outputs register with the head in one
    ``client_put_seal_batch`` round trip (wire v9), not one blocking RPC
    per block."""
    blocks = transform(block)
    refs = ray_tpu.put_batch(blocks)
    return [[ref, b.num_rows(), b.size_bytes()]
            for ref, b in zip(refs, blocks)]


def _slice_to_plane(block: Block, n: int) -> list:
    """Worker side of an equal streaming_split: slice one block into n
    near-equal row ranges sealed into this node's store (rows differ by at
    most 1; seals batched — one registration RPC for all n slices).
    Returns one descriptor row (or None for an empty take) per slot — the
    driver rotates slots over shards."""
    rows = block.num_rows()
    per, extra = divmod(rows, n)
    slices: list = []
    start = 0
    for q in range(n):
        take = per + (1 if q < extra else 0)
        if not take:
            slices.append(None)
            continue
        sl = block.slice(start, start + take)
        start += take
        slices.append(sl)
    refs = ray_tpu.put_batch([s for s in slices if s is not None])
    out: list = []
    it = iter(refs)
    for sl in slices:
        if sl is None:
            out.append(None)
        else:
            out.append([next(it), sl.num_rows(), sl.size_bytes()])
    return out


def _holder_locality(ref) -> "frozenset | None":
    """Holder NodeIDs of a block ref — the transform-placement locality
    hint (head driver only; workers/clients have no directory and return
    None, costing nothing)."""
    from ray_tpu.core.runtime import get_runtime_or_none

    rt = get_runtime_or_none()
    holders = getattr(rt, "plane_holder_nodes", None)
    if holders is None:
        return None
    try:
        return holders(ref.object_id())
    except Exception:
        return None


class _PlaneTransformActor:
    """Actor-pool stage worker: constructed-once transform, plane-sealed
    outputs (the ActorPoolStrategy analog of _transform_to_plane)."""

    def __init__(self, factory):
        self._transform = factory()

    def run(self, block):
        return _transform_to_plane(self._transform, block)


# ------------------------------------------------------------- the pump
class _OpDriver:
    """Admission bookkeeping for one operator (exposes inflight_bytes to
    the gauge producer)."""

    __slots__ = ("stats", "inflight_bytes", "__weakref__")

    def __init__(self, stats: StreamOpStats):
        self.stats = stats
        self.inflight_bytes = 0


def execute_streaming_refs(
    source: "Iterator[Block | BlockRef]",
    ops: list,
    preserve_order: bool = True,
    stats_sink: "list | None" = None,
) -> "Iterator[BlockRef]":
    """Run blocks through ``ops`` (data/executor.py PhysicalOps) with every
    intermediate block plane-resident: tasks take a block (ShmArg/ref),
    seal outputs into their node's store, and return descriptors. The
    returned iterator yields descriptors — callers materialize at their
    edge (fetch_block) or hand them to another plane consumer."""
    stats = [StreamOpStats(op.name) for op in ops]
    if stats_sink is not None:
        stats_sink.extend(stats)
    stream: "Iterator[Block | BlockRef]" = source
    for op, st in zip(ops, stats):
        stream = _drive_op(stream, op, st, preserve_order)
    return (ensure_ref(item) for item in stream)


def _drive_op(upstream, op, stats: StreamOpStats,
              preserve_order: bool) -> "Iterator[BlockRef]":
    """One operator's pump: admit upstream items while under the byte
    budget / concurrency window and the node I/O is not hot; yield output
    descriptors as task chains complete (no stage barrier).

    Hot-loop contract: records ONLY through the handles in ``inst``
    (bound above, at install time) — AST-linted."""
    from ray_tpu.data.executor import ActorPoolStrategy

    inst = _OpInstruments(op.name)
    drv = _OpDriver(stats)
    _LIVE_OPS.add(drv)
    budget = op.memory_budget_bytes or OP_BUDGET_BYTES

    pool = None
    loads: dict = {}
    if isinstance(op.compute, ActorPoolStrategy):
        factory = op.transform_factory or (lambda t=op.transform: t)
        actor_cls = ray_tpu.remote(num_cpus=op.num_cpus)(_PlaneTransformActor)
        pool = [actor_cls.remote(factory)
                for _ in range(max(1, op.compute.size))]
        loads = {i: 0 for i in range(len(pool))}
        window = len(pool) * max(1, op.compute.max_tasks_in_flight_per_actor)
    else:
        remote_fn = ray_tpu.remote(
            num_cpus=op.num_cpus, name=f"data::{op.name}")(_transform_to_plane)
        window = op.max_in_flight

    def submit(item):
        arg = item.ref if isinstance(item, BlockRef) else item
        if pool is None:
            loc = _holder_locality(arg) if isinstance(item, BlockRef) \
                else None
            if loc:
                # score the input block's holder node up: the transform
                # runs where its block already lives (directory has
                # locations, scheduler has node_io_view pressure — joined)
                return remote_fn.options(
                    locality_nodes=loc).remote(op.transform, arg), None
            return remote_fn.remote(op.transform, arg), None
        idx = min(loads, key=loads.get)
        loads[idx] += 1
        return pool[idx].run.remote(arg), idx

    in_flight: list = []  # [(result_ref, actor_idx|None, est_bytes, input)]
    upstream_done = False
    stalled_cause: "str | None" = None   # timing: reset after each drain
    recorded_cause: "str | None" = None  # flight ring: reset on admission
    up = iter(upstream)
    try:
        while True:
            # fill the window: concurrency AND byte budget AND node-I/O
            # pressure; always admit one so a single over-budget block
            # still makes progress
            while not upstream_done and len(in_flight) < window:
                if in_flight and drv.inflight_bytes >= budget:
                    cause = "budget"
                elif in_flight and io_pressure_hot():
                    cause = "pressure"
                else:
                    try:
                        item = next(up)
                    except StopIteration:
                        upstream_done = True
                        break
                    est = item_bytes(item)
                    stats.blocks_in += 1
                    stats.bytes_in += est
                    inst.bytes_in.inc(est)
                    ref, idx = submit(item)
                    in_flight.append((ref, idx, est, item))
                    drv.inflight_bytes += est
                    if drv.inflight_bytes > stats.max_inflight_bytes:
                        stats.max_inflight_bytes = drv.inflight_bytes
                    recorded_cause = None
                    continue
                # admission blocked: drain the head of the window, timing
                # the stall (flight-record the transition, not every block)
                stalled_cause = cause
                if recorded_cause != cause:
                    recorded_cause = cause
                    flight_recorder.record(
                        "data", "backpressure_stall", op=stats.name,
                        cause=cause, inflight_bytes=drv.inflight_bytes,
                        budget=budget)
                break
            if not in_flight:
                if upstream_done:
                    return
                continue
            wait_t0 = time.perf_counter()
            if preserve_order:
                ready_ref, idx, est, _item = in_flight.pop(0)
            else:
                ready, _ = ray_tpu.wait([r for r, _, _, _ in in_flight],
                                        num_returns=1, timeout=None)
                pos = next(i for i, (r, _, _, _) in enumerate(in_flight)
                           if r == ready[0])
                ready_ref, idx, est, _item = in_flight.pop(pos)
            rows = ray_tpu.get(ready_ref)
            waited = time.perf_counter() - wait_t0
            stats.task_time_s += waited
            if stalled_cause is not None:
                stats.backpressure_s += waited
                (inst.stall_budget if stalled_cause == "budget"
                 else inst.stall_pressure).inc(waited)
                stalled_cause = None
            drv.inflight_bytes -= est
            if idx is not None:
                loads[idx] -= 1
            for ref, nrows, nbytes in rows:
                stats.blocks_out += 1
                stats.rows_out += nrows
                stats.bytes_out += nbytes
                stats.plane_puts += 1
                inst.rows_out.inc(nrows)
                inst.bytes_out.inc(nbytes)
                yield BlockRef(ref, nrows, nbytes)
    finally:
        _LIVE_OPS.discard(drv)
        for a in pool or ():
            try:
                ray_tpu.kill(a)
            except Exception:
                pass


def materialize(descs: "Iterator[Block | BlockRef]") -> Iterator[Block]:
    """Consumer edge: land each descriptor's payload in this process once."""
    for d in descs:
        yield fetch_block(d)


# --------------------------------------------------------------- splitter
@dataclass
class _StreamError:
    """Error envelope a pump thread enqueues so every consumer re-raises
    the producing exception (shared with executor.py's legacy splitter)."""

    exc: BaseException


class RefOutputSplitter:
    """Fan a descriptor stream out to n consumers over bounded per-shard
    queues (reference: execution/operators/output_splitter.py) — the
    plane-native streaming_split: queues carry DESCRIPTORS, so each
    consumer (a train rank, possibly in another process) pulls block bytes
    holder→itself; the pump thread never touches payloads.

    ``equal=True`` slices every block into n near-equal parts VIA A TASK
    (the slices seal into the executing node's store) so per-rank row
    counts differ by at most 1 per block — the SPMD gang contract."""

    def __init__(self, stream: "Iterator[Block | BlockRef]", n: int,
                 equal: bool = False, queue_depth: int = 4):
        self.equal = equal
        self.queues: "list[_queue.Queue]" = [
            _queue.Queue(maxsize=max(1, queue_depth)) for _ in range(n)]
        self._thread = threading.Thread(
            target=self._pump, args=(stream,), daemon=True,
            name="data-split-pump")
        self._thread.start()

    # slice tasks kept in flight by the equal-split pump: the next blocks'
    # slicing overlaps the current get, so gang ingest isn't capped at one
    # task round-trip per block (each in-flight task holds ~1 block of
    # slices in its node's store — small, bounded)
    SLICE_PIPELINE = 3

    def _pump(self, stream) -> None:
        n = len(self.queues)
        i = 0
        err: "BaseException | None" = None
        slice_task = ray_tpu.remote(name="data::split_slice")(_slice_to_plane)
        window: deque = deque()  # (result_ref, item) in submission order

        def drain_one():
            # harvest in SUBMISSION order: the remainder-row rotation (i)
            # must advance deterministically per input block
            nonlocal i
            r, item = window.popleft()
            slots = ray_tpu.get(r)
            extra = item_rows(item) % n
            for q, row in enumerate(slots):
                if row is not None:
                    ref, rows, nbytes = row
                    self.queues[(i + q) % n].put(BlockRef(ref, rows, nbytes))
            i += extra  # rotate who gets the remainder rows

        try:
            for item in stream:
                if self.equal:
                    arg = item.ref if isinstance(item, BlockRef) else item
                    window.append((slice_task.remote(arg, n), item))
                    if len(window) >= self.SLICE_PIPELINE:
                        drain_one()
                else:
                    self.queues[i % n].put(ensure_ref(item))
                    i += 1
            while window:
                drain_one()
        except BaseException as e:  # noqa: BLE001 - propagate to consumers
            err = e
        finally:
            tail = _StreamError(err) if err is not None else None
            for q in self.queues:
                q.put(tail)

    def iterator(self, idx: int) -> "Iterator[BlockRef]":
        q = self.queues[idx]
        while True:
            item = q.get()
            if item is None:
                return
            if isinstance(item, _StreamError):
                raise item.exc
            yield item


# -------------------------------------------------------------- prefetch
@dataclass
class IngestStats:
    """Consumer-side starvation accounting for one shard iterator — the
    signal the gang-training never-starve assertion reads."""

    blocks: int = 0
    bytes: int = 0
    wait_s: float = 0.0
    # fetch waits where NO prefetched block was ready (the pipeline
    # genuinely starved the step); the first `depth` blocks are pipeline
    # fill (warmup), not counted
    starved_steps: int = 0


class PrefetchingBlockIterator:
    """Pull descriptors from an upstream iterator and keep up to ``depth``
    block fetches in flight (async gets through the local runtime — in a
    worker these land zero-copy in the worker node's store), so a training
    step finds its next block already local.

    Hot-loop contract: ``_prefetch_pump``/``__next__`` record only into
    plain IngestStats fields — no metric construction, no raw RPC
    (AST-linted with the op pump)."""

    def __init__(self, descs: "Iterator[Block | BlockRef]", depth: int = 4):
        self._descs = iter(descs)
        self._depth = max(1, depth)
        self._window: "list[tuple[Any, Any]]" = []  # [(desc, future|None)]
        self._upstream_done = False
        self.stats = IngestStats()

    def _get_async(self, ref):
        from ray_tpu.core.runtime import get_runtime

        return get_runtime().get_async(ref)

    def _prefetch_pump(self) -> None:
        while not self._upstream_done and len(self._window) < self._depth:
            try:
                d = next(self._descs)
            except StopIteration:
                self._upstream_done = True
                return
            if isinstance(d, BlockRef):
                self._window.append((d, self._get_async(d.ref)))
            else:
                self._window.append((d, None))  # already a local Block

    def __iter__(self):
        return self

    def __next__(self) -> Block:
        self._prefetch_pump()
        if not self._window:
            raise StopIteration
        d, fut = self._window.pop(0)
        t0 = time.perf_counter()
        if fut is None:
            blk = d
        else:
            if not fut.done() and self.stats.blocks >= self._depth:
                self.stats.starved_steps += 1
            blk = fut.result()
            _M_FETCHES.inc()
            _M_DRIVER_BYTES.inc(item_bytes(d))
        waited = time.perf_counter() - t0
        self.stats.wait_s += waited
        self.stats.blocks += 1
        self.stats.bytes += item_bytes(d)
        self._prefetch_pump()  # refill before the caller computes
        return blk
