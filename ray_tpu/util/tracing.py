"""Tracing: span recording around task/actor submit + execute.

Parity: python/ray/util/tracing/tracing_helper.py (opt-in OpenTelemetry spans
around remote calls) + the task timeline pipeline (SURVEY §5.1). Spans are
recorded into an in-process buffer; `spans()` returns OTel-shaped dicts and
`to_chrome_trace()` renders the same Chrome-trace format as `ray timeline`.
OpenTelemetry SDK export can be layered on by registering a processor.
"""

from __future__ import annotations

import contextlib
import contextvars
import threading
import time
import uuid
from dataclasses import dataclass, field
from typing import Any, Callable, Optional

_current_span: "contextvars.ContextVar[Optional[Span]]" = contextvars.ContextVar(
    "ray_tpu_current_span", default=None
)
MAX_BUFFERED_SPANS = 100_000


@dataclass
class Span:
    name: str
    span_id: str
    trace_id: str
    parent_id: str | None
    start_ns: int
    end_ns: int = 0
    attributes: dict = field(default_factory=dict)
    status: str = "OK"


class _Tracer:
    def __init__(self):
        self._spans: list[Span] = []
        self._lock = threading.Lock()
        self._processors: list[Callable[[Span], None]] = []
        self.enabled = False

    def add_span_processor(self, fn: Callable[[Span], None]) -> None:
        self._processors.append(fn)

    def record(self, span: Span) -> None:
        with self._lock:
            self._spans.append(span)
            if len(self._spans) > MAX_BUFFERED_SPANS:
                # ring-buffer semantics: drop the oldest half (bounded memory
                # for long-running traced jobs)
                self.dropped = getattr(self, "dropped", 0) + len(self._spans) // 2
                self._spans = self._spans[len(self._spans) // 2 :]
        for p in self._processors:
            try:
                p(span)
            except Exception:
                pass

    def spans(self) -> list[Span]:
        with self._lock:
            return list(self._spans)

    def clear(self) -> None:
        with self._lock:
            self._spans.clear()


_tracer = _Tracer()


def _otel_processor(s: "Span") -> None:
    from ray_tpu._private import otel

    if otel.configured():
        otel.emit_span(s.name, s.start_ns / 1e9, s.end_ns / 1e9,
                       attributes=s.attributes, trace_id=s.trace_id,
                       span_id=s.span_id, parent_span_id=s.parent_id)


def enable_tracing() -> None:
    """Reference: `ray start --tracing-startup-hook` opt-in. OTLP export
    rides the processor hook when a sink is configured
    (RAY_TPU_OTLP_FILE / RAY_TPU_OTLP_ENDPOINT)."""
    if _otel_processor not in _tracer._processors:
        _tracer.add_span_processor(_otel_processor)
    _tracer.enabled = True


def disable_tracing() -> None:
    _tracer.enabled = False


def is_enabled() -> bool:
    return _tracer.enabled


def add_span_processor(fn: Callable[[Span], None]) -> None:
    _tracer.add_span_processor(fn)


@contextlib.contextmanager
def span(name: str, attributes: dict | None = None):
    """Record a span (no-op unless tracing is enabled). Nested spans link via
    thread-local parent context (tracing_helper's context propagation)."""
    if not _tracer.enabled:
        yield None
        return
    parent: Optional[Span] = _current_span.get()
    s = Span(
        name=name,
        span_id=uuid.uuid4().hex[:16],
        trace_id=parent.trace_id if parent else uuid.uuid4().hex[:32],
        parent_id=parent.span_id if parent else None,
        start_ns=time.time_ns(),
        attributes=dict(attributes or {}),
    )
    token = _current_span.set(s)
    try:
        yield s
    except BaseException:
        s.status = "ERROR"
        raise
    finally:
        s.end_ns = time.time_ns()
        _current_span.reset(token)
        _tracer.record(s)


def spans() -> list[Span]:
    return _tracer.spans()


def clear() -> None:
    _tracer.clear()


def to_chrome_trace() -> list[dict]:
    return [
        {
            "name": s.name,
            "cat": "span",
            "ph": "X",
            "ts": s.start_ns // 1000,
            "dur": max(0, (s.end_ns - s.start_ns) // 1000),
            "pid": 1,
            "tid": abs(hash(s.trace_id)) % 1000,
            "args": {**s.attributes, "status": s.status},
        }
        for s in _tracer.spans()
    ]
