"""Tracing: span recording around task/actor submit + execute.

Parity: python/ray/util/tracing/tracing_helper.py (opt-in OpenTelemetry spans
around remote calls) + the task timeline pipeline (SURVEY §5.1). Spans are
recorded into an in-process buffer; `spans()` returns OTel-shaped dicts and
`to_chrome_trace()` renders the same Chrome-trace format as `ray timeline`.
OpenTelemetry SDK export can be layered on by registering a processor.
"""

from __future__ import annotations

import contextlib
import contextvars
import threading
import time
import uuid
from dataclasses import dataclass, field
from typing import Any, Callable, Optional

_current_span: "contextvars.ContextVar[Optional[Span]]" = contextvars.ContextVar(
    "ray_tpu_current_span", default=None
)
MAX_BUFFERED_SPANS = 100_000


@dataclass
class Span:
    name: str
    span_id: str
    trace_id: str
    parent_id: str | None
    start_ns: int
    end_ns: int = 0
    attributes: dict = field(default_factory=dict)
    status: str = "OK"


class _Tracer:
    def __init__(self):
        self._spans: list[Span] = []
        self._lock = threading.Lock()
        self._processors: list[Callable[[Span], None]] = []
        self.enabled = False

    def add_span_processor(self, fn: Callable[[Span], None]) -> None:
        self._processors.append(fn)

    def record(self, span: Span) -> None:
        with self._lock:
            self._spans.append(span)
            if len(self._spans) > MAX_BUFFERED_SPANS:
                # ring-buffer semantics: drop the oldest half (bounded memory
                # for long-running traced jobs)
                self.dropped = getattr(self, "dropped", 0) + len(self._spans) // 2
                self._spans = self._spans[len(self._spans) // 2 :]
        for p in self._processors:
            try:
                p(span)
            except Exception:
                pass

    def spans(self) -> list[Span]:
        with self._lock:
            return list(self._spans)

    def clear(self) -> None:
        with self._lock:
            self._spans.clear()


_tracer = _Tracer()


def _otel_processor(s: "Span") -> None:
    from ray_tpu._private import otel

    if otel.configured():
        otel.emit_span(s.name, s.start_ns / 1e9, s.end_ns / 1e9,
                       attributes=s.attributes, trace_id=s.trace_id,
                       span_id=s.span_id, parent_span_id=s.parent_id)


TRACING_ENV = "RAY_TPU_TRACING"


def enable_tracing() -> None:
    """Reference: `ray start --tracing-startup-hook` opt-in. OTLP export
    rides the processor hook when a sink is configured
    (RAY_TPU_OTLP_FILE / RAY_TPU_OTLP_ENDPOINT). Sets RAY_TPU_TRACING so
    worker processes spawned from here come up tracing too (worker_env()
    copies os.environ) — their execute spans join the driver's trace via
    the propagated context."""
    import os

    if _otel_processor not in _tracer._processors:
        _tracer.add_span_processor(_otel_processor)
    _tracer.enabled = True
    os.environ[TRACING_ENV] = "1"


def disable_tracing() -> None:
    import os

    _tracer.enabled = False
    os.environ.pop(TRACING_ENV, None)


def enable_from_env() -> None:
    """Worker-boot hook: adopt the driver's tracing opt-in."""
    import os

    if os.environ.get(TRACING_ENV) == "1" and not _tracer.enabled:
        enable_tracing()


def current_context() -> "tuple[str, str] | None":
    """(trace_id, span_id) of the live span, for cross-process propagation
    (the W3C traceparent analog): ship it in task-submit opts and pass it
    to span(parent_ctx=...) on the executing side so the worker's execute
    span joins the submitter's trace instead of rooting a new one."""
    s = _current_span.get()
    if s is None:
        return None
    return (s.trace_id, s.span_id)


def is_enabled() -> bool:
    return _tracer.enabled


def add_span_processor(fn: Callable[[Span], None]) -> None:
    _tracer.add_span_processor(fn)


@contextlib.contextmanager
def span(name: str, attributes: dict | None = None,
         parent_ctx: "tuple[str, str] | None" = None):
    """Record a span (no-op unless tracing is enabled — except that an
    explicit ``parent_ctx`` ALSO records: a propagated context means the
    submitting process opted in, and the execute span must join its trace
    even where local enablement lagged). Nested spans link via thread-local
    parent context (tracing_helper's context propagation); ``parent_ctx``
    — a (trace_id, span_id) pair from ``current_context()``, possibly from
    another process — takes precedence, linking this span under a remote
    parent."""
    if not _tracer.enabled and parent_ctx is None:
        yield None
        return
    parent: Optional[Span] = _current_span.get()
    if parent_ctx is not None:
        trace_id, parent_id = parent_ctx
    elif parent is not None:
        trace_id, parent_id = parent.trace_id, parent.span_id
    else:
        trace_id, parent_id = uuid.uuid4().hex[:32], None
    s = Span(
        name=name,
        span_id=uuid.uuid4().hex[:16],
        trace_id=trace_id,
        parent_id=parent_id,
        start_ns=time.time_ns(),
        attributes=dict(attributes or {}),
    )
    token = _current_span.set(s)
    try:
        yield s
    except BaseException:
        s.status = "ERROR"
        raise
    finally:
        s.end_ns = time.time_ns()
        _current_span.reset(token)
        _tracer.record(s)


def spans() -> list[Span]:
    return _tracer.spans()


def clear() -> None:
    _tracer.clear()


def to_chrome_trace() -> list[dict]:
    # stable lane per trace: sorted trace ids -> 1..N (a per-process
    # hash-salted lane collides and reshuffles between exports — the same
    # fix ISSUE-13 applied to state.timeline's actor lanes)
    spans_ = _tracer.spans()
    lane_of = {t: i + 1
               for i, t in enumerate(sorted({s.trace_id for s in spans_}))}
    return [
        {
            "name": s.name,
            "cat": "span",
            "ph": "X",
            "ts": s.start_ns // 1000,
            "dur": max(0, (s.end_ns - s.start_ns) // 1000),
            "pid": 1,
            "tid": lane_of[s.trace_id],
            "args": {**s.attributes, "status": s.status},
        }
        for s in spans_
    ]
