"""Out-of-band worker stack sampler: SIGUSR-triggered, in-process.

The profiler half of ISSUE 13. The dashboard's jax-profiler capture runs as
a REMOTE TASK — which by construction cannot profile a worker that is
wedged (its executor never picks the capture up). This module closes that
hole the way py-spy/the reference's ``profile_manager.py`` does, but
without an external dependency: every worker installs a signal handler at
boot (``install()``), and the NODE AGENT — a separate process that is
alive exactly when the worker is stuck — triggers a capture by writing a
request file and sending the signal (``capture_out_of_band()``).

Why a signal reaches a stuck worker: CPython delivers signal handlers on
the main thread, and the main-thread blocking primitives that wedge
workers in practice (``lock.acquire``, ``Event.wait``, ``Condition.wait``,
nested ``get``) are signal-interruptible — the handler runs, spawns a
DAEMON sampler thread, and returns so the interrupted wait resumes
untouched. The sampler thread then walks ``sys._current_frames()`` N times
over the window — every thread's live stack, very much including the
blocked main thread — and writes a collapsed-stack (flamegraph-ready)
artifact to a rendezvous file the agent seals into the object plane.

A capture never mutates the target's state beyond one short-lived thread:
no tracing hooks, no settrace, no stopping the world.
"""

from __future__ import annotations

import json
import os
import signal
import sys
import tempfile
import threading
import time
import traceback

# SIGUSR2: SIGUSR1 is popular with app code (and jax debug dumps); both
# overridable for embedders whose runtime claims USR2.
CAPTURE_SIGNAL = getattr(signal,
                         os.environ.get("RAY_TPU_STACK_SIGNAL", "SIGUSR2"))
DEFAULT_SAMPLES = 20
DEFAULT_DURATION_S = 1.0


def stack_dir() -> str:
    """Per-machine rendezvous dir shared by agent and workers (tempdir is
    host-stable; pids key the files — no session plumbing needed)."""
    d = os.path.join(tempfile.gettempdir(), "ray_tpu_stacks")
    os.makedirs(d, exist_ok=True)
    return d


def _req_path(pid: int) -> str:
    return os.path.join(stack_dir(), f"{pid}.req.json")


def _out_path(pid: int) -> str:
    return os.path.join(stack_dir(), f"{pid}.stacks.json")


# --------------------------------------------------------------- target side
_installed = False


def install() -> bool:
    """Register the capture signal handler (worker boot hook; main thread
    only — returns False where that isn't possible, e.g. embedded
    non-main-thread runtimes)."""
    global _installed
    if _installed:
        return True
    try:
        signal.signal(CAPTURE_SIGNAL, _on_capture_signal)
    except ValueError:  # not the main thread
        return False
    _installed = True
    return True


def _on_capture_signal(signum, frame) -> None:
    # Return immediately: the handler interrupted SOMETHING (possibly the
    # blocked wait we were asked to diagnose) — all work happens on a
    # daemon thread so the interrupted call resumes at once.
    threading.Thread(target=_sample_to_file, daemon=True,
                     name="stack-sampler").start()


def sample_stacks(samples: int, period_s: float,
                  skip_idents: "set | None" = None) -> "tuple[dict, int]":
    """N passes over ``sys._current_frames()``: per-thread collapsed stacks
    ``{thread_name: {"frame;frame;...": count}}`` (outermost first, each
    frame ``file:function:line`` — flamegraph-ready) + the pass count."""
    skip = set(skip_idents or ())
    skip.add(threading.get_ident())  # never sample the sampler
    collapsed: dict[str, dict[str, int]] = {}
    taken = 0
    for i in range(max(1, samples)):
        names = {t.ident: t.name for t in threading.enumerate()}
        for ident, frm in sys._current_frames().items():
            if ident in skip:
                continue
            name = names.get(ident, f"thread-{ident}")
            stack = ";".join(
                f"{os.path.basename(fr.filename)}:{fr.name}:{fr.lineno}"
                for fr in traceback.extract_stack(frm))
            per = collapsed.setdefault(name, {})
            per[stack] = per.get(stack, 0) + 1
        taken += 1
        if i + 1 < samples:
            time.sleep(period_s)
    return collapsed, taken


def _sample_to_file() -> None:
    pid = os.getpid()
    try:
        try:
            with open(_req_path(pid)) as f:
                req = json.load(f)
        except (OSError, ValueError):
            req = {}
        samples = int(req.get("samples") or DEFAULT_SAMPLES)
        duration = float(req.get("duration_s") or DEFAULT_DURATION_S)
        t0 = time.time()
        collapsed, taken = sample_stacks(samples,
                                         duration / max(1, samples))
        artifact = {
            "pid": pid, "argv": sys.argv[:3], "ts": t0,
            "duration_s": time.time() - t0, "samples": taken,
            "collapsed": collapsed,
        }
        tmp = _out_path(pid) + ".tmp"
        with open(tmp, "w") as f:
            json.dump(artifact, f)
        os.replace(tmp, _out_path(pid))  # atomic: existence == complete
        try:
            os.unlink(_req_path(pid))
        except OSError:
            pass
    except Exception:
        # a failed capture must never take the worker down with it
        pass


# ---------------------------------------------------------------- agent side
def capture_out_of_band(pid: int, duration_s: float = DEFAULT_DURATION_S,
                        samples: int = DEFAULT_SAMPLES,
                        timeout: "float | None" = None) -> bytes:
    """Drive a capture of ANOTHER process on this machine (the node-agent
    half of the v8 ``profile_capture`` op): write the request file, signal
    the target, wait for the atomically-renamed artifact. Returns the raw
    JSON artifact bytes; raises ProcessLookupError (target gone) or
    TimeoutError (no handler installed / handler starved)."""
    out = _out_path(pid)
    try:
        os.unlink(out)  # stale artifact from an earlier capture
    except OSError:
        pass
    tmp = _req_path(pid) + ".tmp"
    with open(tmp, "w") as f:
        json.dump({"samples": int(samples), "duration_s": float(duration_s)},
                  f)
    os.replace(tmp, _req_path(pid))
    os.kill(pid, CAPTURE_SIGNAL)
    deadline = time.monotonic() + (timeout if timeout is not None
                                   else duration_s + 10.0)
    while time.monotonic() < deadline:
        if os.path.exists(out):
            with open(out, "rb") as f:
                blob = f.read()
            try:
                os.unlink(out)
            except OSError:
                pass
            return blob
        time.sleep(0.05)
    raise TimeoutError(
        f"pid {pid} produced no stack artifact within the window — no "
        f"handler installed (worker predates v8?) or the process is wedged "
        f"in non-interruptible native code")
