"""ray_tpu.util: ecosystem utilities (reference: python/ray/util/)."""

from ray_tpu.util.actor_pool import ActorPool
from ray_tpu.util.queue import Empty, Full, Queue

__all__ = ["ActorPool", "Queue", "Empty", "Full"]

from ray_tpu.util import tpu  # noqa: E402,F401  (slice reservation API)
