"""multiprocessing.Pool API over the task runtime.

Parity: python/ray/util/multiprocessing/ — drop-in Pool whose workers are
runtime tasks (map/starmap/imap/apply_async), letting stdlib-Pool code scale
onto the cluster unchanged.
"""

from ray_tpu.util.multiprocessing.pool import Pool

__all__ = ["Pool"]
