"""Pool implementation (reference: ray.util.multiprocessing.Pool)."""

from __future__ import annotations

import itertools
from typing import Any, Callable, Iterable, Optional

import ray_tpu


class AsyncResult:
    def __init__(self, refs: list, single: bool):
        self._refs = refs
        self._single = single

    def get(self, timeout: float | None = None):
        out = ray_tpu.get(self._refs, timeout=timeout)
        return out[0] if self._single else out

    def wait(self, timeout: float | None = None) -> None:
        ray_tpu.wait(self._refs, num_returns=len(self._refs), timeout=timeout)

    def ready(self) -> bool:
        ready, _ = ray_tpu.wait(self._refs, num_returns=len(self._refs), timeout=0)
        return len(ready) == len(self._refs)

    def successful(self) -> bool:
        try:
            self.get(timeout=0.001)
            return True
        except Exception:
            return False


class Pool:
    def __init__(self, processes: int | None = None, initializer: Callable | None = None,
                 initargs: tuple = (), **_):
        if not ray_tpu.is_initialized():
            ray_tpu.init(ignore_reinit_error=True)
        self._processes = processes or 8
        self._initializer = initializer
        self._initargs = initargs
        self._closed = False

    def _task(self, fn: Callable):
        init, initargs = self._initializer, self._initargs

        def run(*args, **kwargs):
            if init is not None:
                init(*initargs)
            return fn(*args, **kwargs)

        return ray_tpu.remote(num_cpus=1, name=getattr(fn, "__name__", "pool_task"))(run)

    def _check_open(self):
        if self._closed:
            raise ValueError("Pool not running")

    def apply(self, fn: Callable, args: tuple = (), kwds: dict | None = None):
        return self.apply_async(fn, args, kwds).get()

    def apply_async(self, fn: Callable, args: tuple = (), kwds: dict | None = None) -> AsyncResult:
        self._check_open()
        return AsyncResult([self._task(fn).remote(*args, **(kwds or {}))], single=True)

    def map(self, fn: Callable, iterable: Iterable, chunksize: int | None = None) -> list:
        return self.map_async(fn, iterable, chunksize).get()

    def map_async(self, fn: Callable, iterable: Iterable, chunksize=None) -> AsyncResult:
        self._check_open()
        task = self._task(fn)
        return AsyncResult([task.remote(x) for x in iterable], single=False)

    def starmap(self, fn: Callable, iterable: Iterable[tuple]) -> list:
        self._check_open()
        task = self._task(fn)
        return ray_tpu.get([task.remote(*args) for args in iterable])

    def imap(self, fn: Callable, iterable: Iterable, chunksize: int | None = None):
        self._check_open()
        task = self._task(fn)
        refs = [task.remote(x) for x in iterable]
        for r in refs:
            yield ray_tpu.get(r)

    def imap_unordered(self, fn: Callable, iterable: Iterable, chunksize=None):
        self._check_open()
        task = self._task(fn)
        refs = [task.remote(x) for x in iterable]
        while refs:
            ready, refs = ray_tpu.wait(refs, num_returns=1, timeout=None)
            yield ray_tpu.get(ready[0])

    def close(self) -> None:
        self._closed = True

    def terminate(self) -> None:
        self._closed = True

    def join(self) -> None:
        pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.terminate()
        return False
