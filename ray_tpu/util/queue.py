"""Distributed Queue backed by an actor.

Parity: python/ray/util/queue.py (Queue with put/get/qsize/empty/full,
blocking + timeout semantics via the hosting actor).
"""

from __future__ import annotations

import queue as _stdlib_queue
from typing import Any, Optional

import ray_tpu


class _QueueActor:
    def __init__(self, maxsize: int):
        self._q: _stdlib_queue.Queue = _stdlib_queue.Queue(maxsize=maxsize)

    def put(self, item: Any, timeout: Optional[float] = None) -> bool:
        try:
            self._q.put(item, timeout=timeout)
            return True
        except _stdlib_queue.Full:
            return False

    def get(self, timeout: Optional[float] = None):
        try:
            return (True, self._q.get(timeout=timeout))
        except _stdlib_queue.Empty:
            return (False, None)

    def qsize(self) -> int:
        return self._q.qsize()

    def empty(self) -> bool:
        return self._q.empty()

    def full(self) -> bool:
        return self._q.full()


class Empty(Exception):
    pass


class Full(Exception):
    pass


class Queue:
    def __init__(self, maxsize: int = 0, actor_options: dict | None = None):
        opts = {"num_cpus": 0, "max_concurrency": 8, **(actor_options or {})}
        self._actor = ray_tpu.remote(**opts)(_QueueActor).remote(maxsize)

    def put(self, item: Any, block: bool = True, timeout: float | None = None) -> None:
        ok = ray_tpu.get(self._actor.put.remote(item, timeout if block else 0.0))
        if not ok:
            raise Full("Queue is full")

    def get(self, block: bool = True, timeout: float | None = None) -> Any:
        ok, item = ray_tpu.get(self._actor.get.remote(timeout if block else 0.0))
        if not ok:
            raise Empty("Queue is empty")
        return item

    def put_nowait(self, item: Any) -> None:
        self.put(item, block=False)

    def get_nowait(self) -> Any:
        return self.get(block=False)

    def qsize(self) -> int:
        return ray_tpu.get(self._actor.qsize.remote())

    def empty(self) -> bool:
        return ray_tpu.get(self._actor.empty.remote())

    def full(self) -> bool:
        return ray_tpu.get(self._actor.full.remote())
