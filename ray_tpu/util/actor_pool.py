"""ActorPool: load-balance tasks over a fixed set of actors.

Parity: python/ray/util/actor_pool.py (map/map_unordered/submit/get_next).
"""

from __future__ import annotations

from typing import Any, Callable, Iterable

import ray_tpu


class ActorPool:
    def __init__(self, actors: list):
        self._idle = list(actors)
        self._future_to_actor: dict = {}
        self._pending: list = []  # ordered refs
        self._index = 0

    def submit(self, fn: Callable, value: Any) -> None:
        """fn(actor, value) -> ObjectRef."""
        if not self._idle:
            # wait for any in-flight call to finish
            ready, _ = ray_tpu.wait(list(self._future_to_actor), num_returns=1, timeout=None)
            for r in ready:
                self._idle.append(self._future_to_actor.pop(r))
        actor = self._idle.pop(0)
        ref = fn(actor, value)
        self._future_to_actor[ref] = actor
        self._pending.append(ref)

    def has_next(self) -> bool:
        return bool(self._pending)

    def get_next(self, timeout: float | None = None) -> Any:
        ref = self._pending.pop(0)
        out = ray_tpu.get(ref, timeout=timeout)
        actor = self._future_to_actor.pop(ref, None)
        if actor is not None:
            self._idle.append(actor)
        return out

    def get_next_unordered(self, timeout: float | None = None) -> Any:
        ready, _ = ray_tpu.wait(self._pending, num_returns=1, timeout=timeout)
        if not ready:
            raise TimeoutError("No result ready")
        ref = ready[0]
        self._pending.remove(ref)
        out = ray_tpu.get(ref)
        actor = self._future_to_actor.pop(ref, None)
        if actor is not None:
            self._idle.append(actor)
        return out

    def map(self, fn: Callable, values: Iterable) -> Iterable:
        for v in values:
            self.submit(fn, v)
        while self.has_next():
            yield self.get_next()

    def map_unordered(self, fn: Callable, values: Iterable) -> Iterable:
        for v in values:
            self.submit(fn, v)
        while self.has_next():
            yield self.get_next_unordered()
