"""Remote pdb for tasks/actors: `ray_tpu.util.rpdb.set_trace()` inside any
task opens a debugger on a local socket and registers it with the head so
`ray_tpu debug` (scripts/cli.py) can list and attach.

Parity: python/ray/util/rpdb.py (RemotePdb + _driver_set_trace) and the
`ray debug` CLI (scripts/scripts.py debug) — re-scoped to the
single-controller runtime: sessions register over the existing control
plane (worker client RPC) or directly on the head runtime, and attach is a
plain TCP text protocol (telnet-compatible, like the reference's).

Post-mortem: set RAY_TPU_POST_MORTEM=1 and task exceptions drop into the
debugger at the raise point before propagating (reference: RAY_DEBUG
post-mortem mode).
"""

from __future__ import annotations

import os
import pdb
import socket
import sys
import threading
import uuid


class _SocketIO:
    """File-like adapter pdb can use for stdin/stdout over one connection."""

    def __init__(self, conn: socket.socket):
        self._file = conn.makefile("rw", buffering=1, errors="replace")

    def readline(self, *a):
        return self._file.readline(*a)

    def read(self, *a):
        return self._file.read(*a)

    def write(self, data):
        try:
            self._file.write(data)
        except (BrokenPipeError, OSError):
            raise
        return len(data)

    def flush(self):
        try:
            self._file.flush()
        except (BrokenPipeError, OSError):
            pass

    def close(self):
        try:
            self._file.close()
        except OSError:
            pass


class RemotePdb(pdb.Pdb):
    """Pdb bound to an accepted TCP connection (reference: rpdb.py:88)."""

    def __init__(self, conn: socket.socket):
        self._io = _SocketIO(conn)
        super().__init__(stdin=self._io, stdout=self._io)
        self.prompt = "(ray_tpu-pdb) "

    def do_continue(self, arg):
        try:
            return super().do_continue(arg)
        finally:
            self._io.close()

    do_c = do_cont = do_continue


def _register(session: dict) -> None:
    from ray_tpu.core import runtime as rt_mod

    rt = rt_mod.get_runtime_or_none()
    if rt is None:
        return
    try:
        if hasattr(rt, "debug_register"):  # client runtime in a worker
            rt.debug_register(session)
        else:  # in-head task
            rt.debug_sessions[session["id"]] = session
    except Exception:
        pass


def _unregister(session_id: str) -> None:
    from ray_tpu.core import runtime as rt_mod

    rt = rt_mod.get_runtime_or_none()
    if rt is None:
        return
    try:
        if hasattr(rt, "debug_unregister"):
            rt.debug_unregister(session_id)
        else:
            rt.debug_sessions.pop(session_id, None)
    except Exception:
        pass


def _advertise_host() -> str:
    """The address other NODES can reach this process at: the local address
    of a route toward the head (no traffic sent), falling back to loopback
    for headless/single-host runs."""
    from ray_tpu.core import runtime as rt_mod

    rt = rt_mod.get_runtime_or_none()
    h = getattr(rt, "_host", None)  # client runtime: the head's host
    p = getattr(rt, "_port", 80)
    try:
        if h and h not in ("127.0.0.1", "localhost"):
            s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
            try:
                s.connect((h, int(p)))
                return s.getsockname()[0]
            finally:
                s.close()
    except (OSError, ValueError):
        pass
    return "127.0.0.1"


def external_attach_enabled() -> bool:
    """Cross-node attach is an explicit opt-in (RAY_TPU_DEBUGGER_EXTERNAL=1).
    Default is loopback-only, matching the reference's localhost default."""
    return os.environ.get("RAY_TPU_DEBUGGER_EXTERNAL") == "1"


def set_trace(frame=None, *, reason: str = "breakpoint", exc_info=None) -> None:
    """Open a listener, announce the session, BLOCK until a client attaches,
    then hand this thread to pdb. The task resumes on `continue`.

    The listener binds 127.0.0.1 unless RAY_TPU_DEBUGGER_EXTERNAL=1; either
    way the first line an attacher sends must be the per-session token (the
    token travels to attachers over the authenticated control plane, so a
    network peer who can merely reach the port cannot drive pdb)."""
    external = external_attach_enabled()
    listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    listener.bind(("0.0.0.0" if external else "127.0.0.1", 0))
    listener.listen(1)
    port = listener.getsockname()[1]
    host = _advertise_host() if external else "127.0.0.1"
    token = uuid.uuid4().hex
    session = {
        "id": uuid.uuid4().hex[:12],
        "pid": os.getpid(),
        "host": host,
        "port": port,
        "reason": reason,
        "token": token,
        "thread": threading.current_thread().name,
    }
    _register(session)
    sys.stderr.write(
        f"ray_tpu rpdb: waiting for attach at {host}:{port} "
        f"(`ray_tpu debug`)\n")
    sys.stderr.flush()
    conn = None
    try:
        while conn is None:
            cand, _ = listener.accept()
            if _check_token(cand, token):
                conn = cand
            else:
                try:
                    cand.close()
                except OSError:
                    pass
    finally:
        listener.close()
        _unregister(session["id"])
    dbg = RemotePdb(conn)
    if exc_info is not None:
        dbg.reset()
        dbg.interaction(None, exc_info[2])
    else:
        dbg.set_trace(frame or sys._getframe().f_back)


def _check_token(conn: socket.socket, token: str) -> bool:
    """Read exactly up to the first newline (the attach token) with a short
    deadline; reject mismatches so unauthenticated peers never reach the
    debugger. Byte-at-a-time so pipelined pdb input behind the token
    (`printf 'TOKEN\\nc\\n' | nc ...`) stays in the socket for pdb."""
    conn.settimeout(10.0)
    try:
        buf = b""
        while len(buf) < 256:
            ch = conn.recv(1)
            if not ch:
                return False
            if ch == b"\n":
                break
            buf += ch
        ok = buf.decode(errors="replace").strip() == token
        if ok:
            conn.settimeout(None)
        return ok
    except (OSError, UnicodeDecodeError):
        return False


def post_mortem_enabled() -> bool:
    return os.environ.get("RAY_TPU_POST_MORTEM") == "1"


def maybe_post_mortem(exc: BaseException) -> None:
    """Called by executors on task failure when post-mortem mode is on."""
    if not post_mortem_enabled():
        return
    tb = exc.__traceback__
    if tb is None:
        return
    set_trace(reason=f"post-mortem: {type(exc).__name__}: {exc}",
              exc_info=(type(exc), exc, tb))


def list_sessions() -> list[dict]:
    """Active debugger sessions cluster-wide — straight from the head when
    in-process, via RPC from attached clients (`ray_tpu debug --address`)."""
    from ray_tpu.core.runtime import get_runtime

    rt = get_runtime()
    if hasattr(rt, "debug_list"):  # client runtime: ask the head
        return rt.debug_list()
    return list(getattr(rt, "debug_sessions", {}).values())


def attach(session: dict, stdin=None, stdout=None) -> None:
    """Interactive attach: bridge local stdin/stdout to the session socket
    until the debugger disconnects (the CLI's `ray_tpu debug` body)."""
    stdin = stdin or sys.stdin
    stdout = stdout or sys.stdout
    try:
        conn = socket.create_connection((session["host"], session["port"]),
                                        timeout=10)
    except OSError as e:
        if session["host"] in ("127.0.0.1", "localhost"):
            raise ConnectionError(
                f"debug session {session['id']} advertises a loopback address "
                f"({session['host']}:{session['port']}); if the breakpoint is "
                "on another node, restart the worker with "
                "RAY_TPU_DEBUGGER_EXTERNAL=1 to allow cross-node attach"
            ) from e
        raise
    tok = session.get("token")
    if tok:
        conn.sendall(tok.encode() + b"\n")
    conn.settimeout(0.2)
    stop = threading.Event()

    def pump_in():
        while not stop.is_set():
            line = stdin.readline()
            if not line:
                break
            try:
                conn.sendall(line.encode())
            except OSError:
                break

    t = threading.Thread(target=pump_in, daemon=True)
    t.start()
    try:
        while True:
            try:
                data = conn.recv(4096)
            except socket.timeout:
                continue
            except OSError:
                break
            if not data:
                break
            stdout.write(data.decode(errors="replace"))
            stdout.flush()
    finally:
        stop.set()
        try:
            conn.close()
        except OSError:
            pass
