"""Cluster timeline: one Chrome/Perfetto trace for a whole session.

The consumption layer over the PR-8 telemetry plane (ISSUE 13). Two halves:

**Recording (every process).** Worker exec paths stamp per-task PHASE clocks
(received -> args-deserialized -> exec -> outputs-stored, monotonic reads,
``stamp_task_phases``) and subsystems record coarse windows (sampled
compiled-graph steps, whole plane pulls, ``record_span``) into one bounded
in-process ring. The stamping path is bind-only by contract — a list append
under one small lock, no instrument construction/lookup, no RPC — pinned by
``scripts/check_wire_schemas.py::check_phase_stamp_hot_path`` exactly like
the dag exec loop. Entries ride the EXISTING v5 ``metrics_push`` notify
(``phases`` field, inbound-tolerant: old heads drop it) with the same
advance-cursor-only-on-success contract as flight events.

**Merging (the head).** ``export()`` folds every signal the session has into
ONE Chrome-trace JSON array: worker task phases (local + pushed), head-side
task state transitions, tracing spans, sampled dag exec-loop steps, plane
pull windows, flight-recorder instants and gang transitions — process lanes
= nodes, thread lanes = worker pids / stable actor lanes, flow arrows from
the head RUNNING dispatch to the worker's exec window, and cross-node
timestamps re-based onto the head clock via per-node offsets estimated from
heartbeat-borne wall-clock samples (max-filter: one-way delay biases every
sample DOWN, so the largest recent sample is the closest to the true
offset). Reference analog: ``ray timeline`` over the GCS task manager's
aggregated task events + worker profile events (SURVEY §5.1), grown to the
whole-cluster Perfetto view.

Served by ``ray_tpu.util.state.timeline()``, ``GET /api/v0/timeline``, and
``python scripts/timeline.py``.
"""

from __future__ import annotations

import itertools
import os
import threading
import time
from collections import deque
from typing import Optional

# ------------------------------------------------------------- recording ring
# One bounded ring per process. Entry shapes (msgpack-native lists — they
# cross the wire inside metrics_push):
#   ["phase", seq, task_hex, pid, recv_w, args_w, exec0_w, exec1_w,
#    stored_w, status]
#   ["span",  seq, cat, name, pid, t0_w, dur_s, args|None]
# All *_w stamps are WALL seconds: stamped monotonic, converted once at
# append time via the process anchor (monotonic clocks are not comparable
# across processes; wall clocks are re-based per NODE at export).
MAX_EVENTS = int(os.environ.get("RAY_TPU_TIMELINE_EVENTS", "8192"))

_lock = threading.Lock()
_ring: deque = deque(maxlen=MAX_EVENTS)
_seq = itertools.count(1)
_PID = os.getpid()
# wall = monotonic + anchor for THIS process (one-time clock pair read)
_MONO_ANCHOR = time.time() - time.monotonic()
# env-gated so the phase-stamping A/B (MICROBENCH round 12) can switch the
# whole recording path off; checked per stamp as one module-global load
_ENABLED = os.environ.get("RAY_TPU_TASK_PHASES", "1") != "0"


def phase_reply(t_recv: float, t_args: float, t_exec1: float,
                t_stored: float) -> "list | None":
    """Worker half of phase stamping: convert the exec path's monotonic
    reads to wall seconds with the precomputed process anchor and return
    the 4-float clock list that rides the EXISTING done reply on the pool
    pipe (received -> args-deserialized -> exec-end -> outputs-stored;
    exec starts at args-deserialized). Bind-only: four float adds, no
    lock, no instruments, no RPC — pinned by check_phase_stamp_hot_path.
    Returns None when phase recording is off (the A/B switch)."""
    if not _ENABLED:
        return None
    a = _MONO_ANCHOR
    return [t_recv + a, t_args + a, t_exec1 + a, t_stored + a]


def stamp_task_phases(task_bin: "bytes | None", worker_pid: int, clocks,
                      status) -> None:
    """Pool-parent half: append one completed execution's phase record to
    THIS process's ring (``clocks`` = the worker's ``phase_reply`` list,
    already wall seconds on this machine's clock — pool workers are local
    children). The parent is the head driver or the node agent, both of
    which already push metrics — so worker phases ship without any worker
    dialing the control plane. One list append under the ring lock."""
    if not _ENABLED or not clocks or len(clocks) < 4:
        return
    entry = ["phase", next(_seq),
             task_bin.hex() if task_bin else None, worker_pid,
             clocks[0], clocks[1], clocks[1], clocks[2], clocks[3],
             status if isinstance(status, str) else "err"]
    with _lock:
        _ring.append(entry)


def record_span(cat: str, name: str, t0_wall: float, dur_s: float,
                args: "dict | None" = None) -> None:
    """A coarse timeline window (sampled dag step, whole plane pull):
    recorded at subsystem-chosen granularity, NEVER per hot event."""
    if not _ENABLED:
        return
    entry = ["span", next(_seq), cat, name, _PID, t0_wall, dur_s, args]
    with _lock:
        _ring.append(entry)


def drain_since(cursor: int) -> "tuple[list, int]":
    """Entries newer than ``cursor`` + the new cursor — the metrics_push
    incremental ship loop (same contract as flight_recorder.drain_since:
    the caller advances the cursor only after a successful push)."""
    out = []
    with _lock:
        for e in _ring:
            if e[1] > cursor:
                out.append(e)
    return out, (out[-1][1] if out else cursor)


def local_events() -> list:
    with _lock:
        return list(_ring)


def clear() -> None:
    with _lock:
        _ring.clear()


# ------------------------------------------------------- head-side ingestion
# Pushed entries keyed by origin (node_hex, source); bounded so a chatty
# fleet cannot grow the head without bound.
MAX_REMOTE_EVENTS = int(os.environ.get("RAY_TPU_TIMELINE_REMOTE_EVENTS",
                                       "65536"))
_remote_lock = threading.Lock()
_remote: deque = deque(maxlen=MAX_REMOTE_EVENTS)


def _sane_event(e) -> bool:
    if not isinstance(e, (list, tuple)):
        return False
    if e and e[0] == "phase":
        return (len(e) >= 10
                and all(isinstance(v, (int, float)) for v in e[4:9]))
    if e and e[0] == "span":
        # 8 slots minimum: _ring_event_rows unpacks e[:8] — a short entry
        # admitted here would fail EVERY later export, not just this one
        return (len(e) >= 8
                and isinstance(e[2], str) and isinstance(e[3], str)
                and isinstance(e[5], (int, float))
                and isinstance(e[6], (int, float)))
    return False


def ingest_remote(node_hex: str, source: str, events) -> None:
    """Head side: fold one process's pushed timeline entries in, tagged with
    the origin node (shape-sanitized — one buggy pusher degrades to missing
    lanes, never to an export crash)."""
    if not isinstance(events, (list, tuple)):
        return
    with _remote_lock:
        for e in events:
            if _sane_event(e):
                _remote.append((str(node_hex), str(source), list(e)))


def remote_events() -> list:
    with _remote_lock:
        return list(_remote)


# Note: a dead node's already-ingested entries are deliberately KEPT (the
# bounded deque ages them out) — a timeline is a post-mortem artifact, and
# a restarted node registers under a fresh NodeID/lane anyway.


# ------------------------------------------------------------- clock offsets
# offset[node] estimates (node_wall - head_wall). Every heartbeat-borne
# sample is remote_send_wall - head_recv_wall = offset - one_way_delay,
# i.e. biased DOWN by the (non-negative) network+queue delay — so the MAX
# of a recent window is the closest sample to the true offset (the classic
# one-way min-delay filter). Same-host agents sample ~0.
_CLOCK_WINDOW = 32
_clock_lock = threading.Lock()
_clock_samples: dict[str, deque] = {}


def note_clock_sample(node_hex: str, remote_wall: float,
                      local_wall: "float | None" = None) -> None:
    sample = float(remote_wall) - (local_wall if local_wall is not None
                                   else time.time())
    with _clock_lock:
        ring = _clock_samples.get(node_hex)
        if ring is None:
            ring = _clock_samples[node_hex] = deque(maxlen=_CLOCK_WINDOW)
        ring.append(sample)


def clock_offset(node_hex: str) -> float:
    """Best current estimate of ``node_wall - head_wall`` (0.0 unknown)."""
    with _clock_lock:
        ring = _clock_samples.get(node_hex)
        return max(ring) if ring else 0.0


def clock_offsets() -> dict:
    with _clock_lock:
        return {k: max(v) for k, v in _clock_samples.items() if v}


# ------------------------------------------------------------------- export
_NODE_LANE_BASE = 10     # remote node process lanes start here (1 = head,
#                          2 = legacy export-pipeline worker_exec lanes)
_SPAN_LANE_BASE = 200    # span thread lanes on the head process lane
_HEAD_PID = 1
_EXPORT_PID = 2


def _us(ts: float) -> int:
    return int(ts * 1e6)


def _node_lanes(node_hexes) -> dict:
    """Stable process-lane ids: head is pid 1; remote nodes take 10+i in
    sorted order (deterministic across exports and processes — the
    satellite fix for the per-process hash-salted lanes)."""
    lanes = {"head": _HEAD_PID, None: _HEAD_PID, "": _HEAD_PID}
    for i, nh in enumerate(sorted({h for h in node_hexes
                                   if h and h != "head"})):
        lanes[nh] = _NODE_LANE_BASE + i
    return lanes


def _head_transition_events(events: list, trace: list,
                            exec_flow: dict) -> None:
    """Head-observed state transitions -> complete X slices per task, with
    STABLE per-actor/task thread lanes, and open ``ph:"B"`` spans for tasks
    whose terminal event was evicted from the bounded buffer (previously
    silently dropped)."""
    # stable lane ids: sorted distinct lane keys -> 1..N (not hash-salted)
    lane_keys = sorted({ev.get("actor_id") or "tasks" for ev in events})
    lane_of = {k: i + 1 for i, k in enumerate(lane_keys)}
    starts: dict[str, dict] = {}
    for ev in events:
        tid_key = ev.get("actor_id") or "tasks"
        task_id = ev["task_id"]
        if ev["state"] == "RUNNING":
            starts[task_id] = ev
            exec_flow.setdefault(task_id, {})["submit_ts"] = ev["ts"]
        elif ev["state"] in ("FINISHED", "FAILED", "CANCELLED"):
            st = starts.pop(task_id, None)
            if st is None:
                continue
            trace.append({
                "name": ev["name"], "cat": "task", "ph": "X",
                "ts": _us(st["ts"]),
                "dur": max(0, _us(ev["ts"]) - _us(st["ts"])),
                "pid": _HEAD_PID, "tid": lane_of[tid_key],
                "args": {"state": ev["state"], "task_id": task_id},
            })
            exec_flow.setdefault(task_id, {})["end_ts"] = ev["ts"]
    # unpaired RUNNING: the task is live (or its terminal event was evicted)
    # — surface an open span instead of dropping it
    for task_id, st in starts.items():
        trace.append({
            "name": st["name"], "cat": "task", "ph": "B",
            "ts": _us(st["ts"]), "pid": _HEAD_PID,
            "tid": lane_of[st.get("actor_id") or "tasks"],
            "args": {"state": "RUNNING", "task_id": task_id},
        })


def _ring_event_rows(trace: list, exec_flow: dict, lanes: dict) -> None:
    """Local + pushed ring entries -> task_phase slices and subsystem spans,
    remote wall clocks re-based onto the head clock via the node offset."""
    rows = [("head", "local", e) for e in local_events()]
    rows.extend(remote_events())
    offsets = clock_offsets()
    for node_hex, _source, e in rows:
        off = offsets.get(node_hex, 0.0) if node_hex != "head" else 0.0
        pid_lane = lanes.get(node_hex)
        if pid_lane is None:  # client:<host> rows — give them a lane too
            pid_lane = lanes[node_hex] = (_NODE_LANE_BASE
                                          + len([k for k in lanes
                                                 if k not in ("head", None, "")]))
        if e[0] == "phase":
            _kind, _seq, task_hex, wpid, t_recv, t_args, t0, t1, t_store, \
                status = e[:10]
            short = (task_hex or "?")[:12]
            base = {"cat": "task_phase", "ph": "X", "pid": pid_lane,
                    "tid": wpid}
            for name, a, b in (("deser:" + short, t_recv, t_args),
                               ("exec:" + short, t0, t1),
                               ("store:" + short, t1, t_store)):
                trace.append({**base, "name": name, "ts": _us(a - off),
                              "dur": max(0, _us(b - off) - _us(a - off)),
                              "args": {"status": status,
                                       "node": node_hex, "worker_pid": wpid}})
            if task_hex:
                flow = exec_flow.setdefault(task_hex, {})
                flow["exec_ts"] = t0 - off
                flow["exec_pid"] = pid_lane
                flow["exec_tid"] = wpid
        else:  # span
            _kind, _seq, cat, name, wpid, t0, dur, args = e[:8]
            trace.append({
                "name": name, "cat": cat, "ph": "X", "ts": _us(t0 - off),
                "dur": max(0, int(dur * 1e6)),
                "pid": pid_lane, "tid": wpid,
                "args": {**(args if isinstance(args, dict) else {}),
                         "node": node_hex},
            })


def _span_events(trace: list) -> None:
    from ray_tpu.util import tracing

    lane_of: dict[str, int] = {}
    for s in sorted(tracing.spans(), key=lambda s: s.trace_id):
        tid = lane_of.setdefault(s.trace_id,
                                 _SPAN_LANE_BASE + len(lane_of))
        trace.append({
            "name": s.name, "cat": "span", "ph": "X",
            "ts": s.start_ns // 1000,
            "dur": max(0, (s.end_ns - s.start_ns) // 1000),
            "pid": _HEAD_PID, "tid": tid,
            "args": {**s.attributes, "status": s.status,
                     "trace_id": s.trace_id},
        })


def _flight_events(trace: list, lanes: dict) -> None:
    from ray_tpu.util import flight_recorder

    for ev in flight_recorder.records(limit=10000):
        sub = ev.get("subsystem", "?")
        node = ev.get("node_id") or "head"
        trace.append({
            "name": f"{sub}:{ev.get('event', '?')}",
            "cat": "gang" if sub == "gang" else "flight",
            "ph": "i", "s": "g", "ts": _us(ev["ts"]),
            "pid": lanes.get(node, _HEAD_PID), "tid": 0,
            "args": {k: v for k, v in ev.items()
                     if k not in ("seq", "ts")},
        })


def _export_pipeline_events(trace: list) -> None:
    """Worker-side execution windows from the export-event pipeline (when
    export events are on): the legacy ``worker_exec`` lanes on pid 2 —
    kept verbatim for consumers of the pre-ISSUE-13 shape."""
    import glob
    import json

    from ray_tpu._private import export_events
    from ray_tpu.core.runtime import get_runtime_or_none

    profile_dir = None
    rt = get_runtime_or_none()
    session_dir = getattr(rt, "session_dir", None)
    if session_dir is not None:
        profile_dir = os.path.join(session_dir, "export_events")
    elif export_events.enabled() and export_events._DIR is not None:
        profile_dir = export_events._DIR
    if profile_dir is None:
        return
    try:
        for p in glob.glob(os.path.join(profile_dir,
                                        "export_task_profile*.jsonl")):
            with open(p) as f:
                for line in f:
                    try:
                        ev = json.loads(line)["event_data"]
                    except (ValueError, KeyError):
                        continue
                    trace.append({
                        "name": f"exec:{(ev.get('task_id') or '?')[:12]}",
                        "cat": "worker_exec", "ph": "X",
                        "ts": _us(ev["exec_start"]),
                        "dur": max(0, _us(ev["exec_end"])
                                   - _us(ev["exec_start"])),
                        "pid": _EXPORT_PID,
                        "tid": ev.get("worker_pid") or 0,
                        "args": {"status": ev.get("status")},
                    })
    except OSError:
        pass


def _flow_arrows(trace: list, exec_flow: dict) -> None:
    """submit -> exec flow arrows: one ``s``/``f`` pair per task that has
    BOTH a head-side RUNNING dispatch and a worker-side exec window."""
    for task_hex, flow in exec_flow.items():
        if "submit_ts" not in flow or "exec_ts" not in flow:
            continue
        common = {"cat": "flow", "name": "submit", "id": task_hex[:16]}
        trace.append({**common, "ph": "s", "ts": _us(flow["submit_ts"]),
                      "pid": _HEAD_PID, "tid": 0})
        trace.append({**common, "ph": "f", "bp": "e",
                      "ts": _us(flow["exec_ts"]),
                      "pid": flow["exec_pid"], "tid": flow["exec_tid"]})


def _lane_metadata(trace: list, lanes: dict) -> None:
    names = {_HEAD_PID: "head (control plane)",
             _EXPORT_PID: "workers (export pipeline)"}
    for nh, pid in lanes.items():
        if nh not in ("head", None, "") and pid not in names:
            names[pid] = f"node {nh[:12]}"
    for pid, name in sorted(names.items()):
        # "cat" present on every event (consumers index by it freely)
        trace.append({"name": "process_name", "cat": "meta", "ph": "M",
                      "pid": pid, "tid": 0, "args": {"name": name}})


def export(path: Optional[str] = None) -> list[dict]:
    """The whole session as one Chrome/Perfetto trace (JSON array of trace
    events). Load in ``ui.perfetto.dev`` or ``chrome://tracing``."""
    from ray_tpu.core.runtime import get_runtime_or_none

    rt = get_runtime_or_none()
    events = rt.task_events() if hasattr(rt, "task_events") else []

    import sys as _sys

    trace: list[dict] = []
    exec_flow: dict[str, dict] = {}
    node_hexes = [t[0] for t in remote_events()]
    # nodes known only through store-occupancy samples (memory anatomy)
    # still deserve their own named lane for the counter track
    _mem = _sys.modules.get("ray_tpu.core.mem_anatomy")
    if _mem is not None:
        try:
            node_hexes += list(_mem.occupancy_nodes())
        except Exception:
            pass
    lanes = _node_lanes(node_hexes)

    _head_transition_events(events, trace, exec_flow)
    _span_events(trace)
    _ring_event_rows(trace, exec_flow, lanes)
    _flight_events(trace, lanes)
    _export_pipeline_events(trace)
    _flow_arrows(trace, exec_flow)
    _lane_metadata(trace, lanes)
    try:
        # serve request lanes + ingress->prefill->decode flow arrows
        # (serve/anatomy.py, ISSUE 16) — already offset-aligned via this
        # module's clock_offsets; lazy so non-serve sessions never import
        # the serve package here
        _an = _sys.modules.get("ray_tpu.serve.anatomy")
        if _an is not None:
            trace.extend(_an.trace_events())
    except Exception:
        pass  # a malformed ledger must not break the whole export
    try:
        # per-node plane-store occupancy counter tracks (memory anatomy,
        # ISSUE 18): samples carry head wall stamps from ingest time, so
        # they need no cross-node offset alignment
        if _mem is not None:
            trace.extend(_mem.trace_counter_events(
                lambda nh: lanes.get(nh, _HEAD_PID)))
    except Exception:
        pass
    trace.sort(key=lambda e: e.get("ts", 0))
    if path:
        import json

        with open(path, "w") as f:
            json.dump(trace, f)
    return trace
