"""TPU slice reservation: whole-slice gang placement.

Parity: python/ray/util/tpu.py — SlicePlacementGroup (:420) reserves an
entire TPU slice by claiming its head resource and pinning every bundle to
that slice's nodes via the slice-name label; reserve_tpu_slice
(_private/accelerators/tpu.py:269) is the claim primitive;
get_tpu_coordinator_env_vars (:212) builds the MEGASCALE env (here
parallel.mesh.multislice_env).

In this runtime, nodes carry ``slice_name`` + ICI coordinates at
registration (core/scheduler.py NodeState); a slice reservation is a
STRICT_SPREAD placement group label-pinned to one slice's hosts, so the gang
lands on exactly the slice's nodes and the derived bundle resources give
each worker its host's chips.
"""

from __future__ import annotations

import dataclasses
from typing import Optional


@dataclasses.dataclass
class SliceInfo:
    slice_name: str
    num_hosts: int
    chips_per_host: int
    placement_group: object  # PlacementGroup handle reserving the hosts


def list_slices() -> dict[str, list]:
    """slice_name -> that slice's nodes (topology registered at node join)."""
    from ray_tpu.core.runtime import get_runtime

    out: dict[str, list] = {}
    for node in get_runtime().scheduler.nodes():
        if node.slice_name:
            out.setdefault(node.slice_name, []).append(node)
    return out


def reserve_tpu_slice(slice_name: Optional[str] = None,
                      chips_per_host: Optional[int] = None,
                      timeout: float = 60.0) -> SliceInfo:
    """Reserve one whole slice for a gang (reference: SlicePlacementGroup
    util/tpu.py:420 + reserve_tpu_slice accelerators/tpu.py:269).

    Picks ``slice_name`` (or the first fully-idle slice), then creates a
    STRICT_SPREAD placement group with one TPU bundle per host, pinned to
    the slice, and waits for it to commit. A failed/timed-out reservation
    removes its pending group (no phantom claims on the slice)."""
    slices = list_slices()
    if not slices:
        raise RuntimeError("no TPU slices registered in this cluster")
    if slice_name is None:
        idle = [s for s, nodes in sorted(slices.items())
                if all(n.available.get("TPU", 0) == n.total.get("TPU", 0)
                       for n in nodes)]
        if not idle:
            raise RuntimeError(
                f"no fully-idle slice to auto-pick from {sorted(slices)}; "
                "name one explicitly to queue on it")
        slice_name = idle[0]
    if slice_name not in slices:
        raise ValueError(f"unknown slice {slice_name!r}; have {sorted(slices)}")
    nodes = slices[slice_name]
    chips = chips_per_host
    if chips is None:
        chips = int(min(n.total.get("TPU", 0) for n in nodes))
        if chips <= 0:
            raise ValueError(
                f"slice {slice_name!r} has nodes without TPU resources; "
                "fix node registration or pass chips_per_host")
    from ray_tpu.core.api import placement_group, remove_placement_group

    pg = placement_group(
        bundles=[{"TPU": float(chips)} for _ in nodes],
        strategy="STRICT_SPREAD",
        name=f"slice-{slice_name}",
        _slice_name=slice_name,
    )
    if not pg.wait(timeout):
        remove_placement_group(pg)  # don't leave a phantom claim queued
        raise TimeoutError(
            f"slice {slice_name!r} not reservable within {timeout}s")
    return SliceInfo(slice_name=slice_name, num_hosts=len(nodes),
                     chips_per_host=chips, placement_group=pg)


def get_tpu_coordinator_env_vars(coordinator_address: str, num_slices: int,
                                 slice_id: int) -> dict[str, str]:
    """Reference: util/tpu.py:212 — re-exported from parallel.mesh so the
    train and serve layers share one MEGASCALE builder."""
    from ray_tpu.parallel.mesh import multislice_env

    return multislice_env(coordinator_address, num_slices, slice_id)
