"""User-defined metrics: Counter / Gauge / Histogram.

Parity: python/ray/util/metrics.py — tagged metrics recorded by application
code; a registry snapshot serves the dashboard/Prometheus scrape (reference:
per-node metrics agent + opencensus pipeline, SURVEY §5.5).
"""

from __future__ import annotations

import threading
from collections import defaultdict
from typing import Iterable, Optional

_registry_lock = threading.Lock()
_registry: dict[str, "Metric"] = {}


class Metric:
    def __init__(self, name: str, description: str = "", tag_keys: Optional[Iterable[str]] = None):
        self.name = name
        self.description = description
        self.tag_keys = tuple(tag_keys or ())
        self._default_tags: dict[str, str] = {}
        self._lock = threading.Lock()
        with _registry_lock:
            _registry[name] = self

    def set_default_tags(self, tags: dict[str, str]) -> "Metric":
        self._default_tags = dict(tags)
        return self

    def _key(self, tags: dict | None) -> tuple:
        merged = {**self._default_tags, **(tags or {})}
        return tuple(sorted(merged.items()))


class Counter(Metric):
    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        self._values: dict[tuple, float] = defaultdict(float)

    def inc(self, value: float = 1.0, tags: dict | None = None) -> None:
        with self._lock:
            self._values[self._key(tags)] += value

    def snapshot(self) -> dict:
        with self._lock:
            return dict(self._values)


class Gauge(Metric):
    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        self._values: dict[tuple, float] = {}

    def set(self, value: float, tags: dict | None = None) -> None:
        with self._lock:
            self._values[self._key(tags)] = value

    def snapshot(self) -> dict:
        with self._lock:
            return dict(self._values)


class Histogram(Metric):
    def __init__(self, name: str, description: str = "", boundaries: Iterable[float] = (),
                 tag_keys=None):
        super().__init__(name, description, tag_keys)
        self.boundaries = sorted(boundaries) or [0.01, 0.1, 1, 10, 100]
        self._counts: dict[tuple, list[int]] = {}
        self._sums: dict[tuple, float] = defaultdict(float)

    def observe(self, value: float, tags: dict | None = None) -> None:
        key = self._key(tags)
        with self._lock:
            buckets = self._counts.setdefault(key, [0] * (len(self.boundaries) + 1))
            for i, b in enumerate(self.boundaries):
                if value <= b:
                    buckets[i] += 1
                    break
            else:
                buckets[-1] += 1
            self._sums[key] += value

    def snapshot(self) -> dict:
        with self._lock:
            return {k: {"buckets": list(v), "sum": self._sums[k]} for k, v in self._counts.items()}


def registry_snapshot() -> dict:
    """All metrics, for exposition (dashboard / prometheus text format)."""
    with _registry_lock:
        metrics = dict(_registry)
    return {name: m.snapshot() for name, m in metrics.items() if hasattr(m, "snapshot")}


def prometheus_text() -> str:
    """Render the registry in Prometheus exposition format."""
    lines = []
    for name, values in registry_snapshot().items():
        safe = name.replace(".", "_").replace("-", "_")
        for key, val in values.items():
            tags = ",".join(f'{k}="{v}"' for k, v in key)
            label = f"{{{tags}}}" if tags else ""
            if isinstance(val, dict):  # histogram
                lines.append(f"{safe}_sum{label} {val['sum']}")
                lines.append(f"{safe}_count{label} {sum(val['buckets'])}")
            else:
                lines.append(f"{safe}{label} {val}")
    return "\n".join(lines) + "\n"
