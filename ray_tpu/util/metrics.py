"""User-defined + runtime metrics: Counter / Gauge / Histogram.

Parity: python/ray/util/metrics.py — tagged metrics recorded by application
code — plus the per-node metrics-agent pipeline (reference: SURVEY §5.5,
_private/metrics_agent.py): every process records into its own registry,
node agents ship compact snapshots to the head over the ``metrics_push``
wire op, and the head's ``/metrics`` scrape merges them into one
cluster-wide Prometheus view with a ``node_id`` label per remote series.

Hot-path contract: subsystems that record per-event (RPC dispatch, plane
pulls, compiled-graph steps) bind instruments ONCE — at import or install
time — via ``bind()``, which precomputes the series key so recording is a
single locked dict update with no tag merging, no registry lookup
(enforced for the hottest modules by ``scripts/check_wire_schemas.py::
check_hot_path_instruments``). Gauges for values that already live
somewhere (queue depths, bytes in flight) attach a producer callback and
cost nothing until scrape/push time.
"""

from __future__ import annotations

import os
import threading
import time
from bisect import bisect_left
from collections import defaultdict
from typing import Callable, Iterable, Optional

_registry_lock = threading.Lock()
_registry: dict[str, "Metric"] = {}

DEFAULT_HIST_BOUNDARIES = [0.01, 0.1, 1, 10, 100]


class Metric:
    """Base instrument. Re-registering a name RETURNS the existing
    instrument object (extended with any newly declared tag keys) instead
    of silently shadowing it — the reference's ``ray.util.metrics``
    behavior, where a metric name identifies one series family per
    process. A name re-registered as a *different* instrument kind is a
    programming error and raises.

    Construction happens ENTIRELY inside ``__new__`` under the registry
    lock (``__init__`` is a no-op): the create-vs-reuse decision and the
    instance's storage setup are atomic, so two threads racing the first
    registration can never observe a half-initialized instrument."""

    def __new__(cls, name: str, *args, **kwargs):
        with _registry_lock:
            existing = _registry.get(name)
            if existing is not None:
                if type(existing) is not cls:
                    raise ValueError(
                        f"metric {name!r} already registered as "
                        f"{type(existing).__name__}, not {cls.__name__}")
                existing._merge(*args, **kwargs)
                return existing
            inst = super().__new__(cls)
            inst._setup(name, *args, **kwargs)
            _registry[name] = inst
            return inst

    def __init__(self, *args, **kwargs):
        pass  # see __new__: construction is atomic with registration

    def _setup(self, name: str, description: str = "",
               tag_keys: Optional[Iterable[str]] = None) -> None:
        self.name = name
        self.description = description
        self.tag_keys = tuple(tag_keys or ())
        self._default_tags: dict[str, str] = {}
        self._lock = threading.Lock()

    def _merge(self, description: str = "",
               tag_keys: Optional[Iterable[str]] = None) -> None:
        """Duplicate registration: keep the live series, union tag keys."""
        if tag_keys:
            merged = dict.fromkeys(self.tag_keys)
            merged.update(dict.fromkeys(tag_keys))
            self.tag_keys = tuple(merged)
        if description and not self.description:
            self.description = description

    def set_default_tags(self, tags: dict[str, str]) -> "Metric":
        self._check_tags(tags)
        self._default_tags = dict(tags)
        return self

    def _check_tags(self, tags: dict | None) -> None:
        if not tags:
            return
        undeclared = [k for k in tags if k not in self.tag_keys]
        if undeclared:
            raise ValueError(
                f"metric {self.name!r}: tag(s) {undeclared} not declared in "
                f"tag_keys={list(self.tag_keys)} — undeclared tags would "
                "fork silent series (declare them at construction)")

    def _key(self, tags: dict | None) -> tuple:
        self._check_tags(tags)
        merged = {**self._default_tags, **(tags or {})}
        return tuple(sorted(merged.items()))

    def bind(self, tags: dict | None = None):
        """Precompute one series' key: the returned handle records with a
        single locked dict update — the hot-path form (bind at import or
        install time, record per event). The handle is kind-typed: a
        Counter bind exposes only inc(), a Gauge only set(), a Histogram
        only observe() — a kind mismatch fails at bind time, not on the
        hot path."""
        return self._bound_cls(self, self._key(tags))


class _BoundBase:
    """A (metric, series-key) pair with allocation-free record methods."""

    __slots__ = ("_m", "_k")

    def __init__(self, metric: Metric, key: tuple):
        self._m = metric
        self._k = key


class _BoundCounter(_BoundBase):
    __slots__ = ()

    def inc(self, value: float = 1.0) -> None:
        m = self._m
        with m._lock:
            m._values[self._k] += value


class _BoundGauge(_BoundBase):
    __slots__ = ()

    def set(self, value: float) -> None:
        m = self._m
        with m._lock:
            m._values[self._k] = value


class _BoundHistogram(_BoundBase):
    __slots__ = ()

    def observe(self, value: float) -> None:
        self._m._observe_key(self._k, value)


class Counter(Metric):
    _bound_cls = _BoundCounter

    def _setup(self, *args, **kwargs) -> None:
        super()._setup(*args, **kwargs)
        self._values: dict[tuple, float] = defaultdict(float)

    def inc(self, value: float = 1.0, tags: dict | None = None) -> None:
        key = self._key(tags)
        with self._lock:
            self._values[key] += value

    def snapshot(self) -> dict:
        with self._lock:
            return dict(self._values)


class Gauge(Metric):
    _bound_cls = _BoundGauge

    def _setup(self, *args, **kwargs) -> None:
        super()._setup(*args, **kwargs)
        self._values: dict[tuple, float] = {}
        self._producers: list[Callable[[], Iterable[tuple[dict, float]]]] = []

    def set(self, value: float, tags: dict | None = None) -> None:
        key = self._key(tags)
        with self._lock:
            self._values[key] = value

    def attach_producer(
            self, fn: "Callable[[], Iterable[tuple[dict, float]]]") -> None:
        """Register a callback yielding ``(tags, value)`` pairs, sampled at
        snapshot time — zero hot-path cost for values that already live in
        some subsystem (queue depths, bytes in flight). Producer errors are
        swallowed: a scrape must never take the runtime down."""
        with self._lock:
            self._producers.append(fn)

    def snapshot(self) -> dict:
        with self._lock:
            out = dict(self._values)
            producers = list(self._producers)
        for fn in producers:
            try:
                for tags, value in fn():
                    out[self._key(tags)] = value
            except Exception:
                pass
        return out


class Histogram(Metric):
    _bound_cls = _BoundHistogram

    def _setup(self, name: str, description: str = "",
               boundaries: Iterable[float] = (), tag_keys=None) -> None:
        super()._setup(name, description, tag_keys)
        self.boundaries = sorted(boundaries) or list(DEFAULT_HIST_BOUNDARIES)
        self._counts: dict[tuple, list[int]] = {}
        self._sums: dict[tuple, float] = defaultdict(float)

    def _merge(self, description: str = "", boundaries: Iterable[float] = (),
               tag_keys=None) -> None:
        # boundaries are fixed at first registration (live bucket lists
        # can't be re-shaped); later declarations keep the original
        super()._merge(description, tag_keys)

    def observe(self, value: float, tags: dict | None = None) -> None:
        self._observe_key(self._key(tags), value)

    def _observe_key(self, key: tuple, value: float) -> None:
        i = bisect_left(self.boundaries, value)
        with self._lock:
            buckets = self._counts.get(key)
            if buckets is None:
                buckets = self._counts[key] = [0] * (len(self.boundaries) + 1)
            buckets[i] += 1
            self._sums[key] += value

    def snapshot(self) -> dict:
        with self._lock:
            return {k: {"buckets": list(v), "sum": self._sums[k],
                        "boundaries": self.boundaries}
                    for k, v in self._counts.items()}


def get_metric(name: str) -> "Metric | None":
    with _registry_lock:
        return _registry.get(name)


def registry_snapshot() -> dict:
    """All metrics, for exposition (dashboard / prometheus text format)."""
    with _registry_lock:
        metrics = dict(_registry)
    return {name: m.snapshot() for name, m in metrics.items() if hasattr(m, "snapshot")}


# ------------------------------------------------------- cluster aggregation
# Remote snapshots pushed over ``metrics_push`` — by node agents AND by
# worker processes (a node's plane pulls and compiled-graph channels live
# in its workers, not its agent). Keyed by (node_hex, source) where source
# distinguishes processes on one node; each entry keeps the previous
# counters too so byte/sec rates (the striper/scheduler bandwidth signal)
# come for free.
_remote_lock = threading.Lock()
_remote: dict[tuple[str, str], dict] = {}


def wire_snapshot() -> list:
    """This process's registry as a msgpack-native list (tag tuples become
    ``[[k, v], ...]`` lists — msgpack map keys can't be tuples):
    ``[name, kind, [[tags, value], ...]], ...``."""
    out = []
    with _registry_lock:
        metrics = list(_registry.items())
    for name, m in metrics:
        if not hasattr(m, "snapshot"):
            continue
        kind = type(m).__name__.lower()
        series = []
        for key, val in m.snapshot().items():
            tags = [[k, v] for k, v in key]
            series.append([tags, val])
        if series:
            out.append([name, kind, series])
    return out


def _sane_value(val) -> bool:
    if isinstance(val, bool):
        return False
    if isinstance(val, (int, float)):
        return True
    if not (isinstance(val, dict) and isinstance(val.get("buckets"), list)
            and isinstance(val.get("sum"), (int, float))
            and all(isinstance(b, (int, float)) for b in val["buckets"])):
        return False
    # boundaries ride the wire too and feed zip() in _render_series — a
    # non-list (or non-numeric entries) would poison every later scrape
    bounds = val.get("boundaries", [])
    return (isinstance(bounds, (list, tuple))
            and all(isinstance(b, (int, float)) for b in bounds))


def _sanitize_snapshot(snap) -> list:
    """Drop malformed entries from a pushed snapshot BEFORE storing it: a
    single version-skewed or buggy pusher must degrade to missing series,
    never to a /metrics / node_io_view 500 for the whole cluster (the
    stored entry would poison every later render until the peer drops)."""
    out = []
    if not isinstance(snap, (list, tuple)):
        return out
    for ent in snap:
        if not (isinstance(ent, (list, tuple)) and len(ent) == 3
                and isinstance(ent[0], str) and isinstance(ent[1], str)
                and isinstance(ent[2], (list, tuple))):
            continue
        series = []
        for s in ent[2]:
            if not (isinstance(s, (list, tuple)) and len(s) == 2):
                continue
            tags, val = s
            if not isinstance(tags, (list, tuple)) or not _sane_value(val):
                continue
            if all(isinstance(t, (list, tuple)) and len(t) == 2 for t in tags):
                series.append([tags, val])
        if series:
            out.append([ent[0], ent[1], series])
    return out


def ingest_wire_snapshot(node_hex: str, snap: list,
                         source: str = "agent") -> None:
    """Head side: merge one process's pushed snapshot (shape-sanitized).
    Counter-rate estimation keeps the previous push, so ``node_rates()``
    can answer bytes/sec without the head ever subscribing to raw
    events."""
    snap = _sanitize_snapshot(snap)
    now = time.monotonic()
    key = (node_hex, source)
    with _remote_lock:
        prev = _remote.get(key)
        _remote[key] = {
            "snap": snap, "ts": now, "wall_ts": time.time(),
            "prev_snap": prev["snap"] if prev else None,
            "prev_ts": prev["ts"] if prev else None,
        }


def drop_remote_snapshot(node_hex: str, source: "str | None" = None) -> None:
    """Forget a process's series (peer disconnected) — ``source=None``
    drops every source of the node (node death)."""
    with _remote_lock:
        for key in [k for k in _remote
                    if k[0] == node_hex and (source is None or k[1] == source)]:
            _remote.pop(key, None)


def _push_expiry_s() -> "float | None":
    """Staleness bound for pushed series: 3x the push period (None = push
    disabled, nothing expires)."""
    try:
        period = float(os.environ.get("RAY_TPU_METRICS_PUSH_PERIOD_S", "2"))
    except ValueError:
        period = 2.0
    return 3.0 * period if period > 0 else None


def remote_snapshots() -> dict[tuple[str, str], dict]:
    """Live pushed snapshots. A ``(node, src)`` that has not pushed within
    3x the push period is EXPIRED here (and pruned) — previously a dead
    worker's gauges lingered at their last value in /metrics forever; the
    disconnect-drop path only covers peers whose socket death the head
    observed."""
    exp = _push_expiry_s()
    now = time.monotonic()
    with _remote_lock:
        if exp is not None:
            for key in [k for k, ent in _remote.items()
                        if now - ent["ts"] > exp]:
                del _remote[key]
        return dict(_remote)


def _counter_total(snap: list, metric_name: str) -> "float | None":
    for name, kind, series in snap:
        if name == metric_name and kind == "counter":
            return sum(val for _tags, val in series)
    return None


def _gauge_series(snap: list, metric_name: str) -> "list | None":
    for name, kind, series in snap:
        if name == metric_name:
            return series
    return None


def node_rates(metric_name: str) -> dict[str, float]:
    """Per-node rate (units/sec) of a pushed counter, from the last two
    pushes of every source on the node — e.g.
    ``node_rates("ray_tpu_plane_pull_bytes_total")`` is the per-node
    pull-bandwidth estimate node_io_view() serves."""
    out: dict[str, float] = {}
    for (node_hex, _src), ent in remote_snapshots().items():
        cur = _counter_total(ent["snap"], metric_name)
        if cur is None:
            continue
        prev = (_counter_total(ent["prev_snap"], metric_name)
                if ent.get("prev_snap") else None)
        dt = (ent["ts"] - ent["prev_ts"]) if ent.get("prev_ts") else None
        rate = (max(0.0, (cur - prev) / dt)
                if prev is not None and dt and dt > 0 else 0.0)
        out[node_hex] = out.get(node_hex, 0.0) + rate
    return out


def node_counter(metric_name: str) -> dict[str, float]:
    """Latest pushed total of a counter per node (sources summed)."""
    out: dict[str, float] = {}
    for (node_hex, _src), ent in remote_snapshots().items():
        cur = _counter_total(ent["snap"], metric_name)
        if cur is not None:
            out[node_hex] = out.get(node_hex, 0.0) + cur
    return out


def node_gauge(metric_name: str) -> dict[str, float]:
    """Latest pushed value of a gauge per node (series + sources summed)."""
    out: dict[str, float] = {}
    for (node_hex, _src), ent in remote_snapshots().items():
        series = _gauge_series(ent["snap"], metric_name)
        if series is not None:
            out[node_hex] = out.get(node_hex, 0.0) + sum(
                v for _t, v in series if isinstance(v, (int, float)))
    return out


def node_tagged_gauge(metric_name: str,
                      tag_key: str) -> dict[str, dict[str, float]]:
    """Pushed gauge broken out per node AND per one tag's value — e.g.
    ``node_tagged_gauge("ray_tpu_plane_holder_pending_bytes", "holder")``
    gives each node's per-holder pending-bytes map (sources summed)."""
    out: dict[str, dict[str, float]] = {}
    for (node_hex, _src), ent in remote_snapshots().items():
        series = _gauge_series(ent["snap"], metric_name)
        if series is None:
            continue
        per = out.setdefault(node_hex, {})
        for tags, val in series:
            if not isinstance(val, (int, float)):
                continue
            tval = dict(tuple(t) for t in tags).get(tag_key)
            if tval is not None:
                per[str(tval)] = per.get(str(tval), 0.0) + val
    return out


def node_io_rollup() -> dict:
    """Everything ``state.node_io_view()`` needs from the remote-snapshot
    table in ONE pass (the per-metric extractors above each rescan the
    whole table — fine for ad-hoc queries, wasteful for a view the
    scheduler/striper/KV router poll)."""
    pull_rate: dict[str, float] = {}
    pull_total: dict[str, float] = {}
    inflight: dict[str, float] = {}
    reactor: dict[str, float] = {}
    holder: dict[str, dict[str, float]] = {}

    def _sum(series):
        return sum(v for _t, v in series if isinstance(v, (int, float)))

    for (node_hex, _src), ent in remote_snapshots().items():
        cur_total = None
        for name, kind, series in ent["snap"]:
            if name == "ray_tpu_plane_pull_bytes_total" and kind == "counter":
                cur_total = _sum(series)
                pull_total[node_hex] = pull_total.get(node_hex, 0.0) + cur_total
            elif name == "ray_tpu_plane_pull_bytes_in_flight":
                inflight[node_hex] = inflight.get(node_hex, 0.0) + _sum(series)
            elif name == "ray_tpu_rpc_reactor_queue_depth":
                reactor[node_hex] = reactor.get(node_hex, 0.0) + _sum(series)
            elif name == "ray_tpu_plane_holder_pending_bytes":
                per = holder.setdefault(node_hex, {})
                for tags, val in series:
                    if not isinstance(val, (int, float)):
                        continue
                    tval = dict(tuple(t) for t in tags).get("holder")
                    if tval is not None:
                        per[str(tval)] = per.get(str(tval), 0.0) + val
        if cur_total is not None:
            rate = 0.0
            prev_snap, prev_ts = ent.get("prev_snap"), ent.get("prev_ts")
            if prev_snap and prev_ts:
                prev = _counter_total(prev_snap,
                                      "ray_tpu_plane_pull_bytes_total")
                dt = ent["ts"] - prev_ts
                if prev is not None and dt > 0:
                    rate = max(0.0, (cur_total - prev) / dt)
            pull_rate[node_hex] = pull_rate.get(node_hex, 0.0) + rate
    return {"pull_rate": pull_rate, "pull_total": pull_total,
            "inflight": inflight, "reactor_depth": reactor,
            "holder_pending": holder}


def push_once(peer, cursor) -> dict:
    """One metrics_push over ``peer``: ship this process's registry plus
    flight-recorder events and timeline entries (worker task phases +
    subsystem spans, util/timeline) newer than their cursors; returns the
    advanced cursor dict ``{"flight": int, "timeline": int}`` (a bare int
    is accepted as a flight-only cursor from older callers). Cursors only
    move AFTER the notify succeeds, so a failed push re-ships its events
    next time instead of dropping them — shared by the node agent's
    heartbeat loop and the worker pusher. Raises on transport failure (the
    caller owns reconnect/skip policy)."""
    import sys

    from ray_tpu.util import flight_recorder, timeline

    if not isinstance(cursor, dict):
        cursor = {"flight": int(cursor), "timeline": 0}
    events, fl_cursor = flight_recorder.drain_since(cursor.get("flight", 0))
    phases, tl_cursor = timeline.drain_since(cursor.get("timeline", 0))
    # serve-anatomy piggyback: only processes that already loaded the serve
    # stack can have request-phase stamps — checking sys.modules keeps the
    # pusher from importing ray_tpu.serve into every worker
    serve_phases, sv_cursor = None, cursor.get("serve", 0)
    anatomy = sys.modules.get("ray_tpu.serve.anatomy")
    if anatomy is not None:
        serve_phases, sv_cursor = anatomy.drain_since(sv_cursor)
    # memory-anatomy piggyback: same sys.modules gate — only processes that
    # already mapped a plane store carry a ledger, and mem_report() is a
    # stateful snapshot (no cursor: the head replaces the previous report).
    mem = None
    shm = sys.modules.get("ray_tpu.core.shm_store")
    if shm is not None:
        try:
            mem = shm.mem_report()
        except Exception:
            mem = None  # a closing store must not take the push down
    peer.notify("metrics_push", snap=wire_snapshot(), events=events or None,
                phases=phases or None, serve_phases=serve_phases or None,
                mem_report=mem)
    return {"flight": fl_cursor, "timeline": tl_cursor, "serve": sv_cursor}


# ---------------------------------------------------------------- exposition
def _esc_label(v) -> str:
    """Prometheus exposition escaping for label values: backslash, double
    quote, newline — one hostile tag value must not invalidate the whole
    cluster scrape."""
    return (str(v).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _fmt_labels(pairs: Iterable[tuple]) -> str:
    tags = ",".join(f'{k}="{_esc_label(v)}"' for k, v in pairs)
    return f"{{{tags}}}" if tags else ""


def _render_series(lines: list, name: str, key, val,
                   extra_tags: "tuple | None" = None) -> None:
    safe = name.replace(".", "_").replace("-", "_")
    pairs = list(key) + list(extra_tags or ())
    if isinstance(val, dict):  # histogram
        buckets = val["buckets"]
        boundaries = val.get("boundaries") or DEFAULT_HIST_BOUNDARIES
        # cumulative _bucket lines incl. +Inf — without them histogram
        # quantiles aren't plottable (histogram_quantile needs le buckets)
        cum = 0
        for b, n in zip(boundaries, buckets):
            cum += n
            lines.append(
                f"{safe}_bucket{_fmt_labels(pairs + [('le', b)])} {cum}")
        total = cum + (buckets[len(boundaries)]
                       if len(buckets) > len(boundaries) else 0)
        lines.append(
            f"{safe}_bucket{_fmt_labels(pairs + [('le', '+Inf')])} {total}")
        lines.append(f"{safe}_sum{_fmt_labels(pairs)} {val['sum']}")
        lines.append(f"{safe}_count{_fmt_labels(pairs)} {total}")
    else:
        lines.append(f"{safe}{_fmt_labels(pairs)} {val}")


def prometheus_text() -> str:
    """Render the registry — local series plus every node-pushed remote
    snapshot (tagged ``node_id``) — in Prometheus exposition format: the
    cluster-wide scrape the dashboard's /metrics serves."""
    lines: list[str] = []
    for name, values in registry_snapshot().items():
        for key, val in values.items():
            _render_series(lines, name, key, val)
    for (node_hex, source), ent in remote_snapshots().items():
        # src disambiguates processes on one node (agent vs workers) so two
        # pushers can't emit conflicting samples under identical labels
        tag = (("node_id", node_hex), ("src", source))
        for name, kind, series in ent["snap"]:
            for tags, val in series:
                _render_series(lines, name, [tuple(t) for t in tags], val,
                               extra_tags=tag)
    return "\n".join(lines) + ("\n" if lines else "")


def system_prometheus_text() -> str:
    """Runtime-internal gauges in Prometheus exposition format (reference:
    the metrics agent exports core counters — task/actor/object-store state —
    alongside user metrics, _private/metrics_agent.py)."""
    from ray_tpu.core.runtime import get_runtime_or_none

    rt = get_runtime_or_none()
    if rt is None or not hasattr(rt, "scheduler"):
        return ""
    lines = []

    def gauge(name, value, **tags):
        # _fmt_labels escapes backslash/quote/newline per the exposition
        # spec — task states and store stat keys flow in from user-visible
        # strings and must not be able to break the scrape
        lines.append(
            f"ray_tpu_{name}{_fmt_labels(sorted(tags.items()))} {value}")

    states: dict[str, int] = {}
    with rt._lock:
        for t in rt._tasks.values():
            states[t.state] = states.get(t.state, 0) + 1
        actors = list(rt._actors.values())
    for state, n in sorted(states.items()):
        gauge("tasks", n, state=state)
    actor_states: dict[str, int] = {}
    for a in actors:
        actor_states[a.state] = actor_states.get(a.state, 0) + 1
    for state, n in sorted(actor_states.items()):
        gauge("actors", n, state=state)
    gauge("nodes", len(rt.scheduler.nodes()))
    gauge("objects_in_memory_store", rt.memory_store.size())
    if rt.shm_store is not None:
        try:
            for k, v in rt.shm_store.stats().items():
                gauge(f"shm_{k}", v)
        except Exception:
            pass
    if rt.spill is not None:
        for k, v in rt.spill.stats().items():
            gauge(f"spill_{k}", v)
    pool = getattr(rt, "_proc_pool", None)
    if pool is not None:
        gauge("worker_processes_alive", pool.num_alive)
    return "\n".join(lines) + ("\n" if lines else "")
