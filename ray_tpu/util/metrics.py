"""User-defined metrics: Counter / Gauge / Histogram.

Parity: python/ray/util/metrics.py — tagged metrics recorded by application
code; a registry snapshot serves the dashboard/Prometheus scrape (reference:
per-node metrics agent + opencensus pipeline, SURVEY §5.5).
"""

from __future__ import annotations

import threading
from collections import defaultdict
from typing import Iterable, Optional

_registry_lock = threading.Lock()
_registry: dict[str, "Metric"] = {}


class Metric:
    def __init__(self, name: str, description: str = "", tag_keys: Optional[Iterable[str]] = None):
        self.name = name
        self.description = description
        self.tag_keys = tuple(tag_keys or ())
        self._default_tags: dict[str, str] = {}
        self._lock = threading.Lock()
        with _registry_lock:
            _registry[name] = self

    def set_default_tags(self, tags: dict[str, str]) -> "Metric":
        self._default_tags = dict(tags)
        return self

    def _key(self, tags: dict | None) -> tuple:
        merged = {**self._default_tags, **(tags or {})}
        return tuple(sorted(merged.items()))


class Counter(Metric):
    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        self._values: dict[tuple, float] = defaultdict(float)

    def inc(self, value: float = 1.0, tags: dict | None = None) -> None:
        with self._lock:
            self._values[self._key(tags)] += value

    def snapshot(self) -> dict:
        with self._lock:
            return dict(self._values)


class Gauge(Metric):
    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        self._values: dict[tuple, float] = {}

    def set(self, value: float, tags: dict | None = None) -> None:
        with self._lock:
            self._values[self._key(tags)] = value

    def snapshot(self) -> dict:
        with self._lock:
            return dict(self._values)


class Histogram(Metric):
    def __init__(self, name: str, description: str = "", boundaries: Iterable[float] = (),
                 tag_keys=None):
        super().__init__(name, description, tag_keys)
        self.boundaries = sorted(boundaries) or [0.01, 0.1, 1, 10, 100]
        self._counts: dict[tuple, list[int]] = {}
        self._sums: dict[tuple, float] = defaultdict(float)

    def observe(self, value: float, tags: dict | None = None) -> None:
        key = self._key(tags)
        with self._lock:
            buckets = self._counts.setdefault(key, [0] * (len(self.boundaries) + 1))
            for i, b in enumerate(self.boundaries):
                if value <= b:
                    buckets[i] += 1
                    break
            else:
                buckets[-1] += 1
            self._sums[key] += value

    def snapshot(self) -> dict:
        with self._lock:
            return {k: {"buckets": list(v), "sum": self._sums[k]} for k, v in self._counts.items()}


def registry_snapshot() -> dict:
    """All metrics, for exposition (dashboard / prometheus text format)."""
    with _registry_lock:
        metrics = dict(_registry)
    return {name: m.snapshot() for name, m in metrics.items() if hasattr(m, "snapshot")}


def system_prometheus_text() -> str:
    """Runtime-internal gauges in Prometheus exposition format (reference:
    the metrics agent exports core counters — task/actor/object-store state —
    alongside user metrics, _private/metrics_agent.py)."""
    from ray_tpu.core.runtime import get_runtime_or_none

    rt = get_runtime_or_none()
    if rt is None or not hasattr(rt, "scheduler"):
        return ""
    lines = []

    def gauge(name, value, **tags):
        label = ",".join(f'{k}="{v}"' for k, v in tags.items())
        lines.append(f"ray_tpu_{name}{{{label}}} {value}" if label
                     else f"ray_tpu_{name} {value}")

    states: dict[str, int] = {}
    with rt._lock:
        for t in rt._tasks.values():
            states[t.state] = states.get(t.state, 0) + 1
        actors = list(rt._actors.values())
    for state, n in sorted(states.items()):
        gauge("tasks", n, state=state)
    actor_states: dict[str, int] = {}
    for a in actors:
        actor_states[a.state] = actor_states.get(a.state, 0) + 1
    for state, n in sorted(actor_states.items()):
        gauge("actors", n, state=state)
    gauge("nodes", len(rt.scheduler.nodes()))
    gauge("objects_in_memory_store", rt.memory_store.size())
    if rt.shm_store is not None:
        try:
            for k, v in rt.shm_store.stats().items():
                gauge(f"shm_{k}", v)
        except Exception:
            pass
    if rt.spill is not None:
        for k, v in rt.spill.stats().items():
            gauge(f"spill_{k}", v)
    pool = getattr(rt, "_proc_pool", None)
    if pool is not None:
        gauge("worker_processes_alive", pool.num_alive)
    return "\n".join(lines) + ("\n" if lines else "")


def prometheus_text() -> str:
    """Render the registry in Prometheus exposition format."""
    lines = []
    for name, values in registry_snapshot().items():
        safe = name.replace(".", "_").replace("-", "_")
        for key, val in values.items():
            tags = ",".join(f'{k}="{v}"' for k, v in key)
            label = f"{{{tags}}}" if tags else ""
            if isinstance(val, dict):  # histogram
                lines.append(f"{safe}_sum{label} {val['sum']}")
                lines.append(f"{safe}_count{label} {sum(val['buckets'])}")
            else:
                lines.append(f"{safe}{label} {val}")
    return "\n".join(lines) + "\n"
