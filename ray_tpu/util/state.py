"""State API: live introspection of tasks/actors/objects/nodes.

Parity: python/ray/util/state/ (list_actors api.py:793, list_tasks :1020,
summarize :1375+) and `ray timeline` (_private/state.py:1017 — Chrome trace
export of task events).
"""

from __future__ import annotations

from collections import Counter as _Counter
from typing import Optional

from ray_tpu.core.runtime import get_runtime


def list_tasks(filters: Optional[list] = None, limit: int = 1000) -> list[dict]:
    tasks = get_runtime().list_tasks()
    # newest entries win the cap (submission order is insertion order): a
    # head that has run >limit tasks must still surface CURRENT work
    return _apply_filters(tasks, filters)[-limit:]


def list_actors(filters: Optional[list] = None, limit: int = 1000) -> list[dict]:
    return _apply_filters(get_runtime().list_actors(), filters)[:limit]


def get_task(task_id: str) -> dict | None:
    """Single-task drill-down (reference: `ray get tasks <id>`)."""
    return get_runtime().task_detail(task_id)


def list_nodes(limit: int = 1000) -> list[dict]:
    rt = get_runtime()
    return [
        {
            "node_id": n.node_id.hex(),
            "alive": n.alive,
            "draining": n.draining,
            "resources_total": dict(n.total),
            "resources_available": dict(n.available),
            "labels": dict(n.labels),
            # latest heartbeat-reported physical stats (real node agents
            # only; logical in-process nodes have none)
            "stats": rt.node_stats.get(n.node_id),
        }
        for n in rt.scheduler.nodes()
    ][:limit]


def list_objects(limit: int = 1000) -> list[dict]:
    rt = get_runtime()
    out = []
    for oid, ref in rt.reference_counter.all_references().items():
        out.append(
            {
                "object_id": oid.hex(),
                "local_refs": ref.local_refs,
                "submitted_task_refs": ref.submitted_task_refs,
                "lineage_refs": ref.lineage_refs,
                "pinned": ref.pinned,
                "in_store": rt.memory_store.contains(oid),
            }
        )
        if len(out) >= limit:
            break
    return out


def list_placement_groups(limit: int = 1000) -> list[dict]:
    from ray_tpu.core.api import placement_group_table

    return placement_group_table()[:limit]


def flight_records(subsystem: Optional[str] = None,
                   limit: int = 1000) -> list[dict]:
    """Recent structured events from the flight recorder — local rings plus
    everything node agents/workers shipped with their metrics pushes (each
    remote event carries its origin ``node_id``). The "what happened in the
    last 30 seconds" dump (ISSUE 8)."""
    from ray_tpu.util import flight_recorder

    return flight_recorder.records(subsystem, limit)


def gang_view() -> list[dict]:
    """Live elastic gangs (train/elastic.py GangManager): phase, membership
    epoch, world size, last checkpoint step, member placement — the
    state-API face of the gang lifecycle (served at /api/v0/gang)."""
    from ray_tpu.train import elastic

    return elastic.gang_view()


def serve_view(limit: int = 64) -> dict:
    """Serve request anatomy (ISSUE 16): per-deployment SLO scoreboard
    (rolling TTFT/TPOT quantiles, goodput vs the declared
    ``slo_ttft_ms``, predicted TTFT per replica) plus the most recent
    per-request phase ledgers, head-clock aligned — the state-API face of
    ``serve/anatomy.py`` (served at /api/v0/serve)."""
    from ray_tpu.serve import anatomy

    return anatomy.serve_view(limit)


# per-metric previous sample for the HEAD's own rate estimation (remote
# nodes get rates from consecutive metrics_push deltas; the head has no
# pusher, so consecutive node_io_view() calls carry the baseline)
_local_rate_prev: dict[str, tuple] = {}


def _local_metric_total(name: str) -> float:
    from ray_tpu.util import metrics

    m = metrics.get_metric(name)
    if m is None or not hasattr(m, "snapshot"):
        return 0.0
    return sum(v for v in m.snapshot().values() if isinstance(v, (int, float)))


def _local_rate(name: str) -> float:
    import time as _t

    now = _t.monotonic()
    cur = _local_metric_total(name)
    prev = _local_rate_prev.get(name)
    _local_rate_prev[name] = (now, cur)
    if prev is None or now <= prev[0]:
        return 0.0
    return max(0.0, (cur - prev[1]) / (now - prev[0]))


def node_io_view() -> dict:
    """Per-node bandwidth / queue-depth view: the topology signal the
    striper, the scheduler, and the KV router consume (ROADMAP: "a refactor
    that surfaces a per-node bandwidth/queue-depth view in util/state.py
    unlocks the scheduler, the striper, and the KV router in one move").

    Sources: agent/worker ``metrics_push`` snapshots (pull-bandwidth =
    counter deltas between the last two pushes), heartbeat physical stats,
    and the head scheduler's queue depths. Returns::

        {"nodes": {node_hex | "head": {pull_bandwidth_bps, pull_bytes_total,
                                       pending_pull_bytes, holder_pending_bytes,
                                       reactor_queue_depth, sched_running_tasks,
                                       stats}},
         "sched_pending_tasks": int}
    """
    from ray_tpu.util import metrics

    rt = get_runtime()
    if not hasattr(rt, "scheduler"):
        # ClientRuntime (worker / remote driver): the aggregate lives at
        # the head — fail clearly instead of half-crashing mid-function
        raise RuntimeError(
            "node_io_view() is head-only: this process holds a client "
            "runtime; query the head's dashboard at /api/v0/node_io")
    sched = rt.scheduler_queue_depths()
    roll = metrics.node_io_rollup()  # one pass over the pushed snapshots
    pull_rates = roll["pull_rate"]
    pull_totals = roll["pull_total"]
    inflight = roll["inflight"]
    reactor = roll["reactor_depth"]
    holder_pending = roll["holder_pending"]

    def row(k: str) -> dict:
        return {
            "pull_bandwidth_bps": pull_rates.get(k, 0.0),
            "pull_bytes_total": pull_totals.get(k, 0.0),
            "pending_pull_bytes": inflight.get(k, 0.0),
            "holder_pending_bytes": dict(holder_pending.get(k, {})),
            "reactor_queue_depth": reactor.get(k, 0.0),
            "sched_running_tasks": sched["per_node"].get(k, 0),
            "stats": None,
        }

    nodes: dict[str, dict] = {}
    for n in rt.scheduler.nodes():
        if not n.alive:
            continue
        k = n.node_id.hex()
        nodes[k] = row(k)
        nodes[k]["stats"] = rt.node_stats.get(n.node_id)

    # the head process itself (plus any head-host workers, which push under
    # "head"): its own registry is local, not pushed — sample directly
    head = row("head")
    head["pull_bandwidth_bps"] += _local_rate("ray_tpu_plane_pull_bytes_total")
    head["pull_bytes_total"] += _local_metric_total(
        "ray_tpu_plane_pull_bytes_total")
    head["pending_pull_bytes"] += _local_metric_total(
        "ray_tpu_plane_pull_bytes_in_flight")
    head["reactor_queue_depth"] += _local_metric_total(
        "ray_tpu_rpc_reactor_queue_depth")
    hp = metrics.get_metric("ray_tpu_plane_holder_pending_bytes")
    if hp is not None:  # merge local over any head-host worker pushes
        for k, v in hp.snapshot().items():
            holder = dict(k).get("holder", "?")
            head["holder_pending_bytes"][holder] = (
                head["holder_pending_bytes"].get(holder, 0.0) + v)
    nodes["head"] = head
    return {"nodes": nodes, "sched_pending_tasks": sched["pending"]}


def summarize_tasks() -> dict:
    by_state = _Counter(t["state"] for t in get_runtime().list_tasks())
    by_name = _Counter(t["name"] for t in get_runtime().list_tasks())
    return {"by_state": dict(by_state), "by_name": dict(by_name.most_common(20))}


def summarize_actors() -> dict:
    by_state = _Counter(a["state"] for a in get_runtime().list_actors())
    by_class = _Counter(a["class_name"] for a in get_runtime().list_actors())
    return {"by_state": dict(by_state), "by_class": dict(by_class.most_common(20))}


def timeline(path: str | None = None) -> list[dict]:
    """ONE Chrome/Perfetto trace for the whole session (reference: ray
    timeline, grown cluster-wide): head-side task transitions (stable
    per-actor lanes; unpaired RUNNING surfaces as an open span instead of
    being dropped), worker task-phase windows shipped over metrics_push,
    tracing spans, sampled dag exec-loop steps, plane-pull windows,
    flight-recorder instants and gang transitions — cross-node clocks
    offset-aligned, submit->exec flow arrows per task. The merge lives in
    ``ray_tpu.util.timeline.export``."""
    from ray_tpu.util import timeline as tl

    return tl.export(path)


def _apply_filters(rows: list[dict], filters) -> list[dict]:
    if not filters:
        return rows
    for key, op, value in filters:
        if op == "=":
            rows = [r for r in rows if str(r.get(key)) == str(value)]
        elif op == "!=":
            rows = [r for r in rows if str(r.get(key)) != str(value)]
    return rows
