"""State API: live introspection of tasks/actors/objects/nodes.

Parity: python/ray/util/state/ (list_actors api.py:793, list_tasks :1020,
summarize :1375+) and `ray timeline` (_private/state.py:1017 — Chrome trace
export of task events).
"""

from __future__ import annotations

import json
from collections import Counter as _Counter
from typing import Optional

from ray_tpu.core.runtime import get_runtime, get_runtime_or_none


def list_tasks(filters: Optional[list] = None, limit: int = 1000) -> list[dict]:
    tasks = get_runtime().list_tasks()
    # newest entries win the cap (submission order is insertion order): a
    # head that has run >limit tasks must still surface CURRENT work
    return _apply_filters(tasks, filters)[-limit:]


def list_actors(filters: Optional[list] = None, limit: int = 1000) -> list[dict]:
    return _apply_filters(get_runtime().list_actors(), filters)[:limit]


def get_task(task_id: str) -> dict | None:
    """Single-task drill-down (reference: `ray get tasks <id>`)."""
    return get_runtime().task_detail(task_id)


def list_nodes(limit: int = 1000) -> list[dict]:
    rt = get_runtime()
    return [
        {
            "node_id": n.node_id.hex(),
            "alive": n.alive,
            "draining": n.draining,
            "resources_total": dict(n.total),
            "resources_available": dict(n.available),
            "labels": dict(n.labels),
            # latest heartbeat-reported physical stats (real node agents
            # only; logical in-process nodes have none)
            "stats": rt.node_stats.get(n.node_id),
        }
        for n in rt.scheduler.nodes()
    ][:limit]


def list_objects(limit: int = 1000) -> list[dict]:
    rt = get_runtime()
    out = []
    for oid, ref in rt.reference_counter.all_references().items():
        out.append(
            {
                "object_id": oid.hex(),
                "local_refs": ref.local_refs,
                "submitted_task_refs": ref.submitted_task_refs,
                "lineage_refs": ref.lineage_refs,
                "pinned": ref.pinned,
                "in_store": rt.memory_store.contains(oid),
            }
        )
        if len(out) >= limit:
            break
    return out


def list_placement_groups(limit: int = 1000) -> list[dict]:
    from ray_tpu.core.api import placement_group_table

    return placement_group_table()[:limit]


def summarize_tasks() -> dict:
    by_state = _Counter(t["state"] for t in get_runtime().list_tasks())
    by_name = _Counter(t["name"] for t in get_runtime().list_tasks())
    return {"by_state": dict(by_state), "by_name": dict(by_name.most_common(20))}


def summarize_actors() -> dict:
    by_state = _Counter(a["state"] for a in get_runtime().list_actors())
    by_class = _Counter(a["class_name"] for a in get_runtime().list_actors())
    return {"by_state": dict(by_state), "by_class": dict(by_class.most_common(20))}


def timeline(path: str | None = None) -> list[dict]:
    """Chrome-trace events from the task event buffer (reference: ray timeline)."""
    events = get_runtime().task_events()
    # pair RUNNING->terminal per task into complete events
    starts: dict[str, float] = {}
    trace: list[dict] = []
    for ev in events:
        tid = ev["task_id"]
        if ev["state"] == "RUNNING":
            starts[tid] = ev["ts"]
        elif ev["state"] in ("FINISHED", "FAILED", "CANCELLED") and tid in starts:
            t0 = starts.pop(tid)
            trace.append(
                {
                    "name": ev["name"],
                    "cat": "task",
                    "ph": "X",
                    "ts": int(t0 * 1e6),
                    "dur": int((ev["ts"] - t0) * 1e6),
                    "pid": 1,
                    "tid": abs(hash(ev.get("actor_id") or tid)) % 1000,
                    "args": {"state": ev["state"]},
                }
            )
    trace.extend(_worker_profile_events())
    if path:
        with open(path, "w") as f:
            json.dump(trace, f)
    return trace


def _worker_profile_events() -> list[dict]:
    """Worker-side execution windows from the export pipeline (when export
    events are on): one 'exec' lane per worker pid, so the timeline shows
    the dispatch-side span AND the worker's own wall-clock window
    (reference: ray timeline merging worker profile events)."""
    import glob
    import os

    from ray_tpu._private import export_events

    # Resolve the export dir from THIS session's runtime, not the module
    # global: export_events._DIR/_ENABLED are process-wide and re-written by
    # every init/shutdown in the process (suite runs cycle many sessions), so
    # the global can lag the session whose timeline is being asked for.
    profile_dir = None
    rt = get_runtime_or_none()
    session_dir = getattr(rt, "session_dir", None)
    if session_dir is not None:
        profile_dir = os.path.join(session_dir, "export_events")
    elif export_events.enabled() and export_events._DIR is not None:
        profile_dir = export_events._DIR
    if profile_dir is None:
        return []
    out: list[dict] = []
    try:
        for p in glob.glob(os.path.join(profile_dir,
                                        "export_task_profile*.jsonl")):
            with open(p) as f:
                for line in f:
                    try:
                        ev = json.loads(line)["event_data"]
                    except (ValueError, KeyError):
                        continue
                    out.append({
                        "name": f"exec:{(ev.get('task_id') or '?')[:12]}",
                        "cat": "worker_exec",
                        "ph": "X",
                        "ts": int(ev["exec_start"] * 1e6),
                        "dur": int((ev["exec_end"] - ev["exec_start"]) * 1e6),
                        "pid": 2,  # separate track group from head-side spans
                        "tid": ev.get("worker_pid") or 0,
                        "args": {"status": ev.get("status")},
                    })
    except OSError:
        pass
    return out


def _apply_filters(rows: list[dict], filters) -> list[dict]:
    if not filters:
        return rows
    for key, op, value in filters:
        if op == "=":
            rows = [r for r in rows if str(r.get(key)) == str(value)]
        elif op == "!=":
            rows = [r for r in rows if str(r.get(key)) != str(value)]
    return rows
