"""State API: live introspection of tasks/actors/objects/nodes.

Parity: python/ray/util/state/ (list_actors api.py:793, list_tasks :1020,
summarize :1375+) and `ray timeline` (_private/state.py:1017 — Chrome trace
export of task events).
"""

from __future__ import annotations

from collections import Counter as _Counter
from typing import Optional

from ray_tpu.core.runtime import get_runtime


def list_tasks(filters: Optional[list] = None, limit: int = 1000) -> list[dict]:
    tasks = get_runtime().list_tasks()
    # newest entries win the cap (submission order is insertion order): a
    # head that has run >limit tasks must still surface CURRENT work
    return _apply_filters(tasks, filters)[-limit:]


def list_actors(filters: Optional[list] = None, limit: int = 1000) -> list[dict]:
    return _apply_filters(get_runtime().list_actors(), filters)[:limit]


def get_task(task_id: str) -> dict | None:
    """Single-task drill-down (reference: `ray get tasks <id>`)."""
    return get_runtime().task_detail(task_id)


def list_nodes(limit: int = 1000) -> list[dict]:
    rt = get_runtime()
    return [
        {
            "node_id": n.node_id.hex(),
            "alive": n.alive,
            "draining": n.draining,
            "resources_total": dict(n.total),
            "resources_available": dict(n.available),
            "labels": dict(n.labels),
            # latest heartbeat-reported physical stats (real node agents
            # only; logical in-process nodes have none)
            "stats": rt.node_stats.get(n.node_id),
        }
        for n in rt.scheduler.nodes()
    ][:limit]


def list_objects(filters: Optional[list] = None,
                 limit: int = 1000) -> list[dict]:
    rt = get_runtime()
    # plane enrichment (memory anatomy): size/copies/locations per object
    # from the merged store reports + directory — best-effort, the ref
    # listing must keep working on a head with no plane at all
    try:
        from ray_tpu.core import mem_anatomy

        plane = mem_anatomy.object_plane_index()
    except Exception:
        plane = {}
    out = []
    for oid, ref in rt.reference_counter.all_references().items():
        oid_hex = oid.hex()
        p = plane.get(oid_hex)
        out.append(
            {
                "object_id": oid_hex,
                "local_refs": ref.local_refs,
                "submitted_task_refs": ref.submitted_task_refs,
                "lineage_refs": ref.lineage_refs,
                "pinned": ref.pinned,
                "in_store": rt.memory_store.contains(oid),
                "size_bytes": p["size"] if p else None,
                "plane_copies": p["copies"] if p else 0,
                "plane_nodes": p["nodes"] if p else [],
            }
        )
    # newest entries win the cap (ref registration order is insertion
    # order) — same contract as list_tasks: a session that has made >limit
    # objects must still surface CURRENT ones, not the oldest thousand
    return _apply_filters(out, filters)[-limit:]


def cluster_memory_view(limit: int = 1000) -> dict:
    """Cluster memory anatomy (ISSUE 18): per-object rows — size, copy
    count + nodes, pin state, ref state, creator task/actor and node, age —
    joined from the per-process store ledgers shipped on metrics_push,
    plus per-node store rollups and the sweeper's current leak suspects.
    Head-only (served at /api/v0/memory); the `ray_tpu memory` CLI face."""
    from ray_tpu.core import mem_anatomy

    return mem_anatomy.cluster_memory_view(limit)


def list_placement_groups(limit: int = 1000) -> list[dict]:
    from ray_tpu.core.api import placement_group_table

    return placement_group_table()[:limit]


def flight_records(subsystem: Optional[str] = None,
                   limit: int = 1000) -> list[dict]:
    """Recent structured events from the flight recorder — local rings plus
    everything node agents/workers shipped with their metrics pushes (each
    remote event carries its origin ``node_id``). The "what happened in the
    last 30 seconds" dump (ISSUE 8)."""
    from ray_tpu.util import flight_recorder

    return flight_recorder.records(subsystem, limit)


def gang_view() -> list[dict]:
    """Live elastic gangs (train/elastic.py GangManager): phase, membership
    epoch, world size, last checkpoint step, member placement — the
    state-API face of the gang lifecycle (served at /api/v0/gang)."""
    from ray_tpu.train import elastic

    return elastic.gang_view()


def serve_view(limit: int = 64) -> dict:
    """Serve request anatomy (ISSUE 16): per-deployment SLO scoreboard
    (rolling TTFT/TPOT quantiles, goodput vs the declared
    ``slo_ttft_ms``, predicted TTFT per replica) plus the most recent
    per-request phase ledgers, head-clock aligned — the state-API face of
    ``serve/anatomy.py`` (served at /api/v0/serve)."""
    from ray_tpu.serve import anatomy

    return anatomy.serve_view(limit)


# per-metric previous sample for the HEAD's own rate estimation (remote
# nodes get rates from consecutive metrics_push deltas; the head has no
# pusher, so consecutive node_io_view() calls carry the baseline)
_local_rate_prev: dict[str, tuple] = {}


def _local_metric_total(name: str) -> float:
    from ray_tpu.util import metrics

    m = metrics.get_metric(name)
    if m is None or not hasattr(m, "snapshot"):
        return 0.0
    return sum(v for v in m.snapshot().values() if isinstance(v, (int, float)))


def _local_rate(name: str) -> float:
    import time as _t

    now = _t.monotonic()
    cur = _local_metric_total(name)
    prev = _local_rate_prev.get(name)
    _local_rate_prev[name] = (now, cur)
    if prev is None or now <= prev[0]:
        return 0.0
    return max(0.0, (cur - prev[1]) / (now - prev[0]))


def node_io_view() -> dict:
    """Per-node bandwidth / queue-depth view: the topology signal the
    striper, the scheduler, and the KV router consume (ROADMAP: "a refactor
    that surfaces a per-node bandwidth/queue-depth view in util/state.py
    unlocks the scheduler, the striper, and the KV router in one move").

    Sources: agent/worker ``metrics_push`` snapshots (pull-bandwidth =
    counter deltas between the last two pushes), heartbeat physical stats,
    and the head scheduler's queue depths. Returns::

        {"nodes": {node_hex | "head": {pull_bandwidth_bps, pull_bytes_total,
                                       pending_pull_bytes, holder_pending_bytes,
                                       reactor_queue_depth, sched_running_tasks,
                                       stats}},
         "sched_pending_tasks": int}
    """
    from ray_tpu.util import metrics

    rt = get_runtime()
    if not hasattr(rt, "scheduler"):
        # ClientRuntime (worker / remote driver): the aggregate lives at
        # the head — fail clearly instead of half-crashing mid-function
        raise RuntimeError(
            "node_io_view() is head-only: this process holds a client "
            "runtime; query the head's dashboard at /api/v0/node_io")
    sched = rt.scheduler_queue_depths()
    roll = metrics.node_io_rollup()  # one pass over the pushed snapshots
    pull_rates = roll["pull_rate"]
    pull_totals = roll["pull_total"]
    inflight = roll["inflight"]
    reactor = roll["reactor_depth"]
    holder_pending = roll["holder_pending"]

    def row(k: str) -> dict:
        return {
            "pull_bandwidth_bps": pull_rates.get(k, 0.0),
            "pull_bytes_total": pull_totals.get(k, 0.0),
            "pending_pull_bytes": inflight.get(k, 0.0),
            "holder_pending_bytes": dict(holder_pending.get(k, {})),
            "reactor_queue_depth": reactor.get(k, 0.0),
            "sched_running_tasks": sched["per_node"].get(k, 0),
            "stats": None,
        }

    nodes: dict[str, dict] = {}
    for n in rt.scheduler.nodes():
        if not n.alive:
            continue
        k = n.node_id.hex()
        nodes[k] = row(k)
        nodes[k]["stats"] = rt.node_stats.get(n.node_id)

    # the head process itself (plus any head-host workers, which push under
    # "head"): its own registry is local, not pushed — sample directly
    head = row("head")
    head["pull_bandwidth_bps"] += _local_rate("ray_tpu_plane_pull_bytes_total")
    head["pull_bytes_total"] += _local_metric_total(
        "ray_tpu_plane_pull_bytes_total")
    head["pending_pull_bytes"] += _local_metric_total(
        "ray_tpu_plane_pull_bytes_in_flight")
    head["reactor_queue_depth"] += _local_metric_total(
        "ray_tpu_rpc_reactor_queue_depth")
    hp = metrics.get_metric("ray_tpu_plane_holder_pending_bytes")
    if hp is not None:  # merge local over any head-host worker pushes
        for k, v in hp.snapshot().items():
            holder = dict(k).get("holder", "?")
            head["holder_pending_bytes"][holder] = (
                head["holder_pending_bytes"].get(holder, 0.0) + v)
    nodes["head"] = head
    return {"nodes": nodes, "sched_pending_tasks": sched["pending"]}


def summarize_tasks() -> dict:
    by_state = _Counter(t["state"] for t in get_runtime().list_tasks())
    by_name = _Counter(t["name"] for t in get_runtime().list_tasks())
    return {"by_state": dict(by_state), "by_name": dict(by_name.most_common(20))}


def summarize_actors() -> dict:
    by_state = _Counter(a["state"] for a in get_runtime().list_actors())
    by_class = _Counter(a["class_name"] for a in get_runtime().list_actors())
    return {"by_state": dict(by_state), "by_class": dict(by_class.most_common(20))}


def timeline(path: str | None = None) -> list[dict]:
    """ONE Chrome/Perfetto trace for the whole session (reference: ray
    timeline, grown cluster-wide): head-side task transitions (stable
    per-actor lanes; unpaired RUNNING surfaces as an open span instead of
    being dropped), worker task-phase windows shipped over metrics_push,
    tracing spans, sampled dag exec-loop steps, plane-pull windows,
    flight-recorder instants and gang transitions — cross-node clocks
    offset-aligned, submit->exec flow arrows per task. The merge lives in
    ``ray_tpu.util.timeline.export``."""
    from ray_tpu.util import timeline as tl

    return tl.export(path)


def _num(v) -> "float | None":
    try:
        return float(v)
    except (TypeError, ValueError):
        return None


def _apply_filters(rows: list[dict], filters) -> list[dict]:
    """(key, op, value) predicate table over state rows. Ops: ``=`` / ``!=``
    (string equality), ``>`` / ``<`` (numeric — rows whose value doesn't
    coerce to a number are dropped, so `size_bytes > 1e6` never matches a
    None), ``contains`` (case-insensitive substring)."""
    if not filters:
        return rows
    for key, op, value in filters:
        if op == "=":
            rows = [r for r in rows if str(r.get(key)) == str(value)]
        elif op == "!=":
            rows = [r for r in rows if str(r.get(key)) != str(value)]
        elif op in (">", "<"):
            bound = _num(value)
            if bound is None:
                rows = []
                continue
            keep = []
            for r in rows:
                got = _num(r.get(key))
                if got is None:
                    continue
                if (got > bound) if op == ">" else (got < bound):
                    keep.append(r)
            rows = keep
        elif op == "contains":
            needle = str(value).lower()
            rows = [r for r in rows if needle in str(r.get(key)).lower()]
    return rows


def autoscaler_status_view() -> dict:
    """`ray status` parity for the CLI: pending resource shapes (queued
    tasks, pending placement-group bundles, standing demand), grouped,
    each marked ``waiting`` (some alive node could EVER hold the shape —
    it's a capacity queue) or ``infeasible`` (no alive node's TOTAL
    resources fit it — it will never schedule on the current cluster),
    with a human reason line. Mirrors the autoscaler's ``_feasible_now``
    capacity test, not instantaneous availability."""
    rt = get_runtime()
    from ray_tpu.autoscaler.autoscaler import standing_demand

    shapes: list[tuple[dict, str]] = []
    with rt._lock:
        for entry in rt._tasks.values():
            if entry.state == "PENDING" and entry.spec.resources:
                shapes.append((dict(entry.spec.resources), "task"))
    for pg in rt.scheduler.placement_groups():
        if pg.state == "PENDING":
            for b in pg.bundles:
                shapes.append((dict(b.resources), "placement_group"))
    standing = standing_demand()
    for s in standing:
        shapes.append((dict(s), "standing"))
    nodes = [n for n in rt.scheduler.nodes() if n.alive]
    grouped: dict[tuple, dict] = {}
    for shape, source in shapes:
        key = (tuple(sorted(shape.items())), source)
        g = grouped.get(key)
        if g is None:
            feasible = any(
                all(n.total.get(k, 0.0) >= v for k, v in shape.items())
                for n in nodes)
            if feasible:
                reason = "waiting for resources to free up"
            else:
                biggest = {}
                for k in shape:
                    biggest[k] = max(
                        (n.total.get(k, 0.0) for n in nodes), default=0.0)
                lacking = ", ".join(
                    f"{k}: need {shape[k]:g}, largest node has "
                    f"{biggest[k]:g}" for k in sorted(shape)
                    if biggest[k] < shape[k])
                reason = (f"infeasible on current nodes ({lacking})"
                          if lacking else "infeasible on current nodes")
            g = grouped[key] = {
                "shape": dict(shape), "source": source, "count": 0,
                "status": "waiting" if feasible else "infeasible",
                "reason": reason}
        g["count"] += 1
    return {"pending_shapes": sorted(
                grouped.values(),
                key=lambda g: (g["status"], g["source"])),
            "standing_demand": standing}
