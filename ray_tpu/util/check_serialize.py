"""Serializability inspection.

Parity: python/ray/util/check_serialize.py (inspect_serializability) — walks a
callable/object and reports which nested members fail cloudpickle, the standard
debugging tool for 'cannot pickle' task errors.
"""

from __future__ import annotations

from typing import Any, Optional

import cloudpickle


def _try_pickle(obj: Any) -> Optional[str]:
    try:
        cloudpickle.dumps(obj)
        return None
    except Exception as e:  # noqa: BLE001
        return f"{type(e).__name__}: {e}"


def inspect_serializability(obj: Any, name: str | None = None, depth: int = 3,
                            _prefix: str = "") -> tuple[bool, list[dict]]:
    """Returns (serializable, failures). Each failure: {path, error}."""
    label = _prefix + (name or getattr(obj, "__name__", type(obj).__name__))
    err = _try_pickle(obj)
    if err is None:
        return True, []
    failures = [{"path": label, "error": err}]
    if depth <= 0:
        return False, failures
    children: dict[str, Any] = {}
    closure = getattr(obj, "__closure__", None)
    if closure:
        names = obj.__code__.co_freevars
        for nm, cell in zip(names, closure):
            try:
                children[f"closure:{nm}"] = cell.cell_contents
            except ValueError:
                pass
    for attr in ("__self__", "__wrapped__", "__func__"):
        if hasattr(obj, attr):
            children[attr] = getattr(obj, attr)
    gd = getattr(obj, "__globals__", None)
    if gd and hasattr(obj, "__code__"):
        for nm in obj.__code__.co_names:
            if nm in gd:
                children[f"global:{nm}"] = gd[nm]
    if hasattr(obj, "__dict__") and not callable(obj):
        children.update({f"attr:{k}": v for k, v in vars(obj).items()})
    for child_name, child in children.items():
        if _try_pickle(child) is not None:
            ok, sub = inspect_serializability(
                child, child_name, depth - 1, _prefix=label + "."
            )
            failures.extend(sub)
    return False, failures
