"""Flight recorder: bounded per-subsystem rings of recent structured events.

The "what happened in the last 30 seconds" answer log-grepping can't give:
subsystems record rare-but-load-bearing events (pull failovers, channel
poisonings, actor deaths, retry exhaustions, version-negotiation fallbacks)
into small in-memory rings — one deque per subsystem, O(1) append, bounded
memory — and ``ray_tpu.util.state.flight_records()`` / the dashboard's
``/api/v0/flight_records`` dump them on demand or on fatal errors.

Reference analog: Ray's event framework (src/ray/util/event.h RayEvent ring
sinks + the dashboard event page) — here process-local, shipped to the head
piggybacked on the node agents' ``metrics_push``, so a multi-node session's
recent history is inspectable from one place.

Events are plain dicts (msgpack-native values only — they cross the wire):
``{"ts": wall_clock, "subsystem": ..., "event": ..., **fields}``.
Recording is always on: one deque.append under a small lock per RARE event
costs nothing measurable, and a recorder that must be switched on is never
on when the failure happens.
"""

from __future__ import annotations

import itertools
import threading
import time
from collections import deque
from typing import Optional

MAX_EVENTS_PER_SUBSYSTEM = 256

_lock = threading.Lock()
_rings: dict[str, deque] = {}
_seq = itertools.count(1)  # monotone id: the agents' ship-cursor


def record(subsystem: str, event: str, **fields) -> None:
    """Append one structured event. Values must be msgpack-native (str/int/
    float/bool/bytes/lists/dicts) — they ride the metrics_push wire op."""
    entry = {"seq": next(_seq), "ts": time.time(),
             "subsystem": subsystem, "event": event}
    entry.update(fields)
    with _lock:
        ring = _rings.get(subsystem)
        if ring is None:
            ring = _rings[subsystem] = deque(maxlen=MAX_EVENTS_PER_SUBSYSTEM)
        ring.append(entry)


def records(subsystem: Optional[str] = None, limit: int = 1000) -> list[dict]:
    """Recent events, oldest first — one subsystem's ring or all rings
    merged by sequence. ``limit`` caps the merge; <= 0 returns nothing
    (out[-limit:] with a non-positive limit would UNcap instead)."""
    if limit <= 0:
        return []
    with _lock:
        if subsystem is not None:
            out = list(_rings.get(subsystem, ()))
        else:
            out = [e for ring in _rings.values() for e in ring]
    out.sort(key=lambda e: e["seq"])
    return out[-limit:]


def subsystems() -> list[str]:
    with _lock:
        return sorted(_rings)


def drain_since(cursor: int) -> tuple[list[dict], int]:
    """Events newer than ``cursor`` plus the new cursor — the node agent's
    incremental ship loop (each event crosses the wire once)."""
    out = []
    with _lock:
        for ring in _rings.values():
            for e in ring:
                if e["seq"] > cursor:
                    out.append(e)
    out.sort(key=lambda e: e["seq"])
    new_cursor = out[-1]["seq"] if out else cursor
    return out, new_cursor


def ingest_remote(node_hex: str, events: list) -> None:
    """Head side: fold a node's shipped events into local rings, tagged with
    the origin node (remote seq is replaced — the head's cursor space is its
    own)."""
    for e in events:
        if not isinstance(e, dict):
            continue
        fields = {k: v for k, v in e.items()
                  if k not in ("seq", "subsystem", "event", "ts")}
        fields["node_id"] = node_hex
        fields["node_ts"] = e.get("ts")
        record(str(e.get("subsystem", "remote")),
               str(e.get("event", "unknown")), **fields)


def dump(file=None) -> None:
    """Human-readable dump of every ring — called on fatal errors so the
    crash report carries the recent-history context."""
    import sys

    out = file or sys.stderr
    evs = records()
    if not evs:
        return
    print(f"=== ray_tpu flight recorder ({len(evs)} recent events) ===",
          file=out)
    for e in evs:
        extra = {k: v for k, v in e.items()
                 if k not in ("seq", "ts", "subsystem", "event")}
        stamp = time.strftime("%H:%M:%S", time.localtime(e["ts"]))
        print(f"  {stamp} [{e['subsystem']}] {e['event']} {extra}", file=out)
    print("=== end flight recorder ===", file=out, flush=True)


def clear() -> None:
    with _lock:
        _rings.clear()
