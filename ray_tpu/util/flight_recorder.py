"""Flight recorder: bounded per-subsystem rings of recent structured events.

The "what happened in the last 30 seconds" answer log-grepping can't give:
subsystems record rare-but-load-bearing events (pull failovers, channel
poisonings, actor deaths, retry exhaustions, version-negotiation fallbacks)
into small in-memory rings — one deque per subsystem, O(1) append, bounded
memory — and ``ray_tpu.util.state.flight_records()`` / the dashboard's
``/api/v0/flight_records`` dump them on demand or on fatal errors.

Reference analog: Ray's event framework (src/ray/util/event.h RayEvent ring
sinks + the dashboard event page) — here process-local, shipped to the head
piggybacked on the node agents' ``metrics_push``, so a multi-node session's
recent history is inspectable from one place.

Events are plain dicts (msgpack-native values only — they cross the wire):
``{"ts": wall_clock, "subsystem": ..., "event": ..., **fields}``.
Recording is always on: one deque.append under a small lock per RARE event
costs nothing measurable, and a recorder that must be switched on is never
on when the failure happens.
"""

from __future__ import annotations

import itertools
import os
import threading
import time
from collections import deque
from typing import Optional

MAX_EVENTS_PER_SUBSYSTEM = 256

_lock = threading.Lock()
_rings: dict[str, deque] = {}
_seq = itertools.count(1)  # monotone id: the agents' ship-cursor


def record(subsystem: str, event: str, **fields) -> None:
    """Append one structured event. Values must be msgpack-native (str/int/
    float/bool/bytes/lists/dicts) — they ride the metrics_push wire op."""
    entry = {"seq": next(_seq), "ts": time.time(),
             "subsystem": subsystem, "event": event}
    entry.update(fields)
    with _lock:
        ring = _rings.get(subsystem)
        if ring is None:
            ring = _rings[subsystem] = deque(maxlen=MAX_EVENTS_PER_SUBSYSTEM)
        ring.append(entry)


def records(subsystem: Optional[str] = None, limit: int = 1000) -> list[dict]:
    """Recent events, oldest first — one subsystem's ring or all rings
    merged by sequence. ``limit`` caps the merge; <= 0 returns nothing
    (out[-limit:] with a non-positive limit would UNcap instead)."""
    if limit <= 0:
        return []
    with _lock:
        if subsystem is not None:
            out = list(_rings.get(subsystem, ()))
        else:
            out = [e for ring in _rings.values() for e in ring]
    out.sort(key=lambda e: e["seq"])
    return out[-limit:]


def subsystems() -> list[str]:
    with _lock:
        return sorted(_rings)


def drain_since(cursor: int) -> tuple[list[dict], int]:
    """Events newer than ``cursor`` plus the new cursor — the node agent's
    incremental ship loop (each event crosses the wire once)."""
    out = []
    with _lock:
        for ring in _rings.values():
            for e in ring:
                if e["seq"] > cursor:
                    out.append(e)
    out.sort(key=lambda e: e["seq"])
    new_cursor = out[-1]["seq"] if out else cursor
    return out, new_cursor


def ingest_remote(node_hex: str, events: list) -> None:
    """Head side: fold a node's shipped events into local rings, tagged with
    the origin node (remote seq is replaced — the head's cursor space is its
    own)."""
    for e in events:
        if not isinstance(e, dict):
            continue
        fields = {k: v for k, v in e.items()
                  if k not in ("seq", "subsystem", "event", "ts")}
        fields["node_id"] = node_hex
        fields["node_ts"] = e.get("ts")
        record(str(e.get("subsystem", "remote")),
               str(e.get("event", "unknown")), **fields)


# ------------------------------------------------------------ crash dumps
# atexit + fatal-signal hook (ISSUE 13 satellite): the head dumps every
# ring to ``session_dir/flight_dump.json`` on the way down, so the "what
# happened in the last 30 seconds" answer survives head death and is
# available to post-mortems that never got to call the state API.
_dump_path: "str | None" = None
_prev_handlers: dict = {}


def dump_json(path: "str | None" = None) -> "str | None":
    """Write every ring as JSON to ``path`` (default: the installed crash-
    dump path). Atomic tmp+rename; returns the path or None (no path / IO
    error — dumping must never raise on a dying process)."""
    p = path or _dump_path
    if p is None:
        return None
    try:
        payload = {"ts": time.time(), "pid": os.getpid(),
                   "events": records(limit=100000)}
        import json

        os.makedirs(os.path.dirname(p) or ".", exist_ok=True)
        tmp = p + ".tmp"
        with open(tmp, "w") as f:
            json.dump(payload, f)
        os.replace(tmp, p)
        return p
    except Exception:
        return None


def _on_fatal_signal(signum, frame) -> None:
    import signal as _signal

    dump_json()
    prev = _prev_handlers.get(signum)
    if callable(prev):
        prev(signum, frame)
        return
    if prev is _signal.SIG_IGN:
        return
    # default disposition: restore and re-deliver so exit semantics
    # (exit code, core, parent's waitpid status) stay untouched
    _signal.signal(signum, _signal.SIG_DFL)
    os.kill(os.getpid(), signum)


def install_crash_dump(session_dir: str) -> "str | None":
    """Arm the crash dump: atexit hook always; SIGTERM chain-hook when this
    is the main thread (the orchestrator/systemd/GCE-reclaim kill signal).
    Returns the dump path. Idempotent per path; ``uninstall_crash_dump``
    restores the previous handlers."""
    global _dump_path
    import atexit
    import signal as _signal

    _dump_path = os.path.join(session_dir, "flight_dump.json")
    atexit.register(dump_json)
    if threading.current_thread() is threading.main_thread():
        for sig in (_signal.SIGTERM,):
            try:
                prev = _signal.getsignal(sig)
                if prev is _on_fatal_signal:
                    continue
                _prev_handlers[sig] = prev
                _signal.signal(sig, _on_fatal_signal)
            except (ValueError, OSError):
                pass
    return _dump_path


def uninstall_crash_dump(final_dump: bool = True) -> None:
    """Disarm (runtime shutdown): writes one final dump by default — an
    orderly shutdown leaves the same post-mortem artifact a crash would —
    then restores chained handlers so suite-cycled sessions don't stack."""
    global _dump_path
    import atexit
    import signal as _signal

    if final_dump:
        dump_json()
    for sig, prev in list(_prev_handlers.items()):
        try:
            if _signal.getsignal(sig) is _on_fatal_signal:
                _signal.signal(sig, prev)
        except (ValueError, OSError, TypeError):
            pass
    _prev_handlers.clear()
    try:
        atexit.unregister(dump_json)
    except Exception:
        pass
    _dump_path = None


def dump(file=None) -> None:
    """Human-readable dump of every ring — called on fatal errors so the
    crash report carries the recent-history context."""
    import sys

    out = file or sys.stderr
    evs = records()
    if not evs:
        return
    print(f"=== ray_tpu flight recorder ({len(evs)} recent events) ===",
          file=out)
    for e in evs:
        extra = {k: v for k, v in e.items()
                 if k not in ("seq", "ts", "subsystem", "event")}
        stamp = time.strftime("%H:%M:%S", time.localtime(e["ts"]))
        print(f"  {stamp} [{e['subsystem']}] {e['event']} {extra}", file=out)
    print("=== end flight recorder ===", file=out, flush=True)


def clear() -> None:
    with _lock:
        _rings.clear()
