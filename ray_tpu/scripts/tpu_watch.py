"""Persistent TPU tunnel watcher + perf-sweep orchestrator (dev tool).

The tunnel in this environment admits one client at a time and can wedge for
hours after a killed client (rounds 2 and 3 both lost their live bench to it).
This watcher turns tunnel acquisition into a background job for the whole
round: probe cheaply from short-lived subprocesses, and the moment the tunnel
admits, run the `tpu_sweep` configs one per process in priority order,
appending raw results to SWEEP_r04.jsonl, regenerating SWEEP_r04.md, and
refreshing BENCH_CACHE.json whenever a config beats the cached number.

Resume-safe: configs already present in the JSONL are skipped, so the watcher
can be restarted at any time. Exits 0 when every planned config has a result
(or a recorded permanent failure, e.g. OOM).

Usage: nohup python -m ray_tpu.scripts.tpu_watch &   (or a background shell)
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
JSONL = os.path.join(REPO, "SWEEP_r05.jsonl")
MD = os.path.join(REPO, "SWEEP_r05.md")
CACHE = os.path.join(REPO, "BENCH_CACHE.json")

# (plan key, tpu_sweep config letter, extra env). Priority order: most likely
# winners first so a short tunnel window still improves the headline number.
PLAN = [
    ("D", "D", {}),                 # hidden 2048 x 12L, dots remat, bs8
    ("N", "N", {}),                 # same model, bs16
    ("I", "I", {}),                 # hidden 2048 x 16L (~886M), bs8
    ("J", "J", {}),                 # same, bs16
    ("L", "L", {}),                 # hidden 4096 x 6L (~1.3B), bs8
    ("D_fb256", "D", {"RAY_TPU_FLASH_BLOCK_Q": "256", "RAY_TPU_FLASH_BLOCK_K": "256"}),
    ("D_fb512k", "D", {"RAY_TPU_FLASH_BLOCK_Q": "256", "RAY_TPU_FLASH_BLOCK_K": "512"}),
    ("M", "M", {}),                 # huge, full remat
    ("E", "E", {}),                 # big, full remat, bs16
    ("K", "K", {}),                 # big16, full remat, bs16
    ("C", "C", {}),                 # round-2 family, bs16
    ("O", "O", {}),                 # big16 no-remat
    ("B", "B", {}),                 # round-2 winner re-measured (control)
]

PROBE_TIMEOUT = 150.0
SWEEP_TIMEOUT = 1500.0
IDLE_SLEEP = 240.0
V5E_PEAK = 197e12
TARGET_MFU = 0.40  # bench.py's vs_baseline denominator


def log(msg: str) -> None:
    sys.stderr.write(f"[tpu_watch {time.strftime('%H:%M:%S')}] {msg}\n")
    sys.stderr.flush()


def probe() -> bool:
    try:
        r = subprocess.run(
            [sys.executable, "-c", "import jax; print(jax.devices()[0].platform)"],
            capture_output=True, text=True, timeout=PROBE_TIMEOUT,
            env=dict(os.environ), cwd=REPO)
        lines = (r.stdout or "").strip().splitlines()
        plat = lines[-1] if lines else ""
        return r.returncode == 0 and plat not in ("", "cpu")
    except subprocess.TimeoutExpired:
        return False


def done_keys() -> dict:
    out = {}
    try:
        with open(JSONL) as f:
            for line in f:
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue
                out[rec.get("plan_key")] = rec
    except OSError:
        pass
    return out


def append(rec: dict) -> None:
    with open(JSONL, "a") as f:
        f.write(json.dumps(rec) + "\n")


def regen_md() -> None:
    recs = list(done_keys().values())
    ok = [r for r in recs if "tokens_per_sec" in r]
    ok.sort(key=lambda r: -r["tokens_per_sec"])
    lines = [
        "# TPU perf sweep — round 4 (live, one config per process)",
        "",
        "| plan | config | flash bq/bk | params (M) | tokens/s/chip | MFU (6N) | vs 40%-MFU bar |",
        "|---|---|---|---|---|---|---|",
    ]
    for r in ok:
        lines.append(
            f"| {r['plan_key']} | {r.get('config','?')} | {r.get('flash_bq','128')}/"
            f"{r.get('flash_bk','128')} | {r.get('params_m','?')} | "
            f"{r['tokens_per_sec']:.1f} | {r.get('mfu_6n', 0):.4f} | "
            f"{r.get('mfu_6n', 0)/TARGET_MFU:.4f} |")
    bad = [r for r in recs if "tokens_per_sec" not in r]
    if bad:
        lines += ["", "Failed configs:", ""]
        for r in bad:
            lines.append(f"- `{r['plan_key']}`: {r.get('error', 'unknown')}")
    lines += ["", f"_Regenerated {time.strftime('%Y-%m-%dT%H:%M:%S')} by "
              "`ray_tpu/scripts/tpu_watch.py`; raw lines in `SWEEP_r04.jsonl`._"]
    with open(MD, "w") as f:
        f.write("\n".join(lines) + "\n")


def update_cache(rec: dict) -> None:
    vs = rec["mfu_6n"] / TARGET_MFU
    try:
        with open(CACHE) as f:
            cur = json.load(f)
        if cur.get("vs_baseline", 0) >= vs:
            return
    except (OSError, ValueError):
        pass
    commit = ""
    try:
        commit = subprocess.run(["git", "rev-parse", "--short", "HEAD"],
                                capture_output=True, text=True, timeout=10,
                                cwd=REPO).stdout.strip()
    except Exception:
        pass
    with open(CACHE, "w") as f:
        json.dump({
            "metric": "train_tokens_per_sec_per_chip_tpu",
            "value": round(rec["tokens_per_sec"], 2),
            "unit": "tokens/s/chip",
            "vs_baseline": round(vs, 4),
            "measured_at": time.strftime("%Y-%m-%dT%H:%M:%S"),
            "git_commit": commit,
            "note": f"tpu_sweep r4 plan {rec['plan_key']} (config {rec.get('config')}, "
                    f"flash {rec.get('flash_bq')}/{rec.get('flash_bk')})",
        }, f)
    log(f"BENCH_CACHE updated: {rec['tokens_per_sec']:.1f} tok/s "
        f"(vs_baseline {vs:.4f}) from {rec['plan_key']}")


def run_config(plan_key: str, letter: str, extra_env: dict) -> bool:
    """Run one sweep config; returns True if the tunnel still looks usable."""
    env = dict(os.environ)
    env.update(extra_env)
    log(f"running {plan_key} (config {letter}, env {extra_env or '{}'})")
    try:
        r = subprocess.run(
            [sys.executable, "-m", "ray_tpu.scripts.tpu_sweep", letter],
            capture_output=True, text=True, timeout=SWEEP_TIMEOUT, env=env,
            cwd=REPO)
    except subprocess.TimeoutExpired:
        log(f"{plan_key}: TIMED OUT after {SWEEP_TIMEOUT}s — tunnel likely wedged")
        return False
    out = (r.stdout or "").strip().splitlines()
    rec = None
    for line in out:
        try:
            cand = json.loads(line)
            if "tokens_per_sec" in cand:
                rec = cand
        except ValueError:
            continue
    if rec is not None:
        rec["plan_key"] = plan_key
        rec["measured_at"] = time.strftime("%Y-%m-%dT%H:%M:%S")
        append(rec)
        update_cache(rec)
        regen_md()
        log(f"{plan_key}: {rec['tokens_per_sec']:.1f} tok/s (mfu {rec['mfu_6n']:.4f})")
        return True
    err = (r.stderr or "").strip().splitlines()
    tail = " | ".join(err[-3:]) if err else f"rc={r.returncode}, no output"
    if "RESOURCE_EXHAUSTED" in (r.stderr or "") or "out of memory" in (r.stderr or "").lower():
        # Permanent for this chip: record so we don't retry forever.
        append({"plan_key": plan_key, "error": f"OOM: {tail[-300:]}",
                "measured_at": time.strftime("%Y-%m-%dT%H:%M:%S")})
        regen_md()
        log(f"{plan_key}: OOM (recorded, skipping)")
        return True
    log(f"{plan_key}: failed rc={r.returncode}: {tail[-300:]}")
    # Transient (tunnel dropped mid-run etc.) — leave unrecorded for retry.
    return False


def main() -> int:
    log(f"watcher started, plan={len(PLAN)} configs, pid={os.getpid()}")
    while True:
        remaining = [p for p in PLAN if p[0] not in done_keys()]
        if not remaining:
            log("plan complete")
            regen_md()
            return 0
        if not probe():
            log(f"tunnel unavailable ({len(remaining)} configs remaining); "
                f"sleeping {IDLE_SLEEP:.0f}s")
            time.sleep(IDLE_SLEEP)
            continue
        log(f"tunnel ADMITTED — {len(remaining)} configs to go")
        for plan_key, letter, extra_env in remaining:
            if not run_config(plan_key, letter, extra_env):
                break  # re-probe before burning more configs


if __name__ == "__main__":
    raise SystemExit(main())
