"""Scale-envelope bench: nodes / actors / queued tasks / placement groups.

Mirrors the reference's scalability envelope
(/root/reference/release/benchmarks/README.md:11-14 — 2,000 nodes, 40K
actors, 10K running tasks, 1K placement groups, 1M queued tasks on one node)
at single-host scale, and its distributed_test.py measurement shape. Each
section prints a JSON line; `python -m ray_tpu.scripts.scale_bench` writes
the markdown table the round report embeds (SCALE_r04.md).

Run on a quiet machine: the numbers are a capacity envelope (does it work,
where's the knee), not a latency benchmark.
"""

from __future__ import annotations

import argparse
import json
import resource
import time

import ray_tpu
from ray_tpu.core.runtime import get_runtime


def _rss_mb() -> float:
    return resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1024.0


def _rss_now_mb() -> float:
    """CURRENT resident set (not ru_maxrss, which is a lifetime peak — a
    before/after delta off the peak reads ~0 for every arm after the first
    regardless of what it actually allocated)."""
    try:
        with open("/proc/self/status") as f:
            for line in f:
                if line.startswith("VmRSS:"):
                    return int(line.split()[1]) / 1024.0
    except OSError:
        pass
    return _rss_mb()


def bench_nodes(n: int, real: int) -> list[dict]:
    """n logical (in-process) nodes + `real` OS-process node agents: register
    them all, then prove SPREAD scheduling lands tasks on every node."""
    from ray_tpu.cluster_utils import Cluster

    out = []
    cluster = Cluster()
    t0 = time.perf_counter()
    for _ in range(n):
        cluster.add_node(num_cpus=4)
    dt = time.perf_counter() - t0
    out.append({"metric": "logical_nodes_registered", "n": n,
                "rate_per_s": round(n / dt, 1), "secs": round(dt, 3)})

    if real:
        t0 = time.perf_counter()
        ok = 0
        for _ in range(real):
            try:
                cluster.add_node(num_cpus=1, real_process=True, timeout=120.0)
                ok += 1
            except (RuntimeError, TimeoutError) as e:
                out.append({"metric": "real_agent_register_failed_at", "n": ok,
                            "error": str(e)[:120]})
                break
        dt = time.perf_counter() - t0
        out.append({"metric": "real_node_agents_registered", "n": ok,
                    "rate_per_s": round(ok / max(dt, 1e-9), 2),
                    "secs": round(dt, 2)})

    # prove the scheduler spreads across the enlarged cluster (placement is
    # attributed head-side via the task-state API, like the reference's
    # `ray list tasks` node_id column)
    @ray_tpu.remote(scheduling_strategy="SPREAD", num_cpus=0.5)
    def spread_probe():
        return 0

    t0 = time.perf_counter()
    total_nodes = len(get_runtime().scheduler.nodes())
    refs = [spread_probe.remote() for _ in range(min(4 * total_nodes, 800))]
    ray_tpu.get(refs, timeout=600)
    dt = time.perf_counter() - t0
    from ray_tpu.util import state

    used = {t["node_id"] for t in state.list_tasks(limit=10_000)
            if t["name"].startswith("spread_probe") and t["node_id"]}
    out.append({"metric": "spread_nodes_used", "n": len(used),
                "total_nodes": total_nodes, "tasks": len(refs),
                "secs": round(dt, 2)})
    return out


def bench_actors(n: int) -> list[dict]:
    """n live in-head actors: creation rate, one ping through every mailbox."""

    @ray_tpu.remote(num_cpus=0.001)
    class Cell:
        def __init__(self, i):
            self.i = i

        def ping(self):
            return self.i

    t0 = time.perf_counter()
    actors = [Cell.remote(i) for i in range(n)]
    create_dt = time.perf_counter() - t0
    t0 = time.perf_counter()
    got = ray_tpu.get([a.ping.remote() for a in actors], timeout=600)
    ping_dt = time.perf_counter() - t0
    assert got == list(range(n))
    out = [{"metric": "live_actors", "n": n,
            "create_rate_per_s": round(n / create_dt, 1),
            "ping_all_rate_per_s": round(n / ping_dt, 1),
            "rss_mb": round(_rss_mb(), 1)}]
    for a in actors:
        ray_tpu.kill(a)
    return out


def bench_queued_tasks(n: int) -> list[dict]:
    """n tasks queued behind a tiny resource budget: submission rate with the
    queue deep, then drain throughput once capacity opens."""

    @ray_tpu.remote(num_cpus=4, resources={"gate": 1})
    def nop():
        return 0

    # every task needs the 'gate' resource; none exists yet -> all queue
    t0 = time.perf_counter()
    refs = [nop.remote() for _ in range(n)]
    submit_dt = time.perf_counter() - t0
    depth_rss = _rss_mb()

    # open the gate: one node with gate:4 drains 4-wide
    rt = get_runtime()
    rt.scheduler.add_node({"CPU": 16.0, "gate": 4.0})
    rt.scheduler.retry_pending_pgs()
    t0 = time.perf_counter()
    ray_tpu.get(refs, timeout=3600)
    drain_dt = time.perf_counter() - t0
    return [{"metric": "queued_tasks", "n": n,
             "submit_rate_per_s": round(n / submit_dt, 1),
             "queue_depth_rss_mb": round(depth_rss, 1),
             "drain_rate_per_s": round(n / drain_dt, 1)}]


def bench_dispatch(n_agents: int, tasks_per_agent: int = 20) -> list[dict]:
    """Steady-state dispatch throughput to REAL node agents (the round-4
    knee: 5.6 tasks/s at 50 agents with the synchronous per-task round-trip;
    the pushed lease-reuse path pipelines frames down each agent's standing
    connection). Warms every agent's pool first so the number measures
    dispatch, not process spawn."""
    from ray_tpu.cluster_utils import Cluster

    out = []
    cluster = Cluster()
    t0 = time.perf_counter()
    # A dedicated resource pins the probe tasks to THESE agents: without it,
    # SPREAD lets logical nodes / the head absorb tasks and the number stops
    # measuring the pushed agent path.
    nids = [cluster.add_node(num_cpus=1, real_process=True,
                             resources={"dispatchbench": 1})
            for _ in range(n_agents)]
    reg_dt = time.perf_counter() - t0
    out.append({"metric": "dispatch_agents_registered", "n": len(nids),
                "secs": round(reg_dt, 2)})

    @ray_tpu.remote(scheduling_strategy="SPREAD", num_cpus=1,
                    resources={"dispatchbench": 1})
    def nop():
        return 0

    # warm: boot every agent's worker pool (one task each, pinned by SPREAD)
    ray_tpu.get([nop.remote() for _ in range(n_agents)], timeout=900)
    # measure: many in-flight pushed dispatches across all agents
    n_tasks = n_agents * tasks_per_agent
    t0 = time.perf_counter()
    refs = [nop.remote() for _ in range(n_tasks)]
    submit_dt = time.perf_counter() - t0
    ray_tpu.get(refs, timeout=1800)
    total_dt = time.perf_counter() - t0
    out.append({
        "metric": "agent_dispatch",
        "agents": n_agents,
        "tasks": n_tasks,
        "submit_rate_per_s": round(n_tasks / max(submit_dt, 1e-9), 1),
        "dispatch_rate_per_s": round(n_tasks / max(total_dt, 1e-9), 1),
        "secs": round(total_dt, 2),
    })
    return out


def bench_broadcast(n_agents: int, mb: int = 64) -> list[dict]:
    """One large driver object consumed on every node (reference envelope:
    1 GiB broadcast to 50+ nodes, release/benchmarks/README.md:20 — scaled
    to this box). Consumers resolve the arg through the object plane; a node
    that pulled seeds its local store and announces the copy, so later
    pullers can fetch from ANY holder, not just the head."""
    import numpy as np

    from ray_tpu.cluster_utils import Cluster

    out = []
    cluster = Cluster()
    for _ in range(n_agents):
        cluster.add_node(num_cpus=1, real_process=True, isolated_plane=True,
                         resources={"bcast": 1})

    blob = np.random.default_rng(0).integers(
        0, 255, size=mb * 1024 * 1024, dtype=np.uint8)
    ref = ray_tpu.put(blob)

    @ray_tpu.remote(num_cpus=1, resources={"bcast": 1},
                    scheduling_strategy="SPREAD")
    def consume(x):
        return int(x.nbytes)

    t0 = time.perf_counter()
    sizes = ray_tpu.get([consume.remote(ref) for _ in range(n_agents)],
                        timeout=1800)
    dt = time.perf_counter() - t0
    assert all(s == mb * 1024 * 1024 for s in sizes)
    out.append({
        "metric": "object_broadcast",
        "agents": n_agents,
        "object_mb": mb,
        "total_moved_mb": mb * n_agents,
        "secs": round(dt, 2),
        "agg_bandwidth_mb_s": round(mb * n_agents / max(dt, 1e-9), 1),
    })
    return out


def _data_gen_block(i, rows):
    import numpy as np

    import ray_tpu as rt
    from ray_tpu.data.block import Block

    b = Block({"k": np.arange(rows, dtype=np.int64) % 8,
               "v": np.full(rows, i, dtype=np.int64)})
    return [rt.put(b), b.num_rows(), b.size_bytes()]


def _data_consume_block(b):
    return int(b.columns["v"].sum())


def bench_data_ingest(block_mb: int = 16, blocks: int = 16,
                      agents: int = 2,
                      parallelisms: tuple = (2, 4, 8)) -> list[dict]:
    """Streaming data plane sweep (ISSUE-12): end-to-end MB/s of the
    ingestion shape — a FLEET-RESIDENT dataset (generated by agent tasks,
    sealed into agent-local stores) shuffled and consumed by agent tasks —
    interleaved A/B per parallelism between the plane-native exchange
    (blocks move holder→consumer as sealed plane entries; the driver
    carries descriptors) and the driver-get path (every upstream block
    materialized at the driver and re-shipped to the mappers — the seed's
    executor boundary). Reports MB/s, the driver-transit byte counter (the
    plane arm must stay at 0), and driver RSS delta."""
    import numpy as np  # noqa: F401 (worker fns import their own)

    from ray_tpu.cluster_utils import Cluster
    from ray_tpu.data.block import Block
    from ray_tpu.data.exchange import exchange, exchange_refs, hash_partitioner
    from ray_tpu.data.streaming import BlockRef, materialize
    from ray_tpu.util.metrics import get_metric

    cluster = Cluster()
    for _ in range(agents):
        cluster.add_node(num_cpus=4, real_process=True, isolated_plane=True,
                         resources={"datafleet": 4}, timeout=120)
    gen = ray_tpu.remote(num_cpus=1, resources={"datafleet": 1},
                         name="data::gen")(_data_gen_block)
    consume = ray_tpu.remote(num_cpus=1, resources={"datafleet": 1},
                             name="data::consume")(_data_consume_block)
    # each block carries TWO int64 columns (k, v) — divide by 16 so a
    # "block_mb" block really is block_mb; total derives from the sealed
    # descriptors' true byte counts, not the label
    rows = block_mb * (1 << 20) // 16

    def make_source():
        metas = ray_tpu.get([gen.remote(i, rows) for i in range(blocks)],
                            timeout=600)
        return [BlockRef(r, nr, nb) for r, nr, nb in metas]

    def driver_bytes() -> float:
        ctr = get_metric("ray_tpu_data_driver_block_bytes_total")
        return sum(ctr.snapshot().values()) if ctr else 0.0

    out = []
    for par in parallelisms:
        # arm selection is explicit (exchange_refs vs materialize+exchange);
        # the RAY_TPU_DATA_PLANE_STREAMING engine switch only affects
        # Dataset executions and is deliberately left alone here
        for arm in ("driver_get", "plane"):
            descs = make_source()
            total_mb = sum(d.size_bytes for d in descs) / (1 << 20)
            rss0, dbytes0 = _rss_now_mb(), driver_bytes()
            t0 = time.perf_counter()
            refs = []
            if arm == "plane":
                for d in exchange_refs(iter(descs),
                                       hash_partitioner("k", par), par,
                                       lambda bs: Block.concat(bs),
                                       ordered=False):
                    refs.append(consume.remote(d.ref))
            else:
                for b in exchange(materialize(iter(descs)),
                                  hash_partitioner("k", par), par,
                                  lambda bs: Block.concat(bs),
                                  ordered=False):
                    refs.append(consume.remote(b))
            total = sum(ray_tpu.get(refs, timeout=600))
            dt = time.perf_counter() - t0
            assert total == sum(i * rows for i in range(blocks))
            out.append({
                "metric": "data_ingest_shuffle", "arm": arm,
                "parallelism": par, "total_mb": round(total_mb, 1),
                "mb_per_s": round(total_mb / max(dt, 1e-9), 1),
                "secs": round(dt, 2),
                "driver_transit_mb": round(
                    (driver_bytes() - dbytes0) / (1 << 20), 1),
                "driver_rss_delta_mb": round(_rss_now_mb() - rss0, 1),
            })
    return out


class _ChainStage:
    """One pipeline stage for the cross-node compiled-chain sweep."""

    def __init__(self, idx: int):
        self.idx = idx

    def step(self, x):
        return x + 1


def bench_cross_node_chain(max_stages: int = 4, steps: int = 200) -> list[dict]:
    """Compiled-chain steps/s vs stage count with stages spread over 2 REAL
    isolated-plane agents (ISSUE-15): the cross-node fabric's throughput
    curve, with the same chain per-call as the in-row baseline."""
    from ray_tpu.cluster_utils import Cluster
    from ray_tpu.dag import InputNode

    cluster = Cluster(initialize_head=False)
    node_res = [{"xa": 100}, {"xb": 100}]
    for res in node_res:
        cluster.add_node(num_cpus=max_stages, resources=res,
                         real_process=True, isolated_plane=True)
    rows = []
    try:
        for n_stages in range(2, max_stages + 1):
            actors = []
            for i in range(n_stages):
                res_key = "xa" if i % 2 == 0 else "xb"  # alternate agents
                cls = ray_tpu.remote(isolate_process=True, num_cpus=0.5,
                                     resources={res_key: 1})(_ChainStage)
                actors.append(cls.remote(i))
            with InputNode() as inp:
                node = inp
                for a in actors:
                    node = a.step.bind(node)
            dag = node.experimental_compile()
            for w in range(3):
                assert dag.execute(w).get(timeout=60) == w + n_stages
            t0 = time.perf_counter()
            refs = [dag.execute(i) for i in range(steps)]
            out = [r.get(timeout=120) for r in refs]
            dt = time.perf_counter() - t0
            assert out[-1] == steps - 1 + n_stages
            dag.teardown()
            # per-call baseline: the same chain, one actor submit per stage
            t0 = time.perf_counter()
            per_call_steps = max(10, steps // 10)
            for i in range(per_call_steps):
                x = i
                for a in actors:
                    x = ray_tpu.get(a.step.remote(x), timeout=60)
            dt_pc = time.perf_counter() - t0
            rows.append({
                "metric": f"cross_node_chain_{n_stages}stage",
                "compiled_steps_per_s": round(steps / dt, 1),
                "per_call_steps_per_s": round(per_call_steps / dt_pc, 1),
                "speedup": round((steps / dt) / (per_call_steps / dt_pc), 2),
            })
            for a in actors:
                ray_tpu.kill(a)
    finally:
        for nid in list(cluster.node_ids):
            try:
                cluster.remove_node(nid)
            except Exception:
                pass
    return rows


def bench_placement_groups(n: int) -> list[dict]:
    """n simultaneous 1-bundle PGs on a cluster with room for all of them."""
    rt = get_runtime()
    for _ in range(max(0, n // 100)):
        rt.scheduler.add_node({"CPU": 128.0})
    t0 = time.perf_counter()
    pgs = [ray_tpu.placement_group([{"CPU": 1}]) for _ in range(n)]
    for pg in pgs:
        pg.wait(timeout_seconds=600)
    dt = time.perf_counter() - t0
    out = [{"metric": "simultaneous_pgs", "n": n,
            "create_ready_rate_per_s": round(n / dt, 1),
            "secs": round(dt, 2)}]
    t0 = time.perf_counter()
    for pg in pgs:
        ray_tpu.remove_placement_group(pg)
    out.append({"metric": "pg_remove_rate_per_s",
                "value": round(n / (time.perf_counter() - t0), 1)})
    return out


def run(nodes: int, real_agents: int, actors: int, tasks: int, pgs: int,
        dispatch_agents: int = 0, broadcast_agents: int = 0,
        broadcast_mb: int = 64, data_mb: int = 0,
        chain_stages: int = 0) -> list[dict]:
    results = []
    ray_tpu.init(num_cpus=16, ignore_reinit_error=True)
    for section, fn in (
        ("nodes", lambda: bench_nodes(nodes, real_agents)),
        ("dispatch", lambda: bench_dispatch(dispatch_agents) if dispatch_agents else []),
        ("broadcast", lambda: bench_broadcast(broadcast_agents, broadcast_mb)
                      if broadcast_agents else []),
        ("data_ingest", lambda: bench_data_ingest(block_mb=data_mb)
                        if data_mb else []),
        ("cross_node_chain", lambda: bench_cross_node_chain(chain_stages)
                             if chain_stages else []),
        ("actors", lambda: bench_actors(actors)),
        ("queued_tasks", lambda: bench_queued_tasks(tasks)),
        ("placement_groups", lambda: bench_placement_groups(pgs)),
    ):
        t0 = time.perf_counter()
        try:
            rows = fn()
        except Exception as e:  # record the knee instead of dying
            rows = [{"metric": f"{section}_FAILED", "error": f"{type(e).__name__}: {e}"[:200]}]
        for r in rows:
            r["section"] = section
            print(json.dumps(r), flush=True)
        results.extend(rows)
        print(f"# {section} took {time.perf_counter() - t0:.1f}s rss={_rss_mb():.0f}MB",
              flush=True)
    ray_tpu.shutdown()
    return results


def write_md(results: list[dict], path: str, args) -> None:
    ref = "/root/reference/release/benchmarks/README.md:11-14"
    lines = [
        "# Scale envelope — round 5 (single host, 1 shared CPU core)",
        "",
        f"Reference envelope ({ref}): 2,000 nodes / 40K actors / 10K running tasks"
        " / 1K PGs on a 64x64-core cluster; 1M queued tasks on one m4.16xlarge.",
        "This table is the same envelope measured on ONE shared core — the",
        "single-controller design's capacity, not a cluster claim.",
        "",
        "| metric | value |",
        "|---|---|",
    ]
    for r in results:
        m = r.pop("metric")
        r.pop("section", None)
        lines.append(f"| {m} | {json.dumps(r)} |")
    lines += ["", f"_Args: {vars(args)}; regenerate with "
              "`python -m ray_tpu.scripts.scale_bench`._"]
    with open(path, "w") as f:
        f.write("\n".join(lines) + "\n")


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--nodes", type=int, default=60)
    ap.add_argument("--real-agents", type=int, default=8)
    ap.add_argument("--actors", type=int, default=1000)
    ap.add_argument("--tasks", type=int, default=100_000)
    ap.add_argument("--pgs", type=int, default=1000)
    ap.add_argument("--dispatch-agents", type=int, default=0)
    ap.add_argument("--broadcast-agents", type=int, default=0)
    ap.add_argument("--broadcast-mb", type=int, default=64)
    ap.add_argument("--data-mb", type=int, default=0,
                    help="per-block MB for the data-ingestion sweep "
                         "(0 = skip)")
    ap.add_argument("--chain-stages", type=int, default=0,
                    help="max stages for the cross-node compiled-chain "
                         "sweep over 2 real agents (0 = skip)")
    ap.add_argument("--md", default="SCALE_r05.md")
    a = ap.parse_args()
    res = run(a.nodes, a.real_agents, a.actors, a.tasks, a.pgs,
              dispatch_agents=a.dispatch_agents,
              broadcast_agents=a.broadcast_agents,
              broadcast_mb=a.broadcast_mb, data_mb=a.data_mb,
              chain_stages=a.chain_stages)
    if a.md:
        write_md(res, a.md, a)
