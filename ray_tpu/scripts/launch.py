"""Multi-host cluster launcher: `rtpu start` orchestrated over ssh.

Parity: the reference's `ray up` autoscaler launcher + `ray start --address`
manual assembly (python/ray/scripts/scripts.py, autoscaler/_private/updater.py
ssh runner). A cluster spec names the head and worker hosts; `up` starts the
head remotely, reads back its address+token, and joins each worker;
`down` stops everything.

Providers:
- ``ssh``: run the CLI on remote hosts over ssh (BatchMode, no prompts).
- ``local``: spawn the same CLI as local subprocesses — the provider used in
  tests and on a single machine, exercising exactly the commands ssh would.

Spec (JSON or YAML-subset: JSON is always accepted):
    {
      "provider": "ssh" | "local",
      "head": {"host": "10.0.0.1", "port": 7380, "num_cpus": 8},
      "workers": [{"host": "10.0.0.2", "num_cpus": 8, "name": "w1"}],
      "ssh": {"user": "ubuntu", "key": "~/.ssh/id_ed25519",
              "python": "python3"}
    }
"""

from __future__ import annotations

import json
import os
import re
import subprocess
import sys
import time
from typing import Any

def _state_file() -> str:
    d = os.path.join(os.path.expanduser("~"), ".ray_tpu")
    os.makedirs(d, mode=0o700, exist_ok=True)
    return os.path.join(d, "launch_state.json")


def load_spec(path: str) -> dict:
    with open(path) as f:
        return json.load(f)


def _ssh_base(spec: dict, host: str) -> list[str]:
    ssh = spec.get("ssh", {})
    cmd = ["ssh", "-o", "BatchMode=yes", "-o", "StrictHostKeyChecking=accept-new"]
    if ssh.get("key"):
        cmd += ["-i", os.path.expanduser(ssh["key"])]
    user = ssh.get("user")
    cmd.append(f"{user}@{host}" if user else host)
    return cmd


def head_start_command(spec: dict) -> list[str]:
    head = spec["head"]
    py = spec.get("ssh", {}).get("python", sys.executable)
    cmd = [py, "-m", "ray_tpu.scripts.cli"]
    if head.get("num_cpus"):
        cmd += ["--num-cpus", str(head["num_cpus"])]
    cmd += ["start", "--head", "--host", head.get("bind", "0.0.0.0")]
    if head.get("port"):
        cmd += ["--port", str(head["port"])]
    return cmd


def worker_join_command(spec: dict, worker: dict, address: str, token: str) -> list[str]:
    py = spec.get("ssh", {}).get("python", sys.executable)
    cmd = [py, "-m", "ray_tpu.scripts.cli"]
    if worker.get("num_cpus"):
        cmd += ["--num-cpus", str(worker["num_cpus"])]
    cmd += ["start", "--address", address, "--token", token]
    if worker.get("name"):
        cmd += ["--name", worker["name"]]
    return cmd


def _spawn(spec: dict, host: str, argv: list[str], log_path: str) -> subprocess.Popen:
    # truncate: a stale log from a previous run must never satisfy
    # _wait_for_head_info with an old address/token. 0600: the head log
    # carries the control-plane join token — a world-readable log would let
    # any local user join/control the cluster.
    fd = os.open(log_path, os.O_WRONLY | os.O_CREAT | os.O_TRUNC, 0o600)
    os.fchmod(fd, 0o600)  # O_CREAT's mode is ignored for pre-existing files
    log = os.fdopen(fd, "wb")
    if spec.get("provider", "ssh") == "local":
        return subprocess.Popen(argv, stdout=log, stderr=subprocess.STDOUT)
    remote = " ".join(argv)
    return subprocess.Popen(_ssh_base(spec, host) + [remote],
                            stdout=log, stderr=subprocess.STDOUT)


def _wait_for_head_info(log_path: str, timeout: float = 60.0) -> tuple[str, str]:
    """Parse 'Head started at <addr>' + the join token from the head log."""
    deadline = time.time() + timeout
    addr = token = None
    while time.time() < deadline:
        try:
            with open(log_path) as f:
                text = f.read()
        except OSError:
            text = ""
        m = re.search(r"Head started at (\S+)", text)
        t = re.search(r"--token (\S+)", text)
        if m and t:
            addr, token = m.group(1), t.group(1)
            break
        time.sleep(0.25)
    if not addr:
        raise TimeoutError(f"head did not report its address within {timeout}s "
                           f"(see {log_path})")
    return addr, token


def up(spec: dict, log_dir: str | None = None) -> dict:
    """Start head + workers; returns {'address', 'token', 'pids'}.

    Logs default into the 0700 ~/.ray_tpu dir (they carry the join token)."""
    if log_dir is None:
        log_dir = os.path.dirname(_state_file())
    head_log = os.path.join(log_dir, "ray_tpu_head.log")
    head_proc = _spawn(spec, spec["head"]["host"], head_start_command(spec), head_log)
    try:
        addr, token = _wait_for_head_info(head_log)
    except TimeoutError:
        head_proc.terminate()  # never leave a half-started head holding the port
        try:
            head_proc.wait(timeout=10)
        except subprocess.TimeoutExpired:
            head_proc.kill()
        raise
    # a wildcard-advertised local head is joinable at loopback
    if spec.get("provider") == "local":
        addr = f"127.0.0.1:{addr.rsplit(':', 1)[1]}"
    pids = {"head": head_proc.pid}
    for i, w in enumerate(spec.get("workers", [])):
        wlog = os.path.join(log_dir, f"ray_tpu_worker{i}.log")
        proc = _spawn(spec, w["host"], worker_join_command(spec, w, addr, token), wlog)
        pids[w.get("name") or f"worker{i}"] = proc.pid
    state = {"address": addr, "token": token, "pids": pids,
             "provider": spec.get("provider", "ssh")}
    fd = os.open(_state_file(), os.O_WRONLY | os.O_CREAT | os.O_TRUNC, 0o600)
    with os.fdopen(fd, "w") as f:
        json.dump(state, f)
    return state


def down(spec: dict | None = None) -> None:
    """Stop everything started by up() (local provider: by pid; ssh: rtpu stop)."""
    import signal

    try:
        with open(_state_file()) as f:
            state = json.load(f)
    except OSError:
        return
    if state.get("provider") == "local":
        for pid in state.get("pids", {}).values():
            try:
                os.kill(pid, signal.SIGTERM)
            except ProcessLookupError:
                pass
    elif spec is not None:
        py = spec.get("ssh", {}).get("python", "python3")
        for host in [spec["head"]["host"]] + [w["host"] for w in spec.get("workers", [])]:
            subprocess.run(_ssh_base(spec, host) + [f"{py} -m ray_tpu.scripts.cli stop"],
                           timeout=30, capture_output=True)
    try:
        os.unlink(_state_file())
    except OSError:
        pass


def main(argv=None) -> int:
    import argparse

    p = argparse.ArgumentParser(prog="ray-tpu-launch")
    sub = p.add_subparsers(dest="cmd", required=True)
    upp = sub.add_parser("up")
    upp.add_argument("spec")
    dnp = sub.add_parser("down")
    dnp.add_argument("spec", nargs="?")
    args = p.parse_args(argv)
    if args.cmd == "up":
        state = up(load_spec(args.spec))
        print(json.dumps(state))
        return 0
    down(load_spec(args.spec) if args.spec else None)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
