"""Perf sweep on the attached TPU (dev tool, one config per process).

Usage: python -m ray_tpu.scripts.tpu_sweep <config>   # A|B|C|D|E
Same measurement shape as bench.py (init/warmup/timed steps, 6N FLOPs MFU);
when a config wins, promote it into bench.py's on-chip LlamaConfig.
Runs exactly one config then exits cleanly — never run two at once and never
kill it (tunnel discipline: a killed client wedges the tunnel for hours).
"""
import json
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh

from ray_tpu.models import llama
from ray_tpu.train import spmd


def run(name, cfg, batch, seqlen, iters=15):
    import os

    dev = jax.devices()[0]
    assert dev.platform != "cpu", dev
    mesh = Mesh(np.asarray([dev]).reshape(1, 1, 1, 1, 1),
                ("data", "fsdp", "tensor", "seq", "expert"))
    key = jax.random.PRNGKey(0)
    with jax.default_device(dev):
        state = spmd.init_state(cfg, key, optimizer=spmd.make_optimizer(warmup=1))
        step = spmd.make_train_step(cfg, mesh,
                                    optimizer=spmd.make_optimizer(warmup=1))(state)
        tokens = jax.random.randint(key, (batch, seqlen), 0, cfg.vocab_size)
        targets = jax.random.randint(key, (batch, seqlen), 0, cfg.vocab_size)
        state, m = step(state, tokens, targets)
        jax.block_until_ready(m["loss"])
        t0 = time.perf_counter()
        for _ in range(iters):
            state, m = step(state, tokens, targets)
        jax.block_until_ready(m["loss"])
        dt = time.perf_counter() - t0
    tps = batch * seqlen * iters / dt
    n = llama.param_count_analytic(cfg)
    print(json.dumps({"config": name, "tokens_per_sec": round(tps, 1),
                      "mfu_6n": round(tps * 6 * n / 197e12, 4),
                      "params_m": round(n / 1e6),
                      "flash_bq": os.environ.get("RAY_TPU_FLASH_BLOCK_Q", "128"),
                      "flash_bk": os.environ.get("RAY_TPU_FLASH_BLOCK_K", "128")}),
          flush=True)


BASE = dict(vocab_size=32000, hidden_size=1024, intermediate_size=4096,
            num_layers=16, num_heads=16, num_kv_heads=8, max_seq_len=2048,
            rope_theta=10000.0, dtype=jnp.bfloat16)
BIG = dict(vocab_size=32000, hidden_size=2048, intermediate_size=8192,
           num_layers=12, num_heads=16, num_kv_heads=8, max_seq_len=2048,
           rope_theta=10000.0, dtype=jnp.bfloat16)

# ~886M params: hidden 2048 × 16 layers — bigger matmuls, lower attention
# fraction than BASE; still fits v5e HBM with adamw at bf16 moments.
BIG16 = dict(vocab_size=32000, hidden_size=2048, intermediate_size=8192,
             num_layers=16, num_heads=16, num_kv_heads=8, max_seq_len=2048,
             rope_theta=10000.0, dtype=jnp.bfloat16)
# ~1.3B params: hidden 4096 × 6 layers — MXU-saturating 4096-wide matmuls.
HUGE = dict(vocab_size=32000, hidden_size=4096, intermediate_size=14336,
            num_layers=6, num_heads=32, num_kv_heads=8, max_seq_len=2048,
            rope_theta=10000.0, dtype=jnp.bfloat16)

CONFIGS = {
    "A": ("A_full_bs8", llama.LlamaConfig(**BASE, remat=True), 8, 2048),
    "B": ("B_dots_bs8", llama.LlamaConfig(**BASE, remat=True, remat_policy="dots"), 8, 2048),
    "C": ("C_dots_bs16", llama.LlamaConfig(**BASE, remat=True, remat_policy="dots"), 16, 2048),
    "D": ("D_big_dots_bs8", llama.LlamaConfig(**BIG, remat=True, remat_policy="dots"), 8, 2048),
    "E": ("E_big_full_bs16", llama.LlamaConfig(**BIG, remat=True), 16, 2048),
    "F": ("F_dots_bs12", llama.LlamaConfig(**BASE, remat=True, remat_policy="dots"), 12, 2048),
    "G": ("G_dots_bs14", llama.LlamaConfig(**BASE, remat=True, remat_policy="dots"), 14, 2048),
    "H": ("H_noremat_bs8", llama.LlamaConfig(**BASE, remat=False), 8, 2048),
    "I": ("I_big16_dots_bs8", llama.LlamaConfig(**BIG16, remat=True, remat_policy="dots"), 8, 2048),
    "J": ("J_big16_dots_bs16", llama.LlamaConfig(**BIG16, remat=True, remat_policy="dots"), 16, 2048),
    "K": ("K_big16_full_bs16", llama.LlamaConfig(**BIG16, remat=True), 16, 2048),
    "L": ("L_huge_dots_bs8", llama.LlamaConfig(**HUGE, remat=True, remat_policy="dots"), 8, 2048),
    "M": ("M_huge_full_bs8", llama.LlamaConfig(**HUGE, remat=True), 8, 2048),
    "N": ("N_big_dots_bs16", llama.LlamaConfig(**BIG, remat=True, remat_policy="dots"), 16, 2048),
    "O": ("O_big16_noremat_bs8", llama.LlamaConfig(**BIG16, remat=False), 8, 2048),
}

if __name__ == "__main__":
    run(*CONFIGS[sys.argv[1] if len(sys.argv) > 1 else "A"])
