"""ray-tpu CLI.

Parity: python/ray/scripts/scripts.py (`ray status/summary/timeline/list/
job submit`) — argparse instead of click (not in the base image's guarantees).
"""

from __future__ import annotations

import argparse
import json
import sys


def _init_session(args):
    import ray_tpu

    # --address attaches to a RUNNING head as a client (the only way CLI
    # commands can see that head's live state — a bare init() would start a
    # fresh in-process runtime with empty tables)
    addr = getattr(args, "address", None)
    if addr:
        ray_tpu.init(address=addr, token=getattr(args, "token", None),
                     ignore_reinit_error=True)
    else:
        ray_tpu.init(num_cpus=args.num_cpus, ignore_reinit_error=True)


def cmd_status(args) -> int:
    import ray_tpu

    _init_session(args)
    total = ray_tpu.cluster_resources()
    avail = ray_tpu.available_resources()
    print("== ray_tpu status ==")
    print(f"nodes: {len(ray_tpu.nodes())}")
    for k in sorted(total):
        print(f"  {k}: {avail.get(k, 0):.1f}/{total[k]:.1f} available")
    # `ray status` parity: pending demand with an infeasible-vs-waiting
    # verdict per shape (head-local tables; a client attach skips it)
    try:
        from ray_tpu.util import state

        asv = state.autoscaler_status_view()
    except Exception:
        return 0
    print("\nDemand:")
    if not asv["pending_shapes"]:
        print("  (no pending resource demand)")
    for g in asv["pending_shapes"]:
        shape = ", ".join(f"{k}: {v:g}" for k, v in sorted(g["shape"].items()))
        print(f"  {{{shape}}} x {g['count']}  [{g['source']}]  "
              f"{g['status'].upper()}")
        print(f"    {g['reason']}")
    if asv["standing_demand"]:
        print(f"  standing demand entries: {len(asv['standing_demand'])}")
    return 0


def _fmt_bytes(n) -> str:
    n = float(n or 0)
    for unit in ("B", "KiB", "MiB", "GiB", "TiB"):
        if n < 1024 or unit == "TiB":
            return f"{n:.1f}{unit}" if unit != "B" else f"{int(n)}B"
        n /= 1024
    return f"{n:.1f}TiB"


def cmd_memory(args) -> int:
    """Cluster memory anatomy (`ray memory` parity): where the bytes live,
    who made them, what still references them, what looks leaked."""
    from ray_tpu.util import state

    _init_session(args)
    try:
        view = state.cluster_memory_view(limit=args.limit)
    except RuntimeError as e:
        print(f"error: {e}", file=sys.stderr)
        return 1
    rows = view["objects"]
    sort_key = {"size": lambda r: -r["size_bytes"],
                "age": lambda r: -r["age_s"],
                "copies": lambda r: -r["copies"]}[args.sort_by]
    rows = sorted(rows, key=sort_key)
    print("== cluster memory ==")
    total_bytes = sum(r["size_bytes"] for r in rows)
    print(f"objects: {len(rows)}  bytes: {_fmt_bytes(total_bytes)}")
    if args.group_by:
        group_key = {
            "creator": lambda r: f"{r['creator_kind']}:{r['creator']}",
            "node": lambda r: ",".join(r["nodes"]) or "?",
            "state": lambda r: r["ref_state"],
        }[args.group_by]
        groups: dict = {}
        for r in rows:
            g = groups.setdefault(group_key(r),
                                  {"objects": 0, "bytes": 0, "pinned": 0})
            g["objects"] += 1
            g["bytes"] += r["size_bytes"]
            g["pinned"] += 1 if r["pinned"] else 0
        print(f"\n  {'group':<40} {'objects':>8} {'bytes':>10} {'pinned':>7}")
        for name, g in sorted(groups.items(), key=lambda kv: -kv[1]["bytes"]):
            print(f"  {name[:40]:<40} {g['objects']:>8} "
                  f"{_fmt_bytes(g['bytes']):>10} {g['pinned']:>7}")
    else:
        hdr = (f"  {'object_id':<18} {'size':>10} {'copies':>6} {'pin':>4} "
               f"{'refs':>5} {'age':>8} {'creator':<24} nodes")
        print("\n" + hdr)
        for r in rows:
            pin = "yes" if r["pinned"] else "-"
            flag = " LEAK?" if r["leak_suspect"] else ""
            print(f"  {r['object_id'][:16] + '..':<18} "
                  f"{_fmt_bytes(r['size_bytes']):>10} {r['copies']:>6} "
                  f"{pin:>4} {r['ref_count']:>5} {r['age_s']:>7.1f}s "
                  f"{(r['creator_kind'] + ':' + r['creator'])[:24]:<24} "
                  f"{','.join(n[:8] for n in r['nodes'])}{flag}")
    if view["nodes"]:
        print("\nPer-node stores:")
        for n, agg in sorted(view["nodes"].items()):
            used = agg.get("store_used")
            cap = agg.get("store_capacity")
            occ = (f"  store {_fmt_bytes(used)}/{_fmt_bytes(cap)}"
                   if used is not None and cap else "")
            print(f"  {n[:16]:<16} objects={agg['objects']} "
                  f"bytes={_fmt_bytes(agg['bytes'])} "
                  f"pinned={_fmt_bytes(agg['pinned_bytes'])}{occ}")
    if view["leak_suspects"]:
        print("\nLeak suspects (sealed, unreferenced past grace):")
        for r in view["leak_suspects"]:
            print(f"  {r['object_id'][:16]}..  {_fmt_bytes(r['size_bytes'])}"
                  f"  creator={r['creator_kind']}:{r['creator']}"
                  f"  nodes={','.join(n[:8] for n in r['nodes'])}")
    else:
        print("\nNo leak suspects.")
    return 0


def cmd_list(args) -> int:
    from ray_tpu.util import state

    _init_session(args)
    fn = {
        "tasks": state.list_tasks,
        "actors": state.list_actors,
        "nodes": state.list_nodes,
        "objects": state.list_objects,
        "placement-groups": state.list_placement_groups,
    }[args.resource]
    print(json.dumps(fn(), indent=2, default=str))
    return 0


def cmd_summary(args) -> int:
    from ray_tpu.util import state

    _init_session(args)
    fn = {"tasks": state.summarize_tasks, "actors": state.summarize_actors}[args.resource]
    print(json.dumps(fn(), indent=2))
    return 0


def cmd_timeline(args) -> int:
    from ray_tpu.util import state

    _init_session(args)
    out = args.output or "timeline.json"
    state.timeline(out)
    print(f"Wrote Chrome trace to {out} (open chrome://tracing)")
    return 0


def cmd_debug(args) -> int:
    """List active remote-pdb sessions; attach to one (reference: ray debug)."""
    from ray_tpu.util import rpdb

    _init_session(args)
    sessions = rpdb.list_sessions()
    if not sessions:
        print("no active debugger sessions")
        return 0
    target = None
    if args.session_id:
        target = next((s for s in sessions if s["id"] == args.session_id), None)
        if target is None:
            print(f"unknown session {args.session_id}")
    elif len(sessions) == 1:
        target = sessions[0]
    if target is None:
        for s in sessions:
            print(f"{s['id']}  pid={s['pid']}  {s['host']}:{s['port']}  "
                  f"{s['reason']}")
        return 0
    print(f"attaching to {target['id']} ({target['reason']}) — "
          "'c' continues the task, Ctrl-D detaches")
    rpdb.attach(target)
    return 0


def cmd_job_submit(args) -> int:
    from ray_tpu.job_submission import JobSubmissionClient

    client = JobSubmissionClient()
    parts = args.entrypoint
    if parts and parts[0] == "--":
        parts = parts[1:]
    if not parts:
        print("error: no entrypoint given", file=sys.stderr)
        return 2
    job_id = client.submit_job(entrypoint=" ".join(parts))
    print(f"Submitted {job_id}")
    if args.wait:
        status = client.wait_until_finished(job_id, timeout=args.timeout)
        print(client.get_job_logs(job_id), end="")
        print(f"Job {job_id}: {status.value}")
        return 0 if status.value == "SUCCEEDED" else 1
    return 0


def cmd_bench(args) -> int:
    import runpy

    sys.argv = ["bench.py"]
    runpy.run_path("bench.py", run_name="__main__")
    return 0


def _session_file() -> str:
    """Per-user, 0700 session dir: the file holds the control-plane token, so
    it must not be world-readable (and concurrent users must not collide)."""
    import os

    d = os.path.join(os.path.expanduser("~"), ".ray_tpu")
    os.makedirs(d, mode=0o700, exist_ok=True)
    return os.path.join(d, "head_session.json")


def cmd_start(args) -> int:
    """`ray start`-equivalent (reference: scripts.py ray start --head/--address).

    --head: run a standalone head (control plane + scheduler) this process;
    prints the join command for other hosts and the attach address for
    drivers, then blocks until SIGINT/SIGTERM.
    --address: join an existing head as a worker node (this IS the remote
    host entrypoint; runs the node agent in the foreground).
    """
    import os
    import signal

    if args.head and args.address:
        print("error: pass --head OR --address, not both", file=sys.stderr)
        return 2
    if args.head:
        # explicit flags override any inherited env (assignment, not setdefault)
        os.environ["RAY_TPU_CONTROL_PLANE_HOST"] = args.host
        os.environ["RAY_TPU_CONTROL_PLANE_PORT"] = str(args.port or 0)
        import ray_tpu
        from ray_tpu.core import runtime as rt_mod

        ray_tpu.init(num_cpus=args.num_cpus, log_to_driver=False)
        rt = rt_mod.get_runtime()
        if rt.control_plane is None:
            print("error: control plane failed to start", file=sys.stderr)
            return 1
        addr = rt.control_plane.address
        if addr.startswith("0.0.0.0:"):
            # advertise a routable address, not the wildcard bind
            import socket

            try:
                s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
                s.connect(("10.255.255.255", 1))
                ip = s.getsockname()[0]
                s.close()
            except OSError:
                ip = "127.0.0.1"
            addr = f"{ip}:{addr.rsplit(':', 1)[1]}"
        token = rt.control_plane.token
        info = {"address": addr, "token": token, "pid": os.getpid()}
        session_file = _session_file()
        fd = os.open(session_file, os.O_WRONLY | os.O_CREAT | os.O_TRUNC, 0o600)
        with os.fdopen(fd, "w") as f:
            json.dump(info, f)
        print(f"Head started at {addr}")
        print("Join from another host:")
        print(f"  python -m ray_tpu.scripts.cli start --address {addr} --token {token}")
        print("Attach a driver:")
        print(f"  ray_tpu.init(address={addr!r}, token={token!r})")
        stop = {"flag": False}
        signal.signal(signal.SIGTERM, lambda *a: stop.update(flag=True))
        try:
            while not stop["flag"]:
                import time

                time.sleep(0.5)
        except KeyboardInterrupt:
            pass
        ray_tpu.shutdown()
        try:
            os.unlink(session_file)
        except OSError:
            pass
        return 0
    if args.address:
        token = args.token or os.environ.get("RAY_TPU_TOKEN")
        if not token:
            print("error: --token (or RAY_TPU_TOKEN) required to join a head",
                  file=sys.stderr)
            return 2
        from ray_tpu.core.cluster import node_agent_argv

        # cross-host nodes own their object plane; objects move via chunked
        # pulls (core/object_plane.py)
        agent_argv = node_agent_argv(
            args.address, token, num_cpus=float(args.num_cpus or 4),
            name=args.name or "", isolated_plane=True,
        )
        os.execv(sys.executable, agent_argv)
    print("error: pass --head or --address", file=sys.stderr)
    return 2


def cmd_stop(args) -> int:
    """Stop the head started by `start --head` (reference: ray stop)."""
    import os
    import signal

    session_file = _session_file()
    try:
        with open(session_file) as f:
            info = json.load(f)
    except OSError:
        print("No running head session found.")
        return 0
    try:
        os.kill(info["pid"], signal.SIGTERM)
        print(f"Stopped head pid {info['pid']} ({info['address']})")
    except ProcessLookupError:
        print("Head process already gone.")
    try:
        os.unlink(session_file)
    except OSError:
        pass
    return 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="ray-tpu", description="TPU-native distributed runtime CLI")
    p.add_argument("--num-cpus", type=float, default=None)
    p.add_argument("--address", default=None,
                   help="attach to a running head (host:port) instead of "
                        "starting an in-process session")
    p.add_argument("--token", default=None, help="session token for --address")
    sub = p.add_subparsers(dest="cmd", required=True)

    sub.add_parser("status", help="cluster resource status + pending demand")

    mp = sub.add_parser("memory", help="cluster memory anatomy "
                        "(`ray memory` parity: sizes, copies, owners, leaks)")
    mp.add_argument("--sort-by", choices=["size", "age", "copies"],
                    default="size")
    mp.add_argument("--group-by", choices=["creator", "node", "state"],
                    default=None)
    mp.add_argument("--limit", type=int, default=1000)

    lp = sub.add_parser("list", help="list live state")
    lp.add_argument("resource", choices=["tasks", "actors", "nodes", "objects", "placement-groups"])

    sp = sub.add_parser("summary", help="summarize state")
    sp.add_argument("resource", choices=["tasks", "actors"])

    tp = sub.add_parser("timeline", help="export Chrome trace of task events")
    tp.add_argument("-o", "--output", default=None)

    jp = sub.add_parser("job", help="job submission")
    jsub = jp.add_subparsers(dest="job_cmd", required=True)
    jsp = jsub.add_parser("submit")
    jsp.add_argument("--wait", action="store_true")
    jsp.add_argument("--timeout", type=float, default=300.0)
    jsp.add_argument("entrypoint", nargs=argparse.REMAINDER)

    stp = sub.add_parser("start", help="start a head or join one (ray start equiv)")
    stp.add_argument("--head", action="store_true")
    stp.add_argument("--address", default=None, help="head host:port to join")
    stp.add_argument("--token", default=None)
    stp.add_argument("--host", default="0.0.0.0", help="head bind host")
    stp.add_argument("--port", type=int, default=0, help="head bind port (0=ephemeral)")
    stp.add_argument("--name", default=None, help="node name when joining")

    sub.add_parser("stop", help="stop the head started by `start --head`")

    dp = sub.add_parser("debug", help="list / attach to remote pdb sessions "
                        "(reference: `ray debug`)")
    dp.add_argument("session_id", nargs="?", default=None,
                    help="attach to this session (default: the only one, or list)")

    args = p.parse_args(argv)
    if args.cmd == "start":
        return cmd_start(args)
    if args.cmd == "stop":
        return cmd_stop(args)
    if args.cmd == "status":
        return cmd_status(args)
    if args.cmd == "memory":
        return cmd_memory(args)
    if args.cmd == "list":
        return cmd_list(args)
    if args.cmd == "summary":
        return cmd_summary(args)
    if args.cmd == "timeline":
        return cmd_timeline(args)
    if args.cmd == "job":
        return cmd_job_submit(args)
    if args.cmd == "debug":
        return cmd_debug(args)
    return 1


if __name__ == "__main__":
    raise SystemExit(main())
