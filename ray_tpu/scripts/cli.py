"""ray-tpu CLI.

Parity: python/ray/scripts/scripts.py (`ray status/summary/timeline/list/
job submit`) — argparse instead of click (not in the base image's guarantees).
"""

from __future__ import annotations

import argparse
import json
import sys


def _init_session(args):
    import ray_tpu

    ray_tpu.init(num_cpus=args.num_cpus, ignore_reinit_error=True)


def cmd_status(args) -> int:
    import ray_tpu

    _init_session(args)
    total = ray_tpu.cluster_resources()
    avail = ray_tpu.available_resources()
    print("== ray_tpu status ==")
    print(f"nodes: {len(ray_tpu.nodes())}")
    for k in sorted(total):
        print(f"  {k}: {avail.get(k, 0):.1f}/{total[k]:.1f} available")
    return 0


def cmd_list(args) -> int:
    from ray_tpu.util import state

    _init_session(args)
    fn = {
        "tasks": state.list_tasks,
        "actors": state.list_actors,
        "nodes": state.list_nodes,
        "objects": state.list_objects,
        "placement-groups": state.list_placement_groups,
    }[args.resource]
    print(json.dumps(fn(), indent=2, default=str))
    return 0


def cmd_summary(args) -> int:
    from ray_tpu.util import state

    _init_session(args)
    fn = {"tasks": state.summarize_tasks, "actors": state.summarize_actors}[args.resource]
    print(json.dumps(fn(), indent=2))
    return 0


def cmd_timeline(args) -> int:
    from ray_tpu.util import state

    _init_session(args)
    out = args.output or "timeline.json"
    state.timeline(out)
    print(f"Wrote Chrome trace to {out} (open chrome://tracing)")
    return 0


def cmd_job_submit(args) -> int:
    from ray_tpu.job_submission import JobSubmissionClient

    client = JobSubmissionClient()
    parts = args.entrypoint
    if parts and parts[0] == "--":
        parts = parts[1:]
    if not parts:
        print("error: no entrypoint given", file=sys.stderr)
        return 2
    job_id = client.submit_job(entrypoint=" ".join(parts))
    print(f"Submitted {job_id}")
    if args.wait:
        status = client.wait_until_finished(job_id, timeout=args.timeout)
        print(client.get_job_logs(job_id), end="")
        print(f"Job {job_id}: {status.value}")
        return 0 if status.value == "SUCCEEDED" else 1
    return 0


def cmd_bench(args) -> int:
    import runpy

    sys.argv = ["bench.py"]
    runpy.run_path("bench.py", run_name="__main__")
    return 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="ray-tpu", description="TPU-native distributed runtime CLI")
    p.add_argument("--num-cpus", type=float, default=None)
    sub = p.add_subparsers(dest="cmd", required=True)

    sub.add_parser("status", help="cluster resource status")

    lp = sub.add_parser("list", help="list live state")
    lp.add_argument("resource", choices=["tasks", "actors", "nodes", "objects", "placement-groups"])

    sp = sub.add_parser("summary", help="summarize state")
    sp.add_argument("resource", choices=["tasks", "actors"])

    tp = sub.add_parser("timeline", help="export Chrome trace of task events")
    tp.add_argument("-o", "--output", default=None)

    jp = sub.add_parser("job", help="job submission")
    jsub = jp.add_subparsers(dest="job_cmd", required=True)
    jsp = jsub.add_parser("submit")
    jsp.add_argument("--wait", action="store_true")
    jsp.add_argument("--timeout", type=float, default=300.0)
    jsp.add_argument("entrypoint", nargs=argparse.REMAINDER)

    args = p.parse_args(argv)
    if args.cmd == "status":
        return cmd_status(args)
    if args.cmd == "list":
        return cmd_list(args)
    if args.cmd == "summary":
        return cmd_summary(args)
    if args.cmd == "timeline":
        return cmd_timeline(args)
    if args.cmd == "job":
        return cmd_job_submit(args)
    return 1


if __name__ == "__main__":
    raise SystemExit(main())
