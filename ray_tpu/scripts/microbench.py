"""Core microbenchmark suite: `python -m ray_tpu.scripts.microbench`.

Parity: python/ray/_private/ray_perf.py:95-252 (the release-tracked
microbenchmarks: single-client sync tasks, 1:1 actor calls, n:n async actor
calls, put/get throughput). Prints one JSON line per metric so CI can track
regressions; `--quick` trims iteration counts for smoke runs.
"""

from __future__ import annotations

import argparse
import json
import time

import numpy as np


def _rate(n: int, dt: float) -> float:
    return round(n / dt, 2) if dt > 0 else 0.0


def bench_tasks_sync(n: int) -> dict:
    import ray_tpu

    @ray_tpu.remote(isolate_process=False)  # in-process dispatch overhead
    def nop():
        return 0

    ray_tpu.get(nop.remote())  # warm
    t0 = time.perf_counter()
    for _ in range(n):
        ray_tpu.get(nop.remote())
    dt = time.perf_counter() - t0
    return {"metric": "single_client_tasks_sync", "value": _rate(n, dt), "unit": "tasks/s"}


def bench_tasks_async_batch(n: int) -> dict:
    import ray_tpu

    @ray_tpu.remote(isolate_process=False)
    def nop():
        return 0

    ray_tpu.get(nop.remote())
    t0 = time.perf_counter()
    ray_tpu.get([nop.remote() for _ in range(n)])
    dt = time.perf_counter() - t0
    return {"metric": "single_client_tasks_async", "value": _rate(n, dt), "unit": "tasks/s"}


def bench_process_tasks(n: int) -> dict:
    import ray_tpu

    @ray_tpu.remote  # default: OS worker processes (the honest hot path)
    def nop():
        return 0

    # Warm the pool + pipeline paths (the reference's ray_perf warms before
    # timing, ray_perf.py:64); first bursts pay worker boot + cold caches.
    ray_tpu.get([nop.remote() for _ in range(50)])
    t0 = time.perf_counter()
    ray_tpu.get([nop.remote() for _ in range(n)])
    dt = time.perf_counter() - t0
    return {"metric": "process_tasks_async", "value": _rate(n, dt), "unit": "tasks/s"}


def bench_actor_calls_sync(n: int) -> dict:
    import ray_tpu

    @ray_tpu.remote
    class A:
        def nop(self):
            return 0

    a = A.remote()
    ray_tpu.get(a.nop.remote())
    t0 = time.perf_counter()
    for _ in range(n):
        ray_tpu.get(a.nop.remote())
    dt = time.perf_counter() - t0
    ray_tpu.kill(a)  # actors hold CPU capacity; repeats would exhaust it
    return {"metric": "actor_calls_sync_1_1", "value": _rate(n, dt), "unit": "calls/s"}


def bench_actor_calls_async(n: int, num_actors: int = 4) -> dict:
    import ray_tpu

    @ray_tpu.remote
    class A:
        def nop(self):
            return 0

    actors = [A.remote() for _ in range(num_actors)]
    ray_tpu.get([a.nop.remote() for a in actors])
    t0 = time.perf_counter()
    refs = [actors[i % num_actors].nop.remote() for i in range(n)]
    ray_tpu.get(refs)
    dt = time.perf_counter() - t0
    for a in actors:  # release held CPU capacity before the next repeat
        ray_tpu.kill(a)
    return {"metric": "actor_calls_async_n_n", "value": _rate(n, dt), "unit": "calls/s"}


def bench_put_gigabytes(total_mb: int) -> dict:
    import ray_tpu

    chunk = np.random.default_rng(0).standard_normal(1_000_000)  # 8 MB
    n = max(1, total_mb // 8)
    refs = []
    t0 = time.perf_counter()
    for _ in range(n):
        refs.append(ray_tpu.put(chunk))
    dt = time.perf_counter() - t0
    gb = n * chunk.nbytes / 1e9
    out = {"metric": "put_throughput", "value": round(gb / dt, 3), "unit": "GB/s"}
    del refs
    return out


def bench_get_gigabytes(total_mb: int) -> dict:
    import ray_tpu

    chunk = np.random.default_rng(0).standard_normal(1_000_000)
    n = max(1, total_mb // 8)
    refs = [ray_tpu.put(chunk) for _ in range(n)]
    t0 = time.perf_counter()
    for r in refs:
        ray_tpu.get(r)
    dt = time.perf_counter() - t0
    gb = n * chunk.nbytes / 1e9
    return {"metric": "get_throughput_zero_copy", "value": round(gb / dt, 3), "unit": "GB/s"}


def bench_plane_pull(size_mb: int, holders: int = 1) -> dict:
    """Object-plane pull throughput over loopback: chunk frames from the
    holder store(s) landing in the puller's store via ``pull_into`` (the
    zero-copy v3 BLOB path when negotiated). Runs against live plane
    servers, so it measures the real wire path — not the in-process store."""
    import os

    from ray_tpu._private.ids import ObjectID
    from ray_tpu.core.object_plane import ObjectPlaneServer, PlaneClient
    from ray_tpu.core.shm_store import SharedMemoryStore

    nbytes = size_mb << 20
    slack = 16 << 20
    tag = f"{os.getpid()}_{size_mb}_{holders}"
    srcs = [SharedMemoryStore(f"/rtpu_mb_src{i}_{tag}", size=nbytes + slack,
                              owner=True) for i in range(holders)]
    dst = SharedMemoryStore(f"/rtpu_mb_dst_{tag}", size=nbytes + slack,
                            owner=True)
    servers = [ObjectPlaneServer(s) for s in srcs]
    client = PlaneClient(stripe_min_bytes=1 if holders > 1 else None)
    try:
        payload = np.random.default_rng(0).bytes(nbytes)
        oid = ObjectID(os.urandom(ObjectID.SIZE))
        for s in srcs:
            s.put_bytes(oid, payload)
        addrs = [srv.address for srv in servers]
        t0 = time.perf_counter()
        status = client.pull_into(addrs, oid, dst)
        dt = time.perf_counter() - t0
        assert status == "sealed", f"pull failed: {status}"
        dst.delete(oid)  # so repeats re-pull instead of hitting "exists"
        return {"metric": f"plane_pull_{size_mb}mb_{holders}h",
                "value": round(nbytes / dt / 1e6, 1), "unit": "MB/s"}
    finally:
        client.close()
        for srv in servers:
            srv.close()
        for s in srcs:
            s.close()
        dst.close()


def _dag_chain_actors(stages: int):
    import ray_tpu

    @ray_tpu.remote(isolate_process=True)  # own process per stage: the loops
    class Stage:  # spin on shm channels without sharing the driver's GIL
        def proc(self, x):
            return x + 1

    actors = [Stage.remote() for _ in range(stages)]
    ray_tpu.get([a.proc.remote(0) for a in actors])  # wait ALIVE
    return actors


def bench_dag_steps_compiled(n: int, stages: int = 3) -> dict:
    """Compiled actor graph: a `stages`-deep chain executed n times — per
    step, one input-channel write + one output-channel read, ZERO
    control-plane requests (dag/compiled.py; asserted via the rpc op counter
    in tests/test_dag.py)."""
    import ray_tpu
    from ray_tpu.dag import InputNode

    actors = _dag_chain_actors(stages)
    with InputNode() as inp:
        node = inp
        for a in actors:
            node = a.proc.bind(node)
    compiled = node.experimental_compile()
    try:
        compiled.execute(0).get(timeout=60)  # warm the loops + channels
        t0 = time.perf_counter()
        refs = [compiled.execute(i) for i in range(n)]
        out = [r.get(timeout=60) for r in refs]
        dt = time.perf_counter() - t0
        assert out[-1] == (n - 1) + stages
    finally:
        compiled.teardown()
        for a in actors:
            ray_tpu.kill(a)
    return {"metric": "dag_steps_compiled", "value": _rate(n, dt),
            "unit": "steps/s"}


def bench_dag_steps_rpc_baseline(n: int, stages: int = 3) -> dict:
    """The same chain driven the pre-compiled way: per step, one `.remote()`
    per stage (refs chained) + one get — every hop pays control-plane
    dispatch. The compiled/rpc ratio is the headline of ISSUE 7."""
    import ray_tpu

    actors = _dag_chain_actors(stages)
    try:
        t0 = time.perf_counter()
        for i in range(n):
            ref = actors[0].proc.remote(i)
            for a in actors[1:]:
                ref = a.proc.remote(ref)
            out = ray_tpu.get(ref)
        dt = time.perf_counter() - t0
        assert out == (n - 1) + stages
    finally:
        for a in actors:
            ray_tpu.kill(a)
    return {"metric": "dag_steps_rpc_baseline", "value": _rate(n, dt),
            "unit": "steps/s"}


def bench_dag_cross_node(n: int, stages: int = 3) -> dict:
    """ISSUE-15 acceptance A/B, interleaved in ONE setup: a `stages`-deep
    compiled chain with stages alternating over 2 REAL isolated-plane
    agents vs the same chain per-call. The compiled window asserts ZERO
    control-plane requests (``rpc:*`` opcount delta) — cross-node edges
    ride same-machine shm attach / data-plane fabric connections, never
    the control plane."""
    import ray_tpu
    from ray_tpu.cluster_utils import Cluster
    from ray_tpu.core.rpc import opcount
    from ray_tpu.dag import InputNode

    @ray_tpu.remote(isolate_process=True, num_cpus=0.5)
    class Stage:
        def proc(self, x):
            return x + 1

    cluster = Cluster(initialize_head=False)
    for res in ({"mba": 100}, {"mbb": 100}):
        cluster.add_node(num_cpus=stages, resources=res,
                         real_process=True, isolated_plane=True)
    actors = [
        Stage.options(resources={("mba" if i % 2 == 0 else "mbb"): 1}
                      ).remote()
        for i in range(stages)
    ]
    try:
        with InputNode() as inp:
            node = inp
            for a in actors:
                node = a.proc.bind(node)
        compiled = node.experimental_compile()
        try:
            compiled.execute(0).get(timeout=60)  # warm loops + channels
            before = opcount.snapshot()
            t0 = time.perf_counter()
            refs = [compiled.execute(i) for i in range(n)]
            out = [r.get(timeout=120) for r in refs]
            dt = time.perf_counter() - t0
            assert out[-1] == (n - 1) + stages
            rpc_delta = sum(v - before.get(k, 0)
                            for k, v in opcount.snapshot().items()
                            if k.startswith("rpc:"))
        finally:
            compiled.teardown()
        m = max(10, n // 5)
        t0 = time.perf_counter()
        for i in range(m):
            ref = actors[0].proc.remote(i)
            for a in actors[1:]:
                ref = a.proc.remote(ref)
            out = ray_tpu.get(ref)
        dt_pc = time.perf_counter() - t0
        assert out == (m - 1) + stages
    finally:
        for a in actors:
            ray_tpu.kill(a)
        for nid in list(cluster.node_ids):
            try:
                cluster.remove_node(nid)
            except Exception:
                pass
    return {"metric": "dag_cross_node_3stage",
            "value": _rate(n, dt), "unit": "steps/s",
            "per_call_steps_per_s": _rate(m, dt_pc),
            "speedup": round((n / dt) / (m / dt_pc), 2),
            "steady_state_rpc_requests": rpc_delta}


def _median_of(samples: list[dict]) -> dict:
    """Collapse repeated runs of one bench into median + dispersion.

    Single-run numbers on a 1-core shared box swing multiples (observed
    6K-26K/s on actor_calls_async); the reference's harness loops timeit for
    the same reason (ray_perf.py timeit). The headline value is the MEDIAN;
    p25/p75 expose the spread so a lucky run can't masquerade as the truth."""
    import statistics

    out = dict(samples[0])
    for key, val in samples[0].items():
        if isinstance(val, (int, float)) and key not in ("n", "total_mb"):
            vals = sorted(float(s[key]) for s in samples)
            out[key] = round(statistics.median(vals), 2)
            qs = statistics.quantiles(vals, n=4) if len(vals) >= 3 else [vals[0], vals[0], vals[-1]]
            out[f"{key}_p25"] = round(qs[0], 2)
            out[f"{key}_p75"] = round(qs[2], 2)
    out["repeats"] = len(samples)
    return out


def run(quick: bool = False, repeats: int = 5) -> list[dict]:
    import ray_tpu

    ray_tpu.init(num_cpus=8, ignore_reinit_error=True)
    k = 1 if quick else 10
    if quick:
        repeats = 1
    benches = [
        lambda: bench_tasks_sync(100 * k),
        lambda: bench_tasks_async_batch(100 * k),
        lambda: bench_process_tasks(50 * k),
        lambda: bench_actor_calls_sync(100 * k),
        lambda: bench_actor_calls_async(100 * k),
        lambda: bench_put_gigabytes(16 * k),
        lambda: bench_get_gigabytes(16 * k),
        # compiled actor graphs vs per-call dispatch on the same 3-actor chain
        lambda: bench_dag_steps_compiled(200 * k),
        lambda: bench_dag_steps_rpc_baseline(50 * k),
        # ISSUE-15: the same chain with stages on 2 REAL isolated-plane
        # agents (cross-node actor fabric), A/B'd in one setup
        lambda: bench_dag_cross_node(100 * k if not quick else 100),
        # object-plane pulls over live loopback plane servers (wire v3)
        lambda: bench_plane_pull(1, 1),
        lambda: bench_plane_pull(1, 2),
        lambda: bench_plane_pull(16, 1),
        lambda: bench_plane_pull(16, 2),
        lambda: bench_plane_pull(16 * (4 if not quick else 1), 1),
        lambda: bench_plane_pull(16 * (4 if not quick else 1), 2),
    ]
    results = []
    for bench in benches:
        samples = [bench() for _ in range(repeats)]
        results.append(_median_of(samples))
    for r in results:
        print(json.dumps(r), flush=True)
    ray_tpu.shutdown()
    return results


if __name__ == "__main__":
    parser = argparse.ArgumentParser()
    parser.add_argument("--quick", action="store_true")
    parser.add_argument("--repeats", type=int, default=5)
    args = parser.parse_args()
    run(quick=args.quick, repeats=args.repeats)
