"""Core microbenchmark suite: `python -m ray_tpu.scripts.microbench`.

Parity: python/ray/_private/ray_perf.py:95-252 (the release-tracked
microbenchmarks: single-client sync tasks, 1:1 actor calls, n:n async actor
calls, put/get throughput). Prints one JSON line per metric so CI can track
regressions; `--quick` trims iteration counts for smoke runs.
"""

from __future__ import annotations

import argparse
import json
import time

import numpy as np


def _rate(n: int, dt: float) -> float:
    return round(n / dt, 2) if dt > 0 else 0.0


def bench_tasks_sync(n: int) -> dict:
    import ray_tpu

    @ray_tpu.remote(isolate_process=False)  # in-process dispatch overhead
    def nop():
        return 0

    ray_tpu.get(nop.remote())  # warm
    t0 = time.perf_counter()
    for _ in range(n):
        ray_tpu.get(nop.remote())
    dt = time.perf_counter() - t0
    return {"metric": "single_client_tasks_sync", "value": _rate(n, dt), "unit": "tasks/s"}


def bench_tasks_async_batch(n: int) -> dict:
    import ray_tpu

    @ray_tpu.remote(isolate_process=False)
    def nop():
        return 0

    ray_tpu.get(nop.remote())
    t0 = time.perf_counter()
    ray_tpu.get([nop.remote() for _ in range(n)])
    dt = time.perf_counter() - t0
    return {"metric": "single_client_tasks_async", "value": _rate(n, dt), "unit": "tasks/s"}


def bench_process_tasks(n: int) -> dict:
    import ray_tpu

    @ray_tpu.remote  # default: OS worker processes (the honest hot path)
    def nop():
        return 0

    # Warm the pool + pipeline paths (the reference's ray_perf warms before
    # timing, ray_perf.py:64); first bursts pay worker boot + cold caches.
    ray_tpu.get([nop.remote() for _ in range(50)])
    t0 = time.perf_counter()
    ray_tpu.get([nop.remote() for _ in range(n)])
    dt = time.perf_counter() - t0
    return {"metric": "process_tasks_async", "value": _rate(n, dt), "unit": "tasks/s"}


def bench_actor_calls_sync(n: int) -> dict:
    import ray_tpu

    @ray_tpu.remote
    class A:
        def nop(self):
            return 0

    a = A.remote()
    ray_tpu.get(a.nop.remote())
    t0 = time.perf_counter()
    for _ in range(n):
        ray_tpu.get(a.nop.remote())
    dt = time.perf_counter() - t0
    return {"metric": "actor_calls_sync_1_1", "value": _rate(n, dt), "unit": "calls/s"}


def bench_actor_calls_async(n: int, num_actors: int = 4) -> dict:
    import ray_tpu

    @ray_tpu.remote
    class A:
        def nop(self):
            return 0

    actors = [A.remote() for _ in range(num_actors)]
    ray_tpu.get([a.nop.remote() for a in actors])
    t0 = time.perf_counter()
    refs = [actors[i % num_actors].nop.remote() for i in range(n)]
    ray_tpu.get(refs)
    dt = time.perf_counter() - t0
    return {"metric": "actor_calls_async_n_n", "value": _rate(n, dt), "unit": "calls/s"}


def bench_put_gigabytes(total_mb: int) -> dict:
    import ray_tpu

    chunk = np.random.default_rng(0).standard_normal(1_000_000)  # 8 MB
    n = max(1, total_mb // 8)
    refs = []
    t0 = time.perf_counter()
    for _ in range(n):
        refs.append(ray_tpu.put(chunk))
    dt = time.perf_counter() - t0
    gb = n * chunk.nbytes / 1e9
    out = {"metric": "put_throughput", "value": round(gb / dt, 3), "unit": "GB/s"}
    del refs
    return out


def bench_get_gigabytes(total_mb: int) -> dict:
    import ray_tpu

    chunk = np.random.default_rng(0).standard_normal(1_000_000)
    n = max(1, total_mb // 8)
    refs = [ray_tpu.put(chunk) for _ in range(n)]
    t0 = time.perf_counter()
    for r in refs:
        ray_tpu.get(r)
    dt = time.perf_counter() - t0
    gb = n * chunk.nbytes / 1e9
    return {"metric": "get_throughput_zero_copy", "value": round(gb / dt, 3), "unit": "GB/s"}


def run(quick: bool = False) -> list[dict]:
    import ray_tpu

    ray_tpu.init(num_cpus=8, ignore_reinit_error=True)
    k = 1 if quick else 10
    results = [
        bench_tasks_sync(100 * k),
        bench_tasks_async_batch(100 * k),
        bench_process_tasks(50 * k),
        bench_actor_calls_sync(100 * k),
        bench_actor_calls_async(100 * k),
        bench_put_gigabytes(16 * k),
        bench_get_gigabytes(16 * k),
    ]
    for r in results:
        print(json.dumps(r), flush=True)
    ray_tpu.shutdown()
    return results


if __name__ == "__main__":
    parser = argparse.ArgumentParser()
    parser.add_argument("--quick", action="store_true")
    args = parser.parse_args()
    run(quick=args.quick)
