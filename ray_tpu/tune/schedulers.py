"""Trial schedulers: ASHA (async successive halving) + PBT.

Parity: python/ray/tune/schedulers/ — async_hyperband.py (ASHAScheduler) and
pbt.py (PopulationBasedTraining). The scheduler sees per-trial reports and
returns CONTINUE/STOP; PBT additionally mutates lagging trials from leaders.
"""

from __future__ import annotations

import random
from collections import defaultdict
from dataclasses import dataclass, field
from typing import Any, Optional

CONTINUE = "CONTINUE"
STOP = "STOP"


class TrialScheduler:
    def on_result(self, trial_id: str, result: dict) -> str:
        return CONTINUE

    def exploit_config(self, trial_id: str) -> Optional[dict]:
        return None


class FIFOScheduler(TrialScheduler):
    pass


class ASHAScheduler(TrialScheduler):
    """Async successive halving (reference: tune/schedulers/async_hyperband.py).

    At each rung (iteration = grace_period * reduction_factor^k) a trial stops
    unless its metric is in the top 1/reduction_factor of completed rung entries.
    """

    def __init__(self, metric: str = "loss", mode: str = "min", grace_period: int = 1,
                 reduction_factor: int = 3, max_t: int = 100, time_attr: str = "training_iteration"):
        self.metric = metric
        self.mode = mode
        self.grace = grace_period
        self.rf = reduction_factor
        self.max_t = max_t
        self.time_attr = time_attr
        self._rungs: dict[int, list[float]] = defaultdict(list)

    def _rung_for(self, t: int) -> int | None:
        r = self.grace
        while r <= self.max_t:
            if t == r:
                return r
            r *= self.rf
        return None

    def on_result(self, trial_id: str, result: dict) -> str:
        t = int(result.get(self.time_attr, 0))
        if t >= self.max_t:
            return STOP
        rung = self._rung_for(t)
        if rung is None:
            return CONTINUE
        val = result.get(self.metric)
        if val is None:
            return CONTINUE
        rung_vals = self._rungs[rung]
        rung_vals.append(float(val))
        if len(rung_vals) < self.rf:
            return CONTINUE  # not enough peers yet: optimistic continue (async)
        ordered = sorted(rung_vals, reverse=(self.mode == "max"))
        cutoff = ordered[max(0, len(ordered) // self.rf - 1)]
        good = val >= cutoff if self.mode == "max" else val <= cutoff
        return CONTINUE if good else STOP


class PopulationBasedTraining(TrialScheduler):
    """PBT (reference: tune/schedulers/pbt.py): at each perturbation interval,
    bottom-quantile trials copy a top-quantile trial's config (exploit) and
    perturb it (explore)."""

    def __init__(self, metric: str = "score", mode: str = "max",
                 perturbation_interval: int = 2, quantile_fraction: float = 0.25,
                 hyperparam_mutations: dict | None = None, seed: int | None = None,
                 time_attr: str = "training_iteration"):
        self.metric = metric
        self.mode = mode
        self.interval = perturbation_interval
        self.quantile = quantile_fraction
        self.mutations = hyperparam_mutations or {}
        self.time_attr = time_attr
        self.rng = random.Random(seed)
        self._latest: dict[str, tuple[float, dict]] = {}  # trial -> (metric, config)
        self._exploit: dict[str, dict] = {}

    def record_config(self, trial_id: str, config: dict) -> None:
        self._latest.setdefault(trial_id, (float("-inf") if self.mode == "max" else float("inf"), dict(config)))

    def on_result(self, trial_id: str, result: dict) -> str:
        val = result.get(self.metric)
        if val is None:
            return CONTINUE
        _, cfg = self._latest.get(trial_id, (None, {}))
        self._latest[trial_id] = (float(val), cfg)
        t = int(result.get(self.time_attr, 0))
        if t > 0 and t % self.interval == 0 and len(self._latest) >= 3:
            ranked = sorted(self._latest.items(), key=lambda kv: kv[1][0],
                            reverse=(self.mode == "max"))
            n = len(ranked)
            k = max(1, int(n * self.quantile))
            top = ranked[:k]
            bottom_ids = {tid for tid, _ in ranked[-k:]}
            if trial_id in bottom_ids:
                leader_id, (lval, lcfg) = self.rng.choice(top)
                if leader_id != trial_id:
                    self._exploit[trial_id] = self._perturb(lcfg)
        return CONTINUE

    def _perturb(self, config: dict) -> dict:
        out = dict(config)
        for k, spec in self.mutations.items():
            if callable(spec):
                out[k] = spec()
            elif isinstance(spec, list):
                out[k] = self.rng.choice(spec)
            elif isinstance(spec, tuple) and len(spec) == 2:
                factor = self.rng.choice([0.8, 1.2])
                out[k] = min(max(out.get(k, spec[0]) * factor, spec[0]), spec[1])
        return out

    def exploit_config(self, trial_id: str) -> Optional[dict]:
        """Trial-side poll: new config to adopt, if any (cleared on read)."""
        cfg = self._exploit.pop(trial_id, None)
        if cfg is not None:
            cur = self._latest.get(trial_id)
            if cur:
                self._latest[trial_id] = (cur[0], dict(cfg))
        return cfg
