"""Trial schedulers: ASHA (async successive halving) + PBT.

Parity: python/ray/tune/schedulers/ — async_hyperband.py (ASHAScheduler) and
pbt.py (PopulationBasedTraining). The scheduler sees per-trial reports and
returns CONTINUE/STOP; PBT additionally mutates lagging trials from leaders.
"""

from __future__ import annotations

import math
import random
from collections import defaultdict
from dataclasses import dataclass, field
from typing import Any, Optional

CONTINUE = "CONTINUE"
STOP = "STOP"


class TrialScheduler:
    def on_result(self, trial_id: str, result: dict) -> str:
        return CONTINUE

    def exploit_config(self, trial_id: str) -> Optional[dict]:
        return None


class FIFOScheduler(TrialScheduler):
    pass


class ASHAScheduler(TrialScheduler):
    """Async successive halving (reference: tune/schedulers/async_hyperband.py).

    At each rung (iteration = grace_period * reduction_factor^k) a trial stops
    unless its metric is in the top 1/reduction_factor of completed rung entries.
    """

    def __init__(self, metric: str = "loss", mode: str = "min", grace_period: int = 1,
                 reduction_factor: int = 3, max_t: int = 100, time_attr: str = "training_iteration"):
        self.metric = metric
        self.mode = mode
        self.grace = grace_period
        self.rf = reduction_factor
        self.max_t = max_t
        self.time_attr = time_attr
        self._rungs: dict[int, list[float]] = defaultdict(list)

    def _rung_for(self, t: int) -> int | None:
        r = self.grace
        while r <= self.max_t:
            if t == r:
                return r
            r *= self.rf
        return None

    def on_result(self, trial_id: str, result: dict) -> str:
        t = int(result.get(self.time_attr, 0))
        if t >= self.max_t:
            return STOP
        rung = self._rung_for(t)
        if rung is None:
            return CONTINUE
        val = result.get(self.metric)
        if val is None:
            return CONTINUE
        rung_vals = self._rungs[rung]
        rung_vals.append(float(val))
        if len(rung_vals) < self.rf:
            return CONTINUE  # not enough peers yet: optimistic continue (async)
        ordered = sorted(rung_vals, reverse=(self.mode == "max"))
        cutoff = ordered[max(0, len(ordered) // self.rf - 1)]
        good = val >= cutoff if self.mode == "max" else val <= cutoff
        return CONTINUE if good else STOP


class PopulationBasedTraining(TrialScheduler):
    """PBT (reference: tune/schedulers/pbt.py): at each perturbation interval,
    bottom-quantile trials copy a top-quantile trial's config (exploit) and
    perturb it (explore)."""

    def __init__(self, metric: str = "score", mode: str = "max",
                 perturbation_interval: int = 2, quantile_fraction: float = 0.25,
                 hyperparam_mutations: dict | None = None, seed: int | None = None,
                 time_attr: str = "training_iteration"):
        self.metric = metric
        self.mode = mode
        self.interval = perturbation_interval
        self.quantile = quantile_fraction
        self.mutations = hyperparam_mutations or {}
        self.time_attr = time_attr
        self.rng = random.Random(seed)
        self._latest: dict[str, tuple[float, dict]] = {}  # trial -> (metric, config)
        self._exploit: dict[str, dict] = {}

    def record_config(self, trial_id: str, config: dict) -> None:
        self._latest.setdefault(trial_id, (float("-inf") if self.mode == "max" else float("inf"), dict(config)))

    def on_result(self, trial_id: str, result: dict) -> str:
        val = result.get(self.metric)
        if val is None:
            return CONTINUE
        _, cfg = self._latest.get(trial_id, (None, {}))
        self._latest[trial_id] = (float(val), cfg)
        t = int(result.get(self.time_attr, 0))
        if t > 0 and t % self.interval == 0 and len(self._latest) >= 3:
            ranked = sorted(self._latest.items(), key=lambda kv: kv[1][0],
                            reverse=(self.mode == "max"))
            n = len(ranked)
            k = max(1, int(n * self.quantile))
            top = ranked[:k]
            bottom_ids = {tid for tid, _ in ranked[-k:]}
            if trial_id in bottom_ids:
                leader_id, (lval, lcfg) = self.rng.choice(top)
                if leader_id != trial_id:
                    self._exploit[trial_id] = self._perturb(lcfg)
        return CONTINUE

    def _perturb(self, config: dict) -> dict:
        out = dict(config)
        for k, spec in self.mutations.items():
            if callable(spec):
                out[k] = spec()
            elif isinstance(spec, list):
                out[k] = self.rng.choice(spec)
            elif isinstance(spec, tuple) and len(spec) == 2:
                factor = self.rng.choice([0.8, 1.2])
                out[k] = min(max(out.get(k, spec[0]) * factor, spec[0]), spec[1])
        return out

    def exploit_config(self, trial_id: str) -> Optional[dict]:
        """Trial-side poll: new config to adopt, if any (cleared on read)."""
        cfg = self._exploit.pop(trial_id, None)
        if cfg is not None:
            cur = self._latest.get(trial_id)
            if cur:
                self._latest[trial_id] = (cur[0], dict(cfg))
        return cfg


class MedianStoppingRule(TrialScheduler):
    """Stop trials whose running-average is worse than the median of the
    other trials' running averages at the same step (reference:
    tune/schedulers/median_stopping_rule.py)."""

    def __init__(self, metric: str = "loss", mode: str = "min",
                 grace_period: int = 3, min_samples_required: int = 3):
        self.metric, self.mode = metric, mode
        self.grace_period = grace_period
        self.min_samples = min_samples_required
        self._history: dict[str, list[float]] = {}

    def on_result(self, trial_id: str, result: dict) -> str:
        val = result.get(self.metric)
        if val is None:
            return CONTINUE
        hist = self._history.setdefault(trial_id, [])
        hist.append(float(val))
        t = len(hist)
        if t < self.grace_period:
            return CONTINUE
        means = [sum(h[:t]) / min(t, len(h))
                 for tid, h in self._history.items()
                 if tid != trial_id and len(h) >= t]
        if len(means) < self.min_samples:
            return CONTINUE
        means.sort()
        median = means[len(means) // 2]
        mine = sum(hist) / t
        worse = mine > median if self.mode == "min" else mine < median
        return STOP if worse else CONTINUE


class PB2(PopulationBasedTraining):
    """PBT with model-guided exploration (reference: tune/schedulers/pb2.py):
    instead of random perturbation factors, continuous hyperparams are chosen
    by GP-UCB over the population's (config -> score improvement) history —
    a small RBF-kernel GP fit in numpy at each exploit."""

    def __init__(self, *args, ucb_beta: float = 2.0, n_candidates: int = 32, **kw):
        super().__init__(*args, **kw)
        self.ucb_beta = ucb_beta
        self.n_candidates = n_candidates
        # (normalized config vector, score delta) observations per exploit key
        self._obs: list[tuple[list, float]] = []
        self._prev_scores: dict[str, float] = {}

    def _bounds(self):
        return {k: spec for k, spec in self.mutations.items()
                if isinstance(spec, tuple) and len(spec) == 2}

    def _norm(self, cfg: dict) -> list:
        return [(float(cfg.get(k, lo)) - lo) / max(hi - lo, 1e-12)
                for k, (lo, hi) in sorted(self._bounds().items())]

    def on_result(self, trial_id: str, result: dict) -> str:
        val = result.get(self.metric)
        if val is not None:
            prev = self._prev_scores.get(trial_id)
            if prev is not None:
                _, cfg = self._latest.get(trial_id, (None, {}))
                delta = float(val) - prev
                if self.mode == "min":
                    delta = -delta
                self._obs.append((self._norm(cfg), delta))
                self._obs = self._obs[-64:]  # bounded history
            self._prev_scores[trial_id] = float(val)
        return super().on_result(trial_id, result)

    def _gp_fit(self, X, y):
        """Factor the GP once; returns ucb(x) doing only mat-vec work per
        candidate (K is shared across all candidates of one exploit)."""
        import numpy as np

        X, y = np.asarray(X, float), np.asarray(y, float)
        if not len(X):
            return lambda x: 0.0
        ys = (y - y.mean()) / (y.std() + 1e-9)
        ls, noise = 0.3, 1e-2

        def k(a, b):
            d2 = ((a[:, None, :] - b[None, :, :]) ** 2).sum(-1)
            return np.exp(-d2 / (2 * ls * ls))

        K = k(X, X) + noise * np.eye(len(X))
        try:
            K_inv = np.linalg.inv(K)
        except np.linalg.LinAlgError:
            return lambda x: 0.0
        alpha = K_inv @ ys
        beta = self.ucb_beta

        def ucb(x):
            kx = k(X, np.asarray(x, float)[None])[:, 0]
            mu = float(kx @ alpha)
            var = float(1.0 - kx @ (K_inv @ kx))
            return mu + beta * math.sqrt(max(var, 1e-12))

        return ucb

    def _perturb(self, config: dict) -> dict:
        bounds = self._bounds()
        if not bounds:
            return super()._perturb(config)
        out = dict(config)
        # non-tuple mutations keep PBT behavior
        for k, spec in self.mutations.items():
            if callable(spec):
                out[k] = spec()
            elif isinstance(spec, list):
                out[k] = self.rng.choice(spec)
        ucb = self._gp_fit([v for v, _ in self._obs], [d for _, d in self._obs])
        best_cfg, best_score = None, float("-inf")
        for _ in range(self.n_candidates):
            cand = dict(out)
            for k, (lo, hi) in bounds.items():
                cand[k] = self.rng.uniform(lo, hi)
            score = ucb(self._norm(cand))
            if score > best_score:
                best_cfg, best_score = cand, score
        return best_cfg or out


def create_bohb(param_space: dict, metric: str = "loss", mode: str = "min",
                num_samples: int = 64, max_t: int = 100,
                reduction_factor: int = 3, seed: int | None = None):
    """BOHB (reference: tune/schedulers/hb_bohb.py + search/bohb/): HyperBand-
    style successive halving (ASHA rungs) driven by a model-based sampler
    (native TPE). Returns (scheduler, searcher) to pass into TuneConfig."""
    from ray_tpu.tune.search import TPESearcher

    scheduler = ASHAScheduler(metric=metric, mode=mode, max_t=max_t,
                              reduction_factor=reduction_factor)
    searcher = TPESearcher(param_space, metric=metric, mode=mode,
                           num_samples=num_samples, seed=seed)
    return scheduler, searcher
