"""Tuner + TuneController: trial orchestration over the actor runtime.

Parity: python/ray/tune/ — Tuner (tuner.py:43), tune.run (tune.py:267),
TuneController (execution/tune_controller.py:72): an event loop launching trial
actors under a concurrency cap, routing their reports through the searcher and
scheduler, early-stopping per scheduler decisions, tracking a ResultGrid.
"""

from __future__ import annotations

import queue
import threading
import time
import traceback
from dataclasses import dataclass, field
from typing import Any, Callable, Optional

import ray_tpu
from ray_tpu.tune.schedulers import CONTINUE, STOP, FIFOScheduler, TrialScheduler
from ray_tpu.tune.search import BasicVariantGenerator, Searcher


@dataclass
class TuneConfig:
    """Reference: tune/tune_config.py."""

    metric: str = "loss"
    mode: str = "min"
    num_samples: int = 1
    max_concurrent_trials: int = 4
    search_alg: Searcher | None = None
    scheduler: TrialScheduler | None = None


@dataclass
class TrialResult:
    trial_id: str
    config: dict
    metrics: dict = field(default_factory=dict)
    metrics_history: list = field(default_factory=list)
    error: str | None = None
    state: str = "PENDING"


class ResultGrid:
    """Reference: tune/result_grid.py."""

    def __init__(self, results: list[TrialResult], metric: str, mode: str):
        self._results = results
        self.metric = metric
        self.mode = mode

    def __len__(self):
        return len(self._results)

    def __iter__(self):
        return iter(self._results)

    def __getitem__(self, i):
        return self._results[i]

    def get_best_result(self, metric: str | None = None, mode: str | None = None) -> TrialResult:
        metric = metric or self.metric
        mode = mode or self.mode
        done = [r for r in self._results if r.metrics.get(metric) is not None]
        if not done:
            raise ValueError("No trial reported the target metric")
        return sorted(done, key=lambda r: r.metrics[metric], reverse=(mode == "max"))[0]

    def get_dataframe(self):
        import pandas as pd

        rows = []
        for r in self._results:
            row = {"trial_id": r.trial_id, "state": r.state, **{f"config/{k}": v for k, v in r.config.items()}}
            row.update(r.metrics)
            rows.append(row)
        return pd.DataFrame(rows)


class _TrialRunner:
    """Actor hosting one trial's function (reference: tune Trainable/actor)."""

    def __init__(self, trial_id: str, config: dict):
        self.trial_id = trial_id
        self.config = config
        self._reports: "queue.Queue[dict]" = queue.Queue()
        self._done = threading.Event()
        self._stop = threading.Event()
        self._error: str | None = None
        self._new_config: dict | None = None
        self._lock = threading.Lock()

    def run(self, fn: Callable) -> None:
        from ray_tpu.train.context import TrainContext, set_context

        def report_fn(metrics, checkpoint=None):
            self._reports.put(dict(metrics))
            if self._stop.is_set():
                raise _TrialStopped()

        def target():
            set_context(TrainContext(rank=0, world_size=1, report_fn=report_fn))
            try:
                fn(self.config)
            except _TrialStopped:
                pass
            except BaseException:  # noqa: BLE001
                self._error = traceback.format_exc()
            finally:
                self._done.set()

        threading.Thread(target=target, daemon=True, name=f"trial-{self.trial_id}").start()

    def poll(self) -> dict:
        finished = self._done.is_set()
        reports = []
        try:
            while True:
                reports.append(self._reports.get_nowait())
        except queue.Empty:
            pass
        return {"reports": reports, "finished": finished,
                "error": self._error if finished else None}

    def stop(self) -> None:
        self._stop.set()

    def update_config(self, config: dict) -> None:
        with self._lock:
            self.config.update(config)


class _TrialStopped(Exception):
    pass


class Tuner:
    """Reference: tune/tuner.py:43."""

    def __init__(self, trainable: Callable, *, param_space: dict | None = None,
                 tune_config: TuneConfig | None = None, run_config=None):
        self.trainable = trainable
        self.param_space = param_space or {}
        self.tune_config = tune_config or TuneConfig()
        self.run_config = run_config

    def fit(self) -> ResultGrid:
        from ray_tpu.air.callbacks import invoke as _cb

        tc = self.tune_config
        searcher = tc.search_alg or BasicVariantGenerator(self.param_space, tc.num_samples)
        scheduler = tc.scheduler or FIFOScheduler()
        callbacks = list(getattr(self.run_config, "callbacks", None) or [])
        _cb(callbacks, "setup", getattr(self.run_config, "name", None))
        results: list[TrialResult] = []
        running: dict[str, tuple] = {}  # trial_id -> (actor, TrialResult, iteration)
        trial_counter = 0
        actor_cls = ray_tpu.remote(num_cpus=1, max_concurrency=4)(_TrialRunner)

        def launch_next() -> bool:
            nonlocal trial_counter
            trial_id = f"trial_{trial_counter:05d}"
            cfg = searcher.suggest(trial_id)
            if cfg is None:
                return False
            trial_counter += 1
            tr = TrialResult(trial_id, dict(cfg), state="RUNNING")
            results.append(tr)
            _cb(callbacks, "on_trial_start", trial_id, dict(cfg))
            if hasattr(scheduler, "record_config"):
                scheduler.record_config(trial_id, cfg)
            actor = actor_cls.remote(trial_id, cfg)
            ray_tpu.get(actor.run.remote(self.trainable))
            running[trial_id] = [actor, tr, 0]
            return True

        exhausted = False
        while not exhausted or running:
            while not exhausted and len(running) < tc.max_concurrent_trials:
                if not launch_next():
                    exhausted = True
            polls = {tid: ray_tpu.get(entry[0].poll.remote()) for tid, entry in running.items()}
            for tid, poll in polls.items():
                actor, tr, iteration = running[tid]
                for rep in poll["reports"]:
                    iteration += 1
                    running[tid][2] = iteration
                    rep.setdefault("training_iteration", iteration)
                    tr.metrics = rep
                    tr.metrics_history.append(rep)
                    _cb(callbacks, "on_trial_result", tid, rep)
                    searcher.on_trial_complete(tid, rep)
                    decision = scheduler.on_result(tid, rep)
                    new_cfg = scheduler.exploit_config(tid)
                    if new_cfg is not None:
                        tr.config.update(new_cfg)
                        ray_tpu.get(actor.update_config.remote(new_cfg))
                    if decision == STOP:
                        ray_tpu.get(actor.stop.remote())
                        tr.state = "TERMINATED"
                if poll["finished"]:
                    tr.error = poll["error"]
                    tr.state = "ERRORED" if poll["error"] else (
                        "TERMINATED" if tr.state == "TERMINATED" else "COMPLETED"
                    )
                    _cb(callbacks, "on_trial_complete", tid, tr.metrics, tr.error)
                    ray_tpu.kill(actor)
                    del running[tid]
            time.sleep(0.02)
        grid = ResultGrid(results, tc.metric, tc.mode)
        _cb(callbacks, "on_experiment_end", grid)
        return grid


def run(trainable: Callable, *, config: dict | None = None, num_samples: int = 1,
        metric: str = "loss", mode: str = "min", scheduler=None, **kw) -> ResultGrid:
    """Reference: tune.run (tune/tune.py:267) — functional entrypoint."""
    return Tuner(
        trainable,
        param_space=config,
        tune_config=TuneConfig(metric=metric, mode=mode, num_samples=num_samples,
                               scheduler=scheduler),
    ).fit()
