"""Search spaces + search algorithms.

Parity: python/ray/tune/search/ — sample.py distributions (uniform, loguniform,
choice, randint, grid_search) and basic_variant.py (BasicVariantGenerator:
grid expansion × random sampling). Optuna/hyperopt-style suggesters plug in via
the same ``Searcher`` interface (suggest/on_trial_complete).
"""

from __future__ import annotations

import itertools
import math
import random
from dataclasses import dataclass
from typing import Any, Callable, Optional


class Domain:
    def sample(self, rng: random.Random) -> Any:
        raise NotImplementedError


@dataclass
class Uniform(Domain):
    low: float
    high: float

    def sample(self, rng):
        return rng.uniform(self.low, self.high)


@dataclass
class LogUniform(Domain):
    low: float
    high: float

    def sample(self, rng):
        return math.exp(rng.uniform(math.log(self.low), math.log(self.high)))


@dataclass
class Randint(Domain):
    low: int
    high: int

    def sample(self, rng):
        return rng.randrange(self.low, self.high)


@dataclass
class Choice(Domain):
    options: list

    def sample(self, rng):
        return rng.choice(self.options)


@dataclass
class GridSearch:
    values: list


def uniform(low, high) -> Uniform:
    return Uniform(low, high)


def loguniform(low, high) -> LogUniform:
    return LogUniform(low, high)


def randint(low, high) -> Randint:
    return Randint(low, high)


def choice(options) -> Choice:
    return Choice(list(options))


def grid_search(values) -> GridSearch:
    return GridSearch(list(values))


class Searcher:
    """Reference: tune/search/searcher.py Searcher interface."""

    def suggest(self, trial_id: str) -> Optional[dict]:
        raise NotImplementedError

    def on_trial_complete(self, trial_id: str, result: dict | None) -> None:
        pass


class BasicVariantGenerator(Searcher):
    """Grid × random expansion (reference: tune/search/basic_variant.py)."""

    def __init__(self, param_space: dict, num_samples: int = 1, seed: int | None = None):
        self.param_space = param_space
        self.num_samples = num_samples
        self.rng = random.Random(seed)
        grid_keys = [k for k, v in param_space.items() if isinstance(v, GridSearch)]
        grids = [param_space[k].values for k in grid_keys]
        self._grid_points = [dict(zip(grid_keys, combo)) for combo in itertools.product(*grids)] or [{}]
        self._emitted = 0
        self.total = len(self._grid_points) * num_samples

    def suggest(self, trial_id: str) -> Optional[dict]:
        if self._emitted >= self.total:
            return None
        grid = self._grid_points[self._emitted % len(self._grid_points)]
        cfg = {}
        for k, v in self.param_space.items():
            if k in grid:
                cfg[k] = grid[k]
            elif isinstance(v, Domain):
                cfg[k] = v.sample(self.rng)
            else:
                cfg[k] = v
        self._emitted += 1
        return cfg
