"""Search spaces + search algorithms.

Parity: python/ray/tune/search/ — sample.py distributions (uniform, loguniform,
choice, randint, grid_search) and basic_variant.py (BasicVariantGenerator:
grid expansion × random sampling). Optuna/hyperopt-style suggesters plug in via
the same ``Searcher`` interface (suggest/on_trial_complete).
"""

from __future__ import annotations

import itertools
import math
import random
from dataclasses import dataclass
from typing import Any, Callable, Optional


class Domain:
    def sample(self, rng: random.Random) -> Any:
        raise NotImplementedError


@dataclass
class Uniform(Domain):
    low: float
    high: float

    def sample(self, rng):
        return rng.uniform(self.low, self.high)


@dataclass
class LogUniform(Domain):
    low: float
    high: float

    def sample(self, rng):
        return math.exp(rng.uniform(math.log(self.low), math.log(self.high)))


@dataclass
class Randint(Domain):
    low: int
    high: int

    def sample(self, rng):
        return rng.randrange(self.low, self.high)


@dataclass
class Choice(Domain):
    options: list

    def sample(self, rng):
        return rng.choice(self.options)


@dataclass
class GridSearch:
    values: list


def uniform(low, high) -> Uniform:
    return Uniform(low, high)


def loguniform(low, high) -> LogUniform:
    return LogUniform(low, high)


def randint(low, high) -> Randint:
    return Randint(low, high)


def choice(options) -> Choice:
    return Choice(list(options))


def grid_search(values) -> GridSearch:
    return GridSearch(list(values))


class Searcher:
    """Reference: tune/search/searcher.py Searcher interface."""

    def suggest(self, trial_id: str) -> Optional[dict]:
        raise NotImplementedError

    def on_trial_complete(self, trial_id: str, result: dict | None) -> None:
        pass


class BasicVariantGenerator(Searcher):
    """Grid × random expansion (reference: tune/search/basic_variant.py)."""

    def __init__(self, param_space: dict, num_samples: int = 1, seed: int | None = None):
        self.param_space = param_space
        self.num_samples = num_samples
        self.rng = random.Random(seed)
        grid_keys = [k for k, v in param_space.items() if isinstance(v, GridSearch)]
        grids = [param_space[k].values for k in grid_keys]
        self._grid_points = [dict(zip(grid_keys, combo)) for combo in itertools.product(*grids)] or [{}]
        self._emitted = 0
        self.total = len(self._grid_points) * num_samples

    def suggest(self, trial_id: str) -> Optional[dict]:
        if self._emitted >= self.total:
            return None
        grid = self._grid_points[self._emitted % len(self._grid_points)]
        cfg = {}
        for k, v in self.param_space.items():
            if k in grid:
                cfg[k] = grid[k]
            elif isinstance(v, Domain):
                cfg[k] = v.sample(self.rng)
            else:
                cfg[k] = v
        self._emitted += 1
        return cfg


def _reject_grid(param_space: dict, who: str) -> None:
    """Model-based searchers sample, they don't enumerate: a GridSearch entry
    would otherwise pass through verbatim as a config value."""
    for k, v in param_space.items():
        if isinstance(v, GridSearch):
            raise ValueError(
                f"{who} does not support grid_search entries (param {k!r}); "
                "use tune.choice(...) or BasicVariantGenerator")


class TPESearcher(Searcher):
    """Tree-structured Parzen Estimator (reference: the optuna-backed
    tune/search/optuna default sampler, implemented natively).

    History splits into good/bad by the gamma quantile; numeric params draw
    candidates from Gaussians centered on good observations and are scored by
    the good/bad density ratio; categorical params sample from smoothed good
    counts. Falls back to random until n_startup observations exist."""

    def __init__(self, param_space: dict, metric: str = "loss",
                 mode: str = "min", num_samples: int = 64,
                 n_startup: int = 10, gamma: float = 0.25,
                 n_candidates: int = 24, seed: int | None = None):
        _reject_grid(param_space, "TPESearcher")
        self.param_space = param_space
        self.metric, self.mode = metric, mode
        self.num_samples = num_samples
        self.n_startup, self.gamma, self.n_candidates = n_startup, gamma, n_candidates
        self.rng = random.Random(seed)
        self._configs: dict[str, dict] = {}
        self._values: dict[str, float] = {}
        self._emitted = 0

    def on_trial_complete(self, trial_id: str, result: dict | None) -> None:
        if result is None or self.metric not in result:
            return
        v = float(result[self.metric])
        if trial_id in self._values:
            self._values[trial_id] = (min if self.mode == "min" else max)(
                self._values[trial_id], v)
        else:
            self._values[trial_id] = v

    def _split(self):
        done = [(self._values[t], self._configs[t]) for t in self._values
                if t in self._configs]
        done.sort(key=lambda kv: kv[0], reverse=(self.mode == "max"))
        k = max(1, int(len(done) * self.gamma))
        return [c for _, c in done[:k]], [c for _, c in done[k:]]

    @staticmethod
    def _kde_logpdf(x: float, obs: list, bw: float) -> float:
        if not obs:
            return -1e9
        s = sum(math.exp(-0.5 * ((x - o) / bw) ** 2) for o in obs)
        return math.log(s / (len(obs) * bw) + 1e-300)

    def _suggest_param(self, key, dom, good, bad):
        if isinstance(dom, Choice):
            counts = {o if not isinstance(o, (list, dict)) else repr(o): 1.0
                      for o in dom.options}
            for c in good:
                v = c.get(key)
                kk = v if not isinstance(v, (list, dict)) else repr(v)
                if kk in counts:
                    counts[kk] += 1.0
            opts, weights = zip(*[(o, counts[o if not isinstance(o, (list, dict))
                                             else repr(o)]) for o in dom.options])
            return self.rng.choices(list(opts), weights=list(weights))[0]
        if isinstance(dom, (Uniform, LogUniform, Randint)):
            log = isinstance(dom, LogUniform)
            if isinstance(dom, Randint):
                lo, hi = dom.low, dom.high - 1  # Randint.high is exclusive
            elif log:
                lo, hi = math.log(dom.low), math.log(dom.high)
            else:
                lo, hi = dom.low, dom.high
            xf = (lambda v: math.log(v)) if log else float
            g = [xf(c[key]) for c in good if key in c]
            b = [xf(c[key]) for c in bad if key in c]
            bw = max((hi - lo) / 5.0, 1e-12)
            best, best_score = None, -1e18
            for _ in range(self.n_candidates):
                center = self.rng.choice(g) if g else self.rng.uniform(lo, hi)
                x = min(max(self.rng.gauss(center, bw), lo), hi)
                score = self._kde_logpdf(x, g, bw) - self._kde_logpdf(x, b, bw)
                if score > best_score:
                    best, best_score = x, score
            out = math.exp(best) if log else best
            return int(round(out)) if isinstance(dom, Randint) else out
        return dom  # fixed value

    def suggest(self, trial_id: str) -> Optional[dict]:
        if self._emitted >= self.num_samples:
            return None
        self._emitted += 1
        if len(self._values) < self.n_startup:
            cfg = {k: (v.sample(self.rng) if isinstance(v, Domain) else v)
                   for k, v in self.param_space.items()}
        else:
            good, bad = self._split()
            cfg = {k: (self._suggest_param(k, v, good, bad)
                       if isinstance(v, Domain) else v)
                   for k, v in self.param_space.items()}
        self._configs[trial_id] = dict(cfg)
        return cfg


class OptunaSearch(Searcher):
    """Adapter for optuna samplers (reference: tune/search/optuna/).

    Optional dependency: raises a clear ImportError at construction when
    optuna isn't installed (it is not part of this image)."""

    def __init__(self, param_space: dict, metric: str = "loss",
                 mode: str = "min", num_samples: int = 64, sampler=None):
        try:
            import optuna  # noqa: F401
        except ImportError as e:
            raise ImportError(
                "OptunaSearch requires the 'optuna' package; use the native "
                "TPESearcher for an equivalent built-in sampler"
            ) from e
        import optuna

        _reject_grid(param_space, "OptunaSearch")
        self.param_space = param_space
        self.metric, self.mode = metric, mode
        self.num_samples = num_samples
        self._study = optuna.create_study(
            direction="minimize" if mode == "min" else "maximize",
            sampler=sampler)
        self._trials: dict[str, object] = {}
        self._best: dict[str, float] = {}
        self._emitted = 0

    def _flush_tells(self) -> None:
        """Report each buffered trial's BEST value to the study. Deferred to
        suggestion time because the tuner calls on_trial_complete per report
        and optuna accepts exactly one tell per trial — telling the first
        report would train the sampler on warm-up noise."""
        for tid, best in list(self._best.items()):
            t = self._trials.pop(tid, None)
            if t is not None:
                try:
                    self._study.tell(t, best)
                except Exception:
                    pass
            self._best.pop(tid, None)

    def suggest(self, trial_id: str) -> Optional[dict]:
        if self._emitted >= self.num_samples:
            return None
        self._flush_tells()
        self._emitted += 1
        t = self._study.ask()
        cfg = {}
        for k, v in self.param_space.items():
            if isinstance(v, Uniform):
                cfg[k] = t.suggest_float(k, v.low, v.high)
            elif isinstance(v, LogUniform):
                cfg[k] = t.suggest_float(k, v.low, v.high, log=True)
            elif isinstance(v, Randint):
                cfg[k] = t.suggest_int(k, v.low, v.high - 1)
            elif isinstance(v, Choice):
                cfg[k] = t.suggest_categorical(k, v.options)
            else:
                cfg[k] = v
        self._trials[trial_id] = t
        return cfg

    def on_trial_complete(self, trial_id: str, result: dict | None) -> None:
        if trial_id not in self._trials or not result or self.metric not in result:
            return
        v = float(result[self.metric])
        cur = self._best.get(trial_id)
        better = (min if self.mode == "min" else max)
        self._best[trial_id] = v if cur is None else better(cur, v)
