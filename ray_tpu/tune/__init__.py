"""ray_tpu.tune: hyperparameter tuning over trial actors.

Parity surface: ray.tune (Tuner, tune.run, search spaces, ASHA, PBT) —
reference python/ray/tune/.
"""

from ray_tpu.tune.schedulers import (
    PB2,
    ASHAScheduler,
    FIFOScheduler,
    MedianStoppingRule,
    PopulationBasedTraining,
    TrialScheduler,
    create_bohb,
)
from ray_tpu.tune.search import (
    BasicVariantGenerator,
    OptunaSearch,
    Searcher,
    TPESearcher,
    choice,
    grid_search,
    loguniform,
    randint,
    uniform,
)
from ray_tpu.tune.tuner import ResultGrid, TrialResult, TuneConfig, Tuner, run

__all__ = [
    "Tuner", "TuneConfig", "ResultGrid", "TrialResult", "run",
    "uniform", "loguniform", "randint", "choice", "grid_search",
    "BasicVariantGenerator", "Searcher", "TPESearcher", "OptunaSearch",
    "ASHAScheduler", "PopulationBasedTraining", "FIFOScheduler", "TrialScheduler",
    "MedianStoppingRule", "PB2", "create_bohb",
]

from ray_tpu._private.usage_stats import record_library_usage as _rec

_rec("tune")
del _rec
