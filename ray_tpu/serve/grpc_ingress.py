"""gRPC ingress for serve deployments.

Parity: the reference's gRPCProxy (serve/_private/proxy.py:527) — a second
ingress protocol next to HTTP, routing to the same deployment handles. The
wire contract is proto-free (generic byte handlers, JSON payloads) so no
protoc step is needed:

- /ray_tpu.serve.Serve/Predict : unary-unary. Request bytes = JSON
  {"route": "/prefix", "body": {...}}; response bytes = JSON result.
- /ray_tpu.serve.Serve/Stream  : unary-stream. Same request; one JSON frame
  per yielded item of the deployment's streaming method
  (body["stream_method"], default "stream_tokens").

Errors surface as gRPC status INTERNAL/NOT_FOUND with the message.
"""

from __future__ import annotations

import json
import threading
from concurrent.futures import ThreadPoolExecutor
from typing import Optional

import ray_tpu

SERVICE = "ray_tpu.serve.Serve"


def _identity(b: bytes) -> bytes:
    return b


class GrpcProxy:
    def __init__(self, host: str = "127.0.0.1", port: int = 9000):
        import grpc

        self.host, self.port = host, port
        self._grpc = grpc

        def match(path: str):
            from ray_tpu.serve.api import _match_route

            return _match_route(path)

        def parse(request: bytes, context):
            try:
                payload = json.loads(request)
                if not isinstance(payload, dict):
                    raise ValueError("request must be a JSON object")
                return payload.get("route", "/"), payload.get("body", {})
            except (json.JSONDecodeError, UnicodeDecodeError, ValueError) as e:
                context.abort(grpc.StatusCode.INVALID_ARGUMENT,
                              f"invalid JSON request: {e}")

        def predict(request: bytes, context) -> bytes:
            route, body = parse(request, context)
            prefix, handle = match(route)
            if handle is None:
                context.abort(grpc.StatusCode.NOT_FOUND, f"no route for {route!r}")
            try:
                result = ray_tpu.get(handle.remote(body), timeout=120)
                return json.dumps({"result": result}).encode()
            except Exception as e:  # noqa: BLE001
                context.abort(grpc.StatusCode.INTERNAL, str(e)[:500])

        def stream(request: bytes, context):
            route, body = parse(request, context)
            prefix, handle = match(route)
            if handle is None:
                context.abort(grpc.StatusCode.NOT_FOUND, f"no route for {route!r}")
            method = body.get("stream_method", "stream_tokens")
            it = handle.stream(body, method_name=method)
            try:
                for item in it:
                    yield json.dumps({"item": item}).encode()
            except Exception as e:  # noqa: BLE001
                context.abort(grpc.StatusCode.INTERNAL, str(e)[:500])
            finally:
                it.close()

        handlers = grpc.method_handlers_generic_handler(SERVICE, {
            "Predict": grpc.unary_unary_rpc_method_handler(
                predict, request_deserializer=_identity, response_serializer=_identity
            ),
            "Stream": grpc.unary_stream_rpc_method_handler(
                stream, request_deserializer=_identity, response_serializer=_identity
            ),
        })
        self._server = grpc.server(ThreadPoolExecutor(max_workers=32))
        self._server.add_generic_rpc_handlers((handlers,))
        if self._server.add_insecure_port(f"{host}:{port}") == 0:
            raise RuntimeError(f"gRPC proxy failed to bind {host}:{port}")
        self._server.start()

    def stop(self, grace: float = 1.0) -> None:
        self._server.stop(grace)


def grpc_predict(address: str, route: str, body: dict, timeout: float = 120.0) -> dict:
    """Client helper for the proto-free contract."""
    import grpc

    with grpc.insecure_channel(address) as channel:
        call = channel.unary_unary(
            f"/{SERVICE}/Predict",
            request_serializer=_identity, response_deserializer=_identity,
        )
        out = call(json.dumps({"route": route, "body": body}).encode(), timeout=timeout)
    return json.loads(out)


def grpc_stream(address: str, route: str, body: dict, timeout: float = 120.0):
    import grpc

    with grpc.insecure_channel(address) as channel:
        call = channel.unary_stream(
            f"/{SERVICE}/Stream",
            request_serializer=_identity, response_deserializer=_identity,
        )
        for frame in call(json.dumps({"route": route, "body": body}).encode(),
                          timeout=timeout):
            yield json.loads(frame)
