"""SLO-driven deployment autoscaling (ISSUE 17).

The controller's stock autoscaling loop scales on router-reported queue
depth. This scaler is the telemetry-plane alternative for deployments that
declared ``slo_ttft_ms`` and opted in with
``AutoscalingConfig(policy="slo")``: each tick it reads the head's
predicted-TTFT estimator (anatomy's per-replica gauge, rolled up to the
worst replica per deployment) and compares it to the SLO.

- Sustained breach (predicted > SLO for ``upscale_delay_s`` — hysteresis)
  scales the target UP one replica, bounded by ``max_replicas``, with
  ``upscale_delay_s`` also serving as the cooldown between steps.
- Sustained clearance (predicted < SLO x DOWNSCALE_FRACTION for
  ``downscale_delay_s``) scales DOWN toward ``min_replicas`` after the same
  cooldown discipline.
- Every scale-up registers STANDING DEMAND with the cluster autoscaler
  (the PR-10 hook): the deficit's replica resource shapes are visible to
  ``get_pending_demand`` immediately, so nodes can be arriving while the
  new replicas are still queued on the scheduler. Demand clears once
  running replicas catch the target.

Actuation is one controller RPC per scale decision
(``set_target_replicas``); the reconcile loop does the spawning. Listeners
(``add_listener``) fire on every decision — the event-driven seam tests and
dashboards consume.
"""

from __future__ import annotations

import os
import threading
import time

import ray_tpu

_NEVER = -float("inf")


def _default_predicted() -> dict:
    from ray_tpu.serve import anatomy

    return anatomy.predicted_ttft_by_deployment()


class DeploymentAutoscaler:
    DOWNSCALE_FRACTION = 0.5  # clear = predicted below this fraction of SLO

    def __init__(self, controller, *, tick_s: float | None = None,
                 predicted_fn=None, view_fn=None, actuate_fn=None,
                 now_fn=time.monotonic):
        self._controller = controller
        if tick_s is None:
            try:
                tick_s = float(os.environ.get(
                    "RAY_TPU_SERVE_AUTOSCALE_TICK_S", 1.0))
            except (TypeError, ValueError):
                tick_s = 1.0
        self.tick_s = tick_s
        # seams (tests inject signals; defaults read the live planes)
        self._predicted = predicted_fn or _default_predicted
        self._view = view_fn or self._controller_view
        self._actuate = actuate_fn or self._controller_actuate
        self._now = now_fn
        self._state: dict[str, dict] = {}
        self._listeners: list = []
        self._demand_keys: set[str] = set()
        self._lock = threading.Lock()
        self._stop_evt = threading.Event()
        self._thread: threading.Thread | None = None

    # ---- default signal/actuation plumbing (slow path: RPC per tick) ----
    def _controller_view(self) -> dict:
        return ray_tpu.get(self._controller.autoscale_view.remote(),
                           timeout=5)

    def _controller_actuate(self, dep: str, target: int) -> None:
        ray_tpu.get(self._controller.set_target_replicas.remote(dep, target),
                    timeout=5)

    # ---- listeners (event-driven consumers: tests, dashboards) ----
    def add_listener(self, cb) -> None:
        """cb(deployment, action, target) on every scale decision
        (action: "scale_up" | "scale_down")."""
        with self._lock:
            self._listeners.append(cb)

    def _notify(self, dep: str, action: str, target: int) -> None:
        with self._lock:
            listeners = list(self._listeners)
        for cb in listeners:
            try:
                cb(dep, action, target)
            except Exception:
                pass

    # ---- standing demand (PR-10 cluster-autoscaler hook) ----
    def _register_demand(self, dep: str, shape: dict, deficit: int) -> None:
        if deficit <= 0:
            return
        try:
            from ray_tpu.autoscaler.autoscaler import register_standing_demand

            key = f"serve:{dep}"
            register_standing_demand(key, [dict(shape)] * deficit)
            with self._lock:
                self._demand_keys.add(key)
        except Exception:
            pass  # no cluster autoscaler wired: scaling still proceeds

    def _clear_demand(self, dep: str) -> None:
        key = f"serve:{dep}"
        with self._lock:
            if key not in self._demand_keys:
                return
            self._demand_keys.discard(key)
        try:
            from ray_tpu.autoscaler.autoscaler import clear_standing_demand

            clear_standing_demand(key)
        except Exception:
            pass

    # ---- the decision loop ----
    def _dep_state(self, dep: str) -> dict:
        st = self._state.get(dep)
        if st is None:
            st = self._state[dep] = {
                "breach_since": None, "clear_since": None,
                "last_scale": _NEVER, "predicted_ttft_ms": None,
            }
        return st

    def tick(self) -> None:
        try:
            view = self._view()
            pred_map = self._predicted()
        except Exception:
            return  # controller briefly unavailable: skip the tick
        now = self._now()
        for dep, ent in view.items():
            auto = ent.get("autoscaling")
            if not auto or ent.get("policy") != "slo":
                continue
            slo = ent.get("slo_ttft_ms")
            if slo is None:
                continue
            st = self._dep_state(dep)
            pred = pred_map.get(dep)
            st["predicted_ttft_ms"] = pred
            target = ent["target_replicas"]
            running = ent["running_replicas"]
            lo, hi = auto["min_replicas"], auto["max_replicas"]
            breach = pred is not None and pred > float(slo)
            clear = (pred is None
                     or pred < float(slo) * self.DOWNSCALE_FRACTION)
            if breach:
                st["clear_since"] = None
                if st["breach_since"] is None:
                    st["breach_since"] = now
                sustained = now - st["breach_since"] >= auto["upscale_delay_s"]
                cooled = now - st["last_scale"] >= auto["upscale_delay_s"]
                if sustained and cooled and target < hi:
                    new = target + 1
                    try:
                        self._actuate(dep, new)
                    except Exception:
                        continue
                    st["last_scale"] = now
                    st["breach_since"] = now  # re-arm: fresh window per step
                    self._register_demand(dep, ent.get("replica_shape") or {},
                                          new - running)
                    self._notify(dep, "scale_up", new)
                    continue
            elif clear:
                st["breach_since"] = None
                if st["clear_since"] is None:
                    st["clear_since"] = now
                sustained = (now - st["clear_since"]
                             >= auto["downscale_delay_s"])
                cooled = now - st["last_scale"] >= auto["downscale_delay_s"]
                if sustained and cooled and target > lo:
                    new = target - 1
                    try:
                        self._actuate(dep, new)
                    except Exception:
                        continue
                    st["last_scale"] = now
                    st["clear_since"] = now
                    self._notify(dep, "scale_down", new)
            else:
                # between the breach line and the clear line: neither
                # window accumulates (hysteresis band)
                st["breach_since"] = None
                st["clear_since"] = None
            if running >= target:
                self._clear_demand(dep)

    def view(self) -> dict:
        with self._lock:
            demand = sorted(self._demand_keys)
        return {
            "tick_s": self.tick_s,
            "deployments": {d: dict(st) for d, st in self._state.items()},
            "standing_demand": demand,
            "running": self._thread is not None and self._thread.is_alive(),
        }

    # ---- lifecycle ----
    def start(self) -> "DeploymentAutoscaler":
        if self._thread is None or not self._thread.is_alive():
            self._stop_evt.clear()
            self._thread = threading.Thread(target=self._loop, daemon=True,
                                            name="serve-slo-autoscaler")
            self._thread.start()
        return self

    def _loop(self) -> None:
        while not self._stop_evt.wait(self.tick_s):
            try:
                self.tick()
            except Exception:
                pass

    def stop(self) -> None:
        self._stop_evt.set()
        for dep in list(self._state):
            self._clear_demand(dep)
