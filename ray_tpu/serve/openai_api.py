"""OpenAI-compatible ingress for the LLM engine.

Parity: ray.serve.llm `build_openai_app` + the OpenAI-compatible HTTP surface
(python/ray/llm/_internal/serve/core/ingress/ — /v1/completions,
/v1/chat/completions, /v1/models; streaming via SSE chunks terminated by
`data: [DONE]`). The engine is the native continuous-batching TPU engine
(serve/llm.py), not a vLLM delegation.

Tokenization is pluggable: pass any object with encode(str)->list[int] and
decode(list[int])->str (e.g. a HuggingFace tokenizer); the default is a
hermetic byte-level tokenizer so the API surface works without model assets.
"""

from __future__ import annotations

import json
import time
import uuid
from typing import TYPE_CHECKING, Any, Optional

import ray_tpu
from ray_tpu.serve.deployment import deployment as _deployment

if TYPE_CHECKING:
    from ray_tpu.serve.llm import LLMConfig

# Deployments that opted into the OpenAI proxy surface (the proxy only
# dispatches /v1-style method routing for names registered here; arbitrary
# apps keep their plain __call__ routing).
OPENAI_DEPLOYMENT_NAMES: set[str] = {"OpenAIServer"}


class ByteTokenizer:
    """Hermetic fallback tokenizer: UTF-8 bytes shifted past special ids.
    Ids beyond the byte range fold back into it (random-weight demo mode
    samples from the full model vocab)."""

    OFFSET = 3  # 0=pad, 1=bos, 2=eos

    def encode(self, text: str) -> list[int]:
        return [b + self.OFFSET for b in text.encode("utf-8")]

    def decode(self, ids: list[int]) -> str:
        data = bytes((i - self.OFFSET) % 256 for i in ids if i >= self.OFFSET)
        return data.decode("utf-8", errors="replace")


def _render_chat(messages: list[dict]) -> str:
    """Minimal chat template (reference: chat templates live with the model;
    this is the fallback rendering)."""
    parts = [f"{m.get('role', 'user')}: {m.get('content', '')}" for m in messages]
    parts.append("assistant:")
    return "\n".join(parts)


def build_openai_app(config: "LLMConfig | None" = None, *,
                     model_id: str = "ray-tpu-llm",
                     tokenizer=None, num_replicas: int = 1):
    """An OpenAI-API-shaped deployment over the native engine
    (reference: ray.serve.llm build_openai_app). jax-heavy imports stay inside
    this builder so `import ray_tpu.serve` never pays them."""
    from ray_tpu.serve.llm import LLMConfig, LLMEngine

    cfg = config or LLMConfig()
    tok = tokenizer or ByteTokenizer()

    @_deployment(name="OpenAIServer", num_replicas=num_replicas,
                 ray_actor_options={"num_tpus": 0.0}, max_ongoing_requests=64)
    class OpenAIServer:
        def __init__(self, llm_config, tokenizer, model_id: str):
            from ray_tpu.serve.llm import LLMEngine as _Dense
            from ray_tpu.serve.llm_paged import PagedLLMConfig, PagedLLMEngine

            _Engine = PagedLLMEngine if isinstance(llm_config, PagedLLMConfig) else _Dense
            self.engine = _Engine(llm_config)
            self.tok = tokenizer
            self.model_id = model_id

        # ---- OpenAI surface ----
        def models(self, body: dict | None = None) -> dict:
            return {
                "object": "list",
                "data": [{"id": self.model_id, "object": "model",
                          "owned_by": "ray_tpu"}],
            }

        def completions(self, body: dict) -> dict:
            prompt = body.get("prompt", "")
            if isinstance(prompt, list):
                prompt = "".join(prompt)
            ids = self.tok.encode(prompt)
            res = self.engine.generate_sync(ids, body.get("max_tokens"))
            text = self.tok.decode(res.token_ids)
            return {
                "id": f"cmpl-{uuid.uuid4().hex[:24]}",
                "object": "text_completion",
                "created": int(time.time()),
                "model": body.get("model", self.model_id),
                "choices": [{
                    "index": 0,
                    "text": text,
                    "finish_reason": res.finish_reason,
                    "logprobs": None,
                }],
                "usage": {
                    "prompt_tokens": res.num_prompt_tokens,
                    "completion_tokens": res.num_generated,
                    "total_tokens": res.num_prompt_tokens + res.num_generated,
                },
            }

        def chat_completions(self, body: dict) -> dict:
            prompt = _render_chat(body.get("messages", []))
            ids = self.tok.encode(prompt)
            res = self.engine.generate_sync(ids, body.get("max_tokens"))
            text = self.tok.decode(res.token_ids)
            return {
                "id": f"chatcmpl-{uuid.uuid4().hex[:24]}",
                "object": "chat.completion",
                "created": int(time.time()),
                "model": body.get("model", self.model_id),
                "choices": [{
                    "index": 0,
                    "message": {"role": "assistant", "content": text},
                    "finish_reason": res.finish_reason,
                }],
                "usage": {
                    "prompt_tokens": res.num_prompt_tokens,
                    "completion_tokens": res.num_generated,
                    "total_tokens": res.num_prompt_tokens + res.num_generated,
                },
            }

        def _stream_deltas(self, ids: list[int], max_tokens, body=None):
            """Incremental detokenization: decode the WHOLE generated id list
            each step and emit the text delta, holding back a trailing
            partial character (multi-byte/multi-token chars must not split
            into replacement chars across chunks — vLLM's incremental
            detokenizer behavior)."""
            from ray_tpu.serve import anatomy

            arid = anatomy.rid_of(body)
            generated: list[int] = []
            emitted = ""
            for tok_id in self.engine.generate_stream(ids, max_tokens):
                if arid is not None:
                    # replica-clock first-token stamp: closest observer to
                    # the engine, beats the proxy's first-SSE-frame clock
                    anatomy.stamp(arid, "decode_first_token",
                                  anatomy.now_wall())
                    arid = None
                generated.append(int(tok_id))
                text = self.tok.decode(generated)
                if text.endswith("�"):
                    text = text[:-1]  # maybe-incomplete char: wait one token
                if len(text) > len(emitted):
                    delta, emitted = text[len(emitted):], text
                    yield delta
            final = self.tok.decode(generated)
            if len(final) > len(emitted):
                yield final[len(emitted):]

        def chat_completions_stream(self, body: dict):
            """Generator of OpenAI chat chunks (SSE frames at the proxy)."""
            rid = f"chatcmpl-{uuid.uuid4().hex[:24]}"
            prompt = _render_chat(body.get("messages", []))
            ids = self.tok.encode(prompt)
            for delta in self._stream_deltas(ids, body.get("max_tokens"),
                                             body):
                yield {
                    "id": rid,
                    "object": "chat.completion.chunk",
                    "created": int(time.time()),
                    "model": body.get("model", self.model_id),
                    "choices": [{
                        "index": 0,
                        "delta": {"content": delta},
                        "finish_reason": None,
                    }],
                }
            yield {
                "id": rid,
                "object": "chat.completion.chunk",
                "created": int(time.time()),
                "model": body.get("model", self.model_id),
                "choices": [{"index": 0, "delta": {}, "finish_reason": "stop"}],
            }

        def completions_stream(self, body: dict):
            rid = f"cmpl-{uuid.uuid4().hex[:24]}"
            prompt = body.get("prompt", "")
            if isinstance(prompt, list):
                prompt = "".join(prompt)
            ids = self.tok.encode(prompt)
            for delta in self._stream_deltas(ids, body.get("max_tokens"),
                                             body):
                yield {
                    "id": rid,
                    "object": "text_completion",
                    "created": int(time.time()),
                    "model": body.get("model", self.model_id),
                    "choices": [{"index": 0, "text": delta, "finish_reason": None}],
                }
            yield {
                "id": rid,
                "object": "text_completion",
                "created": int(time.time()),
                "model": body.get("model", self.model_id),
                "choices": [{"index": 0, "text": "", "finish_reason": "stop"}],
            }

        def stats(self) -> dict:
            return self.engine.stats()

    return OpenAIServer.bind(cfg, tok, model_id)
