"""serve public API: run/delete/shutdown/status + HTTP ingress.

Parity: python/ray/serve/api.py (serve.run :930, serve.delete, serve.status,
serve.shutdown) and the per-node HTTP proxy (_private/proxy.py:1010 HTTPProxy) —
here a single aiohttp ingress bound to the controller's route table.
"""

from __future__ import annotations

import asyncio
import json
import os
import threading
import time
from typing import Optional

import ray_tpu
from ray_tpu.serve.controller import CONTROLLER_NAME, DeploymentHandle, ServeController
from ray_tpu.serve.deployment import Application

_state: dict = {"controller": None, "proxy": None, "routes": {}}
_STREAM_END = object()
_lock = threading.Lock()

# OpenAI surface: subpath under a route -> (method, streaming method)
_OPENAI_METHODS = {
    "completions": ("completions", "completions_stream"),
    "chat/completions": ("chat_completions", "chat_completions_stream"),
    "models": ("models", None),
}


def _get_or_create_controller():
    from ray_tpu.core.runtime import get_runtime

    with _lock:
        rt = get_runtime()
        if _state.get("_rt") is not rt:
            # a new session started (possibly resumed from persistence):
            # cached handles point at the dead runtime; stop the old proxy so
            # its port is released instead of serving dead handles
            for key in ("proxy", "grpc_proxy"):
                old = _state.get(key)
                if old is not None:
                    try:
                        old.stop()
                    except Exception:
                        pass
            _state.update(controller=None, proxy=None, grpc_proxy=None,
                          routes={}, _rt=rt)
        if _state["controller"] is None:
            try:
                _state["controller"] = ray_tpu.get_actor(CONTROLLER_NAME)
            except ValueError:
                # detached + named: with gcs_storage_path set, the controller
                # is re-created on resume and self-heals apps from its KV
                # checkpoint (reference: controller.py:133 crash recovery)
                cls = ray_tpu.remote(num_cpus=0, max_concurrency=16)(ServeController)
                _state["controller"] = cls.options(
                    name=CONTROLLER_NAME, get_if_exists=True, lifetime="detached"
                ).remote()
        return _state["controller"]


def run(app: Application, *, name: str = "default", route_prefix: str | None = "/",
        blocking: bool = False) -> DeploymentHandle:
    """Deploy an application and return its handle (reference: serve.run api.py:930)."""
    if not ray_tpu.is_initialized():
        ray_tpu.init(ignore_reinit_error=True)
    controller = _get_or_create_controller()
    dep = app.deployment
    prefix = dep.config.route_prefix or route_prefix
    if prefix:
        # validate against the CONTROLLER's route table (authoritative — it
        # includes routes restored from a checkpoint), before deploying so a
        # conflict doesn't leave orphan replicas
        bound = ray_tpu.get(controller.get_routes.remote()).get(prefix)
        if bound is not None and bound != dep.config.name:
            raise ValueError(
                f"Route prefix {prefix!r} is already bound to deployment "
                f"'{bound}'; pass a distinct route_prefix."
            )
    ray_tpu.get(controller.deploy.remote(dep, prefix))
    handle = DeploymentHandle(controller, dep.config.name)
    if prefix:
        with _lock:
            _state["routes"] = {**_state["routes"], prefix: handle}
    # wait for at least one replica
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        if ray_tpu.get(controller.get_replicas.remote(dep.config.name)):
            break
        time.sleep(0.05)
    if blocking:
        try:
            while True:
                time.sleep(1)
        except KeyboardInterrupt:
            pass
    return handle


def get_deployment_handle(name: str) -> DeploymentHandle:
    """Handle to an already-deployed deployment — e.g. after a resumed session
    restored the controller from its checkpoint (reference:
    serve.get_deployment_handle / get_app_handle)."""
    controller = _get_or_create_controller()
    if name not in ray_tpu.get(controller.get_deployment_names.remote()):
        raise ValueError(f"Deployment {name!r} not found")
    return DeploymentHandle(controller, name)


def delete(name: str) -> None:
    controller = _get_or_create_controller()
    ray_tpu.get(controller.delete_deployment.remote(name))
    _state["routes"] = {p: h for p, h in _state["routes"].items() if h.deployment_name != name}


def status() -> dict:
    controller = _get_or_create_controller()
    return ray_tpu.get(controller.status.remote())


def shutdown() -> None:
    try:
        from ray_tpu.serve.front_door import stop_front_door

        stop_front_door()
    except Exception:
        pass
    stop_proxies()
    with _lock:
        c = _state["controller"]
        if c is not None:
            try:
                ray_tpu.get(c.shutdown.remote(), timeout=10)
                ray_tpu.kill(c)
            except Exception:
                pass
            _state["controller"] = None
        if _state["proxy"] is not None:
            _state["proxy"].stop()
            _state["proxy"] = None
        if _state.get("grpc_proxy") is not None:
            _state["grpc_proxy"].stop()
            _state["grpc_proxy"] = None
        _state["routes"] = {}


def _ntokens_of(result) -> int:
    """Generated-token count from the common reply shapes (PD/LLM bodies
    and OpenAI objects both carry usage.completion_tokens)."""
    if isinstance(result, dict):
        usage = result.get("usage")
        if isinstance(usage, dict):
            try:
                return int(usage.get("completion_tokens") or 0)
            except (TypeError, ValueError):
                return 0
        if isinstance(result.get("token_ids"), (list, tuple)):
            return len(result["token_ids"])
    return 0


async def _await_ref(ref, timeout: float, executor=None):
    """Await an ObjectRef on the reactor: the runtime's future-based get
    parks NO thread per in-flight request (reference: the asyncio router of
    serve/_private/router.py:614 — replica replies resolve on the event
    loop). Falls back to an executor get for runtimes without get_async.
    ``executor`` bounds the blocking-get path: each parked get holds one
    worker, so the pool size IS the proxy's in-flight dispatch budget."""
    from ray_tpu.core.runtime import get_runtime
    from ray_tpu.dag import CompiledDAGRef

    rt = get_runtime()
    ga = getattr(rt, "get_async", None)
    # compiled-graph results live in the graph's result buffer, not the
    # object store — get_async only speaks ObjectRef, so compiled refs take
    # the executor path (ray_tpu.get dispatches on ref kind)
    if isinstance(ref, CompiledDAGRef):
        ga = None
    if ga is not None:
        try:
            return await asyncio.wait_for(asyncio.wrap_future(ga(ref)),
                                          timeout)
        except asyncio.TimeoutError as e:
            raise TimeoutError(f"request timed out after {timeout}s") from e
    loop = asyncio.get_running_loop()
    return await loop.run_in_executor(
        executor, lambda: ray_tpu.get(ref, timeout=timeout))


# ------------------------------------------------------------------ HTTP proxy
class HttpProxy:
    """aiohttp ingress: POST <route_prefix> with JSON body -> handle.remote(body).

    Reference: _private/proxy.py HTTPProxy:1010 (ASGI); routes resolved by
    longest matching prefix (proxy_router.py).
    """

    def __init__(self, host: str = "127.0.0.1", port: int = 8000,
                 route_lookup=None, admission=None):
        from concurrent.futures import ThreadPoolExecutor

        self.host = host
        self.port = port
        # pluggable router: per-node proxy actors resolve routes against
        # their own controller-synced table instead of this process's _state
        self._route_lookup = route_lookup
        # pluggable admission gate (serve/admission.py): called with the
        # deployment name BEFORE anatomy.admit — a shed request never
        # creates a ledger, so it can't count against goodput. May block
        # (degrade-to-queue), so it runs on an executor, not the reactor.
        self._admission = admission
        self._loop = None
        self._runner = None
        # dedicated pool for long-lived SSE polls so streams can't starve the
        # default executor used by non-streaming requests
        self._stream_pool = ThreadPoolExecutor(max_workers=64, thread_name_prefix="sse")
        # per-proxy in-flight dispatch budget: every non-streaming request
        # whose result needs a blocking get (compiled-graph refs, runtimes
        # without get_async) parks one worker here until the replica
        # answers — the pool size is THE concurrency ceiling of this
        # ingress, and replicating ingresses (serve/front_door.py) is how
        # the fleet raises the aggregate budget
        self._dispatch_pool = ThreadPoolExecutor(
            max_workers=int(os.environ.get(
                "RAY_TPU_SERVE_INGRESS_CONCURRENCY", "8")),
            thread_name_prefix="dispatch")
        self._thread = threading.Thread(target=self._serve, daemon=True)
        self._started = threading.Event()
        self._thread.start()
        if not self._started.wait(10):
            raise RuntimeError("HTTP proxy failed to start")

    def _serve(self) -> None:
        from aiohttp import web

        async def handler(request: "web.Request") -> "web.Response":
            from ray_tpu.serve import anatomy

            route, handle = self._match(request.path)
            if handle is None:
                return web.json_response({"error": f"no route for {request.path}"}, status=404)
            try:
                body = await request.json() if request.can_read_body else {}
            except json.JSONDecodeError:
                return web.json_response({"error": "invalid JSON body"}, status=400)
            if self._admission is not None:
                loop = asyncio.get_running_loop()
                ok, reason = await loop.run_in_executor(
                    None, self._admission, handle.deployment_name)
                if not ok:
                    return web.json_response(
                        {"error": "shed", "reason": reason,
                         "deployment": handle.deployment_name},
                        status=503, headers={"Retry-After": "1"})
            # anatomy front door: the proxy admits the request (rid rides the
            # body through router -> replica -> engine) and, having admitted,
            # owns the completion record for both reply shapes below
            rid = anatomy.admit(body, handle.deployment_name)
            # OpenAI-compatible endpoints (reference: ray.serve.llm ingress,
            # llm/_internal/serve/core/ingress/): only for deployments that
            # opted into the surface (build_openai_app) — the subpath selects
            # the deployment method, responses are raw OpenAI objects.
            from ray_tpu.serve.openai_api import OPENAI_DEPLOYMENT_NAMES

            sub = request.path[len(route.rstrip("/")):].strip("/") if route else ""
            if sub in _OPENAI_METHODS and handle.deployment_name in OPENAI_DEPLOYMENT_NAMES:
                method, stream_method = _OPENAI_METHODS[sub]
                if isinstance(body, dict) and body.get("stream") and stream_method:
                    body = {**body, "stream_method": stream_method}
                    return await self._stream_response(request, handle, body)
                ref = getattr(handle, method).remote(body)
                try:
                    result = await _await_ref(ref, timeout=120,
                                               executor=self._dispatch_pool)
                except Exception as e:  # noqa: BLE001
                    if rid is not None:
                        anatomy.complete(rid, handle.deployment_name,
                                         ok=False, err=str(e)[:200])
                    return web.json_response(
                        {"error": {"message": str(e)[:500], "type": type(e).__name__}},
                        status=500,
                    )
                if rid is not None:
                    anatomy.complete(rid, handle.deployment_name,
                                     ntokens=_ntokens_of(result))
                return web.json_response(result)
            if isinstance(body, dict) and body.get("stream"):
                return await self._stream_response(request, handle, body)
            ref = handle.remote(body)
            try:
                result = await _await_ref(ref, timeout=60,
                                           executor=self._dispatch_pool)
            except Exception as e:  # noqa: BLE001
                if rid is not None:
                    anatomy.complete(rid, handle.deployment_name,
                                     ok=False, err=str(e)[:200])
                return web.json_response({"error": str(e)[:500]}, status=500)
            if rid is not None:
                anatomy.complete(rid, handle.deployment_name,
                                 ntokens=_ntokens_of(result))
            if isinstance(result, (dict, list, str, int, float)) or result is None:
                return web.json_response({"result": result})
            return web.json_response({"result": repr(result)})

        async def start():
            app = web.Application()
            app.router.add_route("*", "/{tail:.*}", handler)
            self._runner = web.AppRunner(app)
            await self._runner.setup()
            site = web.TCPSite(self._runner, self.host, self.port)
            await site.start()
            if self.port == 0:  # read back the OS-assigned ephemeral port
                self.port = site._server.sockets[0].getsockname()[1]
            self._started.set()

        self._loop = asyncio.new_event_loop()
        asyncio.set_event_loop(self._loop)
        self._loop.run_until_complete(start())
        self._loop.run_forever()

    async def _stream_response(self, request, handle, body):
        """Server-sent events: one `data:` frame per yielded item
        (reference: serve streaming responses through the proxy)."""
        from aiohttp import web

        from ray_tpu.serve import anatomy

        resp = web.StreamResponse(headers={
            "Content-Type": "text/event-stream",
            "Cache-Control": "no-cache",
        })
        await resp.prepare(request)
        loop = asyncio.get_running_loop()
        method = body.get("stream_method", "stream_tokens")
        rid = anatomy.rid_of(body)
        it = handle.stream(body, method_name=method)
        nframes = 0
        err = None

        def next_item():
            try:
                return next(it)
            except StopIteration:
                return _STREAM_END

        try:
            while True:
                try:
                    item = await loop.run_in_executor(self._stream_pool, next_item)
                except Exception as e:  # noqa: BLE001 - stream errors become frames
                    err = str(e).splitlines()[-1][:200] if str(e) else type(e).__name__
                    await resp.write(f"data: {json.dumps({'error': err})}\n\n".encode())
                    break
                if item is _STREAM_END:
                    break
                if nframes == 0 and rid is not None:
                    # front-door first-token clock; an engine-side stamp
                    # (earlier, more precise) folds over this one when the
                    # replica's push beat lands
                    anatomy.stamp(rid, "decode_first_token",
                                  anatomy.now_wall())
                nframes += 1
                await resp.write(f"data: {json.dumps(item)}\n\n".encode())
            await resp.write(b"data: [DONE]\n\n")
            await resp.write_eof()
        except (ConnectionError, ConnectionResetError, asyncio.CancelledError):
            err = err or "client_disconnected"
        finally:
            it.close()  # releases the router's in-flight slot (GeneratorExit)
            if rid is not None:
                anatomy.complete(rid, handle.deployment_name,
                                 ntokens=nframes, ok=err is None, err=err)
        return resp

    def _match(self, path: str):
        if self._route_lookup is not None:
            return self._route_lookup(path)
        return _match_route(path)

    def stop(self) -> None:
        if self._loop is None:
            return

        async def _teardown():
            if self._runner is not None:
                await self._runner.cleanup()  # closes the listening socket
            self._loop.stop()

        try:
            fut = asyncio.run_coroutine_threadsafe(_teardown(), self._loop)
            fut.result(timeout=5)
        except Exception:
            self._loop.call_soon_threadsafe(self._loop.stop)


def _match_route(path: str, routes: dict | None = None):
    """Longest-prefix route match (shared by the HTTP and gRPC ingresses and
    the per-node proxy actors — reference: proxy_router.py)."""
    best = None
    # snapshot: run()/delete() rebind the dict rather than mutating it
    for prefix, handle in list((_state["routes"] if routes is None else routes).items()):
        if path == prefix or path.startswith(prefix.rstrip("/") + "/") or prefix == "/":
            if best is None or len(prefix) > len(best[0]):
                best = (prefix, handle)
    return best if best else (None, None)


def start_http_proxy(host: str = "127.0.0.1", port: int = 8000) -> HttpProxy:
    with _lock:
        if _state["proxy"] is None:
            _state["proxy"] = HttpProxy(host, port)
        return _state["proxy"]


class _ProxyActor:
    """One ingress per placement (reference: _private/proxy.py — a proxy
    ACTOR on every node, any node's address serves traffic). Runs in its own
    process (isolate_process) with a controller-synced route table; requests
    route to replicas through deployment handles over the worker's client
    runtime, so the data plane no longer funnels through the head's single
    aiohttp loop."""

    def __init__(self, port: int = 0, host: str = "127.0.0.1",
                 refresh_s: float = 10.0):
        import ray_tpu as _ray

        self._controller = _ray.get_actor(CONTROLLER_NAME)
        self._routes: dict = {}
        self._refresh_s = refresh_s
        self._stop = threading.Event()
        self._sync()  # serve correctly from the first request
        # Long-poll equivalent: the controller PUSHES route-table updates
        # over pubsub (reference: long_poll.py:318 LongPollHost); the
        # periodic sync is only a slow self-heal fallback now.
        self._sub = None
        try:
            from ray_tpu.experimental import pubsub

            self._sub = pubsub.subscribe("serve:routes")
            threading.Thread(target=self._push_loop, daemon=True,
                             name="proxy-route-push").start()
        except Exception:
            pass
        threading.Thread(target=self._sync_loop, daemon=True,
                         name="proxy-route-sync").start()
        self._proxy = HttpProxy(host, port, route_lookup=self._lookup)

    def _push_loop(self) -> None:
        while not self._stop.is_set():
            try:
                routes = self._sub.poll(timeout=1.0)
            except Exception:
                continue
            if routes is None:
                continue
            try:
                self._apply_routes(routes)
            except Exception:
                pass

    def _sync(self) -> None:
        self._apply_routes(ray_tpu.get(self._controller.get_routes.remote()))

    def _apply_routes(self, routes: dict) -> None:
        # Reuse existing handles: DeploymentHandle construction is expensive
        # (controller RPC + a router watcher thread that lives as long as the
        # handle) — rebuilding per refresh would leak a thread per route per
        # tick and reset the router's in-flight balancing counts.
        prev = self._routes
        new_table = {}
        for prefix, name in routes.items():
            cur = prev.get(prefix)
            if cur is not None and cur.deployment_name == name:
                new_table[prefix] = cur
            else:
                new_table[prefix] = DeploymentHandle(self._controller, name)
        self._routes = new_table

    def _sync_loop(self) -> None:
        while not self._stop.wait(self._refresh_s):
            try:
                self._sync()
            except Exception:
                pass  # controller briefly unavailable; keep the last table

    def _lookup(self, path: str):
        return _match_route(path, self._routes)

    def address(self) -> tuple:
        import socket as _socket

        host = self._proxy.host
        if host == "0.0.0.0":
            host = _socket.gethostbyname(_socket.gethostname())
        return (host, self._proxy.port)

    def ready(self) -> bool:
        return True

    def stop(self) -> None:
        self._stop.set()
        if self._sub is not None:
            try:
                self._sub.close()
            except Exception:
                pass
        self._proxy.stop()


def start_proxies(count: int = 2, base_port: int = 8100,
                  host: str = "127.0.0.1") -> list[tuple]:
    """Start `count` SPREAD-placed proxy actors (one per node when nodes are
    available) and return their (host, port) addresses. The reference runs
    exactly this shape: a proxy actor per node behind any load balancer.
    Binds loopback by default (reference HTTP ingress default); pass
    host="0.0.0.0" to expose the data plane. Safe to call again (names are
    unique per call); a failed boot is killed rather than leaked."""
    import uuid as _uuid

    if host in ("127.0.0.1", "localhost"):
        try:
            n_nodes = len(ray_tpu.nodes())
        except Exception:
            n_nodes = 1
        if n_nodes > 1:
            import warnings

            warnings.warn(
                "start_proxies(host='127.0.0.1') on a multi-node cluster: "
                "proxies placed on other nodes will only accept loopback "
                "traffic there; pass host='0.0.0.0' to serve cross-node "
                "ingress", stacklevel=2)

    addrs = []
    for i in range(count):
        actor = ray_tpu.remote(
            isolate_process=True, num_cpus=0.5,
            scheduling_strategy="SPREAD",
            name=f"SERVE_PROXY:{_uuid.uuid4().hex[:6]}:{i}",
        )(_ProxyActor).remote(port=base_port + i, host=host)
        with _lock:
            # registered BEFORE the readiness wait: a concurrent
            # stop_proxies/shutdown can always find (and kill) it
            _state.setdefault("proxy_actors", []).append(actor)
        try:
            ray_tpu.get(actor.ready.remote(), timeout=60)
            addrs.append(tuple(ray_tpu.get(actor.address.remote(), timeout=30)))
        except Exception:
            try:
                ray_tpu.kill(actor)
            except Exception:
                pass
            with _lock:
                acts = _state.get("proxy_actors", [])
                if actor in acts:
                    acts.remove(actor)
            raise
    return addrs


def stop_proxies() -> None:
    with _lock:
        actors = _state.pop("proxy_actors", [])
    for a in actors:
        try:
            ray_tpu.get(a.stop.remote(), timeout=10)
        except Exception:
            pass
        try:
            ray_tpu.kill(a)
        except Exception:
            pass


def start_grpc_proxy(host: str = "127.0.0.1", port: int = 9000):
    """gRPC ingress next to HTTP (reference: gRPCProxy proxy.py:527)."""
    from ray_tpu.serve.grpc_ingress import GrpcProxy

    with _lock:
        if _state.get("grpc_proxy") is None:
            _state["grpc_proxy"] = GrpcProxy(host, port)
        return _state["grpc_proxy"]
