"""KV-cache-aware request routing.

Parity: the reference's `llm/_internal/serve/routing_policies/kv_aware/`
routes requests to the replica whose paged-KV prefix cache most likely holds
the request's prompt prefix, so shared-prefix workloads (system prompts,
few-shot preambles, multi-turn chats) hit the cache instead of re-prefilling
on a random replica.

Design: the router tracks its own past routing decisions — block-aligned
prompt-prefix hashes map to the replica that last served them (the same
content-hash scheme as the engine's allocator, serve/paged_kv.py). On pick,
the replica holding the LONGEST matching prefix wins, unless its in-flight
depth exceeds the least-loaded replica by more than ``imbalance_tolerance``
(cache affinity must not defeat load balancing). No affinity → pow-2
fallback. State is router-local (no replica RPC on the hot path), sized by
an LRU bound.
"""

from __future__ import annotations

from collections import OrderedDict

from ray_tpu.serve.controller import Router


class KVAwareRouter(Router):
    KIND = "kv_aware"

    def __init__(self, controller, deployment_name: str, *, block_size: int = 16,
                 max_tracked_prefixes: int = 8192, imbalance_tolerance: int = 4):
        self.block_size = block_size
        self.max_tracked_prefixes = max_tracked_prefixes
        self.imbalance_tolerance = imbalance_tolerance
        # prefix hash -> replica key, LRU-ordered (most recent last)
        self._prefix_owner: "OrderedDict[int, str]" = OrderedDict()
        super().__init__(controller, deployment_name)

    # ---- hint extraction: token-id requests carry their prompt ----
    def _routing_hint(self, method_name: str, args, kwargs):
        body = args[0] if args else kwargs.get("body")
        if isinstance(body, dict):
            ids = body.get("prompt_ids")
            if isinstance(ids, (list, tuple)) and ids:
                return list(ids)
        return None

    def _block_hashes(self, prompt_ids: list) -> list[int]:
        """Cumulative content hashes of block-aligned prefixes (longest last),
        mirroring BlockPool.lookup_prefix's addressing."""
        out = []
        h = 0
        bs = self.block_size
        for i in range(0, len(prompt_ids) - len(prompt_ids) % bs, bs):
            h = hash((h, tuple(prompt_ids[i : i + bs])))
            out.append(h)
        return out

    def _select(self, hint):
        # called under self._lock with >=2 replicas
        if hint:
            live = {self._rkey(r): r for r in self._replicas}
            min_load = min(self._inflight.get(k, 0) for k in live)
            hashes = self._block_hashes(hint)
            for h in reversed(hashes):  # longest prefix first
                owner = self._prefix_owner.get(h)
                if owner is None or owner not in live:
                    continue
                if (self._inflight.get(owner, 0)
                        <= min_load + self.imbalance_tolerance):
                    self._prefix_owner.move_to_end(h)
                    self._claim(hashes, owner)
                    return live[owner]
                break  # affinity exists but the owner is overloaded: balance
            chosen = super()._select(None)
            self._claim(hashes, self._rkey(chosen))
            return chosen
        return super()._select(None)

    def _claim(self, hashes: list[int], replica_key: str) -> None:
        for h in hashes:
            self._prefix_owner[h] = replica_key
            self._prefix_owner.move_to_end(h)
        while len(self._prefix_owner) > self.max_tracked_prefixes:
            self._prefix_owner.popitem(last=False)


ROUTER_CLASSES = {"pow2": Router, "kv_aware": KVAwareRouter}


def make_router(kind: str, controller, deployment_name: str) -> Router:
    cls = ROUTER_CLASSES.get(kind)
    if cls is None:
        raise ValueError(
            f"unknown request_router {kind!r} (known: {sorted(ROUTER_CLASSES)})"
        )
    return cls(controller, deployment_name)
