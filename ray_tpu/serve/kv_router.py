"""KV-cache-aware request routing.

Parity: the reference's `llm/_internal/serve/routing_policies/kv_aware/`
routes requests to the replica whose paged-KV prefix cache most likely holds
the request's prompt prefix, so shared-prefix workloads (system prompts,
few-shot preambles, multi-turn chats) hit the cache instead of re-prefilling
on a random replica.

Design: the router tracks its own past routing decisions — block-aligned
prompt-prefix hashes map to the replica that last served them (the same
content-hash scheme as the engine's allocator, serve/paged_kv.py). On pick,
the replica holding the LONGEST matching prefix wins, unless its in-flight
depth exceeds the least-loaded replica by more than ``imbalance_tolerance``
(cache affinity must not defeat load balancing). No affinity → pow-2
fallback. State is router-local (no replica RPC on the hot path), sized by
an LRU bound; owners of removed replicas are pruned on refresh so dead
entries neither burn longest-prefix lookups nor pin LRU slots.

Decode-side placement (PD disaggregation, serve/pd.py): a request whose
body carries a KV-handoff descriptor is scored instead of prefix-matched —
``score = in-flight + node I/O pressure − locality bonus`` — so a handoff
is pulled to the least-loaded decode replica closest to the page holder.
I/O pressure folds in ``state.node_io_view()`` (pending pull bytes per
node, the PR-8 telemetry signal), sampled at most once a second.
"""

from __future__ import annotations

import random
import time
from collections import OrderedDict

import ray_tpu
from ray_tpu.serve.controller import Router


def _default_io_view() -> dict:
    from ray_tpu.util import state

    return state.node_io_view()


class KVAwareRouter(Router):
    KIND = "kv_aware"

    # pending pull bytes per unit of queue-depth-equivalent pressure: a node
    # with 32 MB of KV/object bytes in flight scores like one extra
    # in-flight request (capped so a saturated NIC can't dominate forever)
    IO_PRESSURE_SCALE = 32 << 20
    IO_PRESSURE_CAP = 4.0
    # replica->node map fetch cadence: node placement changes only when
    # replicas are (re)spawned, so this rides a slower clock than the base
    # 0.5s replica refresh — otherwise every handle doubles the
    # controller's routing RPC load with a second round-trip per cycle
    NODE_MAP_PERIOD_S = 2.0

    def __init__(self, controller, deployment_name: str, *, block_size: int = 16,
                 max_tracked_prefixes: int = 8192, imbalance_tolerance: int = 4,
                 locality_bonus: float = 1.0):
        self.block_size = block_size
        self.max_tracked_prefixes = max_tracked_prefixes
        self.imbalance_tolerance = imbalance_tolerance
        self.locality_bonus = locality_bonus
        # prefix hash -> replica key, LRU-ordered (most recent last)
        self._prefix_owner: "OrderedDict[int, str]" = OrderedDict()
        # replica key -> node hex ("head" for head-host replicas); refreshed
        # with the replica list — the decode placement signal
        self._replica_nodes: dict[str, str] = {}
        self._live_snapshot: frozenset = frozenset()
        self._nodes_fetched = 0.0  # last node-map fetch (NODE_MAP_PERIOD_S)
        self._io_cache: tuple = (0.0, {})
        self._io_view_fn = _default_io_view  # test seam
        super().__init__(controller, deployment_name)

    # ---- hint extraction ----
    def _routing_hint(self, method_name: str, args, kwargs):
        body = args[0] if args else kwargs.get("body")
        if isinstance(body, dict):
            hint = None
            handoff = body.get("handoff")
            if isinstance(handoff, dict) and isinstance(
                    handoff.get("kv_ref"), dict):
                hint = ("decode", handoff["kv_ref"])
            else:
                ids = body.get("prompt_ids")
                if isinstance(ids, (list, tuple)) and ids:
                    hint = ("prefix", list(ids))
            if hint is not None:
                a = body.get("_anatomy")
                if isinstance(a, dict):
                    # the ledger's router_decision stamp records WHICH
                    # routing mode placed the request (prefix affinity vs
                    # decode placement scoring)
                    a["route"] = hint[0]
                return hint
        return None

    def _block_hashes(self, prompt_ids: list) -> list[int]:
        """Cumulative content hashes of block-aligned prefixes (longest last),
        mirroring BlockPool.lookup_prefix's addressing."""
        out = []
        h = 0
        bs = self.block_size
        for i in range(0, len(prompt_ids) - len(prompt_ids) % bs, bs):
            h = hash((h, tuple(prompt_ids[i : i + bs])))
            out.append(h)
        return out

    # ---- refresh: prune owners/nodes of removed replicas ----
    def _fetch_node_map(self) -> "dict | None":
        """Pull the replica->node map from the controller (None: keep the
        last map). Overridable seam: the front door's epoch-fed variant
        reads the map from its local routing epoch instead of this RPC."""
        try:
            return ray_tpu.get(self._controller.get_replica_nodes.remote(
                self._name), timeout=2)
        except Exception:
            return None  # older controller / transient failure

    def _refresh(self) -> None:
        before = self._last_refresh
        super()._refresh()
        if self._last_refresh == before:
            return  # base refresh didn't run this cycle
        nodes = None
        now = time.monotonic()
        if now - self._nodes_fetched >= self.NODE_MAP_PERIOD_S:
            self._nodes_fetched = now
            nodes = self._fetch_node_map()
        # warm the io-pressure cache OUTSIDE the lock: node_io_view() is a
        # full metrics rollup, and _select_decode (which reads it) runs
        # under the router lock on the request path
        self._io_pressure()
        with self._lock:
            live = frozenset(self._rkey(r) for r in self._replicas)
            if isinstance(nodes, dict):
                self._replica_nodes = {k: n for k, n in nodes.items()
                                       if k in live}
            self._prune_stale_owners(live)

    def _prune_stale_owners(self, live: frozenset) -> None:
        """Drop prefix entries owned by removed replicas (under the lock).
        A removed replica's cache is gone with it: keeping its entries
        burns every longest-prefix lookup on ``owner not in live`` misses
        and pins LRU slots until the bound finally evicts them."""
        if live == self._live_snapshot:
            return  # replica set unchanged: nothing to prune
        self._live_snapshot = live
        for h in [h for h, o in self._prefix_owner.items()
                  if o not in live]:
            del self._prefix_owner[h]

    # ---- selection ----
    def _select(self, hint):
        # called under self._lock with >=2 replicas. ``hint`` is
        # ("prefix", prompt_ids) | ("decode", kv_ref) | a bare prompt-id
        # list (legacy callers) | None.
        if isinstance(hint, tuple) and len(hint) == 2:
            kind, payload = hint
        elif hint:
            kind, payload = "prefix", hint
        else:
            kind, payload = None, None
        if kind == "decode":
            return self._select_decode(payload)
        if kind == "prefix":
            live = {self._rkey(r): r for r in self._replicas}
            min_load = min(self._inflight.get(k, 0) for k in live)
            hashes = self._block_hashes(payload)
            for h in reversed(hashes):  # longest prefix first
                owner = self._prefix_owner.get(h)
                if owner is None or owner not in live:
                    continue
                if (self._inflight.get(owner, 0)
                        <= min_load + self.imbalance_tolerance):
                    self._prefix_owner.move_to_end(h)
                    self._claim(hashes, owner)
                    return live[owner]
                break  # affinity exists but the owner is overloaded: balance
            chosen = super()._select(None)
            self._claim(hashes, self._rkey(chosen))
            return chosen
        return super()._select(None)

    def _select_decode(self, kv_ref):
        """Decode-side placement: least loaded replica, discounted toward
        the handoff holder's node, penalized by per-node I/O pressure.
        Runs under the router lock: ``_io_pressure`` is a cache hit here in
        steady state because ``_refresh`` warms it outside the lock."""
        live = {self._rkey(r): r for r in self._replicas}
        holder = kv_ref.get("node") if isinstance(kv_ref, dict) else None
        io = self._io_pressure()
        best_key = None
        best_score = None
        keys = list(live)
        random.shuffle(keys)  # break score ties fairly
        for key in keys:
            node = self._replica_nodes.get(key)
            score = float(self._inflight.get(key, 0))
            if node is not None:
                score += io.get(node, 0.0)
                if holder is not None and node == holder:
                    score -= self.locality_bonus
            if best_score is None or score < best_score:
                best_key, best_score = key, score
        return live[best_key]

    def _io_pressure(self) -> dict:
        """node hex -> queue-depth-equivalent I/O pressure, from
        ``state.node_io_view()`` (head-local aggregation; sampled at most
        once a second; {} where the view is unavailable, e.g. in workers)."""
        now = time.monotonic()
        ts, cached = self._io_cache
        if now - ts < 1.0:
            return cached
        pressure: dict = {}
        try:
            view = self._io_view_fn()
            for node, row in (view.get("nodes") or {}).items():
                pending = float(row.get("pending_pull_bytes", 0) or 0)
                pending += sum(
                    (row.get("holder_pending_bytes") or {}).values())
                pressure[node] = min(self.IO_PRESSURE_CAP,
                                     pending / float(self.IO_PRESSURE_SCALE))
        except Exception:
            pressure = {}
        self._io_cache = (now, pressure)
        return pressure

    def _claim(self, hashes: list[int], replica_key: str) -> None:
        for h in hashes:
            self._prefix_owner[h] = replica_key
            self._prefix_owner.move_to_end(h)
        while len(self._prefix_owner) > self.max_tracked_prefixes:
            self._prefix_owner.popitem(last=False)


ROUTER_CLASSES = {"pow2": Router, "kv_aware": KVAwareRouter}


def make_router(kind: str, controller, deployment_name: str) -> Router:
    cls = ROUTER_CLASSES.get(kind)
    if cls is None:
        raise ValueError(
            f"unknown request_router {kind!r} (known: {sorted(ROUTER_CLASSES)})"
        )
    return cls(controller, deployment_name)
