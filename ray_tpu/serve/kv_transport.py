"""Zero-copy KV-cache handoff between prefill and decode engines, over the
object plane.

Parity: the reference's L4 tensor-transport layer (NIXL/RDT,
rdt/nixl_tensor_transport.py) — prefill engines publish a sequence's KV
pages, decode engines on other hosts land them directly into their own page
pool, so the two fleets scale independently and KV bytes move node-to-node
at NIC speed. Here the transport is NOT a bespoke side channel: pages ride
the SAME wire-v3 BLOB pull path every plane object uses (arxiv 1712.05889's
argument for a shared object plane):

- **Publish** (prefill side): the gathered KV pages of one handoff are
  written ONCE into a ``create_for_write`` slot of the transport's
  shared-memory store and sealed — one plane entry per handoff (pages
  batched, not one object per page: a handoff is the transfer unit). The
  returned descriptor is control-plane-sized (ref id, endpoint, shapes);
  the pages never touch the control plane.
- **Pull** (decode side): ``PlaneClient.pull_into`` lands the entry as raw
  BLOB frames ``recv_into`` the decode-side store slot — received bytes are
  written exactly once — and the engine adopts the pages as zero-copy numpy
  views of that slot (the scatter into the device pool is the engine-side
  placement, the analog of NIXL's descriptor-list write).
- **Free** (lifecycle): a published handoff is freed on the FIRST of:
  decode ack (new wire-v7 ``kv_ack`` notify, sent back over the very
  connection the pages were pulled on), TTL expiry (sweeper), or claimant
  death (the puller's connection drops before acking — e.g. a decode
  replica died mid-attach). TTL/death frees are flight-recorded ("kv"
  ring); an abandoned handoff can never pin store memory forever.

Instruments are bound at import (util/metrics.py bind contract); the
publish/pull hot path never constructs or looks up a metric
(``check_wire_schemas.py check_kv_transport`` lints this).
"""

from __future__ import annotations

import os
import threading
import time
import weakref
from typing import Callable, Optional

import numpy as np

from ray_tpu._private.ids import ObjectID
from ray_tpu.core.object_plane import ObjectPlaneServer, PlaneClient
from ray_tpu.core.shm_store import SharedMemoryStore
from ray_tpu.serve import anatomy
from ray_tpu.util import flight_recorder
from ray_tpu.util.metrics import Counter, Gauge

# Defaults (env-tunable): the store holds in-flight handoffs only — pages
# are freed on ack, so steady-state occupancy is (handoff bytes) x (pulls
# in flight), not the engine's whole KV pool.
KV_STORE_BYTES = int(os.environ.get("RAY_TPU_KV_STORE_BYTES", str(128 << 20)))
KV_TTL_S = float(os.environ.get("RAY_TPU_KV_TTL_S", "30"))

_M_HANDOFFS = Counter(
    "ray_tpu_serve_kv_handoffs_total",
    "published KV handoffs retired, by outcome", tag_keys=("outcome",))
_M_ACKED = _M_HANDOFFS.bind({"outcome": "acked"})
_M_TTL = _M_HANDOFFS.bind({"outcome": "ttl_expired"})
_M_DIED = _M_HANDOFFS.bind({"outcome": "claimant_died"})
_M_CLOSED = _M_HANDOFFS.bind({"outcome": "closed"})
_M_PUB_BYTES = Counter(
    "ray_tpu_serve_kv_published_bytes_total",
    "KV page bytes published for handoff").bind()
_M_PULL_BYTES = Counter(
    "ray_tpu_serve_kv_pulled_bytes_total",
    "KV page bytes pulled into this decode engine").bind()

# Live transports, sampled at scrape time for the in-flight handoff gauges.
_TRANSPORTS: "weakref.WeakSet[KVTransport]" = weakref.WeakSet()


def _live_handoff_bytes_producer():
    total = 0
    for t in list(_TRANSPORTS):
        total += t.live_bytes()
    return [({}, total)]


Gauge("ray_tpu_serve_kv_live_handoff_bytes",
      "published-but-unretired KV handoff bytes held in plane stores"
      ).attach_producer(_live_handoff_bytes_producer)


def _sweep_loop(transport_ref: "weakref.ref", wake: threading.Event) -> None:
    """TTL sweeper body (module-level so the thread never pins the
    transport). Exits when the transport is closed OR garbage-collected."""
    interval = None
    while True:
        t = transport_ref()
        if t is None:
            return
        if interval is None:
            interval = max(0.05, min(1.0, t.ttl_s / 4.0))
        del t
        if wake.wait(interval):
            return
        t = transport_ref()
        if t is None:
            return
        t._sweep_tick()
        del t


class KVHandoffLost(RuntimeError):
    """The published pages are gone (TTL/death free beat the pull, or the
    prefill endpoint died). Callers re-prefill instead of retrying the pull."""


class _Handoff:
    __slots__ = ("hid", "oid", "nbytes", "deadline", "claimant", "acked")

    def __init__(self, hid: bytes, oid: ObjectID, nbytes: int, ttl_s: float):
        self.hid = hid
        self.oid = oid
        self.nbytes = nbytes
        self.deadline = time.monotonic() + ttl_s
        self.claimant: Optional[int] = None  # id(peer) of the puller
        self.acked = False


class _KVPlaneServer(ObjectPlaneServer):
    """The prefill-side KV endpoint: a stock plane server over the
    transport's store, plus (a) the v7 ``kv_ack`` side-op and (b) claimant
    tracking — the peer whose ``obj_meta`` opened a handoff's transfer is
    recorded so its death before ack frees the pages immediately."""

    def __init__(self, transport: "KVTransport", store, **kw):
        self._transport = weakref.proxy(transport)
        super().__init__(store, extra_handlers={"kv_ack": self._h_kv_ack},
                         **kw)

    def _h_meta(self, peer, msg):
        try:
            self._transport._note_claim(msg["oid"], id(peer))
        except ReferenceError:
            pass
        return super()._h_meta(peer, msg)

    def _h_kv_ack(self, peer, msg):
        try:
            self._transport._on_ack(msg["hid"])
        except ReferenceError:
            pass
        return True

    def _peer_gone(self, peer) -> None:
        super()._peer_gone(peer)
        try:
            self._transport._on_claimant_gone(id(peer))
        except ReferenceError:
            pass


class KVTransport:
    """One per engine: publish side (prefill) and pull side (decode) of the
    KV handoff plane. Both halves are always available — a PD replica that
    does both (co-located fallback) needs only one transport."""

    def __init__(self, name: str | None = None, *,
                 store: SharedMemoryStore | None = None,
                 store_bytes: int | None = None,
                 host: str = "127.0.0.1", port: int = 0,
                 ttl_s: float | None = None,
                 node_hint: str | None = None):
        self.ttl_s = ttl_s if ttl_s is not None else KV_TTL_S
        self.node_hint = node_hint or os.environ.get("RAY_TPU_NODE_ID",
                                                     "head")
        self._owns_store = store is None
        if store is None:
            name = name or f"rtpu_kv_{os.getpid()}_{id(self):x}"
            # prefault=False: the arena backs a few in-flight handoffs, not
            # a node store — warming all of it would pin store_bytes of RSS
            # per replica; a cold-page publish costs ~0.5 ms/MB once
            store = SharedMemoryStore(f"/{name.lstrip('/')}",
                                      size=store_bytes or KV_STORE_BYTES,
                                      owner=True, prefault=False)
        self._store = store
        self._server = _KVPlaneServer(self, store, host=host, port=port)
        self._client = PlaneClient()
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self._live: dict[bytes, _Handoff] = {}
        self._by_oid: dict[bytes, bytes] = {}  # oid binary -> hid
        # decode side: local secondary copies landed by pull(), swept on
        # the same TTL if the ack path never ran (a failed attach must not
        # strand handoff bytes in this store for the replica's life)
        self._local_pulls: dict[bytes, float] = {}  # oid binary -> deadline
        self._closed = False
        self._sweep_wake = threading.Event()
        # the sweeper holds a WEAK reference: a thread target bound to self
        # would pin the transport forever, making __del__ (and the close()
        # it runs — store/server/socket teardown) unreachable once the
        # owning replica is dropped
        self._sweeper = threading.Thread(
            target=_sweep_loop, args=(weakref.ref(self), self._sweep_wake),
            daemon=True, name=f"kv-ttl-{self.address}")
        self._sweeper.start()
        _TRANSPORTS.add(self)

    # ------------------------------------------------------------ publish side
    @property
    def address(self) -> str:
        return self._server.address

    def publish(self, k: np.ndarray, v: np.ndarray, *,
                meta: dict | None = None) -> dict:
        """Register one handoff's KV pages as a sealed plane entry and
        return the compact descriptor the decode side pulls from.

        ``k``/``v`` are host arrays (the engine's gathered pages); each is
        written ONCE into the store's mapped slot — the only prefill-side
        copy on the handoff path."""
        if self._closed:
            raise RuntimeError("KVTransport is closed")
        k = np.asarray(k)
        v = np.asarray(v)
        if k.dtype != v.dtype:
            # the descriptor ships ONE dtype; a mixed-dtype handoff would
            # reconstruct v as silent garbage on the decode side
            raise ValueError(
                f"KV handoff dtype mismatch: k={k.dtype} v={v.dtype}")
        nbytes = k.nbytes + v.nbytes
        oid = ObjectID(os.urandom(ObjectID.SIZE))
        hid = os.urandom(12)
        t0_w = anatomy.now_wall()
        view = self._store.create_for_write(oid, nbytes)
        if view is None:  # random oid collided with a sealed entry: impossible
            raise RuntimeError("KV handoff oid collision")
        try:
            np.copyto(np.frombuffer(view, dtype=k.dtype,
                                    count=k.size).reshape(k.shape), k)
            np.copyto(np.frombuffer(view, dtype=v.dtype, count=v.size,
                                    offset=k.nbytes).reshape(v.shape), v)
        except BaseException:
            self._store.abort(oid)
            raise
        del view
        self._store.seal(oid)
        h = _Handoff(hid, oid, nbytes, self.ttl_s)
        with self._lock:
            self._live[hid] = h
            self._by_oid[oid.binary()] = hid
        _M_PUB_BYTES.inc(nbytes)
        # anatomy window keyed by oid (no request id in scope on the engine
        # thread; pd.py links rid<->oid): one ring append, hot-path safe
        anatomy.kv_window(oid.binary().hex(), "kv_publish", t0_w,
                          anatomy.now_wall(), nbytes)
        desc = {
            "hid": hid,
            "oid": oid.binary(),
            "addr": self.address,
            "nbytes": nbytes,
            "k_shape": list(k.shape),
            "v_shape": list(v.shape),
            "dtype": str(k.dtype),
            "node": self.node_hint,
        }
        if meta:
            desc["meta"] = dict(meta)
        return desc

    def _note_claim(self, oid_bin: bytes, peer_id: int) -> None:
        with self._lock:
            hid = self._by_oid.get(oid_bin)
            h = self._live.get(hid) if hid is not None else None
            if h is not None:
                h.claimant = peer_id

    def _on_ack(self, hid: bytes) -> None:
        self._retire(hid, "acked")

    def _on_claimant_gone(self, peer_id: int) -> None:
        with self._lock:
            doomed = [h.hid for h in self._live.values()
                      if h.claimant == peer_id and not h.acked]
        for hid in doomed:
            flight_recorder.record(
                "kv", "handoff_claimant_died", hid=hid.hex(),
                addr=self.address)
            self._retire(hid, "claimant_died")

    def _retire(self, hid: bytes, outcome: str) -> bool:
        with self._lock:
            h = self._live.pop(hid, None)
            if h is None:
                return False
            h.acked = outcome == "acked"
            self._by_oid.pop(h.oid.binary(), None)
            # delete BEFORE waking wait_drained so "drained" implies the
            # store entry is retired too. The plane server may still hold a
            # read pin (an in-flight pull): delete marks the entry DELETING
            # and the memory frees when the last pin drops — a racing pull
            # either completes or sees ObjectLost.
            self._store.delete(h.oid)
            self._cv.notify_all()
        if outcome == "acked":
            _M_ACKED.inc()
        elif outcome == "ttl_expired":
            _M_TTL.inc()
        elif outcome == "claimant_died":
            _M_DIED.inc()
        else:
            _M_CLOSED.inc()
        return True

    def _sweep_tick(self) -> None:
        now = time.monotonic()
        with self._lock:
            expired = [h.hid for h in self._live.values()
                       if now > h.deadline]
            stale_local = [ob for ob, dl in self._local_pulls.items()
                           if now > dl]
        for hid in expired:
            flight_recorder.record(
                "kv", "handoff_ttl_expired", hid=hid.hex(),
                addr=self.address, ttl_s=self.ttl_s)
            self._retire(hid, "ttl_expired")
        for ob in stale_local:  # pulled-but-never-acked local copies
            self._drop_local(ObjectID(ob))

    # --------------------------------------------------------------- pull side
    def pull(self, desc: dict, timeout: float = 60.0
             ) -> "tuple[dict, Callable[[], None]]":
        """Land a published handoff's pages locally and return
        ``({"k": ..., "v": ...}, ack)`` — zero-copy numpy views of the
        local store slot, plus the ack callable the engine invokes AFTER
        scattering the pages into its pool (frees both ends). A local
        copy whose ack never runs (failed attach) is TTL-swept."""
        oid = ObjectID(bytes(desc["oid"]))
        addr = desc["addr"]
        nbytes = int(desc["nbytes"])
        t0_w = anatomy.now_wall()
        # the canonical pull policy: zero-copy pull-into-store first,
        # bytes-returning fallback when there is no room (object_plane.py)
        payload, how = self._client.pull_into_or_pull(
            [addr], oid, self._store, timeout=timeout)
        if payload is None:
            raise KVHandoffLost(
                f"KV handoff {bytes(desc['hid']).hex()[:12]} not served "
                f"by {addr} (freed by ack/TTL, or the endpoint died)")
        local = how in ("sealed", "exists")
        if local:
            with self._lock:
                self._local_pulls[oid.binary()] = (
                    time.monotonic() + self.ttl_s)
        view = payload if isinstance(payload, memoryview) \
            else memoryview(payload)
        try:
            if len(view) != nbytes:
                raise KVHandoffLost(
                    f"KV handoff size mismatch: pulled {len(view)} != "
                    f"{nbytes}")
            dtype = np.dtype(desc["dtype"])
            k_shape = tuple(desc["k_shape"])
            v_shape = tuple(desc["v_shape"])
            k = np.frombuffer(view, dtype=dtype,
                              count=int(np.prod(k_shape))).reshape(k_shape)
            v = np.frombuffer(view, dtype=dtype,
                              count=int(np.prod(v_shape)),
                              offset=k.nbytes).reshape(v_shape)
        except BaseException:
            if local:
                # delete tolerates our still-live read pin: the entry goes
                # DELETING and frees when the views are garbage-collected
                self._drop_local(oid)
            raise
        _M_PULL_BYTES.inc(nbytes)
        anatomy.kv_window(oid.binary().hex(), "kv_pull", t0_w,
                          anatomy.now_wall(), nbytes)

        def ack(_local=local, _oid=oid, _desc=desc):
            self.ack(_desc)
            if _local:
                # retire the local secondary copy; the store frees it when
                # the engine's views (k/v above) are garbage-collected
                self._drop_local(_oid)

        return {"k": k, "v": v}, ack

    def _drop_local(self, oid: ObjectID) -> None:
        with self._lock:
            self._local_pulls.pop(oid.binary(), None)
        self._store.delete(oid)

    def ack(self, desc: dict) -> bool:
        """Tell the publisher the pages landed (frees the published entry).
        Rides the pull connection; a <v7 publisher never sees the op — its
        TTL sweep reclaims instead."""
        try:
            peer = self._client._peer(desc["addr"])
            if (peer.negotiated_version or 0) >= 7:
                peer.notify("kv_ack", hid=bytes(desc["hid"]))
                return True
        except Exception:
            pass  # publisher gone / old wire: TTL covers it
        return False

    # --------------------------------------------------------------- lifecycle
    def live_handoffs(self) -> int:
        with self._lock:
            return len(self._live)

    def live_bytes(self) -> int:
        with self._lock:
            return sum(h.nbytes for h in self._live.values())

    def wait_drained(self, timeout: float = 30.0) -> bool:
        """Block until every published handoff has been retired (ack, TTL,
        or claimant death). Condition-variable wait — no sleep polling."""
        with self._cv:
            return self._cv.wait_for(lambda: not self._live, timeout=timeout)

    def stats(self) -> dict:
        with self._lock:
            live, live_bytes = len(self._live), sum(
                h.nbytes for h in self._live.values())
            local = len(self._local_pulls)
        return {"live_handoffs": live, "live_bytes": live_bytes,
                "local_pulls": local, "store": self._store.stats()}

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        self._sweep_wake.set()
        with self._lock:
            doomed = list(self._live)
            stale_local = list(self._local_pulls)
        for hid in doomed:
            self._retire(hid, "closed")
        for ob in stale_local:
            self._drop_local(ObjectID(ob))
        try:
            self._client.close()
        finally:
            self._server.close()
            if self._owns_store:
                self._store.close()

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass
