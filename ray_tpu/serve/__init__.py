"""ray_tpu.serve: model serving over the actor runtime.

Parity surface: ray.serve (@deployment, run, status, delete, shutdown, @batch,
DeploymentHandle, HTTP ingress) — reference python/ray/serve/.
"""

from ray_tpu.serve.api import (
    delete,
    get_deployment_handle,
    run,
    shutdown,
    start_grpc_proxy,
    start_http_proxy,
    start_proxies,
    stop_proxies,
    status,
)
from ray_tpu.serve.batching import batch
from ray_tpu.serve.multiplex import get_multiplexed_model_id, multiplexed
from ray_tpu.serve.openai_api import build_openai_app


def __getattr__(name):
    # jax-heavy engine classes load lazily (importing ray_tpu.serve must not
    # pull jax/llama)
    if name in ("PagedLLMConfig", "PagedLLMEngine"):
        from ray_tpu.serve import llm_paged

        return getattr(llm_paged, name)
    if name in ("KVTransport", "KVHandoffLost"):
        from ray_tpu.serve import kv_transport

        return getattr(kv_transport, name)
    if name in ("build_pd_deployment", "build_prefill_deployment",
                "build_decode_deployment", "build_pd_controller",
                "deploy_pd_app"):
        from ray_tpu.serve import pd

        return getattr(pd, name)
    # front door (ISSUE 17): loads lazily — the ingress fleet pulls aiohttp
    # via HttpProxy, which plain `import ray_tpu.serve` must not require
    if name in ("start_front_door", "stop_front_door", "front_door_addresses",
                "front_door_view", "FrontDoor", "IngressActor",
                "EpochRouter", "EpochKVRouter", "EpochCache"):
        from ray_tpu.serve import front_door

        return getattr(front_door, name)
    if name == "DeploymentAutoscaler":
        from ray_tpu.serve.autoscale import DeploymentAutoscaler

        return DeploymentAutoscaler
    if name in ("AdmissionConfig", "AdmissionGate"):
        from ray_tpu.serve import admission

        return getattr(admission, name)
    raise AttributeError(name)
from ray_tpu.serve.controller import DeploymentHandle, ServeController
from ray_tpu.serve.deployment import Application, AutoscalingConfig, Deployment, deployment

__all__ = [
    "deployment", "Deployment", "Application", "AutoscalingConfig",
    "run", "delete", "status", "shutdown", "start_http_proxy", "start_grpc_proxy",
    "start_proxies", "stop_proxies",
    "get_deployment_handle", "build_openai_app",
    "PagedLLMConfig", "PagedLLMEngine",
    "KVTransport", "KVHandoffLost",
    "build_pd_deployment", "build_prefill_deployment",
    "build_decode_deployment", "build_pd_controller", "deploy_pd_app",
    "batch", "DeploymentHandle", "ServeController",
    "multiplexed", "get_multiplexed_model_id",
    "start_front_door", "stop_front_door", "front_door_addresses",
    "front_door_view", "FrontDoor", "IngressActor",
    "EpochRouter", "EpochKVRouter", "EpochCache",
    "DeploymentAutoscaler", "AdmissionConfig", "AdmissionGate",
]

from ray_tpu._private.usage_stats import record_library_usage as _rec

_rec("serve")
del _rec
